"""Autotuner oracle A/B: static ranking vs measured step time on two CPU
toy workloads (the ROADMAP item-4 payoff, measured end to end).

Two workloads exercise the two halves of the knob surface:

* **train** — one SGD step spanning **mesh x zero x compression** on the
  full 8-device fake pool. The workload factory builds the REAL wire leg
  per candidate (``parallel.zero.reduce_scatter_grads`` /
  ``all_gather_updates`` inside ``shard_map``, or
  ``compressed_psum_mean``, or an exact f32 ``pmean``), so the oracle
  prices the collectives the program actually runs — and the compiled
  HLO's collectives (``telemetry.wire.hlo_wire_bytes``) are counted as
  an independent check that must agree with the prediction per arm.
  All candidate meshes use the SAME device pool, which makes the
  time-rank criterion portable: per-device work and single-core total
  work are order-isomorphic (replication multiplies both), so the
  predicted ordering must match the measured one on any core count.
* **serving** — a decode-tick-shaped program spanning **buckets x
  token-budget**: each tick pads its prefill chunk to the covering
  bucket and decodes ``budget`` rows, so padded tokens drive both the
  roofline prediction and the measured wall time; the statically
  predicted winner must equal the measured winner (top-1) with
  Spearman >= 0.8 over the whole candidate set.

Also measured, not asserted-by-hand: the HBM feasibility prune (a
deliberately tiny budget must classify every candidate infeasible with
a TPU701 error) and ZERO post-warmup recompiles in every confirm run.

Writes the JSON report to stdout:

    JAX_PLATFORMS=cpu python benchmarks/bench_tune.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import force_host_platform  # noqa: E402

HIDDEN = 256
GLOBAL_BATCH = 256
SERVE_HIDDEN = 512


def _covering(buckets, size):
    asc = sorted(int(b) for b in buckets)
    return next((b for b in asc if b >= size), asc[-1])


def make_train_factory(hidden: int, global_batch: int):
    """Factory over mesh x zero x compression: the gradient sync is the
    real wire leg for the candidate — exact pmean, compressed psum, or
    the ZeRO-1 reduce-scatter/all-gather pair — inside a shard_map whose
    in_specs shard the batch over ``data`` (so the traced per-device
    shapes ARE per-device: the oracle sees what each chip would do)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.analysis.tuner import build_point_mesh

    def factory(point):
        mesh = build_point_mesh(point)
        n_data = int(mesh.shape.get("data", 1))
        method = point.compression
        zero = point.zero_stage == 1
        lr = 0.01

        def flatten(tree):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            flat = jnp.concatenate([l.ravel() for l in leaves])
            pad = (-flat.shape[0]) % n_data
            return jnp.pad(flat, (0, pad)), (leaves, treedef, pad)

        def unflatten(flat, spec):
            leaves, treedef, pad = spec
            flat = flat[: flat.shape[0] - pad] if pad else flat
            out, off = [], 0
            for l in leaves:
                out.append(flat[off: off + l.size].reshape(l.shape))
                off += l.size
            return jax.tree_util.tree_unflatten(treedef, out)

        def body(params, batch):
            def loss_fn(p):
                h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
                pred = h @ p["w2"] + p["b2"]
                return jnp.mean((pred - batch["y"]) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            if zero:
                from accelerate_tpu.parallel.zero import (
                    all_gather_updates,
                    reduce_scatter_grads,
                )

                g_flat, spec = flatten(grads)
                p_flat, _ = flatten(params)
                shard, _ = reduce_scatter_grads({"g": g_flat}, "data", n_data, method, None)
                # this rank owns segment [idx*seg_len : (idx+1)*seg_len];
                # sgd's update is a pure function of the grad segment, so
                # only the -lr*g delta rides the all-gather leg
                upd = -lr * (shard["g"] / n_data)
                full, _ = all_gather_updates({"u": upd}, "data", n_data, method, None)
                new_params = unflatten(p_flat + full["u"], spec)
            else:
                if method:
                    from accelerate_tpu.parallel.compression import compressed_psum_mean

                    grads = compressed_psum_mean(grads, "data", method)
                else:
                    grads = jax.lax.pmean(grads, "data")
                new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
            return new_params, jax.lax.pmean(loss, "data")

        step = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P("data")), out_specs=(P(), P()),
            check_rep=False,
        )
        f32 = jnp.float32
        params = {
            "w1": jax.ShapeDtypeStruct((hidden, hidden), f32),
            "b1": jax.ShapeDtypeStruct((hidden,), f32),
            "w2": jax.ShapeDtypeStruct((hidden, hidden), f32),
            "b2": jax.ShapeDtypeStruct((hidden,), f32),
        }
        batch = {
            "x": jax.ShapeDtypeStruct((global_batch, hidden), f32),
            "y": jax.ShapeDtypeStruct((global_batch, hidden), f32),
        }
        return step, (params, batch)

    factory.tune_factory = True
    factory.__name__ = "train_workload"
    return factory


def make_serving_factory(hidden: int):
    """Factory over buckets x token-budget: a tick prefills one chunk
    padded to the covering bucket and decodes ``budget`` rows — padded
    tokens drive compute in both the oracle and the wall clock."""
    import jax
    import jax.numpy as jnp

    def factory(point):
        buckets = point.buckets or (64, 256)
        budget = point.token_budget or 64
        prefill = _covering(buckets, budget)
        decode = budget

        def tick_step(w1, w2, prompt_h, decode_h):
            pre = jnp.tanh(jnp.tanh(prompt_h @ w1) @ w2)
            dec = jnp.tanh(jnp.tanh(decode_h @ w1) @ w2)
            return pre.sum() + dec.sum()

        f32 = jnp.float32
        args = (
            jax.ShapeDtypeStruct((hidden, hidden), f32),
            jax.ShapeDtypeStruct((hidden, hidden), f32),
            jax.ShapeDtypeStruct((prefill, hidden), f32),
            jax.ShapeDtypeStruct((decode, hidden), f32),
        )
        return tick_step, args

    factory.tune_factory = True
    factory.__name__ = "serving_workload"
    return factory


def _rank_pairs(report):
    return [
        (c.predicted_step_us, c.measured_step_us, c.label, c.point)
        for c in report.ranked
        if c.measured_step_us is not None
    ]


def measure_train_wire(factory, report) -> dict:
    """Per-arm independent wire check: the compiled program's HLO
    collectives (shared ring formulas) vs the oracle's per-device
    prediction."""
    import jax

    from accelerate_tpu.analysis.tuner import _materialize, resolve_workload
    from accelerate_tpu.telemetry.wire import hlo_wire_bytes

    out = {}
    for cand in report.ranked:
        step, args = resolve_workload(factory, cand.point, ())
        concrete = _materialize(args)
        hlo = jax.jit(step).lower(*concrete).compile().as_text()
        measured = hlo_wire_bytes(hlo)["total"]
        predicted = cand.wire_bytes
        out[cand.label] = {
            "predicted": int(predicted),
            "measured": int(measured),
            "agree_pct": round(
                100.0 * (1.0 - abs(measured - predicted) / max(1, max(measured, predicted))), 2
            ),
        }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="CI sizing: fewer steps")
    ap.add_argument("--steps", type=int, default=None, help="steady confirm steps per arm")
    args = ap.parse_args(argv)
    steps = args.steps or (4 if args.smoke else 8)

    force_host_platform(8)
    import jax

    from accelerate_tpu.analysis.searchspace import SearchSpace
    from accelerate_tpu.analysis.tuner import spearman, tune
    from accelerate_tpu.parallel.mesh import MeshConfig

    report: dict = {
        "env": {
            "backend": jax.default_backend(),
            "cpu_count": os.cpu_count(),
            "jax": jax.__version__,
            "smoke": bool(args.smoke),
            "steps": steps,
        },
        "criteria": {},
    }

    # ---- train: mesh x zero x compression on the full 8-device pool ----
    train_factory = make_train_factory(HIDDEN, GLOBAL_BATCH)
    train_space = SearchSpace(
        meshes=("data=8", "data=4,tensor=2", "data=2,tensor=4"),
        zero_stages=(0, 1),
        compressions=("none", "int8"),
        max_devices=8,
    )
    train = tune(
        train_factory, train_space, generation="cpu",
        top_k=99, confirm=True, confirm_steps=steps,
    )
    pairs = _rank_pairs(train)
    train_rho = spearman([p for p, *_ in pairs], [m for _, m, *_ in pairs])
    pred_winner = min(pairs, key=lambda t: t[0]) if pairs else None
    meas_winner = min(pairs, key=lambda t: t[1]) if pairs else None
    # mesh-level ordering: group arms by mesh, compare mean predicted vs
    # mean measured ordering — the portable criterion (same device pool,
    # so per-device predicted work and total measured work are
    # order-isomorphic on ANY core count)
    by_mesh: dict = {}
    for p, m, _, point in pairs:
        key = json.dumps(point.mesh_shape, sort_keys=True)
        by_mesh.setdefault(key, []).append((p, m))
    mesh_pred = [sum(p for p, _ in v) / len(v) for v in by_mesh.values()]
    mesh_meas = [sum(m for _, m in v) / len(v) for v in by_mesh.values()]
    mesh_rho = spearman(mesh_pred, mesh_meas)
    wire = measure_train_wire(train_factory, train)
    train_recompiles = train.confirm["recompiles"] if train.confirm else None
    report["train"] = {
        "candidates": [c.as_dict() for c in train.candidates],
        "winner": train.winner.label if train.winner else None,
        "measured_winner": meas_winner[2] if meas_winner else None,
        "top1": bool(pred_winner and meas_winner and pred_winner[3] == meas_winner[3]),
        "spearman": round(train_rho, 4) if train_rho is not None else None,
        "mesh_rank_spearman": round(mesh_rho, 4) if mesh_rho is not None else None,
        "wire": wire,
        "recompiles": train_recompiles,
        "chosen_toml": train.chosen_toml(),
    }

    # ---- serving: buckets x token budget (single device) ---------------
    serve_factory = make_serving_factory(SERVE_HIDDEN)
    serve_space = SearchSpace(
        bucket_sets=("64,256", "128,512"),
        token_budgets=(64, 128, 256),
    )
    base_mesh = MeshConfig(data=1).build(jax.devices()[:1])
    serving = tune(
        serve_factory, serve_space, base_mesh=base_mesh, generation="cpu",
        top_k=99, confirm=True, confirm_steps=steps,
    )
    s_pairs = _rank_pairs(serving)
    s_rho = spearman([p for p, *_ in s_pairs], [m for _, m, *_ in s_pairs])
    s_pred = min(s_pairs, key=lambda t: t[0]) if s_pairs else None
    s_meas = min(s_pairs, key=lambda t: t[1]) if s_pairs else None
    serve_recompiles = serving.confirm["recompiles"] if serving.confirm else None
    report["serving"] = {
        "candidates": [c.as_dict() for c in serving.candidates],
        "winner": serving.winner.label if serving.winner else None,
        "measured_winner": s_meas[2] if s_meas else None,
        "top1": bool(s_pred and s_meas and s_pred[3] == s_meas[3]),
        "spearman": round(s_rho, 4) if s_rho is not None else None,
        "recompiles": serve_recompiles,
        "chosen_toml": serving.chosen_toml(),
    }

    # ---- HBM feasibility prune, exercised for real ---------------------
    pruned = tune(
        train_factory,
        SearchSpace(meshes=("data=8",), max_devices=8),
        generation="cpu",
        hbm_gb=0.0001,
    )
    report["hbm_prune"] = {
        "infeasible": pruned.infeasible_count,
        "tpu701": sum(1 for f in pruned.findings if f.rule == "TPU701"),
    }

    # ---- criteria ------------------------------------------------------
    wire_ok = all(w["agree_pct"] >= 95.0 for w in wire.values()) and len(wire) > 0
    crit = {
        "serving_top1_predicted_equals_measured": bool(report["serving"]["top1"]),
        "serving_spearman_ge_0.8": bool(s_rho is not None and s_rho >= 0.8),
        "train_top1_predicted_equals_measured": bool(report["train"]["top1"]),
        "train_mesh_rank_spearman_eq_1": bool(mesh_rho is not None and mesh_rho >= 0.999),
        "train_wire_predicted_matches_hlo_measured_95pct": bool(wire_ok),
        "hbm_prune_fires_tpu701": bool(
            report["hbm_prune"]["infeasible"] >= 1 and report["hbm_prune"]["tpu701"] >= 1
        ),
        "zero_postwarmup_recompiles": bool(
            (train_recompiles or 0) == 0 and (serve_recompiles or 0) == 0
        ),
    }
    report["criteria"] = crit
    report["notes"] = (
        "All train candidate meshes use the same 8-device pool, so predicted per-device "
        "work and measured total work are order-isomorphic on any core count — the "
        "top-1 and mesh-level rank gates are portable. The full train spearman is "
        "reported but not gated: within-mesh wire-knob deltas are below wall-clock "
        "noise on small steps (the wire itself is gated exactly instead — predicted "
        "bytes must match the compiled HLO's collectives per arm, the core-count-"
        "independent evidence for the comms half of the oracle)."
    )
    report["ok"] = all(crit.values())
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
