"""GPipe schedule-efficiency microbench.

Measures ``pipeline_apply`` (parallel/pipeline.py) against the GPipe
bubble bound: with S stages and M microbatches the best possible time is

    t_ideal = (t_seq / S) * (M + S - 1) / M

where ``t_seq`` is the same layer stack run as a plain single-device scan.
``overhead = t_pipe / t_ideal`` isolates schedule waste (ppermute latency
not hidden, fill/drain bookkeeping, the final replication psum) from the
inherent bubble.

ALSO verifies the schedule structurally from the compiled HLO: exactly ONE
while-loop of M+S-1 ticks (the bound's tick count — each device performs M
useful stage-applies plus the unavoidable S-1 bubble ticks), neighbor-only
collective-permute, and the output collective: a reduce-scatter when M
divides over S (each stage keeps its microbatch block — half the wire
bytes of an all-reduce), the fallback replication psum otherwise.

CAVEAT on the numbers: on the CPU fake mesh the S "devices" share host
cores and collectives are emulated, so wall-clock overhead_vs_bound is an
emulation artifact (it grows with tick count, i.e. with M). On real
multi-chip TPU the per-tick constant is one collective-permute launch,
hidden whenever microbatch compute >> ICI latency. The structural checks
are platform-independent; re-run the timing rows on a pod slice for real
efficiency numbers.

Run under the real 2-process launcher for a pipe=8 wall-clock row whose
collectives cross an actual process boundary (the CI gate does this):

    accelerate-tpu launch --num_processes 2 --cpu --fake_devices 4 \
        -m benchmarks.pipeline_bubble -- --stages 8

Usage: python benchmarks/pipeline_bubble.py [--width 512] [--layers 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import force_host_platform


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--stages", type=int, default=None,
                    help="run only this stage count (multi-process gate uses --stages 8)")
    args = ap.parse_args()

    multiprocess = bool(os.environ.get("ACCELERATE_COORDINATOR_ADDRESS"))
    if multiprocess:
        # launched by the real launcher: jax.distributed init via the env
        # protocol; devices = all processes' fake devices combined
        from accelerate_tpu.state import PartialState

        PartialState()
    else:
        force_host_platform(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.pipeline import pipeline_apply, stage_sharding

    n_dev = len(jax.devices())
    is_main = not multiprocess or jax.process_index() == 0
    w, L = args.width, args.layers

    def layer_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"]) + h

    def make_arrays(mesh, param_spec):
        """Create params/x as GLOBAL arrays via jit out_shardings — works
        identically single- and multi-process (device_put of host data to
        non-addressable shards does not)."""

        def build():
            ks = jax.random.split(jax.random.key(0), 2)
            params = {
                "w": jax.random.normal(ks[0], (L, w, w)) * 0.05,
                "b": jax.random.normal(ks[1], (L, w)) * 0.01,
            }
            x = jax.random.normal(jax.random.key(2), (args.batch, w))
            return params, x

        shardings = (
            {"w": NamedSharding(mesh, param_spec), "b": NamedSharding(mesh, param_spec)},
            NamedSharding(mesh, P()),
        )
        return jax.jit(build, out_shardings=shardings)()

    def timeit(fn, *a, iters=20):
        jax.block_until_ready(fn(*a))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    # sequential baseline: all layers on one device (pipe=1 fallback path).
    # In multiprocess mode a 1-device mesh spanning only process 0 can't be
    # driven from every controller; use a pipe=1 mesh over ALL devices
    # (same program: the n_stages==1 scan path, replicated).
    mesh1 = MeshConfig(data=n_dev if multiprocess else 1, fsdp=1, tensor=1, seq=1, pipe=1, expert=1).build(
        jax.devices() if multiprocess else jax.devices()[:1]
    )
    params1, x1 = make_arrays(mesh1, P())
    seq_fn = jax.jit(lambda p, x: pipeline_apply(layer_fn, p, x, mesh=mesh1, num_microbatches=1))
    t_seq = timeit(seq_fn, params1, x1)

    import re

    stage_counts = (args.stages,) if args.stages else (2, 4, 8)
    rows = []
    for s in stage_counts:
        if n_dev < s or L % s:
            continue
        mesh = MeshConfig(pipe=s, data=1, fsdp=1, tensor=1, seq=1, expert=1).build(jax.devices()[:s])
        sharded, x = make_arrays(mesh, P("pipe"))
        for m in (4, 8, 16):
            if args.batch % m:
                continue
            fn = jax.jit(lambda p, x, _m=m, _mesh=mesh: pipeline_apply(
                layer_fn, p, x, mesh=_mesh, num_microbatches=_m))
            t_pipe = timeit(fn, sharded, x)
            t_ideal = (t_seq / s) * (m + s - 1) / m

            # structural checks against the compiled program
            hlo = fn.lower(sharded, x).compile().as_text()
            n_psum = len(re.findall(r"all-reduce(?:-start)?\(", hlo))
            # every collective-permute must be the neighbor ring
            # {0->1, 1->2, ..., S-1->0} — no skip links, no gathers
            ring = {(j, (j + 1) % s) for j in range(s)}
            pair_sets = [
                {tuple(map(int, p.split(","))) for p in re.findall(r"\{(\d+,\d+)\}", block)}
                for block in re.findall(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}", hlo)
            ]
            if m % s == 0:
                # reduce-scatter output path: NO replication all-reduce at
                # all — the old full-buffer psum is gone (round-4 change)
                out_collective_ok = n_psum == 0 and "reduce-scatter" in hlo
            else:
                out_collective_ok = n_psum <= 1  # fallback replication psum
            structural_ok = bool(
                re.search(rf"constant\({m + s - 1}\)", hlo)  # trip-count constant present
                and pair_sets
                and all(ps == ring for ps in pair_sets)
                and out_collective_ok
                and "all-gather" not in hlo  # params never gathered
            )
            # Two bounds:
            # * t_ideal assumes S devices compute in parallel — the REAL
            #   hardware bound, unattainable on the fake mesh where the S
            #   "devices" share host cores (t_seq/S of wall-clock parallel
            #   speedup cannot exist), so overhead_vs_bound ~ S at best.
            # * serialized bound t_seq*(M+S-1)/M assumes zero parallel
            #   speedup (shared cores) and charges only the schedule's tick
            #   structure — the emulation-meaningful number: it approaches
            #   1 when per-tick compute dominates schedule overhead.
            t_serial_bound = t_seq * (m + s - 1) / m
            rows.append({
                "stages": s, "microbatches": m,
                "ticks": m + s - 1,
                "multiprocess": multiprocess,
                "t_seq_ms": round(t_seq * 1e3, 2),
                "t_pipe_ms": round(t_pipe * 1e3, 2),
                "t_ideal_ms": round(t_ideal * 1e3, 2),
                "overhead_vs_bound": round(t_pipe / t_ideal, 3),
                "overhead_vs_serialized_bound": round(t_pipe / t_serial_bound, 3),
                "structural_ok": structural_ok,
            })
            if is_main:
                print(json.dumps(rows[-1]), flush=True)

    if not rows:
        print(json.dumps({"bench": "pipeline_bubble",
                          "error": f"no runnable (stages, microbatches) for devices={n_dev}, "
                                   f"layers={L}, batch={args.batch}"}), flush=True)
        raise SystemExit(2)
    worst = max(r["overhead_vs_bound"] for r in rows)
    assert all(r["structural_ok"] for r in rows), "schedule structure violates the bubble bound"
    if is_main:
        print(json.dumps({"bench": "pipeline_bubble", "worst_overhead_vs_bound": worst,
                          "structural_bound_ok": True}), flush=True)


if __name__ == "__main__":
    main()
