"""GPipe schedule-efficiency microbench.

Measures ``pipeline_apply`` (parallel/pipeline.py) against the GPipe
bubble bound: with S stages and M microbatches the best possible time is

    t_ideal = (t_seq / S) * (M + S - 1) / M

where ``t_seq`` is the same layer stack run as a plain single-device scan.
``overhead = t_pipe / t_ideal`` isolates schedule waste (ppermute latency
not hidden, fill/drain bookkeeping, the final replication psum) from the
inherent bubble.

ALSO verifies the schedule structurally from the compiled HLO: exactly ONE
while-loop of M+S-1 ticks (the bound's tick count — each device performs M
useful stage-applies plus the unavoidable S-1 bubble ticks), neighbor-only
collective-permute, and a single full-buffer replication psum.

CAVEAT on the numbers: on the CPU fake mesh the S "devices" share host
cores and collectives are emulated, so wall-clock overhead_vs_bound is an
emulation artifact (it grows with tick count, i.e. with M). On real
multi-chip TPU the per-tick constant is one collective-permute launch,
hidden whenever microbatch compute >> ICI latency. The structural checks
are platform-independent; re-run the timing rows on a pod slice for real
efficiency numbers.

Usage: python benchmarks/pipeline_bubble.py [--width 512] [--layers 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import force_host_platform


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    force_host_platform(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.pipeline import pipeline_apply, stage_sharding

    w, L = args.width, args.layers
    ks = jax.random.split(jax.random.key(0), 2)
    params = {
        "w": jax.random.normal(ks[0], (L, w, w)) * 0.05,
        "b": jax.random.normal(ks[1], (L, w)) * 0.01,
    }
    x = jax.random.normal(jax.random.key(2), (args.batch, w))

    def layer_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"]) + h

    def timeit(fn, *a, iters=20):
        jax.block_until_ready(fn(*a))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    # sequential baseline: all layers on one device (pipe=1 fallback path)
    mesh1 = MeshConfig(data=1, fsdp=1, tensor=1, seq=1, pipe=1, expert=1).build(jax.devices()[:1])
    seq_fn = jax.jit(lambda p, x: pipeline_apply(layer_fn, p, x, mesh=mesh1, num_microbatches=1))
    t_seq = timeit(seq_fn, params, x)

    import re

    rows = []
    for s in (2, 4, 8):
        if args.devices < s or L % s:
            continue
        mesh = MeshConfig(pipe=s, data=1, fsdp=1, tensor=1, seq=1, expert=1).build(jax.devices()[:s])
        sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)
        for m in (4, 8, 16):
            if args.batch % m:
                continue
            fn = jax.jit(lambda p, x, _m=m, _mesh=mesh: pipeline_apply(
                layer_fn, p, x, mesh=_mesh, num_microbatches=_m))
            t_pipe = timeit(fn, sharded, x)
            t_ideal = (t_seq / s) * (m + s - 1) / m

            # structural checks against the compiled program
            hlo = fn.lower(sharded, x).compile().as_text()
            n_psum = len(re.findall(r"all-reduce(?:-start)?\(", hlo))
            # every collective-permute must be the neighbor ring
            # {0->1, 1->2, ..., S-1->0} — no skip links, no gathers
            ring = {(j, (j + 1) % s) for j in range(s)}
            pair_sets = [
                {tuple(map(int, p.split(","))) for p in re.findall(r"\{(\d+,\d+)\}", block)}
                for block in re.findall(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}", hlo)
            ]
            structural_ok = bool(
                re.search(rf"constant\({m + s - 1}\)", hlo)  # trip-count constant present
                and pair_sets
                and all(ps == ring for ps in pair_sets)
                and n_psum <= 1  # one replication psum, nothing else
                and "all-gather" not in hlo  # params never gathered
            )
            rows.append({
                "stages": s, "microbatches": m,
                "ticks": m + s - 1,
                "t_seq_ms": round(t_seq * 1e3, 2),
                "t_pipe_ms": round(t_pipe * 1e3, 2),
                "t_ideal_ms": round(t_ideal * 1e3, 2),
                "overhead_vs_bound": round(t_pipe / t_ideal, 3),
                "structural_ok": structural_ok,
            })
            print(json.dumps(rows[-1]), flush=True)

    if not rows:
        print(json.dumps({"bench": "pipeline_bubble",
                          "error": f"no runnable (stages, microbatches) for devices={args.devices}, "
                                   f"layers={L}, batch={args.batch}"}), flush=True)
        raise SystemExit(2)
    worst = max(r["overhead_vs_bound"] for r in rows)
    assert all(r["structural_ok"] for r in rows), "schedule structure violates the bubble bound"
    print(json.dumps({"bench": "pipeline_bubble", "worst_overhead_vs_bound": worst,
                      "structural_bound_ok": True}), flush=True)


if __name__ == "__main__":
    main()
