"""Elastic checkpoint restore benchmark: restore latency + predicted
reshard bytes across topology changes.

The ROADMAP note says evidence must be CPU-derivable, so this measures
what CAN be measured without a pod — wall-clock save/restore latency on
the 8-device fake-CPU mesh — and reports what the cost model *predicts*
for the part a pod would feel: the post-restore reshard traffic (ICI vs
DCN wire bytes from ``analysis.costmodel.reshard_cost``, the same
numbers ``accelerate-tpu checkpoints describe`` prints).

One JSON line per (save mesh -> restore mesh) direction::

    {"bench": "restore", "src": "data=4", "dst": "data=8",
     "compatibility": "elastic", "save_s": ..., "restore_s": ...,
     "predicted_reshard_ici_bytes": ..., "predicted_reshard_dcn_bytes": ...,
     "params_bit_exact": true, "step_preserved": true}

Usage: python benchmarks/bench_restore.py [--small] [--layers N]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

from accelerate_tpu.utils.environment import force_host_platform

force_host_platform(8)  # before any jax import: the fake multi-chip mesh

import argparse
import json
import tempfile
import time


MESHES = {
    "data=4": dict(data=4, num_devices=4),
    "data=8": dict(data=8),
    "data=2,tensor=2": dict(data=2, tensor=2, num_devices=4),
    "data=1": dict(data=1, num_devices=1),
}

DIRECTIONS = [
    ("data=4", "data=8"),        # grow
    ("data=4", "data=1"),        # shrink to one device
    ("data=2,tensor=2", "data=4"),  # re-layout at equal size
    ("data=4", "data=4"),        # identical-topology control (zero reshard)
]


def _reset():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _build(project_dir: str, mesh_name: str, cfg):
    from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin, ProjectConfiguration
    from accelerate_tpu.models import create_llama_model

    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True, total_limit=1
        ),
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(**MESHES[mesh_name])),
    )
    model = acc.prepare_model(create_llama_model(cfg, seq_len=32))
    import optax

    acc.prepare_optimizer(optax.adam(1e-3))
    return acc, model


def bench_direction(src: str, dst: str, cfg) -> dict:
    import jax
    import numpy as np

    from accelerate_tpu.commands.checkpoints import describe_checkpoint
    from accelerate_tpu.ft import CheckpointManager

    with tempfile.TemporaryDirectory() as project_dir:
        acc, model = _build(project_dir, src, cfg)
        acc.step = 7
        t0 = time.perf_counter()
        out = acc.save_state()
        save_s = time.perf_counter() - t0
        want = [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(model.params)]
        assert CheckpointManager(os.path.join(project_dir, "checkpoints")).verify(out).ok

        # what `checkpoints describe` would predict for this direction
        dst_shape = {k: v for k, v in MESHES[dst].items() if k != "num_devices"}
        info = describe_checkpoint(out, target_mesh=dst_shape)

        acc2, model2 = _build(project_dir, dst, cfg)
        t0 = time.perf_counter()
        acc2.load_state()
        restore_s = time.perf_counter() - t0
        got = [np.asarray(x) for x in jax.tree_util.tree_leaves(model2.params)]
        bit_exact = all(np.array_equal(a, b) for a, b in zip(want, got))

        return {
            "bench": "restore",
            "src": src,
            "dst": dst,
            "compatibility": info["compatibility"],
            "array_count": info["reshard"]["array_count"],
            "checkpoint_bytes": info["reshard"]["total_array_bytes"],
            "save_s": round(save_s, 4),
            "restore_s": round(restore_s, 4),
            "predicted_reshard_ici_bytes": info["reshard"]["ici_bytes"],
            "predicted_reshard_dcn_bytes": info["reshard"]["dcn_bytes"],
            "params_bit_exact": bit_exact,
            "step_preserved": acc2.step == 7,
        }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--small", action="store_true", help="tiny model (CI smoke)")
    parser.add_argument("--layers", type=int, default=None)
    args = parser.parse_args()

    from accelerate_tpu.models import LlamaConfig

    if args.small:
        cfg = LlamaConfig(hidden_size=64, intermediate_size=128, num_hidden_layers=args.layers or 2,
                          num_attention_heads=4, num_key_value_heads=4, vocab_size=256)
    else:
        cfg = LlamaConfig(hidden_size=512, intermediate_size=1024, num_hidden_layers=args.layers or 4,
                          num_attention_heads=8, num_key_value_heads=8, vocab_size=4096)

    for src, dst in DIRECTIONS:
        print(json.dumps(bench_direction(src, dst, cfg)))


if __name__ == "__main__":
    main()
