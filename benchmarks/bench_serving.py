"""Open-loop Poisson load generator for the serving scheduler A/B.

Many synthetic clients submit requests at Poisson arrival times that do
NOT depend on completions (open loop — the honest way to measure tail
latency under load: a closed loop self-throttles exactly when the server
is slow, hiding the tail). The SAME pre-generated workload (arrival
times, prompt lengths, decode budgets) is replayed against two engines:

* ``fifo`` — the legacy admit-then-tick loop (``SchedulerConfig(mode=
  "fifo")``): a long chunked prefill runs to completion inside one tick,
  stalling every active decode and every later admission behind it;
* ``continuous`` — the token-budget scheduler: decodes claim their
  tokens first, prefills stream one budget-claimed chunk window per
  tick, so short requests admit and decode while a long prompt is still
  prefilling.

Reported per scheduler: sustained tokens/sec, p50/p95 TTFT *per
priority class*, p95 inter-token latency, shed rate, and the
post-warmup compile count (the recompile-watchdog criterion: bucketed
chunk windows + fixed decode shapes => ZERO XLA compiles in steady
state; the warmup primes every bucket, chunk-window width, and tick
program the workload can reach).

The headline is the INTERACTIVE class's p95 TTFT: the batch class's
latency under the scheduler is policy (it yields the queue, streams its
prefill in budget-claimed chunks, and may be preempted or shed), so a
single mixed percentile would drift between the two populations run to
run and hide exactly the tail the SLO protects.

CPU-jax runnable: ``python benchmarks/bench_serving.py --smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(vals, q):
    return round(float(np.percentile(np.asarray(vals, np.float64), q)), 2) if len(vals) else None


def build_workload(args, vocab, rng):
    """[(arrival_s, prompt, max_new, priority), ...] — generated ONCE so
    every scheduler sees the identical offered load."""
    events, t = [], 0.0
    chunk = max(args.buckets)
    for _ in range(args.clients):
        t += float(rng.exponential(1.0 / args.rate))
        if rng.random() < args.long_frac:
            # the batch-class request: 10+ chunk windows of prefill AND a
            # long decode, so it both stalls a fifo tick and pins a large
            # share of the KV pool for a long time. Priority 1: the fifo
            # baseline ignores priority; the continuous scheduler admits
            # interactive traffic ahead of it, streams its prefill in
            # budget-claimed chunks, and may preempt its decode
            plen = int(rng.integers(10 * chunk + 1, 12 * chunk))
            n_new = int(args.long_decode)
            prio = 1
        else:
            plen = int(rng.integers(2, chunk))
            n_new = int(rng.choice(args.decode_budgets))
            prio = 0
        prompt = rng.integers(1, vocab - 1, size=plen).astype(np.int32)
        events.append((t, prompt, n_new, prio))
    return events


def warmup(engine, args, vocab, rng):
    """Prime every program the workload can reach: one fused prefill per
    bucket, chunk_cold/chunk_warm at every window width (each bucket as a
    suffix window + the full chunk), the decode tick, and sample/insert.
    After this, steady state must be replay-only."""
    chunk = max(args.buckets)
    lens = list(args.buckets) + [chunk + b for b in args.buckets] + [2 * chunk + 2]
    for n in lens:
        engine.submit(rng.integers(1, vocab - 1, size=n).astype(np.int32), max_new_tokens=2)
    engine.run()


def drive(engine, events, chunk):
    """Replay the arrival schedule in real time. Returns ``(elapsed_s,
    rejected, ttft_short_ms, ttft_long_ms)`` — per-request TTFT measured
    at the harness (arrival -> first streamed token via the O(1)
    ``partial`` accessor), split by prompt class so the tail of the many
    short interactive requests is visible separately from the few
    long-context ones whose first token chunked prefill deliberately
    spreads out."""
    from accelerate_tpu.scheduling import ShedError

    t0 = time.monotonic()
    pending = list(events)
    rejected = 0
    waiting = {}  # uid -> (arrival_s, is_long)
    ttft_short, ttft_long = [], []
    while pending or engine.queue or engine.active_count:
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            at, prompt, n_new, prio = pending.pop(0)
            try:
                uid = engine.submit(prompt, max_new_tokens=n_new, priority=prio)
                waiting[uid] = (at, len(prompt) > chunk)
            except ShedError:
                rejected += 1
        if engine.queue or engine.active_count:
            engine.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - (time.monotonic() - t0))))
        now = time.monotonic() - t0
        for uid, (at, is_long) in list(waiting.items()):
            try:
                got_first = engine.partial(uid).size > 0
            except (KeyError, ShedError):
                del waiting[uid]
                continue
            if got_first:
                (ttft_long if is_long else ttft_short).append((now - at) * 1000.0)
                del waiting[uid]
    return time.monotonic() - t0, rejected, ttft_short, ttft_long


def run_one(name, scheduler, model, args, vocab, events, rng):
    from accelerate_tpu.serving import ServingEngine

    engine = ServingEngine(
        model, num_slots=args.slots, prompt_buckets=tuple(args.buckets),
        tick_block=args.tick_block, scheduler=scheduler,
        paged_block_size=args.block_size, pool_blocks=args.pool_blocks,
    )
    warmup(engine, args, vocab, rng)
    # steady-state baseline: warmup latencies out of the windows, compile
    # count snapshotted — everything after this line is replay-only
    m = engine.metrics
    for window in (m.ttft_ms, m.e2e_ms, m.itl_ms, m.queue_wait_ms):
        window.clear()
    compiles_before = engine.program_cache.misses
    completed0, m0_tokens = m.requests_completed, m.tokens_generated
    elapsed, rejected, ttft_short, ttft_long = drive(engine, events, max(args.buckets))
    shed_total = m.requests_shed  # submit rejects + queue-wait sheds
    return {
        "scheduler": name,
        "elapsed_s": round(elapsed, 2),
        "completed": m.requests_completed - completed0,
        "offered": len(events),
        "sustained_tokens_per_sec": round((m.tokens_generated - m0_tokens) / elapsed, 1),
        # headline latency = the interactive class's tail under the mixed
        # load. The batch class's latency is scheduler POLICY (it yields,
        # streams its prefill, may be preempted or shed), so folding both
        # classes into one percentile would let 12 batch requests mask a
        # 10x interactive-tail regression — report each class honestly.
        "interactive_ttft_ms_p50": _pct(ttft_short, 50),
        "interactive_ttft_ms_p95": _pct(ttft_short, 95),
        "batch_ttft_ms_p50": _pct(ttft_long, 50),
        "batch_ttft_ms_p95": _pct(ttft_long, 95),
        "overall_ttft_ms_p95": _pct(ttft_short + ttft_long, 95),
        "itl_ms_p95": _pct(m.itl_ms, 95),
        "queue_wait_ms_p95": _pct(m.queue_wait_ms, 95),
        "shed_rate": round(shed_total / max(1, len(events)), 4),
        "decode_preemptions": m.decode_preemptions,
        "post_warmup_compiles": engine.program_cache.misses - compiles_before,
    }


# ===================================================================== #
# fleet mode (--fleet): multi-replica router benchmark
# ===================================================================== #


def fleet_model():
    """Small enough that a 4-replica fleet drains on a CI box, big enough
    that re-prefilling a multi-chunk preamble visibly costs TTFT."""
    from accelerate_tpu.models import LlamaConfig, create_llama_model

    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=512,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=384,
    )
    return create_llama_model(cfg, seq_len=384), cfg


def fleet_workload(args, vocab, rng):
    """Shared-preamble open-loop schedule: every request is one of
    ``n_preambles`` system prompts (several chunk windows long — the
    tokens prefix reuse saves) plus a short unique suffix. Generated once
    so every arm replays the identical offered load."""
    preambles = [
        rng.integers(1, vocab - 1, size=args.preamble_len).astype(np.int32)
        for _ in range(args.n_preambles)
    ]
    events, t = [], 0.0
    for _ in range(args.fleet_clients):
        t += float(rng.exponential(1.0 / args.fleet_rate))
        pre = preambles[int(rng.integers(0, len(preambles)))]
        suffix = rng.integers(1, vocab - 1, size=int(rng.integers(2, max(args.buckets)))).astype(np.int32)
        prompt = np.concatenate([pre, suffix])
        events.append((t, prompt, int(rng.choice(args.decode_budgets))))
    return events


def make_fleet(model, args, *, replicas, prefix_reuse=True, roles=None, handoff="auto",
               failover="auto", store_dir=None, trace=None):
    from accelerate_tpu.serving_fleet import FleetConfig, FleetRouter

    return FleetRouter.from_model(
        model, num_replicas=replicas,
        config=FleetConfig(
            roles=roles, handoff=handoff, prefix_reuse=prefix_reuse, failover=failover,
            min_prefix_tokens=args.buckets[0], promote_after=2, max_prefix_entries=8,
        ),
        store_dir=store_dir, trace=trace,
        num_slots=args.slots, prompt_buckets=tuple(args.buckets),
        tick_block=args.tick_block, max_len=model.config.max_position_embeddings,
    )


def fleet_warmup(router, args, vocab, rng):
    """Prime every program any arm can reach on EVERY replica: fused
    buckets, chunk windows at each width (plain + as a suffix window),
    the decode tick — and, for disaggregated fleets, one handoff (its
    paste sees host-resident arrays, a distinct input signature). After
    this, steady state must be replay-only across radix hits AND
    misses."""
    chunk = max(args.buckets)
    lens = list(args.buckets) + [chunk + b for b in args.buckets] + [2 * chunk + 2]
    for rep in router.replicas:
        eng = rep.engine
        for n in lens:
            eng.submit(rng.integers(1, vocab - 1, size=n).astype(np.int32), max_new_tokens=2)
        eng.run()
        if rep.can_prefill():
            # prefix-seeded suffix windows (the radix-hit path): register +
            # serve one suffix per bucket width, then drop the prefix
            pid = eng.register_prefix(rng.integers(1, vocab - 1, size=chunk + 2).astype(np.int32))
            for b in args.buckets:
                eng.submit(rng.integers(1, vocab - 1, size=b).astype(np.int32),
                           max_new_tokens=2, prefix_id=pid)
            eng.run()
            eng.unregister_prefix(pid)
    if router.disaggregated:
        src = next(r for r in router.replicas if r.can_prefill())
        for rep in router.replicas:
            if rep.can_decode():
                h = src.engine.prefill_detached(
                    rng.integers(1, vocab - 1, size=args.buckets[0]).astype(np.int32),
                    max_new_tokens=2, uid_key=2**30,
                )
                rep.engine.submit_prefilled(h)
                rep.engine.run()


def fleet_compiles(router) -> int:
    return sum(r.engine.program_cache.misses for r in router.replicas)


def fleet_drive(router, events):
    """Replay the arrival schedule in real time against the router;
    returns ``(elapsed_s, ttft_ms list in submission order, outputs,
    logprobs)`` with TTFT measured at the harness (arrival -> first
    streamed token via ``partial``)."""
    t0 = time.monotonic()
    pending = list(events)
    waiting, ttft, uids = {}, {}, []
    while pending or router._work_remaining():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            at, prompt, n_new = pending.pop(0)
            uid = router.submit(prompt, max_new_tokens=n_new)
            uids.append(uid)
            waiting[uid] = at
        if router._work_remaining():
            router.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - (time.monotonic() - t0))))
        now = time.monotonic() - t0
        for uid, at in list(waiting.items()):
            if router.partial(uid).size > 0:
                ttft[uid] = (now - at) * 1000.0
                del waiting[uid]
    elapsed = time.monotonic() - t0
    outs = [np.asarray(router.poll(u)) for u in uids]
    lps = [np.asarray(router.logprobs(u)) for u in uids]
    return elapsed, [ttft[u] for u in uids], outs, lps


def run_fleet(args) -> int:
    """The fleet benchmark: prefix-reuse A/B (p95 TTFT + exactness),
    aggregate-throughput scaling vs replica count, cold-vs-warm replica
    spin-up over a shared executable store, and KV-handoff byte
    accounting vs the cost-model prediction. Prints the JSON report;
    exit code 1 unless every criterion holds."""
    import tempfile

    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)
    model, cfg = fleet_model()
    vocab = cfg.vocab_size
    args.buckets = (16, 32)
    args.decode_budgets = (8, 16, 24)
    args.preamble_len = args.preamble_len or 96
    args.n_preambles = args.n_preambles or 3
    args.fleet_clients = args.fleet_clients or 40
    args.fleet_rate = args.fleet_rate or 6.0
    args.slots = args.slots or 2
    args.tick_block = args.tick_block or 4
    rng = np.random.default_rng(args.seed)
    events = fleet_workload(args, vocab, rng)
    report = {
        "bench": "bench_serving --fleet",
        "clients": args.fleet_clients,
        "rate_req_per_s": args.fleet_rate,
        "preamble_len": args.preamble_len,
        "n_preambles": args.n_preambles,
        "slots_per_replica": args.slots,
        "buckets": list(args.buckets),
    }

    # -- arm 1: prefix reuse A/B under shared-preamble traffic ----------- #
    arms = {}
    for name, reuse in (("no_reuse", False), ("reuse", True)):
        router = make_fleet(model, args, replicas=2, prefix_reuse=reuse)
        fleet_warmup(router, args, vocab, np.random.default_rng(args.seed + 1))
        for rep in router.replicas:
            for w in (rep.engine.metrics.ttft_ms, rep.engine.metrics.e2e_ms,
                      rep.engine.metrics.itl_ms, rep.engine.metrics.queue_wait_ms):
                w.clear()
        c0 = fleet_compiles(router)
        elapsed, ttft, outs, lps = fleet_drive(router, events)
        merged = router.metrics_merged()
        arms[name] = {
            "elapsed_s": round(elapsed, 2),
            "ttft_ms_p50": _pct(ttft, 50),
            "ttft_ms_p95": _pct(ttft, 95),
            "tokens_per_sec": round(merged.tokens_generated / elapsed, 1),
            "prefix_hits": merged.prefix_hits,
            "prefix_misses": merged.prefix_misses,
            "prefix_tokens_reused": merged.prefix_tokens_reused,
            "post_warmup_compiles": fleet_compiles(router) - c0,
            "_outs": outs,
            "_lps": lps,
        }
    exact_tokens = all(
        np.array_equal(a, b) for a, b in zip(arms["no_reuse"]["_outs"], arms["reuse"]["_outs"])
    )
    exact_lps = all(
        np.array_equal(a, b) for a, b in zip(arms["no_reuse"]["_lps"], arms["reuse"]["_lps"])
    )
    for arm in arms.values():
        del arm["_outs"], arm["_lps"]
    report["prefix_reuse_ab"] = arms
    report["reuse_exact_tokens"] = exact_tokens
    report["reuse_exact_logprobs"] = exact_lps
    report["reuse_ttft_p95_speedup"] = round(
        arms["no_reuse"]["ttft_ms_p95"] / max(1e-9, arms["reuse"]["ttft_ms_p95"]), 3
    )

    # -- arm 2: aggregate throughput scaling vs replica count ------------ #
    # One drain thread per replica; XLA releases the GIL during device
    # compute, so replicas overlap exactly as far as the host has cores.
    # On a single-core host the honest claim is NOT scale-up (physically
    # impossible in-process) but bounded serialization overhead — the
    # criteria below pick the claim that matches the hardware and the
    # report names which one was enforced.
    scaling = {}
    drain_events = [(0.0, p, n) for _, p, n in events]
    for n_rep in (1, 2, 4):
        router = make_fleet(model, args, replicas=n_rep, prefix_reuse=True)
        fleet_warmup(router, args, vocab, np.random.default_rng(args.seed + 1))
        toks0 = sum(r.engine.metrics.tokens_generated for r in router.replicas)
        for _, p, n in drain_events:
            router.submit(p, max_new_tokens=n)
        elapsed = router.drain_threaded()
        toks = sum(r.engine.metrics.tokens_generated for r in router.replicas) - toks0
        scaling[str(n_rep)] = {
            "tokens_per_sec": round(toks / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "tokens": int(toks),
            "aggregate_slots": n_rep * args.slots,
        }
    report["scaling"] = scaling
    report["host_cores"] = os.cpu_count() or 1

    # -- arm 3: replica spin-up, cold vs warm over a shared store -------- #
    warm_lens = (args.buckets[0], 2 * max(args.buckets) + 2)
    with tempfile.TemporaryDirectory() as store_dir:
        router = make_fleet(model, args, replicas=1, prefix_reuse=True, store_dir=store_dir)
        cold = router.spin_up(warm_prompt_lens=warm_lens)
        warm = router.spin_up(warm_prompt_lens=warm_lens)
        router2 = make_fleet(model, args, replicas=1, prefix_reuse=True, store_dir=None)
        nostore = router2.spin_up(warm_prompt_lens=warm_lens)
    report["spinup"] = {
        "cold_store": cold,
        "warm_store": warm,
        "no_store": nostore,
        "speedup": round(nostore["spinup_ms"] / max(1e-9, warm["spinup_ms"]), 3),
    }

    # -- arm 4: disaggregated prefill/decode + handoff accounting -------- #
    router = make_fleet(model, args, replicas=2, prefix_reuse=False,
                        roles=("prefill", "decode"), handoff="always")
    fleet_warmup(router, args, vocab, np.random.default_rng(args.seed + 1))
    c0 = fleet_compiles(router)
    ref_router = make_fleet(model, args, replicas=1, prefix_reuse=False)
    fleet_warmup(ref_router, args, vocab, np.random.default_rng(args.seed + 1))
    handoff_events = events[:10]
    uids = [router.submit(p, max_new_tokens=n) for _, p, n in handoff_events]
    refs = [ref_router.submit(p, max_new_tokens=n) for _, p, n in handoff_events]
    done, ref_done = router.run(), ref_router.run()
    acct = router.handoff_accounting()
    disagg_exact = all(
        np.array_equal(done[u], ref_done[r]) for u, r in zip(uids, refs)
    )
    report["disaggregated"] = {
        **acct,
        "requests": len(uids),
        "exact_vs_local": disagg_exact,
        "post_warmup_compiles": fleet_compiles(router) - c0,
        "bytes_match": acct["bytes_predicted"] == acct["bytes_moved"],
    }

    # -- criteria -------------------------------------------------------- #
    criteria = {
        "reuse_p95_wins": (arms["reuse"]["ttft_ms_p95"] or 1e9)
        < (arms["no_reuse"]["ttft_ms_p95"] or 0),
        "reuse_exact": exact_tokens and exact_lps,
        "reuse_hits": arms["reuse"]["prefix_hits"] > 0
        and arms["no_reuse"]["prefix_hits"] == 0,
        "zero_post_warmup_compiles": arms["reuse"]["post_warmup_compiles"] == 0
        and arms["no_reuse"]["post_warmup_compiles"] == 0
        and report["disaggregated"]["post_warmup_compiles"] == 0,
        # multi-core host: the fleet must actually scale aggregate
        # throughput; single-core host: in-process replicas serialize, so
        # the enforceable claim is that fleet overhead stays bounded
        "scaling_up (multi-core)" if (os.cpu_count() or 1) > 1 else "serial_overhead_bounded (1 core)": (
            max(scaling["2"]["tokens_per_sec"], scaling["4"]["tokens_per_sec"])
            > 1.15 * scaling["1"]["tokens_per_sec"]
            if (os.cpu_count() or 1) > 1
            else scaling["4"]["tokens_per_sec"] >= 0.5 * scaling["1"]["tokens_per_sec"]
        ),
        "warm_spinup_zero_compiles": warm["compiles"] == 0 and warm["deserialized"] > 0,
        "cold_spinup_compiles": nostore["compiles"] > 0,
        "warm_spinup_faster": warm["spinup_ms"] < nostore["spinup_ms"],
        "handoff_bytes_match": report["disaggregated"]["bytes_match"]
        and acct["bytes_moved"] > 0,
        "disagg_exact": disagg_exact,
    }
    report["criteria"] = criteria
    report["ok"] = all(criteria.values())
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


# ===================================================================== #
# chaos mode (--chaos): kill a replica mid-flight, hold the fleet exact
# ===================================================================== #


def chaos_drive(router, events):
    """``fleet_drive`` variant that tolerates requests lost to a replica
    failure: a ``KeyError`` from ``partial``/``poll`` marks the request
    lost instead of aborting the harness, so the bench can FAIL the
    ``zero_lost`` criterion honestly. Returns ``(elapsed_s, ttft_ms by
    uid, uids, outputs by uid, logprobs by uid, lost uids)``."""
    t0 = time.monotonic()
    pending = list(events)
    waiting, ttft, uids, lost = {}, {}, [], []
    while pending or router._work_remaining():
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            at, prompt, n_new = pending.pop(0)
            uid = router.submit(prompt, max_new_tokens=n_new)
            uids.append(uid)
            waiting[uid] = at
        if router._work_remaining():
            router.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - (time.monotonic() - t0))))
        now = time.monotonic() - t0
        for uid, at in list(waiting.items()):
            try:
                streamed = router.partial(uid).size > 0
            except KeyError:
                lost.append(uid)
                del waiting[uid]
                continue
            if streamed:
                ttft[uid] = (now - at) * 1000.0
                del waiting[uid]
    elapsed = time.monotonic() - t0
    outs, lps = {}, {}
    for u in uids:
        try:
            outs[u] = np.asarray(router.poll(u))
            lps[u] = np.asarray(router.logprobs(u))
        except KeyError:
            if u not in lost:
                lost.append(u)
    return elapsed, ttft, uids, outs, lps, lost


def run_chaos(args) -> int:
    """The serving chaos benchmark (``--chaos``): crash a replica
    mid-flight under the open-loop schedule and hold the fleet to
    token-exact failover. A no-fault control arm and the chaos arm
    replay the identical arrivals over 3 mixed replicas sharing one
    executable store; the chaos arm kills ``r1`` at its Nth busy tick
    (``ReplicaChaos("pre_tick")``), survivors absorb every in-flight
    request via priced KV handoff (or prefix recompute when no KV was
    exportable), and ``add_replica()`` then restores capacity from the
    store with zero XLA compiles. Prints the JSON report; exit code 1
    unless every criterion holds."""
    import tempfile

    from accelerate_tpu.test_utils.fault_injection import ReplicaChaos
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)
    model, cfg = fleet_model()
    vocab = cfg.vocab_size
    args.buckets = (16, 32)
    args.decode_budgets = (8, 16, 24)
    args.preamble_len = args.preamble_len or (48 if args.smoke else 64)
    args.n_preambles = args.n_preambles or 2
    args.fleet_clients = args.fleet_clients or (24 if args.smoke else 48)
    args.fleet_rate = args.fleet_rate or 8.0
    args.slots = args.slots or 2
    args.tick_block = args.tick_block or 4
    crash_tick = 6 if args.smoke else 10
    rng = np.random.default_rng(args.seed)
    events = fleet_workload(args, vocab, rng)
    report = {
        "bench": "bench_serving --chaos",
        "clients": args.fleet_clients,
        "rate_req_per_s": args.fleet_rate,
        "replicas": 3,
        "slots_per_replica": args.slots,
        "buckets": list(args.buckets),
        "crash": {"replica": "r1", "point": "pre_tick", "busy_tick": crash_tick,
                  "action": "crash"},
    }

    def paste_warm(router, wrng):
        # the handoff-import paste (host-resident arrays) is a distinct
        # input signature fleet_warmup only covers for disaggregated
        # fleets; failover ships KV between MIXED replicas, so warm it
        # everywhere or the first migration compiles on the survivor
        src = router.replicas[0].engine
        for i, rep in enumerate(router.replicas):
            h = src.prefill_detached(
                wrng.integers(1, vocab - 1, size=args.buckets[0]).astype(np.int32),
                max_new_tokens=2, uid_key=2**30 + i,
            )
            rep.engine.submit_prefilled(dict(h))
            rep.engine.run()

    def build(store):
        router = make_fleet(model, args, replicas=3, prefix_reuse=False,
                            failover="handoff", store_dir=store)
        fleet_warmup(router, args, vocab, np.random.default_rng(args.seed + 1))
        paste_warm(router, np.random.default_rng(args.seed + 2))
        return router, fleet_compiles(router)

    with tempfile.TemporaryDirectory() as store:
        # -- control arm: identical schedule, no fault ------------------- #
        control, c0 = build(store)
        elapsed_c, ttft_c, uids_c, outs_c, lps_c, lost_c = chaos_drive(control, events)
        ttft_c_list = [ttft_c[u] for u in uids_c if u in ttft_c]
        merged_c = control.metrics_merged()
        report["control"] = {
            "elapsed_s": round(elapsed_c, 2),
            "ttft_ms_p50": _pct(ttft_c_list, 50),
            "ttft_ms_p95": _pct(ttft_c_list, 95),
            "tokens_per_sec": round(merged_c.tokens_generated / elapsed_c, 1),
            "completed": len(outs_c),
            "lost": len(lost_c),
            "post_warmup_compiles": fleet_compiles(control) - c0,
        }

        # -- chaos arm: crash r1 at its Nth busy tick -------------------- #
        router, c0 = build(store)
        with ReplicaChaos("pre_tick", replica="r1", action="crash",
                          hits=crash_tick) as chaos:
            elapsed_x, ttft_x, uids_x, outs_x, lps_x, lost_x = chaos_drive(router, events)
        survivor_compiles = fleet_compiles(router) - c0
        acct = router.failover_accounting()
        ttft_x_list = [ttft_x[u] for u in uids_x if u in ttft_x]
        merged_x = router.metrics_merged()
        report["chaos"] = {
            "elapsed_s": round(elapsed_x, 2),
            "ttft_ms_p50": _pct(ttft_x_list, 50),
            "ttft_ms_p95": _pct(ttft_x_list, 95),
            "tokens_per_sec": round(merged_x.tokens_generated / elapsed_x, 1),
            "completed": len(outs_x),
            "lost": len(lost_x),
            "crash_fired": chaos.fired,
            "post_warmup_compiles_survivors": survivor_compiles,
            "failover_accounting": acct,
            "health": {n: {"health": h["health"], "last_error": h["last_error"]}
                       for n, h in router.health().items()},
        }
        exact_tokens = len(outs_x) == len(outs_c) and all(
            np.array_equal(outs_x[u], outs_c[u]) for u in uids_c if u in outs_c
        )
        exact_lps = len(lps_x) == len(lps_c) and all(
            np.array_equal(lps_x[u], lps_c[u]) for u in uids_c if u in lps_c
        )

        # -- recovery: hot re-add over the store, then fresh traffic ----- #
        readd = router.add_replica(warm_prompt_lens=(16, 32, 48, 64, 66))
        new = router.replicas[-1]
        m0 = new.engine.program_cache.misses
        followup = [router.submit(p, max_new_tokens=n) for _, p, n in events[:6]]
        done = router.run()
        followup_ok = all(u in done for u in followup)
        readd["post_traffic_compiles"] = new.engine.program_cache.misses - m0
        serving = sum(
            1 for v in router.health().values()
            if v["health"] in ("healthy", "degraded") and not v["draining"]
        )
        readd["serving_replicas"] = serving
        readd["followup_completed"] = sum(1 for u in followup if u in done)
        report["readd"] = readd

    # in-process CPU fleet: survivors absorb the dead replica's load on
    # the same host cores, so the honest claim is BOUNDED p95 TTFT
    # degradation under the fault, not zero impact; the report names the
    # core count the bound was enforced on.
    report["host_cpu_count"] = os.cpu_count() or 1
    ttft_bound = 10.0 * report["control"]["ttft_ms_p95"] + 250.0
    criteria = {
        "chaos_completion_100": report["chaos"]["completed"] == len(events)
        and not lost_x,
        "zero_lost": acct["failovers_lost"] == 0 and not lost_x and not lost_c,
        "failover_exercised": chaos.fired and acct["failovers"] >= 1,
        "failover_kv_exercised": acct["failovers_kv"] >= 1,
        "accounting_pinned": acct["bytes_predicted"] == acct["bytes_moved"]
        and acct["bytes_moved"] > 0,
        "token_exact_vs_control": exact_tokens,
        "logprob_exact_vs_control": exact_lps,
        "survivor_zero_new_compiles": survivor_compiles == 0,
        "ttft_p95_bounded (single-host)": report["chaos"]["ttft_ms_p95"] <= ttft_bound,
        "readd_zero_compiles": readd["compiles"] == 0 and readd["deserialized"] > 0
        and readd["post_traffic_compiles"] == 0,
        "capacity_recovered": serving == 3 and followup_ok,
    }
    report["ttft_p95_bound_ms"] = round(ttft_bound, 3)
    report["criteria"] = criteria
    report["ok"] = all(criteria.values())
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


# ===================================================================== #
# trace mode (--trace): priced critical paths under disaggregation+chaos
# ===================================================================== #


def _trace_rows(router):
    """Completed fleet-request traces (warmup traffic is engine-submitted
    and carries no ``fuid``, so it filters out here)."""
    return [t for t in router.tracer.completed() if "fuid" in t.get("meta", {})]


def _ttft_decomposition(traces):
    """Per-class p50 of time spent BEFORE the first decode token — the
    trace-derived TTFT split (queue_wait / admit / prefill / kv_handoff /
    resume)."""
    acc = {}
    for tr in traces:
        pre = {}
        for sp in tr["spans"]:
            if sp["name"] == "decode":
                break
            pre[sp["name"]] = pre.get(sp["name"], 0.0) + sp["dur_ms"]
        for name, ms in pre.items():
            acc.setdefault(name, []).append(ms)
    return {name: _pct(vals, 50) for name, vals in sorted(acc.items())}


def run_trace(args) -> int:
    """The tracing benchmark (``--trace``): drive a DISAGGREGATED fleet
    (prefill replica handing KV to decode replicas) under the open-loop
    schedule with request tracing on, crash one decode replica
    mid-decode, and hold the whole telemetry story to account:

    * every completed request's segment sum must reconcile with its
      measured end-to-end latency within 5% (the spans are
      frontier-contiguous by construction — this pins that);
    * every router-side ``kv_handoff`` span's bytes AND microseconds
      must equal an independent ``price_kv_handoff`` recomputation;
    * the crashed requests' traces must show the ``failover`` span with
      ``moved_bytes == predicted_bytes`` (``price_failover``) and their
      outputs must be token- and logprob-exact vs the no-fault control;
    * zero ``trace_drift`` latches (the predictors were honest);
    * the dead replica must leave a flight-recorder dump whose tail
      holds the fault's ``replica_state`` event.

    Prints the JSON report; exit 1 unless every criterion holds."""
    import tempfile

    from accelerate_tpu.analysis.costmodel import price_kv_handoff
    from accelerate_tpu.test_utils.fault_injection import ReplicaChaos
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(1)
    model, cfg = fleet_model()
    vocab = cfg.vocab_size
    args.buckets = (16, 32)
    args.decode_budgets = (8, 16, 24)
    args.preamble_len = args.preamble_len or (48 if args.smoke else 64)
    args.n_preambles = args.n_preambles or 2
    args.fleet_clients = args.fleet_clients or (16 if args.smoke else 32)
    args.fleet_rate = args.fleet_rate or 8.0
    args.slots = args.slots or 2
    args.tick_block = args.tick_block or 4
    crash_tick = 4 if args.smoke else 8
    rng = np.random.default_rng(args.seed)
    events = fleet_workload(args, vocab, rng)
    report = {
        "bench": "bench_serving --trace",
        "clients": args.fleet_clients,
        "rate_req_per_s": args.fleet_rate,
        "replicas": 3,
        "roles": ["prefill", "decode", "decode"],
        "slots_per_replica": args.slots,
        "buckets": list(args.buckets),
        "crash": {"replica": "r1", "point": "mid_decode", "busy_visit": crash_tick,
                  "action": "crash"},
    }

    def build(store):
        router = make_fleet(
            model, args, replicas=3, prefix_reuse=False,
            roles=("prefill", "decode", "decode"), handoff="always",
            failover="handoff", store_dir=store, trace=True,
        )
        fleet_warmup(router, args, vocab, np.random.default_rng(args.seed + 1))
        return router

    def segment_gaps(traces):
        gaps = []
        for tr in traces:
            if tr["status"] != "ok" or tr["dur_ms"] <= 0:
                continue
            seg_sum = sum(sp["dur_ms"] for sp in tr["spans"])
            gaps.append(abs(tr["dur_ms"] - seg_sum) / tr["dur_ms"])
        return gaps

    def handoff_span_audit(router, traces):
        """(spans checked, all bytes exact, all us exact) against an
        independent price_kv_handoff recomputation."""
        per_tok, fixed = router.replicas[0].engine.kv_handoff_dims()
        checked, bytes_ok, us_ok = 0, True, True
        for tr in traces:
            for sp in tr["spans"]:
                if sp["name"] != "kv_handoff" or sp.get("moved_bytes") is None:
                    continue
                pred = price_kv_handoff(
                    per_tok, int(sp["tokens"]), fixed_bytes=fixed,
                    transport=router.config.transport,
                    generation=router.config.generation,
                )
                checked += 1
                if not (sp["moved_bytes"] == sp["predicted_bytes"] == pred["bytes"]):
                    bytes_ok = False
                if round(float(pred["time_us"]), 3) != sp["predicted_us"]:
                    us_ok = False
        return checked, bytes_ok, us_ok

    with tempfile.TemporaryDirectory() as store:
        # -- control arm: identical schedule, no fault ------------------- #
        control = build(store)
        elapsed_c, ttft_c, uids_c, outs_c, lps_c, lost_c = chaos_drive(control, events)
        traces_c = _trace_rows(control)
        gaps_c = segment_gaps(traces_c)
        checked_c, bytes_ok_c, us_ok_c = handoff_span_audit(control, traces_c)
        report["control"] = {
            "elapsed_s": round(elapsed_c, 2),
            "completed": len(outs_c),
            "lost": len(lost_c),
            "traced": len(traces_c),
            "max_segment_gap": round(max(gaps_c), 4) if gaps_c else None,
            "handoff_spans_checked": checked_c,
            "ttft_decomposition_ms_p50": _ttft_decomposition(traces_c),
            "drift_latches": sorted(control.critpath.drift_events),
        }

        # -- chaos arm: crash decode replica r1 mid-decode --------------- #
        router = build(store)
        with ReplicaChaos("mid_decode", replica="r1", action="crash",
                          hits=crash_tick) as chaos:
            elapsed_x, ttft_x, uids_x, outs_x, lps_x, lost_x = chaos_drive(router, events)
        traces_x = _trace_rows(router)
        gaps_x = segment_gaps(traces_x)
        checked_x, bytes_ok_x, us_ok_x = handoff_span_audit(router, traces_x)
        acct = router.failover_accounting()

        failover_spans = [
            (tr, sp)
            for tr in traces_x
            for sp in tr["spans"]
            if sp["name"] == "failover"
        ]
        failover_fuids = sorted({tr["meta"]["fuid"] for tr, _ in failover_spans})
        failover_bytes_ok = all(
            sp["moved_bytes"] == sp["predicted_bytes"]
            for _, sp in failover_spans
            if sp.get("path") == "handoff"
        )
        failover_exact = bool(failover_fuids) and all(
            u in outs_x and u in outs_c
            and np.array_equal(outs_x[u], outs_c[u])
            and np.array_equal(lps_x[u], lps_c[u])
            for u in failover_fuids
        )

        dead = next((r for r in router.replicas if r.health == "dead"), None)
        dump = dead.flightrec.last_dump if dead is not None and dead.flightrec else None
        dump_has_fault = bool(dump) and any(
            e.get("name") == "replica_state" and "SimulatedCrash" in str(e.get("reason", ""))
            for e in dump["events"]
        )
        report["chaos"] = {
            "elapsed_s": round(elapsed_x, 2),
            "completed": len(outs_x),
            "lost": len(lost_x),
            "traced": len(traces_x),
            "crash_fired": chaos.fired,
            "max_segment_gap": round(max(gaps_x), 4) if gaps_x else None,
            "handoff_spans_checked": checked_x,
            "failover_traced_fuids": failover_fuids,
            "failover_accounting": acct,
            "ttft_decomposition_ms_p50": _ttft_decomposition(traces_x),
            "drift_latches": sorted(router.critpath.drift_events),
            "flight_dump": None if not dump else {
                "replica": dead.name,
                "reason": dump["reason"],
                "events": len(dump["events"]),
                "inflight": len(dump["inflight"]),
                "open_spans": len(dump["open_spans"]),
            },
        }

    all_gaps = gaps_c + gaps_x
    criteria = {
        "chaos_completion_100": len(outs_x) == len(events) and not lost_x,
        "every_request_traced": len(traces_c) == len(events) == len(traces_x),
        "segment_sum_within_5pct": bool(all_gaps) and max(all_gaps) <= 0.05,
        "handoff_bytes_exact": checked_c + checked_x > 0 and bytes_ok_c and bytes_ok_x,
        "handoff_us_match_price": us_ok_c and us_ok_x,
        "failover_span_traced": chaos.fired and bool(failover_fuids),
        "failover_bytes_exact": failover_bytes_ok
        and acct["bytes_predicted"] == acct["bytes_moved"],
        "failover_token_and_logprob_exact": failover_exact,
        "zero_drift_latched": not control.critpath.drift_events
        and not router.critpath.drift_events,
        "flight_dump_holds_fault": dump_has_fault,
    }
    report["criteria"] = criteria
    report["ok"] = all(criteria.values())
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


# ===================================================================== #
# proc-chaos mode (--proc-chaos): SIGKILL a real worker PROCESS
# ===================================================================== #


def proc_workload(args, vocab, rng, budgets=(8, 12, 16)):
    """Open-loop Poisson arrivals for the process fleet — short prompts
    with mixed decode budgets, generated once so both arms replay the
    identical offered load."""
    events, t = [], 0.0
    for _ in range(args.proc_clients):
        t += float(rng.exponential(1.0 / args.proc_rate))
        plen = int(rng.integers(3, 13))
        prompt = [int(x) for x in rng.integers(1, vocab - 1, size=plen)]
        events.append((t, prompt, int(rng.choice(budgets))))
    return events


def proc_drive(sup, events, *, settle_s=240.0):
    """Replay the arrival schedule against a running supervisor; returns
    ``(elapsed_s, outs, lps, lost)`` keyed by submission index (fuids are
    minted in submission order in both arms, so index-aligned outputs
    compare token-exactly across arms)."""
    from accelerate_tpu.serving_proc import FleetRequestError

    t0 = time.monotonic()
    pending = list(events)
    fids, outs, lps, lost = [], {}, {}, {}
    deadline = t0 + settle_s
    while (pending or len(outs) + len(lost) < len(fids) or not fids) and time.monotonic() < deadline:
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            _at, prompt, n_new = pending.pop(0)
            fids.append(sup.submit(prompt, max_new_tokens=n_new))
        sup.pump()
        for i, f in enumerate(fids):
            if i in outs or i in lost:
                continue
            try:
                r = sup.poll(f)
            except FleetRequestError as e:
                lost[i] = str(e)
                continue
            if r is not None:
                outs[i] = np.asarray(r)
                lps[i] = np.asarray(sup.logprobs(f))
        if pending and not sup._work_remaining():
            time.sleep(min(0.002, max(0.0, pending[0][0] - (time.monotonic() - t0))))
    return time.monotonic() - t0, outs, lps, lost


def run_proc_chaos(args) -> int:
    """The process-fleet chaos benchmark (``--proc-chaos``): 3 REAL
    engine-worker subprocesses behind the :class:`ProcessSupervisor`,
    warm-started from one shared executable store. A no-fault control arm
    and a chaos arm replay identical arrivals; in the chaos arm worker
    ``w1`` SIGKILLs itself mid-decode (``ReplicaChaos`` installed via the
    spawn environment, so only that incarnation is poisoned). Criteria:
    zero requests lost, failover outputs token- AND logprob-exact vs
    control, failover bytes predicted == moved (``shadow_kv`` snapshots),
    zero post-warmup XLA compiles on the survivors, the respawned worker
    boots with zero compiles from the store, and the dead worker's
    flight-recorder dump holds the kill. Prints the JSON report; exit
    code 1 unless every criterion holds."""
    import glob
    import shutil
    import tempfile

    args.proc_clients = args.proc_clients or (10 if args.smoke else 16)
    # full mode arrives fast enough that the targeted worker holds
    # overlapping DECODING requests when the kill lands — the shadow
    # snapshot then carries KV and the failover takes the priced path
    args.proc_rate = args.proc_rate or (4.0 if args.smoke else 8.0)
    # the kill must land deep enough in decode that the last-polled
    # shadow snapshot carries decode-phase KV (queued/prefill snapshots
    # are recompute-only), but well inside the decode ticks the load
    # actually produces on the targeted worker; tick_block 2 with long
    # budgets stretches each decode across many 10ms status polls so a
    # decode-phase snapshot is always on file when the kill lands
    crash_hit = 12 if args.smoke else 20
    budgets = (16, 24, 32) if args.smoke else (24, 32, 48)
    model_kwargs = {
        "seq_len": 96, "max_position_embeddings": 96,
        "vocab_size": 512, "hidden_size": 128, "intermediate_size": 256,
    }
    engine_kwargs = {
        "num_slots": 2, "prompt_buckets": [8, 16], "max_len": 96, "tick_block": 2,
    }
    vocab = model_kwargs["vocab_size"]
    events = proc_workload(args, vocab, np.random.default_rng(args.seed), budgets)
    report = {
        "bench": "bench_serving --proc-chaos",
        "clients": args.proc_clients,
        "rate_req_per_s": args.proc_rate,
        "workers": 3,
        "engine": engine_kwargs,
        "crash": {"worker": "w1", "point": "mid_decode", "hit": crash_hit,
                  "action": "sigkill"},
        "host_cpu_count": os.cpu_count() or 1,
    }

    def build(run_dir, store_dir, chaos):
        from accelerate_tpu.serving_proc import ProcConfig, ProcessSupervisor

        sup = ProcessSupervisor(ProcConfig(
            workers=3, run_dir=run_dir, store_dir=store_dir,
            model_kwargs=model_kwargs, engine=engine_kwargs,
            warm_prompt_lens=(4, 12), poll_interval_s=0.01,
            heartbeat_timeout_s=20.0, shadow_kv=True, chaos=chaos,
            seed=args.seed,
        ))
        t0 = time.monotonic()
        sup.start(wait=True)
        boot_s = time.monotonic() - t0
        hellos = {
            s["name"]: dict(s["hello"] or {}) for s in sup._slots
        }
        return sup, boot_s, hellos

    def arm_summary(sup, elapsed, outs, lps, lost, boot_s, hellos):
        health = sup.health()
        return {
            "boot_s": round(boot_s, 2),
            "elapsed_s": round(elapsed, 2),
            "completed": len(outs),
            "lost": len(lost),
            "warm_compiles": {n: h.get("compiles") for n, h in hellos.items()},
            "warm_deserialized": {n: h.get("deserialized") for n, h in hellos.items()},
            "health": {n: v["health"] for n, v in health.items()},
            "summary": sup.summary(),
        }

    with tempfile.TemporaryDirectory() as tmp:
        store = os.path.join(tmp, "store")

        # -- control arm: identical schedule, no fault ------------------- #
        control, boot_c, hellos_c = build(os.path.join(tmp, "ctrl"), store, None)
        elapsed_c, outs_c, lps_c, lost_c = proc_drive(control, events)
        report["control"] = arm_summary(control, elapsed_c, outs_c, lps_c, lost_c,
                                        boot_c, hellos_c)
        control.shutdown()

        # -- chaos arm: SIGKILL w1 at its Nth decode tick ---------------- #
        chaos_dir = os.path.join(tmp, "chaos")
        chaos_cfg = {"worker": "w1", "label": "mid_decode", "action": "sigkill",
                     "hits": crash_hit}
        sup, boot_x, hellos_x = build(chaos_dir, store, chaos_cfg)
        elapsed_x, outs_x, lps_x, lost_x = proc_drive(sup, events)

        # survivors must have compiled nothing past their warmup; wait for
        # the respawned incarnation to hello so its spin-up is auditable
        deadline = time.monotonic() + 120.0
        respawned = None
        while time.monotonic() < deadline:
            sup.pump()
            respawned = next(
                (s for s in sup._slots
                 if s["respawns"] > 0 and s["health"] == "healthy" and s["hello"]),
                None,
            )
            if respawned is not None:
                break
            time.sleep(0.05)
        health_x = sup.health()
        survivor_compiles = {}
        for name, h in health_x.items():
            if name in hellos_x and h["health"] in ("healthy", "degraded"):
                warm = int(hellos_x[name].get("compiles") or 0)
                survivor_compiles[name] = int(h.get("compiles") or 0) - warm
        acct = dict(sup.failover_accounting())
        killed_fired = any(
            s["respawns"] > 0 for s in sup._slots
        ) or any(h["health"] == "dead" for h in health_x.values())
        respawn_hello = dict(respawned["hello"]) if respawned is not None else {}

        dump_path = next(iter(glob.glob(os.path.join(chaos_dir, "flight_w1.json"))), None)
        dump_holds_kill = False
        if dump_path:
            with open(dump_path) as f:
                dump = json.load(f)
            dump_holds_kill = any(
                e.get("name") == "proc_exit" and e.get("killed")
                for e in dump.get("events", [])
            )
            if args.proc_artifact_dir:
                os.makedirs(args.proc_artifact_dir, exist_ok=True)
                shutil.copy(dump_path,
                            os.path.join(args.proc_artifact_dir, "bench-proc-flight.json"))

        report["chaos"] = arm_summary(sup, elapsed_x, outs_x, lps_x, lost_x,
                                      boot_x, hellos_x)
        report["chaos"].update({
            "crash_fired": killed_fired,
            "survivor_post_warmup_compiles": survivor_compiles,
            "failover_accounting": acct,
            "respawned_worker": None if respawned is None else respawned["name"],
            "respawn_hello_compiles": respawn_hello.get("compiles"),
            "respawn_hello_deserialized": respawn_hello.get("deserialized"),
            "flight_dump": dump_path and os.path.basename(dump_path),
            "flight_dump_holds_kill": dump_holds_kill,
        })
        sup.shutdown()

    exact_tokens = len(outs_x) == len(outs_c) == len(events) and all(
        np.array_equal(outs_x[i], outs_c[i]) for i in outs_c
    )
    exact_lps = len(lps_x) == len(lps_c) and all(
        np.array_equal(lps_x[i], lps_c[i]) for i in lps_c
    )
    criteria = {
        "chaos_completion_100": len(outs_x) == len(events) and not lost_x,
        "zero_lost": not lost_x and not lost_c and acct["failovers_lost"] == 0,
        "crash_fired": killed_fired,
        "failover_exercised": acct["failovers"] >= 1,
        "failover_kv_exercised": acct["failovers_kv"] >= 1,
        "accounting_pinned": acct["bytes_predicted"] == acct["bytes_moved"]
        and acct["bytes_moved"] > 0,
        "token_exact_vs_control": exact_tokens,
        "logprob_exact_vs_control": exact_lps,
        "survivors_zero_new_compiles": bool(survivor_compiles)
        and all(v == 0 for v in survivor_compiles.values()),
        "respawn_zero_compiles": respawned is not None
        and respawn_hello.get("compiles") == 0
        and (respawn_hello.get("deserialized") or 0) > 0,
        "flight_dump_holds_kill": dump_holds_kill,
    }
    report["criteria"] = criteria
    report["ok"] = all(criteria.values())
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true", help="CPU CI mode: tiny model, bounded load")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: multi-replica router benchmark (reuse A/B, "
                         "scaling, spin-up, handoff accounting)")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos mode: crash a replica mid-flight and hold the fleet to "
                         "token-exact failover + zero-compile capacity recovery")
    ap.add_argument("--trace", action="store_true",
                    help="trace mode: disaggregated fleet with request tracing on — "
                         "segment-sum reconciliation, priced handoff/failover spans, "
                         "crash flight dump")
    ap.add_argument("--proc-chaos", dest="proc_chaos", action="store_true",
                    help="process chaos mode: 3 real engine-worker subprocesses, "
                         "SIGKILL one mid-decode, hold the fleet to zero-lost, "
                         "token/logprob-exact failover and zero-compile respawn")
    ap.add_argument("--proc-clients", dest="proc_clients", type=int, default=None)
    ap.add_argument("--proc-rate", dest="proc_rate", type=float, default=None)
    ap.add_argument("--proc-artifact-dir", dest="proc_artifact_dir", default=None,
                    help="copy the dead worker's flight dump here as "
                         "bench-proc-flight.json (CI artifact)")
    ap.add_argument("--preamble-len", dest="preamble_len", type=int, default=None)
    ap.add_argument("--n-preambles", dest="n_preambles", type=int, default=None)
    ap.add_argument("--fleet-clients", dest="fleet_clients", type=int, default=None)
    ap.add_argument("--fleet-rate", dest="fleet_rate", type=float, default=None)
    ap.add_argument("--clients", type=int, default=None, help="number of synthetic clients")
    ap.add_argument("--rate", type=float, default=None, help="Poisson arrival rate (req/s)")
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--tick-block", dest="tick_block", type=int, default=None)
    ap.add_argument("--long-frac", dest="long_frac", type=float, default=0.12,
                    help="fraction of requests with a multi-chunk prefill (the few "
                         "big-context requests whose prefill must not wreck the "
                         "interactive tail)")
    ap.add_argument("--token-budget", dest="token_budget", type=int, default=None,
                    help="continuous scheduler budget (default slots*tick_block + 2*chunk)")
    ap.add_argument("--pool-blocks", dest="pool_blocks", type=int, default=None,
                    help="paged KV pool size (default: ~60%% headroom over one batch request)")
    ap.add_argument("--max-queue-wait-s", dest="max_queue_wait_s", type=float, default=2.5,
                    help="queue-wait SLO for the sheddable batch class (continuous arm)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--schedulers", default="fifo,continuous")
    args = ap.parse_args(argv)

    if args.proc_chaos:
        raise SystemExit(run_proc_chaos(args))
    if args.trace:
        raise SystemExit(run_trace(args))
    if args.chaos:
        raise SystemExit(run_chaos(args))
    if args.fleet:
        raise SystemExit(run_fleet(args))

    if args.smoke or "--smoke" in (argv or sys.argv):
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)

    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.scheduling import SchedulerConfig

    if args.smoke:
        # small enough for CPU CI, big enough that a multi-chunk prefill
        # visibly stalls a fifo tick (the effect under measurement). The
        # paged pool is sized so one batch-class request pins ~60% of it:
        # fifo's head-of-line admission then starves the interactive
        # class for entire long-decode drains — exactly the pathology the
        # scheduler exists to remove.
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=384, intermediate_size=768,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512,
        )
        seq_len = 512
        args.buckets = (16, 32)
        args.decode_budgets = (16, 24, 32)
        args.long_decode = 96
        args.clients = args.clients or 96
        args.rate = args.rate or 3.0
        args.slots = args.slots or 4
        args.tick_block = args.tick_block or 4
        args.block_size = 16
        args.pool_blocks = args.pool_blocks or 48
    else:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=768, intermediate_size=2048,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
            max_position_embeddings=2048,
        )
        seq_len = 2048
        args.buckets = (64, 128)
        args.decode_budgets = (32, 64, 128)
        args.long_decode = 512
        args.clients = args.clients or 256
        args.rate = args.rate or 8.0
        args.slots = args.slots or 8
        args.tick_block = args.tick_block or 8
        args.block_size = 32
        args.pool_blocks = args.pool_blocks or 96
    model = create_llama_model(cfg, seq_len=seq_len)
    vocab = cfg.vocab_size
    budget = args.token_budget or args.slots * args.tick_block + 2 * max(args.buckets)

    rng = np.random.default_rng(args.seed)
    events = build_workload(args, vocab, rng)
    # the continuous arm uses the scheduler the way a deployment would:
    # token-budget chunked prefill, interactive traffic at priority 0,
    # batch-class big-context requests at priority 1 — preemptible under
    # pool pressure and shed (structured rejection) once their queue wait
    # blows the SLO instead of silently wrecking the tail. The fifo
    # baseline ignores all of it (strict submission order).
    configs = {
        "fifo": SchedulerConfig(mode="fifo"),
        "continuous": SchedulerConfig(
            token_budget=budget, enable_preemption=True,
            max_queue_wait_s=args.max_queue_wait_s,
        ),
    }
    results = {}
    for name in args.schedulers.split(","):
        results[name] = run_one(
            name, configs[name], model, args, vocab, events, np.random.default_rng(args.seed + 1)
        )
    report = {
        "bench": "bench_serving",
        "clients": args.clients,
        "rate_req_per_s": args.rate,
        "slots": args.slots,
        "tick_block": args.tick_block,
        "buckets": list(args.buckets),
        "long_frac": args.long_frac,
        "token_budget": budget,
        "results": results,
    }
    if "fifo" in results and "continuous" in results:
        f, c = results["fifo"], results["continuous"]
        if f["interactive_ttft_ms_p95"] and c["interactive_ttft_ms_p95"]:
            report["interactive_ttft_p95_speedup"] = round(
                f["interactive_ttft_ms_p95"] / c["interactive_ttft_ms_p95"], 3
            )
        report["tokens_per_sec_ratio"] = round(
            c["sustained_tokens_per_sec"] / max(1e-9, f["sustained_tokens_per_sec"]), 3
        )
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
