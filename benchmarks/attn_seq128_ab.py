"""A/B: XLA attention vs the Pallas kernel at BERT-headline shapes (S=128).

The round-4 roofline (`README.md` step breakdown) left ~15 ms/step of
fusion-boundary HBM traffic on the table and named a seq-128-shaped fused
attention kernel as the candidate lever: at S=128 a single 128x128 block
holds the whole score matrix in VMEM, so a one-block kernel never spills
the [B,H,S,S] probabilities to HBM — the traffic XLA's fusion pays in both
directions. The flash kernel's measured 2048 crossover was for its default
multi-block configuration; this measures the degenerate one-block case.

Prints one JSON line per variant (fwd and fwd+bwd). Decision rule: adopt
the kernel for the BERT bench path only if fwd+bwd beats XLA by >3%.

Usage: python benchmarks/attn_seq128_ab.py [--small]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time

from _timing import force


def bench(fn, args, steps):
    out = fn(*args)
    force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    force(out)
    return (time.perf_counter() - t0) / steps * 1000


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    if args.small:
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)

    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.ops.pallas_attention import pallas_flash_attention

    # BERT-base headline shape: batch 256, 12 heads, seq 128, dim 64 (bf16)
    b, s, h, d = (4, 128, 2, 32) if args.small else (256, 128, 12, 64)
    steps = 3 if args.small else args.steps
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(key, (b, s, h, d), jnp.bfloat16) for key in ks)

    variants = {
        "xla": jax.jit(lambda q, k, v: dot_product_attention(q, k, v, use_flash=False)),
        "pallas_1block": jax.jit(
            lambda q, k, v: pallas_flash_attention(q, k, v, block_q=s, block_k=s)
        ),
    }

    def loss_of(fn):
        return jax.jit(jax.grad(lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(), argnums=(0, 1, 2)))

    import numpy as np

    ref = np.asarray(variants["xla"](q, k, v), np.float32)
    for name, fn in variants.items():
        got = np.asarray(fn(q, k, v), np.float32)
        err = float(np.max(np.abs(got - ref)))
        fwd_ms = bench(fn, (q, k, v), steps)
        bwd_ms = bench(loss_of(fn), (q, k, v), steps)
        print(
            json.dumps(
                {
                    "metric": f"attn_s{s}_{name}",
                    "fwd_ms": round(fwd_ms, 3),
                    "fwd_bwd_ms": round(bwd_ms, 3),
                    "max_abs_err_vs_xla": err,
                    "shape": [b, s, h, d],
                }
            )
        )


if __name__ == "__main__":
    main()
