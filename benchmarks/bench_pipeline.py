"""Pipeline analyzer A/B: pipemodel's bubble-adjusted prediction vs
StepTelemetry-measured step time on the real ``pipeline_apply`` schedule.

One workload factory per stage count S in {2, 4} (the rest of the
8-device fake pool is the data axis), each searched over
``num_microbatches`` in {2, 4, 8} through ``accelerate-tpu tune``'s
machinery — so the candidates are scored by the SAME pipeline-aware
tuner hook users get, then confirmed with short measured runs.

The two arms are sized to land in the two regimes the bubble model has
to price against each other, so each arm's winner sits at the opposite
edge of the M sweep with a wide margin (a mid-sweep optimum on an
oversubscribed CPU "mesh" is a coin flip against wall-clock noise):

* **bubble-dominated** (S=4, wide batch, modest params): per-tick
  compute shrinks ~1/M while the fill/drain tax ``(S-1)/(M+S-1)``
  shrinks with M — more microbatches win. Predicted and measured winner
  must both be M=8.
* **floor-dominated** (S=2, tiny batch, fat params): every tick
  re-reads the stage params, so per-tick time is pinned at the HBM
  floor and step time is just ``(M+S-1) x floor`` — fewer ticks win.
  Predicted and measured winner must both be M=2.

Why the ranking is portable to a time-shared CPU "mesh": the GPipe
schedule is SPMD — every stage executes every tick (fill/drain ticks
compute on clamped microbatch indices), so the bubble is *wasted
compute*, not idle time. Total executed work per step is
``S x (M+S-1) x tick_work``, exactly ``S x`` the model's
``predicted_step_us = (M+S-1) x max_tick`` — proportional per fixed S.
The gate is therefore top-1 WITHIN each stage count (predicted-best M
must be the measured-best M), plus Spearman over the M sweep; comparing
across S divides out only when both arms are reported separately.

Also measured, not asserted-by-hand: ZERO post-warmup recompiles in
every confirm run (the schedule is one compiled program per candidate).

Writes the JSON report to stdout:

    JAX_PLATFORMS=cpu python benchmarks/bench_pipeline.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import force_host_platform  # noqa: E402

LAYERS = 8
MICROBATCHES = (2, 4, 8)
# (stages, width, global_batch, regime, expected winner's M)
ARMS = (
    (4, 512, 2048, "bubble", 8),
    (2, 1024, 64, "floor", 2),
)


def make_pipeline_factory(n_stages: int, width: int, global_batch: int):
    """Factory over the pipeline knobs for a fixed S-stage cut of an
    L-layer tanh-MLP trunk; the data axis takes the rest of the pool."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.pipeline import pipeline_apply

    mesh = MeshConfig(pipe=n_stages, data=8 // n_stages).build()

    def layer(p, h):
        return jnp.tanh(h @ p["w"] + p["b"]) + h

    def factory(point):
        kw = point.pipeline_kwargs()

        def step(params, x):
            return pipeline_apply(layer, params, x, mesh=mesh, **kw).sum()

        f32 = jnp.float32
        params = {
            "w": jax.ShapeDtypeStruct((LAYERS, width, width), f32),
            "b": jax.ShapeDtypeStruct((LAYERS, width), f32),
        }
        x = jax.ShapeDtypeStruct((global_batch, width), f32)
        return step, (params, x)

    factory.tune_factory = True
    factory.__name__ = f"pipeline_s{n_stages}"
    return factory


def _pairs(report):
    return [
        (c.predicted_step_us, c.measured_step_us, c.label, c.point)
        for c in report.ranked
        if c.measured_step_us is not None
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="CI sizing: fewer steps")
    ap.add_argument("--steps", type=int, default=None, help="steady confirm steps per arm")
    args = ap.parse_args(argv)
    steps = args.steps or (8 if args.smoke else 12)

    force_host_platform(8)
    import jax

    from accelerate_tpu.analysis.searchspace import SearchSpace
    from accelerate_tpu.analysis.tuner import spearman, tune

    report: dict = {
        "env": {
            "backend": jax.default_backend(),
            "cpu_count": os.cpu_count(),
            "jax": jax.__version__,
            "smoke": bool(args.smoke),
            "steps": steps,
        },
        "workload": {
            "layers": LAYERS,
            "microbatches": list(MICROBATCHES),
            "arms": [
                {"stages": s, "width": w, "global_batch": b, "regime": reg,
                 "expected_winner_m": m}
                for s, w, b, reg, m in ARMS
            ],
        },
        "criteria": {},
        "arms": {},
    }

    crit: dict = {}
    for s, width, global_batch, regime, expect_m in ARMS:
        factory = make_pipeline_factory(s, width, global_batch)
        mesh_spec = f"pipe={s},data={8 // s}"
        space = SearchSpace(
            meshes=(mesh_spec,), microbatch_counts=MICROBATCHES, max_devices=8
        )
        tuned = tune(
            factory, space, generation="cpu",
            top_k=99, confirm=True, confirm_steps=steps, warmup_steps=6,
        )
        pairs = _pairs(tuned)
        rho = spearman([p for p, *_ in pairs], [m for _, m, *_ in pairs])
        pred_winner = min(pairs, key=lambda t: t[0]) if pairs else None
        meas_winner = min(pairs, key=lambda t: t[1]) if pairs else None
        recompiles = tuned.confirm["recompiles"] if tuned.confirm else None
        arm = {
            "mesh": mesh_spec,
            "regime": regime,
            "candidates": [c.as_dict() for c in tuned.candidates],
            "winner": tuned.winner.label if tuned.winner else None,
            "measured_winner": meas_winner[2] if meas_winner else None,
            "top1": bool(pred_winner and meas_winner and pred_winner[3] == meas_winner[3]),
            "spearman": round(rho, 4) if rho is not None else None,
            "bubble_by_m": {
                str(c.point.num_microbatches): c.bubble_fraction
                for c in tuned.ranked
            },
            "recompiles": recompiles,
            "chosen_toml": tuned.chosen_toml(),
        }
        report["arms"][f"stages_{s}"] = arm
        crit[f"s{s}_top1_predicted_equals_measured"] = bool(arm["top1"])
        crit[f"s{s}_winner_is_{regime}_regime_edge"] = bool(
            tuned.winner and tuned.winner.point.num_microbatches == expect_m
        )
        crit[f"s{s}_zero_postwarmup_recompiles"] = bool((recompiles or 0) == 0)
        crit[f"s{s}_all_candidates_bubble_scored"] = bool(
            pairs and all(c.bubble_fraction is not None for c in tuned.ranked)
        )

    report["criteria"] = crit
    report["notes"] = (
        "SPMD GPipe executes every stage every tick, so measured step time is "
        "proportional to S x (M+S-1) x tick_work — S x the model's predicted step "
        "time — making the within-arm M ranking portable to a time-shared CPU pool. "
        "The bubble-dominated arm must pick the largest M, the floor-dominated arm "
        "the smallest; each winner sits at its sweep edge with a wide margin so the "
        "top-1 gate measures the model, not wall-clock luck. Spearman over the "
        "3-point M sweep is reported but only top-1 is gated."
    )
    report["ok"] = all(crit.values())
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
