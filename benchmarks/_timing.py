"""Shared timing helper: force device-side completion with a value fetch.

``jax.block_until_ready`` is advisory on some remote-attached backends (the
axon tunnel used in CI returns immediately), which silently turns timing
loops into dispatch-overhead measurements. Fetching one element D2H cannot
complete before the producing computation has, so it is the reliable sync
point — and one scalar keeps the transfer cost negligible.
"""

from __future__ import annotations


def force(x) -> None:
    """Block until ``x`` (any pytree of jax arrays) has finished computing."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "ndim"):
            idx = (0,) * leaf.ndim
            np.asarray(jax.device_get(leaf[idx]))
        break  # one leaf suffices: same program produced the whole tree
