"""Big-model inference benchmark: checkpoint load time + per-token decode
latency + HBM footprint.

Reference analogue: ``benchmarks/big_model_inference`` (GPT-J-6B / NeoX-20B
tables: model load time, per-token generate latency, device memory). The
TPU-native pipeline measured here is the framework's own:

  save_model (sharded safetensors) -> load_checkpoint_and_dispatch
  (device_map over HBM budget) -> KV-cache ``generate`` (jitted prefill +
  lax.scan decode; generation.py).

Two model sizes: save/load uses a ~0.12B model (host<->device transfers
over the CI tunnel run at ~5 MB/s, so GB-scale weights would measure the
tunnel, not the framework), decode latency uses ~1.1B (compute-side, so
tunnel-immune — only the final token crosses the wire).

Usage: python benchmarks/big_model_inference.py [--small]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import tempfile
import time


def hbm_used_bytes():
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        return stats.get("bytes_in_use", 0)
    except Exception:
        return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU smoke mode")
    ap.add_argument("--decode-only", action="store_true", help="skip the save/load rows")
    args = ap.parse_args()

    import jax
    import numpy as np

    from accelerate_tpu import Accelerator
    from accelerate_tpu.generation import generate, per_token_latency
    from accelerate_tpu.models import LlamaConfig, create_llama_model

    if args.small:
        ckpt_cfg = decode_cfg = LlamaConfig.tiny()
        prompt_len, new_tokens = 8, 8
    else:
        # ~0.12B: gpt2-small-ish shape for the save/load row
        ckpt_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=768, intermediate_size=2048,
            num_hidden_layers=12, num_attention_heads=12,
            num_key_value_heads=12, max_position_embeddings=1024,
        )
        # ~1.1B TinyLlama shape for the decode row (reference's per-token on
        # GPT-J-6B fp16 / 2x Titan RTX is 0.05 s)
        decode_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=22, num_attention_heads=32,
            num_key_value_heads=4, max_position_embeddings=2048,
        )
        prompt_len, new_tokens = 32, 64

    acc = Accelerator(mixed_precision="bf16")

    # --- save / load_checkpoint_and_dispatch ---------------------------- #
    ckpt_params, save_s, load_s = 0, 0.0, 0.0
    if not args.decode_only:
        ckpt_model = acc.prepare_model(create_llama_model(ckpt_cfg, seed=1, seq_len=prompt_len))
        ckpt_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(ckpt_model.params))
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "model")
            t0 = time.perf_counter()
            acc.save_model(ckpt_model, path)
            save_s = time.perf_counter() - t0
            from accelerate_tpu.big_modeling import load_checkpoint_and_dispatch

            t0 = time.perf_counter()
            dispatched = load_checkpoint_and_dispatch(ckpt_model, path, device_map="auto")
            load_s = time.perf_counter() - t0
            assert dispatched is not None
        # return the ckpt model's HBM before the decode model arrives
        from accelerate_tpu.utils.memory import release_memory

        ckpt_model, dispatched = release_memory(ckpt_model, dispatched)

    # --- decode latency: bf16 vs weight-only quantized ------------------- #
    # quantize AFTER prepare: the bf16 policy casts the float kernels, then
    # conversion derives fresh fp32 scales from the cast weights
    from accelerate_tpu.utils.quantization import QuantizationConfig, load_and_quantize_model

    model = acc.prepare_model(create_llama_model(decode_cfg, seed=3, seq_len=prompt_len))
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(model.params))
    hbm = hbm_used_bytes()
    ids = np.ones((1, prompt_len), np.int32)
    out = generate(model, ids, max_new_tokens=new_tokens)  # compile + run
    assert out.shape == (1, prompt_len + new_tokens)
    ref_logits = np.asarray(model.apply_fn(model.params, ids), np.float32)[0]
    tok_s = per_token_latency(model, batch_size=1, prompt_len=prompt_len, n_tokens=min(16, new_tokens))

    quant_rows = {}
    # nf4 runs only in --small: its gather-decode XLA program kernel-faults
    # the remote-attached worker at GB scale; the 4-bit path at size is the
    # Pallas int4 kernel (fused dequant+matmul, ops/pallas_qmatmul.py)
    variants = [("int8", 8, None), ("nf4", 4, 64)] if args.small else [("int8", 8, None), ("int4", 4, 64)]
    for method, bits, gs in variants:
        qmodel = load_and_quantize_model(model, QuantizationConfig(bits=bits, method=method, group_size=gs))
        q_logits = np.asarray(qmodel.apply_fn(qmodel.params, ids), np.float32)[0]
        # on the randomly-initialised bench model the top1-top2 gap is
        # smaller than an honest 4-bit perturbation, so raw argmax
        # agreement is degenerate; report the logit error relative to the
        # logit scale AND relative to the decision gap (>1 gap units could
        # flip a real model's argmax; << 1 could not)
        rel = float(np.linalg.norm(q_logits - ref_logits) / max(np.linalg.norm(ref_logits), 1e-9))
        sorted2 = np.sort(ref_logits, axis=-1)[..., -2:]
        gap = float(np.mean(sorted2[..., 1] - sorted2[..., 0]))
        err_vs_gap = float(np.mean(np.abs(q_logits - ref_logits)) / max(gap, 1e-9))
        top1 = float(np.mean(q_logits.argmax(-1) == ref_logits.argmax(-1)))
        q_tok_s = per_token_latency(qmodel, batch_size=1, prompt_len=prompt_len, n_tokens=min(16, new_tokens))
        quant_rows[method] = {
            "per_token_s": round(q_tok_s, 5),
            "tokens_per_sec": round(1.0 / q_tok_s, 1) if q_tok_s else None,
            "speedup_vs_bf16": round(tok_s / q_tok_s, 2) if q_tok_s else None,
            "prefill_logits_rel_err": round(rel, 4),
            "prefill_err_vs_argmax_gap": round(err_vs_gap, 3),
            "prefill_top1_agreement": round(top1, 4),
        }

    print(
        json.dumps(
            {
                "bench": "big_model_inference",
                "ckpt_params_b": round(ckpt_params / 1e9, 3),
                "save_s": round(save_s, 2),
                "load_s": round(load_s, 2),
                "decode_params_b": round(n_params / 1e9, 3),
                "per_token_s": round(tok_s, 5),
                "tokens_per_sec": round(1.0 / tok_s, 1) if tok_s else None,
                "quantized": quant_rows,
                "hbm_gb": round(hbm / 2**30, 2),
                "device": str(jax.devices()[0].device_kind),
                "reference_baseline": "GPT-J-6B fp16 0.05 s/token (2x Titan RTX)",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
