"""Host-offloaded optimizer state: step-time cost and HBM saving.

Reference analogue: DeepSpeed ZeRO-offload (reference plugin fields
``offload_optimizer_device``, utils/dataclasses.py:1100-1180). Here the tier
is ``ParallelismPlugin(offload_optimizer=True)``: adam moments live on
``pinned_host`` memory-kind shardings and stream through HBM inside the
jitted step.

Measures, on whatever backend is attached (the interesting numbers come
from a real chip):

* steady-state step time with and without offload (the PCIe/stream cost);
* device memory in use after the step settles (``device.memory_stats``,
  TPU-only) — the moments' bytes (8 bytes/param for adam) should vanish
  from the persistent footprint.

Prints one JSON line per mode. Usage:
    python benchmarks/offload_optimizer.py [--params-m 124] [--steps 20]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time

from _timing import force


def device_bytes_in_use():
    import jax

    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    return stats.get("bytes_in_use") if stats else None


def bench_one(offload: bool, steps: int, cfg, seq: int, batch: int):
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, ParallelismPlugin
    from accelerate_tpu.models import causal_lm_loss, create_llama_model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(offload_optimizer=offload),
    )
    model = acc.prepare_model(create_llama_model(cfg, seq_len=seq))
    opt = acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    batch_data = {"input_ids": np.ones((batch, seq), np.int32)}

    loss = step(batch_data)  # compile
    force(loss)
    mem = device_bytes_in_use()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch_data)
    force(loss)
    dt = (time.perf_counter() - t0) / steps
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(model.params))
    kinds = sorted({l.sharding.memory_kind for l in jax.tree_util.tree_leaves(opt.opt_state) if l.ndim >= 1})
    return {
        "mode": "offload" if offload else "dense",
        "step_ms": round(dt * 1000, 2),
        "params_m": round(n_params / 1e6, 1),
        "state_memory_kinds": kinds,
        "device_bytes_in_use": mem,
        "loss": round(float(loss), 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--params-m", type=int, default=124, help="~model size in M params (124 -> gpt2-small-ish llama)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--small", action="store_true", help="tiny config for CPU smoke runs")
    args = ap.parse_args()

    if args.small:
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)

    from accelerate_tpu.models import LlamaConfig

    if args.small:
        cfg, seq, batch = LlamaConfig.tiny(), 32, 4
    else:
        # ~124M-param llama: 12 layers x 768 wide, gpt2-small shape
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=768,
            intermediate_size=2048,
            num_hidden_layers=12,
            num_attention_heads=12,
            num_key_value_heads=12,
            max_position_embeddings=max(args.seq, 512),
        )
        seq, batch = args.seq, args.batch
    rows = [bench_one(False, args.steps, cfg, seq, batch), bench_one(True, args.steps, cfg, seq, batch)]
    for r in rows:
        print(json.dumps(r))
    if rows[0]["device_bytes_in_use"] and rows[1]["device_bytes_in_use"]:
        saved = rows[0]["device_bytes_in_use"] - rows[1]["device_bytes_in_use"]
        print(json.dumps({"hbm_saved_mb": round(saved / 2**20, 1), "expect_mb": round(rows[0]["params_m"] * 8, 1)}))


if __name__ == "__main__":
    main()
