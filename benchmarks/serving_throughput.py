"""Continuous vs static batching on a mixed-length serving workload.

Static batching pads every request in a batch to the batch's longest
prompt and decodes everyone to the batch's largest ``max_new_tokens`` —
stragglers hold the batch. The ServingEngine retires finished sequences
immediately and refills slots mid-stream. This benchmark runs the SAME
workload (mixed prompt lengths, mixed output budgets) both ways and
reports wall-clock + useful-tokens/sec.

Usage: python benchmarks/serving_throughput.py [--small]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU smoke mode")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    args = ap.parse_args()

    if args.small:
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)

    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ServingEngine

    if args.small:
        cfg = LlamaConfig.tiny()
        seq_len, buckets = 16, (8, 16)
        prompt_lens, budgets = (4, 8, 12), (4, 8)
    else:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=768, intermediate_size=2048,
            num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
            max_position_embeddings=512,
        )
        seq_len, buckets = 128, (32, 64, 128)
        prompt_lens, budgets = (16, 40, 90, 120), (16, 48, 96)
    model = create_llama_model(cfg, seq_len=seq_len)

    rng = np.random.default_rng(0)
    workload = [
        (
            rng.integers(1, cfg.vocab_size - 1, size=int(rng.choice(prompt_lens))).astype(np.int32),
            int(rng.choice(budgets)),
        )
        for _ in range(args.requests)
    ]
    useful_tokens = sum(n for _, n in workload)

    def sync(x):
        return int(np.asarray(x).ravel()[-1])

    # ---- static batching: group into batches of `slots`, pad prompts to the
    # batch max, decode everyone to the batch's max budget ------------------
    def run_static():
        outs = []
        for i in range(0, len(workload), args.slots):
            chunk = workload[i : i + args.slots]
            # pad to the same prompt buckets the engine uses and to the
            # chunk's max budget — bounds the number of compiled static
            # programs the same way the engine's buckets do
            max_p = next(b for b in buckets if b >= max(len(p) for p, _ in chunk))
            max_n = max(n for _, n in chunk)
            batch = np.zeros((len(chunk), max_p), np.int32)
            for j, (p, _) in enumerate(chunk):
                # left-pad: timing comparator only — generate() has no pad
                # mask, so padded rows are compute-shape-faithful but not
                # token-faithful; the engine output is the token-exact one
                batch[j, max_p - len(p):] = p
            out = generate(model, batch, max_new_tokens=max_n)
            sync(out)
            outs.append(out)
        return outs

    # warm both paths (compiles)
    t0 = time.perf_counter()
    run_static()
    static_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_static()
    t_static = time.perf_counter() - t0

    # one engine, reused across runs (construction traces/compiles the
    # prefill + tick programs; a server builds it once) — construction
    # is inside the compile timing, symmetric with the paged engine
    t0 = time.perf_counter()
    eng = ServingEngine(model, num_slots=args.slots, prompt_buckets=buckets)

    def run_engine():
        for p, n in workload:
            eng.submit(p, max_new_tokens=n)
        eng.run()

    run_engine()
    engine_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_engine()
    t_engine = time.perf_counter() - t0

    # ---- paged engine: pool sized by the workload's worst tokens-in-flight,
    # not slots x max_len — the capacity win, at (ideally) the same tok/s ----
    bs_ = 4 if args.small else 32
    worst = max(prompt_lens) + max(budgets)
    pool = args.slots * (-(-worst // bs_)) + 1
    # construction compiles the paged tick eagerly — time it with the
    # first run so paged_compile_s is comparable to engine_compile_s
    t0 = time.perf_counter()
    engp = ServingEngine(
        model, num_slots=args.slots, prompt_buckets=buckets,
        paged_block_size=bs_, pool_blocks=pool,
    )

    def run_paged():
        for p, n in workload:
            engp.submit(p, max_new_tokens=n)
        engp.run()

    run_paged()
    paged_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_paged()
    t_paged = time.perf_counter() - t0
    dense_rows = args.slots * eng.max_len
    paged_rows = pool * bs_

    print(json.dumps({
        "bench": "serving_throughput",
        "requests": args.requests,
        "slots": args.slots,
        "useful_tokens": useful_tokens,
        "static_s": round(t_static, 2),
        "static_tok_per_s": round(useful_tokens / t_static, 1),
        "engine_s": round(t_engine, 2),
        "engine_tok_per_s": round(useful_tokens / t_engine, 1),
        "speedup": round(t_static / t_engine, 3),
        "paged_s": round(t_paged, 2),
        "paged_tok_per_s": round(useful_tokens / t_paged, 1),
        "paged_vs_dense_engine": round(t_engine / t_paged, 3),
        "paged_cache_rows_ratio": round(paged_rows / dense_rows, 3),
        "static_compile_s": round(static_compile - t_static, 1),
        "engine_compile_s": round(engine_compile - t_engine, 1),
        "paged_compile_s": round(paged_compile - t_paged, 1),
    }))


if __name__ == "__main__":
    main()
