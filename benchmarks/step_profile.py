"""Op-level breakdown of the headline BERT train step (round-4 VERDICT #7).

Two independent measurements, both robust over the tunnel-attached backend:

1. **Ablation wall-clock**: forward-only, forward+backward, and the full
   step (fwd+bwd+adamw), each timed by value-fetch differencing — the
   share of each phase falls out by subtraction.
2. **Compiled-program accounting**: ``compile().cost_analysis()`` FLOPs +
   bytes for each program, turned into a roofline lower bound
   (max(flops/peak_flops, bytes/peak_bw)) per phase.

Optionally (``--trace DIR``) also captures a ``jax.profiler`` trace for
TensorBoard's op profile.

Prints JSON lines; run on the real chip.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

PEAK_TFLOPS = 197.0  # v5e bf16
PEAK_HBM_GBS = 819.0  # v5e


def force(x) -> None:
    """True barrier: fetch one element (block_until_ready returns early on
    tunnel-attached backends, benchmarks/_timing.py)."""
    jax = __import__("jax")
    arr = jax.tree_util.tree_leaves(x)[0]
    float(np.asarray(arr).ravel()[0])


def timed(fn, *args, n=10):
    # warm TWICE: donation re-lays-out the params after the first call, so
    # call #2 recompiles (31s observed) — one warm call is not enough
    force(fn(*args))
    for _ in range(2):
        out = fn(*args)
    force(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    force(out)
    return (time.perf_counter() - t0) / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="also write a jax.profiler trace here")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument(
        "--phase",
        choices=["fwd", "fwdbwd", "step", "all"],
        default="all",
        help="measure one phase per process (separate processes avoid donation/"
        "allocator interference between the phase programs)",
    )
    ap.add_argument("--remat", action="store_true", help="activation-checkpoint each encoder layer")
    args = ap.parse_args()

    import jax
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.parallel.mesh import batch_sharding

    acc = Accelerator(mixed_precision="bf16")
    model = acc.prepare_model(
        create_bert_model(BertConfig.base(remat=args.remat), seq_len=args.seq)
    )
    acc.prepare_optimizer(optax.adamw(2e-5, weight_decay=0.01))
    loss_fn = lambda p, b: bert_classification_loss(p, b, model.apply_fn)
    step = acc.build_train_step(loss_fn)

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(5, 30000, size=(args.batch, args.seq)).astype(np.int32),
        "attention_mask": np.ones((args.batch, args.seq), np.bool_),
        "labels": rng.integers(0, 2, size=(args.batch,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(acc.mesh))

    # phase programs (same dtype policy the train step uses internally)
    policy = acc.state.dtype_policy

    def cast(p):
        return jax.tree.map(lambda x: x.astype(policy.compute_dtype) if hasattr(x, "astype") else x, p)

    @jax.jit
    def fwd(params, batch):
        return loss_fn(cast(params), batch)

    @jax.jit
    def fwd_bwd(params, batch):
        loss, grads = jax.value_and_grad(lambda p, b: loss_fn(cast(p), b))(params, batch)
        # consume every grad leaf so no branch of the backward is DCE'd
        return loss + sum(g.astype(__import__("jax").numpy.float32).sum() for g in jax.tree_util.tree_leaves(grads)) * 0.0

    def cost(jitted, *a):
        c = jitted.lower(*a).compile().cost_analysis()
        c = c[0] if isinstance(c, (list, tuple)) else c
        fl = float(c.get("flops", 0.0))
        by = float(c.get("bytes accessed", 0.0))
        return fl, by, max(fl / (PEAK_TFLOPS * 1e12), by / (PEAK_HBM_GBS * 1e9))

    result = {"metric": f"bert_phase_{args.phase}", "batch": args.batch, "seq": args.seq}
    if args.phase in ("fwd", "all"):
        t = timed(fwd, model.params, batch, n=args.steps)
        fl, by, lb = cost(fwd, model.params, batch)
        result.update(fwd_ms=round(t * 1e3, 2), fwd_gflops=round(fl / 1e9, 1),
                      fwd_gbytes=round(by / 1e9, 3), fwd_roofline_ms=round(lb * 1e3, 2),
                      fwd_roofline_eff=round(lb / t, 3))
    if args.phase in ("fwdbwd", "all"):
        t = timed(fwd_bwd, model.params, batch, n=args.steps)
        fl, by, lb = cost(fwd_bwd, model.params, batch)
        result.update(fwdbwd_ms=round(t * 1e3, 2), fwdbwd_gflops=round(fl / 1e9, 1),
                      fwdbwd_gbytes=round(by / 1e9, 3), fwdbwd_roofline_ms=round(lb * 1e3, 2),
                      fwdbwd_roofline_eff=round(lb / t, 3))
    if args.phase in ("step", "all"):
        t = timed(step, batch, n=args.steps)
        result.update(step_ms=round(t * 1e3, 2))
    print(json.dumps(result))

    if args.trace:
        with jax.profiler.trace(args.trace):
            out = None
            for _ in range(5):
                out = step(batch)
            force(out)
        print(json.dumps({"trace_dir": args.trace}))


if __name__ == "__main__":
    main()
