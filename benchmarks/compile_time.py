"""Compile-time benchmark: scan-over-layers vs unrolled layer stack.

Reference analogue: ``benchmarks/torch.compile`` (regional compilation —
compile one repeated block, reuse it N times, 5-9x faster compile at equal
inference speed). The TPU-native equivalent is ``lax.scan`` over stacked
layer weights (models/llama.py scan_layers=True): XLA traces and compiles
the block ONCE regardless of depth, where the unrolled stack re-lowers
every layer.

Prints one JSON line per (mode, config): compile seconds + steady-state
forward latency, so the table shows compile-time savings AND that inference
speed is not sacrificed — the same two columns the reference publishes.

Usage: python benchmarks/compile_time.py [--small]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def bench_one(name: str, cfg, batch: int, seq: int):
    import jax
    import numpy as np

    from accelerate_tpu.models import create_llama_model

    model = create_llama_model(cfg, seq_len=seq)
    ids = np.ones((batch, seq), np.int32)

    from _timing import force

    fwd = jax.jit(lambda p, x: model.apply_fn(p, x))
    t0 = time.perf_counter()
    force(fwd(model.params, ids))
    compile_s = time.perf_counter() - t0

    for _ in range(3):
        out = fwd(model.params, ids)
    force(out)
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        out = fwd(model.params, ids)
    force(out)
    latency_ms = (time.perf_counter() - t0) / n * 1000

    print(
        json.dumps(
            {
                "bench": "compile_time",
                "mode": name,
                "layers": cfg.num_hidden_layers,
                "hidden": cfg.hidden_size,
                "batch_x_seq": f"{batch}x{seq}",
                "compile_s": round(compile_s, 2),
                "forward_ms": round(latency_ms, 2),
            }
        ),
        flush=True,
    )
    return compile_s, latency_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU smoke mode")
    args = ap.parse_args()

    from accelerate_tpu.models import LlamaConfig

    if args.small:
        sizes = [dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=8, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=128)]
        batch, seq = 1, 64
    else:
        # deep-and-narrow: depth is what separates per-layer lowering
        # (unrolled) from compile-once (scan); batch*seq large enough that
        # the forward is compute-, not dispatch-, dominated
        sizes = [dict(vocab_size=32000, hidden_size=1024, intermediate_size=2816,
                      num_hidden_layers=48, num_attention_heads=16,
                      num_key_value_heads=4, max_position_embeddings=1024)]
        batch, seq = 8, 512

    # absorb the one-time backend/dispatch warmup so it doesn't land on
    # whichever mode happens to compile first
    import jax

    from _timing import force

    force(jax.jit(lambda x: x * 2)(jax.numpy.ones((8, 8))))

    for size in sizes:
        scan_c, scan_l = bench_one("scan (regional analogue)", LlamaConfig(scan_layers=True, remat=False, **size), batch, seq)
        unroll_c, unroll_l = bench_one("unrolled (full-compile analogue)", LlamaConfig(scan_layers=False, remat=False, **size), batch, seq)
        print(
            json.dumps(
                {
                    "bench": "compile_time",
                    "mode": "summary",
                    "compile_speedup": round(unroll_c / scan_c, 2) if scan_c else None,
                    "latency_ratio_scan_vs_unrolled": round(scan_l / unroll_l, 3) if unroll_l else None,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
