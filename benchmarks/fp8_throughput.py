"""FP8 training benchmark: throughput + loss parity vs bf16.

Reference analogue: ``benchmarks/fp8`` (TE / torchao / MS-AMP scripts whose
acceptance bar is loss parity with the native implementation; no published
throughput table). Here the framework's own fp8 path — every transformer
Dense routed through the custom-VJP scaled e4m3/e5m2 matmul
(ops/fp8.py) when ``mixed_precision="fp8"`` — is measured for throughput
AND checked for loss parity against bf16 on the same data.

Note on v5e: there is no native fp8 MXU path, so fp8 here trades casts for
bandwidth and will not beat bf16 on this chip generation; the number is
recorded so the trade is explicit (on hardware with fp8 matmul units the
same policy switches on real gains).

Usage: python benchmarks/fp8_throughput.py [--small]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def run_mode(mixed_precision: str, batch: int, seq: int, steps: int, small: bool):
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.parallel.mesh import batch_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    acc = Accelerator(mixed_precision=mixed_precision)
    cfg = BertConfig.tiny() if small else BertConfig.base()
    model = acc.prepare_model(create_bert_model(cfg, seq_len=seq))
    acc.prepare_optimizer(optax.adamw(2e-5, weight_decay=0.01))
    step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))

    rng = np.random.default_rng(0)
    batch_data = {
        "input_ids": rng.integers(5, min(30000, cfg.vocab_size - 1), size=(batch, seq)).astype(np.int32),
        "attention_mask": np.ones((batch, seq), np.bool_),
        "labels": rng.integers(0, 2, size=(batch,)).astype(np.int32),
    }
    batch_data = jax.device_put(batch_data, batch_sharding(acc.mesh))

    losses = [float(step(batch_data))]  # compile
    for _ in range(3):
        losses.append(float(step(batch_data)))
    t0 = time.perf_counter()
    last = None
    for _ in range(steps):
        last = step(batch_data)
    losses.append(float(last))
    dt = time.perf_counter() - t0
    return batch * steps / dt, losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU smoke mode")
    args = ap.parse_args()
    batch, seq, steps = (8, 32, 4) if args.small else (128, 128, 20)

    bf16_tput, bf16_losses = run_mode("bf16", batch, seq, steps, args.small)
    fp8_tput, fp8_losses = run_mode("fp8", batch, seq, steps, args.small)

    # loss parity: same data, same init seed — initial losses must agree to
    # fp8 rounding and both must be decreasing
    initial_gap = abs(bf16_losses[0] - fp8_losses[0]) / max(abs(bf16_losses[0]), 1e-9)
    print(
        json.dumps(
            {
                "bench": "fp8_throughput",
                "bf16_samples_per_sec": round(bf16_tput, 1),
                "fp8_samples_per_sec": round(fp8_tput, 1),
                "fp8_speedup": round(fp8_tput / bf16_tput, 3),
                "bf16_loss_first_last": [round(bf16_losses[0], 4), round(bf16_losses[-1], 4)],
                "fp8_loss_first_last": [round(fp8_losses[0], 4), round(fp8_losses[-1], 4)],
                "initial_loss_rel_gap": round(initial_gap, 4),
                "loss_parity_ok": bool(initial_gap < 0.05 and fp8_losses[-1] < fp8_losses[0]),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
