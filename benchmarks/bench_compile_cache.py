"""Compile-cache benchmark: cold vs warm process start, with compile counts.

The claim under test is the whole point of ``accelerate_tpu/aot``: a
process that re-creates the same jitted step/decode programs against a
warm executable store performs **zero XLA compiles** and starts
measurably faster. Honesty requires real process boundaries, so each
measurement runs in a fresh ``python`` subprocess against a shared cache
dir:

* **cold** — empty store: every program compiles (and is serialized);
* **warm** — same store: every program deserializes.

The workload is a llama-tiny train step (``build_train_step`` routed
through the ProgramCache via ``CompileKwargs``) plus a ServingEngine
prefill bucket + decode tick — the two hot surfaces a restarted trainer
and a new serving replica respectively care about. One JSON line per
phase, then a summary line::

    {"bench": "compile_cache", "phase": "cold", "wall_s": ..., "build_ms": ...,
     "xla_compiles": N, "deserialized": 0, ...}
    {"bench": "compile_cache", "phase": "warm", "wall_s": ..., "xla_compiles": 0, ...}
    {"bench": "compile_cache", "phase": "summary", "speedup": ..., "warm_compiles": 0}

Runs entirely on the CPU backend (``JAX_PLATFORMS=cpu``); tier-1/CI safe.

Usage: python benchmarks/bench_compile_cache.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["ACCELERATE_BENCH_REPO"])
from accelerate_tpu.utils.environment import force_host_platform

force_host_platform(1)
t_start = time.perf_counter()

import numpy as np
import optax

from accelerate_tpu import Accelerator, CompileKwargs
from accelerate_tpu.models import LlamaConfig, causal_lm_loss, create_llama_model
from accelerate_tpu.serving import ServingEngine
from accelerate_tpu.telemetry import StepTelemetry

acc = Accelerator(kwargs_handlers=[CompileKwargs(cache_dir=os.environ["ACCELERATE_COMPILE_CACHE_DIR_RAW"])])
cfg = LlamaConfig.tiny()
model = acc.prepare_model(create_llama_model(cfg, seq_len=32))
acc.prepare_optimizer(optax.adamw(1e-3))
step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))

rng = np.random.default_rng(0)
batch = {"input_ids": rng.integers(1, cfg.vocab_size - 1, size=(4, 32)).astype(np.int32)}

telem = StepTelemetry(warmup_steps=2)
tstep = telem.wrap(step)
t0 = time.perf_counter()
for _ in range(3):
    loss = float(tstep(batch))
train_build_ms = (time.perf_counter() - t0) * 1000.0

# serving surface: one prefill bucket + the decode tick, same store
serve_model = create_llama_model(cfg, seq_len=32)
eng = ServingEngine(serve_model, num_slots=2, prompt_buckets=(8,),
                    program_cache=None)  # picks up ACCELERATE_COMPILE_CACHE_DIR
t0 = time.perf_counter()
out = eng.generate_many([np.arange(1, 7, dtype=np.int32)], max_new_tokens=4)
serve_build_ms = (time.perf_counter() - t0) * 1000.0

pc_train = acc.program_cache
pc_serve = eng.program_cache
print(json.dumps({
    "bench": "compile_cache",
    "phase": os.environ["ACCELERATE_BENCH_PHASE"],
    "wall_s": round(time.perf_counter() - t_start, 3),
    "train_build_ms": round(train_build_ms, 1),
    "serve_build_ms": round(serve_build_ms, 1),
    "xla_compiles": pc_train.misses + pc_serve.misses,
    "deserialized": pc_train.deserialized + pc_serve.deserialized,
    "recompiles_watchdog": telem.recompiles,
    "loss": loss,
    "first_token": int(out[0][len(out[0]) - 4]),
}))
"""


def _run_phase(phase: str, cache_dir: str) -> dict:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ACCELERATE_BENCH_REPO=REPO,
        ACCELERATE_BENCH_PHASE=phase,
        ACCELERATE_COMPILE_CACHE_DIR=cache_dir,
        ACCELERATE_COMPILE_CACHE_DIR_RAW=cache_dir,
    )
    # keep the subprocesses honest: no shared jax persistent cache unless
    # it is the one under test
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    t0 = time.perf_counter()
    out = subprocess.run([sys.executable, "-c", _CHILD], capture_output=True, text=True, env=env)
    wall = time.perf_counter() - t0
    if out.returncode != 0:
        raise RuntimeError(f"{phase} phase failed:\n{out.stderr[-2000:]}")
    line = json.loads([l for l in out.stdout.splitlines() if l.startswith("{")][-1])
    line["subprocess_wall_s"] = round(wall, 3)
    print(json.dumps(line))
    return line


def main():
    with tempfile.TemporaryDirectory() as cache_dir:
        cold = _run_phase("cold", cache_dir)
        warm = _run_phase("warm", cache_dir)
    assert warm["loss"] == cold["loss"], "warm-start result drifted from cold"
    assert warm["first_token"] == cold["first_token"], "warm serving output drifted"
    build_cold = cold["train_build_ms"] + cold["serve_build_ms"]
    build_warm = warm["train_build_ms"] + warm["serve_build_ms"]
    print(
        json.dumps(
            {
                "bench": "compile_cache",
                "phase": "summary",
                "cold_build_ms": round(build_cold, 1),
                "warm_build_ms": round(build_warm, 1),
                "build_speedup": round(build_cold / max(build_warm, 1e-9), 2),
                "cold_compiles": cold["xla_compiles"],
                "warm_compiles": warm["xla_compiles"],
                "warm_deserialized": warm["deserialized"],
                "bit_exact": True,
            }
        )
    )
    if warm["xla_compiles"] != 0:
        print("FAIL: warm process still compiled", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
