"""On-chip validation + micro-bench for paged serving (run on one TPU).

Three checks the CPU suite cannot perform (it runs the XLA gather path
or interpret-mode kernels):

1. the Pallas paged-attention kernel compiles and matches the dense
   engine's tokens on real hardware (greedy, GQA model);
2. windowed recycling stays token-exact on-chip;
3. an end-to-end engine micro-bench: wall-clock per OUTPUT token for
   the whole serve loop (prefill + admission + decode ticks), paged
   kernel vs dense — an engine-throughput number, not an isolated
   decode-tick timing.

Usage: python benchmarks/paged_serving_chip_check.py [--slots 8]
Prints one JSON line; exits nonzero on any mismatch.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import os

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--max_new", type=int, default=64)
    args = ap.parse_args()

    import jax

    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, MistralConfig, create_llama_model, create_mistral_model
    from accelerate_tpu.serving import ServingEngine

    assert jax.default_backend() == "tpu", f"needs a TPU, got {jax.default_backend()}"

    cfg = LlamaConfig(
        vocab_size=2048, hidden_size=args.hidden, intermediate_size=2 * args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=512,
    )
    model = create_llama_model(cfg, seq_len=64)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 2000, size=int(n)).astype(np.int32) for n in rng.integers(8, 60, args.slots * 2)]

    def run(engine):
        for p in prompts:
            engine.submit(p, max_new_tokens=args.max_new)
        t0 = time.perf_counter()
        out = engine.run()
        return out, time.perf_counter() - t0

    dense = ServingEngine(model, num_slots=args.slots, prompt_buckets=(16, 64))
    outs_d, _ = run(dense)
    _, t_dense = run(dense)

    paged = ServingEngine(model, num_slots=args.slots, prompt_buckets=(16, 64), paged_block_size=16)
    outs_p, _ = run(paged)
    _, t_paged = run(paged)

    # uids are assigned in submission order in both engines
    mismatch = sum(not np.array_equal(outs_d[u], outs_p[u]) for u in sorted(outs_d))

    # windowed recycling on-chip
    wm = create_mistral_model(MistralConfig.tiny(sliding_window=8), seq_len=64)
    weng = ServingEngine(wm, num_slots=2, prompt_buckets=(16, 64), paged_block_size=4, pool_blocks=10)
    wp = [rng.integers(1, 250, size=40).astype(np.int32) for _ in range(3)]
    wout = weng.generate_many(wp, max_new_tokens=6)
    wref = [np.asarray(generate(wm, p[None], max_new_tokens=6))[0] for p in wp]
    w_ok = all(np.array_equal(a, b) for a, b in zip(wout, wref))

    toks = sum(args.max_new for _ in prompts)
    print(json.dumps({
        "bench": "paged_serving_chip_check",
        "kernel_token_mismatches": mismatch,
        "windowed_exact": bool(w_ok),
        "dense_e2e_ms_per_output_tok": round(1e3 * t_dense / toks, 3),
        "paged_kernel_e2e_ms_per_output_tok": round(1e3 * t_paged / toks, 3),
        "paged_vs_dense_e2e": round(t_dense / t_paged, 3),
    }))
    sys.exit(0 if (mismatch == 0 and w_ok) else 1)


if __name__ == "__main__":
    main()
