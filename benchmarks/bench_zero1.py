"""ZeRO-1 training-wire A/B/C: replicated-fp32 vs zero1 vs zero1+int8.

Three arms train the same model on the same data over an 8-way CPU fake
mesh (the SURVEY §4 multi-chip CI story), and every headline claim is
measured, not asserted:

* **bytes on wire** — the cost model predicts each arm's per-step
  collective traffic (``parallel.compression.wire_bytes``, which
  delegates to ``analysis.costmodel.ring_wire_bytes``), and the
  compiled program's ACTUAL collectives are counted from its
  post-GSPMD HLO (``telemetry.wire.hlo_wire_bytes`` — an independent
  measurement: GSPMD inserts the baseline's implicit grad all-reduce,
  which the jaxpr never shows). The pair lands as a ``wire_bytes``
  telemetry counter; the criterion is agreement within 10% and
  zero1+int8 <= ~25% of the replicated-fp32 baseline.
* **peak HBM** — flight-check's static liveness walk over each arm's
  real jitted step (sharding-aware: it sees the 1/n optimizer state);
  the criterion is the zero1 arm's peak lower than baseline by AT
  LEAST optimizer_state_bytes*(n-1)/n (the sharded accumulation
  buffer wins more on top). The live sampled peak rides along when
  the backend exposes memory stats (CPU jax usually does not — null
  then).
* **parity** — per-step loss deviation vs the replicated baseline:
  ~ulp for fp32 zero1, and for int8 a one-shot reduce-scatter +
  all-gather roundtrip is checked against the published TPU606 bound
  (``COMPRESSION_NUMERICS``).
* **compiles** — each arm's loop runs telemetry-wrapped; the criterion
  is ZERO post-warmup recompiles (the static do_sync pair is two
  stable programs).

Writes the JSON report to stdout:

    JAX_PLATFORMS=cpu python benchmarks/bench_zero1.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.utils.environment import force_host_platform  # noqa: E402


def build_arm(name: str, zero: bool, method, hidden: int, n_data: int):
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
    from accelerate_tpu.modeling import Model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    acc = Accelerator(
        kwargs_handlers=[TelemetryKwargs(enabled=False, hbm_sample_every=4)],
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=n_data),
            zero_stage=1 if zero else 0,
            grad_compression=method,
        ),
    )
    rng = np.random.default_rng(0)
    params = {
        "w1": (rng.normal(size=(hidden, hidden)) * 0.05).astype(np.float32),
        "b1": np.zeros((hidden,), np.float32),
        "w2": (rng.normal(size=(hidden, hidden // 4)) * 0.05).astype(np.float32),
        "b2": np.zeros((hidden // 4,), np.float32),
    }

    def apply_fn(p, x):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    model = acc.prepare_model(Model(apply_fn, params))
    opt = acc.prepare_optimizer(optax.adam(1e-2))

    def loss_fn(p, batch):
        return ((apply_fn(p, batch["x"]) - batch["y"]) ** 2).mean()

    step = acc.build_train_step(loss_fn)
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    return acc, model, opt, step, sharding, loss_fn


def measure_arm(name, zero, method, args_ns):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.parallel.compression import wire_bytes
    from accelerate_tpu.telemetry.wire import hlo_wire_bytes
    from accelerate_tpu.utils.random import key_for_step

    n = args_ns.data
    acc, model, opt, step, sharding, loss_fn = build_arm(
        name, zero, method, args_ns.hidden, n
    )
    tel = acc.telemetry
    box = acc._fast_scale_boxes[-1]

    rng = np.random.default_rng(1)
    w_ref = rng.normal(size=(args_ns.hidden, args_ns.hidden // 4)).astype(np.float32) * 0.3
    x_all = rng.normal(size=(args_ns.batch * 4, args_ns.hidden)).astype(np.float32)
    y_all = np.tanh(x_all) @ w_ref

    batch0 = {
        "x": jax.device_put(x_all[: args_ns.batch], sharding),
        "y": jax.device_put(y_all[: args_ns.batch], sharding),
    }
    sample = (
        model.params, opt.opt_state, box["grad_buf"], None, batch0,
        box["scale_state"], True if zero else jnp.bool_(True),
        key_for_step(0), jnp.float32(-1.0), box["comp_state"],
    )

    # -- wire bytes: cost-model prediction vs compiled-HLO measurement --
    predicted = wire_bytes(model.params, method, n=n, zero_stage=1 if zero else 0)
    hlo = step._jitted.lower(*sample).compile().as_text()
    measured = hlo_wire_bytes(hlo)
    wire_rec = tel.record_wire_bytes(
        predicted, measured["total"], label=name, by_primitive=measured["by_primitive"],
        # one-time backend-upcast warning: a compressed arm whose dominant
        # collective got widened by the backend (XLA:CPU bf16->f32) is
        # named instead of silently losing its wire saving
        requested_wire_dtype=method, sites=measured["sites"],
        platform=jax.default_backend(),
    )

    # -- static peak HBM (flight-check sees the sharded opt state) ------
    inner = step._jitted.__wrapped__
    sync = True if zero else jnp.bool_(True)

    def fn(p, o, g, b, s, r, c, cs, _inner=inner, _sync=sync):
        return _inner(p, o, g, None, b, s, _sync, r, c, cs)

    fn.__name__ = f"{name}_train_step"
    report = acc.flight_check(
        fn, model.params, opt.opt_state, box["grad_buf"], batch0,
        box["scale_state"], key_for_step(0), jnp.float32(-1.0), box["comp_state"],
        donate_argnums=(0, 1, 2),
    )

    opt_bytes_global = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(leaf, "size")
    )

    # -- telemetry-wrapped training loop: parity + recompiles -----------
    wrapped = tel.wrap(step)
    losses = []
    for s in range(args_ns.steps):
        lo = (s * args_ns.batch) % (3 * args_ns.batch)
        batch = {
            "x": jax.device_put(x_all[lo : lo + args_ns.batch], sharding),
            "y": jax.device_put(y_all[lo : lo + args_ns.batch], sharding),
        }
        losses.append(float(wrapped(batch)))

    return {
        "zero_stage": 1 if zero else 0,
        "grad_compression": method,
        "predicted_wire_bytes_per_step": predicted,
        "measured_wire_bytes_per_step": measured["total"],
        "measured_by_primitive": measured["by_primitive"],
        "wire_prediction_drift": wire_rec["drift"],
        "static_peak_hbm_bytes": report.peak_hbm_bytes,
        "sampled_peak_hbm_bytes": tel.hbm.observed_peak_bytes or None,
        "optimizer_state_bytes_global": opt_bytes_global,
        "opt_state_bytes_per_device": sum(
            shard.data.nbytes
            for leaf in jax.tree_util.tree_leaves(opt.opt_state)
            if hasattr(leaf, "addressable_shards")
            for shard in leaf.addressable_shards[:1]
        ),
        "post_warmup_recompiles": tel.recompiles,
        "final_loss": losses[-1],
        "losses": [round(x, 6) for x in losses],
    }


def tpu606_roundtrip_check(n_data: int):
    """One-shot quantized reduce-scatter + all-gather roundtrip vs the
    exact path, checked against the published COMPRESSION_NUMERICS
    bounds (the collective-level TPU606 pin)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.analysis.numerics_rules import COMPRESSION_NUMERICS
    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.zero import all_gather_updates, reduce_scatter_grads
    from accelerate_tpu.utils.compat import shard_map

    mesh = MeshConfig(data=n_data).build()
    g = jax.random.normal(jax.random.key(11), (n_data, 4096), jnp.float32) * 1.7

    def roundtrip(method):
        def body(x):
            flat = {"g": x[0] * (1.0 / n_data)}
            err0 = None if method is None else {"g": jnp.zeros_like(flat["g"])}
            shard, _ = reduce_scatter_grads(flat, ("data",), n_data, method, err0)
            err1 = None if method is None else {"g": jnp.zeros_like(shard["g"])}
            full, _ = all_gather_updates(shard, ("data",), n_data, method, err1)
            return full["g"][None]

        fn = shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False
        )
        return np.asarray(fn(g)).reshape(n_data, -1)[0]

    exact = roundtrip(None)
    amax = float(np.abs(np.asarray(g)).max())
    out = {}
    for method in ("int8", "fp8", "bf16"):
        err = float(np.abs(roundtrip(method) - exact).max())
        bound = COMPRESSION_NUMERICS[method].bound(amax, n_data)
        out[method] = {
            "max_abs_error": err,
            "tpu606_bound": bound,
            "within_bound": bool(err <= bound),
        }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="small fast config (CI)")
    ap.add_argument("--data", type=int, default=8, help="data-parallel degree")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()
    if args.smoke:
        args.hidden, args.steps = min(args.hidden, 256), min(args.steps, 40)

    force_host_platform(args.data)

    arms = {}
    for name, (zero, method) in {
        "baseline": (False, None),
        "zero1": (True, None),
        "zero1_int8": (True, "int8"),
    }.items():
        arms[name] = measure_arm(name, zero, method, args)

    base, z1, zi = arms["baseline"], arms["zero1"], arms["zero1_int8"]
    n = args.data
    opt_win = base["optimizer_state_bytes_global"] * (n - 1) // n
    hbm_drop = base["static_peak_hbm_bytes"] - z1["static_peak_hbm_bytes"]
    dev_fp32 = max(
        abs(a - b) / max(abs(b), 1e-9)
        for a, b in zip(z1["losses"], base["losses"])
    )
    dev_int8 = max(
        abs(a - b) / max(abs(b), 1e-9)
        for a, b in zip(zi["losses"], base["losses"])
    )
    tpu606 = tpu606_roundtrip_check(n)

    report = {
        "bench": "zero1",
        "config": {
            "data_parallel": n,
            "hidden": args.hidden,
            "batch": args.batch,
            "steps": args.steps,
            "param_bytes": int(
                sum(v for v in [args.hidden * args.hidden, args.hidden,
                                args.hidden * (args.hidden // 4), args.hidden // 4]) * 4
            ),
        },
        "arms": arms,
        "criteria": {
            "wire_zero1_int8_over_baseline": round(
                zi["measured_wire_bytes_per_step"] / base["measured_wire_bytes_per_step"], 4
            ),
            "wire_zero1_int8_leq_25pct": bool(
                zi["measured_wire_bytes_per_step"]
                <= 0.27 * base["measured_wire_bytes_per_step"]
            ),
            "wire_prediction_within_10pct": bool(
                all(a["wire_prediction_drift"] <= 0.10 for a in arms.values())
            ),
            "static_hbm_drop_bytes": hbm_drop,
            "optimizer_state_win_bytes": opt_win,
            "hbm_drop_covers_opt_state_win": bool(hbm_drop >= opt_win),
            "fp32_parity_max_rel_dev": dev_fp32,
            "int8_parity_max_rel_dev": dev_int8,
            "tpu606_roundtrip": tpu606,
            "parity_within_tpu606": bool(
                dev_fp32 < 1e-5
                and dev_int8 < 0.05
                and all(v["within_bound"] for v in tpu606.values())
            ),
            "zero_post_warmup_recompiles": bool(
                all(a["post_warmup_recompiles"] == 0 for a in arms.values())
            ),
        },
    }
    report["ok"] = bool(
        report["criteria"]["wire_zero1_int8_leq_25pct"]
        and report["criteria"]["wire_prediction_within_10pct"]
        and report["criteria"]["hbm_drop_covers_opt_state_win"]
        and report["criteria"]["parity_within_tpu606"]
        and report["criteria"]["zero_post_warmup_recompiles"]
    )
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
