"""Long-context attention benchmark: Pallas flash kernel vs XLA einsum.

No reference analogue exists — HF Accelerate has no attention kernels and
no long-context story beyond the Megatron SP flag (SURVEY §5); this
benchmark documents the parity-PLUS capability: O(S) memory causal flash
attention (ops/pallas_attention.py) against the O(S^2) XLA softmax chain,
fwd+bwd (training shape), across sequence lengths.

Usage: python benchmarks/long_context.py [--small]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def bench_attention(seq: int, impl: str, batch: int, heads: int, head_dim: int, interpret: bool):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.attention import dot_product_attention

    q = jax.random.normal(jax.random.key(0), (batch, seq, heads, head_dim), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (batch, seq, heads, head_dim), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (batch, seq, heads, head_dim), jnp.bfloat16)

    use_flash = impl == "flash"

    def loss(q, k, v):
        if use_flash and interpret:
            from accelerate_tpu.ops.attention import sharded_pallas_attention

            out = sharded_pallas_attention(q, k, v, causal=True, interpret=True)
        else:
            out = dot_product_attention(q, k, v, causal=True, use_flash=use_flash)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    from _timing import force

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    t0 = time.perf_counter()
    force(step(q, k, v))
    compile_s = time.perf_counter() - t0
    for _ in range(2):
        out = step(q, k, v)
    force(out)
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        out = step(q, k, v)
    force(out)
    ms = (time.perf_counter() - t0) / n * 1000
    return compile_s, ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU smoke mode (interpret-mode Pallas)")
    args = ap.parse_args()

    import jax

    on_tpu = jax.default_backend() == "tpu"
    if args.small:
        seqs, batch, heads, head_dim = [256], 1, 2, 64
    else:
        seqs, batch, heads, head_dim = [2048, 4096, 8192], 4, 16, 64

    for seq in seqs:
        row = {"bench": "long_context_attention_fwd_bwd", "seq": seq, "batch": batch, "heads": heads}
        try:
            _, xla_ms = bench_attention(seq, "xla", batch, heads, head_dim, interpret=False)
            row["xla_ms"] = round(xla_ms, 2)
        except Exception as e:  # very long seqs can OOM the quadratic path — that IS the result
            row["xla_ms"] = None
            row["xla_error"] = f"{type(e).__name__}"
        _, flash_ms = bench_attention(seq, "flash", batch, heads, head_dim, interpret=not on_tpu)
        row["flash_ms"] = round(flash_ms, 2)
        if row.get("xla_ms"):
            row["flash_speedup"] = round(row["xla_ms"] / flash_ms, 2)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
