"""Headline benchmark: BERT-base fine-tune throughput (samples/sec).

Matches BASELINE.json's metric ("BERT-base MRPC samples/sec + step time").
Runs on whatever accelerator is attached (the driver runs this on one real
TPU chip). Prints ONE JSON line:

  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N, ...}

``vs_baseline`` is measured against a **per-chip A100 baseline of 350
samples/sec** — the commonly reported BERT-base GLUE fine-tune throughput
(seq 128, fp16, HF Trainer) on one A100; the reference's north-star target
(BASELINE.json) is v5e-8 within 10% of 8xA100, i.e. per-chip parity ~0.9+.
"""

from __future__ import annotations

import json
import time

A100_PER_CHIP_SAMPLES_PER_SEC = 350.0


def main():
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.parallel.mesh import batch_sharding

    seq_len = 128
    batch_size = 128  # per-chip; v5e HBM fits this comfortably in bf16

    accelerator = Accelerator(mixed_precision="bf16")
    n_dev = accelerator.state.num_devices
    global_batch = batch_size * accelerator.num_data_shards

    model = accelerator.prepare_model(create_bert_model(BertConfig.base(), seq_len=seq_len))
    optimizer = accelerator.prepare_optimizer(optax.adamw(2e-5, weight_decay=0.01))
    loss_fn = lambda p, b: bert_classification_loss(p, b, model.apply_fn)
    step = accelerator.build_train_step(loss_fn)

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(5, 30000, size=(global_batch, seq_len)).astype(np.int32),
        "attention_mask": np.ones((global_batch, seq_len), np.bool_),
        "labels": rng.integers(0, 2, size=(global_batch,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(accelerator.mesh))

    # compile + warmup; float(loss) both synchronises (scalar D2H fetch)
    # and surfaces NaNs immediately.
    t_compile = time.perf_counter()
    float(step(batch))
    compile_s = time.perf_counter() - t_compile
    for _ in range(3):
        loss = step(batch)
    float(loss)

    # steady state
    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0

    step_time_ms = dt / n_steps * 1000
    samples_per_sec = global_batch * n_steps / dt
    per_chip = samples_per_sec / n_dev

    print(
        json.dumps(
            {
                "metric": "bert_base_seq128_train_samples_per_sec",
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec",
                "vs_baseline": round(per_chip / A100_PER_CHIP_SAMPLES_PER_SEC, 3),
                "step_time_ms": round(step_time_ms, 2),
                "per_chip_samples_per_sec": round(per_chip, 1),
                "compile_s": round(compile_s, 1),
                "n_devices": n_dev,
                "global_batch": global_batch,
                "backend": accelerator.state.backend,
                "baseline": "350 samples/sec/A100 (BERT-base seq128 fp16 fine-tune)",
            }
        )
    )


if __name__ == "__main__":
    main()
