"""Headline benchmark: BERT-base fine-tune throughput (samples/sec).

Matches BASELINE.json's metric ("BERT-base MRPC samples/sec + step time").
Runs on whatever accelerator is attached (the driver runs this on one real
TPU chip). Prints ONE JSON line:

  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N, ...}

``vs_baseline`` is measured against a **per-chip A100 baseline of 350
samples/sec** — the commonly reported BERT-base GLUE fine-tune throughput
(seq 128, fp16, HF Trainer) on one A100; the reference's north-star target
(BASELINE.json) is v5e-8 within 10% of 8xA100, i.e. per-chip parity ~0.9+.

Robustness (round-1 postmortem): the TPU backend behind the axon tunnel can
be transiently UNAVAILABLE at process start — backend init is retried with
backoff, and any terminal failure still prints a single diagnostic JSON line
instead of a bare traceback.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

A100_PER_CHIP_SAMPLES_PER_SEC = 350.0


def _peak_bf16_tflops():
    """bf16 peak TFLOP/s per chip for MFU, from the SAME per-generation
    table the static cost model prices with
    (``analysis.costmodel.PEAK_FLOPS_TABLE``) — runtime MFU and static
    rooflines must never disagree about "peak"."""
    from accelerate_tpu.analysis.costmodel import PEAK_FLOPS_TABLE

    return {gen: row["bf16"] / 1e12 for gen, row in PEAK_FLOPS_TABLE.items()}


def _peak_for_device(devices):
    """(peak_tflops, device_kind string) for the attached chip; v5e (the
    cost-optimised part) is the conservative fallback."""
    table = _peak_bf16_tflops()
    device_kind = getattr(devices[0], "device_kind", "unknown")
    peak = next(
        (v for k, v in table.items() if k in str(device_kind).lower()),
        table["v5e"],
    )
    return peak, device_kind


def _probe_backend(
    max_tries: int | None = None,
    probe_timeout: int | None = None,
    base_delay: float = 15.0,
    budget_s: float | None = None,
):
    """Verify the accelerator backend actually initialises before touching it
    in-process. The axon TPU plugin has two failure modes observed in round 1:
    raising UNAVAILABLE right after the tunnel comes up, and *hanging* inside
    backend init (uninterruptible C call) — so the probe runs in a subprocess
    with a hard timeout and retries with backoff.

    BENCH_r01-r05 postmortem: in harness environments where the tunnel never
    comes up the old ~45-min ride-out just *looked* like bench.py hanging.
    The probe is now bounded twice over — per-try by the subprocess timeout,
    and overall by ``budget_s`` wall clock — and every knob has a flag/env:
    ``--probe-tries``/``ACCELERATE_BENCH_PROBE_TRIES`` (default 4),
    ``--probe-timeout``/``ACCELERATE_BENCH_PROBE_TIMEOUT_S`` (default 120 s
    per try), ``--probe-budget``/``ACCELERATE_BENCH_PROBE_BUDGET_S``
    (default 600 s total). A terminal failure raises with a diagnostic that
    names the ``--platform cpu`` escape hatch; ``main`` turns it into the
    single JSON error line the driver expects."""
    import subprocess

    max_tries = int(os.environ.get("ACCELERATE_BENCH_PROBE_TRIES", 4) if max_tries is None else max_tries)
    probe_timeout = int(
        os.environ.get("ACCELERATE_BENCH_PROBE_TIMEOUT_S", 120) if probe_timeout is None else probe_timeout
    )
    budget_s = float(os.environ.get("ACCELERATE_BENCH_PROBE_BUDGET_S", 600) if budget_s is None else budget_s)
    deadline = time.monotonic() + budget_s
    last = "unknown"
    for attempt in range(max_tries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", "import jax; print('ndev', len(jax.devices()))"],
                capture_output=True,
                text=True,
                timeout=min(probe_timeout, max(1.0, deadline - time.monotonic())),
            )
            if out.returncode == 0 and "ndev" in out.stdout:
                return
            last = (out.stderr or out.stdout).strip().splitlines()[-1][:200] if (out.stderr or out.stdout).strip() else f"rc={out.returncode}"
        except subprocess.TimeoutExpired:
            last = f"backend init hung >{probe_timeout}s"
        delay = min(base_delay * (1.5**attempt), 300.0)
        if attempt == max_tries - 1 or time.monotonic() + delay > deadline:
            break
        print(
            f"bench: backend probe {attempt + 1}/{max_tries} failed ({last}); "
            f"retrying in {delay:.0f}s ({max(0.0, deadline - time.monotonic()):.0f}s of budget left)",
            file=sys.stderr,
        )
        time.sleep(delay)
    raise RuntimeError(
        f"accelerator backend unreachable (probes: {last}; budget {budget_s:.0f}s). "
        "Re-run with --platform cpu (or ACCELERATE_BENCH_PLATFORM=cpu) for a CPU smoke "
        "number, or raise --probe-budget to ride out a tunnel outage."
    )


def _init_backend_with_retry(max_tries: int = 6, base_delay: float = 5.0):
    """jax.devices() with retry: the axon TPU plugin intermittently reports
    UNAVAILABLE right after the tunnel comes up."""
    import jax

    last = None
    for attempt in range(max_tries):
        try:
            return jax.devices()
        except RuntimeError as e:  # noqa: PERF203
            last = e
            if "UNAVAILABLE" not in str(e) and "backend" not in str(e).lower():
                raise
            if attempt == max_tries - 1:
                break
            delay = base_delay * (1.5**attempt)
            print(
                f"bench: backend init attempt {attempt + 1}/{max_tries} failed "
                f"({str(e).splitlines()[0][:120]}); retrying in {delay:.0f}s",
                file=sys.stderr,
            )
            time.sleep(delay)
    raise last


def _bert_step_flops(params, global_batch: int, seq_len: int) -> float:
    """Training-step FLOPs ≈ 6 * non-embedding-params * tokens (fwd 2x,
    bwd 4x). Embedding lookups are gathers, not matmuls — excluded, but the
    tied projection would count for an LM head; BERT classification head is
    tiny either way."""
    import jax
    import numpy as np

    def is_embedding(path):
        return any("embed" in getattr(k, "key", str(k)).lower() for k in path)

    n_params = sum(
        int(np.prod(x.shape))
        for path, x in jax.tree_util.tree_flatten_with_path(params)[0]
        if not is_embedding(path)
    )
    return 6.0 * n_params * global_batch * seq_len


def _llama_step_flops(params, global_batch: int, seq_len: int, cfg) -> float:
    """6 * non-embedding-params * tokens, plus the attention-score FLOPs
    (2*S^2*hidden per layer fwd, x3 with bwd, halved by causality) that the
    params-based formula misses — material at seq 2048."""
    import jax
    import numpy as np

    def is_embedding(path):
        return any("embed" in getattr(k, "key", str(k)).lower() for k in path)

    n_params = sum(
        int(np.prod(x.shape))
        for path, x in jax.tree_util.tree_flatten_with_path(params)[0]
        if not is_embedding(path)
    )
    tokens = global_batch * seq_len
    attn = 0.5 * 12.0 * cfg.num_hidden_layers * global_batch * seq_len**2 * cfg.hidden_size
    # the tied lm_head projection lives under an 'embed' param path (so the
    # filter above drops it) but its logits matmul is real compute
    lm_head = 6.0 * tokens * cfg.hidden_size * cfg.vocab_size if cfg.tie_word_embeddings else 0.0
    return 6.0 * n_params * tokens + attn + lm_head


def run_llama_bench():
    """Second headline: decoder-LM training at long sequence — llama-750M
    class, seq 2048, flash attention + remat + scan-over-layers, fsdp x data
    mesh degenerate to one chip (VERDICT r4 #3: the regime the long-context
    kernels were built for; catches flash-bwd/remat regressions the BERT
    bench can't see). Prints ONE JSON line."""
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, causal_lm_loss, create_llama_model
    from accelerate_tpu.parallel.mesh import MeshConfig, batch_sharding
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import MixedPrecisionPolicy, ParallelismPlugin
    from accelerate_tpu.utils.memory import find_executable_batch_size

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    tiny = bool(os.environ.get("ACCELERATE_BENCH_FORCE_CPU"))
    if tiny:
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)  # idempotent; needed when run standalone
        cfg, seq_len, start_batch = LlamaConfig.tiny(), 128, 4
    else:
        # ~750M: the largest llama-class dense-Adam config that fits one
        # 16 GB v5e with headroom (16 bytes/param of train state = 12.1 GB
        # + seq-2048 boundary activations under remat)
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=1536,
            intermediate_size=6144,
            num_hidden_layers=20,
            num_attention_heads=12,
            num_key_value_heads=6,
            max_position_embeddings=2048,
            tie_word_embeddings=True,
        )
        seq_len, start_batch = 2048, 8

    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=-1, fsdp=1)),
        kwargs_handlers=[MixedPrecisionPolicy(softmax_dtype="bfloat16")],
    )
    n_dev = accelerator.state.num_devices
    devices = jax.devices()

    model = accelerator.prepare_model(create_llama_model(cfg, seq_len=seq_len))
    accelerator.prepare_optimizer(optax.adamw(3e-4, weight_decay=0.01))
    step = accelerator.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))

    rng = np.random.default_rng(0)
    from accelerate_tpu.telemetry import StepTelemetry

    @find_executable_batch_size(starting_batch_size=start_batch)
    def measure(batch_size):
        global_batch = batch_size * accelerator.num_data_shards
        batch = {
            "input_ids": rng.integers(5, cfg.vocab_size - 1, size=(global_batch, seq_len)).astype(np.int32)
        }
        batch = jax.device_put(batch, batch_sharding(accelerator.mesh))
        # fresh telemetry per attempt: an OOM-halved retry changes the batch
        # shape, which must read as a new run, not a recompile storm
        telem = StepTelemetry(warmup_steps=2)
        tstep = telem.wrap(step)
        float(tstep(batch))  # compile (telemetry attributes it); surfaces OOM for the auto-halver
        for _ in range(2):
            loss = tstep(batch)
        float(loss)
        n_steps = 5 if tiny else 12
        t0 = time.perf_counter()
        for _ in range(n_steps):
            loss = tstep(batch)
        float(loss)
        dt = time.perf_counter() - t0
        return global_batch, dt / n_steps, telem

    global_batch, step_s, telem = measure()
    tokens_per_sec = global_batch * seq_len / step_s
    telem_summary = telem.summary()
    compile_s = telem.compile_ms / 1000.0

    peak, device_kind = _peak_for_device(devices)
    flops_per_step = _llama_step_flops(model.params, global_batch, seq_len, cfg)
    mfu = flops_per_step / step_s / (peak * 1e12 * n_dev)

    print(
        json.dumps(
            {
                "metric": "llama_750m_seq2048_flash_train_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(mfu / 0.45, 3),  # target: MFU >= 0.45 at seq 2048
                "step_time_ms": round(step_s * 1000, 2),
                "p95_step_ms": telem_summary.get("p95_step_ms"),
                "recompiles": telem.recompiles,
                "mfu": round(mfu, 4),
                "global_batch": global_batch,
                "seq_len": seq_len,
                "peak_bf16_tflops_assumed": peak,
                "device_kind": str(device_kind),
                "compile_s": round(compile_s, 1),
                "n_devices": n_dev,
                "baseline": "MFU 0.45 at seq 2048 with flash attention (VERDICT r4 #3 target)",
            }
        )
    )


def run_bench():
    import jax
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.parallel.mesh import batch_sharding

    import os

    tiny = bool(os.environ.get("ACCELERATE_BENCH_FORCE_CPU"))
    if tiny:
        # smoke mode (--platform cpu; the axon plugin ignores JAX_PLATFORMS):
        # tiny config + small batch so the escape hatch finishes in seconds,
        # not the hour BERT-base at batch 256 would take on a CPU
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(1)
    else:
        _probe_backend()
    devices = _init_backend_with_retry()

    seq_len = 128
    batch_size = 8 if tiny else 256  # per-chip; best measured v5e throughput (128→1524, 256→1562, 512 regresses)

    from accelerate_tpu.utils import MixedPrecisionPolicy

    # softmax_dtype=bf16: the step is HBM-bound (benchmarks/README.md "step
    # breakdown"); skipping the f32 [B,H,S,S] logits materialisation is the
    # one measured lever (1.10x, loss trajectory within 1.5e-4 @ 20 steps)
    accelerator = Accelerator(
        mixed_precision="bf16",
        kwargs_handlers=[MixedPrecisionPolicy(softmax_dtype="bfloat16")],
    )
    n_dev = accelerator.state.num_devices
    global_batch = batch_size * accelerator.num_data_shards

    model = accelerator.prepare_model(
        create_bert_model(BertConfig.tiny() if tiny else BertConfig.base(), seq_len=seq_len)
    )
    optimizer = accelerator.prepare_optimizer(optax.adamw(2e-5, weight_decay=0.01))
    loss_fn = lambda p, b: bert_classification_loss(p, b, model.apply_fn)
    step = accelerator.build_train_step(loss_fn)

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(5, 1000 if tiny else 30000, size=(global_batch, seq_len)).astype(np.int32),
        "attention_mask": np.ones((global_batch, seq_len), np.bool_),
        "labels": rng.integers(0, 2, size=(global_batch,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(accelerator.mesh))

    # Step telemetry replaces the hand-rolled compile/execute split: the
    # first call's dispatch is attributed as compile, every later call
    # fences on its outputs, and the recompile watchdog proves the steady
    # loop really replays ONE program (a silent recompile here would
    # invalidate the whole samples/sec claim).
    from accelerate_tpu.telemetry import StepTelemetry

    peak, device_kind = _peak_for_device(devices)
    flops_per_step = _bert_step_flops(model.params, global_batch, seq_len)
    telem = StepTelemetry(
        warmup_steps=2,
        flops_per_step=flops_per_step,
        peak_flops_per_device=peak * 1e12,
        n_devices=n_dev,
    )
    step = telem.wrap(step)

    # compile + warmup; float(loss) both synchronises (scalar D2H fetch)
    # and surfaces NaNs immediately.
    float(step(batch))
    compile_s = telem.compile_ms / 1000.0
    for _ in range(3):
        loss = step(batch)
    float(loss)

    # steady state
    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0

    step_time_ms = dt / n_steps * 1000
    samples_per_sec = global_batch * n_steps / dt
    per_chip = samples_per_sec / n_dev
    telem_summary = telem.summary()

    mfu = flops_per_step / (dt / n_steps) / (peak * 1e12 * n_dev)

    print(
        json.dumps(
            {
                "metric": "bert_base_seq128_train_samples_per_sec",
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec",
                "vs_baseline": round(per_chip / A100_PER_CHIP_SAMPLES_PER_SEC, 3),
                "step_time_ms": round(step_time_ms, 2),
                "p95_step_ms": telem_summary.get("p95_step_ms"),
                "recompiles": telem.recompiles,
                "per_chip_samples_per_sec": round(per_chip, 1),
                "mfu": round(mfu, 4),
                "peak_bf16_tflops_assumed": peak,
                "device_kind": str(device_kind),
                "compile_s": round(compile_s, 1),
                "n_devices": n_dev,
                "global_batch": global_batch,
                "backend": accelerator.state.backend,
                "baseline": "350 samples/sec/A100 (BERT-base seq128 fp16 fine-tune)",
            }
        )
    )


def _parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        "bench.py", description="Headline benchmarks (one JSON line per metric)"
    )
    ap.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default=os.environ.get("ACCELERATE_BENCH_PLATFORM", "auto"),
        help="cpu = skip the TPU backend probe entirely and run the CPU smoke "
        "configuration (the escape hatch for harnesses where the TPU tunnel "
        "hangs; also ACCELERATE_BENCH_PLATFORM=cpu)",
    )
    ap.add_argument("--probe-tries", type=int, default=None, help="TPU backend probe attempts (default 4)")
    ap.add_argument("--probe-timeout", type=int, default=None, help="per-probe subprocess timeout seconds (default 120)")
    ap.add_argument("--probe-budget", type=float, default=None, help="total probe wall-clock budget seconds (default 600)")
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv)
    if args.platform == "cpu":
        os.environ["ACCELERATE_BENCH_FORCE_CPU"] = "1"
    for flag, env in (
        (args.probe_tries, "ACCELERATE_BENCH_PROBE_TRIES"),
        (args.probe_timeout, "ACCELERATE_BENCH_PROBE_TIMEOUT_S"),
        (args.probe_budget, "ACCELERATE_BENCH_PROBE_BUDGET_S"),
    ):
        if flag is not None:
            os.environ[env] = str(flag)
    rc = 0
    try:
        run_bench()
    except Exception as e:
        rc = 1
        print(
            json.dumps(
                {
                    "metric": "bert_base_seq128_train_samples_per_sec",
                    "value": 0.0,
                    "unit": "samples/sec",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {str(e)[:400]}",
                    "traceback_tail": traceback.format_exc().splitlines()[-3:],
                }
            )
        )
    # second headline (decoder-LM long-seq training); its failure must not
    # mask a good BERT line and vice versa — each reports independently
    try:
        run_llama_bench()
    except Exception as e:
        rc = 1
        print(
            json.dumps(
                {
                    "metric": "llama_750m_seq2048_flash_train_tokens_per_sec",
                    "value": 0.0,
                    "unit": "tokens/sec",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {str(e)[:400]}",
                    "traceback_tail": traceback.format_exc().splitlines()[-3:],
                }
            )
        )
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    main()
