"""Complete CV example: ResNet classification with tracking, epoch/step
checkpointing (including BatchNorm running statistics), resume, and
gradient clipping.

Reference analogue: examples/complete_cv_example.py (the kitchen-sink
variant of cv_example.py: ``--checkpointing_steps``,
``--resume_from_checkpoint``, ``--with_tracking``).
"""

from __future__ import annotations

import argparse
import os

import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import ResNetConfig, create_resnet_model, resnet_classification_loss

from cv_example import SyntheticPets  # noqa: E402 — sibling script, same dataset


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--mixed_precision", default="bf16")
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--num_epochs", type=int, default=2)
    p.add_argument("--image_size", type=int, default=224)
    p.add_argument("--max_grad_norm", type=float, default=1.0)
    p.add_argument("--output_dir", default="complete_cv_out")
    p.add_argument("--checkpointing_steps", default=None, help='"epoch", an int interval, or omitted')
    p.add_argument("--resume_from_checkpoint", default=None)
    p.add_argument("--with_tracking", action="store_true")
    p.add_argument("--tiny", action="store_true", help="tiny config for CI")
    return p.parse_args()


def main():
    args = parse_args()
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="jsonl" if args.with_tracking else None,
        project_dir=args.output_dir,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    if args.tiny:
        args.image_size = min(args.image_size, 32)
    config = ResNetConfig.tiny() if args.tiny else ResNetConfig.resnet50(num_classes=37)
    dataset = SyntheticPets(n=256 if args.tiny else 1024, image_size=args.image_size, num_classes=config.num_classes)

    loader = accelerator.prepare_data_loader(
        dataset,
        batch_size=max(1, args.batch_size // accelerator.num_data_shards),
        shuffle=True,
        seed=42,
        drop_last=True,
    )
    model = create_resnet_model(config, image_size=args.image_size)
    total_steps = max(1, args.num_epochs * len(loader))
    peak_lr = args.lr if args.lr is not None else (1e-1 if args.tiny else 3e-2)
    schedule = optax.cosine_onecycle_schedule(total_steps, peak_lr, pct_start=0.25)
    optimizer = optax.sgd(schedule, momentum=0.9)

    model, optimizer = accelerator.prepare(model, optimizer)
    accelerator.clip_grad_norm_(None, args.max_grad_norm)
    step = accelerator.build_train_step(
        lambda p, s, b: resnet_classification_loss(p, s, b, model.apply_fn), has_state=True
    )
    eval_step = accelerator.build_eval_step(lambda p, s, x: model.apply_fn(p, x, state=s, train=False))

    start_epoch = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        start_epoch = loader.state_dict().get("sampler_epoch") or 0
        accelerator.print(f"resumed from {args.resume_from_checkpoint} at epoch {start_epoch}")

    ckpt_every = None
    if args.checkpointing_steps and args.checkpointing_steps != "epoch":
        ckpt_every = int(args.checkpointing_steps)

    global_step = accelerator.step  # restored by load_state on resume
    accuracy = 0.0
    for epoch in range(start_epoch, args.num_epochs):
        loader.set_epoch(epoch)
        total_loss = 0.0
        loss = None
        for batch in loader:
            loss = step(batch)
            global_step += 1
            if args.with_tracking:
                total_loss += float(loss)
            if ckpt_every and global_step % ckpt_every == 0:
                accelerator.save_state(os.path.join(args.output_dir, f"step_{global_step}"))
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))

        correct = total = 0
        for batch in loader:
            logits = eval_step(batch["images"])
            preds = accelerator.gather_for_metrics(jnp.argmax(logits, -1))
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accuracy = correct / total
        loss_str = f"{float(loss):.4f}" if loss is not None else "n/a (no train batches after resume skip)"
        accelerator.print(f"epoch {epoch}: accuracy={accuracy:.3f} loss={loss_str}")
        if args.with_tracking:
            accelerator.log(
                {"accuracy": accuracy, "train_loss": total_loss / max(1, len(loader)), "epoch": epoch},
                step=global_step,
            )

    accelerator.save_state(os.path.join(args.output_dir, "final"))
    accelerator.end_training()
    return accuracy


if __name__ == "__main__":
    main()
