"""Complete NLP example: every production knob in one training script —
tracking, step/epoch checkpointing, exact mid-epoch resume, gradient
clipping, LR schedule, metrics gather.

Reference analogue: examples/complete_nlp_example.py (the "kitchen sink"
variant of nlp_example.py whose CLI contract —
``--checkpointing_steps epoch|N``, ``--resume_from_checkpoint``,
``--with_tracking`` — the by_feature scripts each demonstrate in
isolation).
"""

from __future__ import annotations

import argparse
import os

import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model

from nlp_example import SyntheticMRPC  # noqa: E402 — sibling script, same dataset


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--mixed_precision", default="bf16")
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--num_epochs", type=int, default=2)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--max_grad_norm", type=float, default=1.0)
    p.add_argument("--output_dir", default="complete_nlp_out")
    p.add_argument(
        "--checkpointing_steps",
        default=None,
        help='"epoch", an integer step interval, or omitted for no mid-run checkpoints',
    )
    p.add_argument("--resume_from_checkpoint", default=None)
    p.add_argument("--with_tracking", action="store_true")
    p.add_argument("--tiny", action="store_true", help="tiny config for CI")
    return p.parse_args()


def main():
    args = parse_args()
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="jsonl" if args.with_tracking else None,
        project_dir=args.output_dir,
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", config=vars(args))

    config = BertConfig.tiny(num_labels=2) if args.tiny else BertConfig.base()
    dataset = SyntheticMRPC(n=256 if args.tiny else 3668, seq_len=args.seq_len, vocab_size=config.vocab_size)
    model = create_bert_model(config, seq_len=args.seq_len)
    steps_per_epoch = max(1, len(dataset) // args.batch_size)
    schedule = optax.linear_schedule(args.lr, 0.0, args.num_epochs * steps_per_epoch)
    optimizer = optax.adamw(schedule, weight_decay=0.01)

    loader = accelerator.prepare_data_loader(
        dataset,
        batch_size=max(1, args.batch_size // accelerator.num_data_shards),
        shuffle=True,
        seed=42,
    )
    model, optimizer = accelerator.prepare(model, optimizer)
    accelerator.clip_grad_norm_(None, args.max_grad_norm)  # traced into the step
    step = accelerator.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
    eval_step = accelerator.build_eval_step(lambda p, ids, mask: model.apply_fn(p, ids, mask))

    start_epoch = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        # the dataloader's own state (batches_yielded / sampler epoch) is in
        # the checkpoint, so iteration resumes mid-epoch exactly
        start_epoch = loader.state_dict().get("sampler_epoch") or 0
        accelerator.print(f"resumed from {args.resume_from_checkpoint} at epoch {start_epoch}")

    ckpt_every = None
    if args.checkpointing_steps and args.checkpointing_steps != "epoch":
        ckpt_every = int(args.checkpointing_steps)

    global_step = accelerator.step  # restored by load_state on resume
    for epoch in range(start_epoch, args.num_epochs):
        loader.set_epoch(epoch)
        total_loss = 0.0
        loss = None
        for batch in loader:
            loss = step(batch)
            global_step += 1
            if args.with_tracking:
                total_loss += float(loss)
            if ckpt_every and global_step % ckpt_every == 0:
                accelerator.save_state(os.path.join(args.output_dir, f"step_{global_step}"))
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))

        correct = total = 0
        for batch in loader:
            logits = eval_step(batch["input_ids"], batch["attention_mask"])
            preds = accelerator.gather_for_metrics(jnp.argmax(logits, -1))
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accuracy = correct / total
        loss_str = f"{float(loss):.4f}" if loss is not None else "n/a (no train batches after resume skip)"
        accelerator.print(f"epoch {epoch}: accuracy={accuracy:.3f} loss={loss_str}")
        if args.with_tracking:
            accelerator.log(
                {"accuracy": accuracy, "train_loss": total_loss / max(1, len(loader)), "epoch": epoch},
                step=global_step,
            )

    accelerator.save_state(os.path.join(args.output_dir, "final"))
    accelerator.end_training()


if __name__ == "__main__":
    main()
