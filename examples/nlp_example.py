"""BERT-base sequence-classification fine-tune — the framework's canonical
example (reference analogue: examples/nlp_example.py, BERT-base on
GLUE/MRPC, the BASELINE.json headline config).

Offline-friendly: uses HF datasets/tokenizers when available, otherwise a
synthetic MRPC-shaped dataset (token ids + labels) so the example runs on a
bare TPU VM with zero egress. The training loop is the reference's shape:
Accelerator() -> prepare() -> loop -> gather_for_metrics -> save_state.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model


class SyntheticMRPC:
    """MRPC-shaped synthetic data: pairs encoded as token ids, binary label
    correlated with a learnable signal token so accuracy is meaningful."""

    def __init__(self, n=3668, seq_len=128, vocab_size=30522, seed=0):
        rng = np.random.default_rng(seed)
        self.ids = rng.integers(5, vocab_size, size=(n, seq_len)).astype(np.int32)
        self.labels = rng.integers(0, 2, size=(n,)).astype(np.int32)
        # plant a signal: label-1 rows get token 4 early in the sequence
        self.ids[self.labels == 1, 3] = 4
        self.mask = np.ones((n, seq_len), np.bool_)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return {"input_ids": self.ids[i], "attention_mask": self.mask[i], "labels": self.labels[i]}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16")
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=None, help="default: 2e-5 (base), 1e-3 (tiny)")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--tiny", action="store_true", help="tiny config for CI")
    parser.add_argument("--checkpoint_dir", default=None)
    args = parser.parse_args()

    accelerator = Accelerator(mixed_precision=args.mixed_precision, log_with="jsonl", project_dir="runs")
    accelerator.init_trackers("nlp_example", config=vars(args))

    if args.lr is None:
        args.lr = 1e-3 if args.tiny else 2e-5
    config = BertConfig.tiny(num_labels=2) if args.tiny else BertConfig.base()
    dataset = SyntheticMRPC(
        n=512 if args.tiny else 3668, seq_len=args.seq_len, vocab_size=config.vocab_size
    )
    model = create_bert_model(config, seq_len=args.seq_len)
    schedule = optax.linear_schedule(args.lr, 0.0, args.num_epochs * (len(dataset) // args.batch_size))
    optimizer = optax.adamw(schedule, weight_decay=0.01)

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(
        dataset,
        batch_size=max(1, args.batch_size // accelerator.num_data_shards),
        shuffle=True,
        seed=42,
    )
    model, optimizer, loader = accelerator.prepare(model, optimizer, loader)

    loss_fn = lambda p, b: bert_classification_loss(p, b, model.apply_fn)
    step = accelerator.build_train_step(loss_fn)
    eval_step = accelerator.build_eval_step(lambda p, ids, mask: model.apply_fn(p, ids, mask))

    for epoch in range(args.num_epochs):
        t0, n_samples = time.perf_counter(), 0
        for batch in loader:
            loss = step(batch)
            n_samples += batch["input_ids"].shape[0]
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        accelerator.log({"loss": float(loss), "samples_per_sec": n_samples / dt}, step=epoch)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} {n_samples / dt:.1f} samples/s")

        # eval pass with padded-tail truncation
        correct = total = 0
        for batch in loader:
            logits = eval_step(batch["input_ids"], batch["attention_mask"])
            preds = accelerator.gather_for_metrics(jnp.argmax(logits, -1))
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accelerator.print(f"epoch {epoch}: accuracy={correct / total:.3f} ({total} samples)")

    if args.checkpoint_dir:
        accelerator.save_state(args.checkpoint_dir)
    accelerator.end_training()


if __name__ == "__main__":
    main()
