"""Every reference "strategy" as a mesh layout — the TPU-native replacement
for DDP / FSDP / ZeRO / TP / Megatron config blocks (no reference analogue:
the reference needs a different plugin + launcher config per strategy;
here each is one MeshConfig line on the same script).

Run under a fake 8-device mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python examples/by_feature/mesh_parallelism.py
"""

import numpy as np
import optax

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

LAYOUTS = {
    "DDP (data parallel)": MeshConfig(data=-1),
    "FSDP / ZeRO-3": MeshConfig(data=1, fsdp=-1),
    "TP (Megatron splits)": MeshConfig(data=-1, tensor=2),
    "SP (sequence parallel)": MeshConfig(data=-1, seq=2),
    "3D hybrid": MeshConfig(data=2, fsdp=2, tensor=2),
}


def main():
    import jax

    ids = (np.arange(8 * 32).reshape(8, 32) % 1000).astype(np.int32)
    batch = {
        "input_ids": ids,
        "attention_mask": np.ones((8, 32), np.bool_),
        "labels": (np.arange(8) % 2).astype(np.int32),
    }
    for name, mesh_config in LAYOUTS.items():
        if np.prod([v for v in vars(mesh_config).values() if v != -1]) > len(jax.devices()):
            print(f"{name:24s} skipped (needs more devices)")
            continue
        for s in (AcceleratorState, GradientState, PartialState):
            s._reset_state()
        accelerator = Accelerator(
            mixed_precision="bf16",
            parallelism_plugin=ParallelismPlugin(mesh_config=mesh_config),
        )
        model = accelerator.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=32))
        accelerator.prepare_optimizer(optax.adamw(1e-3))
        step = accelerator.build_train_step(
            lambda p, b: bert_classification_loss(p, b, model.apply_fn)
        )
        loss = float(step(batch))
        axes = {k: v for k, v in accelerator.mesh.shape.items() if v > 1}
        print(f"{name:24s} mesh={axes or '{1 device}'} loss={loss:.3f}")


if __name__ == "__main__":
    main()
