"""LocalSGD: skip cross-replica grad sync for N steps, then average params
(reference analogue: examples/by_feature/local_sgd.py).
"""

from accelerate_tpu import Accelerator, LocalSGD

from _common import final_weights, make_task


def main():
    accelerator = Accelerator()
    model, optimizer, dataloader, loss_fn = make_task(accelerator)

    with LocalSGD(
        accelerator=accelerator, model=model, local_sgd_steps=8, enabled=True
    ) as local_sgd:
        for epoch in range(10):
            for batch in dataloader:
                with accelerator.accumulate(model):
                    accelerator.backward(loss_fn, batch)
                    optimizer.step()
                    optimizer.zero_grad()
                    local_sgd.step()

    a, b = final_weights(model)
    accelerator.print(f"LocalSGD result: a={a:.3f} (want 2), b={b:.3f} (want 3)")
    assert abs(a - 2) < 0.3 and abs(b - 3) < 0.3


if __name__ == "__main__":
    main()
