"""Gradient accumulation for autoregressive models
(reference analogue:
examples/by_feature/gradient_accumulation_for_autoregressive_models.py).

The causal-LM subtlety the reference example exists to teach: microbatches
carry different numbers of REAL (non-padded) tokens, so averaging each
microbatch's mean loss over-weights short batches. The fix is the same
here: scale each microbatch's summed loss by the number of real tokens in
the WHOLE accumulation window (num_samples_in_epoch bookkeeping,
reference :286-301). On TPU the window is still one jitted step per
microbatch — only the loss normalisation changes.
"""

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.models.llama import next_token_cross_entropy
from accelerate_tpu.utils import GradientAccumulationPlugin, set_seed

ACCUM = 4
SEQ = 16
BATCH = 8  # per-shard


def make_batches(n_windows, vocab, rng):
    """Variable-length sequences padded to SEQ: the loss_mask marks real
    tokens (what the reference gets from the tokenizer's attention mask)."""
    for _ in range(n_windows * ACCUM):
        ids = rng.integers(5, vocab, size=(BATCH, SEQ)).astype(np.int32)
        lengths = rng.integers(SEQ // 2, SEQ + 1, size=(BATCH,))
        mask = (np.arange(SEQ)[None, :] < lengths[:, None]).astype(np.float32)
        ids = np.where(mask > 0, ids, 0)
        yield {"input_ids": ids, "loss_mask": mask}


def main():
    import jax.numpy as jnp
    import optax

    set_seed(7)
    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=ACCUM)
    )
    cfg = LlamaConfig.tiny()
    model = accelerator.prepare_model(create_llama_model(cfg, seq_len=SEQ))
    accelerator.prepare_optimizer(optax.adamw(2e-3))

    def loss_fn(params, batch):
        # token-SUM loss normalised by the window's total real tokens: every
        # real token contributes equally regardless of its microbatch
        # (reference :286-301). The per-microbatch mean xentropy is
        # recovered by scaling with (microbatch tokens / window tokens)*ACCUM
        # because build_train_step averages the ACCUM microbatch losses.
        logits = model.apply_fn(params, batch["input_ids"])
        mean_loss = next_token_cross_entropy(logits, batch)
        mb_tokens = batch["loss_mask"].sum()
        window_tokens = batch["window_tokens"][0]
        return mean_loss * (mb_tokens / window_tokens) * ACCUM

    step = accelerator.build_train_step(loss_fn)

    rng = np.random.default_rng(0)
    batches = list(make_batches(12, cfg.vocab_size, rng))
    losses = []
    for w in range(0, len(batches), ACCUM):
        window = batches[w : w + ACCUM]
        window_tokens = np.float32(sum(b["loss_mask"].sum() for b in window))
        for b in window:
            b = dict(b, window_tokens=np.full((b["input_ids"].shape[0],), window_tokens, np.float32))
            losses.append(float(step(b)))

    first, last = np.mean(losses[:ACCUM]), np.mean(losses[-ACCUM:])
    accelerator.print(f"windowed CE: first={first:.3f} last={last:.3f}")
    assert last < first, (first, last)


if __name__ == "__main__":
    main()
