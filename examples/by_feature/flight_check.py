"""SPMD flight-check before the first compile: estimate peak HBM, price
the collectives, and catch deadlock/reshard/donation hazards statically.

Two surfaces on the same step function:

* ``Accelerator.flight_check(step_fn, *sample_args)`` — programmatic,
  against the accelerator's live mesh;
* ``accelerate-tpu flight-check examples/by_feature/flight_check.py::train_step``
  — the CLI resolves ``train_step`` here and reads its sample shapes from
  ``train_step_sample_args()`` below (or pass ``--arg f32[32,128]``).

The step is a plain MLP SGD update written shard_map-style (an explicit
``pmean`` over the data axis) so the traffic report has a collective to
price; the params argument is deliberately NOT donated so the report shows
what donation would save (and ``Accelerator.lint`` flags it as TPU103).
"""

import jax
import jax.numpy as jnp

HIDDEN = 512
FEATURES = 128
BATCH = 32


def train_step(params, batch):
    """One SGD step: forward, mean-squared loss, grads, cross-replica
    gradient mean (the explicit ``pmean`` the traffic report prices),
    update."""

    def loss_fn(p):
        # perf-check TPU501 prices the toy sizes honestly: the batch-of-32
        # contraction of the backward dW matmuls (K=batch) pads the
        # 128-lane MXU tile 75%, and the 1-wide regression head pads
        # 99.2%. Real fixes are batch>=128 / a wider head; this example
        # keeps the small shapes (the flight-check transcript depends on
        # them) and suppresses the warnings instead.
        h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])  # tpu-lint: disable=TPU501
        pred = h @ p["w2"] + p["b2"]  # tpu-lint: disable=TPU501
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = jax.lax.pmean(grads, "data")
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g, params, grads)
    return new_params, loss


def train_step_sample_args():
    """Abstract sample shapes for the CLI (nothing is allocated)."""
    f32 = jnp.float32
    params = {
        "w1": jax.ShapeDtypeStruct((FEATURES, HIDDEN), f32),
        "b1": jax.ShapeDtypeStruct((HIDDEN,), f32),
        "w2": jax.ShapeDtypeStruct((HIDDEN, 1), f32),
        "b2": jax.ShapeDtypeStruct((1,), f32),
    }
    batch = {
        "x": jax.ShapeDtypeStruct((BATCH, FEATURES), f32),
        "y": jax.ShapeDtypeStruct((BATCH, 1), f32),
    }
    return params, batch


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    report = accelerator.flight_check(train_step, *train_step_sample_args())
    accelerator.print(report.render_text())
    # donation would let XLA reuse the params buffer in place:
    donated = accelerator.flight_check(train_step, *train_step_sample_args(), donate_argnums=(0,))
    accelerator.print(
        f"donate_argnums=(0,) marks {donated.donated_bytes:,} B of params reusable in place "
        f"(peak {report.peak_hbm_bytes:,} -> {donated.peak_hbm_bytes:,} B/device)"
    )


if __name__ == "__main__":
    main()
