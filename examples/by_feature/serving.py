"""Continuous-batching serving: mixed-length requests through a slot pool.

Reference analogue: examples/inference/distributed/phi2.py etc. drive
transformers generate under process splits; here the serving loop itself
is framework surface (accelerate_tpu/serving.py) — slots, prefill
buckets, one vmapped decode tick per block of tokens.

Run: python examples/by_feature/serving.py
"""

from __future__ import annotations

import numpy as np


def main():
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ServingEngine

    model = create_llama_model(LlamaConfig.tiny(), seq_len=64)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (5, 11, 3, 8, 14, 6)]

    engine = ServingEngine(model, num_slots=2, prompt_buckets=(8, 16), tick_block=4)
    outs = engine.generate_many(prompts, max_new_tokens=8)

    # every output is token-exact vs a dedicated static generate() call
    for prompt, out in zip(prompts, outs):
        want = np.asarray(generate(model, prompt[None], max_new_tokens=8))[0]
        np.testing.assert_array_equal(out, want)
    print(f"served {len(prompts)} mixed-length requests through 2 slots, token-exact")

    # incremental submission (a server loop shape): streaming partial(),
    # per-token logprobs, and a per-request stop sequence
    gen = outs[0][len(prompts[0]):]
    uid = engine.submit(
        prompts[0], max_new_tokens=8, stop_sequences=[[int(gen[1]), int(gen[2])]]
    )
    while engine.poll(uid) is None:
        engine.step()
    lps = engine.logprobs(uid)
    final = engine.poll(uid)
    assert len(final) < len(outs[0]), "stop sequence should end generation early"
    print(
        f"incremental request stopped at the 2-token stop sequence: "
        f"{final[-4:].tolist()}, logprobs {np.round(lps, 2).tolist()}"
    )

    # paged KV cache: pool capacity set by tokens in flight, not
    # slots x max_len (128 here) — a 14-block pool serves 4 slots
    # (admission waits when blocks run out, then drains exactly)
    block_size, pool_blocks = 8, 14
    paged = ServingEngine(
        model, num_slots=4, prompt_buckets=(8, 16),
        paged_block_size=block_size, pool_blocks=pool_blocks,
    )
    free0 = paged.pool_free_blocks
    outs_paged = paged.generate_many(prompts, max_new_tokens=8)
    for want, got in zip(outs, outs_paged):
        np.testing.assert_array_equal(got, want)
    assert paged.pool_free_blocks == free0
    pool_rows = pool_blocks * block_size
    dense_rows = paged.num_slots * paged.max_len
    print(
        f"paged: same tokens from a pool of {pool_rows} cache rows "
        f"({pool_rows / dense_rows:.0%} of the {dense_rows} dense rows)"
    )
    print("serving example OK")


if __name__ == "__main__":
    main()
