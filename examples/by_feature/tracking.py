"""Experiment tracking (reference analogue: examples/by_feature/tracking.py).

`init_trackers` starts every configured tracker (TensorBoard by default when
available); `accelerator.log` fans metrics out to all of them on the main
process only.
"""

import tempfile

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import ProjectConfiguration

from _common import make_task


def main():
    with tempfile.TemporaryDirectory() as logdir:
        accelerator = Accelerator(
            log_with="all",
            project_config=ProjectConfiguration(project_dir=logdir),
        )
        accelerator.init_trackers("by_feature_tracking", config={"lr": 0.1, "batch_size": 16})
        model, optimizer, dataloader, loss_fn = make_task(accelerator)
        step = accelerator.build_train_step(loss_fn)

        global_step = 0
        for epoch in range(2):
            for batch in dataloader:
                loss = step(batch)
                accelerator.log({"train/loss": float(loss)}, step=global_step)
                global_step += 1
        accelerator.print(f"logged {global_step} steps to {len(accelerator.trackers)} tracker(s)")
        accelerator.end_training()


if __name__ == "__main__":
    main()
