"""Host-concurrency analysis for threaded serving code: find the ABBA
deadlock, the cross-thread race, and the lock-held sleep *before* any
thread runs — then prove the fleet's health protocol by model checking.

Everything here is pure stdlib (``accelerate_tpu.analysis.hostsim`` /
``fleet_rules`` import no jax), so this example runs on any machine:

    python examples/by_feature/fleet_check.py
    accelerate-tpu fleet-check examples/by_feature/fleet_check.py --no-protocol
    accelerate-tpu fleet-check --selfcheck     # the full TPU901-905 proof

``SeededRouter`` below packs four real defects into one small class —
each is a pattern the TPU9xx tier catches in code review instead of as a
production hang; ``FixedRouter`` is the clean twin the lint stays silent
on. The second half runs the protocol model checker against the *real*
``serving_fleet.py`` and prints the chaos-coverage map (every explored
failure path -> the ``ReplicaChaos`` test that observes it).
"""

import textwrap

SEEDED = textwrap.dedent(
    '''
    """A router with four seeded host-concurrency defects."""
    import threading
    import time


    class SeededRouter:
        def __init__(self):
            self._lock = threading.Lock()
            self._stats_lock = threading.Lock()
            self.health = "healthy"

        def route(self):
            with self._lock:              # A then B ...
                with self._stats_lock:
                    pass

        def report(self):
            with self._stats_lock:        # ... B then A: TPU901 ABBA deadlock
                with self._lock:
                    time.sleep(0.5)       # TPU903: 0.5s stall for every waiter

        def set_health(self, v):
            self.health = v               # TPU902: written with no lock ...

        def drain(self):
            def worker():
                if self.health == "healthy":   # ... read from another thread
                    pass
            t = threading.Thread(target=worker)
            t.start()                     # TPU905: never joined
            self.set_health("dead")
    '''
)

FIXED = textwrap.dedent(
    '''
    """The same router with every defect repaired."""
    import threading
    import time


    class FixedRouter:
        def __init__(self):
            self._lock = threading.Lock()
            self._stats_lock = threading.Lock()
            self.health = "healthy"

        def route(self):
            with self._lock:              # one global order: _lock before
                with self._stats_lock:    # _stats_lock, everywhere
                    pass

        def report(self):
            with self._lock:
                with self._stats_lock:
                    pass
            time.sleep(0.5)               # the wait moved off the lock

        def set_health(self, v):
            with self._lock:
                self.health = v

        def drain(self):
            def worker():
                with self._lock:
                    if self.health == "healthy":
                        pass
            t = threading.Thread(target=worker)
            t.start()
            self.set_health("dead")
            t.join()
    '''
)


def main():
    from accelerate_tpu.analysis import render_text
    from accelerate_tpu.analysis.fleet_rules import coverage_map, fleet_protocol_check
    from accelerate_tpu.analysis.hostsim import host_check_source

    print("=== seeded router: four defects, four findings ===")
    findings = host_check_source(SEEDED, path="seeded_router.py")
    print(render_text(findings))
    assert sorted({f.rule for f in findings}) == ["TPU901", "TPU902", "TPU903", "TPU905"]

    print("=== fixed twin: silent ===")
    clean = host_check_source(FIXED, path="fixed_router.py")
    print(render_text(clean))
    assert clean == []

    print("=== the real fleet protocol, proved ===")
    proto_findings, report = fleet_protocol_check()
    assert proto_findings == [], render_text(proto_findings)
    print(f"explored {report.explored_states} reachable fleet states: "
          "no stranded requests, poisoned KV never ships, breaker exact")
    print("chaos coverage (model-checks = chaos-observes):")
    for path, test in sorted(coverage_map(report).items()):
        print(f"  {path:35s} -> {test}")


if __name__ == "__main__":
    main()
