"""Static Pallas kernel analysis before the first compile: extract every
``pl.pallas_call`` from the traced step, prove the blocks fit VMEM, the
tiles align to the MXU/VPU geometry, the index maps cover the output
without races, and the registered ``KernelCostSpec`` contract still
describes what the kernel body actually does (TPU1001–1006).

Two surfaces on the same decode step:

* ``Accelerator.kernel_check(step_fn, *sample_args)`` — programmatic,
  against the accelerator's live mesh;
* ``accelerate-tpu kernel-check examples/by_feature/kernel_check.py::decode_step``
  — the CLI reads the sample shapes from ``decode_step_sample_args()``
  below (or pass ``--arg f32[16,128]`` twice).

``decode_step`` uses the shipped :func:`block_matmul_softmax` reference
kernel, whose contract is exact — zero findings, and perfmodel prices
the declared 0.55 MFLOP on the roofline instead of the zero it would
count through an opaque call. The TPU1005 half of the story is shown
against a throwaway file: ``accelerate-tpu kernel-check <paths>`` (the
AST registration gate ``--changed`` scopes in CI) errors on any
``pallas_call`` whose kernel carries no contract, because an unpriced
kernel silently zeroes the roofline, liveness walk and interval proof
above it.
"""

import jax
import jax.numpy as jnp

BATCH = 16  # decode rows in flight
WIDTH = 128  # model dim == vocab tile (one MXU lane width)

_UNREGISTERED_SNIPPET = '''\
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def anonymous_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def step(x):
    return pl.pallas_call(
        anonymous_kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(x)
'''


def decode_step(x, w):
    """One decode logits step: ``softmax(x @ w)`` through the registered
    reference kernel (8-row blocks, w resident per grid step)."""
    from accelerate_tpu.kernels import block_matmul_softmax

    return block_matmul_softmax(x, w)


def decode_step_sample_args():
    """Abstract sample shapes for the CLI (nothing is allocated)."""
    return (
        jax.ShapeDtypeStruct((BATCH, WIDTH), jnp.float32),
        jax.ShapeDtypeStruct((WIDTH, WIDTH), jnp.float32),
    )


def main():
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(8)  # fake 8-device CPU mesh, same as the test suite
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    report = accelerator.kernel_check(decode_step, *decode_step_sample_args())
    accelerator.print(report.render_text())
    assert not report.findings, "the registered reference kernel must be clean"

    perf = accelerator.perf_check(decode_step, *decode_step_sample_args())
    priced = [o for o in perf.ops if o.primitive.startswith("pallas_call:")]
    accelerator.print(
        f"\nperfmodel prices the contract: {priced[0].primitive} at "
        f"{priced[0].flops / 1e6:.2f} MFLOP (declared, not zero)"
    )

    # the registration gate: an unregistered kernel is a TPU1005 error
    import tempfile

    from accelerate_tpu.analysis import render_text
    from accelerate_tpu.analysis.kernelmodel import scan_paths

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as fh:
        fh.write(_UNREGISTERED_SNIPPET)
        path = fh.name
    findings = scan_paths([path])
    accelerator.print("\n" + render_text(findings))
    assert any(f.rule == "TPU1005" for f in findings), "seeded TPU1005 must fire"


if __name__ == "__main__":
    main()
