"""Diffusion: train a tiny DDPM and generate images, distributed.

Reference analogue: examples/inference/distributed/
distributed_image_generation.py + stable_diffusion.py (drive a diffusers
pipeline under PartialState process splits). Here the denoiser (UNet2D),
schedule, and jitted DDIM sampler are in-tree (accelerate_tpu.diffusion),
and distribution is the usual mesh story:

* training: batch over ``data``/``fsdp``; the noise-prediction loss uses
  the step's folded rng (``build_train_step`` rng contract);
* sampling: ``sample`` is mesh-aware like ``generate`` — a sharded model
  denoises in place, batch split over ``data``.

Run (CPU fake mesh):
    python examples/by_feature/diffusion.py --fake-devices 8
Run (TPU):
    python examples/by_feature/diffusion.py
"""

from __future__ import annotations

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fake-devices", type=int, default=0)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--sample-steps", type=int, default=8)
    args = ap.parse_args()

    if args.fake_devices:
        from accelerate_tpu.utils.environment import force_host_platform

        force_host_platform(args.fake_devices)

    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.diffusion import diffusion_loss, make_schedule, sample
    from accelerate_tpu.models import UNetConfig, create_unet_model
    from accelerate_tpu.parallel.mesh import batch_sharding

    import jax

    acc = Accelerator(mixed_precision="bf16", log_with="jsonl", project_dir="runs")
    acc.init_trackers("diffusion_example")
    model = acc.prepare_model(create_unet_model(UNetConfig.tiny(sample_size=8), seed=0))
    acc.prepare_optimizer(optax.adam(2e-3))
    schedule = make_schedule(128)
    step = acc.build_train_step(
        lambda p, b, rng: diffusion_loss(p, b, model.apply_fn, schedule, rng)
    )

    # toy dataset: blurry gaussian blobs
    rng = np.random.default_rng(0)
    grid = np.stack(np.meshgrid(np.linspace(-1, 1, 8), np.linspace(-1, 1, 8)), -1)

    def make_batch(n):
        centers = rng.uniform(-0.5, 0.5, size=(n, 1, 1, 2))
        blob = np.exp(-((grid[None] - centers) ** 2).sum(-1) / 0.1)
        return np.repeat(blob[..., None], 3, axis=-1).astype(np.float32) * 2 - 1

    global_batch = args.batch * acc.num_data_shards
    for i in range(args.steps):
        batch = jax.device_put({"images": make_batch(global_batch)}, batch_sharding(acc.mesh))
        loss = step(batch)
        if i % 20 == 0:
            acc.print(f"step {i}: loss {float(loss):.4f}")

    imgs = np.asarray(sample(model, 4, num_steps=args.sample_steps, schedule=schedule))
    acc.print(f"sampled {imgs.shape}, range [{imgs.min():.2f}, {imgs.max():.2f}]")
    assert np.isfinite(imgs).all()
    # media parity (reference: tracking.py:373 log_images): samples land in
    # runs/diffusion_example/media/ as PNGs via the jsonl tracker — swap
    # log_with for "wandb"/"tensorboard" to stream them to a dashboard
    acc.log_images({"samples": [(img + 1) / 2 for img in imgs]}, step=args.steps)
    acc.end_training()
    acc.print("diffusion example OK")


if __name__ == "__main__":
    main()
