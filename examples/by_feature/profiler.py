"""Profiling a training step (reference analogue:
examples/by_feature/profiler.py — torch.profiler Chrome traces;
here `jax.profiler` TensorBoard/Perfetto traces via the same ctx API).
"""

import os
import tempfile

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import ProfileKwargs

from _common import make_task


def main():
    with tempfile.TemporaryDirectory() as trace_dir:
        profile_kwargs = ProfileKwargs(output_trace_dir=trace_dir)
        accelerator = Accelerator(kwargs_handlers=[profile_kwargs])
        model, optimizer, dataloader, loss_fn = make_task(accelerator)
        step = accelerator.build_train_step(loss_fn)

        batch = next(iter(dataloader))
        step(batch)  # compile outside the profiled region

        with accelerator.profile() as prof:
            for _ in range(10):
                step(batch)
        dumped = any(os.scandir(trace_dir))
        accelerator.print(f"trace written to {trace_dir}: {dumped}")


if __name__ == "__main__":
    main()
