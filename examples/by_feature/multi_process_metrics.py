"""Exact metrics across processes with gather_for_metrics
(reference analogue: examples/by_feature/multi_process_metrics.py — the
padded tail of the last uneven batch is dropped so every sample counts
exactly once).
"""

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

from _common import make_task


def main():
    accelerator = Accelerator()
    model, optimizer, dataloader, loss_fn = make_task(accelerator, length=250)  # 250 !% 16
    step = accelerator.build_train_step(loss_fn)
    for epoch in range(3):
        for batch in dataloader:
            step(batch)

    # eval: gather predictions from all ranks, dedup the padded tail
    eval_ds = RegressionDataset(length=250, seed=7)
    eval_dl = accelerator.prepare_data_loader(eval_ds, batch_size=16)
    preds, targets = [], []
    for batch in eval_dl:
        pred = model.apply_fn(model.params, batch["x"])
        pred, target = accelerator.gather_for_metrics((pred, batch["y"]))
        preds.append(np.asarray(pred))
        targets.append(np.asarray(target))
    preds, targets = np.concatenate(preds), np.concatenate(targets)
    assert preds.shape[0] == len(eval_ds), (preds.shape, len(eval_ds))
    mse = float(((preds - targets) ** 2).mean())
    accelerator.print(f"eval on exactly {preds.shape[0]} samples, MSE={mse:.4f}")


if __name__ == "__main__":
    main()
