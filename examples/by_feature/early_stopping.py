"""Cross-process early stopping with set_trigger/check_trigger
(reference analogue: examples/by_feature/early_stopping.py — a flag tensor
all-reduce so ANY rank can stop ALL ranks at the same step).
"""

from accelerate_tpu import Accelerator

from _common import make_task


def main():
    accelerator = Accelerator()
    model, optimizer, dataloader, loss_fn = make_task(accelerator)
    step = accelerator.build_train_step(loss_fn)

    target = 0.05
    stopped_at = None
    for epoch in range(20):
        for batch in dataloader:
            loss = float(step(batch))
            if loss < target:
                # any rank may trip the trigger...
                accelerator.set_trigger()
            # ...every rank sees it at the same point
            if accelerator.check_trigger():
                stopped_at = (epoch, loss)
                break
        if stopped_at:
            break
    accelerator.print(f"early-stopped at epoch {stopped_at[0]} with loss {stopped_at[1]:.4f}")
    assert stopped_at is not None


if __name__ == "__main__":
    main()
