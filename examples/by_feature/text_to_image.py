"""Text-to-image latent diffusion, end to end in-tree.

Reference analogue: examples/inference/distributed/stable_diffusion.py —
the reference drives a diffusers ``StableDiffusionPipeline`` (VAE +
CLIP text encoder + cross-attention UNet) under ``PartialState`` process
splits. Here all three models are in-tree (models/vae.py, models/clip.py,
models/unet.py) and the pipeline is ``diffusion.text_to_image``: encode
prompts, denoise latents with classifier-free guidance in one jitted
``lax.scan``, decode with the VAE. Prompt batches split over processes
with ``accelerator.split_between_processes`` exactly like the reference.

This is CI-sized: tiny models, random weights — it demonstrates the
wiring (one training step on the latent objective, then a guided sample),
not image quality.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.diffusion import latent_diffusion_loss, make_schedule, text_to_image
from accelerate_tpu.models.clip import CLIPConfig, create_clip_model
from accelerate_tpu.models.unet import UNetConfig, create_unet_model
from accelerate_tpu.models.vae import VAEConfig, create_vae_model


def main():
    accelerator = Accelerator()
    vae = create_vae_model(VAEConfig.tiny(), seed=0)
    clip = create_clip_model(CLIPConfig.tiny(), seed=0)
    unet = accelerator.prepare_model(
        create_unet_model(
            UNetConfig.tiny(
                sample_size=vae.config.latent_size,
                in_channels=vae.config.latent_channels,
                out_channels=vae.config.latent_channels,
                context_dim=clip.config.text_hidden_size,
            ),
            seed=0,
        )
    )
    sched = make_schedule(64)

    # one latent-diffusion training step: VAE and text encoder are frozen
    # conditioning machinery; only the UNet trains
    batch = {
        "pixel_values": jax.random.normal(jax.random.key(0), (4, 16, 16, 3)) * 0.5,
        "input_ids": jax.random.randint(jax.random.key(1), (4, 8), 3, 120),
    }
    opt = optax.adam(1e-3)
    opt_state = opt.init(unet.params)

    @jax.jit
    def train_step(params, opt_state, rng):
        loss, grads = jax.value_and_grad(
            lambda p: latent_diffusion_loss(
                p, batch, unet.apply_fn, sched, rng,
                vae=vae, text_encoder=clip.encode_text, text_params=clip.params,
            )
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params = unet.params
    for i in range(3):
        params, opt_state, loss = train_step(params, opt_state, jax.random.key(i))
    unet.params = params  # sample() reads model.params — publish the trained weights
    accelerator.print(f"latent-diffusion loss after 3 steps: {float(loss):.4f}")
    assert np.isfinite(float(loss))

    # distributed inference: each process renders its share of the prompts
    all_prompts = [jnp.full((8,), tok, jnp.int32) for tok in (3, 7, 11, 13)]
    with accelerator.split_between_processes(all_prompts) as prompts:
        imgs = text_to_image(
            unet, vae, clip, jnp.stack(prompts),
            guidance_scale=3.0, num_steps=4, schedule=sched, seed=accelerator.process_index,
        )
    accelerator.print(f"rendered {imgs.shape[0]} images of shape {imgs.shape[1:]} on this process")
    assert np.isfinite(np.asarray(imgs)).all()


if __name__ == "__main__":
    main()
