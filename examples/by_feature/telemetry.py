"""Runtime telemetry: step timeline, recompile watchdog, HBM sampling,
and the summarize CLI (docs/usage_guides/telemetry.md).

Trains the tiny regression task with telemetry armed, deliberately
perturbs the batch shape once so the recompile watchdog fires, then
summarizes the run's JSONL in-process (the same report
``accelerate-tpu telemetry summarize`` prints).
"""

import os
import tempfile

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.telemetry import render_text, summarize_file
from accelerate_tpu.utils import TelemetryKwargs

from _common import make_task


def main():
    with tempfile.TemporaryDirectory() as run_dir:
        accelerator = Accelerator(
            project_dir=run_dir,
            kwargs_handlers=[TelemetryKwargs(hbm_sample_every=5, forward_to_trackers_every=0)],
        )
        model, optimizer, dataloader, loss_fn = make_task(accelerator)
        step = accelerator.telemetry.wrap(accelerator.build_train_step(loss_fn))

        for _ in range(4):
            for batch in dataloader:
                step(batch)

        # a drifting batch shape is the classic silent-recompile bug the
        # watchdog exists for — provoke it once, on purpose
        bad_batch = {k: np.asarray(v)[:-1] for k, v in batch.items()}
        step(bad_batch)

        accelerator.telemetry.close()
        path = os.path.join(run_dir, "telemetry.jsonl")
        report = summarize_file(path)
        accelerator.print(render_text(report))

        assert report["steps"]["recompiles"] == 1, report["steps"]
        assert report["steps"]["p95_step_ms"] is not None
        accelerator.print(
            f"watchdog caught the shape drift: {report['steps']['recompile_details'][0]['changed']}"
        )


if __name__ == "__main__":
    main()
