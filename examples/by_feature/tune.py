"""Autotune a step function statically: ``accelerate-tpu tune`` searches
the configuration knob surface with the analyzers as the oracle.

Two surfaces on the same workloads:

* ``Accelerator.tune(train_workload)`` — programmatic, against the
  accelerator's device pool;
* ``accelerate-tpu tune examples/by_feature/tune.py::train_workload
  --mesh data=8`` — the CLI resolves the *workload factory* here (the
  ``tune_factory`` attribute marks it) and calls it once per candidate
  :class:`~accelerate_tpu.analysis.ConfigPoint`, so the traced program
  really changes with the knobs: the gradient sync switches between an
  exact f32 ``pmean`` and a compressed wire
  (``parallel.compression.compressed_psum_mean``), and the batch pads
  to the candidate's bucket.

``serving_workload`` is the serving-side twin: a decode-tick-shaped
program whose prefill chunk pads to the candidate's covering bucket and
whose decode block scales with ``slots x tick_block`` — the shape the
token-budget and bucket knobs actually control in ``ServingEngine``.

Every candidate is scored in milliseconds (flight-check HBM prune +
perfmodel roofline + costmodel wire bytes); nothing compiles unless you
pass ``--confirm``, which measures the top-k with short StepTelemetry
runs and reports predicted-vs-measured rank agreement.
"""

import jax
import jax.numpy as jnp

HIDDEN = 256
FEATURES = 128
BATCH = 24


def _covering(buckets, size):
    asc = sorted(int(b) for b in buckets)
    return next((b for b in asc if b >= size), asc[-1])


def train_workload(point):
    """Factory: one SGD step whose batch bucket and gradient-sync wire
    follow the candidate point (mesh x compression x bucket)."""
    batch = _covering(point.buckets, BATCH) if point.buckets else BATCH
    method = point.compression

    def train_step(params, batch_xy):
        def loss_fn(p):
            h = jnp.tanh(batch_xy["x"] @ p["w1"] + p["b1"])
            pred = h @ p["w2"] + p["b2"]
            return jnp.mean((pred - batch_xy["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if method:
            from accelerate_tpu.parallel.compression import compressed_psum_mean

            grads = compressed_psum_mean(grads, "data", method)
        else:
            grads = jax.lax.pmean(grads, "data")
        new_params = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g, params, grads)
        return new_params, loss

    f32 = jnp.float32
    params = {
        "w1": jax.ShapeDtypeStruct((FEATURES, HIDDEN), f32),
        "b1": jax.ShapeDtypeStruct((HIDDEN,), f32),
        "w2": jax.ShapeDtypeStruct((HIDDEN, HIDDEN), f32),
        "b2": jax.ShapeDtypeStruct((HIDDEN,), f32),
    }
    sample_batch = {
        "x": jax.ShapeDtypeStruct((batch, FEATURES), f32),
        "y": jax.ShapeDtypeStruct((batch, HIDDEN), f32),
    }
    return train_step, (params, sample_batch)


train_workload.tune_factory = True


def serving_workload(point):
    """Factory: one engine-tick-shaped program — a prefill chunk padded
    to the candidate's covering bucket plus a ``slots x tick_block``
    decode block (buckets x token_budget x tick x slots)."""
    buckets = point.buckets or (32, 128)
    budget = point.token_budget or 64
    tick = point.tick_block or 8
    slots = point.num_slots or 4
    prefill_tokens = _covering(buckets, min(budget, max(buckets)))
    decode_tokens = slots * tick

    def tick_step(w, prompt_h, decode_h):
        pre = jnp.tanh(prompt_h @ w)
        dec = jnp.tanh(decode_h @ w)
        return pre.sum() + dec.sum()

    f32 = jnp.float32
    args = (
        jax.ShapeDtypeStruct((HIDDEN, HIDDEN), f32),
        jax.ShapeDtypeStruct((prefill_tokens, HIDDEN), f32),
        jax.ShapeDtypeStruct((decode_tokens, HIDDEN), f32),
    )
    return tick_step, args


serving_workload.tune_factory = True


def main():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.analysis import SearchSpace
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(8)  # fake 8-device CPU mesh, same as the test suite
    accelerator = Accelerator()
    # train side: layouts x wire schemes over this pool
    report = accelerator.tune(train_workload, generation="cpu")
    accelerator.print(report.render_text())

    # serving side: bucket sets x token budgets against a declared
    # prompt-length histogram (TPU703 prices the padding waste)
    space = SearchSpace(
        bucket_sets=("32,128", "64,256"),
        token_budgets=(32, 64),
        max_devices=1,
    )
    serving = accelerator.tune(
        serving_workload,
        space=space,
        generation="cpu",
        # the declared prompt-length histogram: 28-token chat turns with a
        # tail of 120-token documents. The (32,128) bucket set covers it
        # within the waste threshold; the (64,256) candidates earn a
        # TPU703 finding — padding waste is part of the ranking story
        shape_histogram={28: 100, 120: 10},
    )
    accelerator.print(serving.render_text())


if __name__ == "__main__":
    main()
