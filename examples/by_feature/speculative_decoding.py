"""Speculative decoding: a draft model accelerates the target, token-exactly.

Run: python examples/by_feature/speculative_decoding.py
"""

from __future__ import annotations

import numpy as np


def main():
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.speculative import speculative_generate

    target = create_llama_model(LlamaConfig.tiny(), seed=0, seq_len=64)
    draft = create_llama_model(LlamaConfig.tiny(), seed=7, seq_len=64)

    ids = (np.arange(12) % 250).astype(np.int32)[None]
    want = np.asarray(generate(target, ids, max_new_tokens=24))
    got, stats = speculative_generate(
        target, draft, ids, max_new_tokens=24, gamma=4, return_stats=True
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    print(
        f"token-exact; {stats['emitted']} tokens in {stats['target_forwards']} target "
        f"forwards ({stats['tokens_per_target_forward']:.2f} tok/forward, "
        f"accept rate {stats['accept_rate']:.2f})"
    )

    # perfect draft = the upper bound: gamma+1 tokens per target forward
    _, best = speculative_generate(
        target, target, ids, max_new_tokens=24, gamma=4, return_stats=True
    )
    print(f"perfect-draft bound: {best['tokens_per_target_forward']:.2f} tok/forward")

    # speculative CONTINUOUS BATCHING: the same draft/verify core drives
    # the serving engine's slot pool (accepted+1 tokens per target pass,
    # per slot) — streams stay exactly the target's greedy output
    from accelerate_tpu.serving import ServingEngine

    eng = ServingEngine(
        # tick_block ~= max_new/(gamma+1): each tick iteration emits up to
        # gamma+1 tokens per slot (serving.md sizing note)
        target, num_slots=2, prompt_buckets=(8, 16), draft_model=target, gamma=4, tick_block=3
    )
    prompts = [ids[0, :8], ids[0, :5]]
    for p, got in zip(prompts, eng.generate_many(prompts, max_new_tokens=12)):
        np.testing.assert_array_equal(got, np.asarray(generate(target, p[None], max_new_tokens=12))[0])
    s = eng.spec_stats
    print(
        f"speculative serving: {s['emitted']} tokens in {s['steps']} slot-forwards "
        f"({s['emitted'] / max(1, s['steps']):.2f} tokens per slot-forward, bound {4 + 1})"
    )
    print("speculative decoding example OK")


if __name__ == "__main__":
    main()
