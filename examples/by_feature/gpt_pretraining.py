"""GPT pretraining on a hybrid device mesh
(reference analogue: examples/by_feature/megatron_lm_gpt_pretraining.py —
tp/pp/dp GPT-2 pretraining through the MegatronLM plugin).

The Megatron stack collapses to a mesh layout here: ``data x fsdp x
tensor`` via ``MeshConfig``, with the zoo's GPT-2 providing the Megatron
column/row sharding rules. Everything else — causal-LM loss, cosine
schedule with warmup, gradient clipping, perplexity eval — matches the
reference example's recipe (its args: lr 5e-4 warmup + clip 1.0).
"""

import numpy as np

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.models import GPT2Config, create_gpt2_model
from accelerate_tpu.models.llama import next_token_cross_entropy
from accelerate_tpu.utils import set_seed

SEQ = 32
VOCAB_REAL = 96


def synthetic_corpus(n_docs, rng):
    """Zipf-ish token stream chunked into SEQ blocks (the reference
    group_texts step, megatron_lm_gpt_pretraining.py:400-430)."""
    stream = rng.zipf(1.5, size=n_docs * SEQ * 2) % VOCAB_REAL
    n_blocks = len(stream) // SEQ
    return stream[: n_blocks * SEQ].reshape(n_blocks, SEQ).astype(np.int32)


def main():
    import jax
    import optax

    set_seed(0)
    n_dev = len(jax.devices())
    mesh = MeshConfig(data=-1, tensor=2) if n_dev % 2 == 0 and n_dev > 1 else MeshConfig()
    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=mesh),
    )

    cfg = GPT2Config.tiny(vocab_size=128)
    model = accelerator.prepare_model(create_gpt2_model(cfg, seq_len=SEQ))
    schedule = optax.warmup_cosine_decay_schedule(0.0, 5e-4, warmup_steps=8, decay_steps=96)
    accelerator.prepare_optimizer(optax.adamw(schedule, weight_decay=0.01))
    accelerator.clip_grad_norm_(model.params, 1.0)

    blocks = synthetic_corpus(64, np.random.default_rng(1))
    train, val = blocks[:-8], blocks[-8:]
    loader = accelerator.prepare_data_loader(
        [{"input_ids": b} for b in train], batch_size=max(1, 16 // accelerator.num_data_shards),
        shuffle=True, seed=3,
    )

    step = accelerator.build_train_step(
        lambda p, b: next_token_cross_entropy(model.apply_fn(p, b["input_ids"]), b)
    )
    eval_step = accelerator.build_eval_step(lambda p, ids: model.apply_fn(p, ids))

    def perplexity():
        logits = eval_step(val)
        loss = next_token_cross_entropy(np.asarray(logits, np.float32), {"input_ids": val})
        return float(np.exp(np.asarray(loss)))

    ppl0 = perplexity()
    for epoch in range(6):
        loader.set_epoch(epoch)
        for batch in loader:
            loss = step(batch)
    ppl1 = perplexity()
    accelerator.print(
        f"mesh={dict(accelerator.mesh.shape)} loss={float(loss):.3f} ppl {ppl0:.1f} -> {ppl1:.1f}"
    )
    assert ppl1 < ppl0, (ppl0, ppl1)


if __name__ == "__main__":
    main()
