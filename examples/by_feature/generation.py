"""Autoregressive generation with the jitted KV-cache decode loop
(no reference analogue — the reference delegates generation to
transformers; here it is framework surface: accelerate_tpu/generation.py).

Trains tiny-llama a few steps, then generates greedy and sampled
continuations and reports per-token decode latency."""

import numpy as np
import optax

from accelerate_tpu import Accelerator, generate, per_token_latency
from accelerate_tpu.models import LlamaConfig, causal_lm_loss, create_llama_model
from accelerate_tpu.parallel.mesh import batch_sharding


def main():
    import jax

    accelerator = Accelerator(mixed_precision="bf16")
    model = accelerator.prepare_model(create_llama_model(LlamaConfig.tiny(), seq_len=32))
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    step = accelerator.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))

    rng = np.random.default_rng(0)
    batch = jax.device_put(
        {"input_ids": rng.integers(5, 250, size=(16, 32)).astype(np.int32)},
        batch_sharding(accelerator.mesh),
    )
    for i in range(5):
        loss = step(batch)
    accelerator.print(f"trained 5 steps, loss={float(loss):.3f}")

    prompt = np.asarray([[5, 6, 7, 8]], np.int32)
    greedy = generate(model, prompt, max_new_tokens=8)
    sampled = generate(model, prompt, max_new_tokens=8, temperature=0.8, top_k=40, seed=7)
    accelerator.print(f"greedy : {np.asarray(greedy)[0].tolist()}")
    accelerator.print(f"sampled: {np.asarray(sampled)[0].tolist()}")

    dt = per_token_latency(model, batch_size=1, prompt_len=16, n_tokens=8)
    accelerator.print(f"per-token decode latency: {dt * 1e3:.2f} ms")

    # encoder-decoder generation: encode once, cached decoder steps
    from accelerate_tpu import generate_seq2seq
    from accelerate_tpu.models import T5Config, create_t5_model

    t5 = create_t5_model(T5Config.tiny(max_decode_len=32), seed=0, seq_len=16)
    src = rng.integers(5, 250, size=(1, 16)).astype(np.int32)
    summary = generate_seq2seq(t5, src, max_new_tokens=8)
    accelerator.print(f"seq2seq: {np.asarray(summary)[0].tolist()}")


if __name__ == "__main__":
    main()
