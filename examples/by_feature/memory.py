"""Automatic batch-size finding on OOM (reference analogue:
examples/by_feature/memory.py — `find_executable_batch_size` halves the
batch size and retries until training fits).
"""

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import find_executable_batch_size

from _common import final_weights, make_task


def main():
    accelerator = Accelerator()

    @find_executable_batch_size(starting_batch_size=4096)
    def train(batch_size):
        accelerator.free_memory()
        if batch_size > 64:
            # stand-in for a real HBM OOM so the example runs anywhere
            raise RuntimeError(f"RESOURCE_EXHAUSTED: pretend OOM at batch {batch_size}")
        model, optimizer, dataloader, loss_fn = make_task(accelerator, batch_size=batch_size)
        step = accelerator.build_train_step(loss_fn)
        for epoch in range(3):
            for batch in dataloader:
                step(batch)
        return batch_size, final_weights(model)

    batch_size, (a, b) = train()
    accelerator.print(f"trained at batch_size={batch_size}: a={a:.3f} b={b:.3f}")
    assert batch_size == 64


if __name__ == "__main__":
    main()
