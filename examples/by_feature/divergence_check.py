"""Multi-host divergence analysis before the job ever reaches a pod:
prove every rank runs the same collective program, statically.

The classic failure this catches is the main-process-guarded collective —

    if accelerator.is_main_process:
        metrics = accelerator.gather(metrics)   # non-main ranks never arrive

— which hangs every host forever with no error. ``analysis.divergence``
symbolically executes the script for k synthetic ranks, tracks which
values can differ across hosts (``process_index``, per-host filesystem
and RNG reads), diffs the per-rank collective traces, and reports the
TPU4xx findings.

Three surfaces on the same analysis:

* ``accelerate-tpu divergence train.py`` (or ``train.py::main``) — CLI;
* ``analysis.analyze_source``/``analyze_file``/``analyze_paths`` —
  programmatic, shown below;
* ``Accelerator.lint(step_fn, *sample_args)`` — runs it over the calling
  module automatically, alongside the jaxpr tier.

This example analyzes a seeded-deadlock script and its fixed version and
prints both reports — entirely statically (the bad script is never
executed; nothing here needs a TPU or even jax).
"""

import textwrap

from accelerate_tpu.analysis import analyze_source, render_text

DEADLOCKED = textwrap.dedent(
    '''
    """Evaluation loop with a seeded multi-host deadlock."""
    import os


    def evaluate(accelerator, batches):
        total = 0.0
        for batch in batches:
            total += batch
        if accelerator.is_main_process:
            total = accelerator.gather(total)      # TPU401: gather is collective
        for shard in os.listdir("results"):        # per-host trip count...
            accelerator.reduce(shard)              # TPU402: ...around a collective
        with open("summary.txt", "w") as fh:       # TPU405: every host writes it
            fh.write(str(total))
        accelerator.wait_for_everyone()
    '''
)

FIXED = textwrap.dedent(
    '''
    """The same loop, rank-uniform."""


    def evaluate(accelerator, batches, shards):
        total = 0.0
        for batch in batches:
            total += batch
        total = accelerator.gather(total)           # every rank, together
        for shard in shards:                        # uniform trip count
            accelerator.reduce(shard)
        if accelerator.is_main_process:             # guard the WRITE, not the sync
            with open("summary.txt", "w") as fh:
                fh.write(str(total))
        accelerator.wait_for_everyone()
    '''
)


def main():
    findings = analyze_source(DEADLOCKED, path="deadlocked.py")
    print("seeded-deadlock script:")
    print(textwrap.indent(render_text(findings), "  "))
    assert {f.rule for f in findings} >= {"TPU401", "TPU402", "TPU405"}

    fixed = analyze_source(FIXED, path="fixed.py")
    print("\nfixed script:")
    print(textwrap.indent(render_text(fixed), "  "))
    assert fixed == []
    print("\ndivergence_check: ALL OK")


if __name__ == "__main__":
    main()
