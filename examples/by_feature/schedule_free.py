"""Schedule-free optimization (reference analogue:
examples/by_feature/schedule_free.py — Meta's schedule-free AdamW needs
train/eval mode switching; the optax.contrib port exposes the same idea
as a pure transform plus an eval-param extraction).
"""

import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel


def main():
    accelerator = Accelerator()
    model = accelerator.prepare_model(RegressionModel())
    # schedule-free wraps a base optimizer; no LR schedule is needed —
    # that's the point (reference wraps torch AdamWScheduleFree)
    tx = optax.contrib.schedule_free_sgd(1.0, warmup_steps=8)
    optimizer = accelerator.prepare_optimizer(tx)
    loader = accelerator.prepare_data_loader(
        RegressionDataset(length=256, seed=0), batch_size=16, shuffle=True, seed=42
    )

    def loss_fn(params, batch):
        pred = model.apply_fn(params, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    step = accelerator.build_train_step(loss_fn)
    for epoch in range(3):
        loader.set_epoch(epoch)
        for batch in loader:
            loss = step(batch)

    # the torch API's optimizer.eval() mode-switch becomes a pure function:
    # evaluation params are extracted from the optimizer state
    eval_params = optax.contrib.schedule_free_eval_params(optimizer.opt_state, model.params)
    a = float(np.asarray(eval_params["a"]))
    b = float(np.asarray(eval_params["b"]))
    accelerator.print(f"schedule-free trained: a={a:.3f} (true 2.0) b={b:.3f} (true 3.0) loss={float(loss):.5f}")
    assert abs(a - 2.0) < 0.3 and abs(b - 3.0) < 0.3, "schedule-free training did not converge"


if __name__ == "__main__":
    main()
