"""Shared bits for the by_feature examples: a tiny regression task that
trains in seconds on CPU or one TPU chip.

(The reference's by_feature scripts each re-derive from nlp_example.py and
share `get_dataloaders`; here the shared piece is explicit —
reference: examples/by_feature/README.md.)
"""

from __future__ import annotations

import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel


def make_task(accelerator: Accelerator, batch_size: int = 16, length: int = 256, lr: float = 0.1):
    """model, optimizer, dataloader, loss_fn for y = 2x + 3 regression."""
    model = accelerator.prepare_model(RegressionModel())
    optimizer = accelerator.prepare_optimizer(optax.sgd(lr))
    dataloader = accelerator.prepare_data_loader(
        RegressionDataset(length=length, seed=0), batch_size=batch_size, shuffle=True, seed=42
    )

    def loss_fn(params, batch):
        pred = model.apply_fn(params, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    return model, optimizer, dataloader, loss_fn


def final_weights(model) -> tuple[float, float]:
    import jax

    leaves = jax.tree.leaves(model.params)
    return float(np.asarray(leaves[0]).ravel()[0]), float(np.asarray(leaves[1]).ravel()[0])
