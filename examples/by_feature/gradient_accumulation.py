"""Gradient accumulation with the imperative API
(reference analogue: examples/by_feature/gradient_accumulation.py).

`accumulate()` buffers gradients for N microbatches and applies them on the
boundary; on TPU the fast path (`build_train_step`) does the same thing as
a `lax.scan` over microbatches inside one jitted step — shown at the end.
"""

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import GradientAccumulationPlugin

from _common import final_weights, make_task


def main():
    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=4)
    )
    model, optimizer, dataloader, loss_fn = make_task(accelerator, batch_size=8)

    for epoch in range(12):
        for batch in dataloader:
            with accelerator.accumulate(model):
                accelerator.backward(loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()

    a, b = final_weights(model)
    accelerator.print(f"imperative path: a={a:.3f} (want 2), b={b:.3f} (want 3)")
    assert abs(a - 2) < 0.3 and abs(b - 3) < 0.3

    # fast path: the same accumulation fused into one jitted step
    accelerator.free_memory()
    accelerator2 = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=4)
    )
    model, optimizer, dataloader, loss_fn = make_task(accelerator2, batch_size=8)
    step = accelerator2.build_train_step(loss_fn)
    for epoch in range(12):
        for batch in dataloader:
            step(batch)
    a, b = final_weights(model)
    accelerator2.print(f"fused path:      a={a:.3f} (want 2), b={b:.3f} (want 3)")


if __name__ == "__main__":
    main()
