"""FSDP training with peak-memory tracking
(reference analogue: examples/by_feature/fsdp_with_peak_mem_tracking.py —
a TrackMemory context records CPU/GPU peak around prepare and each epoch).

On TPU the interesting number is peak HBM (``device.memory_stats()``); on
backends that don't report it (the CPU fake mesh) the tracker falls back
to process RSS, same as the reference's psutil path.
"""

import contextlib
import resource

import numpy as np

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.utils.memory import get_device_memory_stats

from _common import final_weights, make_task


class TrackMemory(contextlib.AbstractContextManager):
    """Records begin/end/peak memory around a block (the reference's
    TorchTracemalloc, fsdp_with_peak_mem_tracking.py:80-120)."""

    def __enter__(self):
        self.begin = self._used()
        return self

    def _used(self):
        stats = get_device_memory_stats()
        hbm = stats.get("bytes_in_use") if stats else None
        if hbm:
            return hbm
        # CPU fallback: ru_maxrss is KiB on Linux
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    def _peak(self):
        stats = get_device_memory_stats()
        peak = stats.get("peak_bytes_in_use") if stats else None
        if peak:
            return peak
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    def __exit__(self, *exc):
        self.end = self._used()
        self.peak = self._peak()
        self.used_mb = (self.end - self.begin) / 2**20
        self.peaked_mb = max(0.0, (self.peak - self.begin) / 2**20)
        return False


def main():
    import jax

    fsdp = 2 if len(jax.devices()) % 2 == 0 else 1  # single-chip runs stay dp
    accelerator = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=-1, fsdp=fsdp))
    )

    with TrackMemory() as prep_mem:
        model, optimizer, dataloader, loss_fn = make_task(accelerator, batch_size=16)
        step = accelerator.build_train_step(loss_fn)
    accelerator.print(f"prepare: +{prep_mem.used_mb:.1f} MB (peak +{prep_mem.peaked_mb:.1f} MB)")

    for epoch in range(12):
        with TrackMemory() as epoch_mem:
            dataloader.set_epoch(epoch)
            for batch in dataloader:
                loss = step(batch)
        accelerator.print(
            f"epoch {epoch}: loss={float(loss):.4f} "
            f"mem +{epoch_mem.used_mb:.1f} MB (peak +{epoch_mem.peaked_mb:.1f} MB)"
        )

    a, b = final_weights(model)
    assert abs(a - 2.0) < 0.1 and abs(b - 3.0) < 0.1, (a, b)
    assert epoch_mem.peak >= 0


if __name__ == "__main__":
    main()
