"""Automatic gradient accumulation (reference analogue:
examples/by_feature/automatic_gradient_accumulation.py — combine
`find_executable_batch_size` with gradient accumulation so the OBSERVED
batch size stays constant when OOM forces the per-step batch down).
"""

from accelerate_tpu import Accelerator
from accelerate_tpu.utils import find_executable_batch_size

from _common import final_weights, make_task

OBSERVED_BATCH_SIZE = 64


def main():
    accelerator = Accelerator()

    @find_executable_batch_size(starting_batch_size=OBSERVED_BATCH_SIZE)
    def train(batch_size):
        accelerator.free_memory()
        # keep the effective batch constant: what doesn't fit in one step
        # is accumulated over OBSERVED/batch_size micro-steps
        accelerator.gradient_accumulation_steps = OBSERVED_BATCH_SIZE // batch_size
        if batch_size > 16:
            raise RuntimeError(f"RESOURCE_EXHAUSTED: pretend OOM at batch {batch_size}")
        model, optimizer, dataloader, loss_fn = make_task(accelerator, batch_size=batch_size, lr=0.4)
        step = accelerator.build_train_step(loss_fn)
        for epoch in range(24):
            dataloader.set_epoch(epoch)
            for batch in dataloader:
                step(batch)
        return batch_size, final_weights(model)

    batch_size, (a, b) = train()
    accum = accelerator.gradient_accumulation_steps
    accelerator.print(
        f"fits at batch_size={batch_size} x accum={accum} (observed {batch_size * accum}): a={a:.3f} b={b:.3f}"
    )
    assert batch_size == 16 and accum == 4
    assert abs(a - 2.0) < 0.4 and abs(b - 3.0) < 0.4


if __name__ == "__main__":
    main()
