"""K-fold cross validation under one Accelerator (reference analogue:
examples/by_feature/cross_validation.py — train on k-1 folds, evaluate on
the held-out fold, average metrics across folds with gather).
"""

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.test_utils import RegressionDataset

from _common import make_task


class FoldView:
    """A dataset view selecting a subset of indices (the reference uses
    datasets.select; here plain index math keeps it dependency-free)."""

    def __init__(self, base, indices):
        self.base, self.indices = base, list(indices)

    def __len__(self):
        return len(self.indices)

    def __getitem__(self, i):
        return self.base[self.indices[i]]


def main(k: int = 4):
    accelerator = Accelerator()
    base = RegressionDataset(length=128, seed=0)
    folds = np.array_split(np.arange(len(base)), k)

    fold_losses = []
    for held_out in range(k):
        train_idx = np.concatenate([f for i, f in enumerate(folds) if i != held_out])
        model, optimizer, _, loss_fn = make_task(accelerator, batch_size=4)
        train_loader = accelerator.prepare_data_loader(
            FoldView(base, train_idx), batch_size=4, shuffle=True, seed=42
        )
        step = accelerator.build_train_step(loss_fn)
        for epoch in range(8):
            train_loader.set_epoch(epoch)
            for batch in train_loader:
                step(batch)

        # held-out evaluation with padded-tail-exact gather
        eval_loader = accelerator.prepare_data_loader(FoldView(base, folds[held_out]), batch_size=8)
        sq_errors = []
        for batch in eval_loader:
            pred = model.apply_fn(model.params, batch["x"])
            err = accelerator.gather_for_metrics((pred - batch["y"]) ** 2)
            sq_errors.append(np.asarray(err))
        fold_loss = float(np.concatenate(sq_errors).mean())
        fold_losses.append(fold_loss)
        accelerator.free_memory()
        accelerator.print(f"fold {held_out}: held-out MSE {fold_loss:.4f}")

    mean = float(np.mean(fold_losses))
    accelerator.print(f"{k}-fold CV MSE: {mean:.4f} (+/- {float(np.std(fold_losses)):.4f})")
    assert mean < 0.5, f"cross-validated model did not learn (MSE {mean})"


if __name__ == "__main__":
    main()
