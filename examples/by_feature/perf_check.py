"""Static roofline before the first compile: price every matmul and
collective, predict the step time and MFU ceiling, and catch TPU5xx
inefficiencies while they are still one-line fixes.

Two surfaces on the same step function:

* ``Accelerator.perf_check(step_fn, *sample_args)`` — programmatic,
  against the accelerator's live mesh;
* ``accelerate-tpu perf-check examples/by_feature/perf_check.py::train_step``
  — the CLI reads the sample shapes from ``train_step_sample_args()``
  below (or pass ``--arg f32[128,256]``), and ``--baseline prev.json``
  turns it into a per-op regression diff.

The step below runs its matmuls in f32 on data that was upcast from
bf16 — exactly the TPU505 pattern — so the report both prices the step
AND names the one-line fix (bf16 inputs with
``preferred_element_type=jnp.float32``: same accumulation, ~2x the MXU
rate). The fixed twin is checked too, showing the predicted saving.
"""

import jax
import jax.numpy as jnp

HIDDEN = 1024
FEATURES = 256
BATCH = 128


def train_step(params, batch):
    """Forward + MSE + SGD with an f32 matmul over upcast bf16 activations
    (the seeded TPU505 finding) and a cross-replica gradient mean."""

    def loss_fn(p):
        x = batch["x"].astype(jnp.float32)  # bf16 -> f32 upcast: TPU505
        h = jnp.tanh(x @ p["w1"])
        pred = h @ p["w2"]
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = jax.lax.pmean(grads, "data")
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.01 * g, params, grads)
    return new_params, loss


def fixed_step(params, batch):
    """The TPU505 fix: STORE the first-layer weights bf16 and feed the
    matmul bf16 operands with ``preferred_element_type=f32`` — identical
    accumulation, no per-step casts, half the operand HBM."""

    def loss_fn(p):
        h = jnp.tanh(jax.lax.dot(batch["x"], p["w1"], preferred_element_type=jnp.float32))
        pred = h @ p["w2"]
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = jax.lax.pmean(grads, "data")
    new_params = jax.tree_util.tree_map(lambda p, g: (p - 0.01 * g).astype(p.dtype), params, grads)
    return new_params, loss


def train_step_sample_args():
    """Abstract sample shapes for the CLI (nothing is allocated)."""
    params = {
        "w1": jax.ShapeDtypeStruct((FEATURES, HIDDEN), jnp.float32),
        "w2": jax.ShapeDtypeStruct((HIDDEN, FEATURES), jnp.float32),
    }
    batch = {
        "x": jax.ShapeDtypeStruct((BATCH, FEATURES), jnp.bfloat16),
        "y": jax.ShapeDtypeStruct((BATCH, FEATURES), jnp.float32),
    }
    return params, batch


def fixed_step_sample_args():
    params = {
        "w1": jax.ShapeDtypeStruct((FEATURES, HIDDEN), jnp.bfloat16),
        "w2": jax.ShapeDtypeStruct((HIDDEN, FEATURES), jnp.float32),
    }
    batch = {
        "x": jax.ShapeDtypeStruct((BATCH, FEATURES), jnp.bfloat16),
        "y": jax.ShapeDtypeStruct((BATCH, FEATURES), jnp.float32),
    }
    return params, batch


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    report = accelerator.perf_check(train_step, *train_step_sample_args(), generation="v5e")
    accelerator.print(report.render_text())
    fixed = accelerator.perf_check(fixed_step, *fixed_step_sample_args(), generation="v5e")
    accelerator.print(
        f"\nTPU505 fix (bf16 matmul, f32 accumulate): predicted step "
        f"{report.predicted_step_ms:.3f} -> {fixed.predicted_step_ms:.3f} ms, "
        f"MFU ceiling {report.mfu_upper_bound:.1%} -> {fixed.mfu_upper_bound:.1%}"
    )
    assert any(f.rule == "TPU505" for f in report.findings), "seeded TPU505 must fire"
    assert not any(f.rule == "TPU505" for f in fixed.findings), "fixed twin must be clean"


if __name__ == "__main__":
    main()
