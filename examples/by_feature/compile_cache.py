"""Kill repeat compiles: persistent executable cache + AOT warm start
(docs/usage_guides/compilation.md; no reference analogue — the reference
delegates compilation to torch).

Phase 1 trains cold with a ``CompileKwargs`` handler: every step program
compiles once, then lands in the executable store as a serialized XLA
executable. Phase 2 simulates a restarted process (a new Accelerator
over the same cache dir — a preemption-resumed trainer or a new serving
replica): the SAME programs deserialize from the store with **zero** XLA
compiles, the loss trajectory is bit-exact, and the recompile watchdog
stays silent. Phase 3 shows auto-bucketing: ragged prompt lengths
through a ServingEngine compile one program per learned bucket, not one
per length.
"""

import tempfile
import time

import numpy as np

from accelerate_tpu import Accelerator, CompileKwargs
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

from _common import make_task


def train(cache_dir: str, epochs: int = 3) -> tuple[list, object]:
    accelerator = Accelerator(kwargs_handlers=[CompileKwargs(cache_dir=cache_dir)])
    model, optimizer, dataloader, loss_fn = make_task(accelerator)
    step = accelerator.build_train_step(loss_fn)
    losses = []
    for epoch in range(epochs):
        dataloader.set_epoch(epoch)
        for batch in dataloader:
            losses.append(float(step(batch)))
    return losses, accelerator.program_cache


def main():
    with tempfile.TemporaryDirectory() as cache_dir:
        t0 = time.perf_counter()
        cold_losses, cold_pc = train(cache_dir)
        cold_s = time.perf_counter() - t0
        print(f"cold run : {cold_s:5.2f}s  {cold_pc.misses} XLA compile(s), "
              f"{len(cold_pc.store.keys())} executable(s) stored")

        # "restart": fresh singletons + fresh Accelerator over the same dir
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        t0 = time.perf_counter()
        warm_losses, warm_pc = train(cache_dir)
        warm_s = time.perf_counter() - t0
        print(f"warm run : {warm_s:5.2f}s  {warm_pc.misses} XLA compile(s), "
              f"{warm_pc.deserialized} deserialized")
        assert warm_pc.misses == 0, "warm start must not compile"
        assert warm_losses == cold_losses, "warm trajectory must be bit-exact"
        print(f"speedup  : {cold_s / warm_s:.2f}x, trajectory bit-exact")

        # auto-bucketing: ragged prompt lengths -> one compile per learned
        # bucket (still inside the cache-dir scope: jax's persistent cache
        # was pointed here for the rest of the process)
        from accelerate_tpu.models import LlamaConfig, create_llama_model
        from accelerate_tpu.serving import ServingEngine

        model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
        engine = ServingEngine(model, num_slots=2, prompt_buckets=(4,), auto_bucketing=True)
        prompts = [np.arange(1, 1 + n, dtype=np.int32) for n in (3, 5, 7, 9, 2, 6)]
        engine.generate_many(prompts, max_new_tokens=3)
        print(f"serving  : {len(prompts)} ragged prompts -> buckets {engine.bucketer.buckets}, "
              f"{len(engine._prefill)} prefill compile(s)")
        assert len(engine._prefill) <= len(engine.bucketer.buckets)
    print("compile_cache example: ALL OK")


if __name__ == "__main__":
    main()
