"""Big-model inference end-to-end (reference analogue:
benchmarks/big_model_inference + big_modeling.py:512
``load_checkpoint_and_dispatch``):

1. export a sharded safetensors checkpoint with ``save_model``;
2. reload it with ``load_checkpoint_and_dispatch`` under an artificially
   tiny HBM budget, so layers spill to the host-RAM and disk tiers;
3. run the forward with ``StreamedExecutor`` — per-layer weight streaming
   with double-buffered async transfers (the AlignDevicesHook replacement);
4. assert the streamed logits match the fully in-memory model.

Also exercises ``device_map="balanced"`` (``get_balanced_memory``).
"""

import tempfile

import jax
import numpy as np

from accelerate_tpu.big_modeling import StreamedExecutor, load_checkpoint_and_dispatch
from accelerate_tpu.checkpointing import save_model
from accelerate_tpu.models import LlamaConfig, create_llama_model


def unflatten(flat: dict) -> dict:
    out: dict = {}
    for path, value in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = np.asarray(value)
    return out


def main():
    cfg = LlamaConfig.tiny()
    cfg.scan_layers = False  # per-layer params: layer_0 .. layer_N
    seq_len = 16
    model = create_llama_model(cfg, seq_len=seq_len)
    ids = (np.arange(2 * seq_len).reshape(2, seq_len) % cfg.vocab_size).astype(np.int32)
    reference_logits = np.asarray(model(ids))

    with tempfile.TemporaryDirectory() as tmp:
        # 1. sharded export (small shard size forces an indexed shard set)
        ckpt_dir = f"{tmp}/ckpt"
        save_model(model, ckpt_dir, max_shard_size="100KB")

        # 2. reload into a fresh skeleton under a tiny device budget:
        # ~first layer on device 0, the rest spills to host RAM, tail to disk
        skeleton = create_llama_model(cfg, seq_len=seq_len, seed=1)
        sizes = {
            k: sum(np.prod(x.shape) * 4 for x in jax.tree.leaves(v))
            for k, v in skeleton.params.items()
        }
        budget = int(sizes["embed_tokens"] + sizes["layer_0"] * 1.5)
        dispatched = load_checkpoint_and_dispatch(
            skeleton,
            ckpt_dir,
            device_map="auto",
            max_memory={0: budget, "cpu": int(sizes["layer_1"])},
            offload_dir=f"{tmp}/offload",
        )
        placements = set(dispatched.device_map.values())
        print("placement tiers used:", sorted(map(str, placements)))
        assert "cpu" in placements and "disk" in placements, dispatched.device_map
        dp = dispatched.dispatched_params

        # 3. streamed forward: embed on device, stream each layer's weights
        from accelerate_tpu.models.llama import LlamaLayer, RMSNorm

        flat_all = {k: dp[k] for k in dp.keys()}
        tree = unflatten(flat_all)
        layer_params = [tree[f"layer_{i}"] for i in range(cfg.num_hidden_layers)]
        layer_mod = LlamaLayer(cfg)

        def layer_fn(params_i, carry, i):
            hidden, positions = carry
            return layer_mod.apply({"params": params_i}, hidden, positions), positions

        executor = StreamedExecutor(layer_params, layer_fn)
        embed = jax.device_put(tree["embed_tokens"]["embedding"])
        hidden = embed[ids]
        positions = np.broadcast_to(np.arange(seq_len), ids.shape)
        hidden, _ = executor((hidden, positions))
        norm_mod = RMSNorm(cfg.rms_norm_eps)
        hidden = norm_mod.apply({"params": tree["final_norm"]}, hidden)
        logits = np.asarray(hidden.astype(np.float32) @ tree["lm_head"]["kernel"])

        # 4. streamed result == in-memory result
        np.testing.assert_allclose(logits, reference_logits, rtol=2e-4, atol=2e-4)
        print("streamed logits match in-memory forward")

        # 5. greedy generation through the streamed executor (reference
        # benchmark: benchmarks/big_model_inference generates per-token).
        # Each step re-streams the layer stack over the grown sequence.
        def streamed_forward(token_ids):
            s = token_ids.shape[1]
            h = embed[token_ids]
            pos = np.broadcast_to(np.arange(s), token_ids.shape)
            h, _ = executor((h, pos))
            h = norm_mod.apply({"params": tree["final_norm"]}, h)
            return np.asarray(h.astype(np.float32) @ tree["lm_head"]["kernel"])

        prompt = ids[:1, :4]
        generated = prompt
        for _ in range(4):
            step_logits = streamed_forward(generated)
            next_tok = step_logits[:, -1].argmax(-1).astype(np.int32)[:, None]
            generated = np.concatenate([generated, next_tok], axis=1)
        assert generated.shape == (1, 8)
        # greedy decode must match the in-memory model's choices
        ref_next = np.asarray(model(generated[:, :-1]))[:, -1].argmax(-1)
        assert int(ref_next[0]) == int(generated[0, -1]), (ref_next, generated)
        print("streamed greedy generation OK:", generated[0].tolist())

        # balanced placement spreads groups across all local devices
        balanced = load_checkpoint_and_dispatch(
            create_llama_model(cfg, seq_len=seq_len, seed=2), ckpt_dir, device_map="balanced"
        )
        used = {v for v in balanced.device_map.values() if v not in ("cpu", "disk")}
        print("balanced over devices:", sorted(map(str, used)))
        assert len(used) >= min(2, len(jax.local_devices()))

    print("big_model_inference OK")


if __name__ == "__main__":
    main()
