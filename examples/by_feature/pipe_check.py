"""Static pipeline-schedule analysis before the first compile: split the
GPipe region into per-stage sub-programs, roofline each stage, predict
the bubble fraction and the bubble-adjusted step time, and catch TPU8xx
schedule defects while they are still one-line fixes.

Two surfaces on the same pipelined step:

* ``Accelerator.pipe_check(step_fn, *sample_args)`` — programmatic,
  against the accelerator's live mesh (or hand it a ``PipelineSpec`` /
  ``PipelinedModel`` directly);
* ``accelerate-tpu pipe-check examples/by_feature/pipe_check.py::train_step
  --mesh pipe=4,data=2`` — the CLI reads the sample shapes from
  ``train_step_sample_args()`` below (or pass ``--arg f32[32,16]``).

The step below runs the real ``parallel.pipeline`` schedule with only
``num_microbatches=2`` over 4 stages — the seeded TPU803 pattern: the
fill/drain bubble is 3/5 of the schedule and the finding names the
covering microbatch count. The declared ``PIPE_SPEC`` twin at
``num_microbatches=16`` is checked too, showing the predicted saving.
"""

import jax
import jax.numpy as jnp

LAYERS = 8
WIDTH = 16
BATCH = 32
STAGES = 4


def _layer(p, h):
    return jnp.tanh(h @ p["w"] + p["b"]) + h


def train_step(params, x):
    """The real GPipe schedule from ``parallel.pipeline`` with too few
    microbatches (the seeded TPU803 finding)."""
    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.pipeline import pipeline_apply

    mesh = MeshConfig(pipe=STAGES, data=2).build()
    return pipeline_apply(_layer, params, x, mesh=mesh, num_microbatches=2).sum()


def train_step_sample_args():
    """Abstract sample shapes for the CLI (nothing is allocated)."""
    params = {
        "w": jax.ShapeDtypeStruct((LAYERS, WIDTH, WIDTH), jnp.float32),
        "b": jax.ShapeDtypeStruct((LAYERS, WIDTH), jnp.float32),
    }
    return params, jax.ShapeDtypeStruct((BATCH, WIDTH), jnp.float32)


def _pipe_spec(num_microbatches=16):
    """The declared twin: same layers, enough microbatches to cover the
    bubble — what TPU803 tells you to write."""
    from accelerate_tpu.analysis.pipemodel import PipelineSpec
    from accelerate_tpu.parallel.mesh import MeshConfig

    mesh = MeshConfig(pipe=STAGES, data=2).build()
    params, x = train_step_sample_args()
    return PipelineSpec(_layer, params, x, mesh, num_microbatches=num_microbatches)


def main():
    from accelerate_tpu.utils.environment import force_host_platform

    force_host_platform(8)  # fake 8-device CPU mesh, same as the test suite
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    report = accelerator.pipe_check(train_step, *train_step_sample_args())
    accelerator.print(report.render_text())
    fixed = accelerator.pipe_check(_pipe_spec())
    accelerator.print(
        f"\nTPU803 fix (num_microbatches 2 -> 16): bubble "
        f"{report.bubble_fraction:.3f} -> {fixed.bubble_fraction:.3f}, predicted step "
        f"{report.predicted_step_ms:.4f} -> {fixed.predicted_step_ms:.4f} ms"
    )
    assert any(f.rule == "TPU803" for f in report.findings), "seeded TPU803 must fire"
    assert not any(f.rule == "TPU803" for f in fixed.findings), "fixed twin must be clean"


if __name__ == "__main__":
    main()
