"""Preemption-safe training: auto-resume + SIGTERM-to-final-checkpoint
+ topology-elastic restore
(docs/usage_guides/fault_tolerance.md; no reference analogue).

Run it twice against the same project dir to see auto-resume pick up
exactly where the first run stopped; send the process SIGTERM mid-run to
see the final synchronous checkpoint + clean exit. The last phase
resumes the SAME checkpoints on a different mesh — the elastic-restore
path: arrays reshard on load, RNG is re-derived deterministically, and
the sampler offset is redistributed (all announced via warnings and
telemetry events, never silent).
"""

import tempfile

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin, ProjectConfiguration
from accelerate_tpu.utils import FaultToleranceKwargs

from _common import final_weights, make_task


def train(project_dir: str, max_steps: int = 24, mesh_config: MeshConfig = None) -> int:
    accelerator = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=mesh_config) if mesh_config else None,
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True, total_limit=3
        ),
        kwargs_handlers=[FaultToleranceKwargs()],  # installs the SIGTERM/SIGINT handler
    )
    model, optimizer, dataloader, loss_fn = make_task(accelerator)
    step = accelerator.build_train_step(loss_fn)

    try:
        accelerator.load_state()  # auto-resume: newest checkpoint that verifies
        accelerator.print(f"resumed at step {accelerator.step}")
    except FileNotFoundError:
        accelerator.print("no checkpoint found; starting fresh")

    while accelerator.step < max_steps:
        for batch in dataloader:
            step(batch)
            if accelerator.step % 8 == 0:
                accelerator.save_state(async_save=True)  # background commit
            if accelerator.should_checkpoint:  # preemption notice arrived
                accelerator.save_state()  # drains async saves; commits synchronously
            if accelerator.should_stop or accelerator.step >= max_steps:
                break
        if accelerator.should_stop:
            accelerator.print("preempted — final checkpoint committed, exiting cleanly")
            break

    accelerator.wait_for_checkpoint()
    return accelerator.step


def main():
    with tempfile.TemporaryDirectory() as project_dir:
        # first run: train half way, as if the pod were reclaimed after
        reached = train(project_dir, max_steps=12)
        print(f"first run stopped at step {reached}")

        # 'restarted' run: auto-resumes from the newest valid checkpoint
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        reached = train(project_dir, max_steps=24)
        print(f"second run finished at step {reached}")
        assert reached >= 24

        # elastic restore: the fleet shrank — resume the same checkpoints
        # on a 4-device data=2 x tensor=2 mesh. Arrays reshard on load;
        # `accelerate-tpu checkpoints describe <dir> --mesh data=2,tensor=2`
        # predicts the reshard bytes this pays.
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        reached = train(
            project_dir, max_steps=32,
            mesh_config=MeshConfig(data=2, tensor=2, num_devices=4),
        )
        print(f"elastic run (mesh data=2,tensor=2) finished at step {reached}")
        assert reached >= 32


if __name__ == "__main__":
    main()
