"""LoRA fine-tuning (reference analogue: torch users pair Accelerate with
``peft``; src/accelerate/utils/modeling.py:73 ``is_peft_model``. On TPU
LoRA is a pure pytree transform — ``utils/lora.py``): freeze the base
params, train only the low-rank adapter tree, export merged weights."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models.bert import BertConfig, bert_classification_loss, create_bert_model
from accelerate_tpu.utils.lora import LoRAConfig, lora_init, lora_merge, lora_num_params


def main():
    accelerator = Accelerator()
    model = accelerator.prepare_model(
        create_bert_model(
            BertConfig(vocab_size=211, hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
                       intermediate_size=128, num_labels=2),
            seq_len=32,
        )
    )
    cfg = LoRAConfig(rank=4, alpha=8.0)
    adapters = lora_init(jax.random.key(0), model.params, cfg)
    trainable, total, pct = lora_num_params(model.params, adapters)
    accelerator.print(f"LoRA: training {trainable:,} of {total:,} params ({pct:.2f}%)")

    # a learnable synthetic task: label = whether token 7 appears in the text
    key = jax.random.key(1)
    ids = jax.random.randint(key, (128, 32), 0, 211)
    batch = {
        "input_ids": ids,
        "attention_mask": jnp.ones_like(ids),
        "labels": (ids == 7).any(axis=1).astype(jnp.int32),
    }

    # the ADAPTER tree is the trainable pytree: the optimizer, and any mesh
    # layout, see only it — the base params are frozen by construction
    opt = optax.adam(5e-3)
    opt_state = opt.init(adapters)
    base = model.params

    @jax.jit
    def step(adapters, opt_state):
        def loss_fn(ad):
            return bert_classification_loss(lora_merge(base, ad, cfg), batch, model.apply_fn)

        loss, grads = jax.value_and_grad(loss_fn)(adapters)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(adapters, updates), opt_state, loss

    first = None
    for i in range(30):
        adapters, opt_state, loss = step(adapters, opt_state)
        first = first if first is not None else float(loss)
    accelerator.print(f"loss {first:.4f} -> {float(loss):.4f}")
    assert float(loss) < first, "adapter training did not reduce the loss"

    # export: merge once, ship a plain checkpoint — no LoRA at inference
    merged = lora_merge(base, adapters, cfg)
    delta = jax.tree_util.tree_map(lambda a, b: float(jnp.abs(a - b).max()), base, merged)
    changed = sum(1 for v in jax.tree_util.tree_leaves(delta) if v > 0)
    accelerator.print(f"merged export: {changed} kernels changed, base params untouched")
    assert changed == 4  # q and v kernels of both layers

    # ---- QLoRA: the same transform over a QUANTIZED frozen base ----------
    from accelerate_tpu.utils.quantization import (
        QTensor, QuantizationConfig, load_and_quantize_model, quantized_bytes,
    )

    qmodel = load_and_quantize_model(
        model,
        QuantizationConfig(bits=8, min_size=1, skip_patterns=(
            "embed", "lm_head", "norm", "bias", "scale", "pooler", "classifier")),
    )
    q_adapters = lora_init(jax.random.key(2), qmodel.params, cfg)
    accelerator.print(
        f"QLoRA: base packed to {quantized_bytes(qmodel.params):,} bytes; "
        f"adapters {sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(q_adapters)):,} params"
    )
    q_opt_state = opt.init(q_adapters)

    @jax.jit
    def q_step(ad, opt_state):
        def loss_fn(ad):
            return bert_classification_loss(
                lora_merge(qmodel.params, ad, cfg), batch, qmodel.apply_fn)

        loss, grads = jax.value_and_grad(loss_fn)(ad)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(ad, updates), opt_state, loss

    q_first = None
    for _ in range(30):
        q_adapters, q_opt_state, q_loss = q_step(q_adapters, q_opt_state)
        q_first = q_first if q_first is not None else float(q_loss)
    accelerator.print(f"QLoRA loss {q_first:.4f} -> {float(q_loss):.4f}")
    assert float(q_loss) < q_first, "QLoRA training did not reduce the loss"
    q_merged = lora_merge(qmodel.params, q_adapters, cfg)
    still_q = sum(isinstance(l, QTensor)
                  for l in jax.tree_util.tree_leaves(q_merged, is_leaf=lambda l: isinstance(l, QTensor)))
    accelerator.print(f"QLoRA merged export: {still_q} untargeted kernels still quantized")
    assert still_q > 0


if __name__ == "__main__":
    main()
