"""Static numerics analysis before the first compile: interpret the step
over value intervals and dtype provenance, and catch TPU6xx precision
hazards while they are still one-line fixes.

Two surfaces on the same step function:

* ``Accelerator.numerics_check(step_fn, *sample_args)`` — programmatic,
  against the accelerator's live mesh;
* ``accelerate-tpu numerics-check examples/by_feature/numerics_check.py::train_step``
  — the CLI reads the sample shapes from ``train_step_sample_args()``
  below (or pass ``--arg bf16[128,512]``), and ``--assume lo,hi`` states
  the input-value assumption the proofs are relative to.

The step below contracts a 512-long axis in a bf16 matmul whose
accumulator stays bf16 — exactly the TPU601 pattern — so the report both
bounds the step AND prices the worst-case relative error
(``K·eps/2 = 512·2^-7/2 = 2.0``, i.e. the sum can be 200% wrong in the
worst case). The fixed twin keeps the same bf16 operands but accumulates
in f32 via ``preferred_element_type`` — same wire/HBM bytes, exact
accumulation — and is checked to produce zero findings.
"""

import jax
import jax.numpy as jnp

HIDDEN = 512
FEATURES = 128
BATCH = 128


def train_step(params, batch):
    """Forward + MSE with a bf16 matmul whose accumulator stays bf16 over
    the K=512 contraction (the seeded TPU601 finding)."""
    h = jnp.tanh(batch["x"] @ params["w1"])  # bf16 @ bf16 -> bf16 accumulate
    pred = h.astype(jnp.float32) @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def fixed_step(params, batch):
    """The TPU601 fix: same bf16 operands, f32 accumulation via
    ``preferred_element_type`` — the MXU keeps full rate and the sum is
    exact; narrow once afterwards if bf16 activations are wanted."""
    acc = jax.lax.dot(batch["x"], params["w1"], preferred_element_type=jnp.float32)
    h = jnp.tanh(acc)
    pred = h @ params["w2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def train_step_sample_args():
    """Abstract sample shapes for the CLI (nothing is allocated)."""
    params = {
        "w1": jax.ShapeDtypeStruct((HIDDEN, HIDDEN), jnp.bfloat16),
        "w2": jax.ShapeDtypeStruct((HIDDEN, FEATURES), jnp.float32),
    }
    batch = {
        "x": jax.ShapeDtypeStruct((BATCH, HIDDEN), jnp.bfloat16),
        "y": jax.ShapeDtypeStruct((BATCH, FEATURES), jnp.float32),
    }
    return params, batch


def fixed_step_sample_args():
    return train_step_sample_args()


def main():
    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    report = accelerator.numerics_check(train_step, *train_step_sample_args())
    accelerator.print(report.render_text())
    [finding] = [f for f in report.findings if f.rule == "TPU601"]
    accelerator.print(f"\npriced bound: {finding.message}")

    fixed = accelerator.numerics_check(fixed_step, *fixed_step_sample_args())
    accelerator.print(
        "\nTPU601 fix (preferred_element_type=f32): "
        f"{len(fixed.findings)} findings — exact f32 accumulation over the "
        f"{HIDDEN}-long contraction at full MXU rate"
    )
    assert any(f.rule == "TPU601" for f in report.findings), "seeded TPU601 must fire"
    assert not fixed.findings, "fixed twin must be clean"


if __name__ == "__main__":
    main()
