"""save_state / load_state round-trip + resume with skip_first_batches
(reference analogue: examples/by_feature/checkpointing.py).
"""

import tempfile

import numpy as np

from accelerate_tpu import Accelerator, skip_first_batches

from _common import final_weights, make_task


def main():
    accelerator = Accelerator()
    model, optimizer, dataloader, loss_fn = make_task(accelerator)
    step = accelerator.build_train_step(loss_fn)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        # train 1 epoch + 3 batches of the second, checkpoint mid-epoch
        for batch in dataloader:
            step(batch)
        for i, batch in enumerate(dataloader):
            if i == 3:
                break
            step(batch)
        accelerator.save_state(ckpt_dir)
        a_saved, b_saved = final_weights(model)

        # keep training, then roll back
        for batch in dataloader:
            step(batch)
        accelerator.load_state(ckpt_dir)
        a_loaded, b_loaded = final_weights(model)
        assert (a_saved, b_saved) == (a_loaded, b_loaded), "load_state must restore params"

        # resume the interrupted epoch where it left off
        resumed = skip_first_batches(dataloader, num_batches=3)
        n = sum(1 for _ in resumed)
        accelerator.print(f"restored a={a_loaded:.3f} b={b_loaded:.3f}; resumed epoch has {n} batches left")


if __name__ == "__main__":
    main()
