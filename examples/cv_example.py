"""ResNet image-classification fine-tune — the canonical CV example
(reference analogue: examples/cv_example.py, timm ResNet-50 on the
Oxford-IIIT Pet dataset with OneCycleLR).

Offline-friendly: a synthetic pets-shaped dataset (class-correlated color
blobs) replaces the real images so the example runs on a bare TPU VM with
zero egress. The loop is the reference's shape: Accelerator() -> prepare()
-> one-cycle schedule -> train -> gather_for_metrics eval accuracy.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.models import ResNetConfig, create_resnet_model, resnet_classification_loss


class SyntheticPets:
    """Pets-shaped synthetic data: each class gets a characteristic color
    bias plus noise, so accuracy is a meaningful signal."""

    def __init__(self, n=1024, image_size=224, num_classes=37, seed=0):
        rng = np.random.default_rng(seed)
        self.labels = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
        means = rng.normal(0.0, 1.0, size=(num_classes, 3)).astype(np.float32)
        noise = rng.normal(0.0, 0.5, size=(n, image_size, image_size, 3)).astype(np.float32)
        self.images = noise + means[self.labels][:, None, None, :]

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return {"images": self.images[i], "labels": self.labels[i]}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16")
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=None, help="default: 3e-2 (one-cycle peak)")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--tiny", action="store_true", help="tiny config for CI")
    parser.add_argument("--checkpoint_dir", default=None)
    args = parser.parse_args()

    accelerator = Accelerator(mixed_precision=args.mixed_precision, log_with="jsonl", project_dir="runs")
    accelerator.init_trackers("cv_example", config=vars(args))

    if args.tiny:
        args.image_size = min(args.image_size, 32)
    config = ResNetConfig.tiny() if args.tiny else ResNetConfig.resnet50(num_classes=37)
    dataset = SyntheticPets(
        n=256 if args.tiny else 1024, image_size=args.image_size, num_classes=config.num_classes
    )

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(
        dataset,
        batch_size=max(1, args.batch_size // accelerator.num_data_shards),
        shuffle=True,
        seed=42,
        drop_last=True,
    )

    model = create_resnet_model(config, image_size=args.image_size)
    steps_per_epoch = len(loader)
    total_steps = max(1, args.num_epochs * steps_per_epoch)
    peak_lr = args.lr if args.lr is not None else (1e-1 if args.tiny else 3e-2)
    # the reference uses torch OneCycleLR (cv_example.py); optax's onecycle
    # is the same warmup->anneal policy
    schedule = optax.cosine_onecycle_schedule(total_steps, peak_lr, pct_start=0.25)
    optimizer = optax.sgd(schedule, momentum=0.9)

    model, optimizer, loader = accelerator.prepare(model, optimizer, loader)
    loss_fn = lambda p, s, b: resnet_classification_loss(p, s, b, model.apply_fn)
    step = accelerator.build_train_step(loss_fn, has_state=True)
    eval_step = accelerator.build_eval_step(lambda p, s, x: model.apply_fn(p, x, state=s, train=False))

    for epoch in range(args.num_epochs):
        t0, n_samples = time.perf_counter(), 0
        for batch in loader:
            loss = step(batch)
            n_samples += batch["images"].shape[0]
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        accelerator.log({"loss": float(loss), "samples_per_sec": n_samples / dt}, step=epoch)
        accelerator.print(f"epoch {epoch}: loss={float(loss):.4f} {n_samples / dt:.1f} samples/s")

        # eval with running BN statistics + padded-tail truncation
        correct = total = 0
        for batch in loader:
            logits = eval_step(batch["images"])
            preds = accelerator.gather_for_metrics(jnp.argmax(logits, -1))
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accelerator.print(f"epoch {epoch}: accuracy={correct / total:.3f} ({total} samples)")

    if args.checkpoint_dir:
        accelerator.save_state(args.checkpoint_dir)
    accelerator.end_training()
    return correct / total


if __name__ == "__main__":
    main()
