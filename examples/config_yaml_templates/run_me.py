"""Print the accelerator state the active config produces.

Reference analogue: examples/config_yaml_templates/run_me.py — a base
script that outputs the accelerate config for the given environment. Run
it with each template to see what the keys do:

    accelerate-tpu launch --config_file examples/config_yaml_templates/hybrid_mesh.yaml \
        examples/config_yaml_templates/run_me.py
"""

from accelerate_tpu import Accelerator

accelerator = Accelerator()
accelerator.print(f"Accelerator state from the current environment:\n{accelerator.state}")
accelerator.end_training()
