#!/bin/bash
# Single TPU VM (reference: examples/slurm/submit_multigpu.sh).
#SBATCH --job-name=tpu-single
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=1
#SBATCH --ntasks-per-node=1
#SBATCH --time=01:59:00

export REPO_DIR="${REPO_DIR:-$PWD}"
export SCRIPT="${SCRIPT:-$REPO_DIR/examples/complete_nlp_example.py}"

srun accelerate-tpu launch --mixed_precision bf16 "$SCRIPT" \
    --output_dir "$REPO_DIR/examples/output"
