#!/bin/bash
# Multi-host TPU slice under Slurm (reference: examples/slurm/submit_multinode.sh).
# One launcher per node; each node runs its local share of the processes
# with a global rank offset of SLURM_NODEID * procs-per-node, all
# rendezvousing at the head node's coordinator.
#SBATCH --job-name=tpu-multihost
#SBATCH -D .
#SBATCH --output=O-%x.%j
#SBATCH --error=E-%x.%j
#SBATCH --nodes=4                   # TPU VMs in the slice
#SBATCH --ntasks-per-node=1         # ONE launcher per node (it spawns local procs)
#SBATCH --time=01:59:00

export PROCS_PER_NODE="${PROCS_PER_NODE:-1}"   # chips driven per VM
head_node_ip=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n 1)

export REPO_DIR="${REPO_DIR:-$PWD}"
export SCRIPT="${SCRIPT:-$REPO_DIR/examples/complete_nlp_example.py}"

# SLURM_NODEID becomes --machine_rank on each node; the launcher computes
# global process ids as machine_rank * procs_per_machine + local_rank.
srun bash -c "accelerate-tpu launch \
    --num_processes $((SLURM_NNODES * PROCS_PER_NODE)) \
    --num_machines \$SLURM_NNODES \
    --machine_rank \$SLURM_NODEID \
    --main_process_ip $head_node_ip \
    --main_process_port 29500 \
    --mixed_precision bf16 \
    --mesh_data $((SLURM_NNODES * PROCS_PER_NODE)) \
    $SCRIPT --output_dir $REPO_DIR/examples/output"
