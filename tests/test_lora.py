"""LoRA functional-transform tests (reference analogue: the PEFT-model
handling asserted around utils/modeling.py:73 ``is_peft_model``; the LoRA
math itself has no reference analogue — torch users bring ``peft``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu.models.bert import BertConfig, bert_classification_loss, create_bert_model
from accelerate_tpu.utils.lora import (
    LoRAConfig,
    load_lora,
    lora_init,
    lora_merge,
    lora_num_params,
    lora_shardings,
    lora_targets,
    save_lora,
)

TINY = BertConfig(
    vocab_size=97,
    hidden_size=32,
    num_hidden_layers=2,
    num_attention_heads=2,
    intermediate_size=64,
    num_labels=2,
)


@pytest.fixture(scope="module")
def bert():
    return create_bert_model(TINY, seq_len=16)


def _batch(rng, batch=4, seq=16):
    ids_rng, labels_rng = jax.random.split(rng)
    return {
        "input_ids": jax.random.randint(ids_rng, (batch, seq), 0, TINY.vocab_size),
        "attention_mask": jnp.ones((batch, seq), jnp.int32),
        "labels": jax.random.randint(labels_rng, (batch,), 0, 2),
    }


def test_targets_and_param_fraction(bert):
    cfg = LoRAConfig(rank=4)
    targets = lora_targets(bert.params, cfg)
    # q and v of both layers, nothing else
    assert len(targets) == 4 and all(("query" in t or "value" in t) for t in targets)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    trainable, total, pct = lora_num_params(bert.params, adapters)
    assert trainable == 4 * 2 * (32 * 4)  # 4 kernels x (A + B) x (32x4)
    assert pct < 5.0


def test_init_is_identity(bert):
    """B starts at zero, so merge(params, init_adapters) == params and the
    adapted model computes exactly the base model."""
    cfg = LoRAConfig(rank=4)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    merged = lora_merge(bert.params, adapters, cfg)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b), bert.params, merged)


def test_training_moves_only_adapters(bert):
    """A short adapter-only fine-tune: loss decreases, adapters leave
    zero, and the base params are untouched (frozen by construction)."""
    cfg = LoRAConfig(rank=4, alpha=8.0)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    batch = _batch(jax.random.key(1))
    opt = optax.adam(1e-2)
    opt_state = opt.init(adapters)
    base = bert.params

    @jax.jit
    def step(adapters, opt_state):
        def loss_fn(ad):
            return bert_classification_loss(lora_merge(base, ad, cfg), batch, bert.apply_fn)

        loss, grads = jax.value_and_grad(loss_fn)(adapters)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(adapters, updates), opt_state, loss

    losses = []
    for _ in range(8):
        adapters, opt_state, loss = step(adapters, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    b_norms = [float(jnp.abs(v).max()) for k, v in _flat(adapters).items() if k.endswith("lora_b")]
    assert all(n > 0 for n in b_norms)
    # export path: the merged model scores the batch identically to the
    # runtime-merge the step trained with
    merged = lora_merge(base, adapters, cfg)
    np.testing.assert_allclose(
        float(bert_classification_loss(merged, batch, bert.apply_fn)), losses[-1], rtol=0.5
    )


def _flat(tree):
    from accelerate_tpu.parallel.sharding import path_str

    return {path_str(kp): leaf for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}


def test_stacked_scan_kernels():
    """Scan-stacked [L, in, out] kernels get [L, in, r]/[L, r, out]
    adapters and a broadcasted contraction."""
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    cfg = LlamaConfig(
        vocab_size=64,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=2,
        intermediate_size=64,
        scan_layers=True,
    )
    model = create_llama_model(cfg, seq_len=8)
    lcfg = LoRAConfig(rank=2)
    adapters = lora_init(jax.random.key(0), model.params, lcfg)
    flat = _flat(adapters)
    a = next(v for k, v in flat.items() if "q_proj" in k and k.endswith("lora_a"))
    assert a.shape == (2, 32, 2)
    merged = lora_merge(model.params, adapters, lcfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(model.apply_fn(merged, ids)), np.asarray(model.apply_fn(model.params, ids)), rtol=1e-6
    )


def test_rejects_raw_codes_and_no_match(bert):
    with pytest.raises(ValueError, match="matched no parameter"):
        lora_init(jax.random.key(0), bert.params, LoRAConfig(targets="nonexistent_layer"))
    # a plain integer leaf (in-scan QuantDense qdata style) still refuses
    qparams = {"attn": {"q_proj": {"kernel": jnp.zeros((8, 8), jnp.int8)}}}
    with pytest.raises(ValueError, match="integer codes"):
        lora_init(jax.random.key(0), qparams, LoRAConfig(targets=r"q_proj/kernel"))
    # a target regex naming a QuantDense LAYER (kernel gone, only qdata/qscale
    # params remain) gets the actionable in-scan error, not a silent skip
    qd = {"layer_0": {"q_proj": {"qdata": jnp.zeros((1, 8, 8), jnp.int8),
                                 "qscale": jnp.ones((1, 1, 8), jnp.float32)}}}
    with pytest.raises(ValueError, match="QuantDense"):
        lora_init(jax.random.key(0), qd, LoRAConfig(targets=r"q_proj$"))
    # an unanchored regex hits the codes directly — still an actionable error
    with pytest.raises(ValueError, match="quantize_params"):
        lora_init(jax.random.key(0), qd, LoRAConfig(targets=r"q_proj"))


def test_qlora_init_identity_and_frozen_codes():
    """QLoRA: a QTensor kernel is a first-class target — adapters attach at
    the kernel path, merge at init reproduces the dequantized base exactly,
    and the packed codes never leave the tree (frozen by construction)."""
    from accelerate_tpu.utils.quantization import (
        QTensor, QuantizationConfig, dequantize_params, quantize_params,
    )

    params = {"attn": {"q_proj": {"kernel": jax.random.normal(jax.random.key(1), (64, 64))},
                       "o_proj": {"kernel": jax.random.normal(jax.random.key(2), (64, 64))}}}
    qparams = quantize_params(params, QuantizationConfig(min_size=1))
    cfg = LoRAConfig(rank=4, targets=r"q_proj/kernel$")
    assert lora_targets(qparams, cfg) == ["attn/q_proj/kernel"]
    adapters = lora_init(jax.random.key(0), qparams, cfg)
    a = adapters["attn"]["q_proj"]["kernel"]["lora_a"]
    assert a.shape == (64, 4) and jnp.issubdtype(a.dtype, jnp.floating)
    merged = lora_merge(qparams, adapters, cfg)
    # target kernel is dense after merge; the untargeted one stays quantized
    assert not isinstance(merged["attn"]["q_proj"]["kernel"], QTensor)
    assert isinstance(merged["attn"]["o_proj"]["kernel"], QTensor)
    np.testing.assert_allclose(
        np.asarray(merged["attn"]["q_proj"]["kernel"]),
        np.asarray(dequantize_params(qparams)["attn"]["q_proj"]["kernel"]),
        rtol=1e-6,
    )


def test_qlora_trains_adapters_on_quantized_base(bert):
    """End-to-end QLoRA: int8 base + float adapters; only adapters get
    gradients, loss decreases, and the merged export can be re-quantized."""
    from accelerate_tpu.utils.quantization import (
        QTensor, QuantizationConfig, dequantize_params, quantize_params,
    )

    from accelerate_tpu.utils.quantization import load_and_quantize_model

    qmodel = load_and_quantize_model(bert, QuantizationConfig(bits=8, min_size=1, skip_patterns=(
        "embed", "lm_head", "norm", "bias", "scale", "pooler", "classifier")))
    qparams = qmodel.params
    cfg = LoRAConfig(rank=4)
    target_paths = lora_targets(qparams, cfg)
    assert target_paths, "quantized q/v kernels must still be targetable"
    adapters = lora_init(jax.random.key(0), qparams, cfg)

    def loss_fn(ad, batch):
        # qmodel.apply_fn dequantizes the REMAINING QTensor leaves in-jit;
        # merged target kernels are already dense
        return bert_classification_loss(lora_merge(qparams, ad, cfg), batch, qmodel.apply_fn)

    opt = optax.adam(5e-2)
    opt_state = opt.init(adapters)
    batch = _batch(jax.random.key(3))

    @jax.jit
    def step(ad, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(ad, batch)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(ad, updates), opt_state, loss

    losses = []
    for _ in range(8):
        adapters, opt_state, loss = step(adapters, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, losses

    def leaf_at(tree, path):
        node = tree
        for part in path.split("/"):
            node = node[part]
        return node

    # base stayed quantized+frozen through training; export re-quantizes fine
    assert isinstance(leaf_at(qparams, target_paths[0]), QTensor)
    merged = lora_merge(qparams, adapters, cfg)
    assert not isinstance(leaf_at(merged, target_paths[0]), QTensor)
    requant = quantize_params(
        dequantize_params(merged), QuantizationConfig(bits=8, min_size=1))
    assert any(isinstance(l, QTensor) for l in jax.tree.leaves(
        requant, is_leaf=lambda l: isinstance(l, QTensor)))


def test_save_load_roundtrip(bert, tmp_path):
    cfg = LoRAConfig(rank=4, alpha=16.0)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    path = str(tmp_path / "adapters.npz")
    save_lora(adapters, path, cfg)
    loaded, loaded_cfg = load_lora(path)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b), adapters, loaded)
    # the config rides along so the merge scale survives the round-trip
    assert loaded_cfg.rank == 4 and loaded_cfg.alpha == 16.0 and loaded_cfg.targets == cfg.targets
    assert loaded_cfg.scaling == cfg.scaling


def test_sharded_lora_matches_single_device(bert):
    """tensor2 x data2: the adapter shardings derived from the base rules
    produce the same loss trajectory as unsharded training."""
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("data", "tensor"))
    cfg = LoRAConfig(rank=4)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    shardings = lora_shardings(adapters, bert.sharding_rules, mesh)
    placed = jax.tree_util.tree_map(jax.device_put, adapters, shardings)
    batch = _batch(jax.random.key(1))
    base = bert.params

    def loss_fn(ad):
        return bert_classification_loss(lora_merge(base, ad, cfg), batch, bert.apply_fn)

    grads_ref = jax.grad(loss_fn)(adapters)
    with mesh:
        grads_sharded = jax.jit(jax.grad(loss_fn))(placed)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5),
        grads_ref,
        grads_sharded,
    )


def test_lora_model_rides_the_accelerator(bert):
    """lora_model: the wrapped Model's params ARE the adapters, so
    prepare/build_train_step/checkpoint machinery works unchanged and
    trains adapters only."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.lora import lora_model

    AcceleratorState._reset_state() if hasattr(AcceleratorState, "_reset_state") else None
    accelerator = Accelerator()
    cfg = LoRAConfig(rank=4, alpha=8.0)
    lora = lora_model(bert, cfg, rng=jax.random.key(0))
    lora = accelerator.prepare_model(lora)
    optimizer = accelerator.prepare_optimizer(optax.adam(5e-3))
    batch = _batch(jax.random.key(1))
    base_before = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), bert.params)

    def loss_fn(adapters, b):
        return bert_classification_loss(adapters, b, lora.apply_fn)

    step = accelerator.build_train_step(loss_fn, model=lora, optimizer=optimizer)
    losses = [float(step(batch)) for _ in range(6)]
    assert losses[-1] < losses[0]
    # the base stayed frozen; only adapters moved
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b), bert.params, base_before
    )
    flat = _flat(lora.params)
    assert any(float(jnp.abs(v).max()) > 0 for k, v in flat.items() if k.endswith("lora_b"))
    # merged export from the wrapper
    merged = lora.merged_params()
    out = bert.apply_fn(merged, batch["input_ids"], batch["attention_mask"])
    assert np.isfinite(np.asarray(out)).all()


def test_lora_model_prepares_sharded(bert):
    """Under a tensor mesh, prepare_model shards the adapters by the
    derived per-path rules (B output-dim over tensor where the base
    kernel is column-split)."""
    from accelerate_tpu.parallel.sharding import infer_shardings
    from accelerate_tpu.utils.lora import lora_adapter_rules, lora_init

    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("data", "tensor"))
    cfg = LoRAConfig(rank=4)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    rules = lora_adapter_rules(adapters, bert.sharding_rules or [])
    shardings = infer_shardings(adapters, rules, mesh)
    flat_sh = _flat(shardings)
    # base query kernel is column-split P(None, "tensor") -> B shards its
    # output dim over tensor, A's rank dim stays replicated
    b_spec = next(v.spec for k, v in flat_sh.items() if "query" in k and k.endswith("lora_b"))
    a_spec = next(v.spec for k, v in flat_sh.items() if "query" in k and k.endswith("lora_a"))
    assert tuple(b_spec) == (None, "tensor"), b_spec
    assert "tensor" not in tuple(a_spec), a_spec
    placed = jax.tree_util.tree_map(jax.device_put, adapters, shardings)
    assert all(leaf.sharding.mesh.shape == mesh.shape for leaf in jax.tree_util.tree_leaves(placed))


def test_adapter_rules_use_actual_base_placements(bert):
    """base_specs (a prepared model's real placements, e.g. fsdp
    auto-shardings) take precedence over the regex rules, and rules are
    fully anchored so sibling paths cannot shadow each other."""
    from accelerate_tpu.utils.lora import lora_adapter_rules
    import re as _re

    cfg = LoRAConfig(rank=4)
    adapters = lora_init(jax.random.key(0), bert.params, cfg)
    qpath = "encoder/layer_0/attention/query/kernel"
    rules = lora_adapter_rules(adapters, bert.sharding_rules, {qpath: P("fsdp", None)})
    by_path = {r: s for r, s in rules}
    a_rule = "^" + _re.escape(qpath + "/lora_a") + "$"
    b_rule = "^" + _re.escape(qpath + "/lora_b") + "$"
    assert tuple(by_path[a_rule]) == ("fsdp", None)   # A follows W's input-dim fsdp split
    assert tuple(by_path[b_rule]) == (None, None)
    # an un-overridden sibling still derives from the regex rules
    v_rule = "^" + _re.escape("encoder/layer_0/attention/value/kernel/lora_b") + "$"
    assert tuple(by_path[v_rule]) == (None, "tensor")


def test_lora_model_propagates_state(bert):
    """Non-trainable collections (model.state) ride through the wrapper."""
    from accelerate_tpu.utils.lora import lora_model

    bert.state = {"marker": jnp.ones((1,))}
    try:
        lora = lora_model(bert, LoRAConfig(rank=2), rng=jax.random.key(0))
        assert lora.state is bert.state
    finally:
        bert.state = None
