"""Speculative continuous batching (serving.py draft_model mode): a draft
model proposes gamma tokens per slot, one target forward verifies them —
emitted streams are exactly the target's greedy output at both acceptance
extremes, with variable per-iteration emit counts threading correctly
through slot reuse, EOS/stop retirement, streaming, and logprobs."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def target():
    return create_llama_model(LlamaConfig.tiny(), seq_len=64, seed=0)


@pytest.fixture(scope="module")
def draft():
    # a different (1-layer, different-init) model: near-zero acceptance,
    # so every token comes from the correction path
    return create_llama_model(LlamaConfig.tiny(num_hidden_layers=1), seq_len=64, seed=1)


def _reference(model, prompt, n):
    return np.asarray(generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n))[0]


def test_disjoint_draft_token_exact(target, draft):
    """Low-acceptance regime: outputs still exactly match target greedy."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (5, 9, 3, 12)]
    eng = ServingEngine(
        target, num_slots=2, prompt_buckets=(8, 16), tick_block=2, draft_model=draft, gamma=3
    )
    for p, got in zip(prompts, eng.generate_many(prompts, max_new_tokens=6)):
        np.testing.assert_array_equal(got, _reference(target, p, 6))
    # 6 tokens per request, minus the one emitted by admission prefill
    assert eng.spec_stats["emitted"] == 4 * (6 - 1), eng.spec_stats


def test_self_draft_full_acceptance(target):
    """draft == target: every proposal matches the target's own argmax, so
    each iteration emits gamma+1 tokens (the all-accepted bonus path and
    the extra draft cache pass both exercised)."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (5, 9)]
    eng = ServingEngine(
        target, num_slots=2, prompt_buckets=(8, 16), tick_block=2, draft_model=target, gamma=3
    )
    for p, got in zip(prompts, eng.generate_many(prompts, max_new_tokens=9)):
        np.testing.assert_array_equal(got, _reference(target, p, 9))
    rate = eng.spec_stats["accepted"] / (eng.spec_stats["steps"] * 3)
    assert rate == 1.0, eng.spec_stats


def test_spec_streaming_logprobs_and_stop(target, draft):
    eng = ServingEngine(
        target, num_slots=1, prompt_buckets=(8,), tick_block=2, draft_model=draft, gamma=2
    )
    prompt = np.ones((4,), np.int32)
    full = _reference(target, prompt, 8)
    gen = full[len(prompt):]
    stop = [int(gen[2]), int(gen[3])]
    first = next(i for i in range(len(gen) - 1) if [int(gen[i]), int(gen[i + 1])] == stop)
    uid = eng.submit(prompt, max_new_tokens=8, stop_sequences=[stop])
    while eng.poll(uid) is None:
        assert len(eng.partial(uid)) == len(eng.logprobs(uid))
        eng.step()
    final = eng.poll(uid)
    assert len(final) == len(prompt) + first + 2
    np.testing.assert_array_equal(final, full[: len(final)])
    assert np.all(eng.logprobs(uid) <= 0)


def test_spec_eos_and_slot_reuse(target, draft):
    """EOS retires mid-iteration (overshoot within the accepted run is
    discarded) and the freed slot serves the next request token-exact."""
    prompt = np.ones((4,), np.int32)
    full = _reference(target, prompt, 8)
    eos = int(full[6])
    eng = ServingEngine(
        target, num_slots=1, prompt_buckets=(8,), tick_block=3,
        draft_model=draft, gamma=3, eos_token_id=eos,
    )
    u1 = eng.submit(prompt, max_new_tokens=8)
    u2 = eng.submit((np.arange(5) % 200).astype(np.int32), max_new_tokens=4)
    while eng.poll(u1) is None or eng.poll(u2) is None:
        eng.step()
    got1 = eng.poll(u1)
    assert got1[-1] == eos and len(got1) <= len(full)
    np.testing.assert_array_equal(got1, full[: len(got1)])
    np.testing.assert_array_equal(
        eng.poll(u2), _reference(target, (np.arange(5) % 200).astype(np.int32), 4)
    )
    assert eng.active_count == 0


def test_spec_mode_constraints(target, draft):
    with pytest.raises(NotImplementedError, match="dense-layout"):
        ServingEngine(target, draft_model=draft, paged_block_size=4)
    with pytest.raises(NotImplementedError, match="greedy-only"):
        ServingEngine(target, draft_model=draft, temperature=0.7)
    eng = ServingEngine(target, num_slots=1, prompt_buckets=(8,), draft_model=draft, max_len=32)
    with pytest.raises(ValueError, match="bucket-sized"):
        eng.submit(np.ones((20,), np.int32))
    with pytest.raises(NotImplementedError, match="prefix caching"):
        eng.register_prefix(np.ones((4,), np.int32))
    with pytest.raises(ValueError, match="gamma"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=30)  # 4+30+gamma > 32


def test_spec_serving_sharded_target(target, draft):
    """Speculative serving over a TP-sharded target (shard_model): the
    draft stays replicated, tokens equal unsharded target greedy."""
    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (5, 7)]
    want = [_reference(target, p, 5) for p in prompts]

    sharded = create_llama_model(LlamaConfig.tiny(), seq_len=64, seed=0)
    shard_model(sharded, MeshConfig(data=2, fsdp=2, tensor=2).build())
    eng = ServingEngine(
        sharded, num_slots=2, prompt_buckets=(8, 16), tick_block=2, draft_model=draft, gamma=3
    )
    for w, got in zip(want, eng.generate_many(prompts, max_new_tokens=5)):
        np.testing.assert_array_equal(got, w)
    # pin that the SPECULATIVE path ran (not a silent greedy fallback)
    assert eng.spec_stats["emitted"] == 2 * (5 - 1), eng.spec_stats
