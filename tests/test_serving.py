"""Continuous-batching serving engine (serving.py): token-exact parity
with generate(), slot reuse, mixed lengths, EOS retirement."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


def _reference(model, prompt, n):
    out = generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n)
    return np.asarray(out)[0]


def test_single_request_matches_generate(tiny_llama):
    prompt = (np.arange(8) % 250).astype(np.int32)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8, 16))
    [got] = eng.generate_many([prompt], max_new_tokens=6)
    np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 6))


def test_mixed_lengths_and_more_requests_than_slots(tiny_llama):
    """8 prompts of different lengths through 2 slots: every output equals
    the static generate() result — slots are reused and prompts hit
    different prefill buckets."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 8, 5, 12, 2, 7, 9, 4)]
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8, 16))
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for prompt, got in zip(prompts, outs):
        np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 5))


def test_incremental_submit_midstream(tiny_llama):
    """Requests submitted while others decode still come out token-exact
    (the point of continuous batching)."""
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,))
    a = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=8)
    eng.step()
    eng.step()
    b = eng.submit(np.arange(20, 25, dtype=np.int32), max_new_tokens=4)
    eng.run()
    np.testing.assert_array_equal(eng.poll(a), _reference(tiny_llama, np.arange(1, 7), 8))
    np.testing.assert_array_equal(eng.poll(b), _reference(tiny_llama, np.arange(20, 25), 4))


def test_eos_retires_slot(tiny_llama):
    prompt = np.ones((4,), np.int32)
    full = _reference(tiny_llama, prompt, 8)
    eos = int(full[6])  # a token generate actually emits
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,), eos_token_id=eos)
    [got] = eng.generate_many([prompt], max_new_tokens=8)
    # engine stops AT the eos; generate() freezes and pads with eos after it
    assert len(got) <= len(full)
    np.testing.assert_array_equal(got, full[: len(got)])
    assert got[-1] == eos
    assert eng.active_count == 0


def test_partial_streams_and_cancel(tiny_llama):
    """partial() exposes the growing suffix mid-decode; cancel() frees
    the slot immediately and the surviving request stays token-exact."""
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), tick_block=2)
    a = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=8)
    b = eng.submit(np.arange(20, 25, dtype=np.int32), max_new_tokens=8)
    assert eng.partial(a).size == 0  # queued: nothing yet
    eng.step()
    grew = eng.partial(a).size
    assert 0 < grew < 8 and eng.poll(a) is None  # mid-decode prefix of the answer
    got = eng.cancel(b)
    assert got.size >= 1  # b had started too
    eng.run()
    np.testing.assert_array_equal(eng.poll(a), _reference(tiny_llama, np.arange(1, 7), 8))
    # partial stays suffix-only after completion: a delta streamer never
    # re-emits prompt tokens on the finishing tick
    np.testing.assert_array_equal(eng.partial(a), eng.poll(a)[6:])
    assert eng.poll(b) is None  # cancelled ids never resolve
    with pytest.raises(KeyError):
        eng.partial(b)
    with pytest.raises(ValueError, match="finished"):
        eng.cancel(a)
    c = eng.submit(np.ones(3, np.int32), max_new_tokens=4)
    assert eng.cancel(c).size == 0  # cancelled straight out of the queue
    with pytest.raises(KeyError):
        eng.cancel(999)


def test_cancel_frees_paged_blocks(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,), tick_block=2, paged_block_size=4)
    free0 = eng.pool_free_blocks
    uid = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=12)
    eng.step()
    assert eng.pool_free_blocks < free0
    eng.cancel(uid)
    assert eng.pool_free_blocks == free0  # blocks returned immediately
    # slot is reusable and exact afterwards
    [out] = eng.generate_many([np.arange(9, 12, dtype=np.int32)], max_new_tokens=4)
    np.testing.assert_array_equal(out, _reference(tiny_llama, np.arange(9, 12), 4))


def test_validation_errors(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,), max_len=16)
    with pytest.raises(ValueError, match="cache"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=99)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="max_position_embeddings"):
        ServingEngine(tiny_llama, max_len=999)


def test_long_prompt_chunked_prefill(tiny_llama):
    """A prompt longer than the largest bucket streams through end-aligned
    chunk windows — output still token-exact vs static generate()."""
    prompt = (np.arange(12) % 250 + 1).astype(np.int32)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8))
    [got] = eng.generate_many([prompt], max_new_tokens=4)
    np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 4))


def test_long_prompt_unaligned_overlap(tiny_llama):
    """Length not a multiple of the chunk: the final window overlaps the
    previous one (end-aligned) and recomputes identical K/V."""
    prompt = (np.arange(13) % 250 + 1).astype(np.int32)  # C=8 -> windows [0,8), [5,13)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    [got] = eng.generate_many([prompt], max_new_tokens=3)
    np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 3))


def test_prefix_cache_token_exact(tiny_llama):
    """Two requests share a registered prefix: each copies the prefix KV
    row and prefills only its suffix; outputs equal full-prompt generate()."""
    prefix = (np.arange(6) % 250 + 3).astype(np.int32)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8))
    pid = eng.register_prefix(prefix)
    sufa = np.asarray([9, 8, 7], np.int32)
    sufb = np.asarray([11, 12], np.int32)
    a = eng.submit(sufa, max_new_tokens=5, prefix_id=pid)
    b = eng.submit(sufb, max_new_tokens=5, prefix_id=pid)
    eng.run()
    np.testing.assert_array_equal(
        eng.poll(a), _reference(tiny_llama, np.concatenate([prefix, sufa]), 5))
    np.testing.assert_array_equal(
        eng.poll(b), _reference(tiny_llama, np.concatenate([prefix, sufb]), 5))


def test_prefix_with_overlapping_window_into_prefix(tiny_llama):
    """A short suffix after a mid-length prefix: the single warm window
    starts INSIDE the prefix region and rewrites identical K/V there."""
    prefix = (np.arange(5) + 1).astype(np.int32)
    suffix = (np.arange(9) + 40).astype(np.int32)  # 5+9=14, C=8: windows [5,13)->[6,14)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    pid = eng.register_prefix(prefix)
    uid = eng.submit(suffix, max_new_tokens=2, prefix_id=pid)
    eng.run()
    np.testing.assert_array_equal(
        eng.poll(uid), _reference(tiny_llama, np.concatenate([prefix, suffix]), 2))


def test_prefix_validation_and_eviction(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,), max_len=16)
    with pytest.raises(ValueError, match="unknown prefix_id"):
        eng.submit(np.ones((2,), np.int32), prefix_id=7)
    with pytest.raises(ValueError, match="empty"):
        eng.register_prefix(np.zeros((0,), np.int32))
    pid = eng.register_prefix(np.ones((6,), np.int32))
    with pytest.raises(ValueError, match="cache"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=8, prefix_id=pid)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), prefix_id=pid)
    # eviction: refused while a queued request references it, ok after drain
    uid = eng.submit(np.asarray([3, 4], np.int32), max_new_tokens=2, prefix_id=pid)
    with pytest.raises(ValueError, match="still referenced"):
        eng.unregister_prefix(pid)
    eng.run()
    assert eng.poll(uid) is not None
    eng.unregister_prefix(pid)
    assert pid not in eng._prefixes
    with pytest.raises(ValueError, match="unknown prefix_id"):
        eng.unregister_prefix(pid)


def test_gpt2_family_works_too():
    from accelerate_tpu.models import GPT2Config, create_gpt2_model

    model = create_gpt2_model(GPT2Config.tiny(), seq_len=16)
    prompt = (np.arange(6) % 200).astype(np.int32)
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8,))
    [got] = eng.generate_many([prompt], max_new_tokens=4)
    np.testing.assert_array_equal(got, _reference(model, prompt, 4))


def test_sampling_deterministic_per_seed(tiny_llama):
    """Temperature sampling: same seed -> identical outputs, different
    seed -> different; greedy engines are unaffected by seed."""
    prompts = [np.arange(1, 7, dtype=np.int32), np.arange(30, 38, dtype=np.int32)]

    def run(seed, temperature=1.0):
        eng = ServingEngine(
            tiny_llama, num_slots=2, prompt_buckets=(8,), temperature=temperature, top_k=8, seed=seed
        )
        return eng.generate_many(prompts, max_new_tokens=6)

    a, b, c = run(1), run(1), run(2)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_top_k1_collapses_to_greedy(tiny_llama):
    prompt = (np.arange(8) % 250).astype(np.int32)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,), temperature=5.0, top_k=1)
    [got] = eng.generate_many([prompt], max_new_tokens=5)
    np.testing.assert_array_equal(got, _reference(tiny_llama, prompt, 5))


def test_serving_with_tp_sharded_model(tiny_llama):
    """The engine composes with mesh-sharded params (serving a model too
    big for one chip): TP-sharded slots produce the single-device tokens."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompt = (np.arange(8) % 250).astype(np.int32)
    want = _reference(tiny_llama, prompt, 5)

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model, MeshConfig(data=1, tensor=4).build(jax.devices()[:4]))
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8,))
    [got] = eng.generate_many([prompt], max_new_tokens=5)
    np.testing.assert_array_equal(got, want)


def test_params_update_after_construction_is_used(tiny_llama):
    """decode ticks read self.model.params at call time — swapping weights
    after engine construction changes outputs (no stale closure)."""
    import jax

    prompt = (np.arange(8) % 250).astype(np.int32)
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    [before] = eng.generate_many([prompt], max_new_tokens=5)
    old = tiny_llama.params
    try:
        tiny_llama.params = jax.tree.map(lambda p: p * 1.5, old)
        [after] = eng.generate_many([prompt], max_new_tokens=5)
    finally:
        tiny_llama.params = old
    assert not np.array_equal(before, after)


def test_bucket_and_budget_validation(tiny_llama):
    import pytest as _pytest

    with _pytest.raises(ValueError, match="bucket"):
        ServingEngine(tiny_llama, prompt_buckets=(8, 999))
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    with _pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.ones((4,), np.int32), max_new_tokens=0)


def test_gptneox_family_works_too():
    from accelerate_tpu.models import GPTNeoXConfig, create_gptneox_model

    model = create_gptneox_model(GPTNeoXConfig.tiny(), seq_len=16)
    prompt = (np.arange(6) % 200).astype(np.int32)
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8,))
    [got] = eng.generate_many([prompt], max_new_tokens=4)
    np.testing.assert_array_equal(got, _reference(model, prompt, 4))


def test_stop_sequences_end_generation(tiny_llama):
    """Per-request stop sequences (vLLM `stop` analogue at the token
    level): generation ends when the generated tail matches, the matched
    tokens stay in the output, other requests are unaffected."""
    prompt = np.ones((4,), np.int32)
    full = _reference(tiny_llama, prompt, 8)
    gen = full[len(prompt):]
    stop = [int(gen[3]), int(gen[4])]  # a 2-token run generate actually emits
    # first place the pair occurs (the engine must stop there, which is
    # positions 3-4 unless the pair also shows up earlier in this output)
    first = next(i for i in range(len(gen) - 1) if [int(gen[i]), int(gen[i + 1])] == stop)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8))
    u_stop = eng.submit(prompt, max_new_tokens=8, stop_sequences=[stop])
    u_free = eng.submit(prompt, max_new_tokens=8)
    while eng.poll(u_stop) is None or eng.poll(u_free) is None:
        eng.step()
    got_stop, got_free = eng.poll(u_stop), eng.poll(u_free)
    np.testing.assert_array_equal(got_free, full)       # no stop: full output
    assert len(got_stop) == len(prompt) + first + 2     # ends right at the match
    np.testing.assert_array_equal(got_stop, full[: len(got_stop)])
    assert list(got_stop[-2:]) == stop                  # stop tokens retained
    assert eng.active_count == 0


def test_stop_sequence_validation(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(4,))
    with pytest.raises(ValueError, match="empty stop sequence"):
        eng.submit(np.ones((2,), np.int32), stop_sequences=[[]])


def test_logprobs_match_full_context_forward(tiny_llama):
    """Per-token logprobs (vLLM-style surface): for greedy decoding they
    must equal the f32 log-softmax of a FULL-context forward at each
    generated position — one reference computation, both cache layouts."""
    import jax

    prompt = (np.arange(6) % 250).astype(np.int32)
    for kwargs in ({}, {"paged_block_size": 4}):
        eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8, 16), **kwargs)
        uid = eng.submit(prompt, max_new_tokens=5)
        while eng.poll(uid) is None:
            eng.step()
        full = eng.poll(uid)
        lps = eng.logprobs(uid)
        assert lps.shape == (5,) and lps.dtype == np.float32

        logits = tiny_llama.apply_fn(tiny_llama.params, full[None, :-1].astype(np.int32))
        ref_rows = np.asarray(logits[0], np.float32)
        for i in range(5):
            ctx = len(prompt) + i  # tokens seen before generating full[ctx]
            row = ref_rows[ctx - 1]
            want = row[full[ctx]] - np.log(np.exp(row - row.max()).sum()) - row.max()
            np.testing.assert_allclose(lps[i], want, atol=2e-3, err_msg=f"{kwargs} token {i}")


def test_logprobs_lifecycle(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    u1 = eng.submit(np.ones((4,), np.int32), max_new_tokens=3)
    u2 = eng.submit(np.ones((5,), np.int32), max_new_tokens=3)  # queued behind u1
    assert eng.logprobs(u2).shape == (0,)  # queued: empty
    while eng.poll(u1) is None:
        eng.step()
    assert len(eng.logprobs(u1)) == 3
    with pytest.raises(KeyError):
        eng.logprobs(999)


# --------------------------------------------------------------------- #
# serving metrics (telemetry/serving_metrics.py, wired by the engine)
# --------------------------------------------------------------------- #


def test_serving_metrics_counters_and_latency(tiny_llama):
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 8, 5)]
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8, 16))
    eng.generate_many(prompts, max_new_tokens=5)
    snap = eng.metrics.snapshot()
    assert snap["requests_submitted"] == 3
    assert snap["requests_completed"] == 3
    assert snap["requests_cancelled"] == 0
    assert snap["prefills"] == 3
    assert snap["tokens_generated"] == 15  # 3 requests x 5 tokens, no overshoot counted
    assert snap["queue_depth"] == 0 and snap["active_slots"] == 0
    assert snap["ttft_ms_p50"] > 0 and snap["ttft_ms_p95"] >= snap["ttft_ms_p50"]
    assert snap["e2e_ms_p50"] >= snap["ttft_ms_p50"]
    assert snap["tokens_per_sec"] > 0
    assert snap["kv_block_utilization"] is None  # dense mode


def test_serving_metrics_cancel_and_queue_depth(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=1, prompt_buckets=(8,))
    u1 = eng.submit(np.ones((4,), np.int32), max_new_tokens=4)
    u2 = eng.submit(np.ones((4,), np.int32), max_new_tokens=4)
    assert eng.metrics.queue_depth == 2
    eng.step()  # u1 admitted+decoding, u2 queued
    eng.cancel(u2)
    assert eng.metrics.requests_cancelled == 1
    eng.run()
    snap = eng.metrics.snapshot()
    assert snap["requests_submitted"] == 2
    assert snap["requests_completed"] == 1
    assert snap["requests_cancelled"] == 1


def test_serving_metrics_kv_utilization_and_preemptions(tiny_llama):
    # pool sized so request 1 takes EVERY usable block and request 2 must
    # wait; tick_block small so request 1 stays in flight across steps
    eng = ServingEngine(
        tiny_llama, num_slots=2, prompt_buckets=(8,), paged_block_size=4,
        pool_blocks=5, tick_block=2,
    )
    u1 = eng.submit(np.ones((4,), np.int32), max_new_tokens=10)
    u2 = eng.submit(np.ones((4,), np.int32), max_new_tokens=10)
    eng.step()
    util = eng.metrics.kv_block_utilization
    assert util is not None and 0.0 < util <= 1.0
    eng.run()
    assert eng.metrics.preemptions >= 1  # admission blocked at least once
    assert eng.metrics.requests_completed == 2
    assert eng.metrics.kv_block_utilization == 0.0  # all blocks returned


def test_serving_metrics_prometheus_exposition(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,))
    eng.generate_many([np.ones((4,), np.int32)], max_new_tokens=3)
    text = eng.metrics.prometheus_text()
    assert "# HELP accelerate_tpu_serving_ttft_ms" in text
    assert "# TYPE accelerate_tpu_serving_requests_submitted_total counter" in text
    assert "accelerate_tpu_serving_requests_completed_total 1" in text
    assert "accelerate_tpu_serving_tokens_generated_total 3" in text
    assert 'accelerate_tpu_serving_ttft_ms{quantile="0.5"}' in text
    # every sample line parses as "name[{labels}] value"
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, value = line.rsplit(" ", 1)
        float(value)


def test_serving_metrics_replica_label(tiny_llama):
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,))
    eng.metrics.replica = "r7"
    eng.generate_many([np.ones((4,), np.int32)], max_new_tokens=3)
    text = eng.metrics.prometheus_text()
    assert 'accelerate_tpu_serving_requests_completed_total{replica="r7"} 1' in text
    assert 'accelerate_tpu_serving_ttft_ms{replica="r7",quantile="0.5"}' in text
    assert 'accelerate_tpu_serving_ttft_ms_count{replica="r7"} 1' in text
    assert eng.metrics.snapshot()["replica"] == "r7"


def test_serving_metrics_merge_aggregates_fleet_view(tiny_llama):
    from accelerate_tpu.telemetry.serving_metrics import ServingMetrics, fleet_prometheus_text

    engines = []
    for name in ("r0", "r1"):
        eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,))
        eng.metrics.replica = name
        eng.generate_many([np.ones((4,), np.int32)], max_new_tokens=3)
        engines.append(eng)
    merged = ServingMetrics.merge([e.metrics for e in engines])
    assert merged.requests_completed == 2
    assert merged.tokens_generated == 6
    # pooled latency windows: fleet percentiles see every replica's samples
    assert len(merged.ttft_ms) == 2
    snap = merged.snapshot()
    assert snap["replica"] == "fleet" and snap["requests_completed"] == 2
    text = merged.prometheus_text()
    assert 'accelerate_tpu_serving_tokens_generated_total{replica="fleet"} 6' in text
    # one scrape body for the whole fleet: ONE HELP/TYPE block per metric,
    # one labeled sample per replica
    fleet_text = fleet_prometheus_text([e.metrics for e in engines])
    assert fleet_text.count("# TYPE accelerate_tpu_serving_requests_completed_total counter") == 1
    assert 'requests_completed_total{replica="r0"} 1' in fleet_text
    assert 'requests_completed_total{replica="r1"} 1' in fleet_text
    for line in fleet_text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, value = line.rsplit(" ", 1)
        float(value)


def test_serving_metrics_mirror_to_event_log(tiny_llama, tmp_path):
    from accelerate_tpu.telemetry import EventLog, read_events

    log = EventLog(str(tmp_path / "serve.jsonl"), rank=0)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(8,), telemetry_log=log)
    eng.generate_many([np.ones((4,), np.int32)], max_new_tokens=3)
    eng.metrics.emit()
    log.close()
    events = read_events(str(tmp_path / "serve.jsonl"))
    names = {e["name"] for e in events}
    assert "serving.requests_completed" in names and "serving.tokens_generated" in names
    # and the summarize CLI surface understands them
    from accelerate_tpu.telemetry import render_text, summarize

    report = summarize(events)
    assert report["serving"]["requests_completed"] == 1
    assert "tokens_generated" in render_text(report)
