"""GPipe pipeline parallelism tests (reference parity: prepare_pippy,
inference.py:124 — except ours is also differentiable/trainable)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import MeshConfig
from accelerate_tpu.parallel.pipeline import (
    PipelinedModel,
    pipeline_apply,
    prepare_pipeline,
    stage_sharding,
)


def _layer_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"]) + h


def _stack(n_layers=8, width=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    return {
        "w": jax.random.normal(ks[0], (n_layers, width, width)) * 0.1,
        "b": jax.random.normal(ks[1], (n_layers, width)) * 0.01,
    }


def _sequential(params, x):
    def body(h, p):
        return _layer_fn(p, h), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("mesh_cfg", [dict(pipe=4, data=2), dict(pipe=8), dict(pipe=2)])
@pytest.mark.parametrize("num_microbatches", [1, 4])
def test_matches_sequential(mesh_cfg, num_microbatches):
    mesh = MeshConfig(**mesh_cfg).build()
    params = _stack()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
    ref = _sequential(params, x)
    sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)
    out = jax.jit(
        lambda p, x: pipeline_apply(
            _layer_fn, p, x, mesh=mesh, num_microbatches=num_microbatches
        )
    )(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    mesh = MeshConfig(pipe=4).build()
    params = _stack(n_layers=4, width=8)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8))

    def loss_pipe(p, x):
        return pipeline_apply(_layer_fn, p, x, mesh=mesh, num_microbatches=2).sum()

    def loss_ref(p, x):
        return _sequential(p, x).sum()

    g_pipe = jax.jit(jax.grad(loss_pipe))(
        jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params), x
    )
    g_ref = jax.grad(loss_ref)(params, x)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_neighbour_traffic_only():
    """The schedule must move activations via collective-permute, never
    all-gather the stacked trunk params."""
    mesh = MeshConfig(pipe=4).build()
    params = _stack()
    x = jnp.zeros((8, 16))
    sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)
    fn = jax.jit(lambda p, x: pipeline_apply(_layer_fn, p, x, mesh=mesh, num_microbatches=4))
    hlo = fn.lower(sharded, x).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo, "pipeline must not all-gather stage params"


def test_prepare_pipeline_end_to_end():
    """pre (embed) -> pipelined trunk -> post (head), the prepare_pippy-shaped
    API, with batch sharded over data and trunk over pipe."""
    mesh = MeshConfig(pipe=4, data=2).build()
    width, vocab = 16, 11
    k = jax.random.PRNGKey(3)
    params = {
        "pre": jax.random.normal(k, (vocab, width)) * 0.1,
        "layers": _stack(n_layers=8, width=width),
        "post": jax.random.normal(k, (width, vocab)) * 0.1,
    }

    def pre_fn(p, ids):
        return p[ids], ()

    def post_fn(p, h):
        return h @ p

    pm = prepare_pipeline(
        pre_fn, lambda p, h: _layer_fn(p, h), post_fn, params, mesh=mesh, num_microbatches=2
    )
    assert isinstance(pm, PipelinedModel)
    ids = jnp.arange(8) % vocab
    out = jax.jit(pm)(pm.params, ids)

    ref = post_fn(params["post"], _sequential(params["layers"], pre_fn(params["pre"], ids)[0]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
    # trunk params physically live one stage per device group
    leaf = pm.params["layers"]["w"]
    assert leaf.sharding.spec == jax.sharding.PartitionSpec("pipe")


def test_rejects_indivisible():
    mesh = MeshConfig(pipe=4).build()
    params = _stack(n_layers=6)
    x = jnp.zeros((8, 16))
    with pytest.raises(ValueError):
        pipeline_apply(_layer_fn, params, x, mesh=mesh, num_microbatches=2)
    with pytest.raises(ValueError):
        pipeline_apply(_layer_fn, _stack(n_layers=8), jnp.zeros((3, 16)), mesh=mesh, num_microbatches=2)


def test_trivial_pipe_axis():
    mesh = MeshConfig(data=8).build()
    params = _stack()
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 16))
    out = jax.jit(lambda p, x: pipeline_apply(_layer_fn, p, x, mesh=mesh, num_microbatches=2))(
        params, x
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(params, x)), atol=1e-5, rtol=1e-5)


def test_batch_shaped_broadcast_arg():
    """broadcast_args sharing the batch dim (e.g. position ids) must be
    microbatched per-stage alongside the activation."""
    mesh = MeshConfig(pipe=4, data=2).build()
    params = _stack(n_layers=8, width=16)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 16))
    pos = jax.random.normal(jax.random.PRNGKey(6), (16, 16))  # [B, W] extra

    def layer_with_pos(p, h, pos):
        return jnp.tanh(h @ p["w"] + p["b"] + pos) + h

    def seq(params, x, pos):
        def body(h, p):
            return layer_with_pos(p, h, pos), None

        out, _ = jax.lax.scan(body, x, params)
        return out

    sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)
    out = jax.jit(
        lambda p, x, pos: pipeline_apply(
            layer_with_pos, p, x, mesh=mesh, num_microbatches=4, broadcast_args=(pos,)
        )
    )(sharded, x, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(seq(params, x, pos)), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("interleave", [2, 4])
def test_interleaved_matches_sequential(interleave):
    """interleave splits each microbatch into row blocks so per-block
    ppermutes overlap the other blocks' compute — results must be
    IDENTICAL to the plain schedule."""
    mesh = MeshConfig(pipe=4, data=2).build()
    params = _stack()
    x = jax.random.normal(jax.random.PRNGKey(7), (16, 16))
    ref = _sequential(params, x)
    sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)
    out = jax.jit(
        lambda p, x: pipeline_apply(
            _layer_fn, p, x, mesh=mesh, num_microbatches=2, interleave=interleave
        )
    )(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_interleaved_with_batched_arg_and_grad():
    mesh = MeshConfig(pipe=4).build()
    params = _stack(n_layers=4, width=8)
    x = jax.random.normal(jax.random.PRNGKey(8), (8, 8))
    pos = jax.random.normal(jax.random.PRNGKey(9), (8, 8))

    def layer_with_pos(p, h, pos):
        return jnp.tanh(h @ p["w"] + p["b"] + pos) + h

    def seq(params, x, pos):
        def body(h, p):
            return layer_with_pos(p, h, pos), None

        return jax.lax.scan(body, x, params)[0]

    sharded = jax.tree.map(lambda l: jax.device_put(l, stage_sharding(mesh)), params)

    def loss(p, x):
        return pipeline_apply(
            layer_with_pos, p, x, mesh=mesh, num_microbatches=2,
            broadcast_args=(pos,), interleave=2,
        ).sum()

    g = jax.jit(jax.grad(loss))(sharded, x)
    g_ref = jax.grad(lambda p, x: seq(p, x, pos).sum())(params, x)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)
