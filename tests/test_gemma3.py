"""Gemma3 family (models/gemma3.py): dual rope bases + per-head qk-norm +
5:1 local/global attention through decode and serving. HF importer parity
lives in test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Gemma3Config, create_gemma3_model


@pytest.fixture(scope="module")
def tiny_gemma3():
    return create_gemma3_model(Gemma3Config.tiny(), seq_len=32)


def test_structure(tiny_gemma3):
    cfg = Gemma3Config.tiny()
    assert cfg.layer_types == ("sliding_attention", "full_attention")
    assert cfg.rope_local_theta == 10_000.0 and cfg.rope_theta == 1_000_000.0
    layer0 = tiny_gemma3.params["layer_0"]
    for norm in ("input_norm", "post_attn_norm", "pre_ffn_norm", "post_ffn_norm"):
        assert norm in layer0, norm  # the sandwich
    assert layer0["attn"]["q_norm"]["scale"].shape == (cfg.head_dim,)  # per-head
    assert "lm_head" not in tiny_gemma3.params  # always tied


def test_default_pattern_is_five_to_one():
    cfg = Gemma3Config(num_hidden_layers=12)
    assert cfg.layer_types.count("full_attention") == 2
    assert cfg.layer_types[5] == "full_attention" and cfg.layer_types[11] == "full_attention"


def test_greedy_decode_matches_full_prefix(tiny_gemma3):
    """The cached decode path must apply the per-layer theta AND the band
    exactly like the full forward — token equality past the window."""
    ids = (np.arange(2 * 12).reshape(2, 12) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_gemma3, ids, max_new_tokens=8))
    full = ids
    for _ in range(8):
        logits = np.asarray(tiny_gemma3(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_serving(tiny_gemma3):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 12, 6)]
    eng = ServingEngine(tiny_gemma3, num_slots=2, prompt_buckets=(4, 8, 16))
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_gemma3, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)
