"""Collective/pytree op tests (reference analogue: tests/test_utils.py ops
section + test_utils/scripts/test_ops.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils import (
    broadcast,
    broadcast_object_list,
    concatenate,
    convert_outputs_to_fp32,
    convert_to_fp32,
    find_batch_size,
    gather,
    gather_object,
    pad_across_processes,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
)


def test_send_to_device_pytree():
    batch = {"x": np.ones((4, 2)), "y": [np.zeros(3), np.arange(5)], "meta": "keep"}
    out = send_to_device(batch)
    assert isinstance(out["x"], jax.Array)
    assert out["meta"] == "keep"
    np.testing.assert_array_equal(np.asarray(out["y"][1]), np.arange(5))


def test_send_to_device_with_sharding(mesh8):
    sharding = NamedSharding(mesh8, P("data"))
    out = send_to_device(np.ones((16, 2)), sharding)
    assert out.sharding == sharding


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(2), "skip": np.ones(2)}
    out = send_to_device(batch, skip_keys=["skip"])
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["skip"], np.ndarray)


def test_gather_sharded_array(mesh8):
    x = jax.device_put(np.arange(16.0).reshape(16, 1), NamedSharding(mesh8, P("data")))
    out = gather(x)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(16.0).reshape(16, 1))


def test_gather_object_single_process():
    assert gather_object([1, "a"]) == [1, "a"]


def test_broadcast_single_process():
    x = np.ones((3,))
    np.testing.assert_array_equal(broadcast(x), x)
    objs = [1, 2]
    assert broadcast_object_list(objs) == [1, 2]


def test_reduce_mean_sharded(mesh8):
    x = jax.device_put(np.full((8, 2), 3.0), NamedSharding(mesh8, P("data")))
    out = reduce(x, "mean")
    np.testing.assert_allclose(out, np.full((8, 2), 3.0))


def test_pad_across_processes_noop_single():
    x = np.ones((3, 2))
    np.testing.assert_array_equal(pad_across_processes(x, dim=0), x)


def test_pad_input_tensors():
    x = {"a": np.arange(10).reshape(10, 1)}
    out = pad_input_tensors(x, batch_size=10, num_processes=4)
    assert out["a"].shape[0] == 12
    np.testing.assert_array_equal(out["a"][10:].ravel(), [0, 1])


def test_find_batch_size():
    assert find_batch_size({"x": np.ones((5, 3)), "y": np.ones((5,))}) == 5
    assert find_batch_size({"x": 1}) is None


def test_convert_to_fp32():
    tree = {"a": jnp.ones(2, dtype=jnp.bfloat16), "b": jnp.ones(2, dtype=jnp.int32)}
    out = convert_to_fp32(tree)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == jnp.int32


def test_convert_outputs_to_fp32_wrapper():
    fn = convert_outputs_to_fp32(lambda x: {"out": x.astype(jnp.bfloat16)})
    out = fn(jnp.ones(3))
    assert out["out"].dtype == jnp.float32


def test_concatenate_dicts():
    a = {"x": np.ones((2, 3))}
    b = {"x": np.zeros((4, 3))}
    out = concatenate([a, b])
    assert out["x"].shape == (6, 3)


def test_recursively_apply_error_on_other_type():
    with pytest.raises(TypeError):
        recursively_apply(lambda x: x, {"a": object()}, error_on_other_type=True)


# ---------------------------------------------------------------------- #
# expanded op coverage (reference: tests/test_utils.py, 47 tests over the
# ops surface — slice/concat/pad/init/structure helpers)
# ---------------------------------------------------------------------- #


def test_get_data_structure_and_initialize_roundtrip():
    from accelerate_tpu.utils.operations import get_data_structure, initialize_tensors

    data = {"a": np.ones((2, 3), np.float32), "b": [np.zeros((4,), np.int32)]}
    skeleton = get_data_structure(data)
    rebuilt = initialize_tensors(skeleton)
    assert rebuilt["a"].shape == (2, 3) and rebuilt["a"].dtype == np.float32
    assert rebuilt["b"][0].shape == (4,) and rebuilt["b"][0].dtype == np.int32


def test_slice_tensors_per_process():
    from accelerate_tpu.utils.operations import slice_tensors

    data = {"x": np.arange(8).reshape(8, 1)}
    out = slice_tensors(data, slice(2, 6))
    np.testing.assert_array_equal(np.asarray(out["x"]).ravel(), [2, 3, 4, 5])


def test_concatenate_nested_and_mismatch():
    a = {"x": np.ones((2, 3)), "y": [np.zeros((2,))]}
    b = {"x": np.ones((4, 3)), "y": [np.zeros((1,))]}
    out = concatenate([a, b])
    assert out["x"].shape == (6, 3) and out["y"][0].shape == (3,)


def test_pad_across_processes_dim_and_pad_first():
    x = jnp.arange(6.0).reshape(2, 3)
    same = pad_across_processes(x, dim=0)
    np.testing.assert_array_equal(np.asarray(same), np.asarray(x))  # single process: no-op
    # out-of-range dim is a no-op, matching the reference's guard
    assert pad_across_processes(x, dim=5).shape == x.shape


def test_pad_input_tensors_uneven_and_exact():
    x = np.arange(10).reshape(10, 1)
    padded = pad_input_tensors(x, batch_size=10, num_processes=4)
    assert padded.shape[0] == 12  # ceil(10/4)*4
    np.testing.assert_array_equal(np.asarray(padded[:10]), x)
    exact = pad_input_tensors(x, batch_size=10, num_processes=5)
    assert exact.shape[0] == 10  # already divisible


def test_find_batch_size_priority_and_none():
    assert find_batch_size({"a": np.ones((7, 2)), "b": np.ones((7,))}) == 7
    assert find_batch_size([np.ones((3, 2))]) == 3
    assert find_batch_size({"s": "str"}) is None


def test_convert_to_fp32_leaves_ints_alone():
    out = convert_to_fp32({"f": jnp.ones(2, jnp.bfloat16), "i": jnp.ones(2, jnp.int32)})
    assert out["f"].dtype == jnp.float32
    assert out["i"].dtype == jnp.int32


def test_broadcast_object_list_single_process():
    objs = ["a", {"b": 1}]
    out = broadcast_object_list(list(objs))
    assert out == objs


def test_reduce_sum_and_scale(mesh8):
    AcceleratorState()
    sharding = NamedSharding(AcceleratorState().mesh, P(("data",)))
    x = jax.device_put(jnp.ones(8), sharding)
    out = reduce(x, "sum", scale=0.5)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 0.5))


def test_gather_preserves_structure(mesh8):
    AcceleratorState()
    sharding = NamedSharding(AcceleratorState().mesh, P(("data",)))
    tree = {"a": jax.device_put(jnp.arange(8.0), sharding), "n": [jax.device_put(jnp.ones((8, 2)), sharding)]}
    out = gather(tree)
    assert set(out.keys()) == {"a", "n"}
    assert np.asarray(out["n"][0]).shape == (8, 2)


def test_recursively_apply_namedtuple():
    import collections

    Point = collections.namedtuple("Point", ["x", "y"])
    p = Point(np.ones(2), np.zeros(3))
    out = recursively_apply(lambda t: t + 1, p)
    assert isinstance(out, Point)
    np.testing.assert_array_equal(np.asarray(out.x), np.full(2, 2.0))
