"""Data loader tests (reference analogue: tests/test_data_loader.py, 897 LoC
of BatchSamplerShard index math; here the invariants are: global arrays with
correct batch sharding, seedable cross-epoch shuffling, remainder
bookkeeping, skip_first_batches resume)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    DataLoaderShard,
    IterableDataLoaderShard,
    SeedableRandomSampler,
    default_collate,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import AcceleratorState, GradientState


class ToyDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return {"x": np.float32(i), "y": np.float32(2 * i)}


def global_values(batch):
    return np.asarray(jax.device_get(batch["x"])).ravel().tolist()


def test_even_dataset_batches(mesh8):
    AcceleratorState()
    dl = DataLoaderShard(ToyDataset(32), batch_size=2)  # global batch = 16
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (16,)
    # sharded over the data axis
    assert len(batches[0]["x"].sharding.device_set) == 8
    assert global_values(batches[0]) == [float(i) for i in range(16)]


def test_remainder_and_padding(mesh8):
    AcceleratorState()
    gs = GradientState()
    dl = DataLoaderShard(ToyDataset(20), batch_size=2)  # 16 + 4 -> padded batch
    batches = []
    remainders = []
    for b in dl:
        batches.append(b)
        remainders.append((gs.end_of_dataloader, gs.remainder))
    assert len(batches) == 2
    assert remainders[0] == (False, -1)
    assert remainders[1] == (True, 4)
    # padded batch wraps around from batch start
    vals = global_values(batches[1])
    assert vals[:4] == [16.0, 17.0, 18.0, 19.0]
    assert len(vals) == 16


def test_drop_last(mesh8):
    AcceleratorState()
    dl = DataLoaderShard(ToyDataset(20), batch_size=2, drop_last=True)
    assert len(list(dl)) == 1
    assert len(dl) == 1


def test_shuffle_is_seeded_and_epoch_varies(mesh8):
    AcceleratorState()
    dl = DataLoaderShard(ToyDataset(16), batch_size=2, shuffle=True, seed=7)
    epoch0 = [v for b in dl for v in global_values(b)]
    epoch1 = [v for b in dl for v in global_values(b)]
    assert sorted(epoch0) == [float(i) for i in range(16)]
    assert epoch0 != epoch1  # set_epoch advanced
    # reproducible: fresh loader with same seed gives same epoch-0 order
    dl2 = DataLoaderShard(ToyDataset(16), batch_size=2, shuffle=True, seed=7)
    epoch0_again = [v for b in dl2 for v in global_values(b)]
    assert epoch0 == epoch0_again


def test_skip_first_batches(mesh8):
    AcceleratorState()
    dl = DataLoaderShard(ToyDataset(32), batch_size=2)
    all_batches = [global_values(b) for b in dl]
    skip_first_batches(dl, 1)
    resumed = [global_values(b) for b in dl]
    assert resumed == all_batches[1:]
    # skip resets after one epoch
    assert len(list(dl)) == 2


def test_iterable_loader(mesh8):
    AcceleratorState()

    def gen():
        for i in range(20):
            yield {"x": np.float32(i)}

    dl = IterableDataLoaderShard(gen(), batch_size=2)
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (16,)
    assert dl.remainder == 4


def test_gradient_state_registration(mesh8):
    AcceleratorState()
    gs = GradientState()
    dl = DataLoaderShard(ToyDataset(16), batch_size=2)
    assert not gs.in_dataloader
    for _ in dl:
        assert gs.in_dataloader
    assert not gs.in_dataloader


def test_prepare_data_loader_idempotent(mesh8):
    AcceleratorState()
    dl = prepare_data_loader(ToyDataset(16), batch_size=2)
    assert prepare_data_loader(dl) is dl


def test_prepare_from_torch_loader(mesh8):
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader as TorchDL

    class TDS(torch.utils.data.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return {"x": torch.tensor(float(i))}

    AcceleratorState()
    tdl = TorchDL(TDS(), batch_size=2, shuffle=False)
    dl = prepare_data_loader(tdl)
    batches = list(dl)
    assert batches[0]["x"].shape == (16,)
    assert global_values(batches[0]) == [float(i) for i in range(16)]


def test_seedable_sampler_epochs():
    s = SeedableRandomSampler(10, seed=3)
    order0 = list(s)
    s.set_epoch(1)
    assert list(s) != order0
    s.set_epoch(0)
    assert list(s) == order0


def test_collate_tuples():
    out = default_collate([(np.float32(1), np.float32(2)), (np.float32(3), np.float32(4))])
    assert isinstance(out, tuple)
    np.testing.assert_array_equal(out[0], [1, 3])


# ---------------------------------------------------------------------- #
# Exhaustive index math (reference: tests/test_data_loader.py's
# BatchSamplerShard sweeps across length x batch x drop_last x
# even_batches — 897 LoC of explicit expectations; here the same space is
# swept against invariants)
# ---------------------------------------------------------------------- #


def _host_batches(dl):
    """Raw host-side batches (device_placement=False): pure index math."""
    return [[int(v) for v in np.asarray(b["x"]).ravel()] for b in dl]


@pytest.mark.parametrize("drop_last", [False, True])
@pytest.mark.parametrize("even_batches", [False, True])
@pytest.mark.parametrize("split_batches", [False, True])
def test_index_math_sweep(drop_last, even_batches, split_batches):
    import math

    for length in (1, 2, 7, 16, 20, 31, 32, 33, 61):
        for batch_size in (1, 2, 4, 8):
            dl = DataLoaderShard(
                ToyDataset(length),
                batch_size=batch_size,
                drop_last=drop_last,
                even_batches=even_batches,
                split_batches=split_batches,
                device_placement=False,
            )
            g = dl.total_batch_size
            assert g == batch_size  # single shard: split or not, g == batch_size
            batches = _host_batches(dl)
            ctx = f"len={length} bs={batch_size} drop={drop_last} even={even_batches} split={split_batches}"

            # __len__ contract
            assert len(batches) == len(dl), ctx
            expected_n = length // g if drop_last else math.ceil(length / g)
            assert len(batches) == expected_n, ctx

            # every full batch is the exact consecutive index run
            for bi, batch in enumerate(batches[:-1] if batches else []):
                assert batch == list(range(bi * g, (bi + 1) * g)), ctx

            if not batches:
                continue
            last = batches[-1]
            rem = length % g
            if drop_last or rem == 0:
                assert last == list(range((len(batches) - 1) * g, len(batches) * g)), ctx
            elif even_batches:
                # wrap-around pad to the full global batch
                tail = list(range(length - rem, length))
                assert len(last) == g, ctx
                assert last[:rem] == tail, ctx
                if length >= g - rem:
                    assert last[rem:] == list(range(g - rem)), ctx
                else:
                    # dataset smaller than the pad: wraparound cycles it
                    assert set(last[rem:]) <= set(range(length)), ctx
            else:
                # minimal pad to a shard multiple (1 shard -> no pad)
                assert last == list(range(length - rem, length)), ctx

            # coverage: every real (non-dropped) index appears; padding may
            # duplicate rows, so this is a subset check, not exact-once
            covered = set(i for b in batches for i in b)
            expect = set(range((length // g) * g if drop_last else length))
            assert expect <= covered, ctx


def test_index_math_sharded_mesh(mesh8):
    """Same invariants with 8 data shards: global batch grows, padded tail
    is a multiple of the shard count, remainder reports REAL rows."""
    AcceleratorState()
    gs = GradientState()
    for length, batch_size in ((61, 2), (33, 1), (20, 2)):
        dl = DataLoaderShard(ToyDataset(length), batch_size=batch_size)
        g = dl.total_batch_size
        assert g == batch_size * 8
        seen = []
        remainder = None
        for b in dl:
            assert b["x"].shape[0] == g  # never ragged
            seen.extend(global_values(b))
            if gs.end_of_dataloader:
                remainder = gs.remainder
        rem = length % g
        assert remainder == (rem if rem else -1), (length, batch_size, remainder)
        assert set(range(length)) <= set(int(v) for v in seen)


# ---------------------------------------------------------------------- #
# Mode-equivalence matrix: the iterable loader and the dispatcher must
# produce exactly the batches the map-style shard loader produces, across
# batch_size x drop_last x even_batches x split_batches x skip, including
# mid-epoch resume (reference: test_data_loader.py dispatcher/iterable
# sweeps + test_sync.py resume).
# ---------------------------------------------------------------------- #


def _make_loader(kind, length, **kw):
    """kind: map | iterable | dispatch_map | dispatch_iter — all host-only."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher

    kw.setdefault("device_placement", False)
    if kind == "map":
        return DataLoaderShard(ToyDataset(length), **kw)
    if kind == "iterable":
        return IterableDataLoaderShard([{"x": np.float32(i)} for i in range(length)], **kw)
    if kind == "dispatch_map":
        return DataLoaderDispatcher(DataLoaderShard(ToyDataset(length), **kw))
    if kind == "dispatch_iter":
        return DataLoaderDispatcher(
            IterableDataLoaderShard([{"x": np.float32(i)} for i in range(length)], **kw)
        )
    raise AssertionError(kind)


@pytest.mark.parametrize("kind", ["iterable", "dispatch_map", "dispatch_iter"])
@pytest.mark.parametrize("drop_last", [False, True])
@pytest.mark.parametrize("even_batches", [False, True])
def test_mode_equivalence_matrix(kind, drop_last, even_batches):
    """Every non-map mode yields the same index stream as the map loader."""
    for length in (1, 7, 16, 20, 33):
        for batch_size in (1, 4, 8):
            for split_batches in (False, True):
                kw = dict(
                    batch_size=batch_size,
                    drop_last=drop_last,
                    even_batches=even_batches,
                    split_batches=split_batches,
                )
                ref = _host_batches(_make_loader("map", length, **kw))
                got = _host_batches(_make_loader(kind, length, **kw))
                assert got == ref, (
                    f"{kind} len={length} bs={batch_size} drop={drop_last} "
                    f"even={even_batches} split={split_batches}: {got} != {ref}"
                )


@pytest.mark.parametrize("kind", ["map", "iterable", "dispatch_map", "dispatch_iter"])
@pytest.mark.parametrize("drop_last", [False, True])
def test_skip_first_batches_matrix(kind, drop_last):
    """skip_first_batches(k) == uninterrupted[k:], for every k through (and
    past) the end, in every mode. The k-lands-on-the-tail corner included."""
    for length, batch_size in ((20, 8), (33, 8), (16, 4)):
        full = _host_batches(
            _make_loader(kind, length, batch_size=batch_size, drop_last=drop_last)
        )
        for k in range(len(full) + 2):
            dl = _make_loader(kind, length, batch_size=batch_size, drop_last=drop_last)
            skip_first_batches(dl, k)
            got = _host_batches(dl)
            assert got == full[k:], f"{kind} len={length} drop={drop_last} skip={k}"
            # skip is consumed: the next epoch is complete again
            assert _host_batches(dl) == full, f"{kind} skip not reset after epoch"


@pytest.mark.parametrize("kind", ["map", "iterable", "dispatch_map", "dispatch_iter"])
def test_state_dict_resume_matrix(kind):
    """Break mid-epoch, save state, rebuild, load: the resumed run must
    deliver exactly the remaining batches (the dispatch+resume corner)."""
    length, batch_size, stop_after = 33, 4, 3
    full = _host_batches(_make_loader(kind, length, batch_size=batch_size))
    dl = _make_loader(kind, length, batch_size=batch_size)
    seen = []
    for b in dl:
        seen.append([int(v) for v in np.asarray(b["x"]).ravel()])
        if len(seen) == stop_after:
            break
    state = dl.state_dict()
    assert state["batches_yielded"] == stop_after

    dl2 = _make_loader(kind, length, batch_size=batch_size)
    dl2.load_state_dict(state)
    resumed = _host_batches(dl2)
    assert seen + resumed == full, f"{kind}: resume diverged"


@pytest.mark.parametrize("kind", ["map", "iterable"])
def test_remainder_matrix(kind):
    """remainder reports REAL rows of the padded tail (or -1 when exact),
    for both padding policies, in shard and dispatch modes."""
    import math

    gs = GradientState()
    for length, batch_size, even in ((20, 8, True), (20, 8, False), (16, 8, True), (3, 8, True)):
        dl = _make_loader(kind, length, batch_size=batch_size, even_batches=even)
        tail_remainder = None
        for _ in dl:
            if gs.end_of_dataloader:
                tail_remainder = gs.remainder
        rem = length % dl.total_batch_size
        # remainder = real rows of the tail, but only when the tail was
        # actually padded (gather_for_metrics truncation); an unpadded short
        # tail (even_batches=False on a shard-multiple) reports -1
        padded_to = dl.total_batch_size if even else math.ceil(rem / dl._num_shards()) * dl._num_shards()
        expect = rem if (rem and padded_to != rem) else -1
        assert tail_remainder == expect, (kind, length, batch_size, even)


def test_iterable_split_batches_means_global():
    """split_batches: batch_size IS the global batch (reference
    data_loader.py:996 semantics), identically for the iterable loader."""
    dl = _make_loader("iterable", 16, batch_size=8, split_batches=True)
    assert dl.total_batch_size == 8
    assert [len(b) for b in _host_batches(dl)] == [8, 8]


def test_prepare_data_loader_dispatch_iterable():
    """prepare_data_loader(dispatch_batches=True) accepts a pure stream."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher

    def gen():
        for i in range(20):
            yield {"x": np.float32(i)}

    dl = prepare_data_loader(gen(), batch_size=4, dispatch_batches=True, put_on_device=False)
    assert isinstance(dl, DataLoaderDispatcher)
    batches = _host_batches(dl)
    assert batches[0] == [0, 1, 2, 3]


def test_even_batches_false_pads_to_shard_multiple(mesh8):
    """even_batches=False: the tail batch shrinks to ceil(rem/shards)*shards
    (static shapes — never ragged) instead of the full global batch."""
    import math

    AcceleratorState()
    dl = DataLoaderShard(ToyDataset(20), batch_size=2, even_batches=False)
    batches = list(dl)
    rem = 20 % dl.total_batch_size  # 4
    expected_tail = math.ceil(rem / 8) * 8  # 8
    assert batches[-1]["x"].shape[0] == expected_tail
    assert batches[0]["x"].shape[0] == dl.total_batch_size
