"""FP8 training path (reference analogue: benchmarks/fp8/* loss-parity
scripts + tests/test_fp8.py — accelerate's fp8 integration must track the
bf16 loss curve)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.ops.fp8 import _fp8_matmul, fp8_dot_general, fp8_enabled, policy_dot_general


def test_fp8_matmul_close_to_fp32():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(8, 64)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    exact = a @ b
    approx = _fp8_matmul(a, b)
    # e4m3 has ~2 decimal digits; relative error on a 64-deep dot stays small
    rel = float(jnp.max(jnp.abs(approx - exact)) / jnp.max(jnp.abs(exact)))
    assert rel < 0.05, rel


def test_fp8_matmul_grads_close_to_fp32():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def loss8(a, b):
        return jnp.sum(_fp8_matmul(a, b) ** 2)

    def loss32(a, b):
        return jnp.sum((a @ b) ** 2)

    g8 = jax.grad(loss8, argnums=(0, 1))(a, b)
    g32 = jax.grad(loss32, argnums=(0, 1))(a, b)
    for q, e in zip(g8, g32):
        rel = float(jnp.max(jnp.abs(q - e)) / (jnp.max(jnp.abs(e)) + 1e-9))
        assert rel < 0.1, rel


def test_fp8_dot_general_fallback_patterns():
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(2, 3, 5)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(5, 7)).astype(np.float32))
    # Dense pattern routes through fp8
    dn = (((2,), (0,)), ((), ()))
    out = fp8_dot_general(a, b, dn)
    assert out.shape == (2, 3, 7)
    # non-Dense pattern (batched) falls back to exact lax.dot_general
    c = jnp.asarray(rng.normal(size=(2, 5, 3)).astype(np.float32))
    d = jnp.asarray(rng.normal(size=(2, 3, 4)).astype(np.float32))
    dn_b = (((2,), (1,)), ((0,), (0,)))
    np.testing.assert_allclose(
        fp8_dot_general(c, d, dn_b), jax.lax.dot_general(c, d, dn_b), rtol=1e-6
    )


def _train_bert_tiny(mixed_precision, steps=12):
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model

    acc = Accelerator(mixed_precision=mixed_precision)
    model = acc.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=16, seed=0))
    acc.prepare_optimizer(optax.adamw(5e-4))
    step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, 64, size=(16, 16)).astype(np.int32),
        "attention_mask": np.ones((16, 16), np.bool_),
        "labels": rng.integers(0, 2, size=(16,)).astype(np.int32),
    }
    return [float(step(batch)) for _ in range(steps)]


def test_fp8_policy_enabled_via_mixed_precision():
    from accelerate_tpu.state import AcceleratorState

    assert not fp8_enabled()
    Accelerator(mixed_precision="fp8")
    assert fp8_enabled()
    assert policy_dot_general() is fp8_dot_general
    AcceleratorState._reset_state()


def test_fp8_loss_parity_vs_bf16():
    """mixed_precision="fp8" must track the bf16 loss curve on BERT-tiny
    (the reference's benchmarks/fp8 parity bar)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    losses_bf16 = _train_bert_tiny("bf16")
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    losses_fp8 = _train_bert_tiny("fp8")

    # both converge and the curves stay close
    assert losses_fp8[-1] < 0.5 * losses_fp8[0]
    for lb, lf in zip(losses_bf16, losses_fp8):
        assert abs(lb - lf) < 0.1, (losses_bf16, losses_fp8)
