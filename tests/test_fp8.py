"""FP8 training path (reference analogue: benchmarks/fp8/* loss-parity
scripts + tests/test_fp8.py — accelerate's fp8 integration must track the
bf16 loss curve)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.ops.fp8 import _fp8_matmul, fp8_dot_general, fp8_enabled, policy_dot_general


def test_fp8_matmul_close_to_fp32():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(8, 64)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    exact = a @ b
    approx = _fp8_matmul(a, b)
    # e4m3 has ~2 decimal digits; relative error on a 64-deep dot stays small
    rel = float(jnp.max(jnp.abs(approx - exact)) / jnp.max(jnp.abs(exact)))
    assert rel < 0.05, rel


def test_fp8_matmul_grads_close_to_fp32():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def loss8(a, b):
        return jnp.sum(_fp8_matmul(a, b) ** 2)

    def loss32(a, b):
        return jnp.sum((a @ b) ** 2)

    g8 = jax.grad(loss8, argnums=(0, 1))(a, b)
    g32 = jax.grad(loss32, argnums=(0, 1))(a, b)
    for q, e in zip(g8, g32):
        rel = float(jnp.max(jnp.abs(q - e)) / (jnp.max(jnp.abs(e)) + 1e-9))
        assert rel < 0.1, rel


def test_fp8_dot_general_fallback_patterns():
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=(2, 3, 5)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(5, 7)).astype(np.float32))
    # Dense pattern routes through fp8
    dn = (((2,), (0,)), ((), ()))
    out = fp8_dot_general(a, b, dn)
    assert out.shape == (2, 3, 7)
    # non-Dense pattern (batched) falls back to exact lax.dot_general
    c = jnp.asarray(rng.normal(size=(2, 5, 3)).astype(np.float32))
    d = jnp.asarray(rng.normal(size=(2, 3, 4)).astype(np.float32))
    dn_b = (((2,), (1,)), ((0,), (0,)))
    np.testing.assert_allclose(
        fp8_dot_general(c, d, dn_b), jax.lax.dot_general(c, d, dn_b), rtol=1e-6
    )


def _train_bert_tiny(mixed_precision, steps=12):
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model

    acc = Accelerator(mixed_precision=mixed_precision)
    model = acc.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=16, seed=0))
    acc.prepare_optimizer(optax.adamw(5e-4))
    step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, 64, size=(16, 16)).astype(np.int32),
        "attention_mask": np.ones((16, 16), np.bool_),
        "labels": rng.integers(0, 2, size=(16,)).astype(np.int32),
    }
    return [float(step(batch)) for _ in range(steps)]


def test_fp8_policy_enabled_via_mixed_precision():
    from accelerate_tpu.state import AcceleratorState

    assert not fp8_enabled()
    Accelerator(mixed_precision="fp8")
    assert fp8_enabled()
    assert policy_dot_general() is fp8_dot_general
    AcceleratorState._reset_state()


@pytest.mark.slow
def test_fp8_loss_parity_vs_bf16():
    """mixed_precision="fp8" must track the bf16 loss curve on BERT-tiny
    (the reference's benchmarks/fp8 parity bar)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    losses_bf16 = _train_bert_tiny("bf16")
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    losses_fp8 = _train_bert_tiny("fp8")

    # both converge and the curves stay close
    assert losses_fp8[-1] < 0.5 * losses_fp8[0]
    for lb, lf in zip(losses_bf16, losses_fp8):
        assert abs(lb - lf) < 0.1, (losses_bf16, losses_fp8)


def test_scale_from_history_recipe():
    from accelerate_tpu.ops.fp8 import E4M3_MAX, scale_from_history

    h = jnp.asarray([2.0, 8.0, 4.0])
    assert float(scale_from_history(h)) == pytest.approx(E4M3_MAX / 8.0)
    assert float(scale_from_history(h, algo="most_recent")) == pytest.approx(E4M3_MAX / 2.0)
    assert float(scale_from_history(h, margin=1)) == pytest.approx(E4M3_MAX / 16.0)


def test_fp8_dense_delayed_scaling_updates_history():
    """FP8Dense: forward matches a plain dense within e4m3 tolerance and the
    amax histories roll forward in the fp8 collection."""
    from accelerate_tpu.ops.fp8 import FP8Dense

    layer = FP8Dense(32, amax_history_len=4)
    x = jax.random.normal(jax.random.key(0), (8, 16), jnp.float32)
    variables = layer.init(jax.random.key(1), x)
    ref = x @ variables["params"]["kernel"]

    out, mutated = layer.apply(variables, x, mutable=["fp8"])
    rel = float(jnp.linalg.norm(out.astype(jnp.float32) - ref) / jnp.linalg.norm(ref))
    assert rel < 0.06, rel
    hist = mutated["fp8"]["amax_history_x"]
    assert float(hist[0]) == pytest.approx(float(jnp.max(jnp.abs(x))), rel=1e-5)
    # second apply rolls the newest amax to the front
    out2, mutated2 = layer.apply({**variables, **mutated}, x * 2.0, mutable=["fp8"])
    h2 = mutated2["fp8"]["amax_history_x"]
    assert float(h2[0]) == pytest.approx(2 * float(jnp.max(jnp.abs(x))), rel=1e-5)
    assert float(h2[1]) == pytest.approx(float(hist[0]), rel=1e-5)


@pytest.mark.slow
def test_fp8_delayed_llama_trains_with_state():
    """mixed_precision='fp8' + Fp8RecipeKwargs(delayed_scaling=True): the
    llama zoo builds FP8Dense blocks, the amax histories thread through
    build_train_step(has_state=True), and a few steps reduce the loss."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.llama import LlamaConfig, causal_lm_loss_state, create_llama_model
    from accelerate_tpu.utils.dataclasses import Fp8RecipeKwargs

    acc = Accelerator(
        mixed_precision="fp8", kwargs_handlers=[Fp8RecipeKwargs(amax_history_len=8, margin=0)]
    )
    model = acc.prepare_model(
        create_llama_model(LlamaConfig.tiny(scan_layers=True, remat=False), seq_len=16)
    )
    assert model.state is not None and "fp8" in model.state
    blk = model.state["fp8"]["layers"]["block"]
    assert blk["attn"]["q_proj"]["amax_history_x"].shape == (2, 8)  # [layers, H]

    acc.prepare_optimizer(optax.adamw(3e-3))
    step = acc.build_train_step(
        lambda p, s, b: causal_lm_loss_state(p, s, b, model.apply_fn), has_state=True
    )
    h_before = np.asarray(model.state["fp8"]["layers"]["block"]["attn"]["q_proj"]["amax_history_x"])
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 250, size=(4, 16)).astype(np.int32)
    losses = [float(step({"input_ids": ids})) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    # the step must WRITE BACK the rolled histories into model.state
    h_after = np.asarray(model.state["fp8"]["layers"]["block"]["attn"]["q_proj"]["amax_history_x"])
    assert not np.array_equal(h_after, h_before), "fp8 state not threaded through the step"
    assert np.count_nonzero(h_after) > np.count_nonzero(h_before)
