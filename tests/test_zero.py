"""ZeRO-1 cross-replica optimizer sharding tests.

The contract under test (docs/usage_guides/zero_redundancy.md):
reduce-scatter grads over the batch axes -> each replica updates only its
1/n flat segment of params + optimizer state (state *born* sharded) ->
all-gather the updates. fp32 is BIT-EXACT against the replicated
baseline; quantized wire methods stay within the published TPU606
bounds; the sharded optimizer state checkpoints and elastically
restores across a mesh change."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.modeling import Model
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

RNG = np.random.default_rng(7)
W_TRUE = RNG.normal(size=(32, 17)).astype(np.float32)  # 17: exercises padding
X_ALL = RNG.normal(size=(64, 32)).astype(np.float32)
Y_ALL = X_ALL @ W_TRUE
W0 = RNG.normal(size=(32, 17)).astype(np.float32) * 0.1


def mat_loss(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return ((pred - batch["y"]) ** 2).mean()


@pytest.fixture(autouse=True)
def bound_live_executables_per_test():
    """This module builds several Accelerators (= several jitted step
    programs) per test; with the whole file's executables held live,
    XLA:CPU's compiler can segfault on a late fresh compile (the
    conftest-documented ~570-live-programs crash). Clearing per TEST
    keeps the live set tiny; cross-test recompiles hit the persistent
    disk cache."""
    yield
    jax.clear_caches()


@pytest.fixture
def no_persistent_compile_cache():
    """Disable jax's persistent compilation cache for one test.

    Same contract as the fixture of the same name in test_compression.py:
    steps that carry error-feedback state are numerically reliable when
    freshly compiled but XLA:CPU's restore-from-disk-cache can poison the
    carried residuals to NaN (the PR-7 non-self-contained
    deserialized-executable bug class) — so the quantized-carry semantics
    are tested against the freshly-compiled executable."""
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def make_trainer(mesh_config, zero, method=None, accum=1, tx=None, mixed=None):
    _reset()
    acc = Accelerator(
        mixed_precision=mixed,
        gradient_accumulation_steps=accum,
        parallelism_plugin=ParallelismPlugin(
            mesh_config=mesh_config,
            zero_stage=1 if zero else 0,
            grad_compression=method,
        ),
    )
    model = acc.prepare_model(
        Model(
            lambda p, x: x @ p["w"] + p["b"],
            {"w": W0.copy(), "b": np.zeros((17,), np.float32)},
        )
    )
    opt = acc.prepare_optimizer(tx if tx is not None else optax.adam(0.05))
    step = acc.build_train_step(mat_loss)
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))

    def run(n_steps, start=0):
        losses = []
        for s in range(start, start + n_steps):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            batch = {
                "x": jax.device_put(X_ALL[idx], sharding),
                "y": jax.device_put(Y_ALL[idx], sharding),
            }
            losses.append(float(step(batch)))
        return losses

    return acc, model, opt, step, run


#: replicated data=8 baseline loss trajectories, memoized per step count —
#: several tests compare against the same baseline; training it once keeps
#: this module inside the tier-1 wall-clock budget
_BASELINE_LOSSES: dict = {}


def baseline_losses_data8(steps: int):
    if steps not in _BASELINE_LOSSES:
        _, _, _, _, run = make_trainer(MeshConfig(data=8), zero=False)
        _BASELINE_LOSSES[steps] = run(steps)
    return _BASELINE_LOSSES[steps]


# --------------------------------------------------------------------- #
# parity matrix: (1,), (4,), (2,2) data axes
# --------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "mesh_config",
    [
        MeshConfig(data=1, num_devices=1),
        MeshConfig(data=4, num_devices=4),
        MeshConfig(data=2, fsdp=2, num_devices=4),
        MeshConfig(data=8),
    ],
    ids=["data1", "data4", "data2x2", "data8"],
)
def test_zero1_fp32_parity_bit_exact(mesh_config):
    """fp32 ZeRO-1 must reproduce the replicated baseline's PARAMETER
    trajectory BIT-EXACTLY on the same mesh. (The update is applied to
    the param segment inside the shard body so the add fuses with the
    optimizer chain exactly as the baseline's does.) The reported loss
    scalar may differ by an ulp on non-power-of-two batch shards — the
    user loss_fn's local mean divides before the psum, the implicit
    path divides after — so the loss check is ulp-tolerant here and
    exactly pinned in ``test_zero1_fully_bit_exact_on_pow2_shapes``."""
    acc, model, opt, step, run = make_trainer(mesh_config, zero=False)
    base_l = run(14)
    base = jax.tree.map(np.asarray, model.params)

    acc, model, opt, step, run = make_trainer(mesh_config, zero=True)
    zero_l = run(14)
    zero = jax.tree.map(np.asarray, model.params)

    np.testing.assert_allclose(zero_l, base_l, rtol=2e-6, atol=0)
    for k in base:
        assert np.array_equal(base[k], zero[k]), k
    assert base_l[-1] < base_l[0]


def test_zero1_fully_bit_exact_on_pow2_shapes():
    """With power-of-two per-shard element counts every mean is an exact
    scaling, and the ENTIRE trajectory — losses, params, optimizer
    moments — is bit-identical to the replicated baseline."""

    def trainer(zero):
        _reset()
        acc = Accelerator(
            parallelism_plugin=ParallelismPlugin(
                mesh_config=MeshConfig(data=8), zero_stage=1 if zero else 0
            )
        )
        model = acc.prepare_model(
            Model(
                lambda p, x: x @ p["w"] + p["b"],
                {"w": W0[:, :16].copy(), "b": np.zeros((16,), np.float32)},
            )
        )
        opt = acc.prepare_optimizer(optax.adam(0.05))
        step = acc.build_train_step(mat_loss)
        sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
        losses = []
        for s in range(20):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            losses.append(float(step({
                "x": jax.device_put(X_ALL[idx], sharding),
                "y": jax.device_put(Y_ALL[idx][:, :16], sharding),
            })))
        return losses, jax.tree.map(np.asarray, model.params), opt

    base_l, base_p, base_o = trainer(False)
    zero_l, zero_p, zero_o = trainer(True)
    assert zero_l == base_l, (zero_l[-3:], base_l[-3:])
    for k in base_p:
        assert np.array_equal(base_p[k], zero_p[k]), k
    for a, b in zip(
        jax.tree_util.tree_leaves(base_o.opt_state),
        jax.tree_util.tree_leaves(zero_o.opt_state),
    ):
        assert np.array_equal(np.asarray(a).reshape(-1), np.asarray(b).reshape(-1))


def test_zero1_fp32_parity_across_meshes():
    """(2,2) batch axes vs a plain data=4 baseline: the zero shard axis is
    the flattened (data, fsdp) group and the math is identical."""
    _, m4, _, _, run4 = make_trainer(MeshConfig(data=4, num_devices=4), zero=False)
    l4 = run4(14)
    p4 = jax.tree.map(np.asarray, m4.params)
    _, m22, _, _, run22 = make_trainer(
        MeshConfig(data=2, fsdp=2, num_devices=4), zero=True
    )
    l22 = run22(14)
    p22 = jax.tree.map(np.asarray, m22.params)
    np.testing.assert_allclose(l22, l4, rtol=2e-6, atol=0)
    for k in p4:
        assert np.array_equal(p4[k], p22[k]), k


def test_zero1_accumulation_parity():
    """Gradient accumulation rides the sharded buffer (reduce-scatter per
    microbatch, ZeRO-2 flavour) and stays bit-exact vs the baseline."""
    _, mb, _, _, runb = make_trainer(MeshConfig(data=8), zero=False, accum=2)
    lb = runb(16)
    pb = jax.tree.map(np.asarray, mb.params)
    _, mz, _, _, runz = make_trainer(MeshConfig(data=8), zero=True, accum=2)
    lz = runz(16)
    pz = jax.tree.map(np.asarray, mz.params)
    np.testing.assert_allclose(lz, lb, rtol=2e-6, atol=0)
    for k in pb:
        assert np.array_equal(pb[k], pz[k]), k


@pytest.mark.parametrize("method", ["int8", "fp8", "bf16"])
def test_zero1_quantized_parity_within_bound(method, no_persistent_compile_cache):
    """zero_stage=1 + quantized wire: trajectory tracks the replicated
    fp32 baseline within quantization tolerance and converges (error
    feedback carries what the quantizer drops)."""
    base_l = baseline_losses_data8(30)
    _, _, _, _, runq = make_trainer(MeshConfig(data=8), zero=True, method=method)
    q_l = runq(30)
    np.testing.assert_allclose(q_l, base_l, atol=0.06, rtol=0.15)
    assert q_l[-1] < q_l[0] / 2


def test_zero1_collectives_within_tpu606_bound(mesh8):
    """The TPU606 pin at the collective level: one reduce-scatter +
    all-gather round trip through the quantized pair stays within the
    published per-element bound of its numerics model — with zero carried
    residual, the bound must hold for a single shot."""
    from accelerate_tpu.analysis.numerics_rules import COMPRESSION_NUMERICS
    from accelerate_tpu.parallel.zero import all_gather_updates, reduce_scatter_grads
    from accelerate_tpu.utils.compat import shard_map

    n = 8
    g = jax.random.normal(jax.random.key(3), (8, 1024), jnp.float32) * 2.5

    def roundtrip(method):
        def body(x):
            flat = {"g": x[0] * (1.0 / n)}
            err0 = None if method is None else {"g": jnp.zeros_like(flat["g"])}
            shard, _ = reduce_scatter_grads(flat, ("data",), n, method, err0)
            err1 = None if method is None else {"g": jnp.zeros_like(shard["g"])}
            full, _ = all_gather_updates(shard, ("data",), n, method, err1)
            return full["g"][None]

        fn = shard_map(
            body, mesh=mesh8, in_specs=P("data"), out_specs=P("data"), check_vma=False
        )
        out = np.asarray(fn(g))
        return out.reshape(8, -1)[0]

    exact = roundtrip(None)
    amax = float(np.abs(np.asarray(g)).max())
    for method in ("int8", "fp8", "bf16"):
        err = float(np.abs(roundtrip(method) - exact).max())
        bound = COMPRESSION_NUMERICS[method].bound(amax, n)
        assert err <= bound, (
            f"{method}: |error| {err:.3e} exceeds the TPU606 bound {bound:.3e} "
            f"({COMPRESSION_NUMERICS[method].describe})"
        )


# --------------------------------------------------------------------- #
# the HBM claim: optimizer state born sharded
# --------------------------------------------------------------------- #


def test_zero1_opt_state_born_sharded():
    acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True)
    n = 8
    for leaf in jax.tree_util.tree_leaves(opt.opt_state):
        if getattr(leaf, "ndim", 0) == 0:
            continue
        spec = leaf.sharding.spec
        assert spec and spec[0], f"vector state leaf not sharded: {leaf.shape} {spec}"
        # per-device shard is 1/n of the global flat length
        assert leaf.addressable_shards[0].data.shape[0] * n == leaf.shape[0]
    # padding: w is 32*17=544 -> stays 544 (divisible); b is 17 -> pads to 24
    lens = sorted({l.shape[0] for l in jax.tree_util.tree_leaves(opt.opt_state) if getattr(l, "ndim", 0)})
    assert lens == [24, 544]
    run(3)  # and it trains


def test_zero1_flight_check_sees_sharded_state():
    """The static peak-HBM walk must see the 1/n optimizer state: the
    zero1 arm's predicted peak drops vs the replicated baseline by AT
    LEAST the optimizer-state win opt_bytes*(n-1)/n (the sharded
    accumulation buffer wins more on top)."""
    from accelerate_tpu.utils.random import key_for_step

    peaks, opt_bytes = {}, {}
    for zero in (False, True):
        acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=zero)
        box = acc._fast_scale_boxes[-1]
        inner = step._jitted.__wrapped__
        sync = True if zero else jnp.bool_(True)

        def fn(p, o, g, b, s, r, c, cs, _inner=inner, _sync=sync):
            return _inner(p, o, g, None, b, s, _sync, r, c, cs)

        sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
        batch = {
            "x": jax.device_put(X_ALL[:16], sharding),
            "y": jax.device_put(Y_ALL[:16], sharding),
        }
        report = acc.flight_check(
            fn, model.params, opt.opt_state, box["grad_buf"], batch,
            box["scale_state"], key_for_step(0), jnp.float32(-1.0), box["comp_state"],
            donate_argnums=(0, 1, 2),
        )
        peaks[zero] = report.peak_hbm_bytes
        opt_bytes[zero] = sum(
            l.size * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(opt.opt_state)
            if hasattr(l, "size")
        )
    n = 8
    opt_win = opt_bytes[False] * (n - 1) // n
    assert peaks[True] < peaks[False], peaks
    assert peaks[False] - peaks[True] >= opt_win, (peaks, opt_win)


# --------------------------------------------------------------------- #
# wire bytes: prediction vs compiled-HLO measurement
# --------------------------------------------------------------------- #


def test_zero1_wire_bytes_predicted_vs_measured():
    """costmodel-predicted bytes-on-wire vs the compiled program's actual
    collectives (telemetry.wire): within 10% on every arm, and zero1+int8
    moves ~25% of the replicated-f32 baseline's bytes."""
    from accelerate_tpu.parallel.compression import wire_bytes
    from accelerate_tpu.telemetry.wire import hlo_wire_bytes
    from accelerate_tpu.utils.random import key_for_step

    measured, predicted = {}, {}
    for name, (zero, method) in {
        "baseline": (False, None),
        "zero1": (True, None),
        "zero1_int8": (True, "int8"),
    }.items():
        acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=zero, method=method)
        box = acc._fast_scale_boxes[-1]
        sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
        batch = {
            "x": jax.device_put(X_ALL[:16], sharding),
            "y": jax.device_put(Y_ALL[:16], sharding),
        }
        args = (
            model.params, opt.opt_state, box["grad_buf"], None, batch,
            box["scale_state"], True if zero else jnp.bool_(True),
            key_for_step(0), jnp.float32(-1.0), box["comp_state"],
        )
        hlo = step._jitted.lower(*args).compile().as_text()
        measured[name] = hlo_wire_bytes(hlo)["total"]
        predicted[name] = wire_bytes(
            model.params, method, n=8, zero_stage=1 if zero else 0
        )
    for name in measured:
        drift = abs(measured[name] - predicted[name]) / predicted[name]
        assert drift < 0.10, (name, predicted[name], measured[name])
    assert measured["zero1_int8"] <= 0.30 * measured["baseline"]


def test_zero1_no_gradient_sized_allreduce_in_hlo():
    """The compiled sync program must not all-reduce anything
    gradient-sized — the wire claim is reduce-scatter + all-gather."""
    import re

    from accelerate_tpu.utils.random import key_for_step

    acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True)
    box = acc._fast_scale_boxes[-1]
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    batch = {
        "x": jax.device_put(X_ALL[:16], sharding),
        "y": jax.device_put(Y_ALL[:16], sharding),
    }
    hlo = step._jitted.lower(
        model.params, opt.opt_state, box["grad_buf"], None, batch,
        box["scale_state"], True, key_for_step(0), jnp.float32(-1.0),
        box["comp_state"],
    ).compile().as_text()
    assert "reduce-scatter" in hlo and "all-gather" in hlo
    for m in re.finditer(r"= \(?f32\[([0-9,]*)\][^=]*? all-reduce\(", hlo):
        dims = [int(d) for d in m.group(1).split(",") if d]
        size = int(np.prod(dims)) if dims else 1
        assert size < 544, f"gradient-sized all-reduce survived: {m.group(0)}"


# --------------------------------------------------------------------- #
# sharded grad norm: clip + watchdog (regression)
# --------------------------------------------------------------------- #


def test_zero1_clip_grad_norm_matches_baseline():
    """clip_grad_norm_ on ZeRO-sharded shards: the norm is computed via a
    psum of local partial sums (never a gathered tree) and the clipped
    trajectory matches the replicated baseline bit-for-bit... the norm
    itself within float tolerance (summation order differs by design)."""
    def clipped(zero):
        acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=zero)
        acc.clip_grad_norm_(max_norm=0.5)
        losses = run(12)
        return losses, float(acc._last_grad_norm), jax.tree.map(np.asarray, model.params)

    bl, bnorm, bp = clipped(False)
    zl, znorm, zp = clipped(True)
    assert np.isclose(znorm, bnorm, rtol=1e-5), (znorm, bnorm)
    np.testing.assert_allclose(zl, bl, atol=1e-5, rtol=1e-5)
    for k in bp:
        np.testing.assert_allclose(zp[k], bp[k], atol=1e-6)


def test_sharded_global_norm_is_psum_of_partials(mesh8):
    from accelerate_tpu.parallel.zero import sharded_global_norm
    from accelerate_tpu.utils.compat import shard_map

    x = jax.random.normal(jax.random.key(0), (8, 64), jnp.float32)

    fn = shard_map(
        lambda v: sharded_global_norm({"g": v[0]}, ("data",))[None],
        mesh=mesh8, in_specs=P("data"), out_specs=P("data"), check_vma=False,
    )
    got = np.asarray(fn(x))
    want = float(np.linalg.norm(np.asarray(x).reshape(-1)))
    assert np.allclose(got, want, rtol=1e-5)


def test_nonfinite_watchdog_probes_sharded_grads_without_gather(mesh8):
    """Regression: the watchdog's grad probe must find a non-finite leaf
    in a data-sharded tree via an on-device reduction (np.asarray on a
    distributed array would gather it)."""
    from accelerate_tpu.telemetry import NonFiniteWatchdog

    sharded = jax.device_put(
        np.ones((8, 16), np.float32), NamedSharding(mesh8, P("data"))
    )
    bad = sharded.at[5, 3].set(np.nan)
    wd = NonFiniteWatchdog(every=1)
    rec = wd.observe(1, grads={"ok": sharded, "boom": bad})
    assert rec["bad_leaf"] == "grads['boom']"
    assert wd.nonfinite_event is not None
    # clean tree stays quiet
    wd2 = NonFiniteWatchdog(every=1)
    assert wd2.observe(1, grads={"ok": sharded})["bad_leaf"] is None


def test_zero1_fp16_overflow_holds_params_and_recovers(no_persistent_compile_cache):
    """An overflowed fp16 microbatch must hold params/opt state (finite
    gate), back off the scale, and NOT poison the error-feedback carries
    under the quantized wire."""
    acc, model, opt, step, run = make_trainer(
        MeshConfig(data=8), zero=True, method="int8", mixed="fp16"
    )
    run(5)
    before = jax.tree.map(np.asarray, model.params)
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    bad = {
        "x": jax.device_put(np.full((16, 32), 1e4, np.float32), sharding),
        "y": jax.device_put(np.zeros((16, 17), np.float32), sharding),
    }
    step(bad)
    after = jax.tree.map(np.asarray, model.params)
    for k in before:
        assert np.array_equal(before[k], after[k]), f"params moved on overflow: {k}"
    losses = run(28, start=1)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# --------------------------------------------------------------------- #
# checkpoint + elastic restore
# --------------------------------------------------------------------- #


def test_zero1_checkpoint_elastic_restore_across_mesh_change():
    """Save the sharded optimizer state on a data=4 mesh, restore onto
    data=2: values survive exactly (strip saved padding, re-pad for the
    new degree), land 1/n-sharded on the new mesh, and training resumes
    on the baseline trajectory."""
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        acc, model, opt, step, run = make_trainer(
            MeshConfig(data=4, num_devices=4), zero=True
        )
        run(6)
        saved_leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(opt.opt_state)]
        sizes = opt._zero1_state_sizes
        acc.save_state(ck)

        acc2, model2, opt2, step2, run2 = make_trainer(
            MeshConfig(data=2, num_devices=2), zero=True
        )
        acc2.load_state(ck)
        new_leaves = jax.tree_util.tree_leaves(opt2.opt_state)
        for old, new, size in zip(saved_leaves, new_leaves, sizes):
            t = size if size is not None else min(old.size, np.asarray(new).size)
            assert np.array_equal(
                old.reshape(-1)[:t], np.asarray(new).reshape(-1)[:t]
            ), (old.shape, np.asarray(new).shape, size)
            if size is not None:
                assert new.shape[0] % 2 == 0
                assert new.sharding.spec[0], "restored leaf lost its shard layout"
        # params restored exactly; training continues on the baseline path
        assert np.array_equal(
            np.asarray(model.params["w"]), np.asarray(model2.params["w"])
        )
        # reference: an uninterrupted data=2 run from the restored point
        resumed = run2(6, start=6)
        assert np.isfinite(resumed).all()


def test_zero1_same_mesh_restore_is_exact():
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True)
        l1 = run(4)
        acc.save_state(ck)
        cont = run(4, start=4)

        acc2, model2, opt2, step2, run2 = make_trainer(MeshConfig(data=8), zero=True)
        acc2.load_state(ck)
        cont2 = run2(4, start=4)
        assert cont == cont2


# --------------------------------------------------------------------- #
# dogfood: the analysis moat runs clean over the zero step
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("method", [None, "int8"])
def test_zero1_step_analysis_clean(method):
    """perf-check carries no TPU502/503 (redundant / latency-bound
    collectives) and numerics-check no TPU6xx findings over the real
    jitted zero step — the quantized wire carries error feedback, which
    is exactly what TPU606 demands."""
    from accelerate_tpu.utils.random import key_for_step

    acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True, method=method)
    box = acc._fast_scale_boxes[-1]
    inner = step._jitted.__wrapped__

    def fn(p, o, g, b, s, r, c, cs):
        return inner(p, o, g, None, b, s, True, r, c, cs)

    fn.__name__ = "zero1_train_step"
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    batch = {
        "x": jax.device_put(X_ALL[:16], sharding),
        "y": jax.device_put(Y_ALL[:16], sharding),
    }
    args = (
        model.params, opt.opt_state, box["grad_buf"], batch,
        box["scale_state"], key_for_step(0), jnp.float32(-1.0), box["comp_state"],
    )
    perf = acc.perf_check(fn, *args)
    bad = [f for f in perf.findings if f.rule in ("TPU502", "TPU503")]
    assert bad == [], [f.message for f in bad]
    assert not any(f.is_error for f in perf.findings), [f.message for f in perf.findings]
    numerics = acc.numerics_check(fn, *args)
    assert numerics.findings == [], [f.message for f in numerics.findings]


def test_zero1_zero_recompiles_post_warmup():
    """Two stable programs (sync + non-sync): after the warmup step, no
    signature is ever new — the recompile watchdog stays quiet."""
    acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True, accum=2)
    tel = acc.telemetry
    wrapped = tel.wrap(step)
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    for s in range(12):
        idx = np.arange(s * 16, (s + 1) * 16) % 64
        wrapped({
            "x": jax.device_put(X_ALL[idx], sharding),
            "y": jax.device_put(Y_ALL[idx], sharding),
        })
    assert tel.recompiles == 0, tel.summary()


# --------------------------------------------------------------------- #
# knob surface / validation
# --------------------------------------------------------------------- #


def test_zero1_plugin_validation():
    with pytest.raises(ValueError, match="powersgd"):
        ParallelismPlugin(zero_stage=1, grad_compression="powersgd:2")
    with pytest.raises(ValueError, match="offload"):
        ParallelismPlugin(zero_stage=1, offload_optimizer=True)
    with pytest.raises(ValueError, match="shard_optimizer_state"):
        ParallelismPlugin(zero_stage=1, shard_optimizer_state=True)
    with pytest.raises(ValueError, match="zero_stage"):
        ParallelismPlugin(zero_stage=3)
    ParallelismPlugin(zero_stage=1, grad_compression="fp8")  # stacks


def test_zero1_env_knob(monkeypatch):
    monkeypatch.setenv("ACCELERATE_ZERO_STAGE", "1")
    plugin = ParallelismPlugin.from_env()
    assert plugin.zero_stage == 1


def test_zero1_rejects_tensor_axes():
    _reset()
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=4, tensor=2), zero_stage=1
        )
    )
    model = acc.prepare_model(
        Model(lambda p, x: x @ p["w"], {"w": np.zeros((32, 16), np.float32)})
    )
    with pytest.raises(ValueError, match="batch axes"):
        acc.prepare_optimizer(optax.sgd(0.1))
        acc.build_train_step(lambda p, b: ((b["x"] @ p["w"]) ** 2).mean())


def test_zero1_nonelementwise_transform_falls_back_with_warning(caplog):
    """zero_stage=1 with a factored optax transform (adafactor couples
    elements within a leaf) must not silently change the update
    semantics: it warns ONCE naming the offending state node
    (FactoredState) and the fallback taken, then takes the passive
    shard_optimizer_state layout — state GSPMD-sharded over the data
    axis, no flat-segment wire split."""
    import logging

    from accelerate_tpu import accelerator as acc_mod

    acc_mod._ZERO1_FALLBACK_WARNED.clear()
    caplog.set_level(logging.WARNING, logger="accelerate_tpu.accelerator")
    acc, model, opt, step, run = make_trainer(
        MeshConfig(data=8), zero=True, tx=optax.adafactor(0.1)
    )
    warned = [r for r in caplog.records if "zero_stage=1 requires an elementwise" in r.getMessage()]
    assert len(warned) == 1
    assert "FactoredState" in warned[0].getMessage()
    assert "shard_optimizer_state" in warned[0].getMessage()
    # explicit layout skipped, fallback recorded on the optimizer
    assert getattr(opt, "_zero1_layout", None) is None
    assert acc.zero1_fallback_reason(opt) == ("FactoredState",)
    # the state is passively sharded over the data axis (1/n per device)
    specs = {
        tuple(getattr(leaf.sharding, "spec", ()) or ())
        for leaf in jax.tree_util.tree_leaves(opt.opt_state)
        if getattr(leaf, "ndim", 0) >= 1
    }
    assert any("data" in str(s) for s in specs), specs
    # and the step still trains (batches cycle with period 4: compare
    # the same batch before/after one full data pass)
    losses = run(5)
    assert losses[4] < losses[0]
    # one-time: a second adafactor trainer does not re-warn
    caplog.clear()
    make_trainer(MeshConfig(data=8), zero=True, tx=optax.adafactor(0.1))
    assert not [r for r in caplog.records if "zero_stage=1 requires" in r.getMessage()]
    # an elementwise transform keeps the explicit flat-segment path
    _, _, opt3, _, _ = make_trainer(MeshConfig(data=8), zero=True, tx=optax.adam(0.05))
    assert getattr(opt3, "_zero1_layout", None) is not None
    assert acc.zero1_fallback_reason(opt3) is None


def test_zero1_imperative_path_rejected():
    acc, model, opt, step, run = make_trainer(MeshConfig(data=8), zero=True)
    with pytest.raises(NotImplementedError, match="build_train_step"):
        acc.backward(mat_loss, {"x": X_ALL[:16], "y": Y_ALL[:16]})
        opt.step()


def test_zero1_degenerates_on_single_shard():
    """data=1: nothing to shard — the plain replicated path runs and the
    optimizer state keeps its parameter shapes."""
    acc, model, opt, step, run = make_trainer(MeshConfig(data=1, num_devices=1), zero=True)
    assert getattr(opt, "_zero1_layout", None) is None
    shapes = {tuple(l.shape) for l in jax.tree_util.tree_leaves(opt.opt_state) if getattr(l, "ndim", 0)}
    assert (32, 17) in shapes
    run(2)


# --------------------------------------------------------------------- #
# satellite: grad_compression now composes with has_state / has_aux
# --------------------------------------------------------------------- #


def test_compression_composes_with_has_aux_and_state():
    """The former `does not compose with has_state/has_aux` restriction at
    the top of build_train_step is lifted: aux and mutable state thread
    through the explicit per-shard-grad path (float leaves pmean'd)."""

    def loss_with_state(params, state, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        loss = ((pred - batch["y"]) ** 2).mean()
        new_state = {"batch_mean": batch["x"].mean(), "count": state["count"] + 1}
        return loss, (new_state, {"mse": loss})

    def train(method):
        _reset()
        acc = Accelerator(
            parallelism_plugin=ParallelismPlugin(
                mesh_config=MeshConfig(data=8), grad_compression=method
            )
        )
        model = acc.prepare_model(
            Model(
                lambda p, x: x @ p["w"] + p["b"],
                {"w": W0.copy(), "b": np.zeros((17,), np.float32)},
            )
        )
        model.state = {"batch_mean": jnp.float32(0.0), "count": jnp.int32(0)}
        acc.prepare_optimizer(optax.adam(0.05))
        step = acc.build_train_step(loss_with_state, has_state=True, has_aux=True)
        sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
        out = []
        for s in range(20):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            loss, aux = step({
                "x": jax.device_put(X_ALL[idx], sharding),
                "y": jax.device_put(Y_ALL[idx], sharding),
            })
            out.append((float(loss), float(aux["mse"])))
        return out, model.state

    plain, state_p = train(None)
    comp, state_c = train("int8")
    assert int(state_c["count"]) == 20
    np.testing.assert_allclose(
        float(state_c["batch_mean"]), float(state_p["batch_mean"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        [l for l, _ in comp], [l for l, _ in plain], atol=0.05, rtol=0.1
    )
    for loss, mse in comp:
        assert np.isclose(loss, mse)


def test_zero1_with_has_aux():
    """ZeRO-1 threads aux through the shard body (pmean'd)."""

    def loss_aux(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        loss = ((pred - batch["y"]) ** 2).mean()
        return loss, {"mae": jnp.abs(pred - batch["y"]).mean()}

    _reset()
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=8), zero_stage=1)
    )
    model = acc.prepare_model(
        Model(
            lambda p, x: x @ p["w"] + p["b"],
            {"w": W0.copy(), "b": np.zeros((17,), np.float32)},
        )
    )
    acc.prepare_optimizer(optax.adam(0.05))
    step = acc.build_train_step(loss_aux, has_aux=True)
    sharding = NamedSharding(acc.mesh, P(("data", "fsdp")))
    losses = []
    for s in range(10):
        idx = np.arange(s * 16, (s + 1) * 16) % 64
        loss, aux = step({
            "x": jax.device_put(X_ALL[idx], sharding),
            "y": jax.device_put(Y_ALL[idx], sharding),
        })
        losses.append(float(loss))
        assert np.isfinite(float(aux["mae"]))
    assert losses[-1] < losses[0]


def test_zero1_optimizer_state_dict_roundtrip_repads():
    """The host-side state_dict/load_state_dict pair (the
    register_for_checkpointing path, not orbax) also re-pads a snapshot
    taken at a different data-parallel degree."""
    acc4, _, opt4, _, run4 = make_trainer(MeshConfig(data=4, num_devices=4), zero=True)
    run4(3)
    snap = opt4.state_dict()
    sizes = opt4._zero1_state_sizes

    acc2, _, opt2, _, run2 = make_trainer(MeshConfig(data=2, num_devices=2), zero=True)
    opt2.load_state_dict(snap)
    for old, new, size in zip(
        snap["leaves"], jax.tree_util.tree_leaves(opt2.opt_state), sizes
    ):
        t = size if size is not None else np.asarray(old).size
        assert np.array_equal(
            np.asarray(old).reshape(-1)[:t], np.asarray(new).reshape(-1)[:t]
        )
    run2(2, start=3)
