"""Compile management (accelerate_tpu/aot): executable store round-trips,
cross-process warm start with zero XLA compiles, content-key invalidation,
poison rejection, shape bucketing, and the CompileKwargs/serving wiring."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.aot import (
    CorruptEntryError,
    ExecutableStore,
    ProgramCache,
    ShapeBucketer,
    StaleEntryError,
    content_key,
    deserialize_compiled,
    next_pow2,
    pad_batch_tree,
    resolve_cache_dir,
    serialize_compiled,
)
from accelerate_tpu.telemetry.eventlog import EventLog, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fn(x, w):
    return jnp.tanh(x @ w).sum()


def _avals():
    return (
        jax.ShapeDtypeStruct((8, 16), jnp.float32),
        jax.ShapeDtypeStruct((16, 16), jnp.float32),
    )


# --------------------------------------------------------------------- #
# store + round-trip
# --------------------------------------------------------------------- #


def test_serialize_roundtrip_bit_exact():
    """Serialized -> deserialized executable produces bit-identical
    outputs to the original compiled program."""
    lowered = jax.jit(_fn).lower(*_avals())
    compiled = lowered.compile()
    loaded = deserialize_compiled(serialize_compiled(compiled))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16)).astype(np.float32)
    w = rng.standard_normal((16, 16)).astype(np.float32)
    a, b = np.asarray(compiled(x, w)), np.asarray(loaded(x, w))
    np.testing.assert_array_equal(a, b)


def test_store_put_get_and_header(tmp_path):
    store = ExecutableStore(str(tmp_path))
    store.put("k" * 64, b"payload-bytes", name="demo")
    assert store.get("k" * 64) == b"payload-bytes"
    header = store.read_header("k" * 64)
    assert header["name"] == "demo" and header["size"] == len(b"payload-bytes")
    assert store.get("absent" * 8) is None
    assert store.keys() == ["k" * 64]


def test_store_rejects_poisoned_entry(tmp_path):
    store = ExecutableStore(str(tmp_path))
    store.put("k" * 64, b"payload-bytes", name="demo")
    path = store._entry_path("k" * 64)
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[:-4] + b"XXXX")
    with pytest.raises(CorruptEntryError):
        store.get("k" * 64)


def test_store_rejects_stale_jax_version(tmp_path):
    """An entry whose header claims a different jax version must never
    deserialize — the stale-key invalidation the content key provides is
    double-checked at read time."""
    store = ExecutableStore(str(tmp_path))
    store.put("k" * 64, b"payload-bytes", name="demo")
    path = store._entry_path("k" * 64)
    with open(path, "rb") as f:
        magic, header, payload = f.readline(), json.loads(f.readline()), f.read()
    header["jax"] = "0.0.1-somethingelse"
    with open(path, "wb") as f:
        f.write(magic + json.dumps(header).encode() + b"\n" + payload)
    with pytest.raises(StaleEntryError):
        store.get("k" * 64)


def test_content_key_changes_with_shape_mesh_and_salt(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    base = content_key(jax.jit(_fn).lower(*_avals()))
    other_shape = content_key(
        jax.jit(_fn).lower(
            jax.ShapeDtypeStruct((16, 16), jnp.float32), jax.ShapeDtypeStruct((16, 16), jnp.float32)
        )
    )
    sharded_aval = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=NamedSharding(mesh8, P("data")))
    other_mesh = content_key(jax.jit(_fn).lower(sharded_aval, _avals()[1]))
    salted = content_key(jax.jit(_fn).lower(*_avals()), extra=("v2",))
    assert len({base, other_shape, other_mesh, salted}) == 4
    # and deterministic for identical input
    assert base == content_key(jax.jit(_fn).lower(*_avals()))


# --------------------------------------------------------------------- #
# ProgramCache
# --------------------------------------------------------------------- #


def test_program_cache_memory_then_disk_hit(tmp_path):
    pc = ProgramCache(store=ExecutableStore(str(tmp_path)))
    pc.compile(_fn, *_avals(), name="t")
    pc.compile(_fn, *_avals(), name="t")
    assert (pc.misses, pc.hits, pc.deserialized) == (1, 1, 0)

    fresh = ProgramCache(store=ExecutableStore(str(tmp_path)))
    compiled = fresh.compile(_fn, *_avals(), name="t")
    assert (fresh.misses, fresh.deserialized) == (0, 1)
    assert float(compiled(np.ones((8, 16), np.float32), np.ones((16, 16), np.float32))) == pytest.approx(
        float(jax.jit(_fn)(np.ones((8, 16), np.float32), np.ones((16, 16), np.float32)))
    )


def test_program_cache_rejects_and_heals_poison(tmp_path, tmp_path_factory):
    log_path = str(tmp_path_factory.mktemp("log") / "run.jsonl")
    pc = ProgramCache(store=ExecutableStore(str(tmp_path)))
    pc.compile(_fn, *_avals(), name="t")
    key = pc.store.keys()[0]
    path = pc.store._entry_path(key)
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2] + b"\xff" * 16 + blob[len(blob) // 2 :])

    log = EventLog(log_path, rank=0)
    healed = ProgramCache(store=ExecutableStore(str(tmp_path)), log=log)
    compiled = healed.compile(_fn, *_avals(), name="t")
    log.close()
    assert healed.rejected == 1 and healed.misses == 1
    # the heal re-stored a GOOD entry: a third cache deserializes again
    third = ProgramCache(store=ExecutableStore(str(tmp_path)))
    third.compile(_fn, *_avals(), name="t")
    assert third.deserialized == 1
    names = [e["name"] for e in read_events(log_path)]
    assert "compile_cache_reject" in names and "compile_cache_miss" in names
    assert compiled is not None


def test_wrap_jit_dispatch_and_cache_size(tmp_path):
    pc = ProgramCache(store=ExecutableStore(str(tmp_path)))
    w = pc.wrap_jit(jax.jit(_fn), name="w")
    x, wgt = np.ones((8, 16), np.float32), np.ones((16, 16), np.float32)
    a = float(w(x, wgt))
    assert w._cache_size() == 1 and pc.misses == 1
    b = float(w(x, wgt))  # table hit: no new program
    assert w._cache_size() == 1 and pc.misses == 1 and a == b
    w(np.ones((4, 16), np.float32), wgt)  # new shape -> second program
    assert w._cache_size() == 2 and pc.misses == 2


def test_aot_export_import_roundtrip(tmp_path):
    src = ProgramCache(store=ExecutableStore(str(tmp_path / "src")))
    src.compile(_fn, *_avals(), name="t")
    archive = str(tmp_path / "bundle.tar.gz")
    assert src.aot_export(archive) == 1

    dst = ProgramCache(store=ExecutableStore(str(tmp_path / "dst")))
    assert dst.aot_load(archive) == 1
    dst.compile(_fn, *_avals(), name="t")
    assert (dst.misses, dst.deserialized) == (0, 1)


def test_resolve_cache_dir_precedence(tmp_path, monkeypatch):
    monkeypatch.delenv("ACCELERATE_COMPILE_CACHE_DIR", raising=False)
    assert resolve_cache_dir() is None
    assert resolve_cache_dir(project_dir="/p") == os.path.join("/p", "compile_cache")
    monkeypatch.setenv("ACCELERATE_COMPILE_CACHE_DIR", str(tmp_path))
    assert resolve_cache_dir(project_dir="/p") == str(tmp_path)
    assert resolve_cache_dir("/explicit", project_dir="/p") == "/explicit"


# --------------------------------------------------------------------- #
# cross-process warm start (the acceptance-criteria matrix)
# --------------------------------------------------------------------- #

_CHILD_COMPILE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
import jax, jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from accelerate_tpu.aot import ExecutableStore, ProgramCache

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
def step(x, w):
    return jnp.tanh(x @ w).sum()
pc = ProgramCache(store=ExecutableStore({store!r}))
sharded = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=NamedSharding(mesh, P("data")))
dense = jax.ShapeDtypeStruct((16, 16), jnp.float32)
compiled = pc.compile(step, sharded, dense, name="xproc_step")
out = float(compiled(np.ones((8, 16), np.float32), np.ones((16, 16), np.float32)))
print("CHILD", pc.misses, pc.deserialized, out)
"""


def test_cross_process_cache_hit_matrix(tmp_path, monkeypatch):
    """The acceptance matrix: a subprocess compiles the (sharded-input)
    step into the store; this 'restarted' process re-creates the same
    program and performs ZERO XLA compiles — proved by the ProgramCache
    counters, the `compile_cache_hit` telemetry event, and the recompile
    watchdog staying at 0 across post-warm-start steps."""
    store_dir = str(tmp_path / "store")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    out = subprocess.run(
        [sys.executable, "-c", _CHILD_COMPILE.format(repo=REPO, store=store_dir)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    child = out.stdout.strip().splitlines()[-1].split()
    assert child[:3] == ["CHILD", "1", "0"]  # child compiled, nothing to deserialize

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from accelerate_tpu.telemetry import StepTelemetry

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))

    def step(x, w):
        return jnp.tanh(x @ w).sum()

    log_path = str(tmp_path / "run.jsonl")
    log = EventLog(log_path, rank=0)
    pc = ProgramCache(store=ExecutableStore(store_dir), log=log)
    sharded = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=NamedSharding(mesh, P("data")))
    dense = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    compiled = pc.compile(step, sharded, dense, name="xproc_step")
    assert pc.misses == 0 and pc.deserialized == 1  # zero XLA compiles here

    telem = StepTelemetry(log, warmup_steps=1)
    wrapped = telem.wrap(compiled)
    x = jax.device_put(np.ones((8, 16), np.float32), NamedSharding(mesh, P("data")))
    w = np.ones((16, 16), np.float32)
    results = [float(wrapped(x, w)) for _ in range(5)]
    log.close()
    assert telem.recompiles == 0
    assert results == [pytest.approx(float(child[3]))] * 5  # bit-consistent with the child
    events = read_events(log_path)
    hits = [e for e in events if e.get("name") == "compile_cache_hit"]
    assert hits and hits[0]["source"] == "disk" and hits[0]["deserialize_ms"] >= 0
    assert not [e for e in events if e.get("name") == "compile_cache_miss"]


# --------------------------------------------------------------------- #
# ShapeBucketer
# --------------------------------------------------------------------- #


def test_bucketer_minimal_covering_bucket():
    b = ShapeBucketer((8, 32, 128))
    assert b.bucket(3) == 8
    assert b.bucket(8) == 8
    assert b.bucket(9) == 32
    assert b.bucket(100) == 128


def test_bucketer_never_shrinks_and_grows_by_pow2():
    b = ShapeBucketer((8,))
    assert b.bucket(20) == 32  # minted: next_pow2(20)
    assert b.buckets == (8, 32)
    for n in (1, 7, 20, 31, 32):
        assert b.bucket(n) >= n
    before = set(b.buckets)
    b.refine()
    assert before.issubset(set(b.buckets))  # grow-only


def test_bucketer_multiple_of_and_max_size():
    b = ShapeBucketer((6,), multiple_of=4)
    assert b.buckets == (8,)  # seed rounded up to the shard multiple
    assert b.bucket(9) % 4 == 0
    capped = ShapeBucketer((8,), max_size=24, multiple_of=4)
    assert capped.bucket(17) == 24  # pow2 would be 32; clamped to max_size
    with pytest.raises(ValueError):
        capped.bucket(25)


def test_bucketer_refines_from_histogram():
    b = ShapeBucketer((64,), refine_every=10_000)  # refine manually
    for _ in range(50):
        b.bucket(17)
    added = b.refine()
    assert 17 in added and 17 in b.buckets
    assert b.bucket(17) == 17  # tighter bucket now wins
    assert b.bucket(18) == 64  # everything else unchanged


def test_next_pow2_and_pad_batch_tree():
    assert [next_pow2(n) for n in (1, 2, 3, 8, 9)] == [1, 2, 4, 8, 16]
    batch = {"x": np.arange(12).reshape(3, 4), "y": np.arange(3), "scalar": 7}
    padded = pad_batch_tree(batch, 8)
    assert padded["x"].shape == (8, 4) and padded["y"].shape == (8,)
    np.testing.assert_array_equal(padded["y"], [0, 1, 2, 0, 1, 2, 0, 1])  # wrap-around
    assert padded["scalar"] == 7
    assert pad_batch_tree(batch, 2)["x"].shape == (3, 4)  # never truncates


# --------------------------------------------------------------------- #
# auto-bucketing end to end: ragged stream, bounded compiles, quiet watchdog
# --------------------------------------------------------------------- #


def test_ragged_stream_bounded_compiles_watchdog_silent():
    """Acceptance: a stream of ragged batch shapes through auto-bucketing
    triggers at most len(buckets) compiles and the recompile watchdog is
    SILENT after warmup."""
    from accelerate_tpu.telemetry import StepTelemetry

    bucketer = ShapeBucketer((8, 16))
    pc = ProgramCache()
    dispatch = pc.wrap_jit(jax.jit(lambda b: (b["x"] * 2).sum()), name="ragged")
    telem = StepTelemetry(warmup_steps=2)
    step = telem.wrap(dispatch)

    rng = np.random.default_rng(0)
    sizes = [5, 13] + [int(rng.integers(1, 17)) for _ in range(50)]
    for n in sizes:  # first two cover both buckets during warmup
        batch = {"x": np.ones((n, 4), np.float32)}
        step(pad_batch_tree(batch, bucketer.bucket(n)))
    assert bucketer.buckets == (8, 16)
    assert pc.misses <= len(bucketer.buckets)
    assert dispatch._cache_size() <= len(bucketer.buckets)
    assert telem.recompiles == 0  # silent after warmup


def test_dataloader_auto_bucketing_pads_ragged_tail():
    from accelerate_tpu.data_loader import DataLoaderShard

    ds = [{"x": np.full((4,), i, np.float32)} for i in range(21)]
    dl = DataLoaderShard(
        ds, batch_size=8, even_batches=False, auto_bucketing=True, device_placement=False
    )
    shapes = [b["x"].shape for b in dl]
    # steady batches stay 8 (seeded bucket); the 5-row tail pads to 8 too
    assert shapes == [(8, 4), (8, 4), (8, 4)]
    assert dl.remainder == 5  # gather_for_metrics truncation still exact
    assert dl.bucketer.buckets == (8,)
    # wrap-around rows replay the batch head, even_batches tail semantics
    last = list(dl)[-1]
    np.testing.assert_array_equal(last["x"][:, 0], [16, 17, 18, 19, 20, 16, 17, 18])


def test_iterable_loader_auto_bucketing_single_program_shape():
    from accelerate_tpu.data_loader import IterableDataLoaderShard

    class Stream:
        def __iter__(self):
            for i in range(30):
                yield {"x": np.full((2,), i, np.float32)}

    dl = IterableDataLoaderShard(
        Stream(), batch_size=7, even_batches=False, auto_bucketing=True, device_placement=False
    )
    shapes = {b["x"].shape for b in dl}
    assert shapes == {(7, 2)}  # 4 full batches + 2-row tail, all one bucket
    assert dl.remainder == 2


# --------------------------------------------------------------------- #
# CompileKwargs / Accelerator wiring
# --------------------------------------------------------------------- #


def _make_accelerator(cache_dir):
    import optax

    from accelerate_tpu import Accelerator, CompileKwargs

    acc = Accelerator(kwargs_handlers=[CompileKwargs(cache_dir=cache_dir)])
    params = {"w": np.ones((4, 4), np.float32)}
    apply_fn = lambda p, x: x @ p["w"]  # noqa: E731
    model = acc.prepare_model((apply_fn, params))
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(lambda p, b: ((apply_fn(p, b["x"]) - b["y"]) ** 2).mean())
    batch = {"x": np.ones((8, 4), np.float32), "y": np.zeros((8, 4), np.float32)}
    return acc, step, batch


def test_compile_kwargs_activates_program_cache(tmp_path, reset_singletons):
    from accelerate_tpu import Accelerator

    acc, step, batch = _make_accelerator(str(tmp_path))
    losses = [float(step(batch)) for _ in range(3)]
    assert acc.program_cache is not None and acc.program_cache.misses >= 1
    assert step._jitted._cache_size() >= 1  # watchdog probe works through the wrapper
    assert acc.program_cache.store is not None and len(acc.program_cache.store.keys()) >= 1

    # "restart": a fresh Accelerator + fresh ProgramCache over the same dir
    # rebuilds the same step with ZERO compiles and a bit-exact trajectory
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(), GradientState._reset_state(), PartialState._reset_state()
    acc2, step2, batch2 = _make_accelerator(str(tmp_path))
    losses2 = [float(step2(batch2)) for _ in range(3)]
    assert losses2 == losses
    assert acc2.program_cache.misses == 0 and acc2.program_cache.deserialized >= 1


def test_bare_accelerator_has_no_program_cache(monkeypatch, reset_singletons):
    from accelerate_tpu import Accelerator

    monkeypatch.delenv("ACCELERATE_COMPILE_CACHE_DIR", raising=False)
    assert Accelerator().program_cache is None


def test_env_var_activates_program_cache(tmp_path, monkeypatch, reset_singletons):
    from accelerate_tpu import Accelerator

    monkeypatch.setenv("ACCELERATE_COMPILE_CACHE_DIR", str(tmp_path))
    acc = Accelerator()
    assert acc.program_cache is not None
    assert acc.program_cache.store.path == os.path.join(str(tmp_path), "executables")


# --------------------------------------------------------------------- #
# serving: lazy buckets + per-bucket compile_ms + auto-bucketing
# --------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def tiny_llama():
    from accelerate_tpu.models import LlamaConfig, create_llama_model

    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


def test_serving_buckets_compile_lazily(tiny_llama, tmp_path):
    from accelerate_tpu.serving import ServingEngine

    log_path = str(tmp_path / "serve.jsonl")
    log = EventLog(log_path, rank=0)
    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4, 8, 16), telemetry_log=log)
    assert len(eng._prefill) == 0  # construction compiled NO prefill bucket
    eng.generate_many([np.arange(1, 6, dtype=np.int32)], max_new_tokens=3)
    assert eng._prefill.compiled_buckets() == (8,)  # only the bucket traffic hit
    assert ("prefill", 8) in eng.bucket_compile_ms and eng.bucket_compile_ms[("prefill", 8)] > 0
    log.close()
    events = [e for e in read_events(log_path) if e.get("name") == "serving_bucket_compile"]
    assert [(e["program"], e["bucket"]) for e in events] == [("prefill", 8)]
    assert events[0]["compile_ms"] > 0


def test_serving_auto_bucketing_token_exact(tiny_llama):
    """Auto-bucketing mints covering buckets on demand and outputs stay
    token-exact vs generate(); compile count stays O(buckets)."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.serving import ServingEngine

    eng = ServingEngine(tiny_llama, num_slots=2, prompt_buckets=(4,), auto_bucketing=True)
    prompts = [np.arange(1, 1 + n, dtype=np.int32) for n in (3, 5, 6, 9, 2)]
    outs = eng.generate_many(prompts, max_new_tokens=4)
    for prompt, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_llama, prompt[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(got, ref)
    # lengths 3,5,6,9,2 -> buckets {4, 8, 16}: three prefill compiles, not five
    assert eng.bucketer.buckets == (4, 8, 16)
    assert eng._prefill.compiled_buckets() == (4, 8, 16)


_CHILD_SERVE = """
import os, sys
sys.path.insert(0, {repo!r})
from accelerate_tpu.utils.environment import force_host_platform
force_host_platform(1)
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
import numpy as np
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ServingEngine
from accelerate_tpu.aot import ExecutableStore, ProgramCache

model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
eng = ServingEngine(model, num_slots=1, prompt_buckets=(8,),
                    program_cache=ProgramCache(store=ExecutableStore({store!r})))
[ref] = eng.generate_many([np.arange(1, 7, dtype=np.int32)], max_new_tokens=3)
pc = eng.program_cache
print("REPLICA", pc.misses, pc.deserialized, " ".join(str(t) for t in ref))
"""


def test_serving_warm_replica_reuses_store(tmp_path):
    """The new-replica warm-start story: a cold replica fills the store,
    a second replica deserializes EVERY engine program with zero XLA
    compiles and token-exact output. Both replicas are real subprocesses
    — a replica is a fresh process by definition, and that is also the
    regime where XLA:CPU serialization is dependable (a long-lived
    process with many resident programs can emit non-self-contained
    blobs, which the ProgramCache reject-and-heal path downgrades to a
    recompile rather than a wrong result)."""
    store_dir = str(tmp_path / "serve_store")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("XLA_FLAGS", None)

    def replica():
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_SERVE.format(repo=REPO, store=store_dir)],
            capture_output=True, text=True, env=env, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        tag, misses, deser, *tokens = out.stdout.strip().splitlines()[-1].split()
        assert tag == "REPLICA"
        return int(misses), int(deser), np.asarray([int(t) for t in tokens], np.int32)

    cold_misses, cold_deser, ref = replica()
    assert cold_misses >= 1 and cold_deser == 0

    warm_misses, warm_deser, got = replica()
    assert warm_misses == 0, "warm replica must not compile"
    assert warm_deser == cold_misses  # every program came from the store
    np.testing.assert_array_equal(got, ref)


# --------------------------------------------------------------------- #
# watchdog suggested_bucket + CLI
# --------------------------------------------------------------------- #


def test_watchdog_suggests_pad_bucket():
    from accelerate_tpu.telemetry import StepTelemetry

    st = StepTelemetry(warmup_steps=1)
    step = st.wrap(jax.jit(lambda x: x.sum()))
    step(jnp.ones((7, 128)))
    step(jnp.ones((7, 128)))
    step(jnp.ones((5, 128)))  # post-warmup drift on dim 0
    assert st.recompiles == 1
    (ev,) = st.recompile_events
    assert any("pad to float32[8,128]" in s for s in ev["suggested_bucket"])


def test_watchdog_no_suggestion_for_dtype_change():
    from accelerate_tpu.telemetry import StepTelemetry

    st = StepTelemetry(warmup_steps=1)
    step = st.wrap(jax.jit(lambda x: x.sum()))
    step(jnp.ones((8, 8)))
    step(jnp.ones((8, 8)))
    step(jnp.ones((8, 8), jnp.bfloat16))  # dtype drift: padding can't fix
    assert st.recompiles == 1
    assert st.recompile_events[0]["suggested_bucket"] == []


def _run_cli(*argv, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *argv],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )


@pytest.mark.slow
def test_cli_compile_cache_selfcheck():
    out = _run_cli("compile-cache", "--selfcheck")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "poisoned entry rejected" in out.stdout


@pytest.mark.slow
def test_cli_compile_cache_warm_stats_clear(tmp_path):
    fn_file = tmp_path / "stepfn.py"
    fn_file.write_text(
        "import jax.numpy as jnp\n\ndef step(x, w):\n    return jnp.tanh(x @ w).sum()\n"
    )
    d = str(tmp_path / "cache")
    out = _run_cli(
        "compile-cache", "warm", f"{fn_file}::step", "--arg", "f32[8,16]", "--arg", "f32[16,16]",
        "--dir", d,
    )
    assert out.returncode == 0 and "compiled + stored" in out.stdout, out.stdout + out.stderr
    out = _run_cli(
        "compile-cache", "warm", f"{fn_file}::step", "--arg", "f32[8,16]", "--arg", "f32[16,16]",
        "--dir", d,
    )
    assert "deserialized (already warm)" in out.stdout

    out = _run_cli("compile-cache", "stats", "--dir", d, "--format", "json")
    report = json.loads(out.stdout)
    assert report["entries"] == 1 and report["programs"][0]["name"] == "step"

    out = _run_cli("compile-cache", "clear", "--dir", d)
    assert "would remove 1" in out.stdout  # dry-run by default
    out = _run_cli("compile-cache", "clear", "--dir", d, "--yes")
    assert "removed 1" in out.stdout
    out = _run_cli("compile-cache", "stats", "--dir", d, "--format", "json")
    assert json.loads(out.stdout)["entries"] == 0
