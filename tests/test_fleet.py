"""Fleet-scale serving (serving_fleet.py): radix prefix cache semantics,
router policy, disaggregated KV handoff exactness + cost-model byte
accounting, fleet SLO shedding, and zero-compile replica spin-up."""

import os
import subprocess
import sys

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.scheduling import FleetRoutingPolicy, RoutingConfig, ShedError
from accelerate_tpu.serving import ServingEngine
from accelerate_tpu.serving_fleet import (
    FleetConfig,
    FleetRequestError,
    FleetRouter,
    HandoffCodec,
    RadixPrefixCache,
)
from accelerate_tpu.test_utils.fault_injection import ReplicaChaos, SimulatedCrash

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


@pytest.fixture(autouse=True)
def bound_live_executables_per_test():
    """This module builds several engines (= many resident programs) per
    test; clearing per TEST keeps the process-wide live-executable set
    tiny (the conftest-documented XLA:CPU late-fresh-compile segfault
    class). Cross-test recompiles hit the persistent disk cache."""
    yield
    import jax

    jax.clear_caches()


def _reference(model, prompt, n):
    return np.asarray(generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n))[0]


def _engine(model, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("prompt_buckets", (4, 8))
    return ServingEngine(model, **kw)


# --------------------------------------------------------------------- #
# routing policy (scheduling.py)
# --------------------------------------------------------------------- #


def test_routing_policy_least_loaded_and_round_robin():
    p = FleetRoutingPolicy(RoutingConfig(policy="least_loaded"))
    assert p.pick_replica([3, 1, 2], [0, 1, 2]) == 1
    assert p.pick_replica([1, 1, 2], [0, 1, 2]) == 0  # tie -> lowest index
    assert p.pick_replica([0, 9, 0], [1, 2]) == 2  # eligibility filters
    rr = FleetRoutingPolicy(RoutingConfig(policy="round_robin"))
    picks = [rr.pick_replica([0, 0, 0], [0, 1, 2]) for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]


def test_routing_policy_fleet_shed_respects_priority_floor():
    p = FleetRoutingPolicy(RoutingConfig(max_fleet_queue_depth=4))
    assert p.shed_on_submit(0, 100) is None  # priority 0 unsheddable
    assert p.shed_on_submit(1, 3) is None
    assert "fleet queue depth" in p.shed_on_submit(1, 4)


def test_routing_config_validation():
    with pytest.raises(ValueError, match="policy"):
        RoutingConfig(policy="random")
    with pytest.raises(ValueError, match="max_fleet_queue_depth"):
        RoutingConfig(max_fleet_queue_depth=0)
    with pytest.raises(ValueError, match="roles"):
        FleetConfig(roles=("mixed", "oracle"))
    with pytest.raises(ValueError, match="handoff"):
        FleetConfig(handoff="sometimes")


# --------------------------------------------------------------------- #
# radix prefix cache
# --------------------------------------------------------------------- #


def test_radix_promotes_shared_preamble_and_reuse_is_exact(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = (np.arange(1, 7) % 250).astype(np.int32)
    p1 = np.concatenate([pre, [41, 42]]).astype(np.int32)
    p2 = np.concatenate([pre, [51, 52, 53]]).astype(np.int32)
    assert rad.lookup(p1) is None and rad.observe(p1) is None
    assert rad.lookup(p2) is None
    pid = rad.observe(p2)  # second prompt through the shared preamble
    assert pid is not None
    assert rad.lookup(p2) == (pid, 6)  # the 6-token divergence point
    # engine-path exactness: suffix prefill over the registered cache
    uid = eng.submit(p2[6:], max_new_tokens=4, prefix_id=pid)
    eng.run()
    np.testing.assert_array_equal(eng.poll(uid), _reference(tiny_llama, p2, 4))
    st = rad.stats()
    assert st["hits"] == 1 and st["registrations"] == 1
    assert eng.metrics.prefix_hits == 1 and eng.metrics.prefix_tokens_reused == 6


def test_radix_min_tokens_and_proper_prefix_rules(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=8, promote_after=2)
    short = np.arange(1, 6, dtype=np.int32)  # 5-token LCP < min 8
    rad.observe(np.concatenate([short, [9]]))
    assert rad.observe(np.concatenate([short, [10]])) is None
    # a prompt EQUAL to a registered prefix must not match (no suffix)
    rad2 = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = np.arange(20, 29, dtype=np.int32)
    rad2.observe(np.concatenate([pre, [1]]))
    pid = rad2.observe(np.concatenate([pre, [2]]))
    assert pid is not None
    assert rad2.lookup(pre) is None  # nothing left to prefill
    assert rad2.lookup(np.concatenate([pre, [3]])) == (pid, 9)


def test_radix_lru_eviction_frees_engine_prefix(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2, max_entries=1)
    pre_a = np.arange(1, 6, dtype=np.int32)
    pre_b = np.arange(30, 36, dtype=np.int32)
    rad.observe(np.concatenate([pre_a, [7]]))
    pid_a = rad.observe(np.concatenate([pre_a, [8]]))
    assert pid_a is not None and len(eng._prefixes) == 1
    rad.observe(np.concatenate([pre_b, [7]]))
    pid_b = rad.observe(np.concatenate([pre_b, [8]]))
    assert pid_b is not None
    # budget 1: the older entry was unregistered from the engine too
    assert rad.stats()["evictions"] == 1 and len(rad.entries) == 1
    assert pid_a not in eng._prefixes and pid_b in eng._prefixes
    assert eng.metrics.prefix_evictions == 1
    assert rad.lookup(np.concatenate([pre_a, [9]])) is None


def test_radix_eviction_skips_referenced_entry(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2, max_entries=1)
    pre_a = np.arange(1, 6, dtype=np.int32)
    rad.observe(np.concatenate([pre_a, [7]]))
    pid_a = rad.observe(np.concatenate([pre_a, [8]]))
    m = rad.lookup(np.concatenate([pre_a, [9]]))
    eng.submit(np.asarray([9], np.int32), max_new_tokens=2, prefix_id=m[0])
    # a queued request pins pid_a: the new registration may not evict it
    pre_b = np.arange(30, 36, dtype=np.int32)
    rad.observe(np.concatenate([pre_b, [7]]))
    rad.observe(np.concatenate([pre_b, [8]]))
    assert pid_a in eng._prefixes  # still registered (referenced)
    assert len(rad.entries) == 2  # over budget until the reference drains
    eng.run()
    pre_c = np.arange(60, 66, dtype=np.int32)
    rad.observe(np.concatenate([pre_c, [7]]))
    rad.observe(np.concatenate([pre_c, [8]]))
    assert len(rad.entries) <= 2  # eviction caught up after the drain


def test_radix_invalidate(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = np.arange(1, 7, dtype=np.int32)
    rad.observe(np.concatenate([pre, [1]]))
    pid = rad.observe(np.concatenate([pre, [2]]))
    assert rad.invalidate(pid) == 1
    assert rad.lookup(np.concatenate([pre, [3]])) is None
    assert pid not in eng._prefixes
    with pytest.raises(ValueError, match="unknown prefix_id"):
        rad.invalidate(pid)


# --------------------------------------------------------------------- #
# KV handoff (engine surface)
# --------------------------------------------------------------------- #


def test_handoff_token_and_logprob_exact_dense_and_paged(tiny_llama):
    prompt = (np.arange(1, 10) % 250).astype(np.int32)
    ref = _reference(tiny_llama, prompt, 5)
    src = _engine(tiny_llama)
    h = src.prefill_detached(prompt, max_new_tokens=5, uid_key=3)
    for dst_kw in ({}, {"paged_block_size": 4}):
        dst = _engine(tiny_llama, **dst_kw)
        uid = dst.submit_prefilled(dict(h))
        dst.run()
        np.testing.assert_array_equal(dst.poll(uid), ref)
        # logprob-exact vs a local submit on a fresh engine
        local = _engine(tiny_llama)
        lu = local.submit(prompt, max_new_tokens=5)
        local.run()
        np.testing.assert_array_equal(dst.logprobs(uid), local.logprobs(lu))


def test_handoff_sampled_stream_matches_local_submit(tiny_llama):
    """temperature>0: the handoff carries the advanced sampling chain, so
    a disaggregated request's sampled stream equals the single-engine
    stream for the same (seed, uid)."""
    prompt = (np.arange(1, 9) % 250).astype(np.int32)
    local = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    lu = local.submit(prompt, max_new_tokens=6)
    local.run()
    src = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    dst = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    uid = dst.submit_prefilled(src.prefill_detached(prompt, max_new_tokens=6, uid_key=lu))
    dst.run()
    np.testing.assert_array_equal(dst.poll(uid), local.poll(lu))
    np.testing.assert_array_equal(dst.logprobs(uid), local.logprobs(lu))


def test_handoff_bytes_match_costmodel_prediction(tiny_llama):
    from accelerate_tpu.analysis.costmodel import price_kv_handoff

    eng = _engine(tiny_llama)
    per_tok, fixed = eng.kv_handoff_dims()
    assert per_tok > 0
    for n in (3, 8, 11):
        prompt = (np.arange(1, n + 1) % 250).astype(np.int32)
        h = eng.prefill_detached(prompt, max_new_tokens=2, uid_key=n)
        pred = price_kv_handoff(per_tok, n, fixed_bytes=fixed, generation="cpu")
        assert pred["bytes"] == h["wire_bytes"] == per_tok * n + fixed
        assert pred["time_us"] > 0


def test_handoff_validation(tiny_llama):
    eng = _engine(tiny_llama)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.prefill_detached(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="exceeds the slot cache"):
        eng.prefill_detached(np.ones((8,), np.int32), max_new_tokens=150)
    h = eng.prefill_detached(np.ones((4,), np.int32), max_new_tokens=4)
    bad = dict(h)
    bad["total"] = 3
    with pytest.raises(ValueError, match="handoff total"):
        eng.submit_prefilled(bad)
    big = dict(h)
    big["max_new_tokens"] = 150
    with pytest.raises(ValueError, match="exceeds the slot cache"):
        eng.submit_prefilled(big)


def test_handoff_request_survives_preemption(tiny_llama):
    """A handed-off request evicted mid-decode resumes by ordinary
    recompute (the handoff is consumed at first admission) and stays
    token-exact."""
    from accelerate_tpu.scheduling import SchedulerConfig

    prompt = (np.arange(1, 9) % 250).astype(np.int32)
    ref = _reference(tiny_llama, prompt, 8)
    src = _engine(tiny_llama)
    dst = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4, 8), tick_block=2,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    uid = dst.submit_prefilled(
        src.prefill_detached(prompt, max_new_tokens=8, uid_key=0), priority=1
    )
    dst.step()  # handoff admitted, decoding
    assert dst.partial(uid).size > 0
    hi = dst.submit(np.asarray([5, 6], np.int32), max_new_tokens=2, priority=0)
    dst.run()  # priority-0 arrival preempts the handoff decode
    assert dst.metrics.decode_preemptions >= 1
    np.testing.assert_array_equal(dst.poll(uid), ref)
    assert dst.poll(hi) is not None


# --------------------------------------------------------------------- #
# the router
# --------------------------------------------------------------------- #


def test_fleet_outputs_exact_and_prefix_affinity(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(min_prefix_tokens=4, promote_after=2),
        num_slots=2, prompt_buckets=(4, 8),
    )
    pre = (np.arange(1, 7) % 250).astype(np.int32)
    prompts = [np.concatenate([pre, [40 + i]]).astype(np.int32) for i in range(6)]
    uids = [fr.submit(p, max_new_tokens=4) for p in prompts]
    out = fr.run()
    for u, p in zip(uids, prompts):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 4))
    stats = fr.radix_stats()
    # after promotion, affinity routes every preamble-sharing request to
    # the owning replica: exactly one replica holds the entry + the hits
    owners = [n for n, s in stats.items() if s["entries"] > 0]
    assert len(owners) == 1
    assert stats[owners[0]]["hits"] >= 1
    merged = fr.metrics_merged()
    assert merged.prefix_hits == sum(s["hits"] for s in stats.values())
    assert merged.requests_completed == len(prompts)


def test_fleet_no_reuse_config(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    assert all(r.radix is None for r in fr.replicas)
    p = (np.arange(1, 9) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


def test_fleet_level_shed(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(routing=RoutingConfig(max_fleet_queue_depth=1), prefix_reuse=False),
        num_slots=1, prompt_buckets=(4, 8),
    )
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2)
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2)
    # aggregate queue depth (minus in-flight) crosses the fleet SLO for a
    # sheddable class; priority 0 stays admissible
    with pytest.raises(ShedError, match="fleet queue depth"):
        while True:
            fr.submit(np.ones((4,), np.int32), max_new_tokens=2, priority=1)
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2, priority=0)
    assert fr.fleet_shed == 1
    fr.run()


def test_fleet_disaggregated_exact_and_accounted(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="always", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    prompts = [(np.arange(1, 8 + i) % 250).astype(np.int32) for i in range(3)]
    uids = [fr.submit(p, max_new_tokens=4) for p in prompts]
    out = fr.run()
    for u, p in zip(uids, prompts):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 4))
    acct = fr.handoff_accounting()
    assert acct["handoffs"] == 3
    assert acct["bytes_predicted"] == acct["bytes_moved"] > 0
    # decode replica did all the decoding; prefill replica served no slots
    assert fr.replicas[1].engine.metrics.requests_completed == 3
    assert fr.replicas[0].engine.metrics.requests_completed == 0


def test_fleet_disaggregated_auto_decision(tiny_llama):
    """auto mode prices every candidate transfer BEFORE it happens and
    takes exactly one decision per request (handoff or local re-prefill),
    and handoff=never pins the local path."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="auto", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u = fr.submit((np.arange(1, 9) % 250).astype(np.int32), max_new_tokens=3)
    out = fr.run()
    assert u in out
    acct = fr.handoff_accounting()
    assert acct["handoffs"] + acct["handoffs_local"] == 1
    fr2 = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="never", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u2 = fr2.submit((np.arange(1, 9) % 250).astype(np.int32), max_new_tokens=3)
    out2 = fr2.run()
    np.testing.assert_array_equal(out2[u2], _reference(tiny_llama, (np.arange(1, 9) % 250), 3))
    assert fr2.handoff_accounting() == {
        "handoffs": 0, "handoffs_local": 1, "bytes_predicted": 0,
        "bytes_moved": 0, "time_us_predicted": 0.0,
    }


def test_fleet_partial_logprobs_cancel(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
        num_slots=1, prompt_buckets=(4, 8), tick_block=2,
    )
    p = (np.arange(1, 9) % 250).astype(np.int32)
    u1 = fr.submit(p, max_new_tokens=6)
    u2 = fr.submit(p, max_new_tokens=6)
    assert fr.partial(u1).size == 0 and fr.poll(u1) is None
    fr.step()
    got = fr.cancel(u2)
    assert isinstance(got, np.ndarray)
    with pytest.raises(KeyError):
        fr.partial(u2)
    fr.run()
    assert fr.poll(u1) is not None
    assert fr.logprobs(u1).shape[0] == len(fr.partial(u1))
    with pytest.raises(KeyError, match="unknown request id"):
        fr.poll(10_000)


def test_fleet_drain_threaded_matches_sequential(tiny_llama):
    prompts = [(np.arange(1, 5 + i) % 250).astype(np.int32) for i in range(8)]
    outs = {}
    for mode in ("seq", "thr"):
        fr = FleetRouter.from_model(
            tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
            num_slots=2, prompt_buckets=(4, 8),
        )
        uids = [fr.submit(p, max_new_tokens=3) for p in prompts]
        if mode == "thr":
            fr.drain_threaded()
        out = fr.run()  # seq drive / collect
        outs[mode] = [out[u] for u in uids]
    for a, b in zip(outs["seq"], outs["thr"]):
        np.testing.assert_array_equal(a, b)


def test_fleet_watchdog_silent_across_radix_hits_and_misses(tiny_llama):
    """Post-warmup compile count stays 0 across prefix registrations,
    hits, misses, and evictions — the recompile-watchdog discipline at
    fleet level."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1,
        config=FleetConfig(min_prefix_tokens=4, promote_after=2, max_prefix_entries=1),
        num_slots=2, prompt_buckets=(4, 8),
    )
    eng = fr.replicas[0].engine
    rng = np.random.default_rng(0)
    # warm every width: buckets, chunk windows, prefix-suffix windows
    for n in (4, 8, 10, 13):
        eng.submit(rng.integers(1, 250, size=n).astype(np.int32), max_new_tokens=2)
    eng.run()
    pid = eng.register_prefix(rng.integers(1, 250, size=9).astype(np.int32))
    for b in (4, 8):
        eng.submit(rng.integers(1, 250, size=b).astype(np.int32), max_new_tokens=2, prefix_id=pid)
    eng.run()
    eng.unregister_prefix(pid)
    c0 = eng.program_cache.misses
    pre_a = rng.integers(1, 250, size=6).astype(np.int32)
    pre_b = rng.integers(1, 250, size=7).astype(np.int32)
    uids = []
    for pre in (pre_a, pre_a, pre_a, pre_b, pre_b, pre_b):
        sfx = rng.integers(1, 250, size=int(rng.integers(2, 5))).astype(np.int32)
        uids.append(fr.submit(np.concatenate([pre, sfx]), max_new_tokens=3))
    out = fr.run()
    assert len(out) == len(uids)
    stats = fr.radix_stats()["r0"]
    assert stats["registrations"] >= 2 and stats["hits"] >= 2
    assert eng.program_cache.misses - c0 == 0, "radix traffic must not compile"


def test_fleet_spin_up_warm_starts_from_shared_store(tiny_llama, tmp_path):
    """In-process spin-up over a shared store: every program either
    deserializes or is a reject-and-heal recompile — never a silent cold
    compile. (The STRICT 0-compile contract holds for fresh-process
    replicas — bench_serving --fleet and the subprocess test below — and
    in-process under a single-device backend; under the suite's 8-device
    fake mesh XLA:CPU can emit non-self-contained blobs from a long-lived
    process, the PR-7-documented class the reject path heals.)"""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1, config=FleetConfig(prefix_reuse=False),
        store_dir=str(tmp_path / "fleet_store"),
        num_slots=2, prompt_buckets=(4, 8),
    )
    cold = fr.spin_up(warm_prompt_lens=(4,))
    assert cold["compiles"] > 0 and cold["deserialized"] == 0
    warm = fr.spin_up(warm_prompt_lens=(4,))
    pc = fr.replicas[2].engine.program_cache
    assert warm["deserialized"] > 0
    assert warm["compiles"] == pc.rejected, "only healed rejects may recompile"
    assert warm["deserialized"] + warm["compiles"] == cold["compiles"]
    assert len(fr.replicas) == 3
    # the spun-up replica serves real traffic
    p = (np.arange(1, 6) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


# --------------------------------------------------------------------- #
# fault tolerance: health machine, token-exact failover, chaos matrix
# --------------------------------------------------------------------- #

_FT_PROMPTS = [(np.arange(1, 6 + i) % 250).astype(np.int32) for i in range(6)]
_FT_NEW = 4


def _ft_fleet(model, *, failover="auto", tick_block=8, **cfg_kw):
    cfg_kw.setdefault("prefix_reuse", False)
    return FleetRouter.from_model(
        model, num_replicas=2, config=FleetConfig(failover=failover, **cfg_kw),
        num_slots=2, prompt_buckets=(4, 8), tick_block=tick_block,
    )


@pytest.fixture(scope="module")
def ft_control(tiny_llama):
    """No-fault control run of the chaos workload: per-submission-index
    full token streams and logprobs every chaos arm must reproduce."""
    fr = _ft_fleet(tiny_llama)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS]
    out = fr.run()
    ctl = [(np.asarray(out[u]), np.asarray(fr.logprobs(u))) for u in uids]
    import jax

    jax.clear_caches()
    return ctl


@pytest.mark.parametrize("failover", ["recompute", "handoff"])
@pytest.mark.parametrize("label", ["pre_tick", "mid_prefill", "mid_decode"])
def test_chaos_crash_matrix_token_and_logprob_exact(tiny_llama, ft_control, label, failover):
    """The crash-at-every-point failover matrix: kill replica r0 at each
    labeled serving point with requests queued, mid-prefill, and
    mid-decode; every in-flight request must complete on the survivor
    token- AND logprob-exact vs the no-fault control, zero lost, zero
    duplicated — whichever migration path the router is pinned to."""
    fr = _ft_fleet(tiny_llama, failover=failover)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS]
    fr.step()  # some requests decoding on r0, one still queued
    with ReplicaChaos(label, replica="r0", action="crash") as chaos:
        out = fr.run()
    assert chaos.fired
    assert fr.health()["r0"]["health"] == "dead"
    assert sorted(out) == sorted(uids)  # all complete, none duplicated
    for u, (ref_toks, ref_lps) in zip(uids, ft_control):
        np.testing.assert_array_equal(out[u], ref_toks)
        np.testing.assert_array_equal(fr.logprobs(u), ref_lps)
    acct = fr.failover_accounting()
    assert acct["failovers"] >= 1 and acct["failovers_lost"] == 0
    if failover == "recompute":
        assert acct["failovers_kv"] == 0


def test_chaos_pre_handoff_disaggregated_fails_over(tiny_llama):
    """Killing the prefill replica at the pre_handoff dispatch point must
    not lose the pending requests: the dispatcher requeues them, marks
    the prefill replica dead, and the decode replica self-prefills with
    the same uid_key — token-exact."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="always", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    prompts = [(np.arange(1, 8 + i) % 250).astype(np.int32) for i in range(3)]
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in prompts]
    with ReplicaChaos("pre_handoff", replica="r0", action="crash") as chaos:
        out = fr.run()
    assert chaos.fired
    assert fr.health()["r0"]["health"] == "dead"
    assert fr.failover_accounting()["failovers_lost"] == 0
    for u, p in zip(uids, prompts):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, _FT_NEW))


def test_chaos_poison_quarantines_and_never_ships_kv(tiny_llama, ft_control):
    """A non-finite watchdog trip quarantines (numerics suspect, the
    replica itself may be fine) and fails over by recompute ONLY — the
    poisoned KV must never be pasted into a survivor."""
    fr = _ft_fleet(tiny_llama, failover="auto")
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS]
    fr.step()
    with ReplicaChaos("mid_decode", replica="r0", action="poison") as chaos:
        out = fr.run()
    assert chaos.fired
    h = fr.health()["r0"]
    assert h["health"] == "quarantined" and "NonFinitePoison" in h["last_error"]
    acct = fr.failover_accounting()
    assert acct["failovers"] >= 1 and acct["failovers_kv"] == 0
    assert acct["failovers_lost"] == 0 and acct["bytes_moved"] == 0
    for u, (ref_toks, ref_lps) in zip(uids, ft_control):
        np.testing.assert_array_equal(out[u], ref_toks)
        np.testing.assert_array_equal(fr.logprobs(u), ref_lps)


@pytest.mark.parametrize("failover", ["recompute", "handoff"])
def test_chaos_sampled_failover_exact(tiny_llama, failover):
    """temperature>0: the exported key_data pins each request's sampling
    chain, so a failed-over sampled stream equals the no-fault control —
    over the KV-paste path AND the full recompute path."""
    prompts = [(np.arange(1, 7 + i) % 250).astype(np.int32) for i in range(4)]

    def build():
        return FleetRouter.from_model(
            tiny_llama, num_replicas=2,
            config=FleetConfig(prefix_reuse=False, failover=failover),
            num_slots=2, prompt_buckets=(4, 8), tick_block=2, temperature=0.9, seed=7,
        )

    ctl = build()
    cu = [ctl.submit(p, max_new_tokens=_FT_NEW) for p in prompts]
    ctl_out = ctl.run()
    fr = build()
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in prompts]
    fr.step()
    with ReplicaChaos("pre_tick", replica="r0", action="crash") as chaos:
        out = fr.run()
    assert chaos.fired and fr.failover_accounting()["failovers"] >= 1
    for u, c in zip(uids, cu):
        np.testing.assert_array_equal(out[u], ctl_out[c])
        np.testing.assert_array_equal(fr.logprobs(u), ctl.logprobs(c))


def test_chaos_survivor_serves_with_zero_new_compiles(tiny_llama):
    """The recompile-watchdog discipline survives a replica death: after
    warming fused buckets, chunk windows, and the decode tick on the
    survivor, absorbing r0's failed-over load compiles NOTHING new."""
    fr = _ft_fleet(tiny_llama, failover="handoff")
    rng = np.random.default_rng(3)
    for rep in fr.replicas:  # warm both so pre-crash traffic is covered too
        for n in (4, 8, 10, 13):
            rep.engine.submit(rng.integers(1, 250, size=n).astype(np.int32), max_new_tokens=2)
        rep.engine.run()
        # the KV paste sees host-resident arrays — a distinct signature
        h = fr.replicas[0].engine.prefill_detached(
            rng.integers(1, 250, size=4).astype(np.int32), max_new_tokens=2, uid_key=2**30
        )
        rep.engine.submit_prefilled(dict(h))
        rep.engine.run()
    survivor = fr.replicas[1].engine
    c0 = survivor.program_cache.misses
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS]
    fr.step()
    with ReplicaChaos("pre_tick", replica="r0", action="crash"):
        out = fr.run()
    assert sorted(out) == sorted(uids)
    assert fr.failover_accounting()["failovers"] >= 1
    assert survivor.program_cache.misses - c0 == 0, "failover absorption must not compile"


def test_failover_priced_before_it_happens_and_pinned(tiny_llama):
    """The router prices every KV failover with the costmodel BEFORE
    moving bytes; the accounting pins prediction == actual bytes moved
    (and carries the recompute alternative it was judged against)."""
    fr = _ft_fleet(tiny_llama, failover="handoff", tick_block=2)
    uids = [fr.submit(p, max_new_tokens=6) for p in _FT_PROMPTS[:4]]
    fr.step()  # decode phase on both replicas -> exports carry KV rows
    with ReplicaChaos("pre_tick", replica="r0", action="crash"):
        out = fr.run()
    assert sorted(out) == sorted(uids)
    acct = fr.failover_accounting()
    assert acct["failovers_kv"] >= 1
    assert acct["bytes_predicted"] == acct["bytes_moved"] > 0
    assert acct["time_us_predicted"] > 0


def test_price_failover_costmodel():
    from accelerate_tpu.analysis.costmodel import price_failover

    p = price_failover(4096, 512, 100, 7_000_000_000)
    assert p["rows"] == 611 and p["handoff"]["bytes"] >= 4096 * 611
    assert p["path"] in ("handoff", "recompute")
    # KV not exportable (paged / speculative / poisoned) -> recompute,
    # even when the wire would have been cheaper
    assert price_failover(4096, 512, 100, 7_000_000_000, kv_exportable=False)["path"] == "recompute"
    # a zero-generated failover still re-prefills the full prompt
    assert price_failover(4096, 16, 0, 7_000_000_000)["rows"] == 16


def test_hang_degrades_then_quarantines_and_heals(tiny_llama):
    """Tick-timeout state machine: one slow tick degrades, consecutive
    slow ticks quarantine (work migrates with KV intact — the tick
    finished, just late); a degraded replica heals after clean ticks."""
    fr = _ft_fleet(tiny_llama, tick_block=2, quarantine_after_timeouts=2, heal_after_ticks=3)
    rng = np.random.default_rng(11)
    for rep in fr.replicas:  # every program compiles OUTSIDE the timeout window
        for n in (4, 8, 10, 13):
            rep.engine.submit(rng.integers(1, 250, size=n).astype(np.int32), max_new_tokens=2)
        rep.engine.run()
    uids = [fr.submit(p, max_new_tokens=8) for p in _FT_PROMPTS[:4]]
    fr.step()
    fr.config.tick_timeout_s = 0.05
    with ReplicaChaos("pre_tick", replica="r0", action="hang", hang_s=0.2, repeat=True):
        fr.step()
        assert fr.health()["r0"]["health"] == "degraded"
        out = fr.run()  # second slow tick -> quarantined, work migrates
    assert fr.health()["r0"]["health"] == "quarantined"
    assert sorted(out) == sorted(uids)
    assert fr.failover_accounting()["failovers_lost"] == 0
    for u, p in zip(uids, _FT_PROMPTS):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 8))
    # heal: a single hiccup degrades, then clean BUSY ticks restore healthy
    fr2 = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(prefix_reuse=False, heal_after_ticks=2),
        num_slots=2, prompt_buckets=(4, 8), tick_block=2,
    )
    warm = fr2.replicas[0].engine
    warm.submit((np.arange(1, 5) % 250).astype(np.int32), max_new_tokens=4)
    warm.run()  # prefill + decode programs compiled OUTSIDE the window
    fr2.submit((np.arange(1, 5) % 250).astype(np.int32), max_new_tokens=10)
    fr2.step()
    fr2.config.tick_timeout_s = 0.05
    with ReplicaChaos("pre_tick", replica="r0", action="hang", hang_s=0.2):
        fr2.step()
    assert fr2.health()["r0"]["health"] == "degraded"
    fr2.step()  # tick_block=2: plenty of clean busy ticks left
    fr2.step()
    assert fr2.health()["r0"]["health"] == "healthy"


def test_drain_under_load_and_unique_respawn_names(tiny_llama):
    """drain() migrates every in-flight request and removes the replica
    without losing a token; a later add_replica must never reuse a
    retired name."""
    fr = _ft_fleet(tiny_llama)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS[:4]]
    fr.step()
    res = fr.drain("r0")
    assert res["replica"] == "r0" and res["lost"] == 0
    assert [r.name for r in fr.replicas] == ["r1"]
    out = fr.run()
    assert sorted(out) == sorted(uids)
    for u, p in zip(uids, _FT_PROMPTS):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, _FT_NEW))
    info = fr.add_replica(warm_prompt_lens=(4,))
    names = [r.name for r in fr.replicas]
    assert names == ["r1", "r2"], "retired names must never be reused"
    assert info["replica"] == "r2"
    u = fr.submit(_FT_PROMPTS[0], max_new_tokens=2)
    assert u in fr.run()
    fr.drain("r1")
    with pytest.raises(ValueError, match="no other serving replica"):
        fr.drain("r2")


def test_capacity_lost_sheds_until_add_replica(tiny_llama):
    """Killing the last serving replica sheds new submissions at the
    fleet edge with a structured ShedError; add_replica restores
    admission (the zero-compile spin-up path) and the fleet serves
    again."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1, config=FleetConfig(prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u_doomed = fr.submit(_FT_PROMPTS[0], max_new_tokens=2)
    fr.fail_replica("r0")
    assert fr.health()["r0"]["health"] == "dead"
    # nowhere to migrate: the in-flight request is honestly LOST
    assert fr.failover_accounting()["failovers_lost"] == 1
    with pytest.raises(KeyError, match="lost"):
        fr.poll(u_doomed)
    with pytest.raises(ShedError, match="capacity lost"):
        fr.submit(_FT_PROMPTS[1], max_new_tokens=2)
    fr.add_replica(warm_prompt_lens=(4,))
    p = (np.arange(1, 6) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


def test_chaos_poison_sole_replica_capacity_lost(tiny_llama):
    """Poisoning the ONLY replica quarantines it with nowhere to migrate:
    the in-flight request is honestly lost (allow_kv=False — nothing is
    pasted anywhere), the breaker sheds new submissions, and add_replica
    restores service. Pins the model checker's poison/capacity_lost
    path (analysis.fleet_rules.CHAOS_COVERAGE)."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1, config=FleetConfig(prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u_doomed = fr.submit(_FT_PROMPTS[0], max_new_tokens=2)
    fr.fail_replica("r0", error=RuntimeError("nonfinite logits from watchdog"))
    h = fr.health()["r0"]
    assert h["health"] == "quarantined" and "nonfinite" in h["last_error"]
    acct = fr.failover_accounting()
    assert acct["failovers_lost"] == 1 and acct["failovers_kv"] == 0
    with pytest.raises(KeyError, match="lost"):
        fr.poll(u_doomed)
    with pytest.raises(ShedError, match="capacity lost"):
        fr.submit(_FT_PROMPTS[1], max_new_tokens=2)
    fr.add_replica(warm_prompt_lens=(4,))
    p = (np.arange(1, 6) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


def test_chaos_hang_sole_replica_capacity_lost(tiny_llama):
    """Repeated tick timeouts on the ONLY replica quarantine it with no
    survivor to take the work: lost-with-reason, breaker sheds, and
    add_replica recovers. Pins the model checker's timeout/capacity_lost
    path (analysis.fleet_rules.CHAOS_COVERAGE)."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1,
        config=FleetConfig(prefix_reuse=False, quarantine_after_timeouts=2),
        num_slots=2, prompt_buckets=(4, 8), tick_block=2,
    )
    warm = fr.replicas[0].engine
    warm.submit((np.arange(1, 5) % 250).astype(np.int32), max_new_tokens=4)
    warm.run()  # prefill + decode compiled OUTSIDE the timeout window
    u_doomed = fr.submit((np.arange(1, 5) % 250).astype(np.int32), max_new_tokens=10)
    fr.step()
    fr.config.tick_timeout_s = 0.05
    with ReplicaChaos("pre_tick", replica="r0", action="hang", hang_s=0.2, repeat=True):
        fr.step()
        assert fr.health()["r0"]["health"] == "degraded"
        fr.step()
    assert fr.health()["r0"]["health"] == "quarantined"
    assert fr.failover_accounting()["failovers_lost"] == 1
    with pytest.raises(KeyError, match="lost"):
        fr.poll(u_doomed)
    with pytest.raises(ShedError, match="capacity lost"):
        fr.submit(_FT_PROMPTS[1], max_new_tokens=2)
    fr.add_replica(warm_prompt_lens=(4,))
    p = (np.arange(1, 6) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


def test_drain_threaded_health_writes_hold_replica_lock(tiny_llama):
    """Regression for the dogfooded TPU902: _set_health mutates
    Replica.health under rep.lock and the drain_threaded workers read
    is_serving under the same lock, so a mid-drain failover can't tear a
    transition. Hammer a threaded drain with a mid-flight crash — the
    pre-fix race window — and hold the PR-15 exactness claims."""
    fr = _ft_fleet(tiny_llama)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS[:4]]
    with ReplicaChaos("pre_tick", replica="r0", action="crash") as chaos:
        fr.drain_threaded()
    assert chaos.fired
    assert fr.health()["r0"]["health"] == "dead"
    out = {u: fr.poll(u) for u in uids}
    for u, p in zip(uids, _FT_PROMPTS):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, _FT_NEW))
    # the static gate that keeps the fix fixed
    from accelerate_tpu.analysis.hostsim import host_check_file

    fleet_src = os.path.join(REPO, "accelerate_tpu", "serving_fleet.py")
    assert [f.rule for f in host_check_file(fleet_src)] == []


def test_fleet_request_error_surfaces(tiny_llama, monkeypatch):
    """poll/partial/logprobs/cancel on unknown or failed-over ids raise
    the structured error naming the last known state; cancel on a dead
    replica succeeds WITHOUT touching the dead engine."""
    fr = _ft_fleet(tiny_llama)
    with pytest.raises(FleetRequestError, match="unknown request id"):
        fr.poll(12345)
    with pytest.raises(KeyError):  # it is still a KeyError for old callers
        fr.logprobs(12345)
    # lost: export dies with the replica -> nothing to salvage
    u1 = fr.submit(_FT_PROMPTS[0], max_new_tokens=_FT_NEW)
    monkeypatch.setattr(
        fr.replicas[0].engine, "export_inflight",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("export channel down")),
    )
    fr.fail_replica("r0", error=RuntimeError("host unreachable"))
    with pytest.raises(FleetRequestError, match="no snapshot recovered"):
        fr.partial(u1)
    got = fr.cancel(u1)  # cancelling a lost request succeeds, once
    assert isinstance(got, np.ndarray) and got.size == 0
    with pytest.raises(FleetRequestError, match="unknown request id"):
        fr.cancel(u1)
    # stranded on a dead replica (white-box: dodge the auto-migration)
    fr2 = _ft_fleet(tiny_llama)
    u2 = fr2.submit(_FT_PROMPTS[0], max_new_tokens=_FT_NEW)
    fr2.step()
    fr2.replicas[0].health = "dead"
    fr2.replicas[0].last_error = "RuntimeError: kernel panic"
    with pytest.raises(FleetRequestError, match="dead replica 'r0'"):
        fr2.poll(u2)
    called = []
    monkeypatch.setattr(fr2.replicas[0].engine, "cancel",
                        lambda uid: called.append(uid))
    got2 = fr2.cancel(u2)
    assert got2.size == 0 and called == [], "must not touch the dead engine"
    # done requests refuse cancel with a pointer to poll()
    fr3 = _ft_fleet(tiny_llama)
    u3 = fr3.submit(_FT_PROMPTS[0], max_new_tokens=2)
    fr3.run()
    fr3.drain("r0") if fr3._map[u3][1] == 0 else fr3.drain("r1")
    with pytest.raises(ValueError, match="poll"):
        fr3.cancel(u3)


def test_handoff_codec_roundtrip_exact(tiny_llama):
    """The wire codec: a prefill_detached payload serializes to ONE bytes
    blob and back (dtype-agnostic — the receiving engine's row template
    is the source of truth) with the decoded handoff token- and
    logprob-exact, greedy and sampled."""
    prompt = (np.arange(1, 10) % 250).astype(np.int32)
    for kw in ({}, {"temperature": 0.9, "seed": 5}):
        src = _engine(tiny_llama, **kw)
        local = _engine(tiny_llama, **kw)
        lu = local.submit(prompt, max_new_tokens=5)
        local.run()
        h = src.prefill_detached(prompt, max_new_tokens=5, uid_key=lu)
        blob = HandoffCodec.encode(h)
        assert isinstance(blob, bytes) and len(blob) >= h["wire_bytes"]
        dst = _engine(tiny_llama, **kw)
        h2 = HandoffCodec.decode(blob, dst)
        assert h2["total"] == h["total"] and h2["wire_bytes"] == h["wire_bytes"]
        uid = dst.submit_prefilled(h2)
        dst.run()
        np.testing.assert_array_equal(dst.poll(uid), local.poll(lu))
        np.testing.assert_array_equal(dst.logprobs(uid), local.logprobs(lu))


def test_drain_threaded_surfaces_and_survives_worker_crash(tiny_llama):
    """drain_threaded must never hang on a worker death: with a survivor
    the fleet completes via failover (the fault surfaces through health
    + metrics); with NO survivor the first captured exception is
    re-raised on the caller's thread after join."""
    fr = _ft_fleet(tiny_llama)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS[:4]]
    with ReplicaChaos("pre_tick", replica="r0", action="crash") as chaos:
        fr.drain_threaded()
    assert chaos.fired
    assert fr.health()["r0"]["health"] == "dead"
    for u, p in zip(uids, _FT_PROMPTS):
        np.testing.assert_array_equal(fr.poll(u), _reference(tiny_llama, p, _FT_NEW))
    solo = FleetRouter.from_model(
        tiny_llama, num_replicas=1, config=FleetConfig(prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    solo.submit(_FT_PROMPTS[0], max_new_tokens=2)
    with ReplicaChaos("pre_tick", replica="r0", action="crash"):
        with pytest.raises(SimulatedCrash):
            solo.drain_threaded()


def test_failover_metrics_and_prometheus(tiny_llama):
    fr = _ft_fleet(tiny_llama, tick_block=2)
    uids = [fr.submit(p, max_new_tokens=_FT_NEW) for p in _FT_PROMPTS[:4]]
    fr.step()
    with ReplicaChaos("pre_tick", replica="r0", action="crash"):
        out = fr.run()
    assert sorted(out) == sorted(uids)
    m = fr.metrics_merged()
    snap = m.snapshot()
    assert snap["failovers_out"] >= 1 and snap["failovers_in"] >= 1
    assert snap["failovers_lost"] == 0 and snap["replica_errors"] == 1
    assert snap["replica_state"] == 3  # merged gauge: worst replica (dead)
    text = m.prometheus_text()
    for needle in ("failovers_in_total", "failovers_out_total", "failovers_lost_total",
                   "replica_errors_total", 'replica_state{replica="fleet"} 3'):
        assert needle in text, needle


def test_failover_handoff_leg_retries_transient_io(tiny_llama, monkeypatch):
    """The KV import leg rides utils.retry: one transient OSError on the
    destination must not lose the request or downgrade it to recompute."""
    fr = _ft_fleet(tiny_llama, failover="handoff", tick_block=2, failover_retry_base_delay_s=0.001)
    uids = [fr.submit(p, max_new_tokens=6) for p in _FT_PROMPTS[:4]]
    fr.step()
    dst = fr.replicas[1].engine
    real = dst.import_inflight
    flaky = {"left": 1}

    def import_flaky(snap):
        if snap.get("cache") is not None and flaky["left"]:
            flaky["left"] -= 1
            raise OSError("transient transport failure")
        return real(snap)

    monkeypatch.setattr(dst, "import_inflight", import_flaky)
    with ReplicaChaos("pre_tick", replica="r0", action="crash"):
        out = fr.run()
    assert sorted(out) == sorted(uids)
    assert flaky["left"] == 0  # the fault actually fired
    acct = fr.failover_accounting()
    assert acct["failovers_kv"] >= 1 and acct["failovers_lost"] == 0
    for u, p in zip(uids, _FT_PROMPTS):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 6))


# --------------------------------------------------------------------- #
# fleet-level cross-process warm spin-up (promotes the PR-7 test)
# --------------------------------------------------------------------- #

_CHILD_FLEET_REPLICA = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from accelerate_tpu.utils.environment import force_host_platform
force_host_platform(1)
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.serving_fleet import FleetConfig, FleetRouter

model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
router = FleetRouter.from_model(
    model, num_replicas=1,
    config=FleetConfig(min_prefix_tokens=4, promote_after=2),
    store_dir={store!r}, num_slots=2, prompt_buckets=(4, 8),
)
pre = (np.arange(1, 7) % 250).astype(np.int32)
prompts = [np.concatenate([pre, [40 + i]]).astype(np.int32) for i in range(4)]
uids = [router.submit(p, max_new_tokens=3) for p in prompts]
out = router.run()
eng = router.replicas[0].engine
radix = router.radix_stats()["r0"]
toks = " ".join(str(t) for t in np.concatenate([out[u] for u in uids]))
print("FLEETREP", eng.program_cache.misses, eng.program_cache.deserialized,
      radix["hits"], radix["registrations"], toks)
"""


@pytest.mark.slow
def test_fleet_warm_replica_subprocess_zero_compiles(tmp_path):
    """The fleet-level warm-replica assertion: a FRESH SUBPROCESS builds
    a replica over the shared ExecutableStore and serves shared-preamble
    traffic with 0 XLA compiles — with its radix cache starting COLD
    (prefix registration replays the chunk programs from the store too).
    Promotes the PR-7 two-subprocess engine test to the fleet layer."""
    store = str(tmp_path / "store")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("XLA_FLAGS", None)

    def replica():
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_FLEET_REPLICA.format(repo=REPO, store=store)],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        tag, misses, deser, hits, regs, *tokens = out.stdout.strip().splitlines()[-1].split()
        assert tag == "FLEETREP"
        return int(misses), int(deser), int(hits), int(regs), tokens

    cold_misses, cold_deser, cold_hits, cold_regs, ref = replica()
    assert cold_misses >= 1 and cold_deser == 0
    assert cold_regs == 1 and cold_hits >= 1  # radix promoted + reused

    warm_misses, warm_deser, warm_hits, warm_regs, got = replica()
    assert warm_misses == 0, "warm fleet replica must not compile"
    assert warm_deser == cold_misses  # every program came from the store
    assert warm_regs == 1 and warm_hits == cold_hits  # radix started cold, re-promoted
    assert got == ref  # token-exact across processes
