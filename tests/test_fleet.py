"""Fleet-scale serving (serving_fleet.py): radix prefix cache semantics,
router policy, disaggregated KV handoff exactness + cost-model byte
accounting, fleet SLO shedding, and zero-compile replica spin-up."""

import os
import subprocess
import sys

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.scheduling import FleetRoutingPolicy, RoutingConfig, ShedError
from accelerate_tpu.serving import ServingEngine
from accelerate_tpu.serving_fleet import FleetConfig, FleetRouter, RadixPrefixCache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


@pytest.fixture(autouse=True)
def bound_live_executables_per_test():
    """This module builds several engines (= many resident programs) per
    test; clearing per TEST keeps the process-wide live-executable set
    tiny (the conftest-documented XLA:CPU late-fresh-compile segfault
    class). Cross-test recompiles hit the persistent disk cache."""
    yield
    import jax

    jax.clear_caches()


def _reference(model, prompt, n):
    return np.asarray(generate(model, np.asarray(prompt, np.int32)[None], max_new_tokens=n))[0]


def _engine(model, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("prompt_buckets", (4, 8))
    return ServingEngine(model, **kw)


# --------------------------------------------------------------------- #
# routing policy (scheduling.py)
# --------------------------------------------------------------------- #


def test_routing_policy_least_loaded_and_round_robin():
    p = FleetRoutingPolicy(RoutingConfig(policy="least_loaded"))
    assert p.pick_replica([3, 1, 2], [0, 1, 2]) == 1
    assert p.pick_replica([1, 1, 2], [0, 1, 2]) == 0  # tie -> lowest index
    assert p.pick_replica([0, 9, 0], [1, 2]) == 2  # eligibility filters
    rr = FleetRoutingPolicy(RoutingConfig(policy="round_robin"))
    picks = [rr.pick_replica([0, 0, 0], [0, 1, 2]) for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]


def test_routing_policy_fleet_shed_respects_priority_floor():
    p = FleetRoutingPolicy(RoutingConfig(max_fleet_queue_depth=4))
    assert p.shed_on_submit(0, 100) is None  # priority 0 unsheddable
    assert p.shed_on_submit(1, 3) is None
    assert "fleet queue depth" in p.shed_on_submit(1, 4)


def test_routing_config_validation():
    with pytest.raises(ValueError, match="policy"):
        RoutingConfig(policy="random")
    with pytest.raises(ValueError, match="max_fleet_queue_depth"):
        RoutingConfig(max_fleet_queue_depth=0)
    with pytest.raises(ValueError, match="roles"):
        FleetConfig(roles=("mixed", "oracle"))
    with pytest.raises(ValueError, match="handoff"):
        FleetConfig(handoff="sometimes")


# --------------------------------------------------------------------- #
# radix prefix cache
# --------------------------------------------------------------------- #


def test_radix_promotes_shared_preamble_and_reuse_is_exact(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = (np.arange(1, 7) % 250).astype(np.int32)
    p1 = np.concatenate([pre, [41, 42]]).astype(np.int32)
    p2 = np.concatenate([pre, [51, 52, 53]]).astype(np.int32)
    assert rad.lookup(p1) is None and rad.observe(p1) is None
    assert rad.lookup(p2) is None
    pid = rad.observe(p2)  # second prompt through the shared preamble
    assert pid is not None
    assert rad.lookup(p2) == (pid, 6)  # the 6-token divergence point
    # engine-path exactness: suffix prefill over the registered cache
    uid = eng.submit(p2[6:], max_new_tokens=4, prefix_id=pid)
    eng.run()
    np.testing.assert_array_equal(eng.poll(uid), _reference(tiny_llama, p2, 4))
    st = rad.stats()
    assert st["hits"] == 1 and st["registrations"] == 1
    assert eng.metrics.prefix_hits == 1 and eng.metrics.prefix_tokens_reused == 6


def test_radix_min_tokens_and_proper_prefix_rules(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=8, promote_after=2)
    short = np.arange(1, 6, dtype=np.int32)  # 5-token LCP < min 8
    rad.observe(np.concatenate([short, [9]]))
    assert rad.observe(np.concatenate([short, [10]])) is None
    # a prompt EQUAL to a registered prefix must not match (no suffix)
    rad2 = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = np.arange(20, 29, dtype=np.int32)
    rad2.observe(np.concatenate([pre, [1]]))
    pid = rad2.observe(np.concatenate([pre, [2]]))
    assert pid is not None
    assert rad2.lookup(pre) is None  # nothing left to prefill
    assert rad2.lookup(np.concatenate([pre, [3]])) == (pid, 9)


def test_radix_lru_eviction_frees_engine_prefix(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2, max_entries=1)
    pre_a = np.arange(1, 6, dtype=np.int32)
    pre_b = np.arange(30, 36, dtype=np.int32)
    rad.observe(np.concatenate([pre_a, [7]]))
    pid_a = rad.observe(np.concatenate([pre_a, [8]]))
    assert pid_a is not None and len(eng._prefixes) == 1
    rad.observe(np.concatenate([pre_b, [7]]))
    pid_b = rad.observe(np.concatenate([pre_b, [8]]))
    assert pid_b is not None
    # budget 1: the older entry was unregistered from the engine too
    assert rad.stats()["evictions"] == 1 and len(rad.entries) == 1
    assert pid_a not in eng._prefixes and pid_b in eng._prefixes
    assert eng.metrics.prefix_evictions == 1
    assert rad.lookup(np.concatenate([pre_a, [9]])) is None


def test_radix_eviction_skips_referenced_entry(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2, max_entries=1)
    pre_a = np.arange(1, 6, dtype=np.int32)
    rad.observe(np.concatenate([pre_a, [7]]))
    pid_a = rad.observe(np.concatenate([pre_a, [8]]))
    m = rad.lookup(np.concatenate([pre_a, [9]]))
    eng.submit(np.asarray([9], np.int32), max_new_tokens=2, prefix_id=m[0])
    # a queued request pins pid_a: the new registration may not evict it
    pre_b = np.arange(30, 36, dtype=np.int32)
    rad.observe(np.concatenate([pre_b, [7]]))
    rad.observe(np.concatenate([pre_b, [8]]))
    assert pid_a in eng._prefixes  # still registered (referenced)
    assert len(rad.entries) == 2  # over budget until the reference drains
    eng.run()
    pre_c = np.arange(60, 66, dtype=np.int32)
    rad.observe(np.concatenate([pre_c, [7]]))
    rad.observe(np.concatenate([pre_c, [8]]))
    assert len(rad.entries) <= 2  # eviction caught up after the drain


def test_radix_invalidate(tiny_llama):
    eng = _engine(tiny_llama)
    rad = RadixPrefixCache(eng, min_prefix_tokens=4, promote_after=2)
    pre = np.arange(1, 7, dtype=np.int32)
    rad.observe(np.concatenate([pre, [1]]))
    pid = rad.observe(np.concatenate([pre, [2]]))
    assert rad.invalidate(pid) == 1
    assert rad.lookup(np.concatenate([pre, [3]])) is None
    assert pid not in eng._prefixes
    with pytest.raises(ValueError, match="unknown prefix_id"):
        rad.invalidate(pid)


# --------------------------------------------------------------------- #
# KV handoff (engine surface)
# --------------------------------------------------------------------- #


def test_handoff_token_and_logprob_exact_dense_and_paged(tiny_llama):
    prompt = (np.arange(1, 10) % 250).astype(np.int32)
    ref = _reference(tiny_llama, prompt, 5)
    src = _engine(tiny_llama)
    h = src.prefill_detached(prompt, max_new_tokens=5, uid_key=3)
    for dst_kw in ({}, {"paged_block_size": 4}):
        dst = _engine(tiny_llama, **dst_kw)
        uid = dst.submit_prefilled(dict(h))
        dst.run()
        np.testing.assert_array_equal(dst.poll(uid), ref)
        # logprob-exact vs a local submit on a fresh engine
        local = _engine(tiny_llama)
        lu = local.submit(prompt, max_new_tokens=5)
        local.run()
        np.testing.assert_array_equal(dst.logprobs(uid), local.logprobs(lu))


def test_handoff_sampled_stream_matches_local_submit(tiny_llama):
    """temperature>0: the handoff carries the advanced sampling chain, so
    a disaggregated request's sampled stream equals the single-engine
    stream for the same (seed, uid)."""
    prompt = (np.arange(1, 9) % 250).astype(np.int32)
    local = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    lu = local.submit(prompt, max_new_tokens=6)
    local.run()
    src = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    dst = _engine(tiny_llama, temperature=0.9, seed=5, num_slots=1)
    uid = dst.submit_prefilled(src.prefill_detached(prompt, max_new_tokens=6, uid_key=lu))
    dst.run()
    np.testing.assert_array_equal(dst.poll(uid), local.poll(lu))
    np.testing.assert_array_equal(dst.logprobs(uid), local.logprobs(lu))


def test_handoff_bytes_match_costmodel_prediction(tiny_llama):
    from accelerate_tpu.analysis.costmodel import price_kv_handoff

    eng = _engine(tiny_llama)
    per_tok, fixed = eng.kv_handoff_dims()
    assert per_tok > 0
    for n in (3, 8, 11):
        prompt = (np.arange(1, n + 1) % 250).astype(np.int32)
        h = eng.prefill_detached(prompt, max_new_tokens=2, uid_key=n)
        pred = price_kv_handoff(per_tok, n, fixed_bytes=fixed, generation="cpu")
        assert pred["bytes"] == h["wire_bytes"] == per_tok * n + fixed
        assert pred["time_us"] > 0


def test_handoff_validation(tiny_llama):
    eng = _engine(tiny_llama)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.prefill_detached(np.zeros((0,), np.int32))
    with pytest.raises(ValueError, match="exceeds the slot cache"):
        eng.prefill_detached(np.ones((8,), np.int32), max_new_tokens=150)
    h = eng.prefill_detached(np.ones((4,), np.int32), max_new_tokens=4)
    bad = dict(h)
    bad["total"] = 3
    with pytest.raises(ValueError, match="handoff total"):
        eng.submit_prefilled(bad)
    big = dict(h)
    big["max_new_tokens"] = 150
    with pytest.raises(ValueError, match="exceeds the slot cache"):
        eng.submit_prefilled(big)


def test_handoff_request_survives_preemption(tiny_llama):
    """A handed-off request evicted mid-decode resumes by ordinary
    recompute (the handoff is consumed at first admission) and stays
    token-exact."""
    from accelerate_tpu.scheduling import SchedulerConfig

    prompt = (np.arange(1, 9) % 250).astype(np.int32)
    ref = _reference(tiny_llama, prompt, 8)
    src = _engine(tiny_llama)
    dst = ServingEngine(
        tiny_llama, num_slots=1, prompt_buckets=(4, 8), tick_block=2,
        scheduler=SchedulerConfig(enable_preemption=True),
    )
    uid = dst.submit_prefilled(
        src.prefill_detached(prompt, max_new_tokens=8, uid_key=0), priority=1
    )
    dst.step()  # handoff admitted, decoding
    assert dst.partial(uid).size > 0
    hi = dst.submit(np.asarray([5, 6], np.int32), max_new_tokens=2, priority=0)
    dst.run()  # priority-0 arrival preempts the handoff decode
    assert dst.metrics.decode_preemptions >= 1
    np.testing.assert_array_equal(dst.poll(uid), ref)
    assert dst.poll(hi) is not None


# --------------------------------------------------------------------- #
# the router
# --------------------------------------------------------------------- #


def test_fleet_outputs_exact_and_prefix_affinity(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(min_prefix_tokens=4, promote_after=2),
        num_slots=2, prompt_buckets=(4, 8),
    )
    pre = (np.arange(1, 7) % 250).astype(np.int32)
    prompts = [np.concatenate([pre, [40 + i]]).astype(np.int32) for i in range(6)]
    uids = [fr.submit(p, max_new_tokens=4) for p in prompts]
    out = fr.run()
    for u, p in zip(uids, prompts):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 4))
    stats = fr.radix_stats()
    # after promotion, affinity routes every preamble-sharing request to
    # the owning replica: exactly one replica holds the entry + the hits
    owners = [n for n, s in stats.items() if s["entries"] > 0]
    assert len(owners) == 1
    assert stats[owners[0]]["hits"] >= 1
    merged = fr.metrics_merged()
    assert merged.prefix_hits == sum(s["hits"] for s in stats.values())
    assert merged.requests_completed == len(prompts)


def test_fleet_no_reuse_config(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    assert all(r.radix is None for r in fr.replicas)
    p = (np.arange(1, 9) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


def test_fleet_level_shed(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(routing=RoutingConfig(max_fleet_queue_depth=1), prefix_reuse=False),
        num_slots=1, prompt_buckets=(4, 8),
    )
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2)
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2)
    # aggregate queue depth (minus in-flight) crosses the fleet SLO for a
    # sheddable class; priority 0 stays admissible
    with pytest.raises(ShedError, match="fleet queue depth"):
        while True:
            fr.submit(np.ones((4,), np.int32), max_new_tokens=2, priority=1)
    fr.submit(np.ones((4,), np.int32), max_new_tokens=2, priority=0)
    assert fr.fleet_shed == 1
    fr.run()


def test_fleet_disaggregated_exact_and_accounted(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="always", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    prompts = [(np.arange(1, 8 + i) % 250).astype(np.int32) for i in range(3)]
    uids = [fr.submit(p, max_new_tokens=4) for p in prompts]
    out = fr.run()
    for u, p in zip(uids, prompts):
        np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 4))
    acct = fr.handoff_accounting()
    assert acct["handoffs"] == 3
    assert acct["bytes_predicted"] == acct["bytes_moved"] > 0
    # decode replica did all the decoding; prefill replica served no slots
    assert fr.replicas[1].engine.metrics.requests_completed == 3
    assert fr.replicas[0].engine.metrics.requests_completed == 0


def test_fleet_disaggregated_auto_decision(tiny_llama):
    """auto mode prices every candidate transfer BEFORE it happens and
    takes exactly one decision per request (handoff or local re-prefill),
    and handoff=never pins the local path."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="auto", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u = fr.submit((np.arange(1, 9) % 250).astype(np.int32), max_new_tokens=3)
    out = fr.run()
    assert u in out
    acct = fr.handoff_accounting()
    assert acct["handoffs"] + acct["handoffs_local"] == 1
    fr2 = FleetRouter.from_model(
        tiny_llama, num_replicas=2,
        config=FleetConfig(roles=("prefill", "decode"), handoff="never", prefix_reuse=False),
        num_slots=2, prompt_buckets=(4, 8),
    )
    u2 = fr2.submit((np.arange(1, 9) % 250).astype(np.int32), max_new_tokens=3)
    out2 = fr2.run()
    np.testing.assert_array_equal(out2[u2], _reference(tiny_llama, (np.arange(1, 9) % 250), 3))
    assert fr2.handoff_accounting() == {
        "handoffs": 0, "handoffs_local": 1, "bytes_predicted": 0,
        "bytes_moved": 0, "time_us_predicted": 0.0,
    }


def test_fleet_partial_logprobs_cancel(tiny_llama):
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
        num_slots=1, prompt_buckets=(4, 8), tick_block=2,
    )
    p = (np.arange(1, 9) % 250).astype(np.int32)
    u1 = fr.submit(p, max_new_tokens=6)
    u2 = fr.submit(p, max_new_tokens=6)
    assert fr.partial(u1).size == 0 and fr.poll(u1) is None
    fr.step()
    got = fr.cancel(u2)
    assert isinstance(got, np.ndarray)
    with pytest.raises(KeyError):
        fr.partial(u2)
    fr.run()
    assert fr.poll(u1) is not None
    assert fr.logprobs(u1).shape[0] == len(fr.partial(u1))
    with pytest.raises(KeyError, match="unknown request id"):
        fr.poll(10_000)


def test_fleet_drain_threaded_matches_sequential(tiny_llama):
    prompts = [(np.arange(1, 5 + i) % 250).astype(np.int32) for i in range(8)]
    outs = {}
    for mode in ("seq", "thr"):
        fr = FleetRouter.from_model(
            tiny_llama, num_replicas=2, config=FleetConfig(prefix_reuse=False),
            num_slots=2, prompt_buckets=(4, 8),
        )
        uids = [fr.submit(p, max_new_tokens=3) for p in prompts]
        if mode == "thr":
            fr.drain_threaded()
        out = fr.run()  # seq drive / collect
        outs[mode] = [out[u] for u in uids]
    for a, b in zip(outs["seq"], outs["thr"]):
        np.testing.assert_array_equal(a, b)


def test_fleet_watchdog_silent_across_radix_hits_and_misses(tiny_llama):
    """Post-warmup compile count stays 0 across prefix registrations,
    hits, misses, and evictions — the recompile-watchdog discipline at
    fleet level."""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1,
        config=FleetConfig(min_prefix_tokens=4, promote_after=2, max_prefix_entries=1),
        num_slots=2, prompt_buckets=(4, 8),
    )
    eng = fr.replicas[0].engine
    rng = np.random.default_rng(0)
    # warm every width: buckets, chunk windows, prefix-suffix windows
    for n in (4, 8, 10, 13):
        eng.submit(rng.integers(1, 250, size=n).astype(np.int32), max_new_tokens=2)
    eng.run()
    pid = eng.register_prefix(rng.integers(1, 250, size=9).astype(np.int32))
    for b in (4, 8):
        eng.submit(rng.integers(1, 250, size=b).astype(np.int32), max_new_tokens=2, prefix_id=pid)
    eng.run()
    eng.unregister_prefix(pid)
    c0 = eng.program_cache.misses
    pre_a = rng.integers(1, 250, size=6).astype(np.int32)
    pre_b = rng.integers(1, 250, size=7).astype(np.int32)
    uids = []
    for pre in (pre_a, pre_a, pre_a, pre_b, pre_b, pre_b):
        sfx = rng.integers(1, 250, size=int(rng.integers(2, 5))).astype(np.int32)
        uids.append(fr.submit(np.concatenate([pre, sfx]), max_new_tokens=3))
    out = fr.run()
    assert len(out) == len(uids)
    stats = fr.radix_stats()["r0"]
    assert stats["registrations"] >= 2 and stats["hits"] >= 2
    assert eng.program_cache.misses - c0 == 0, "radix traffic must not compile"


def test_fleet_spin_up_warm_starts_from_shared_store(tiny_llama, tmp_path):
    """In-process spin-up over a shared store: every program either
    deserializes or is a reject-and-heal recompile — never a silent cold
    compile. (The STRICT 0-compile contract holds for fresh-process
    replicas — bench_serving --fleet and the subprocess test below — and
    in-process under a single-device backend; under the suite's 8-device
    fake mesh XLA:CPU can emit non-self-contained blobs from a long-lived
    process, the PR-7-documented class the reject path heals.)"""
    fr = FleetRouter.from_model(
        tiny_llama, num_replicas=1, config=FleetConfig(prefix_reuse=False),
        store_dir=str(tmp_path / "fleet_store"),
        num_slots=2, prompt_buckets=(4, 8),
    )
    cold = fr.spin_up(warm_prompt_lens=(4,))
    assert cold["compiles"] > 0 and cold["deserialized"] == 0
    warm = fr.spin_up(warm_prompt_lens=(4,))
    pc = fr.replicas[2].engine.program_cache
    assert warm["deserialized"] > 0
    assert warm["compiles"] == pc.rejected, "only healed rejects may recompile"
    assert warm["deserialized"] + warm["compiles"] == cold["compiles"]
    assert len(fr.replicas) == 3
    # the spun-up replica serves real traffic
    p = (np.arange(1, 6) % 250).astype(np.int32)
    u = fr.submit(p, max_new_tokens=3)
    out = fr.run()
    np.testing.assert_array_equal(out[u], _reference(tiny_llama, p, 3))


# --------------------------------------------------------------------- #
# fleet-level cross-process warm spin-up (promotes the PR-7 test)
# --------------------------------------------------------------------- #

_CHILD_FLEET_REPLICA = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from accelerate_tpu.utils.environment import force_host_platform
force_host_platform(1)
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.serving_fleet import FleetConfig, FleetRouter

model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
router = FleetRouter.from_model(
    model, num_replicas=1,
    config=FleetConfig(min_prefix_tokens=4, promote_after=2),
    store_dir={store!r}, num_slots=2, prompt_buckets=(4, 8),
)
pre = (np.arange(1, 7) % 250).astype(np.int32)
prompts = [np.concatenate([pre, [40 + i]]).astype(np.int32) for i in range(4)]
uids = [router.submit(p, max_new_tokens=3) for p in prompts]
out = router.run()
eng = router.replicas[0].engine
radix = router.radix_stats()["r0"]
toks = " ".join(str(t) for t in np.concatenate([out[u] for u in uids]))
print("FLEETREP", eng.program_cache.misses, eng.program_cache.deserialized,
      radix["hits"], radix["registrations"], toks)
"""


@pytest.mark.slow
def test_fleet_warm_replica_subprocess_zero_compiles(tmp_path):
    """The fleet-level warm-replica assertion: a FRESH SUBPROCESS builds
    a replica over the shared ExecutableStore and serves shared-preamble
    traffic with 0 XLA compiles — with its radix cache starting COLD
    (prefix registration replays the chunk programs from the store too).
    Promotes the PR-7 two-subprocess engine test to the fleet layer."""
    store = str(tmp_path / "store")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("XLA_FLAGS", None)

    def replica():
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_FLEET_REPLICA.format(repo=REPO, store=store)],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        tag, misses, deser, hits, regs, *tokens = out.stdout.strip().splitlines()[-1].split()
        assert tag == "FLEETREP"
        return int(misses), int(deser), int(hits), int(regs), tokens

    cold_misses, cold_deser, cold_hits, cold_regs, ref = replica()
    assert cold_misses >= 1 and cold_deser == 0
    assert cold_regs == 1 and cold_hits >= 1  # radix promoted + reused

    warm_misses, warm_deser, warm_hits, warm_regs, got = replica()
    assert warm_misses == 0, "warm fleet replica must not compile"
    assert warm_deser == cold_misses  # every program came from the store
    assert warm_regs == 1 and warm_hits == cold_hits  # radix started cold, re-promoted
    assert got == ref  # token-exact across processes
