"""Kernel tier (``analysis.kernelmodel`` + ``analysis.kernel_rules`` +
``kernels.contracts``): site extraction from traced pallas calls, the
TPU1001–1006 rules with their clean twins, interpret-mode parity of the
shipped reference kernel against the stock lax path, the contract hooks
in perfmodel/flight-check/numerics, the warn-once blindness satellite,
and the CLI surfaces (paths gate, ``--changed``, ``--selfcheck``)."""

import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pl = pytest.importorskip("jax.experimental.pallas")

from accelerate_tpu.analysis import kernel_check, scan_paths
from accelerate_tpu.analysis.kernelmodel import counted_cost, vmem_occupancy_bytes
from accelerate_tpu.kernels import block_accumulate, block_matmul_softmax
from accelerate_tpu.kernels.contracts import (
    KernelCostSpec,
    UnknownOpWarning,
    register_kernel_cost,
    reset_unknown_op_warnings,
    unregister_kernel_cost,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPU_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}

# the reference decode-logits shape, hand-computed (see kernels/reference.py):
# 2·B·D·N MXU + 14·B·N VPU flops; per-step blocks (8·128 x + 128·128 w + 8·128
# out, f32) streamed over 2 grid steps for HBM and double-buffered for VMEM.
B, D, N = 16, 128, 128
REF_FLOPS = 2 * B * D * N + 14 * B * N  # 552_960
REF_HBM = (8 * D * 4 + D * N * 4 + 8 * N * 4) * 2  # 147_456
REF_VMEM_PEAK = 2 * (8 * D * 4 + D * N * 4 + 8 * N * 4) + 8 * N * 4  # 151_552


def _xw(dtype=jnp.float32):
    x = np.linspace(-1.0, 1.0, B * D, dtype=np.float32).reshape(B, D)
    w = np.linspace(-0.5, 0.5, D * N, dtype=np.float32).reshape(D, N)
    return jnp.asarray(x, dtype), jnp.asarray(w, dtype)


def _sds():
    return (
        jax.ShapeDtypeStruct((B, D), jnp.float32),
        jax.ShapeDtypeStruct((D, N), jnp.float32),
    )


def _softmax_step(x, w):
    return block_matmul_softmax(x, w)


def _rules(report):
    return [f.rule for f in report.findings]


# --------------------------------------------------------------------- #
# interpret-mode parity: the reference kernel IS the stock lax path
# --------------------------------------------------------------------- #


def test_reference_parity_f32_bit_exact():
    x, w = _xw()
    got = block_matmul_softmax(x, w, interpret=True)
    want = jax.nn.softmax(x @ w, axis=-1)
    assert jnp.array_equal(got, want), "f32 reference kernel must be bit-exact"


def test_reference_parity_bf16_within_declared_interval():
    x, w = _xw(jnp.bfloat16)
    got = np.asarray(block_matmul_softmax(x, w, interpret=True), np.float32)
    # the registered interval transfer declares row softmax ⊆ [0, 1]
    assert got.min() >= 0.0 and got.max() <= 1.0
    want = np.asarray(
        jax.nn.softmax(x.astype(jnp.float32) @ w.astype(jnp.float32), axis=-1)
    )
    np.testing.assert_allclose(got, want, atol=2e-2)


def test_block_accumulate_in_place_parity():
    acc, _ = _xw()
    delta = acc * 0.5
    got = block_accumulate(acc, delta, interpret=True)
    assert jnp.array_equal(got, acc + delta)


# --------------------------------------------------------------------- #
# extraction + the counted cost (hand-computed pins)
# --------------------------------------------------------------------- #


def test_extraction_and_counted_cost_exact(mesh8):
    report = kernel_check(
        _softmax_step, *_sds(), mesh=mesh8, generation="cpu", probe=False
    )
    assert report.findings == []
    assert len(report.sites) == 1
    site = report.sites[0]
    assert site.kernel_name == "block_matmul_softmax_kernel"
    assert site.spec is not None
    assert site.grid == (2,)
    assert [b.block_shape for b in site.in_blocks] == [(8, D), (D, N)]
    assert [b.block_shape for b in site.out_blocks] == [(8, N)]
    assert site.io_aliases == ()
    assert site.interpret
    assert counted_cost(site) == (REF_FLOPS, REF_HBM)
    assert vmem_occupancy_bytes(site) == REF_HBM  # same blocks, double-buffered
    # the declaration agrees exactly — the selfcheck reference in numbers
    assert float(site.spec.flops(*site.in_avals)) == REF_FLOPS
    assert float(site.spec.hbm_bytes(*site.in_avals)) == REF_HBM
    assert float(site.spec.vmem_peak_bytes(*site.in_avals)) == REF_VMEM_PEAK


def test_extraction_aliases_and_clean_alias_twin(mesh8):
    sds = jax.ShapeDtypeStruct((B, N), jnp.float32)
    report = kernel_check(
        block_accumulate, sds, sds, mesh=mesh8, generation="cpu", probe=False
    )
    assert report.findings == []
    assert report.sites[0].io_aliases == ((0, 0),)


def test_interpret_probe_runs(mesh8):
    report = kernel_check(_softmax_step, *_sds(), mesh=mesh8, generation="cpu")
    assert report.interpret_probe == "ran: outputs finite"


# --------------------------------------------------------------------- #
# the six rules on seeded defects (select= isolates each rule)
# --------------------------------------------------------------------- #


def _check(fn, *sds, mesh, rule):
    return kernel_check(
        fn, *sds, mesh=mesh, generation="cpu", select=(rule,), probe=False
    )


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def test_tpu1001_vmem_overflow(mesh8):
    def step(x):  # (512, 512) f32 blocks: 2 MB/step double-buffered ≫ 512 KB cpu
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((512, 512), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((512, 512), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((1024, 512), jnp.float32),
            interpret=True,
        )(x)

    report = _check(step, jax.ShapeDtypeStruct((1024, 512), jnp.float32), mesh=mesh8, rule="TPU1001")
    assert _rules(report) == ["TPU1001"]
    assert report.findings[0].is_error


def test_tpu1002_tile_misalignment(mesh8):
    def step(x):  # lane dim 100 is not a multiple of the 128 MXU lane
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 100), jnp.float32),
            interpret=True,
        )(x)

    report = _check(step, jax.ShapeDtypeStruct((16, 100), jnp.float32), mesh=mesh8, rule="TPU1002")
    assert set(_rules(report)) == {"TPU1002"}
    assert "misaligned" in report.findings[0].message


def test_tpu1003_index_map_gap(mesh8):
    def step(x):  # out map pins every grid step to block (0, 0): (1, 0) is garbage
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            interpret=True,
        )(x)

    report = _check(step, jax.ShapeDtypeStruct((16, 128), jnp.float32), mesh=mesh8, rule="TPU1003")
    assert _rules(report) == ["TPU1003"]
    assert report.findings[0].is_error and "unwritten" in report.findings[0].message


def test_tpu1004_alias_hazard(mesh8):
    def step(x):  # aliased operand read from block (0,0) while writing (i,0)
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            input_output_aliases={0: 0},
            interpret=True,
        )(x)

    report = _check(step, jax.ShapeDtypeStruct((16, 128), jnp.float32), mesh=mesh8, rule="TPU1004")
    assert _rules(report) == ["TPU1004"]


def _anon_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _anon_call(x):
    return pl.pallas_call(
        _anon_kernel,
        grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
        interpret=True,
    )(x)


def test_tpu1005_unregistered_call(mesh8):
    report = _check(
        _anon_call, jax.ShapeDtypeStruct((16, 128), jnp.float32), mesh=mesh8, rule="TPU1005"
    )
    assert _rules(report) == ["TPU1005"]
    assert report.findings[0].is_error


def _drifty_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def test_tpu1006_declaration_drift(mesh8):
    # counted: 1 mul x 8·128 elements x 2 steps = 2048 flops; declare 3x that
    register_kernel_cost(
        KernelCostSpec(
            name="_drifty_kernel",
            flops=lambda x: float(3 * 2 * x.shape[0] * x.shape[1]),
            hbm_bytes=lambda x: float(2 * x.shape[0] * x.shape[1] * 4),  # exact
            vmem_peak_bytes=lambda x: float(4 * 8 * x.shape[1] * 4),
        )
    )
    try:

        def step(x):
            return pl.pallas_call(
                _drifty_kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
                interpret=True,
            )(x)

        report = _check(
            step, jax.ShapeDtypeStruct((16, 128), jnp.float32), mesh=mesh8, rule="TPU1006"
        )
        assert _rules(report) == ["TPU1006"]
        assert "FLOPs" in report.findings[0].message  # only the FLOPs line drifts
    finally:
        unregister_kernel_cost("_drifty_kernel")


def test_kernel_selfcheck_green(mesh8):
    from accelerate_tpu.analysis import run_kernel_selfcheck

    ok, lines = run_kernel_selfcheck(mesh8)
    assert ok, "\n".join(lines)
    assert sum("detected" in l for l in lines) == 6
    assert sum("zero findings" in l for l in lines) == 6
    assert any("cost reference" in l and "exact" in l for l in lines)


# --------------------------------------------------------------------- #
# the contract feeds the other tiers
# --------------------------------------------------------------------- #


def test_perfmodel_prices_the_declared_cost(mesh8):
    from accelerate_tpu.analysis import perf_check

    report = perf_check(_softmax_step, *_sds(), mesh=mesh8, rules=False)
    ops = [o for o in report.ops if o.primitive == "pallas_call:block_matmul_softmax_kernel"]
    assert len(ops) == 1
    assert ops[0].flops == REF_FLOPS
    assert ops[0].hbm_bytes == REF_HBM
    assert report.unpriced == []


def test_perfmodel_unpriced_and_warn_once(mesh8):
    from accelerate_tpu.analysis import perf_check

    reset_unknown_op_warnings()
    sds = jax.ShapeDtypeStruct((16, 128), jnp.float32)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        first = perf_check(_anon_call, sds, mesh=mesh8, rules=False)
        second = perf_check(_anon_call, sds, mesh=mesh8, rules=False)
    assert first.unpriced == ["_anon_kernel"]
    assert second.unpriced == ["_anon_kernel"]
    blind = [w for w in caught if issubclass(w.category, UnknownOpWarning)]
    assert len(blind) == 1, "repeat walks must not repeat the blindness warning"
    assert "_anon_kernel" in str(blind[0].message)
    reset_unknown_op_warnings()


def test_flightcheck_charges_declared_vmem_peak():
    from accelerate_tpu.analysis.flightcheck import _sub_transient_bytes

    closed = jax.make_jaxpr(_softmax_step)(*_sds())
    eqn = next(e for e in closed.jaxpr.eqns if e.primitive.name == "pallas_call")
    assert _sub_transient_bytes(eqn) == REF_VMEM_PEAK

    reset_unknown_op_warnings()
    closed = jax.make_jaxpr(_anon_call)(jax.ShapeDtypeStruct((16, 128), jnp.float32))
    eqn = next(e for e in closed.jaxpr.eqns if e.primitive.name == "pallas_call")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert _sub_transient_bytes(eqn) == 0
    assert any(issubclass(w.category, UnknownOpWarning) for w in caught)
    reset_unknown_op_warnings()


def test_numerics_interval_through_registered_kernel(mesh8):
    from accelerate_tpu.analysis import numerics_check

    r = numerics_check(_softmax_step, *_sds(), mesh=mesh8, assume=(-3.0, 3.0))
    out = r.outputs[0]
    assert (out.lo, out.hi) == (0.0, 1.0)  # the declared softmax transfer


# --------------------------------------------------------------------- #
# the AST registration gate + CLI surfaces
# --------------------------------------------------------------------- #

_UNREGISTERED_SRC = """\
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def mystery_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def step(x):
    return pl.pallas_call(
        mystery_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(x)
"""


def test_scan_paths_fires_and_respects_suppression(tmp_path):
    p = tmp_path / "unregistered.py"
    p.write_text(_UNREGISTERED_SRC)
    findings = scan_paths([str(p)])
    assert [f.rule for f in findings] == ["TPU1005"]
    assert "mystery_kernel" in findings[0].message

    p.write_text(
        _UNREGISTERED_SRC.replace(
            "    return pl.pallas_call(",
            "    return pl.pallas_call(  # tpu-lint: disable=TPU1005",
        )
    )
    assert scan_paths([str(p)]) == []

    registered = tmp_path / "registered.py"
    registered.write_text(
        _UNREGISTERED_SRC.replace("mystery_kernel", "block_matmul_softmax_kernel")
    )
    assert scan_paths([str(registered)]) == []


def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "kernel-check", *args],
        capture_output=True, text=True, env=CPU_ENV, cwd=cwd, timeout=240,
    )


def test_cli_paths_mode_unregistered_exits_nonzero(tmp_path):
    p = tmp_path / "unregistered.py"
    p.write_text(_UNREGISTERED_SRC)
    result = _run_cli(str(p))
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TPU1005" in result.stdout


def test_cli_changed_without_git_falls_back(tmp_path):
    p = tmp_path / "unregistered.py"
    p.write_text(_UNREGISTERED_SRC)
    result = _run_cli("--changed", str(p), cwd=str(tmp_path))
    assert result.returncode == 1
    assert "needs a git work tree" in result.stderr
    assert "TPU1005" in result.stdout


def test_cli_traced_example_clean():
    result = _run_cli(
        "examples/by_feature/kernel_check.py::decode_step", "--mesh", "data=8"
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "findings: none" in result.stdout
    assert "[registered]" in result.stdout


def test_cli_selfcheck():
    result = _run_cli("--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 6
    assert result.stdout.count("clean twin") == 6
    assert "cost reference" in result.stdout and "exact" in result.stdout
