"""Speculative decoding: token-exactness vs plain greedy decode, accept
accounting, cache-frontier correctness (speculative.py)."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.speculative import speculative_generate


@pytest.fixture(scope="module")
def target():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


@pytest.fixture(scope="module")
def draft():
    # different weights (seed) = a realistic imperfect draft
    return create_llama_model(LlamaConfig.tiny(), seed=7, seq_len=16)


def test_token_exact_with_imperfect_draft(target, draft):
    """The whole point: whatever the draft proposes, the output equals the
    target's own greedy decode exactly."""
    ids = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(target, ids, max_new_tokens=10))
    for gamma in (1, 2, 4):
        got = np.asarray(speculative_generate(target, draft, ids, max_new_tokens=10, gamma=gamma))
        np.testing.assert_array_equal(got, want), gamma


def test_perfect_draft_accepts_everything(target):
    """Draft == target: every proposal accepted — gamma+1 tokens per
    target forward (the speedup upper bound) and still token-exact."""
    ids = np.ones((1, 4), np.int32)
    # budget 10 = 1 (prefill) + 3 steps x (gamma+1): no final truncation,
    # so the usable accept_rate is exactly 1.0
    want = np.asarray(generate(target, ids, max_new_tokens=10))
    got, stats = speculative_generate(
        target, target, ids, max_new_tokens=10, gamma=2, return_stats=True
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["accept_rate"] == 1.0, stats
    # 1 prefill + 3 spec steps = 4 target forwards for 10 tokens
    assert stats["target_forwards"] == 4, stats
    assert stats["tokens_per_target_forward"] > 2.0, stats


def test_eos_stops_early(target, draft):
    ids = np.ones((1, 4), np.int32)
    full = np.asarray(generate(target, ids, max_new_tokens=8))[0]
    eos = int(full[6])
    got = np.asarray(
        speculative_generate(target, draft, ids, max_new_tokens=8, gamma=2, eos_token_id=eos)
    )[0]
    assert got[-1] == eos
    np.testing.assert_array_equal(got, full[: len(got)])


def test_validation(target, draft):
    ids = np.ones((1, 4), np.int32)
    with pytest.raises(ValueError, match="batch-1"):
        speculative_generate(target, draft, np.ones((2, 4), np.int32))
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(target, draft, ids, gamma=0)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        speculative_generate(target, draft, ids, max_new_tokens=140)


def test_sharded_target_and_draft_token_exact(target, draft):
    """Mesh-sharded target+draft decode speculatively to the same tokens
    (the big-model setting the feature exists for)."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    ids = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(target, ids, max_new_tokens=8))

    t2 = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    d2 = create_llama_model(LlamaConfig.tiny(), seed=7, seq_len=16)
    mesh = MeshConfig(data=1, tensor=4).build(jax.devices()[:4])
    shard_model(t2, mesh)
    shard_model(d2, mesh)
    got = np.asarray(speculative_generate(t2, d2, ids, max_new_tokens=8, gamma=3))
    np.testing.assert_array_equal(got, want)


def test_draft_swap_does_not_reuse_stale_runner(target):
    """A different draft object (same shapes) must NOT hit the previous
    draft's cached closure."""
    ids = np.ones((1, 4), np.int32)
    d1 = create_llama_model(LlamaConfig.tiny(), seed=1, seq_len=16)
    speculative_generate(target, d1, ids, max_new_tokens=4, gamma=2)
    d2 = create_llama_model(LlamaConfig.tiny(), seed=2, seq_len=16)
    got = np.asarray(speculative_generate(target, d2, ids, max_new_tokens=4, gamma=2))
    want = np.asarray(generate(target, ids, max_new_tokens=4))
    np.testing.assert_array_equal(got, want)  # token-exact regardless of draft
