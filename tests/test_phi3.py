"""Phi-3 family (models/phi3.py): fused-checkpoint split + windowed
decode through the llama surface. HF importer parity lives in
test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Phi3Config, create_phi3_model
from accelerate_tpu.models.hub import split_phi3_fused_state


@pytest.fixture(scope="module")
def tiny_phi3():
    return create_phi3_model(Phi3Config.tiny(), seq_len=16)


def test_fused_split_points():
    """qkv split respects GQA widths; gate/up keeps HF's chunk order."""
    rng = np.random.default_rng(0)
    hd, h, h_kv = 8, 4, 2
    qkv = rng.normal(size=((h + 2 * h_kv) * hd, 16)).astype(np.float32)
    gu = rng.normal(size=(24, 16)).astype(np.float32)
    state = {
        "model.layers.0.self_attn.qkv_proj.weight": qkv,
        "model.layers.0.mlp.gate_up_proj.weight": gu,
        "model.norm.weight": np.ones((16,), np.float32),
    }
    out = split_phi3_fused_state(state, num_heads=h, num_kv_heads=h_kv)
    np.testing.assert_array_equal(out["model.layers.0.self_attn.q_proj.weight"], qkv[: h * hd])
    np.testing.assert_array_equal(
        out["model.layers.0.self_attn.k_proj.weight"], qkv[h * hd : (h + h_kv) * hd]
    )
    np.testing.assert_array_equal(out["model.layers.0.self_attn.v_proj.weight"], qkv[(h + h_kv) * hd :])
    np.testing.assert_array_equal(out["model.layers.0.mlp.gate_proj.weight"], gu[:12])
    np.testing.assert_array_equal(out["model.layers.0.mlp.up_proj.weight"], gu[12:])
    assert "model.norm.weight" in out  # untouched keys pass through


def test_greedy_decode_matches_full_prefix(tiny_phi3):
    """The 8-token window threads through the KV-cache decode contract."""
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_phi3, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_phi3(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_paged_serving(tiny_phi3):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 10)]
    eng = ServingEngine(tiny_phi3, num_slots=2, prompt_buckets=(4, 16), paged_block_size=4)
    outs = eng.generate_many(prompts, max_new_tokens=4)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_phi3, p[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(got, ref)
