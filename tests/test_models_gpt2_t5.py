"""GPT-2 + T5 model-family tests (reference's Megatron parsers cover
bert/gpt2/t5/llama — dataclasses.py:2532-2662; this completes that set)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.models import (
    GPT2Config,
    T5Config,
    causal_lm_loss,
    create_gpt2_model,
    create_t5_model,
    seq2seq_lm_loss,
)


def test_gpt2_forward_and_tied_head():
    cfg = GPT2Config.tiny()
    model = create_gpt2_model(cfg, seq_len=16)
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # tied head: no separate lm_head params
    assert "lm_head" not in model.params


def test_gpt2_train_step_tp_mesh():
    cfg = GPT2Config.tiny()
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, tensor=4)),
    )
    model = acc.prepare_model(create_gpt2_model(cfg, seq_len=16))
    from jax.sharding import PartitionSpec as P

    assert model.params["layer_0"]["attn"]["q_proj"]["kernel"].sharding.spec == P(None, "tensor")
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    ids = (np.arange(4 * 16).reshape(4, 16) % cfg.vocab_size).astype(np.int32)
    l0 = float(step({"input_ids": ids}))
    for _ in range(4):
        l = float(step({"input_ids": ids}))
    assert np.isfinite(l0) and l < l0


def test_t5_forward_and_loss_decreases():
    cfg = T5Config.tiny()
    model = create_t5_model(cfg, seq_len=16)
    ids = (np.arange(2 * 16).reshape(2, 16) % cfg.vocab_size).astype(np.int32)
    logits = model(ids, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, tensor=4))
    )
    model = acc.prepare_model(create_t5_model(cfg, seq_len=16))
    acc.prepare_optimizer(optax.adam(1e-3))
    step = acc.build_train_step(lambda p, b: seq2seq_lm_loss(p, b, model.apply_fn))
    batch = {"input_ids": ids, "labels": ids}
    l0 = float(step(batch))
    for _ in range(5):
        l = float(step(batch))
    assert np.isfinite(l0) and l < l0


def test_t5_label_masking():
    cfg = T5Config.tiny()
    model = create_t5_model(cfg, seq_len=8)
    ids = (np.arange(2 * 8).reshape(2, 8) % cfg.vocab_size).astype(np.int32)
    labels_full = ids.copy()
    labels_masked = ids.copy()
    labels_masked[:, 4:] = -100  # ignore second half
    l_full = float(seq2seq_lm_loss(model.params, {"input_ids": ids, "labels": labels_full}, model.apply_fn))
    l_masked = float(seq2seq_lm_loss(model.params, {"input_ids": ids, "labels": labels_masked}, model.apply_fn))
    assert np.isfinite(l_full) and np.isfinite(l_masked)
    assert abs(l_full - l_masked) > 1e-6  # masking changes the loss


def test_hf_gpt2_import_split_qkv():
    from accelerate_tpu.models.hub import convert_hf_gpt2_state

    cfg = GPT2Config.tiny()
    h = cfg.hidden_size
    rng = np.random.default_rng(2)
    state = {
        "transformer.wte.weight": rng.normal(size=(cfg.vocab_size, h)).astype(np.float32),
        "transformer.wpe.weight": rng.normal(size=(cfg.max_position_embeddings, h)).astype(np.float32),
        "transformer.ln_f.weight": np.ones(h, np.float32),
        "transformer.ln_f.bias": np.zeros(h, np.float32),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"transformer.h.{i}."
        state.update({
            p + "ln_1.weight": np.ones(h, np.float32),
            p + "ln_1.bias": np.zeros(h, np.float32),
            p + "ln_2.weight": np.ones(h, np.float32),
            p + "ln_2.bias": np.zeros(h, np.float32),
            p + "attn.c_attn.weight": rng.normal(size=(h, 3 * h)).astype(np.float32),
            p + "attn.c_attn.bias": np.zeros(3 * h, np.float32),
            p + "attn.c_proj.weight": rng.normal(size=(h, h)).astype(np.float32),
            p + "attn.c_proj.bias": np.zeros(h, np.float32),
            p + "mlp.c_fc.weight": rng.normal(size=(h, cfg.intermediate_size)).astype(np.float32),
            p + "mlp.c_fc.bias": np.zeros(cfg.intermediate_size, np.float32),
            p + "mlp.c_proj.weight": rng.normal(size=(cfg.intermediate_size, h)).astype(np.float32),
            p + "mlp.c_proj.bias": np.zeros(h, np.float32),
        })
    tree = convert_hf_gpt2_state(state)
    # fused qkv split into thirds, Conv1D orientation kept ([in, out])
    np.testing.assert_allclose(
        tree["layer_0"]["attn"]["k_proj"]["kernel"],
        state["transformer.h.0.attn.c_attn.weight"][:, h:2 * h],
    )
    # imported tree loads into the model and it runs
    model = create_gpt2_model(cfg, seq_len=8)
    from accelerate_tpu.models.hub import _merge_into

    _merge_into(model, tree)
    assert model.imported_weight_count > 0
    out = model(jnp.zeros((1, 8), jnp.int32))
    assert out.shape == (1, 8, cfg.vocab_size)


def test_hf_t5_import_structure():
    from accelerate_tpu.models.hub import _merge_into, convert_hf_t5_state

    cfg = T5Config.tiny()
    h, ff, inner = cfg.hidden_size, cfg.intermediate_size, cfg.num_attention_heads * cfg.head_dim
    rng = np.random.default_rng(3)
    state = {
        "shared.weight": rng.normal(size=(cfg.vocab_size, h)).astype(np.float32),
        "encoder.final_layer_norm.weight": np.ones(h, np.float32),
        "decoder.final_layer_norm.weight": np.ones(h, np.float32),
    }
    for stack, n_sub in (("encoder", 2), ("decoder", 3)):
        for i in range(cfg.num_layers):
            p = f"{stack}.block.{i}.layer."
            attn0 = "SelfAttention"
            state.update({
                p + f"0.{attn0}.q.weight": rng.normal(size=(inner, h)).astype(np.float32),
                p + f"0.{attn0}.k.weight": rng.normal(size=(inner, h)).astype(np.float32),
                p + f"0.{attn0}.v.weight": rng.normal(size=(inner, h)).astype(np.float32),
                p + f"0.{attn0}.o.weight": rng.normal(size=(h, inner)).astype(np.float32),
                p + "0.layer_norm.weight": np.ones(h, np.float32),
            })
            if i == 0:
                state[p + f"0.{attn0}.relative_attention_bias.weight"] = rng.normal(
                    size=(cfg.relative_attention_num_buckets, cfg.num_attention_heads)
                ).astype(np.float32)
            if stack == "decoder":
                state.update({
                    p + "1.EncDecAttention.q.weight": rng.normal(size=(inner, h)).astype(np.float32),
                    p + "1.EncDecAttention.k.weight": rng.normal(size=(inner, h)).astype(np.float32),
                    p + "1.EncDecAttention.v.weight": rng.normal(size=(inner, h)).astype(np.float32),
                    p + "1.EncDecAttention.o.weight": rng.normal(size=(h, inner)).astype(np.float32),
                    p + "1.layer_norm.weight": np.ones(h, np.float32),
                })
            ffn_sub = n_sub - 1
            state.update({
                p + f"{ffn_sub}.DenseReluDense.wi.weight": rng.normal(size=(ff, h)).astype(np.float32),
                p + f"{ffn_sub}.DenseReluDense.wo.weight": rng.normal(size=(h, ff)).astype(np.float32),
                p + f"{ffn_sub}.layer_norm.weight": np.ones(h, np.float32),
            })
    tree = convert_hf_t5_state(state)
    np.testing.assert_allclose(
        tree["dec_layer_0"]["cross_attn"]["q_proj"]["kernel"],
        state["decoder.block.0.layer.1.EncDecAttention.q.weight"].T,
    )
    model = create_t5_model(cfg, seq_len=8)
    _merge_into(model, tree)
    assert model.imported_weight_count == len(state)
    out = model(jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32))
    assert out.shape == (1, 8, cfg.vocab_size)


def test_t5_position_bias_shared_across_layers():
    """Every layer's self-attention must receive the layer-0 relative
    position bias (HF T5Stack shares it); zeroing the table must change
    the contribution of layers > 0, not just layer 0."""
    cfg = T5Config.tiny()
    model = create_t5_model(cfg, seq_len=8)
    ids = (np.arange(2 * 8).reshape(2, 8) % cfg.vocab_size).astype(np.int32)

    # gradient of the output w.r.t. the layer-0 bias table flows through
    # layers 1..N iff the bias is threaded into them; compare against a
    # 1-layer model where only layer 0 consumes it.
    def out_sum(params):
        return jnp.sum(model.apply_fn(params, ids, ids))

    g = jax.grad(out_sum)(model.params)
    g_table = g["enc_layer_0"]["attn"]["relative_bias/embedding"]
    assert float(jnp.abs(g_table).sum()) > 0

    # direct check of the threading: an encoder layer *without* its own
    # table must respond to an externally supplied position_bias.
    from accelerate_tpu.models.t5 import T5EncoderLayer

    layer = T5EncoderLayer(cfg, has_relative_bias=False)
    h = jax.random.normal(jax.random.key(1), (1, 8, cfg.hidden_size), jnp.float32)
    mask = jnp.ones((1, 8), jnp.bool_)
    params = layer.init(jax.random.key(0), h, mask)
    out_none, bias_none = layer.apply(params, h, mask, None)
    assert bias_none is None
    big_bias = jnp.full((1, cfg.num_attention_heads, 8, 8), 5.0, jnp.float32)
    bias = big_bias.at[..., 0].set(-5.0)
    out_bias, bias_out = layer.apply(params, h, mask, bias)
    assert bias_out is bias
    assert float(jnp.abs(out_bias - out_none).max()) > 1e-6
