"""Multi-process serving fleet (serving_proc.py + serving_transport.py):
framed-socket transport failure semantics, the supervisor's worker
lifecycle driven by REAL process death (SIGKILL failover, hang
degrade/quarantine, poison recompute-only, drain, respawn backoff cap,
restart-storm breaker), per-process telemetry merging, the HTTP/SSE
front door, and the model-checker drift gates that pin every explored
lifecycle path to a named test in THIS file.

The subprocess tests are ``slow`` (each boots real engine workers); the
transport, telemetry, front-door-unit, and drift-gate tests are tier-1.
"""

import dataclasses
import http.client
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib

import numpy as np
import pytest

from accelerate_tpu.serving_transport import (
    MAGIC,
    VERSION,
    FrameError,
    PeerClosedError,
    WorkerError,
    recv_exact,
    recv_msg,
    request,
    send_msg,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_HEADER = struct.Struct(">2sBBIII")


# --------------------------------------------------------------------- #
# transport: framing
# --------------------------------------------------------------------- #


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_roundtrip_json_and_blob():
    a, b = _pair()
    try:
        blob = bytes(range(256)) * 17
        sent = send_msg(a, {"op": "status", "ack": [1, 2]}, blob)
        obj, rblob = recv_msg(b)
        assert obj == {"op": "status", "ack": [1, 2]}
        assert rblob == blob
        assert sent == _HEADER.size + len(json.dumps(obj, separators=(",", ":"))) + len(blob)
        # empty-blob frame on the same connection stays in sync
        send_msg(b, {"ok": True})
        obj2, rblob2 = recv_msg(a)
        assert obj2 == {"ok": True} and rblob2 == b""
    finally:
        a.close()
        b.close()


def test_recv_exact_loops_over_partial_reads():
    """TCP segmentation (short writes on the peer) must be invisible:
    the peer dribbles one frame a few bytes at a time."""
    a, b = _pair()
    payload = json.dumps({"op": "x"}, separators=(",", ":")).encode()
    blob = os.urandom(503)
    crc = zlib.crc32(blob, zlib.crc32(payload))
    wire = _HEADER.pack(MAGIC, VERSION, 0, len(payload), len(blob), crc) + payload + blob

    def dribble():
        for i in range(0, len(wire), 7):
            a.sendall(wire[i : i + 7])
            time.sleep(0.0005)
        a.close()

    t = threading.Thread(target=dribble)
    t.start()
    try:
        obj, rblob = recv_msg(b)
        assert obj == {"op": "x"} and rblob == blob
    finally:
        t.join()
        b.close()


def test_oversized_frame_refused_before_body():
    """A corrupt length field must raise BEFORE any body allocation (and
    without consuming the declared gigabytes)."""
    a, b = _pair()
    try:
        header = _HEADER.pack(MAGIC, VERSION, 0, 1 << 30, 1 << 30, 0)
        a.sendall(header)
        with pytest.raises(FrameError, match="exceeds"):
            recv_msg(b, max_frame=1 << 20)
        # sender-side twin: an oversized payload refuses to serialize
        with pytest.raises(FrameError, match="exceeds"):
            send_msg(a, {"op": "big"}, b"x" * 32, max_frame=16)
    finally:
        a.close()
        b.close()


@pytest.mark.parametrize(
    "mutate, match",
    [
        (lambda h, p: (b"XX" + h[2:], p), "magic"),
        (lambda h, p: (h[:2] + bytes([VERSION + 1]) + h[3:], p), "version"),
        (lambda h, p: (h, p[:-1] + bytes([p[-1] ^ 0xFF])), "crc32"),
    ],
)
def test_corrupt_frame_structured_error(mutate, match):
    a, b = _pair()
    try:
        payload = json.dumps({"op": "x"}, separators=(",", ":")).encode()
        header = _HEADER.pack(
            MAGIC, VERSION, 0, len(payload), 0, zlib.crc32(b"", zlib.crc32(payload))
        )
        header, payload = mutate(header, payload)
        a.sendall(header + payload)
        with pytest.raises(FrameError, match=match):
            recv_msg(b)
    finally:
        a.close()
        b.close()


def test_undecodable_json_is_frame_error():
    a, b = _pair()
    try:
        payload = b"\xff\xfe not json"
        header = _HEADER.pack(MAGIC, VERSION, 0, len(payload), 0, zlib.crc32(payload))
        a.sendall(header + payload)
        with pytest.raises(FrameError, match="undecodable"):
            recv_msg(b)
    finally:
        a.close()
        b.close()


def test_peer_death_mid_frame_raises_peer_closed():
    """Worker death mid-frame is a structured error with the byte
    position, never a hang: header promises 64 payload bytes, the peer
    dies after 10."""
    a, b = _pair()
    try:
        header = _HEADER.pack(MAGIC, VERSION, 0, 64, 0, 0)
        a.sendall(header + b"x" * 10)
        a.close()
        with pytest.raises(PeerClosedError) as ei:
            recv_msg(b)
        assert ei.value.got == 10 and ei.value.want == 64
    finally:
        b.close()


def test_recv_exact_zero_and_eof_semantics():
    a, b = _pair()
    try:
        assert recv_exact(b, 0) == b""
        a.close()
        with pytest.raises(PeerClosedError):
            recv_exact(b, 1)
    finally:
        b.close()


def test_worker_error_reply_raises_structured():
    a, b = _pair()

    def server():
        obj, _ = recv_msg(b)
        send_msg(b, {"err": {"kind": "bad_uid", "detail": f"no uid {obj['uid']}"}})

    t = threading.Thread(target=server)
    t.start()
    try:
        with pytest.raises(WorkerError, match="no uid 7") as ei:
            request(a, {"op": "result", "uid": 7}, timeout=5.0)
        assert ei.value.kind == "bad_uid"
    finally:
        t.join()
        a.close()
        b.close()


# --------------------------------------------------------------------- #
# telemetry: per-process seq disambiguation + supervisor run dirs
# --------------------------------------------------------------------- #


def test_merge_events_disambiguates_per_process_seq():
    """Two worker PROCESSES restart their ``seq`` counters at 0; with a
    coarse shared clock the merge must order by worker id, not interleave
    the colliding (ts, seq) pairs arbitrarily."""
    from accelerate_tpu.telemetry.eventlog import merge_events

    w0 = [{"ts": 1.0, "seq": 0, "name": "a0"}, {"ts": 1.0, "seq": 1, "name": "a1"}]
    w1 = [{"ts": 1.0, "seq": 0, "name": "b0"}, {"ts": 1.0, "seq": 1, "name": "b1"}]
    merged = merge_events(w0, w1, source_ids=["w0", "w1"])
    assert [r["name"] for r in merged] == ["a0", "a1", "b0", "b1"]
    # without source ids, the record's own rank disambiguates
    for r in w0:
        r["rank"] = 0
    for r in w1:
        r["rank"] = 1
    merged = merge_events(w1, w0)  # adversarial list order
    assert [r["name"] for r in merged] == ["a0", "a1", "b0", "b1"]


def test_trace_summarize_reads_supervisor_run_dir(tmp_path, capsys):
    """``accelerate-tpu trace summarize <run_dir>`` merges the per-process
    ``events_*.jsonl`` logs into one deterministic timeline."""
    from accelerate_tpu.commands.trace import _load_events

    for name, rank in (("supervisor", -1), ("w0", 0), ("w1", 1)):
        recs = [
            {"v": 1, "seq": s, "ts": 10.0, "rank": rank, "kind": "event", "name": f"{name}_{s}"}
            for s in range(3)
        ]
        with open(tmp_path / f"events_{name}.jsonl", "w") as f:
            f.write("\n".join(json.dumps(r) for r in recs) + "\n")
    events = _load_events(str(tmp_path))
    assert len(events) == 9
    # per-source seq stays total within each worker despite the tied ts
    for name in ("supervisor", "w0", "w1"):
        sub = [e["name"] for e in events if e["name"].startswith(name)]
        assert sub == [f"{name}_{s}" for s in range(3)]


# --------------------------------------------------------------------- #
# front door units (fake supervisor — no subprocesses)
# --------------------------------------------------------------------- #


class _FakeSupervisor:
    """Duck-typed stand-in: enough surface for TelemetryHTTPD.for_supervisor."""

    def __init__(self, health):
        self._health = health
        self._streams = {}
        self._next = 0
        self.submitted = []

    def submit(self, prompt, max_new_tokens, stop_sequences, priority, wait):
        rid = self._next
        self._next += 1
        self.submitted.append({"prompt": prompt, "priority": priority})
        self._streams[rid] = {
            "state": "done",
            "tokens": [5, 6],
            "lps": [-0.5, -0.25],
            "final": list(prompt) + [5, 6],
            "lost_reason": None,
        }
        return rid

    def cancel(self, rid):
        s = self._streams[rid]
        s["state"] = "cancelled"
        return s["tokens"]

    def _stream(self, rid):
        return self._streams[rid]

    def health(self):
        return self._health

    def prometheus_text(self):
        return "proc_requests 0\n"


def _httpd(health):
    from accelerate_tpu.telemetry.httpd import TelemetryHTTPD

    sup = _FakeSupervisor(health)
    httpd = TelemetryHTTPD.for_supervisor(sup, port=0)
    httpd.start()
    return sup, httpd


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path, headers=headers or {})
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def test_healthz_503_on_zero_live_workers():
    """The ISSUE-pinned fix: /healthz must flip 503 when no worker
    process is live — dead/quarantined/spawning rows are not capacity."""
    sup, httpd = _httpd(
        {
            "w0": {"health": "dead", "slot": 0},
            "w1.2": {"health": "quarantined", "slot": 1},
            "w2": {"health": "spawning", "slot": 2},
        }
    )
    try:
        status, body = _get(httpd.port, "/healthz")
        assert status == 503
        assert json.loads(body)["serving"] is False
    finally:
        httpd.stop()


def test_healthz_200_while_any_worker_serves():
    sup, httpd = _httpd(
        {"w0": {"health": "dead", "slot": 0}, "w1": {"health": "degraded", "slot": 1}}
    )
    try:
        status, body = _get(httpd.port, "/healthz")
        assert status == 200
        assert json.loads(body)["serving"] is True
    finally:
        httpd.stop()


def test_front_door_submit_priority_headers_and_cancel():
    sup, httpd = _httpd({"w0": {"health": "healthy", "slot": 0}})
    try:
        conn = http.client.HTTPConnection("127.0.0.1", httpd.port, timeout=10)
        conn.request(
            "POST",
            "/v1/generate",
            body=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 2}),
            headers={"X-SLO-Class": "interactive"},
        )
        r = conn.getresponse()
        out = json.loads(r.read())
        conn.close()
        assert r.status == 200 and out["state"] == "done"
        assert out["final"] == [1, 2, 3, 5, 6]
        from accelerate_tpu.telemetry.httpd import SLO_CLASSES

        assert sup.submitted[0]["priority"] == SLO_CLASSES["interactive"]

        # cancel replies the tokens so far
        rid = sup.submit([9], 4, [], 0, True)
        conn = http.client.HTTPConnection("127.0.0.1", httpd.port, timeout=10)
        conn.request("DELETE", f"/v1/generate/{rid}")
        r = conn.getresponse()
        out = json.loads(r.read())
        conn.close()
        assert r.status == 200 and out["cancelled"] is True and out["tokens"] == [5, 6]
        # unknown id -> structured 404
        conn = http.client.HTTPConnection("127.0.0.1", httpd.port, timeout=10)
        conn.request("DELETE", "/v1/generate/9999")
        assert conn.getresponse().status == 404
        conn.close()
    finally:
        httpd.stop()


def test_front_door_sse_streams_tokens_then_done():
    sup, httpd = _httpd({"w0": {"health": "healthy", "slot": 0}})
    try:
        conn = http.client.HTTPConnection("127.0.0.1", httpd.port, timeout=10)
        conn.request(
            "POST",
            "/v1/generate",
            body=json.dumps({"prompt": [1], "max_new_tokens": 2, "stream": True}),
        )
        r = conn.getresponse()
        assert r.getheader("Content-Type", "").startswith("text/event-stream")
        raw = r.read().decode()
        conn.close()
        events = [
            (lines[0].split(": ", 1)[1], json.loads(lines[1].split(": ", 1)[1]))
            for block in raw.strip().split("\n\n")
            if (lines := block.split("\n"))
        ]
        kinds = [k for k, _ in events]
        assert kinds == ["token", "token", "done"]
        assert [d["token"] for k, d in events if k == "token"] == [5, 6]
        assert events[-1][1]["state"] == "done"
    finally:
        httpd.stop()


# --------------------------------------------------------------------- #
# model-checker drift gates (mirror of test_fleet_rules.py)
# --------------------------------------------------------------------- #


def _real_proc_spec():
    from accelerate_tpu.analysis.fleet_rules import load_proc_spec

    spec, problems = load_proc_spec(os.path.join(REPO, "accelerate_tpu"))
    assert spec is not None, f"extraction drifted: {problems}"
    return spec


def test_proc_spec_extracts_from_real_source():
    spec = _real_proc_spec()
    assert set(spec.states) == {"spawning", "healthy", "degraded", "quarantined", "dead"}
    assert spec.kind_target("crash") == "dead"
    assert spec.kind_target("poison") == "quarantined"
    assert spec.kind_kv("poison") is False and spec.kind_kv("crash") is True
    assert spec.respawn_cap_guard and spec.storm_breaker_guard
    assert spec.sheds_on_zero_routable


def test_proc_protocol_real_machine_zero_findings():
    from accelerate_tpu.analysis.fleet_rules import (
        PROC_CHAOS_COVERAGE,
        proc_model_check,
        proc_protocol_check,
    )

    findings, report = proc_protocol_check(package_root=os.path.join(REPO, "accelerate_tpu"))
    assert findings == [], [f.message for f in findings]
    assert not report.truncated
    assert report.explored_paths == set(PROC_CHAOS_COVERAGE)
    # determinism: a re-check explores the identical state space
    report2 = proc_model_check(_real_proc_spec())
    assert report2.explored_states == report.explored_states


def test_proc_chaos_coverage_pins_real_tests():
    """Every lifecycle path the checker explores must name a process-level
    chaos test DEFINED IN THIS FILE — model-checks equal chaos-observes."""
    import ast

    from accelerate_tpu.analysis.fleet_rules import PROC_CHAOS_COVERAGE

    tree = ast.parse(open(os.path.abspath(__file__)).read())
    defined = {n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)}
    for path_key, test in PROC_CHAOS_COVERAGE.items():
        assert test in defined, f"{path_key} pinned to missing test {test}"


def test_seeded_defect_unbounded_respawn_fires():
    from accelerate_tpu.analysis.fleet_rules import proc_protocol_check

    spec = dataclasses.replace(_real_proc_spec(), respawn_cap_guard=False)
    findings, report = proc_protocol_check(spec=spec)
    assert any("respawn-unbounded" in f.message for f in findings)
    assert all(f.rule == "TPU904" for f in findings)


def test_seeded_defect_restart_storm_unchecked_fires():
    from accelerate_tpu.analysis.fleet_rules import proc_protocol_check

    spec = dataclasses.replace(_real_proc_spec(), storm_breaker_guard=False)
    findings, _ = proc_protocol_check(spec=spec)
    assert any("restart-storm-unchecked" in f.message for f in findings)


def test_seeded_defect_missing_shed_strands_requests():
    from accelerate_tpu.analysis.fleet_rules import proc_protocol_check

    spec = dataclasses.replace(_real_proc_spec(), sheds_on_zero_routable=False)
    findings, _ = proc_protocol_check(spec=spec)
    assert any("breaker-missing" in f.message for f in findings)


def test_seeded_defect_poisoned_kv_shipped_fires():
    from accelerate_tpu.analysis.fleet_rules import proc_protocol_check

    spec = _real_proc_spec()
    trusting = tuple(
        (k, True if k == "poison" else v) for k, v in spec.kv_trust
    )
    findings, _ = proc_protocol_check(spec=dataclasses.replace(spec, kv_trust=trusting))
    assert any("poisoned-kv-shipped" in f.message for f in findings)


def test_unpinned_path_is_a_finding():
    from accelerate_tpu.analysis.fleet_rules import PROC_CHAOS_COVERAGE, proc_protocol_check

    partial = dict(PROC_CHAOS_COVERAGE)
    partial.pop(("respawn", "storm_breaker"))
    findings, _ = proc_protocol_check(spec=_real_proc_spec(), chaos_coverage=partial)
    assert any("storm_breaker" in f.message and "pinned to no" in f.message for f in findings)


# --------------------------------------------------------------------- #
# subprocess fleet harness (slow)
# --------------------------------------------------------------------- #

PROC_MODEL = {"seq_len": 64, "max_position_embeddings": 64}
PROC_ENGINE = {"num_slots": 2, "prompt_buckets": [8], "max_len": 64, "tick_block": 2}


@pytest.fixture(scope="module")
def proc_store(tmp_path_factory):
    """One ExecutableStore shared by every fleet in this module: the
    first boot compiles, every later worker (including respawns)
    deserializes — the zero-compile warm-start contract under test."""
    return str(tmp_path_factory.mktemp("proc_store"))


def _cfg(run_dir, store_dir, workers=2, **kw):
    from accelerate_tpu.serving_proc import ProcConfig

    kw.setdefault("model_kwargs", PROC_MODEL)
    kw.setdefault("engine", PROC_ENGINE)
    kw.setdefault("warm_prompt_lens", (4,))
    kw.setdefault("poll_interval_s", 0.01)
    kw.setdefault("heartbeat_timeout_s", 15.0)
    kw.setdefault("shadow_kv", True)
    return ProcConfig(workers=workers, run_dir=str(run_dir), store_dir=store_dir, **kw)


def _boot(cfg):
    from accelerate_tpu.serving_proc import ProcessSupervisor

    sup = ProcessSupervisor(cfg)
    sup.start(wait=True)
    assert any(h["health"] == "healthy" for h in sup.health().values()), sup.health()
    return sup


def _pump_until(sup, cond, timeout_s=120.0, msg=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        sup.pump()
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"pump_until timed out: {msg or cond}")


def _drive_all(sup, fids, timeout_s=120.0):
    """Poll every request to a terminal state; returns (outs, lost)."""
    from accelerate_tpu.serving_proc import FleetRequestError

    outs, lost = {}, {}

    def done():
        for f in fids:
            if f in outs or f in lost:
                continue
            try:
                r = sup.poll(f)
            except FleetRequestError as e:
                lost[f] = str(e)
                continue
            if r is not None:
                outs[f] = np.asarray(r)
        return len(outs) + len(lost) == len(fids)

    _pump_until(sup, done, timeout_s, "requests to finish")
    return outs, lost


def _prompts(n, rng=None, lo=3, hi=9):
    rng = rng or np.random.default_rng(0)
    return [[int(x) for x in rng.integers(1, 255, size=int(rng.integers(lo, hi)))] for _ in range(n)]


# ---- chaos-coverage-pinned lifecycle tests (names are load-bearing: ---- #
# ---- PROC_CHAOS_COVERAGE pins each explored path to one of these) ----- #


@pytest.mark.slow
def test_proc_sigkill_failover_completes_on_survivor(tmp_path, proc_store):
    """(crash, failover) + (respawn, ok): SIGKILL a real worker process
    mid-decode; its in-flight requests complete on the survivor, nothing
    is lost, and the slot respawns into a fresh healthy incarnation."""
    cfg = _cfg(
        tmp_path, proc_store,
        chaos={"worker": "w1", "label": "mid_decode", "action": "sigkill", "hits": 4},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(4)]
        outs, lost = _drive_all(sup, fids)
        assert not lost and len(outs) == 4
        acct = sup.failover_accounting()
        assert acct["failovers"] >= 1 and acct["failovers_lost"] == 0
        # the killed slot comes back as w1.<n>, healthy, zero compiles
        _pump_until(
            sup,
            lambda: any(
                s["respawns"] > 0 and s["health"] == "healthy" and s["hello"]
                for s in sup._slots
            ),
            msg="respawn to hello",
        )
        re = next(s for s in sup._slots if s["respawns"] > 0)
        assert re["name"].startswith("w1.")
        assert re["hello"]["compiles"] == 0 and re["hello"]["deserialized"] > 0
        # the flight dump written at death holds the kill
        dump = json.load(open(os.path.join(str(tmp_path), "flight_w1.json")))
        assert any(e.get("name") == "proc_exit" and e.get("killed") for e in dump["events"])
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_sole_worker_death_lost_not_stranded(tmp_path, proc_store):
    """(crash, capacity_lost) + (failover, lost_counted) + (capacity_lost,
    shed) + (respawn, giveup): the only worker dies with no respawn
    budget — in-flight requests surface as LOST (a structured error, not
    a hang) and new submits shed at the supervisor edge."""
    from accelerate_tpu.serving_proc import FleetRequestError

    cfg = _cfg(
        tmp_path, proc_store, workers=1, max_respawns=0,
        chaos={"worker": "w0", "label": "mid_decode", "action": "sigkill", "hits": 3},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(2)]
        outs, lost = _drive_all(sup, fids)
        assert lost, "sole-worker death must surface as FleetRequestError"
        summary = sup.summary()
        assert summary["lost"] == len(lost)
        assert sup.failover_accounting()["failovers_lost"] == len(lost)
        # the slot gave up (max_respawns=0) instead of respawn-looping
        assert any(s["gave_up"] for s in sup._slots)
        # zero routable capacity -> a fresh submit sheds, never queues
        fid = sup.submit([1, 2, 3], max_new_tokens=4)
        _pump_until(sup, lambda: sup._stream(fid)["state"] in ("shed", "lost"), 30)
        with pytest.raises(FleetRequestError):
            sup.poll(fid)
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_restart_storm_opens_breaker(tmp_path, proc_store):
    """(respawn, storm_breaker): correlated kills trip the fleet-wide
    restart-storm circuit breaker instead of churning respawns forever."""
    cfg = _cfg(
        tmp_path, proc_store, workers=2, max_respawns=5,
        storm_threshold=1, storm_window_s=300.0,
        respawn_backoff_base_s=0.01, respawn_backoff_max_s=0.05,
    )
    sup = _boot(cfg)
    try:
        def kill_slot_one():
            slot = sup._slots[1]
            if slot["health"] == "healthy" and slot["proc"] is not None:
                os.kill(slot["proc"].pid, signal.SIGKILL)
                return True
            return False

        assert kill_slot_one()
        # first death schedules respawn #1 (window count 1); wait for the
        # fresh incarnation, then kill it too -> count >= threshold ->
        # breaker opens and the slot gives up
        _pump_until(
            sup,
            lambda: sup._slots[1]["respawns"] == 1 and sup._slots[1]["health"] == "healthy",
            msg="first respawn",
        )
        assert kill_slot_one()
        _pump_until(sup, lambda: sup.summary()["breaker_open"], msg="storm breaker")
        assert sup._slots[1]["gave_up"]
        assert sup._slots[1]["respawns"] == 1  # no further attempts
        # the surviving worker still serves
        fid = sup.submit([1, 2, 3, 4], max_new_tokens=4)
        outs, lost = _drive_all(sup, [fid], 60)
        assert not lost
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_hang_degrades_then_heals(tmp_path, proc_store):
    """(timeout, degraded) + (degraded, heal): one transport timeout
    degrades the worker; clean polls heal it back to healthy — no kill,
    no migration, no respawn."""
    cfg = _cfg(
        tmp_path, proc_store, workers=1,
        heartbeat_timeout_s=0.6, quarantine_after_timeouts=50, heal_after_polls=3,
        chaos={"worker": "w0", "label": "mid_decode", "action": "hang",
               "hits": 2, "hang_s": 1.2},
    )
    sup = _boot(cfg)
    try:
        seen = set()

        def watch():
            seen.add(sup.health()["w0"]["health"])
            return "degraded" in seen

        fid = sup.submit([1, 2, 3], max_new_tokens=12)
        _pump_until(sup, watch, 60, "degraded")
        _pump_until(sup, lambda: sup.health()["w0"]["health"] == "healthy", 60, "heal")
        outs, lost = _drive_all(sup, [fid], 60)
        assert not lost
        assert sup.summary()["respawns_total"] == 0
        assert sup.failover_accounting()["failovers"] == 0
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_stall_quarantines_and_respawns(tmp_path, proc_store):
    """(timeout, quarantine): a hard stall crosses the timeout threshold
    -> the worker is killed + quarantined, its requests migrate to the
    survivor, and the slot respawns."""
    cfg = _cfg(
        tmp_path, proc_store, workers=2,
        heartbeat_timeout_s=0.6, quarantine_after_timeouts=2,
        chaos={"worker": "w1", "label": "mid_decode", "action": "hang",
               "hits": 3, "hang_s": 30.0},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(4)]
        outs, lost = _drive_all(sup, fids, 150)
        assert not lost and len(outs) == 4
        sup._log.flush()  # the event log buffers; the file is read mid-run
        log = [
            json.loads(line)
            for line in open(os.path.join(str(tmp_path), "events_supervisor.jsonl"))
        ]
        states = [e["state"] for e in log if e.get("name") == "proc_health"]
        assert "quarantined" in states
        assert sup.summary()["respawns_total"] >= 1
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_sole_worker_stall_lost_not_stranded(tmp_path, proc_store):
    """(timeout, capacity_lost): the only worker stalls into quarantine
    with no survivor — requests are lost with a structured reason."""
    cfg = _cfg(
        tmp_path, proc_store, workers=1, max_respawns=0,
        heartbeat_timeout_s=0.6, quarantine_after_timeouts=2,
        chaos={"worker": "w0", "label": "mid_decode", "action": "hang",
               "hits": 3, "hang_s": 30.0},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(2)]
        outs, lost = _drive_all(sup, fids, 150)
        assert lost and not outs
        assert sup.health()["w0"]["health"] == "quarantined"
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_poison_quarantines_recompute_only(tmp_path, proc_store):
    """(poison, quarantine_no_kv): a numerics-poisoned worker is
    quarantined and its requests migrate WITHOUT their KV snapshots —
    allow_kv=False forces the recompute path (poisoned cache never
    ships)."""
    cfg = _cfg(
        tmp_path, proc_store, workers=2,
        chaos={"worker": "w1", "label": "mid_decode", "action": "poison", "hits": 3},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(4)]
        outs, lost = _drive_all(sup, fids, 150)
        assert not lost and len(outs) == 4
        acct = sup.failover_accounting()
        assert acct["failovers"] >= 1
        assert acct["failovers_kv"] == 0 and acct["failovers_recompute"] >= 1
        assert acct["bytes_moved"] == 0
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_sole_worker_poison_lost_not_stranded(tmp_path, proc_store):
    """(poison, capacity_lost): poison with no survivor — lost, counted,
    structured."""
    cfg = _cfg(
        tmp_path, proc_store, workers=1, max_respawns=0,
        chaos={"worker": "w0", "label": "mid_decode", "action": "poison", "hits": 3},
    )
    sup = _boot(cfg)
    try:
        fids = [sup.submit(p, max_new_tokens=16) for p in _prompts(2)]
        outs, lost = _drive_all(sup, fids, 120)
        assert lost and not outs
        assert sup.failover_accounting()["failovers_lost"] == len(lost)
    finally:
        sup.shutdown()


@pytest.mark.slow
def test_proc_drain_worker_migrates(tmp_path, proc_store):
    """(drain, migrate): planned maintenance — drain_worker exports the
    full in-flight state (KV included), migrates to the survivor, and
    shuts the process down; every request still completes."""
    cfg = _cfg(tmp_path, proc_store, workers=2)
    sup = _boot(cfg)
    try:
        # least-outstanding routing only reaches w1 once it is serving —
        # a slow boot would otherwise send every request to w0
        _pump_until(
            sup,
            lambda: all(h["health"] == "healthy" for h in sup.health().values()),
            120, "both workers healthy",
        )
        fids = [sup.submit(p, max_new_tokens=24) for p in _prompts(4)]
        _pump_until(
            sup,
            lambda: any(len(s["uids"]) > 0 for s in sup._slots if s["name"] == "w1"),
            60, "w1 to own work",
        )
        routed_to_w1 = len(sup._slots[1]["uids"])
        res = sup.drain_worker("w1")
        assert res["migrated"] >= routed_to_w1 - 1  # some may have just finished
        assert sup.health()["w1"]["health"] == "dead"
        outs, lost = _drive_all(sup, fids, 150)
        assert not lost and len(outs) == 4
        assert sup.failover_accounting()["failovers_lost"] == 0
    finally:
        sup.shutdown()


# ---- zero-compile spin-up + end-to-end front door ---------------------- #


@pytest.mark.slow
def test_fresh_subprocess_zero_compile_spin_up(tmp_path, proc_store):
    """A fresh worker PROCESS against a warmed store deserializes every
    executable: hello reports 0 compiles. The first boot of this module
    may compile; the second boot (same store) must not."""
    sup = _boot(_cfg(tmp_path / "a", proc_store, workers=1))
    sup.shutdown()
    sup = _boot(_cfg(tmp_path / "b", proc_store, workers=1))
    try:
        hello = sup._slots[0]["hello"]
        assert hello["compiles"] == 0, hello
        assert hello["deserialized"] > 0
        fid = sup.submit([1, 2, 3, 4], max_new_tokens=8)
        outs, lost = _drive_all(sup, [fid], 60)
        assert not lost
        # steady state stays replay-only on the warmed worker
        assert sup.health()["w0"]["compiles"] == 0
    finally:
        sup.shutdown()


def _sse_blocks(resp, n_events, timeout_s=60.0):
    """Read SSE blocks incrementally from an http.client response."""
    buf = b""
    events = []
    deadline = time.monotonic() + timeout_s
    while len(events) < n_events and time.monotonic() < deadline:
        chunk = resp.read1(4096) if hasattr(resp, "read1") else resp.read(1)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            block, buf = buf.split(b"\n\n", 1)
            lines = block.decode().split("\n")
            ev = lines[0].split(": ", 1)[1]
            data = json.loads(lines[1].split(": ", 1)[1])
            events.append((ev, data))
    return events


@pytest.mark.slow
def test_serve_end_to_end_http_sse_cancel_drain(tmp_path, proc_store):
    """``accelerate-tpu serve`` end to end against a real subprocess:
    HTTP submit, SSE streaming, cancellation, /metrics + /healthz on real
    liveness, and SIGTERM draining to exit 0."""
    ready = tmp_path / "ready.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "accelerate_tpu.commands.serve",
            "--workers", "1", "--run-dir", str(tmp_path / "run"),
            "--store-dir", proc_store, "--http-port", "0",
            "--model-kwargs", json.dumps(PROC_MODEL),
            "--engine-kwargs", json.dumps(PROC_ENGINE),
            "--ready-file", str(ready), "--max-runtime-s", "300",
        ],
        env=env, cwd=REPO,
        stdout=open(tmp_path / "serve.log", "w"), stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.monotonic() + 180
        while not ready.exists() and time.monotonic() < deadline:
            assert proc.poll() is None, open(tmp_path / "serve.log").read()
            time.sleep(0.1)
        assert ready.exists(), "serve never became ready"
        port = json.load(open(ready))["http_port"]

        status, body = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["serving"] is True

        # plain JSON submit waits for the result
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/generate",
            body=json.dumps({"prompt": [1, 2, 3, 4], "max_new_tokens": 4}),
        )
        r = conn.getresponse()
        out = json.loads(r.read())
        conn.close()
        assert r.status == 200 and out["state"] == "done"
        assert len(out["final"]) == 8 and out["final"][:4] == [1, 2, 3, 4]

        # SSE stream: token events then done (exact same fleet answer)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/generate",
            body=json.dumps({"prompt": [1, 2, 3, 4], "max_new_tokens": 4}),
            headers={"Accept": "text/event-stream"},
        )
        r = conn.getresponse()
        assert r.getheader("Content-Type", "").startswith("text/event-stream")
        events = _sse_blocks(r, 5)
        conn.close()
        assert [k for k, _ in events] == ["token"] * 4 + ["done"]
        assert [d["token"] for k, d in events[:4]] == out["final"][4:]

        # cancellation: start a long stream, cancel it from the side
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/generate",
            body=json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 48, "stream": True}),
        )
        r = conn.getresponse()
        rid = int(r.getheader("X-Request-Id"))
        c2 = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c2.request("DELETE", f"/v1/generate/{rid}")
        assert c2.getresponse().status == 200
        c2.close()
        tail = _sse_blocks(r, 64)
        conn.close()
        assert tail and tail[-1][0] == "done" and tail[-1][1]["state"] == "cancelled"

        # /metrics speaks prometheus with real per-worker gauges
        status, body = _get(port, "/metrics")
        assert status == 200 and b"proc_worker_state" in body

        # SIGTERM drains gracefully: exit 0
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0, open(tmp_path / "serve.log").read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
