"""Fault-tolerance tests: atomic commit protocol, crash-at-every-point
matrix, integrity manifests, auto-resume, preemption, retries.

The invariant under test (ISSUE 4 acceptance): for every labeled crash
point during save and for corrupt/truncated checkpoint files,
``Accelerator.load_state()`` auto-resume restores a bit-exact valid
state (step, params, opt_state, sampler position, RNG) from the newest
committed checkpoint, and no code path ever deletes the last valid
checkpoint before a new one commits.
"""

import json
import os
import signal
from pathlib import Path

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ProjectConfiguration
from accelerate_tpu.ft import (
    CRASH_POINTS,
    CheckpointManager,
    PreemptionHandler,
    build_manifest,
    read_manifest,
    verify_manifest,
    write_manifest,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.test_utils import (
    CrashPoint,
    RegressionDataset,
    RegressionModel,
    SimulatedCrash,
    corrupt_file,
    linear_loss_fn,
)
from accelerate_tpu.utils import FaultToleranceKwargs
from accelerate_tpu.utils.retry import backoff_delays, retry, retry_call

BATCH = {"x": np.ones((8,), np.float32), "y": 2 * np.ones((8,), np.float32)}


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _fresh(project_dir, total_limit=None, with_loader=False, **acc_kwargs):
    """A 'new process': reset the singletons and build a full training
    setup with automatic checkpoint naming."""
    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(project_dir), automatic_checkpoint_naming=True, total_limit=total_limit
        ),
        **acc_kwargs,
    )
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.adam(0.05))
    loader = None
    if with_loader:
        loader = acc.prepare(RegressionDataset(length=64, seed=11))
        loader.batch_size = 8 // acc.num_data_shards
    step = acc.build_train_step(linear_loss_fn)
    return acc, model, step, loader


def _next_rand_from(state):
    """The next np.random draw a process restored to `state` will produce."""
    rs = np.random.RandomState()
    rs.set_state(state)
    return float(rs.rand())


def _snapshot(acc, model):
    return {
        "a": float(np.asarray(model.params["a"])),
        "b": float(np.asarray(model.params["b"])),
        "opt": [float(np.asarray(x).sum()) for x in __import__("jax").tree_util.tree_leaves(acc._optimizers[-1].opt_state)],
        "step": acc.step,
        "next_rand": _next_rand_from(np.random.get_state()),
    }


# --------------------------------------------------------------------------- #
# the crash matrix
# --------------------------------------------------------------------------- #

# which checkpoint auto-resume must land on after a crash at each point:
# before the manifest exists the save never committed -> the OLD checkpoint;
# from pre_rename on, the manifest IS written (commit point) -> the NEW
# state must be recovered (gc finishes the rename)
EXPECT_SOURCE = {
    "pre_write": "old",
    "mid_pytree": "old",
    "pre_manifest": "old",
    "pre_rename": "new",
    "mid_prune": "new",
}
assert set(EXPECT_SOURCE) == set(CRASH_POINTS)


@pytest.mark.parametrize("label", CRASH_POINTS)
def test_crash_at_every_point_resumes_on_valid_checkpoint(tmp_path, label):
    # mid_prune only fires when pruning has victims: give it a total_limit
    total_limit = 2 if label == "mid_prune" else None
    acc, model, step, loader = _fresh(tmp_path, total_limit=total_limit, with_loader=True)

    # deliver 2 batches mid-epoch, train, take one GOOD checkpoint
    it = iter(loader)
    next(it), next(it)
    step(BATCH)
    step(BATCH)
    acc.save_state()
    old = _snapshot(acc, model)

    if label == "mid_prune":
        # pruning needs existing checkpoints beyond the limit
        step(BATCH)
        acc.save_state()

    # train further, then the save CRASHES at `label`
    step(BATCH)
    next(it)  # 3 batches delivered now
    new = _snapshot(acc, model)
    with CrashPoint(label) as cp:
        with pytest.raises(SimulatedCrash):
            acc.save_state()
    assert cp.fired, f"crash point {label} was never reached"
    del it

    # ---- 'new process': auto-resume must land on the newest VALID state ----
    acc2, model2, step2, loader2 = _fresh(tmp_path, total_limit=total_limit, with_loader=True)
    acc2.load_state()  # input_dir=None -> auto-resume
    want = new if EXPECT_SOURCE[label] == "new" else old
    if label == "mid_prune":
        # two saves happened between `old` and the crash-save
        want = new
    assert float(np.asarray(model2.params["a"])) == pytest.approx(want["a"])
    assert float(np.asarray(model2.params["b"])) == pytest.approx(want["b"])
    assert acc2.step == want["step"]
    # RNG restored bit-exactly: the next draw matches what the crashed
    # process would have drawn after its last committed save
    assert float(np.random.rand()) == pytest.approx(want["next_rand"], abs=0)
    # sampler position: batches already delivered at the committed save
    expected_skip = 3 if EXPECT_SOURCE[label] == "new" or label == "mid_prune" else 2
    assert loader2.skip_batches == expected_skip

    # no .tmp garbage survives resume, and training + saving continue
    mgr = CheckpointManager(tmp_path / "checkpoints")
    assert mgr.tmp_dirs() == []
    step2(BATCH)
    committed_before = {p.name for p in mgr.all_valid(deep=True)}
    assert committed_before, "resume must leave at least one valid checkpoint"
    out = acc2.save_state()
    assert mgr.verify(out).ok
    # the next save went to a FRESH index (no overwrite of history)
    assert os.path.basename(out) not in committed_before


def test_crash_save_never_deletes_last_valid_checkpoint(tmp_path):
    """With total_limit=1 the seed code pruned the only good checkpoint
    BEFORE writing the new one — a crash in that window lost both."""
    acc, model, step, _ = _fresh(tmp_path, total_limit=1)
    step(BATCH)
    acc.save_state()  # checkpoint_0
    mgr = CheckpointManager(tmp_path / "checkpoints")
    assert [p.name for p in mgr.all_valid(deep=True)] == ["checkpoint_0"]

    step(BATCH)
    for label in ("pre_write", "mid_pytree", "pre_manifest"):
        with CrashPoint(label):
            with pytest.raises(SimulatedCrash):
                acc.save_state()
        # the old checkpoint MUST still be there and valid
        assert mgr.verify(tmp_path / "checkpoints" / "checkpoint_0").ok, label

    # an uninterrupted save finally prunes it, post-commit
    out = acc.save_state()
    names = {p.name for p in mgr.all_valid(deep=True)}
    assert os.path.basename(out) in names
    assert "checkpoint_0" not in names


def test_prune_protects_resume_source(tmp_path):
    """Satellite: total_limit pruning excludes the checkpoint the run is
    resuming from, even when it is the oldest."""
    acc, model, step, _ = _fresh(tmp_path, total_limit=1)
    step(BATCH)
    acc.save_state()  # checkpoint_0

    acc2, model2, step2, _ = _fresh(tmp_path, total_limit=1)
    src = acc2.load_state()
    assert os.path.basename(src) == "checkpoint_0"
    step2(BATCH)
    acc2.save_state()  # checkpoint_1; limit=1 would normally kill checkpoint_0
    names = {p.name for p in CheckpointManager(tmp_path / "checkpoints").all_valid(deep=True)}
    assert names == {"checkpoint_0", "checkpoint_1"}  # resume source survives


def test_iteration_restored_on_resume(tmp_path):
    """Satellite regression: the seed wrote `save_iteration` but never read
    it, so a resumed run started at checkpoint_0 again and overwrote it."""
    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    acc.save_state()
    a0 = float(np.asarray(model.params["a"]))

    acc2, model2, step2, _ = _fresh(tmp_path)
    acc2.load_state()
    assert acc2.project_configuration.iteration == 1
    step2(BATCH)
    acc2.save_state()
    base = tmp_path / "checkpoints"
    assert (base / "checkpoint_1").is_dir(), "resumed save must continue the numbering"
    # checkpoint_0 untouched: reload it and compare
    acc3, model3, _, _ = _fresh(tmp_path)
    acc3.load_state(str(base / "checkpoint_0"))
    assert float(np.asarray(model3.params["a"])) == pytest.approx(a0)
    assert acc3.project_configuration.iteration == 1  # explicit load restores the counter too


# --------------------------------------------------------------------------- #
# corruption / truncation detection
# --------------------------------------------------------------------------- #

def test_auto_resume_walks_back_past_corrupt_checkpoint(tmp_path):
    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    acc.save_state()  # checkpoint_0 (good)
    a0 = float(np.asarray(model.params["a"]))
    step(BATCH)
    acc.save_state()  # checkpoint_1 (to be corrupted)

    base = tmp_path / "checkpoints"
    corrupt_file(base / "checkpoint_1" / "accelerate_state.json", mode="garbage")
    mgr = CheckpointManager(base)
    res = mgr.verify(base / "checkpoint_1")
    assert not res.ok and any("crc32" in p for p in res.problems)

    acc2, model2, _, _ = _fresh(tmp_path)
    src = acc2.load_state()
    assert os.path.basename(src) == "checkpoint_0"
    assert float(np.asarray(model2.params["a"])) == pytest.approx(a0)


@pytest.mark.parametrize("mode", ["truncate", "delete"])
def test_verify_detects_damaged_pytree_files(tmp_path, mode):
    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    out = acc.save_state()
    mgr = CheckpointManager(tmp_path / "checkpoints")
    assert mgr.verify(out).ok
    manifest = read_manifest(out)
    # damage the largest recorded orbax array file
    rel = max(manifest["pytree_files"], key=manifest["pytree_files"].get)
    corrupt_file(os.path.join(out, rel), mode=mode)
    res = mgr.verify(out)
    assert not res.ok
    assert any(rel in p for p in res.problems)
    assert mgr.latest(deep=True) is None  # nothing valid left to resume from
    acc2, _, _, _ = _fresh(tmp_path)
    with pytest.raises(FileNotFoundError):
        acc2.load_state()


def test_uncommitted_checkpoint_is_invisible(tmp_path):
    """A directory without a manifest (pre-FT checkpoint or kill mid-write)
    never surfaces through discovery."""
    base = tmp_path / "checkpoints"
    (base / "checkpoint_0").mkdir(parents=True)
    (base / "checkpoint_0" / "accelerate_state.json").write_text(json.dumps({"step": 3}))
    mgr = CheckpointManager(base)
    assert mgr.all_checkpoints() != []
    assert mgr.all_valid() == []
    assert mgr.latest() is None
    problems = verify_manifest(base / "checkpoint_0")
    assert any("no commit manifest" in p for p in problems)


def test_truncated_manifest_means_uncommitted(tmp_path):
    d = tmp_path / "checkpoint_0"
    d.mkdir()
    (d / "data.json").write_text("{}")
    write_manifest(d, build_manifest(d, step=1, iteration=0))
    corrupt_file(d / "commit_success.json", mode="truncate", nbytes=8)
    assert read_manifest(d) is None
    assert verify_manifest(d) != []


# --------------------------------------------------------------------------- #
# async-save failure drain (satellite)
# --------------------------------------------------------------------------- #

def test_failed_async_save_never_looks_committed(tmp_path):
    """If a background write fails, the drain must abort the commit and
    remove the partial directory — nothing may mistake it for a
    checkpoint."""
    from accelerate_tpu import checkpointing

    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    acc.save_state()  # checkpoint_0, good
    step(BATCH)
    acc.save_state(async_save=True)  # checkpoint_1 in flight

    assert len(checkpointing._PENDING_ASYNC) == 1
    pending = checkpointing._PENDING_ASYNC[0]

    class _Exploding:
        def __init__(self, inner):
            self._inner = inner

        def wait_until_finished(self):
            self._inner.wait_until_finished()  # let the real write land...
            raise OSError("simulated filer failure")  # ...then report failure

        def close(self):
            self._inner.close()

    pending.checkpointers = [_Exploding(c) for c in pending.checkpointers]
    with pytest.raises(OSError, match="simulated filer failure"):
        acc.wait_for_checkpoint()

    base = tmp_path / "checkpoints"
    mgr = CheckpointManager(base)
    assert not (base / "checkpoint_1").exists(), "failed save must not be committed"
    assert mgr.tmp_dirs() == [], "failed save's partial dir must be removed"
    assert [p.name for p in mgr.all_valid(deep=True)] == ["checkpoint_0"]
    # and a later save still works (pending list was consumed)
    out = acc.save_state()
    assert mgr.verify(out).ok


def test_async_save_commits_manifest_on_drain(tmp_path):
    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    out = acc.save_state(async_save=True)
    base = tmp_path / "checkpoints"
    acc.wait_for_checkpoint()
    assert (base / "checkpoint_0").is_dir()
    res = CheckpointManager(base).verify(out)
    assert res.ok, res.problems
    assert res.manifest["step"] == acc.step


# --------------------------------------------------------------------------- #
# preemption
# --------------------------------------------------------------------------- #

def test_preemption_handler_latches_flag():
    handler = PreemptionHandler(signals=("SIGTERM",))
    try:
        assert handler.install()
        assert not handler.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert handler.preempted
        assert handler.received == "SIGTERM"
    finally:
        handler.uninstall()


def test_accelerator_preemption_checkpoint_and_stop(tmp_path):
    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True),
        kwargs_handlers=[FaultToleranceKwargs(preemption_signals=("SIGTERM",))],
    )
    try:
        model = acc.prepare_model(RegressionModel())
        acc.prepare_optimizer(optax.sgd(0.1))
        step = acc.build_train_step(linear_loss_fn)
        assert acc.preemption_handler is not None and acc.preemption_handler.installed
        assert not acc.should_stop and not acc.should_checkpoint

        step(BATCH)
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        assert acc.should_checkpoint and acc.should_stop

        # the loop's reaction: one final SYNCHRONOUS checkpoint
        out = acc.save_state(async_save=True)  # async demoted to sync under preemption
        from accelerate_tpu import checkpointing

        assert checkpointing._PENDING_ASYNC == [], "preempted save must be synchronous"
        assert CheckpointManager(tmp_path / "checkpoints").verify(out).ok
        assert not acc.should_checkpoint, "final checkpoint taken exactly once"
        assert acc.should_stop
    finally:
        if acc.preemption_handler is not None:
            acc.preemption_handler.uninstall()


def test_accelerator_without_ft_handler_installs_nothing():
    _reset()
    acc = Accelerator()
    assert acc.preemption_handler is None
    assert not acc.should_stop and not acc.should_checkpoint


# --------------------------------------------------------------------------- #
# retry decorator
# --------------------------------------------------------------------------- #

def test_retry_succeeds_after_transient_failures():
    sleeps, calls = [], []

    @retry(attempts=4, base_delay=0.01, sleep=sleeps.append)
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert flaky() == "ok"
    assert len(calls) == 3
    assert len(sleeps) == 2


def test_retry_gives_up_and_reports():
    events = []
    with pytest.raises(OSError):
        retry_call(
            lambda: (_ for _ in ()).throw(OSError("dead")),
            attempts=3,
            base_delay=0.01,
            sleep=lambda s: None,
            on_retry=lambda a, d, e: events.append(("retry", a)),
            on_giveup=lambda a, e: events.append(("giveup", a)),
        )
    assert events == [("retry", 1), ("retry", 2), ("giveup", 3)]


def test_retry_does_not_catch_simulated_crash():
    def boom():
        raise SimulatedCrash("not retryable")

    with pytest.raises(SimulatedCrash):
        retry_call(boom, attempts=5, sleep=lambda s: None)


def test_backoff_delays_grow_and_cap():
    delays = list(backoff_delays(5, base_delay=1.0, max_delay=4.0, jitter=0.0, rng=lambda: 0.0))
    assert delays == [1.0, 2.0, 4.0, 4.0]
    jittered = list(backoff_delays(3, base_delay=1.0, max_delay=9.0, jitter=0.5, rng=lambda: 1.0))
    assert jittered == [1.5, 3.0]


# --------------------------------------------------------------------------- #
# telemetry integration
# --------------------------------------------------------------------------- #

def test_checkpoint_events_land_in_telemetry_log(tmp_path):
    from accelerate_tpu.telemetry import read_events

    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True),
    )
    acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(linear_loss_fn)
    tel = acc.telemetry  # activate the event log
    step(BATCH)
    acc.save_state()
    acc.load_state()
    tel.close()

    names = [e["name"] for e in read_events(tel.path)]
    assert "ckpt_save" in names
    assert "ckpt_commit" in names
    assert "ckpt_auto_resume" in names


# --------------------------------------------------------------------------- #
# topology-elastic restore (ISSUE 6)
# --------------------------------------------------------------------------- #

from accelerate_tpu import MeshConfig, ParallelismPlugin  # noqa: E402
from accelerate_tpu.ft import (  # noqa: E402
    RESTORE_CRASH_POINTS,
    compare_topology,
    derive_rng_state,
    predict_reshard,
    redistribute_sampler_state,
)

# the elastic matrix meshes, all realisable on the 8-device fake-CPU
# harness: (4,) and (2,2) use a 4-device subset, (1,) a single device
MESHES = {
    "4": dict(data=4, num_devices=4),
    "8": dict(data=8),
    "2x2": dict(data=2, tensor=2, num_devices=4),
    "1": dict(data=1, num_devices=1),
}

# save-side -> restore-side pairs: both ISSUE sources against every
# target, plus the reverse direction for the targets that are not
# themselves sources
MATRIX = {
    "4": ("8", "4", "2x2", "1"),
    "2x2": ("8", "4", "2x2", "1"),
    "8": ("4", "2x2"),
    "1": ("4", "2x2"),
}


def _fresh_mesh(project_dir, mesh_kwargs, with_loader=True):
    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(project_dir), automatic_checkpoint_naming=True),
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(**mesh_kwargs)),
    )
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.adam(0.05))
    loader = None
    if with_loader:
        loader = acc.prepare(RegressionDataset(length=64, seed=11))
        loader.batch_size = 8 // acc.num_data_shards  # global batch stays 8
    return acc, model, loader


def _array_snapshot(acc, model):
    import jax

    return {
        "params": [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(model.params)],
        "opt": [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(acc._optimizers[-1].opt_state)],
        "step": acc.step,
    }


def _assert_bit_exact(acc, model, want):
    import jax

    for got, exp in zip(jax.tree_util.tree_leaves(model.params), want["params"]):
        assert np.array_equal(np.asarray(got), exp), "params must restore bit-exact"
    for got, exp in zip(jax.tree_util.tree_leaves(acc._optimizers[-1].opt_state), want["opt"]):
        assert np.array_equal(np.asarray(got), exp), "opt_state must restore bit-exact"
    assert acc.step == want["step"]


@pytest.mark.parametrize("src", list(MATRIX))
def test_elastic_restore_matrix(tmp_path, src):
    """ISSUE 6 acceptance: a checkpoint saved on mesh ``src`` restores
    bit-exact params/opt-state and the correct step/sampler offset on
    every target mesh, including a resume after an injected mid-restore
    crash per direction."""
    acc, model, loader = _fresh_mesh(tmp_path, MESHES[src])
    step = acc.build_train_step(linear_loss_fn)
    it = iter(loader)
    next(it), next(it)  # 2 global batches delivered mid-epoch
    step(BATCH)
    step(BATCH)
    acc.save_state()
    want = _array_snapshot(acc, model)
    del it

    for dst in MATRIX[src]:
        # injected crash mid-restore, then the retry must still succeed
        acc2, model2, loader2 = _fresh_mesh(tmp_path, MESHES[dst])
        with CrashPoint("mid_restore_arrays") as cp:
            with pytest.raises(SimulatedCrash):
                acc2.load_state()
        assert cp.fired
        src_path = acc2.load_state()  # checkpoint untouched by the crash
        assert os.path.basename(src_path) == "checkpoint_0"
        _assert_bit_exact(acc2, model2, want)
        assert loader2.skip_batches == 2, f"{src}->{dst}: sampler offset lost"
        # training continues on the new topology and the next save commits
        step2 = acc2.build_train_step(linear_loss_fn)
        step2(BATCH)


def test_elastic_restore_emits_telemetry_and_rederives_rng(tmp_path):
    from accelerate_tpu.telemetry import read_events

    acc, model, loader = _fresh_mesh(tmp_path, MESHES["4"])
    step = acc.build_train_step(linear_loss_fn)
    step(BATCH)
    acc.save_state()

    acc2, model2, loader2 = _fresh_mesh(tmp_path, MESHES["8"])
    tel = acc2.telemetry
    acc2.load_state()
    tel.close()
    events = {e["name"]: e for e in read_events(tel.path)}
    assert "ckpt_elastic_restore" in events, "elastic path must never be silent"
    assert events["ckpt_elastic_restore"]["severity"] == "warning"
    assert any("mesh" in c for c in events["ckpt_elastic_restore"]["changes"])
    assert "ckpt_rng_rederive" in events
    # the re-derived streams are deterministic: a second identical elastic
    # restore draws the same next value
    first_draw = float(np.random.rand())
    acc3, model3, loader3 = _fresh_mesh(tmp_path, MESHES["8"])
    acc3.load_state()
    assert float(np.random.rand()) == pytest.approx(first_draw, abs=0)


def test_identical_topology_restore_stays_bit_exact(tmp_path):
    """The elastic path must NOT fire on a same-topology resume: RNG
    comes back from the pickles, stream positions intact."""
    acc, model, loader = _fresh_mesh(tmp_path, MESHES["4"])
    step = acc.build_train_step(linear_loss_fn)
    step(BATCH)
    acc.save_state()
    want_rand = _next_rand_from(np.random.get_state())

    acc2, model2, loader2 = _fresh_mesh(tmp_path, MESHES["4"])
    acc2.load_state()
    assert float(np.random.rand()) == pytest.approx(want_rand, abs=0)


def test_elastic_sampler_offset_redistribution(tmp_path):
    """Different global batch on the restore side: the global sample
    offset (2 batches x 8 samples) re-splits into 1 batch of 16."""
    acc, model, loader = _fresh_mesh(tmp_path, MESHES["4"])  # global batch 8
    it = iter(loader)
    next(it), next(it)
    step = acc.build_train_step(linear_loss_fn)
    step(BATCH)
    acc.save_state()
    del it

    acc2, model2, loader2 = _fresh_mesh(tmp_path, MESHES["2x2"])
    loader2.batch_size = 16 // acc2.num_data_shards  # global batch 16
    acc2.load_state()
    assert loader2.skip_batches == 1  # 16 samples / 16 per global batch


def test_redistribute_sampler_state_math():
    s = {"batches_yielded": 6, "global_batch_size": 8, "sampler_seed": 3}
    out, replayed = redistribute_sampler_state(s, 16)
    assert out["batches_yielded"] == 3 and replayed == 0
    out, replayed = redistribute_sampler_state(s, 32)
    assert out["batches_yielded"] == 1 and replayed == 16  # rounds DOWN: replay, never skip
    assert out["sampler_seed"] == 3  # permutation identity survives
    # identity when nothing changed or nothing is known
    assert redistribute_sampler_state(s, 8) == (s, 0)
    assert redistribute_sampler_state({"batches_yielded": 2}, 16)[1] == 0


def test_derive_rng_state_is_deterministic_and_rank_folded():
    a = derive_rng_state(42, process_index=0, step=10)
    assert a == derive_rng_state(42, process_index=0, step=10)
    assert a != derive_rng_state(42, process_index=1, step=10)  # fold-in of the new rank
    assert a != derive_rng_state(43, process_index=0, step=10)
    assert a != derive_rng_state(42, process_index=0, step=11)


def test_compare_topology_tiers():
    saved = {"process_count": 2, "mesh_shape": {"data": 4, "tensor": 1}, "dcn_axes": [],
             "data_parallel_degree": 4}
    same = dict(saved, mesh_shape={"data": 4})  # trivial axes are normalised away
    assert compare_topology(saved, same).status == "identical"
    assert compare_topology(None, same).status == "unknown"
    moved = compare_topology(saved, dict(saved, mesh_shape={"data": 8}, data_parallel_degree=8))
    assert moved.status == "elastic" and moved.is_elastic
    assert any("mesh" in c for c in moved.changes)
    grown = compare_topology(saved, dict(saved, process_count=4))
    assert grown.status == "elastic"
    assert any("process count" in c for c in grown.changes)


def test_predict_reshard_prices_ici_dcn_split():
    saved = {
        "mesh_shape": {"data": 4}, "dcn_axes": [], "data_parallel_degree": 4,
        "arrays": {"w": {"shape": [8, 4], "dtype": "float32", "spec": ["data", None], "bytes": 1024}},
    }
    none = predict_reshard(saved)  # same topology -> nothing moves
    assert none.total_bytes == 0 and none.moved_count == 0
    ici = predict_reshard(saved, {"data": 8}, ())
    assert ici.ici_bytes == 1024 * 7 // 8 and ici.dcn_bytes == 0
    hybrid = predict_reshard(saved, {"data": 4, "fsdp": 2}, ("fsdp",))
    assert hybrid.ici_bytes == 1024 * 3 // 4  # ring over the 4-way ICI stage
    assert hybrid.dcn_bytes == 1024 * 1 // 2  # ring over the 2-way DCN stage
    assert predict_reshard(None).total_bytes == 0


# --------------------------------------------------------------------------- #
# restore-side fault injection / corruption matrix
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("label", RESTORE_CRASH_POINTS)
def test_crash_at_every_restore_point_leaves_checkpoint_valid(tmp_path, label):
    """Restore never mutates the checkpoint: a kill at any restore point
    leaves it deep-valid, and a fresh auto-resume lands on it with the
    exact saved state (including RNG stream positions)."""
    acc, model, step, loader = _fresh(tmp_path, with_loader=True)
    step(BATCH)
    acc.save_state()
    want = _snapshot(acc, model)

    acc2, model2, step2, loader2 = _fresh(tmp_path, with_loader=True)
    with CrashPoint(label) as cp:
        with pytest.raises(SimulatedCrash):
            acc2.load_state()
    assert cp.fired, f"restore crash point {label} was never reached"

    mgr = CheckpointManager(tmp_path / "checkpoints")
    assert mgr.verify(tmp_path / "checkpoints" / "checkpoint_0").ok, "crash mid-restore damaged the checkpoint"
    acc3, model3, step3, loader3 = _fresh(tmp_path, with_loader=True)
    src = acc3.load_state()
    assert os.path.basename(src) == "checkpoint_0"
    assert float(np.asarray(model3.params["a"])) == pytest.approx(want["a"])
    assert acc3.step == want["step"]
    assert float(np.random.rand()) == pytest.approx(want["next_rand"], abs=0)


def test_elastic_auto_resume_walks_back_past_truncated_shard(tmp_path):
    """Restore-side corruption under a topology change: the newest
    checkpoint has a truncated orbax shard, so the elastic auto-resume
    must walk back and reshard the older one."""
    from accelerate_tpu.ft import read_manifest as _read_manifest

    acc, model, loader = _fresh_mesh(tmp_path, MESHES["4"])
    step = acc.build_train_step(linear_loss_fn)
    step(BATCH)
    acc.save_state()  # checkpoint_0 (good)
    want = _array_snapshot(acc, model)
    step(BATCH)
    acc.save_state()  # checkpoint_1 (to be truncated)
    base = tmp_path / "checkpoints"
    manifest = _read_manifest(base / "checkpoint_1")
    rel = max(manifest["pytree_files"], key=manifest["pytree_files"].get)
    corrupt_file(base / "checkpoint_1" / rel, mode="truncate")

    acc2, model2, loader2 = _fresh_mesh(tmp_path, MESHES["8"])
    src = acc2.load_state()
    assert os.path.basename(src) == "checkpoint_0"
    _assert_bit_exact(acc2, model2, want)


@pytest.mark.parametrize("strip", ["v1", "topology"])
def test_pre_elastic_manifest_restores_on_identical_topology(tmp_path, strip):
    """Backward compat: a schema-v1 manifest (or a v2 manifest whose
    topology block was deleted) still commits and restores bit-exact on
    the topology that wrote it."""
    from accelerate_tpu.ft import write_manifest as _write_manifest

    acc, model, step, loader = _fresh(tmp_path, with_loader=True)
    step(BATCH)
    acc.save_state()
    want = _snapshot(acc, model)
    ck = tmp_path / "checkpoints" / "checkpoint_0"
    manifest = read_manifest(ck)
    assert manifest["schema_version"] == 2 and "topology" in manifest
    manifest.pop("topology")
    if strip == "v1":
        manifest["schema_version"] = 1
    _write_manifest(ck, manifest)

    acc2, model2, step2, loader2 = _fresh(tmp_path, with_loader=True)
    src = acc2.load_state()  # discovery still accepts the old manifest
    assert os.path.basename(src) == "checkpoint_0"
    assert float(np.asarray(model2.params["a"])) == pytest.approx(want["a"])
    assert acc2.step == want["step"]
    # identical topology + no record -> the legacy bit-exact RNG path
    assert float(np.random.rand()) == pytest.approx(want["next_rand"], abs=0)


def test_missing_rng_file_warns_and_emits_telemetry(tmp_path):
    """Satellite: a missing rng_state_{i}.pkl must be LOUD (the seed
    silently resumed with fresh-process RNG)."""
    from accelerate_tpu.telemetry import read_events

    acc, model, step, _ = _fresh(tmp_path)
    step(BATCH)
    out = acc.save_state()
    (Path(out) / "rng_state_0.pkl").unlink()

    acc2, model2, step2, _ = _fresh(tmp_path)
    tel = acc2.telemetry
    acc2.load_state(out)  # explicit dir: bypasses deep-verify discovery
    tel.close()
    events = [e for e in read_events(tel.path) if e["name"] == "ckpt_rng_missing"]
    assert events and events[0]["severity"] == "warning"
    assert events[0]["file"] == "rng_state_0.pkl"
    # params still restore
    assert float(np.asarray(model2.params["a"])) == pytest.approx(float(np.asarray(model.params["a"])))


def test_sampler_count_mismatch_warns(tmp_path):
    """Satellite: restoring onto a different number of prepared
    dataloaders must not silently restore a prefix."""
    from accelerate_tpu.telemetry import read_events

    acc, model, step, loader = _fresh(tmp_path, with_loader=True)
    step(BATCH)
    out = acc.save_state()

    acc2, model2, step2, _ = _fresh(tmp_path)  # no loader prepared
    tel = acc2.telemetry
    acc2.load_state(out)
    tel.close()
    events = [e for e in read_events(tel.path) if e["name"] == "ckpt_sampler_mismatch"]
    assert events and events[0]["severity"] == "error"
    assert events[0]["saved"] == 1 and events[0]["prepared"] == 0


# --------------------------------------------------------------------------- #
# preemption agreement (one-rank SIGTERM -> all ranks checkpoint)
# --------------------------------------------------------------------------- #

def test_agree_preempt_max_single_process():
    from accelerate_tpu.parallel.collectives import agree_preempt_max

    assert agree_preempt_max(0) == 0
    assert agree_preempt_max(1) == 1


def test_preemption_agreement_flips_unsignalled_rank(tmp_path, monkeypatch):
    """A SIGTERM delivered to only SOME hosts: the agreement max-reduce
    must flip should_checkpoint/should_stop on a rank that never saw the
    signal, and its final save must demote async to sync."""
    from accelerate_tpu.parallel import collectives

    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True),
        kwargs_handlers=[FaultToleranceKwargs(preemption_signals=("SIGTERM",))],
    )
    try:
        acc.prepare_model(RegressionModel())
        acc.prepare_optimizer(optax.sgd(0.1))
        step = acc.build_train_step(linear_loss_fn)
        step(BATCH)
        # pretend to be one host of two; the OTHER host got the SIGTERM
        acc.state.partial_state.num_processes_host = 2
        calls = []

        def fake_agree(value):
            calls.append(value)
            return 1  # some rank's flag is up

        monkeypatch.setattr(collectives, "agree_preempt_max", fake_agree)
        assert acc.should_checkpoint and acc.should_stop
        assert calls == [0], "agreement must run exactly once, with the LOCAL (unsignalled) flag"
        assert acc.preemption_handler.received == "REMOTE"
        n_calls = len(calls)
        assert acc.should_stop  # latched: no further collectives
        assert len(calls) == n_calls

        out = acc.save_state(async_save=True)  # demoted to sync under agreed preemption
        from accelerate_tpu import checkpointing

        assert checkpointing._PENDING_ASYNC == []
        assert CheckpointManager(tmp_path / "checkpoints").verify(out).ok
        assert not acc.should_checkpoint and acc.should_stop
    finally:
        if acc.preemption_handler is not None:
            acc.preemption_handler.uninstall()


def test_preemption_agreement_false_when_no_rank_signalled(tmp_path, monkeypatch):
    from accelerate_tpu.parallel import collectives

    _reset()
    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path), automatic_checkpoint_naming=True),
        kwargs_handlers=[FaultToleranceKwargs(preemption_signals=("SIGTERM",))],
    )
    try:
        acc.state.partial_state.num_processes_host = 2
        monkeypatch.setattr(collectives, "agree_preempt_max", lambda v: v)
        assert not acc.should_checkpoint and not acc.should_stop
        assert acc.preemption_handler.received is None
    finally:
        if acc.preemption_handler is not None:
            acc.preemption_handler.uninstall()
