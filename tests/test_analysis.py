"""The TPU correctness linter (``accelerate_tpu.analysis``): one
deliberately-broken fixture per rule, asserting rule ID, severity, and
suppression behaviour — plus the negative (clean-code) paths that keep the
linter quiet, and the self-lint guarantee that the repo's own tree is
error-free."""

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.analysis import (
    ERROR,
    RULES,
    WARNING,
    Finding,
    LintConfig,
    exit_code,
    lint_paths,
    lint_source,
    lint_step,
    render_json,
    render_text,
    run_selfcheck,
)

# --------------------------------------------------------------------- #
# tier 1 — jaxpr rules against the 8-device fake mesh
# --------------------------------------------------------------------- #


def _rules(findings):
    return [f.rule for f in findings]


def test_tpu101_wrong_collective_axis(mesh8):
    def step(x):
        return jax.lax.psum(x, "model")  # mesh8 has no 'model' axis

    findings = lint_step(step, jax.ShapeDtypeStruct((8, 16), jnp.float32), mesh=mesh8)
    assert _rules(findings) == ["TPU101"]
    assert findings[0].severity == ERROR
    assert "'model'" in findings[0].message


def test_tpu101_valid_axis_is_clean(mesh8):
    def step(x):
        return jax.lax.psum(x, "data")  # bound via the replicated shard_map retrace

    findings = lint_step(step, jax.ShapeDtypeStruct((8, 16), jnp.float32), mesh=mesh8)
    assert "TPU101" not in _rules(findings)


def test_tpu102_silent_promotion_detected(mesh8):
    def step(x):
        return (x.astype(jnp.float32) * 2.0).sum()  # widened value escapes

    findings = lint_step(step, jax.ShapeDtypeStruct((8, 16), jnp.bfloat16), mesh=mesh8)
    assert "TPU102" in _rules(findings)
    f = next(f for f in findings if f.rule == "TPU102")
    assert f.severity == WARNING
    assert "bfloat16" in f.message and "float32" in f.message


def test_tpu102_transient_accumulation_is_clean(mesh8):
    # jnp reductions widen bf16 for accumulation and immediately narrow
    # back — that f32 region never escapes and must not be flagged
    def step(x):
        return jnp.mean(x) + jnp.sum(x)

    findings = lint_step(step, jax.ShapeDtypeStruct((8, 16), jnp.bfloat16), mesh=mesh8)
    assert "TPU102" not in _rules(findings)


def test_tpu103_missed_donation_and_donated(mesh8):
    params = {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)}  # 16 KiB
    batch = jax.ShapeDtypeStruct((8, 16), jnp.float32)

    def step(p, b):
        new = jax.tree_util.tree_map(lambda x: x - 0.1, p)
        return new, b.sum()

    findings = lint_step(step, params, batch, mesh=mesh8)
    assert _rules(findings) == ["TPU103"]
    assert findings[0].severity == WARNING
    assert "donate_argnums=(0,)" in findings[0].message

    assert lint_step(step, params, batch, mesh=mesh8, donate_argnums=(0,)) == []


def test_tpu103_small_buffers_not_advised(mesh8):
    small = {"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)}  # 64 B < floor

    def step(p):
        return jax.tree_util.tree_map(lambda x: x + 1.0, p)

    assert lint_step(step, small, mesh=mesh8) == []


def test_tpu104_unconstrained_output_sharding(mesh8):
    sharded = jax.device_put(np.zeros((64, 16), np.float32), NamedSharding(mesh8, P("data")))

    def step(x):
        return (x * 2.0).sum(axis=-1)

    findings = lint_step(step, sharded, mesh=mesh8)
    assert "TPU104" in _rules(findings)
    assert "'data'" in next(f for f in findings if f.rule == "TPU104").message

    def constrained(x):
        return jax.lax.with_sharding_constraint(x * 2.0, NamedSharding(mesh8, P("data")))

    assert "TPU104" not in _rules(lint_step(constrained, sharded, mesh=mesh8))


def test_tpu104_via_in_shardings_specs(mesh8):
    # declared (not concrete) input shardings feed the same check
    x = jax.ShapeDtypeStruct((64, 16), jnp.float32)

    def step(x):
        return x * 2.0

    findings = lint_step(step, x, mesh=mesh8, in_shardings=(P("data"),))
    assert "TPU104" in _rules(findings)


def test_lint_step_requires_mesh():
    with pytest.raises(ValueError, match="mesh"):
        lint_step(lambda x: x, jnp.ones(4))


def test_ignore_filters_rules(mesh8):
    params = {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)}

    def step(p):
        return jax.tree_util.tree_map(lambda x: x + 1.0, p)

    assert lint_step(step, params, mesh=mesh8, ignore=("TPU103",)) == []


def test_accelerator_lint_hook():
    from accelerate_tpu import Accelerator

    acc = Accelerator()

    def step(params, batch):
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        return new, jax.lax.psum(batch.sum(), "bogus")

    findings = acc.lint(
        step,
        {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        jax.ShapeDtypeStruct((8, 16), jnp.float32),
    )
    assert _rules(findings) == ["TPU101"]


# --------------------------------------------------------------------- #
# tier 2 — AST rules on source fixtures
# --------------------------------------------------------------------- #

_HOST_CALL_SRC = textwrap.dedent(
    '''
    """Fixture."""
    import jax


    @jax.jit
    def step(x):
        host = jax.device_get(x)
        return float(x) + host.item()
    '''
)


def test_tpu201_host_calls_in_jit():
    findings = lint_source(_HOST_CALL_SRC, path="fix.py", config=LintConfig(select=frozenset({"TPU201"})))
    assert _rules(findings) == ["TPU201", "TPU201", "TPU201"]  # device_get, float(x), .item()
    assert all(f.severity == ERROR for f in findings)
    assert findings[0].line == 8  # jax.device_get line


def test_tpu201_not_flagged_outside_jit():
    src = '"""Fixture."""\nimport jax\n\n\ndef step(x):\n    return jax.device_get(x)\n'
    assert lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU201"}))) == []


def test_tpu201_float_of_constant_ok():
    src = textwrap.dedent(
        '''
        """Fixture."""
        import jax


        @jax.jit
        def step(x):
            return x * float("-inf")
        '''
    )
    assert lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU201"}))) == []


def test_tpu202_tracer_branch():
    src = textwrap.dedent(
        '''
        """Fixture."""
        import jax


        @jax.jit
        def step(x):
            if x > 0:
                return x
            return -x
        '''
    )
    findings = lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU202"})))
    assert _rules(findings) == ["TPU202"]
    assert findings[0].severity == WARNING
    assert "'step'" in findings[0].message


def test_tpu202_static_and_none_checks_are_clean():
    src = textwrap.dedent(
        '''
        """Fixture: all trace-static branch tests."""
        import functools

        import jax


        @functools.partial(jax.jit, static_argnames=("causal",))
        def step(x, mask=None, causal=False):
            if causal:              # static arg
                x = x + 1
            if mask is None:        # None check
                x = x * 2
            if x.ndim == 3:         # static attribute
                x = x.sum(0)
            if len(x) > 1:          # static len()
                x = x + 0
            return x
        '''
    )
    assert lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU202"}))) == []


def test_tpu203_unhashable_static_default():
    src = textwrap.dedent(
        '''
        """Fixture."""
        import functools

        import jax


        @functools.partial(jax.jit, static_argnums=(1,))
        def step(x, layers=[64, 64]):
            return x
        '''
    )
    findings = lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU203"})))
    assert _rules(findings) == ["TPU203"]
    assert findings[0].severity == ERROR
    assert "'layers'" in findings[0].message


def test_tpu203_hashable_static_default_ok():
    src = textwrap.dedent(
        '''
        """Fixture."""
        import functools

        import jax


        @functools.partial(jax.jit, static_argnames=("block",))
        def step(x, block=(64, 64)):
            return x
        '''
    )
    assert lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU203"}))) == []


def test_tpu204_eager_jax_import_zones():
    src = '"""Fixture."""\nimport jax\n\nV = str(jax.__version__)\n'
    always = LintConfig(select=frozenset({"TPU204"}), lazy_jax="always")
    never = LintConfig(select=frozenset({"TPU204"}), lazy_jax="never")
    auto = LintConfig(select=frozenset({"TPU204"}), lazy_jax="auto")

    assert _rules(lint_source(src, path="pkg/mod.py", config=always)) == ["TPU204"]
    assert lint_source(src, path="pkg/mod.py", config=never) == []
    # auto: the convention zone is the orchestration layer only
    assert _rules(lint_source(src, path="accelerate_tpu/foo.py", config=auto)) == ["TPU204"]
    assert _rules(lint_source(src, path="accelerate_tpu/commands/foo.py", config=auto)) == ["TPU204"]
    assert lint_source(src, path="accelerate_tpu/ops/foo.py", config=auto) == []
    assert lint_source(src, path="somewhere/else.py", config=auto) == []


def test_tpu001_unused_import_and_init_exemption():
    src = '"""Fixture."""\nimport os\n\nV = 1\n'
    findings = lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU001"})))
    assert _rules(findings) == ["TPU001"]
    assert findings[0].line == 2
    # __init__.py re-exports are exempt
    assert lint_source(src, path="pkg/__init__.py", config=LintConfig(select=frozenset({"TPU001"}))) == []


def test_tpu002_missing_docstring():
    findings = lint_source("V = 1\n", path="fix.py", config=LintConfig(select=frozenset({"TPU002"})))
    assert _rules(findings) == ["TPU002"]
    assert lint_source('"""Doc."""\nV = 1\n', path="fix.py", config=LintConfig(select=frozenset({"TPU002"}))) == []


# --------------------------------------------------------------------- #
# suppressions, reporters, registry
# --------------------------------------------------------------------- #


def test_inline_suppression_by_id_and_bare():
    by_id = _HOST_CALL_SRC.replace(
        "host = jax.device_get(x)", "host = jax.device_get(x)  # tpu-lint: disable=TPU201"
    )
    findings = lint_source(by_id, path="fix.py", config=LintConfig(select=frozenset({"TPU201"})))
    assert all(f.line != 8 for f in findings)  # that line is silenced, others remain
    assert len(findings) == 2

    bare = by_id.replace(
        "return float(x) + host.item()", "return float(x) + host.item()  # tpu-lint: disable"
    )
    assert lint_source(bare, path="fix.py", config=LintConfig(select=frozenset({"TPU201"}))) == []


def test_suppression_of_other_rule_does_not_silence():
    src = _HOST_CALL_SRC.replace(
        "host = jax.device_get(x)", "host = jax.device_get(x)  # tpu-lint: disable=TPU999X"
    )
    # unknown/other IDs in the comment leave the finding in place
    findings = lint_source(src, path="fix.py", config=LintConfig(select=frozenset({"TPU201"})))
    assert len(findings) == 3


def test_render_text_format_is_parseable():
    f = Finding("TPU201", "host sync", path="a/b.py", line=12)
    line = render_text([f], summary=False)
    assert line == "a/b.py:12: TPU201 host sync"


def test_render_json_round_trip():
    findings = lint_source(_HOST_CALL_SRC, path="fix.py", config=LintConfig(select=frozenset({"TPU201"})))
    payload = json.loads(render_json(findings))
    assert len(payload) == 3
    assert payload[0]["rule"] == "TPU201"
    assert payload[0]["severity"] == "error"
    assert payload[0]["name"] == "host-call-in-jit"
    assert payload[0]["path"] == "fix.py"


def test_exit_code_contract():
    err = Finding("TPU201", "x")
    warn = Finding("TPU202", "x")
    assert exit_code([]) == 0
    assert exit_code([warn]) == 0
    assert exit_code([warn], strict=True) == 1
    assert exit_code([err, warn]) == 1


def test_registry_ids_are_stable():
    assert set(RULES) == {
        "TPU001", "TPU002", "TPU003",
        "TPU101", "TPU102", "TPU103", "TPU104",
        "TPU201", "TPU202", "TPU203", "TPU204",
        "TPU301", "TPU302", "TPU303",
        "TPU401", "TPU402", "TPU403", "TPU404", "TPU405",
        "TPU501", "TPU502", "TPU503", "TPU504", "TPU505",
        "TPU601", "TPU602", "TPU603", "TPU604", "TPU605", "TPU606",
        "TPU701", "TPU702", "TPU703", "TPU704", "TPU705",
        "TPU801", "TPU802", "TPU803", "TPU804", "TPU805",
        "TPU901", "TPU902", "TPU903", "TPU904", "TPU905",
        "TPU1001", "TPU1002", "TPU1003", "TPU1004", "TPU1005", "TPU1006",
    }
    with pytest.raises(ValueError):
        Finding("TPU999", "no such rule")


def test_render_sarif_shape():
    from accelerate_tpu.analysis import render_sarif

    findings = [
        Finding("TPU201", "host sync", path="a/b.py", line=12),
        Finding("TPU301", "deadlocky collective"),  # jaxpr tier: no location
    ]
    doc = json.loads(render_sarif(findings))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "accelerate-tpu-lint"
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rules == {"TPU201", "TPU301"}
    results = run["results"]
    assert results[0]["ruleId"] == "TPU201" and results[0]["level"] == "error"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "a/b.py"
    assert loc["region"]["startLine"] == 12
    # location-less finding anchors to the synthetic artifact
    assert results[1]["locations"][0]["physicalLocation"]["artifactLocation"]["uri"] == "<jaxpr>"
    # ruleIndex round-trips into the rules array
    for res in results:
        assert run["tool"]["driver"]["rules"][res["ruleIndex"]]["id"] == res["ruleId"]


def test_render_sarif_empty():
    from accelerate_tpu.analysis import render_sarif

    doc = json.loads(render_sarif([]))
    assert doc["runs"][0]["results"] == []
    assert doc["runs"][0]["tool"]["driver"]["rules"] == []


# --------------------------------------------------------------------- #
# the repo itself must stay lint-clean; the selfcheck must stay green
# --------------------------------------------------------------------- #


def test_repo_tree_is_lint_clean():
    import pathlib

    pkg = pathlib.Path(__file__).parent.parent / "accelerate_tpu"
    errors = [f for f in lint_paths([pkg]) if f.is_error]
    assert errors == [], "\n".join(render_text(errors, summary=False).splitlines())


def test_selfcheck_all_rules_fire(mesh8):
    ok, lines = run_selfcheck(mesh8)
    assert ok, "\n".join(lines)
    assert sum("detected" in line for line in lines) == 50  # 6 AST + 4 jaxpr + 3 flight + 5 divergence + 5 perf + 6 numerics + 5 config + 5 pipe + 5 fleet + 6 kernel
    assert any("clean idiomatic script: zero findings" in line for line in lines)
