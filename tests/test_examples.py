"""Run every by_feature example end-to-end on the CPU fake mesh
(reference analogue: tests/test_examples.py, 308 LoC)."""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).parent.parent / "examples" / "by_feature"
EXAMPLES = sorted(p.name for p in EXAMPLES_DIR.glob("*.py") if not p.name.startswith("_"))

ENV = {
    **os.environ,
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
}


@pytest.mark.parametrize("example", EXAMPLES)
def test_example_runs(example):
    result = subprocess.run(
        [sys.executable, example],
        cwd=EXAMPLES_DIR,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert result.returncode == 0, f"{example} failed:\n{result.stdout}\n{result.stderr}"


def test_all_examples_discovered():
    # guard against the glob silently matching nothing
    assert len(EXAMPLES) >= 8, EXAMPLES
