"""Run every by_feature example end-to-end on the CPU fake mesh
(reference analogue: tests/test_examples.py, 308 LoC).

The whole module is the ``slow`` tier: every test is a fresh subprocess
(own jax init + compiles). Run with ``pytest -m slow`` / ``make test-all``.
"""

import os
import pathlib
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

EXAMPLES_DIR = pathlib.Path(__file__).parent.parent / "examples" / "by_feature"
EXAMPLES = sorted(p.name for p in EXAMPLES_DIR.glob("*.py") if not p.name.startswith("_"))

REPO_ROOT = str(pathlib.Path(__file__).parent.parent)

ENV = {
    **os.environ,
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
    # examples run from examples/by_feature; the package lives at the repo
    # root, which is not on sys.path for a subprocess
    "PYTHONPATH": os.pathsep.join(p for p in (REPO_ROOT, os.environ.get("PYTHONPATH", "")) if p),
}


@pytest.mark.parametrize("example", EXAMPLES)
def test_example_runs(example):
    result = subprocess.run(
        [sys.executable, example],
        cwd=EXAMPLES_DIR,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert result.returncode == 0, f"{example} failed:\n{result.stdout}\n{result.stderr}"


def test_all_examples_discovered():
    # guard against the glob silently matching nothing
    assert len(EXAMPLES) >= 8, EXAMPLES


@pytest.mark.parametrize("example", ["nlp_example.py", "cv_example.py"])
def test_root_example_runs_tiny(example):
    """The two canonical examples (reference: examples/nlp_example.py,
    examples/cv_example.py) in CI size."""
    result = subprocess.run(
        [sys.executable, example, "--tiny", "--num_epochs", "1"],
        cwd=EXAMPLES_DIR.parent,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert result.returncode == 0, f"{example} failed:\n{result.stdout}\n{result.stderr}"


@pytest.mark.parametrize("example", ["complete_nlp_example.py", "complete_cv_example.py"])
def test_complete_example_checkpoint_and_resume(example, tmp_path):
    """Kitchen-sink examples (reference: examples/complete_*_example.py):
    train with tracking + epoch checkpointing, then resume from the epoch-0
    checkpoint and finish."""
    out = tmp_path / "out"
    common = ["--tiny", "--num_epochs", "2", "--with_tracking", "--output_dir", str(out)]
    run = subprocess.run(
        [sys.executable, example, *common, "--checkpointing_steps", "epoch"],
        cwd=EXAMPLES_DIR.parent,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert run.returncode == 0, f"{example} failed:\n{run.stdout}\n{run.stderr}"
    assert (out / "epoch_0").is_dir() and (out / "final").is_dir()

    resume = subprocess.run(
        [sys.executable, example, *common, "--resume_from_checkpoint", str(out / "epoch_0")],
        cwd=EXAMPLES_DIR.parent,
        env=ENV,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert resume.returncode == 0, f"{example} resume failed:\n{resume.stdout}\n{resume.stderr}"
    assert "resumed from" in resume.stdout
