"""Gemma2 family (models/gemma2.py): sandwich norms + softcaps +
alternating local/global attention through decode and serving. HF importer
parity lives in test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Gemma2Config, create_gemma2_model


@pytest.fixture(scope="module")
def tiny_gemma2():
    return create_gemma2_model(Gemma2Config.tiny(), seq_len=32)


def test_structure(tiny_gemma2):
    cfg = Gemma2Config.tiny()
    assert cfg.layer_types == ("sliding_attention", "full_attention")
    layer0 = tiny_gemma2.params["layer_0"]
    for norm in ("input_norm", "post_attn_norm", "pre_ffn_norm", "post_ffn_norm"):
        assert norm in layer0, norm  # the sandwich
    assert "lm_head" not in tiny_gemma2.params  # always tied


def test_greedy_decode_matches_full_prefix(tiny_gemma2):
    """The cached decode path must apply the softcaps, the
    query_pre_attn_scalar scale, AND the per-layer window exactly like the
    full forward — token equality over enough steps to cross the window."""
    ids = (np.arange(2 * 12).reshape(2, 12) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_gemma2, ids, max_new_tokens=8))
    full = ids
    for _ in range(8):
        logits = np.asarray(tiny_gemma2(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_final_softcap_bounds_logits(tiny_gemma2):
    ids = np.ones((1, 8), np.int32)
    logits = np.asarray(tiny_gemma2(ids))
    assert np.abs(logits).max() <= 30.0 + 1e-5  # final_logit_softcap


def test_serving(tiny_gemma2):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 12, 6)]
    eng = ServingEngine(tiny_gemma2, num_slots=2, prompt_buckets=(4, 8, 16))
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_gemma2, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)


def test_paged_serving_raises(tiny_gemma2):
    """The paged kernel has no tanh-cap branch: refuse loudly rather than
    silently dropping the softcap."""
    from accelerate_tpu.serving import ServingEngine

    with pytest.raises(NotImplementedError, match="softcapping"):
        eng = ServingEngine(tiny_gemma2, num_slots=1, prompt_buckets=(8,), paged_block_size=4)
        eng.generate_many([np.ones((4,), np.int32)], max_new_tokens=3)
