"""Pallas flash-attention kernel vs the XLA reference implementation.

Runs the TPU kernel in Pallas interpreter mode on CPU (shapes kept small —
interpret mode executes block-by-block in Python). Checks forward and all
three input gradients for: non-causal, causal, GQA, unpadded-odd sequence
lengths, and the decode case Sq < Sk (bottom-right causal alignment)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.utils.compat import shard_map
from accelerate_tpu.ops.pallas_attention import pallas_flash_attention


def _make_qkv(rng, b, sq, sk, h, h_kv, d, dtype=jnp.float32):
    keys = jax.random.split(rng, 3)
    q = jax.random.normal(keys[0], (b, sq, h, d), dtype)
    k = jax.random.normal(keys[1], (b, sk, h_kv, d), dtype)
    v = jax.random.normal(keys[2], (b, sk, h_kv, d), dtype)
    return q, k, v


def _ref(q, k, v, causal):
    return dot_product_attention(q, k, v, causal=causal, use_flash=False)


def _kernel(q, k, v, causal):
    return pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64, interpret=True)


CASES = [
    # b, sq, sk, h, h_kv, d, causal
    pytest.param(2, 128, 128, 2, 2, 32, False, id="mha-noncausal"),
    pytest.param(2, 128, 128, 2, 2, 32, True, id="mha-causal"),
    pytest.param(1, 128, 128, 4, 2, 32, True, id="gqa-causal"),
    pytest.param(1, 100, 100, 2, 1, 32, True, id="odd-seq-padded"),
    pytest.param(1, 64, 192, 2, 2, 32, True, id="decode-sq-lt-sk"),
]


@pytest.mark.parametrize("b,sq,sk,h,h_kv,d,causal", CASES)
def test_forward_matches_reference(b, sq, sk, h, h_kv, d, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(0), b, sq, sk, h, h_kv, d)
    out = _kernel(q, k, v, causal)
    expected = _ref(q, k, v, causal)
    assert out.shape == expected.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,sq,sk,h,h_kv,d,causal",
    [
        pytest.param(1, 128, 128, 2, 2, 32, True, id="mha-causal"),
        pytest.param(1, 128, 128, 4, 2, 32, True, id="gqa-causal"),
        pytest.param(1, 100, 100, 2, 2, 32, False, id="odd-seq-noncausal"),
    ],
)
def test_gradients_match_reference(b, sq, sk, h, h_kv, d, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(1), b, sq, sk, h, h_kv, d)

    def loss_kernel(q, k, v):
        return (_kernel(q, k, v, causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (_ref(q, k, v, causal) ** 2).sum()

    grads = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(grads, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=2e-3, rtol=2e-3, err_msg=f"d{name} mismatch"
        )


def _ref_banded(q, k, v, window):
    """Banded reference: causal + Mistral band via the XLA mask path."""
    sq, sk = q.shape[1], k.shape[1]
    q_pos = jnp.arange(sq)[:, None] + (sk - sq)
    band = (jnp.arange(sk)[None, :] > q_pos - window)[None, None]
    return dot_product_attention(q, k, v, mask=band, causal=True, use_flash=False)


@pytest.mark.parametrize(
    "b,s,h,h_kv,d,window",
    [
        pytest.param(2, 128, 2, 2, 32, 40, id="mha-band"),
        pytest.param(1, 128, 4, 2, 32, 64, id="gqa-band-blockmult"),
        pytest.param(1, 100, 2, 2, 32, 17, id="odd-seq-odd-band"),
        pytest.param(1, 128, 2, 2, 32, 500, id="band-wider-than-seq"),
        pytest.param(1, 128, 2, 2, 32, 1, id="self-only-band"),
    ],
)
def test_banded_forward_matches_reference(b, s, h, h_kv, d, window):
    q, k, v = _make_qkv(jax.random.PRNGKey(5), b, s, s, h, h_kv, d)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True, window=window)
    want = _ref_banded(q, k, v, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_banded_decode_alignment_sq_lt_sk():
    """Band + bottom-right alignment (chunked prefill / decode shapes):
    the `sk - sq` offset threads through the band mask, the block skip,
    and the XLA fold identically."""
    q, k, v = _make_qkv(jax.random.PRNGKey(8), 1, 32, 128, 2, 2, 32)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True, window=40)
    want = _ref_banded(q, k, v, 40)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_banded_gradients_match_reference():
    b, s, h, h_kv, d, window = 1, 128, 4, 2, 32, 40
    q, k, v = _make_qkv(jax.random.PRNGKey(6), b, s, s, h, h_kv, d)

    def loss_kernel(q, k, v):
        out = pallas_flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, interpret=True, window=window
        )
        return (out**2).sum()

    def loss_ref(q, k, v):
        return (_ref_banded(q, k, v, window) ** 2).sum()

    grads = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(grads, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=2e-3, rtol=2e-3, err_msg=f"d{name} mismatch"
        )


def test_banded_requires_causal():
    q, k, v = _make_qkv(jax.random.PRNGKey(7), 1, 64, 64, 2, 2, 32)
    with pytest.raises(ValueError, match="causal"):
        pallas_flash_attention(q, k, v, causal=False, interpret=True, window=8)
    from accelerate_tpu.ops.attention import dot_product_attention as dpa

    with pytest.raises(ValueError, match="causal"):
        dpa(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match=">= 1"):
        dpa(q, k, v, causal=True, window=0)
    with pytest.raises(ValueError, match=">= 1"):
        pallas_flash_attention(q, k, v, causal=True, interpret=True, window=0)
    # explicit flash + band off-TPU must refuse, not silently go quadratic
    with pytest.raises(ValueError, match="TPU"):
        dpa(q, k, v, causal=True, window=8, use_flash=True)


def test_jit_and_scan_fallback_agree():
    """The jitted Pallas path and the lax.scan fallback agree bitwise-ish."""
    from accelerate_tpu.ops.flash_attention import flash_attention as scan_flash

    q, k, v = _make_qkv(jax.random.PRNGKey(2), 1, 128, 128, 2, 2, 32)
    fn = jax.jit(functools.partial(_kernel, causal=True))
    out = fn(q, k, v)
    out_scan = scan_flash(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_scan), atol=2e-5, rtol=2e-5)


def test_sharded_dispatch_stays_partitioned():
    """sharded_pallas_attention must run the kernel per-shard under
    shard_map: no all-gather in the HLO, output sharding preserved
    (regression: bare pallas_call is opaque to GSPMD and forced a
    mesh-wide all-gather + replicated output)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import MeshConfig
    from accelerate_tpu.ops.attention import sharded_pallas_attention

    mesh = MeshConfig(data=2, tensor=4).build()
    q, k, v = _make_qkv(jax.random.PRNGKey(3), 2, 128, 128, 8, 4, 32)
    shard = NamedSharding(mesh, P("data", None, "tensor", None))
    args = tuple(jax.device_put(x, shard) for x in (q, k, v))

    fn = jax.jit(
        functools.partial(sharded_pallas_attention, causal=True, mesh=mesh, interpret=True)
    )
    hlo = fn.lower(*args).compile().as_text()
    assert "all-gather" not in hlo, "sharded pallas dispatch must not all-gather q/k/v"
    out = fn(*args)
    assert out.sharding.spec == P("data", None, "tensor", None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-3, rtol=2e-3)


def test_sharded_dispatch_falls_back_without_mesh():
    from accelerate_tpu.ops.attention import sharded_pallas_attention

    q, k, v = _make_qkv(jax.random.PRNGKey(4), 1, 128, 128, 2, 2, 32)
    out = sharded_pallas_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-3, rtol=2e-3)


def test_sharded_dispatch_inside_shard_map():
    """Calling the sharded dispatch from within an existing shard_map region
    (e.g. the GPipe trunk) must use the bare kernel on the local block, not
    nest another shard_map (regression: nested shard_map over the same mesh
    raises a context-mesh mismatch at trace time)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import MeshConfig
    from accelerate_tpu.ops.attention import sharded_pallas_attention

    mesh = MeshConfig(data=8).build()
    q, k, v = _make_qkv(jax.random.PRNGKey(5), 8, 128, 128, 2, 2, 32)

    def local(q, k, v):
        return sharded_pallas_attention(q, k, v, causal=True, mesh=mesh, interpret=True)

    spec = P("data")
    fn = jax.jit(
        shard_map(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    )
    shard = NamedSharding(mesh, spec)
    out = fn(*(jax.device_put(x, shard) for x in (q, k, v)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-3, rtol=2e-3)
