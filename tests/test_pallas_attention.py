"""Pallas flash-attention kernel vs the XLA reference implementation.

Runs the TPU kernel in Pallas interpreter mode on CPU (shapes kept small —
interpret mode executes block-by-block in Python). Checks forward and all
three input gradients for: non-causal, causal, GQA, unpadded-odd sequence
lengths, and the decode case Sq < Sk (bottom-right causal alignment)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.ops.pallas_attention import pallas_flash_attention


def _make_qkv(rng, b, sq, sk, h, h_kv, d, dtype=jnp.float32):
    keys = jax.random.split(rng, 3)
    q = jax.random.normal(keys[0], (b, sq, h, d), dtype)
    k = jax.random.normal(keys[1], (b, sk, h_kv, d), dtype)
    v = jax.random.normal(keys[2], (b, sk, h_kv, d), dtype)
    return q, k, v


def _ref(q, k, v, causal):
    return dot_product_attention(q, k, v, causal=causal, use_flash=False)


def _kernel(q, k, v, causal):
    return pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64, interpret=True)


CASES = [
    # b, sq, sk, h, h_kv, d, causal
    pytest.param(2, 128, 128, 2, 2, 32, False, id="mha-noncausal"),
    pytest.param(2, 128, 128, 2, 2, 32, True, id="mha-causal"),
    pytest.param(1, 128, 128, 4, 2, 32, True, id="gqa-causal"),
    pytest.param(1, 100, 100, 2, 1, 32, True, id="odd-seq-padded"),
    pytest.param(1, 64, 192, 2, 2, 32, True, id="decode-sq-lt-sk"),
]


@pytest.mark.parametrize("b,sq,sk,h,h_kv,d,causal", CASES)
def test_forward_matches_reference(b, sq, sk, h, h_kv, d, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(0), b, sq, sk, h, h_kv, d)
    out = _kernel(q, k, v, causal)
    expected = _ref(q, k, v, causal)
    assert out.shape == expected.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,sq,sk,h,h_kv,d,causal",
    [
        pytest.param(1, 128, 128, 2, 2, 32, True, id="mha-causal"),
        pytest.param(1, 128, 128, 4, 2, 32, True, id="gqa-causal"),
        pytest.param(1, 100, 100, 2, 2, 32, False, id="odd-seq-noncausal"),
    ],
)
def test_gradients_match_reference(b, sq, sk, h, h_kv, d, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(1), b, sq, sk, h, h_kv, d)

    def loss_kernel(q, k, v):
        return (_kernel(q, k, v, causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (_ref(q, k, v, causal) ** 2).sum()

    grads = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(grads, expected, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), atol=2e-3, rtol=2e-3, err_msg=f"d{name} mismatch"
        )


def test_jit_and_scan_fallback_agree():
    """The jitted Pallas path and the lax.scan fallback agree bitwise-ish."""
    from accelerate_tpu.ops.flash_attention import flash_attention as scan_flash

    q, k, v = _make_qkv(jax.random.PRNGKey(2), 1, 128, 128, 2, 2, 32)
    fn = jax.jit(functools.partial(_kernel, causal=True))
    out = fn(q, k, v)
    out_scan = scan_flash(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_scan), atol=2e-5, rtol=2e-5)
