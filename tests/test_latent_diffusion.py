"""Latent diffusion: VAE, text-conditioned UNet, and the text→image
pipeline (reference analogue:
examples/inference/distributed/stable_diffusion.py — the diffusers
latent-diffusion pipeline the reference drives; VAE/cross-attention/
pipeline are in-tree here: models/vae.py, models/unet.py AttnBlock,
diffusion.py text_to_image)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.diffusion import latent_diffusion_loss, make_schedule, sample, text_to_image
from accelerate_tpu.models.clip import CLIPConfig, create_clip_model
from accelerate_tpu.models.unet import UNetConfig, create_unet_model
from accelerate_tpu.models.vae import VAEConfig, create_vae_model, vae_loss


@pytest.fixture(scope="module")
def vae():
    return create_vae_model(VAEConfig.tiny(), seed=0)


@pytest.fixture(scope="module")
def clip():
    return create_clip_model(CLIPConfig.tiny(), seed=0)


@pytest.fixture(scope="module")
def latent_unet(vae, clip):
    vcfg = vae.config
    return create_unet_model(
        UNetConfig.tiny(
            sample_size=vcfg.latent_size,
            in_channels=vcfg.latent_channels,
            out_channels=vcfg.latent_channels,
            context_dim=clip.config.text_hidden_size,
        ),
        seed=0,
    )


def test_vae_shapes_and_roundtrip(vae):
    cfg = vae.config
    x = jax.random.normal(jax.random.key(0), (2, cfg.sample_size, cfg.sample_size, 3))
    z, mean, logvar = vae.encode_fn(vae.params, x, jax.random.key(1))
    assert z.shape == (2, cfg.latent_size, cfg.latent_size, cfg.latent_channels)
    assert mean.shape == z.shape and logvar.shape == z.shape
    # deterministic encode (no rng) returns the scaled mean
    z_det, mean2, _ = vae.encode_fn(vae.params, x)
    np.testing.assert_allclose(np.asarray(z_det), np.asarray(mean2) * cfg.scaling_factor, rtol=1e-6)
    img = vae.decode_fn(vae.params, z)
    assert img.shape == x.shape and np.isfinite(np.asarray(img)).all()


def test_vae_training_decreases_loss(vae):
    x = jax.random.normal(jax.random.key(0), (4, 16, 16, 3)) * 0.5
    batch = {"pixel_values": x}
    opt = optax.adam(1e-3)
    params = vae.params
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, key):
        loss, grads = jax.value_and_grad(
            lambda p: vae_loss(p, batch, vae.apply_fn, key, config=vae.config)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for i in range(6):
        params, opt_state, loss = step(params, opt_state, jax.random.key(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_text_conditional_unet_uses_context(latent_unet, clip):
    """Different text conditioning must change the predicted noise."""
    cfg = latent_unet.config
    x = jax.random.normal(jax.random.key(0), (2, cfg.sample_size, cfg.sample_size, cfg.in_channels))
    t = jnp.array([5, 9], jnp.int32)
    ids_a = jnp.full((2, 8), 3, jnp.int32)
    ids_b = jnp.full((2, 8), 7, jnp.int32)
    ctx_a = clip.encode_text(clip.params, ids_a)
    ctx_b = clip.encode_text(clip.params, ids_b)
    assert ctx_a.shape == (2, 8, clip.config.text_hidden_size)
    out_a = latent_unet.apply_fn(latent_unet.params, x, t, encoder_hidden_states=ctx_a)
    out_b = latent_unet.apply_fn(latent_unet.params, x, t, encoder_hidden_states=ctx_b)
    assert out_a.shape == x.shape
    assert not np.allclose(np.asarray(out_a), np.asarray(out_b))
    with pytest.raises(ValueError, match="encoder_hidden_states"):
        latent_unet.apply_fn(latent_unet.params, x, t)


def test_latent_diffusion_train_step(latent_unet, vae, clip):
    sched = make_schedule(64)
    key = jax.random.key(0)
    batch = {
        "pixel_values": jax.random.normal(key, (2, 16, 16, 3)) * 0.5,
        "input_ids": jnp.full((2, 8), 3, jnp.int32),
    }

    def loss_fn(p, rng):
        return latent_diffusion_loss(
            p, batch, latent_unet.apply_fn, sched, rng,
            vae=vae, text_encoder=clip.encode_text, text_params=clip.params,
        )

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(latent_unet.params, jax.random.key(1))
    assert np.isfinite(float(loss))
    gnorm = optax.global_norm(grads)
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0
    # conditioning grads flow into the cross-attention projections
    cross = [
        leaf for kp, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]
        if "cross_k_proj" in str(kp)
    ]
    assert cross and any(float(jnp.abs(g).max()) > 0 for g in cross)


def test_text_to_image_pipeline(latent_unet, vae, clip):
    sched = make_schedule(64)
    prompts = jnp.stack([jnp.full((8,), 3, jnp.int32), jnp.full((8,), 7, jnp.int32)])
    imgs = text_to_image(
        latent_unet, vae, clip, prompts,
        guidance_scale=3.0, num_steps=4, schedule=sched, seed=0,
    )
    assert imgs.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(imgs)).all()
    # seeded determinism (ddim, eta=0)
    imgs2 = text_to_image(
        latent_unet, vae, clip, prompts,
        guidance_scale=3.0, num_steps=4, schedule=sched, seed=0,
    )
    np.testing.assert_array_equal(np.asarray(imgs), np.asarray(imgs2))
    # different prompts give different images
    prompts_b = jnp.stack([jnp.full((8,), 11, jnp.int32), jnp.full((8,), 13, jnp.int32)])
    imgs3 = text_to_image(
        latent_unet, vae, clip, prompts_b,
        guidance_scale=3.0, num_steps=4, schedule=sched, seed=0,
    )
    assert not np.array_equal(np.asarray(imgs), np.asarray(imgs3))


def test_guidance_validation(latent_unet, vae, clip):
    sched = make_schedule(64)
    with pytest.raises(ValueError, match="encoder_hidden_states"):
        sample(latent_unet, 1, num_steps=2, schedule=sched)


def test_single_unbatched_prompt(latent_unet, vae, clip):
    """A 1-D prompt is promoted to a batch of one."""
    sched = make_schedule(64)
    imgs = text_to_image(
        latent_unet, vae, clip, jnp.full((8,), 5, jnp.int32),
        guidance_scale=2.0, num_steps=2, schedule=sched, seed=0,
    )
    assert imgs.shape == (1, 16, 16, 3) and np.isfinite(np.asarray(imgs)).all()
