"""KV-cache generation tests (no reference analogue: the reference
delegates generation to transformers; here the jitted decode loop is
framework surface — generation.py)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.generation import generate, per_token_latency
from accelerate_tpu.models import LlamaConfig, create_llama_model


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(LlamaConfig.tiny(), seq_len=16)


def test_greedy_matches_full_prefix(tiny_llama):
    """Cached incremental decode must produce EXACTLY the tokens of the
    (O(S^2)-per-token) full-prefix argmax loop."""
    model = tiny_llama
    ids = (np.arange(2 * 8).reshape(2, 8) % 256).astype(np.int32)
    out = np.asarray(generate(model, ids, max_new_tokens=5))
    full = ids
    for _ in range(5):
        logits = np.asarray(model(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_generate_shapes_and_dtypes(tiny_llama):
    out = generate(tiny_llama, np.ones((3, 4), np.int32), max_new_tokens=1)
    assert out.shape == (3, 5) and out.dtype == jax.numpy.int32
    out = generate(tiny_llama, np.ones((1, 4), np.int32), max_new_tokens=7)
    assert out.shape == (1, 11)


def test_temperature_sampling_deterministic_per_seed(tiny_llama):
    ids = np.ones((2, 4), np.int32)
    a = np.asarray(generate(tiny_llama, ids, max_new_tokens=6, temperature=1.0, seed=1))
    b = np.asarray(generate(tiny_llama, ids, max_new_tokens=6, temperature=1.0, seed=1))
    c = np.asarray(generate(tiny_llama, ids, max_new_tokens=6, temperature=1.0, seed=2))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # different seed, different samples


def test_top_k_restricts_support(tiny_llama):
    """top_k=1 at any temperature collapses to greedy."""
    ids = np.ones((2, 4), np.int32)
    greedy = np.asarray(generate(tiny_llama, ids, max_new_tokens=4))
    topk1 = np.asarray(generate(tiny_llama, ids, max_new_tokens=4, temperature=5.0, top_k=1, seed=3))
    np.testing.assert_array_equal(greedy, topk1)


def test_eos_padding(tiny_llama):
    """After a sequence emits EOS every later position is EOS."""
    ids = np.ones((1, 4), np.int32)
    greedy = np.asarray(generate(tiny_llama, ids, max_new_tokens=8))
    eos = int(greedy[0, 5])  # force the 2nd generated token to be "EOS"
    out = np.asarray(generate(tiny_llama, ids, max_new_tokens=8, eos_token_id=eos))
    seen = list(out[0, 4:])
    after = seen[seen.index(eos):]
    assert all(t == eos for t in after), seen


def test_per_token_latency_runs(tiny_llama):
    dt = per_token_latency(tiny_llama, batch_size=1, prompt_len=8, n_tokens=4)
    assert dt > 0


def test_training_unaffected_by_decode_support():
    """The decode branch must be invisible to the training path: loss and
    grads identical with and without the cache machinery touched."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import causal_lm_loss
    from accelerate_tpu.parallel.mesh import batch_sharding

    acc = Accelerator(mixed_precision="bf16")
    model = acc.prepare_model(create_llama_model(LlamaConfig.tiny(), seq_len=16))
    acc.prepare_optimizer(optax.adamw(1e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    batch = jax.device_put(
        {"input_ids": np.ones((8, 16), np.int32)}, batch_sharding(acc.mesh)
    )
    losses = [float(step(batch)) for _ in range(3)]
    assert losses[-1] < losses[0]
    # generation works on the freshly trained params
    out = generate(model, np.ones((1, 4), np.int32), max_new_tokens=3)
    assert out.shape == (1, 7)


def test_gpt2_greedy_matches_full_prefix():
    """The decode contract generalises across the zoo: GPT-2's cached
    decode equals full-prefix argmax too."""
    from accelerate_tpu.models import GPT2Config, create_gpt2_model

    model = create_gpt2_model(GPT2Config.tiny(), seq_len=16)
    ids = (np.arange(2 * 8).reshape(2, 8) % 256).astype(np.int32)
    out = np.asarray(generate(model, ids, max_new_tokens=4))
    full = ids
    for _ in range(4):
        logits = np.asarray(model(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_cache_overflow_raises(tiny_llama):
    """prompt + max_new_tokens beyond the cache size must raise, not wrap."""
    ids = np.ones((1, 120), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(tiny_llama, ids, max_new_tokens=32)  # 152 > 128


def test_generate_runner_is_cached(tiny_llama):
    """Repeat generate() calls with the same static config must reuse one
    jitted runner (no per-call retrace)."""
    ids = np.ones((1, 4), np.int32)
    generate(tiny_llama, ids, max_new_tokens=3)
    runners = tiny_llama._generate_runners
    n = len(runners)
    generate(tiny_llama, ids, max_new_tokens=3)
    assert len(runners) == n  # same key reused
    generate(tiny_llama, ids, max_new_tokens=4)
    assert len(runners) == n + 1


def test_zero_and_negative_max_new_tokens(tiny_llama):
    ids = np.ones((2, 4), np.int32)
    out = generate(tiny_llama, ids, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), ids)  # [B, S]: no extra token
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(tiny_llama, ids, max_new_tokens=-1)


def test_gptneox_greedy_matches_full_prefix():
    """GPT-NeoX cached decode (partial rotary + parallel residual) equals
    full-prefix argmax token-exactly."""
    from accelerate_tpu.models import GPTNeoXConfig, create_gptneox_model

    model = create_gptneox_model(GPTNeoXConfig.tiny(), seq_len=16)
    ids = (np.arange(2 * 8).reshape(2, 8) % 256).astype(np.int32)
    out = np.asarray(generate(model, ids, max_new_tokens=5))
    full = ids
    for _ in range(5):
        logits = np.asarray(model(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_t5_seq2seq_greedy_matches_full_rerun():
    """Cached encoder-decoder generation must equal greedy decoding via
    full teacher-forced re-runs (the same gold standard as the decoder-only
    tests): encoder runs ONCE, decoder steps hit the KV cache + stored
    encoder output."""
    from accelerate_tpu.generation import generate_seq2seq
    from accelerate_tpu.models.t5 import T5Config, create_t5_model

    m = create_t5_model(T5Config.tiny(max_decode_len=32), seed=0, seq_len=8)
    src = (np.arange(2 * 8).reshape(2, 8) % 250).astype(np.int32)

    dec = np.zeros((2, 1), np.int32)
    for _ in range(6):
        logits = m.apply_fn(m.params, src, dec)
        nxt = np.asarray(logits)[:, -1].argmax(-1).astype(np.int32)
        dec = np.concatenate([dec, nxt[:, None]], axis=1)

    out = np.asarray(generate_seq2seq(m, src, max_new_tokens=6))
    np.testing.assert_array_equal(out, dec)


def test_t5_seq2seq_respects_attention_mask_and_eos():
    from accelerate_tpu.generation import generate_seq2seq
    from accelerate_tpu.models.t5 import T5Config, create_t5_model

    m = create_t5_model(T5Config.tiny(max_decode_len=16), seed=1, seq_len=8)
    src = (np.arange(2 * 8).reshape(2, 8) % 250).astype(np.int32)
    mask = np.ones((2, 8), bool)
    mask[:, 5:] = False  # padded tail must not change with its content
    out_a = np.asarray(generate_seq2seq(m, src, max_new_tokens=4, attention_mask=mask))
    src_b = src.copy()
    src_b[:, 5:] = 7  # garbage under the mask
    out_b = np.asarray(generate_seq2seq(m, src_b, max_new_tokens=4, attention_mask=mask))
    np.testing.assert_array_equal(out_a, out_b)

    # eos freezes a finished sequence
    eos = int(out_a[0, 1])
    out_eos = np.asarray(generate_seq2seq(m, src, max_new_tokens=6, attention_mask=mask, eos_token_id=eos))
    assert (out_eos[0, 1:] == eos).all()

    with pytest.raises(ValueError, match="max_decode_len"):
        generate_seq2seq(m, src, max_new_tokens=99)
