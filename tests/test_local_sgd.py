"""LocalSGD tests (reference analogue: tests/test_local_sgd.py — skip-sync
then param averaging; here: per-replica vmapped steps with periodic
average over the `data` mesh axis)."""

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, LocalSGD
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel, linear_loss_fn
from accelerate_tpu import MeshConfig
from accelerate_tpu.utils import ParallelismPlugin


def _make_acc():
    return Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=4, fsdp=2)))


def _batches(n, bs, seed=0):
    ds = RegressionDataset(length=n * bs, seed=seed)
    for i in range(n):
        sl = slice(i * bs, (i + 1) * bs)
        yield {"x": np.array(ds.x[sl]), "y": np.array(ds.y[sl])}


def test_local_sgd_replicas_diverge_then_sync():
    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.sgd(0.05))
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=4) as lsgd:
        step = lsgd.build_local_step(linear_loss_fn)
        batches = list(_batches(8, 16))
        for i, batch in enumerate(batches):
            step(batch)
            lsgd.step()
            stack = np.asarray(lsgd.replica_params["a"])
            if (i + 1) % 4 == 0:
                # just averaged: all replicas equal
                assert np.allclose(stack, stack[0]), stack
            else:
                # replicas see different data slices -> diverge
                assert not np.allclose(stack, stack[0])
    # on exit params are collapsed back into the model, synced
    assert np.asarray(model.params["a"]).ndim == 0 or np.asarray(model.params["a"]).shape == ()


def test_local_sgd_converges():
    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.sgd(0.1))
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=8) as lsgd:
        step = lsgd.build_local_step(linear_loss_fn)
        for epoch in range(30):
            for batch in _batches(4, 16, seed=epoch):
                step(batch)
                lsgd.step()
    a, b = float(np.asarray(model.params["a"])), float(np.asarray(model.params["b"]))
    assert abs(a - 2.0) < 0.2 and abs(b - 3.0) < 0.2, (a, b)


def test_local_sgd_disabled_passthrough():
    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.sgd(0.1))
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=4, enabled=False) as lsgd:
        step = lsgd.build_local_step(linear_loss_fn)
        for batch in _batches(6, 16):
            loss = step(batch)
            lsgd.step()
    assert np.isfinite(float(np.asarray(loss)))


def test_local_sgd_no_per_step_collectives():
    """The local step's compiled HLO must contain no cross-replica
    collectives — that is the entire point of LocalSGD."""
    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.sgd(0.05))
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=4) as lsgd:
        lsgd.build_local_step(linear_loss_fn)
        batch = next(_batches(1, 16))
        lowered = lsgd._local_step.lower(lsgd._stacked[0], lsgd._stacked[1], batch)
        hlo = lowered.compile().as_text()
        for coll in ("all-reduce", "all-gather", "collective-permute", "all-to-all"):
            assert coll not in hlo, f"found {coll} in local step HLO"


def test_local_sgd_writes_back_optimizer_state():
    """On exit the prepared optimizer's state must reflect the LocalSGD
    training (not the stale pre-block state)."""
    import jax

    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.adam(0.05))
    before = jax.tree_util.tree_leaves(opt.opt_state)
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=4) as lsgd:
        step = lsgd.build_local_step(linear_loss_fn)
        for batch in _batches(8, 16):
            step(batch)
            lsgd.step()
    after = jax.tree_util.tree_leaves(opt.opt_state)
    # Adam mu/nu must have moved; step count must be 8
    changed = any(
        not np.allclose(np.asarray(b), np.asarray(a)) for b, a in zip(before, after) if hasattr(b, "shape")
    )
    assert changed
    counts = [np.asarray(l) for l in after if np.asarray(l).dtype.kind in "iu"]
    assert any(c == 8 for c in counts), counts


def test_local_sgd_carries_preexisting_optimizer_state():
    """Entering a LocalSGD block mid-run must seed the replicas with the
    optimizer's accumulated state (Adam moments + step count), not a fresh
    init — and the count must keep increasing across the block."""
    import jax

    acc = _make_acc()
    model, opt = acc.prepare(RegressionModel(), optax.adam(0.05))
    pre_step = acc.build_train_step(linear_loss_fn)
    for batch in _batches(5, 64):
        pre_step(batch)
    pre_counts = [
        int(np.asarray(l)) for l in jax.tree_util.tree_leaves(opt.opt_state) if np.asarray(l).dtype.kind in "iu"
    ]
    assert any(c == 5 for c in pre_counts), pre_counts
    pre_moments = [np.asarray(l) for l in jax.tree_util.tree_leaves(opt.opt_state) if np.asarray(l).dtype.kind == "f"]
    with LocalSGD(accelerator=acc, model=model, local_sgd_steps=4) as lsgd:
        step = lsgd.build_local_step(linear_loss_fn)
        # the replica stacks start from the real state, not zeros
        stacked_moments = [
            np.asarray(l) for l in jax.tree_util.tree_leaves(lsgd._stacked[1]) if np.asarray(l).dtype.kind == "f"
        ]
        for pre, stk in zip(pre_moments, stacked_moments):
            assert np.allclose(np.broadcast_to(pre, stk.shape), stk), "replicas re-initialised optimizer state"
        for batch in _batches(4, 16):
            step(batch)
            lsgd.step()
    counts = [
        int(np.asarray(l)) for l in jax.tree_util.tree_leaves(opt.opt_state) if np.asarray(l).dtype.kind in "iu"
    ]
    assert any(c == 9 for c in counts), f"step count reset across LocalSGD block: {counts}"
