"""Context-parallel attention tests: ring + all_to_all must match the
single-device reference attention bit-for-bit-ish on the 8-device fake mesh
(SURVEY §5 long-context: the reference has no such mechanism — parity-plus)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import MeshConfig
from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.context import context_parallel_attention, sequence_sharding


def _qkv(b=2, s=64, h=4, h_kv=None, d=16, seed=0):
    h_kv = h_kv or h
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, h_kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, h_kv, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("method", ["ring", "all_to_all"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(method, causal):
    mesh = MeshConfig(data=2, seq=4).build()
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, use_flash=False)
    shard = sequence_sharding(mesh)
    qs, ks_, vs = (jax.device_put(x, shard) for x in (q, k, v))
    out = context_parallel_attention(qs, ks_, vs, mesh=mesh, causal=causal, method=method)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("method", ["ring", "all_to_all"])
def test_gqa(method):
    mesh = MeshConfig(seq=4).build()
    # GQA: 8 query heads, 4 kv heads (4 divides the seq axis for all_to_all)
    q, k, v = _qkv(h=8, h_kv=4)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    shard = sequence_sharding(mesh)
    out = context_parallel_attention(
        *(jax.device_put(x, shard) for x in (q, k, v)), mesh=mesh, causal=True, method=method
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("method", ["ring", "all_to_all"])
@pytest.mark.parametrize("window", [5, 16, 200])
def test_banded_matches_reference(method, window):
    """Sliding-window band under context parallelism: absolute positions
    make the band invariant to the ring rotation / head re-sharding;
    windows crossing shard boundaries (5, 16 with S_loc=16) and wider
    than the sequence (200) all match the dense banded reference."""
    mesh = MeshConfig(data=2, seq=4).build()
    q, k, v = _qkv(h=8, h_kv=4)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False, window=window)
    shard = sequence_sharding(mesh)
    out = context_parallel_attention(
        *(jax.device_put(x, shard) for x in (q, k, v)),
        mesh=mesh, causal=True, method=method, window=window,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_banded_ring_gradients_match():
    mesh = MeshConfig(seq=8).build()
    q, k, v = _qkv(s=64)
    shard = sequence_sharding(mesh)

    def loss_cp(q, k, v):
        out = context_parallel_attention(
            jax.device_put(q, shard), jax.device_put(k, shard), jax.device_put(v, shard),
            mesh=mesh, causal=True, method="ring", window=11,
        )
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True, use_flash=False, window=11).astype(jnp.float32) ** 2).sum()

    grads = jax.grad(loss_cp, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(grads, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e), atol=2e-3, rtol=2e-3, err_msg=f"d{name}")


def test_banded_requires_causal_cp():
    mesh = MeshConfig(seq=4).build()
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        context_parallel_attention(q, k, v, mesh=mesh, causal=False, window=8)


def test_ring_gradients_match():
    mesh = MeshConfig(seq=8).build()
    q, k, v = _qkv(s=64)
    shard = sequence_sharding(mesh)

    def loss_ring(q, k, v):
        return context_parallel_attention(q, k, v, mesh=mesh, causal=True, method="ring").sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=True, use_flash=False).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(*(jax.device_put(x, shard) for x in (q, k, v)))
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_trivial_seq_axis_falls_back():
    mesh = MeshConfig(data=8).build()
    q, k, v = _qkv(s=32)
    out = context_parallel_attention(q, k, v, mesh=mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_only_neighbour_traffic():
    """The ring method's HLO must use collective-permute (neighbour
    exchange), never all-gathering the sequence."""
    mesh = MeshConfig(seq=4).build()
    q, k, v = _qkv(s=32)
    shard = sequence_sharding(mesh)
    args = tuple(jax.device_put(x, shard) for x in (q, k, v))
    hlo = (
        context_parallel_attention.lower(*args, mesh=mesh, causal=True, method="ring")
        .compile()
        .as_text()
    )
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo, "ring attention must not all-gather KV"


def test_rejects_indivisible_seq():
    mesh = MeshConfig(seq=8).build()
    q, k, v = _qkv(s=36)
    with pytest.raises(ValueError):
        context_parallel_attention(q, k, v, mesh=mesh)


def test_llama_forward_with_seq_parallel_matches_dense():
    """End-to-end: tiny Llama under a seq=4 mesh (ring attention inside the
    jitted forward) must match the dense single-mesh forward."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import ParallelismPlugin

    cfg = LlamaConfig.tiny(scan_layers=False, remat=False)
    ref_model = create_llama_model(cfg, seq_len=32)
    ids = np.arange(2 * 32, dtype=np.int32).reshape(2, 32) % cfg.vocab_size
    ref_out = np.asarray(ref_model(ids))

    for s in (AcceleratorState, GradientState, PartialState):
        s._reset_state()
    acc = Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, seq=4)))
    model = acc.prepare_model(create_llama_model(cfg, seq_len=32))
    out = np.asarray(jax.jit(model.apply_fn)(model.params, ids))
    np.testing.assert_allclose(out, ref_out, atol=3e-4, rtol=3e-4)
