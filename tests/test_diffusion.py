"""UNet2D diffusion family: denoiser, schedule, jitted samplers, training,
and mesh-sharded sampling (reference analogue: the distributed image
generation examples, examples/inference/distributed/stable_diffusion.py —
pipeline internals in-tree here)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.diffusion import diffusion_loss, make_schedule, sample
from accelerate_tpu.models import UNetConfig, create_unet_model


@pytest.fixture(scope="module")
def tiny_unet():
    return create_unet_model(UNetConfig.tiny(), seed=0)


def test_unet_shapes_and_dtype(tiny_unet):
    x = np.zeros((2, 8, 8, 3), np.float32)
    t = np.array([0, 999], np.int32)
    out = tiny_unet.apply_fn(tiny_unet.params, x, t)
    assert out.shape == (2, 8, 8, 3)
    assert out.dtype == jax.numpy.float32


def test_schedule_monotonic():
    for kind in ("linear", "cosine"):
        s = make_schedule(100, kind=kind)
        assert s["alphas_bar"].shape == (100,)
        assert np.all(np.diff(s["alphas_bar"]) < 0)  # strictly decaying
        assert 0.0 < s["alphas_bar"][-1] < s["alphas_bar"][0] <= 1.0


def test_ddim_deterministic_and_seeded(tiny_unet):
    s = make_schedule(64)
    a = np.asarray(sample(tiny_unet, 2, num_steps=4, schedule=s, seed=1))
    b = np.asarray(sample(tiny_unet, 2, num_steps=4, schedule=s, seed=1))
    c = np.asarray(sample(tiny_unet, 2, num_steps=4, schedule=s, seed=2))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.shape == (2, 8, 8, 3) and np.isfinite(a).all()


def test_ddpm_sampler_runs(tiny_unet):
    s = make_schedule(64)
    out = np.asarray(sample(tiny_unet, 1, num_steps=4, schedule=s, method="ddpm"))
    assert out.shape == (1, 8, 8, 3) and np.isfinite(out).all()


def test_sampler_runner_cached(tiny_unet):
    s = make_schedule(64)
    sample(tiny_unet, 2, num_steps=4, schedule=s)
    n = len(tiny_unet._generate_runners)
    sample(tiny_unet, 2, num_steps=4, schedule=s)
    assert len(tiny_unet._generate_runners) == n
    sample(tiny_unet, 2, num_steps=3, schedule=s)
    assert len(tiny_unet._generate_runners) == n + 1


def test_invalid_args(tiny_unet):
    s = make_schedule(64)
    with pytest.raises(ValueError, match="num_steps"):
        sample(tiny_unet, 1, num_steps=0, schedule=s)
    with pytest.raises(ValueError, match="method"):
        sample(tiny_unet, 1, num_steps=2, schedule=s, method="euler")
    with pytest.raises(ValueError, match="class-conditional"):
        sample(tiny_unet, 1, num_steps=2, schedule=s, guidance_scale=2.0)


def test_training_step_decreases_loss():
    import optax

    from accelerate_tpu import Accelerator

    acc = Accelerator(mixed_precision="bf16")
    model = acc.prepare_model(create_unet_model(UNetConfig.tiny(), seed=0))
    acc.prepare_optimizer(optax.adam(2e-3))
    schedule = make_schedule(64)
    step = acc.build_train_step(
        lambda p, b, rng: diffusion_loss(p, b, model.apply_fn, schedule, rng)
    )
    rng = np.random.default_rng(0)
    batch = {"images": rng.standard_normal((8, 8, 8, 3)).astype(np.float32) * 0.1}
    losses = [float(step(batch)) for _ in range(30)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_class_conditional_guidance():
    model = create_unet_model(UNetConfig.tiny(num_classes=4), seed=0)
    s = make_schedule(32)
    labels = np.array([0, 1], np.int32)
    out = np.asarray(sample(model, 2, num_steps=3, schedule=s, class_labels=labels, guidance_scale=2.0))
    assert out.shape == (2, 8, 8, 3) and np.isfinite(out).all()
    # guidance changes the output vs unguided
    plain = np.asarray(sample(model, 2, num_steps=3, schedule=s, class_labels=labels))
    assert not np.array_equal(out, plain)
    with pytest.raises(ValueError, match="class_labels"):
        sample(model, 2, num_steps=3, schedule=s)


@pytest.mark.parametrize("mesh_kw", [dict(data=4, tensor=1), dict(data=1, tensor=4)])
def test_sharded_sampling_matches_single_device(mesh_kw):
    """Params TP/data-sharded -> identical images (the distributed image
    generation story: reference distributed_image_generation.py).

    Resolution of the long-standing hybrid-mesh failure: it was neither a
    tolerance problem nor reduction order — XLA:CPU's SPMD partitioner
    (jax 0.4.37) miscompiles this graph whenever a param is sharded over
    one axis of a MULTI-axis mesh (partial replication), producing O(1)
    wrong values; any single-axis mesh compiles correctly. Exact parity is
    asserted on the pure-DP and pure-TP meshes (the partitioned programs a
    CPU host can compile faithfully); the hybrid layout keeps a smoke test
    below so the data x tensor path stays exercised end-to-end.
    """
    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    s = make_schedule(32)
    single = create_unet_model(UNetConfig.tiny(), seed=3)
    want = np.asarray(sample(single, 2, num_steps=3, schedule=s, seed=5))

    model = create_unet_model(UNetConfig.tiny(), seed=3)
    mesh = MeshConfig(**mesh_kw).build(jax.devices()[:4])
    shard_model(model, mesh)
    got = np.asarray(sample(model, 2, num_steps=3, schedule=s, seed=5))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_sharded_sampling_hybrid_mesh_runs():
    """data x tensor hybrid sampling end-to-end. Numerical parity with the
    single-device run is NOT asserted: XLA:CPU miscompiles partially
    replicated shardings on multi-axis meshes (see the parity test above);
    on real TPU backends the layout is exact."""
    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    s = make_schedule(32)
    model = create_unet_model(UNetConfig.tiny(), seed=3)
    mesh = MeshConfig(data=2, tensor=2).build(jax.devices()[:4])
    shard_model(model, mesh)
    got = np.asarray(sample(model, 2, num_steps=3, schedule=s, seed=5))
    assert got.shape == (2, 8, 8, 3) and np.isfinite(got).all()


def test_schedule_change_is_not_served_from_cache(tiny_unet):
    """The runner cache must key on schedule CONTENT: same num_steps with a
    different schedule must re-trace, not reuse baked-in alphas."""
    a = np.asarray(sample(tiny_unet, 1, num_steps=3, schedule=make_schedule(64), seed=0))
    b = np.asarray(sample(tiny_unet, 1, num_steps=3, schedule=make_schedule(256), seed=0))
    assert not np.array_equal(a, b)
