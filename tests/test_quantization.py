"""Weight-only quantization + fp8 tests (reference parity:
tests/test_quantization.py for bnb int8/int4, utils/ao.py fp8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils.quantization import (
    QTensor,
    QuantizationConfig,
    dequantize,
    dequantize_params,
    fp8_dot,
    fp8_quantize,
    load_and_quantize_model,
    quantize,
    quantize_params,
    quantized_bytes,
    quantized_matmul,
)


def _w(shape, seed=0, scale=0.05):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32) * scale


@pytest.mark.parametrize("method,tol", [("int8", 1.5e-3), ("int4", 3e-2), ("nf4", 3e-2)])
@pytest.mark.parametrize("group_size", [None, 32])
def test_roundtrip_error(method, tol, group_size):
    w = _w((128, 64))
    cfg = QuantizationConfig(method=method, group_size=group_size)
    qt = quantize(w, cfg)
    back = dequantize(qt)
    assert back.shape == w.shape and back.dtype == w.dtype
    err = float(jnp.abs(back - w).max())
    assert err < tol, f"{method} group={group_size}: max err {err}"


def test_stacked_and_1d_shapes():
    cfg = QuantizationConfig(method="int4", group_size=16)
    for shape in [(4, 64, 32), (2, 3, 32, 16), (64,)]:
        w = _w(shape, seed=1)
        back = dequantize(quantize(w, cfg))
        assert back.shape == w.shape
        assert float(jnp.abs(back - w).max()) < 2e-2


def test_memory_shrinks():
    w = _w((256, 256))
    q8 = quantize(w, QuantizationConfig(bits=8))
    q4 = quantize(w, QuantizationConfig(bits=4, group_size=64))
    assert q8.nbytes < w.nbytes * 0.6
    assert q4.nbytes < w.nbytes * 0.35


def test_qtensor_is_pytree_and_jittable():
    qt = quantize(_w((64, 64)), QuantizationConfig())
    leaves, treedef = jax.tree_util.tree_flatten(qt)
    assert len(leaves) == 2
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    out = jax.jit(dequantize)(rebuilt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(dequantize(qt)))


@pytest.mark.parametrize("method,group_size", [("int8", None), ("int8", 32), ("nf4", 32)])
def test_quantized_matmul_matches_dequant(method, group_size):
    w = _w((128, 64))
    x = _w((8, 128), seed=2, scale=1.0)
    qt = quantize(w, QuantizationConfig(method=method, group_size=group_size))
    y = quantized_matmul(x, qt)
    ref = x @ dequantize(qt)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref, np.float32), atol=2e-2, rtol=2e-2)


def test_quantize_params_skips_and_selects():
    params = {
        "embed_tokens": {"embedding": _w((100, 64))},
        "layer_0": {"mlp": {"kernel": _w((64, 128))}, "norm": {"scale": jnp.ones(64)}},
        "tiny": _w((4, 4)),
    }
    q = quantize_params(params, QuantizationConfig())
    assert isinstance(q["layer_0"]["mlp"]["kernel"], QTensor)
    assert not isinstance(q["embed_tokens"]["embedding"], QTensor)  # skip pattern
    assert not isinstance(q["layer_0"]["norm"]["scale"], QTensor)
    assert not isinstance(q["tiny"], QTensor)  # below min_size
    assert quantized_bytes(q) > 0
    back = dequantize_params(q)
    assert back["layer_0"]["mlp"]["kernel"].shape == (64, 128)


def test_load_and_quantize_model_end_to_end():
    """Tiny Llama quantized to int8: logits close to fp32, params smaller."""
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    model = create_llama_model(LlamaConfig.tiny(scan_layers=True, remat=False), seq_len=16)
    ids = (np.arange(2 * 16).reshape(2, 16) % 250).astype(np.int32)
    ref = np.asarray(model(ids), np.float32)

    qmodel = load_and_quantize_model(model, QuantizationConfig(bits=8))
    out = np.asarray(jax.jit(qmodel.apply_fn)(qmodel.params, ids), np.float32)
    # logits drift from weight rounding but ranking should broadly hold
    assert np.mean(np.argmax(out, -1) == np.argmax(ref, -1)) > 0.9
    np.testing.assert_allclose(out, ref, atol=0.35, rtol=0.5)
    assert quantized_bytes(qmodel.params) < model.parameter_bytes() * 0.55


def test_load_and_quantize_model_uses_in_scan_qdense():
    """Llama models convert to the QuantDense layout: packed codes ARE the
    params (sliced per layer by nn.scan), not a wrapped dequantize."""
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    model = create_llama_model(LlamaConfig.tiny(scan_layers=True, remat=False), seq_len=16)
    qmodel = load_and_quantize_model(model, QuantizationConfig(bits=8))
    assert qmodel.config.quant_method == "int8"
    blk = qmodel.params["layers"]["block"]
    qdata = blk["attn"]["q_proj"]["qdata"]
    assert qdata.dtype == jnp.int8
    assert qdata.shape[0] == model.config.num_hidden_layers  # stacked layer dim
    assert "kernel" not in blk["attn"]["q_proj"]
    # non-projection leaves stay float
    assert qmodel.params["embed_tokens"]["embedding"].dtype == model.params["embed_tokens"]["embedding"].dtype


@pytest.mark.parametrize("method,group_size", [("int8", None), ("nf4", 16)])
def test_qdense_matches_dequantized_matmul(method, group_size):
    from accelerate_tpu.ops.qdense import QuantDense

    w = _w((64, 48), seed=7)
    x = _w((4, 64), seed=8, scale=1.0)
    qt = quantize(w, QuantizationConfig(method=method, group_size=group_size, bits=8 if method == "int8" else 4))
    layer = QuantDense(48, method=method, group_size=group_size, dtype=jnp.float32)
    out = layer.apply({"params": {"qdata": qt.data, "qscale": qt.scale}}, x)
    ref = x @ dequantize(qt)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("scan_layers", [True, False])
def test_quantized_decode_matches_bf16_decode(scan_layers):
    """generate() through QuantDense stays close to the unquantized model:
    the prefill logits agree and greedy decode runs the full KV-cache loop."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    model = create_llama_model(LlamaConfig.tiny(scan_layers=scan_layers, remat=False), seq_len=16)
    qmodel = load_and_quantize_model(model, QuantizationConfig(bits=8))
    ids = (np.arange(2 * 8).reshape(2, 8) % 250).astype(np.int32)

    ref_logits, _ = model.apply_fn(model.params, jnp.asarray(ids), decode=True, cache=None)
    q_logits, _ = qmodel.apply_fn(qmodel.params, jnp.asarray(ids), decode=True, cache=None)
    np.testing.assert_allclose(np.asarray(q_logits, np.float32), np.asarray(ref_logits, np.float32), atol=0.35, rtol=0.5)

    out = generate(qmodel, ids, max_new_tokens=4)
    assert out.shape == (2, 12)
    assert np.array_equal(np.asarray(out[:, :8]), ids)


def test_quantized_model_shards_on_tensor_axis():
    """The qdata/qscale sharding rules put column-parallel splits on the
    trailing (out) dim and row-parallel splits on the group dim."""
    from accelerate_tpu import Accelerator, ParallelismPlugin
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    acc = Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=4, tensor=2)))
    model = create_llama_model(LlamaConfig.tiny(scan_layers=True, remat=False), seq_len=16)
    qmodel = load_and_quantize_model(model, QuantizationConfig(bits=8))
    qmodel = acc.prepare_model(qmodel)
    blk = qmodel.params["layers"]["block"]
    q_spec = blk["attn"]["q_proj"]["qdata"].sharding.spec
    o_spec = blk["attn"]["o_proj"]["qdata"].sharding.spec
    assert q_spec[-1] == "tensor", q_spec
    # row-parallel: the group (contraction) dim, index 2 of [L, n_g, g, out],
    # carries ``tensor``; the out dim is unsharded (trailing Nones may be
    # trimmed from the spec)
    assert tuple(o_spec)[:3] == (None, None, "tensor") and (len(o_spec) < 4 or o_spec[3] is None), o_spec
    ids = (np.arange(4 * 16).reshape(4, 16) % 250).astype(np.int32)
    out = jax.jit(qmodel.apply_fn)(qmodel.params, ids)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_fp8_quantize_and_dot():
    x = _w((32, 64), seed=3, scale=1.0)
    x8, inv = fp8_quantize(x)
    assert x8.dtype == jnp.float8_e4m3fn
    np.testing.assert_allclose(np.asarray(x8, np.float32) * float(inv), np.asarray(x), atol=0.05, rtol=0.1)

    a, b = _w((16, 64), seed=4, scale=1.0), _w((64, 32), seed=5, scale=1.0)
    y = np.asarray(fp8_dot(a, b), np.float32)
    ref = np.asarray(a @ b)
    # e4m3 carries ~3 mantissa bits; bound the relative Frobenius error
    rel = np.linalg.norm(y - ref) / np.linalg.norm(ref)
    assert rel < 0.05, f"fp8 matmul relative error {rel}"


def test_rejects_bad_config():
    with pytest.raises(ValueError):
        QuantizationConfig(bits=3)
    with pytest.raises(ValueError):
        QuantizationConfig(method="int2")


@pytest.mark.parametrize("b,infeat,out,g", [(1, 128, 256, 64), (4, 256, 384, 128), (3, 256, 128, 64)])
def test_pallas_int4_matmul_matches_dequant(b, infeat, out, g):
    """The fused dequant+matmul kernel (interpret mode on the CPU mesh)
    must match dequantize-then-matmul to bf16 rounding."""
    from accelerate_tpu.ops.pallas_qmatmul import int4_matmul

    w = _w((infeat, out), seed=11)
    x = jax.random.normal(jax.random.key(12), (b, infeat), jnp.bfloat16)
    qt = quantize(w, QuantizationConfig(bits=4, method="int4", group_size=g))
    ref = x.astype(jnp.float32) @ dequantize(qt, jnp.float32)
    got = int4_matmul(x, qt.data, qt.scale, group_size=g, interpret=True).astype(jnp.float32)
    err = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert err < 0.02, err


def test_pallas_int4_rejects_bad_shapes():
    from accelerate_tpu.ops.pallas_qmatmul import int4_matmul

    w = _w((128, 256), seed=13)
    qt = quantize(w, QuantizationConfig(bits=4, method="int4", group_size=32))
    x = jnp.ones((1, 128), jnp.bfloat16)
    with pytest.raises(ValueError):
        int4_matmul(x, qt.data, qt.scale, group_size=32, interpret=True)  # group % 64


def test_w8a8_qdense_close_to_weight_only():
    """w8a8 (native int8 MXU path) adds per-row activation rounding on top
    of the weight rounding — output stays within ~1% of the W8A16 path."""
    from accelerate_tpu.ops.qdense import QuantDense

    w = _w((128, 96), seed=20)
    x = jax.random.normal(jax.random.key(21), (4, 128), jnp.float32)
    qt = quantize(w, QuantizationConfig(bits=8, method="w8a8"))
    params = {"params": {"qdata": qt.data, "qscale": qt.scale}}
    y_w8a8 = QuantDense(96, method="w8a8", dtype=jnp.float32).apply(params, x)
    y_ref = QuantDense(96, method="int8", dtype=jnp.float32).apply(params, x)
    rel = float(jnp.linalg.norm(y_w8a8 - y_ref) / jnp.linalg.norm(y_ref))
    assert rel < 0.02, rel


def test_w8a8_llama_end_to_end():
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    model = create_llama_model(LlamaConfig.tiny(scan_layers=True, remat=False), seq_len=16)
    qmodel = load_and_quantize_model(model, QuantizationConfig(bits=8, method="w8a8"))
    ids = (np.arange(2 * 8).reshape(2, 8) % 250).astype(np.int32)
    ref = np.asarray(model(ids), np.float32)
    out = np.asarray(jax.jit(qmodel.apply_fn)(qmodel.params, ids), np.float32)
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 0.1, rel


def test_nf4_tpu_size_guard(monkeypatch):
    """The XLA nf4 codebook gather kernel-faults the TPU worker at GB scale
    (round-3 finding); decodes past the safety limit must raise an
    actionable error BEFORE the faulting op, on TPU only."""
    import accelerate_tpu.utils.quantization as Q

    w = _w((64, 32), seed=5)
    qt = quantize(w, QuantizationConfig(bits=4, method="nf4"))

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("ACCELERATE_NF4_MAX_ELEMENTS", "100")
    with pytest.raises(ValueError, match="int4"):
        qt.dequantize()

    # generous limit or CPU backend: decode works
    monkeypatch.setenv("ACCELERATE_NF4_MAX_ELEMENTS", str(2**20))
    np.testing.assert_allclose(np.asarray(qt.dequantize()), np.asarray(w), atol=0.05)
    monkeypatch.setenv("ACCELERATE_NF4_MAX_ELEMENTS", "100")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    qt.dequantize()  # no raise off-TPU


def test_nf4_aggregate_guard_at_quantize_time(monkeypatch):
    """The wrapped-apply fallback decodes every leaf per forward: the
    aggregate guard fires at quantize_params time, not at first run."""
    from accelerate_tpu.utils.quantization import quantize_params

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("ACCELERATE_NF4_MAX_ELEMENTS", str(3 * 4096))
    params = {f"layer_{i}": {"w": _w((64, 64), seed=i)} for i in range(4)}  # 4 x 4096
    with pytest.raises(ValueError, match="ACCELERATE_NF4_MAX_ELEMENTS"):
        quantize_params(params, QuantizationConfig(bits=4, method="nf4"))
    # int4 at identical scale stays allowed
    quantize_params(params, QuantizationConfig(bits=4, method="int4"))
