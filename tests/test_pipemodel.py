"""Pipeline-schedule analyzer tests: the per-stage roofline / bubble
model (``analysis.pipemodel``), the TPU80x rules
(``analysis.pipe_rules``), the ``accelerate-tpu pipe-check`` CLI, the
searchspace/tuner pipeline knobs, and — the wire-unit pin — byte-exact
agreement between ``costmodel.price_collective`` and the HLO collective
counters (``telemetry.wire``) on a real compiled ``pipeline_apply``
program."""

import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from accelerate_tpu.analysis.costmodel import (
    BANDWIDTH_TABLE,
    hbm_bandwidth,
    peak_flops,
    price_collective,
)
from accelerate_tpu.analysis.pipe_rules import (
    PIPE_BUBBLE_THRESHOLD,
    covering_microbatches,
)
from accelerate_tpu.analysis.pipemodel import (
    PipelineSpec,
    analyze_pipeline,
    from_pipelined_model,
    pipe_check,
)
from accelerate_tpu.parallel.mesh import MeshConfig

CPU_ENV = {
    **os.environ,
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, env=None, timeout=420):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True, text=True, env=env or CPU_ENV, timeout=timeout,
    )


def _mm(p, h):
    return h @ p


def _pipe_mesh(s):
    return MeshConfig(pipe=s, data=8 // s).build()


def _spec(layer_fn, s, *, m, width=16, batch=16, layers=None, **kw):
    """A declared S-stage single-matmul-per-layer schedule (the selfcheck
    fixture family): stacked [L, W, W] params, [B, W] activations."""
    L = layers if layers is not None else 2 * s
    params = jax.ShapeDtypeStruct((L, width, width), jnp.float32)
    x = jax.ShapeDtypeStruct((batch, width), jnp.float32)
    return PipelineSpec(layer_fn, params, x, _pipe_mesh(s), num_microbatches=m, **kw)


def _hand(s, m, *, width=16, batch=16, layers_per_stage=2, interleave=1):
    """Hand-computed reference for the _spec family, straight from the
    costmodel tables (mirrors the selfcheck's pinned arithmetic)."""
    b_mb = batch // m
    b_blk = b_mb // interleave
    flops = 2 * b_blk * width * width
    hbm = (b_blk * width + width * width + b_blk * width) * 4
    t_layer = max(
        flops / (peak_flops("cpu", "bf16") / 2.0) * 1e6,  # f32 matmul class
        hbm / hbm_bandwidth("cpu") * 1e6,
    )
    stage_c = interleave * layers_per_stage * t_layer
    act = batch * width * 4 // m
    block_us = (act // interleave) / BANDWIDTH_TABLE["cpu"]["ici"] * 1e6
    block_c = stage_c / interleave
    exposed = block_us + (interleave - 1) * max(0.0, block_us - block_c)
    ticks = m + s - 1
    tick = stage_c + exposed
    return {
        "stage_compute_us": stage_c,
        "exposed_us": exposed,
        "hidden_us": interleave * block_us - exposed,
        "step_us": ticks * tick,
        "bubble": 1.0 - (m * s * stage_c) / (s * ticks * tick),
    }


def _close(a, b):
    assert math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-12), (a, b)


# --------------------------------------------------------------------- #
# the bubble / roofline model, pinned against hand arithmetic
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("s,m", [(2, 4), (4, 8)])
def test_declared_schedule_exact_bubble(s, m):
    r = analyze_pipeline(_spec(_mm, s, m=m), generation="cpu")
    ref = _hand(s, m)
    assert r.n_stages == s and r.num_microbatches == m
    assert r.ticks == m + s - 1
    _close(r.ideal_bubble_fraction, (s - 1) / (m + s - 1))
    _close(r.stages[0].compute_us, ref["stage_compute_us"])
    _close(r.exposed_permute_us, ref["exposed_us"])
    _close(r.predicted_step_us, ref["step_us"])
    _close(r.bubble_fraction, ref["bubble"])


def test_bubble_shrinks_with_microbatches():
    bubbles = [
        analyze_pipeline(_spec(_mm, 4, m=m), generation="cpu").bubble_fraction
        for m in (1, 2, 4, 8, 16)
    ]
    assert bubbles == sorted(bubbles, reverse=True)
    # predict_step_us_at: identity at its own M, and the covering M
    # (what TPU803 prices) beats a full-bubble schedule
    r1 = analyze_pipeline(_spec(_mm, 4, m=1), generation="cpu")
    _close(r1.predict_step_us_at(1), r1.predicted_step_us)
    assert r1.predict_step_us_at(covering_microbatches(4)) < r1.predicted_step_us


def test_imbalanced_cut_inflates_max_tick():
    bal = analyze_pipeline(_spec(_mm, 4, m=8), generation="cpu")
    imb = analyze_pipeline(
        _spec(_mm, 4, m=8, stage_layers=(5, 1, 1, 1)), generation="cpu"
    )
    assert [s.layers for s in imb.stages] == [5, 1, 1, 1]
    # the fat stage paces every tick: 5/2 the balanced per-stage compute
    _close(imb.max_tick_us - imb.exposed_permute_us,
           2.5 * (bal.max_tick_us - bal.exposed_permute_us))
    assert imb.predicted_step_us > bal.predicted_step_us
    assert imb.bubble_fraction > bal.bubble_fraction


def test_interleave_overlap_accounting():
    r1 = analyze_pipeline(_spec(_mm, 4, m=4), generation="cpu")
    r4 = analyze_pipeline(_spec(_mm, 4, m=4, interleave=4), generation="cpu")
    assert r1.interleave == 1 and r4.interleave == 4
    # k=1: single block, nothing to hide behind
    _close(r1.exposed_permute_us, r1.permute_block_us)
    _close(r1.hidden_permute_us, 0.0)
    # blocks split the activation: block handoff is 1/4 the full one
    _close(r4.permute_block_us, r1.permute_block_us / 4)
    # conservation: every block's permute is either exposed or hidden
    _close(r4.exposed_permute_us + r4.hidden_permute_us, 4 * r4.permute_block_us)
    ref = _hand(4, 4, interleave=4)
    _close(r4.exposed_permute_us, ref["exposed_us"])
    _close(r4.hidden_permute_us, ref["hidden_us"])
    _close(r4.predicted_step_us, ref["step_us"])
    # an interleave that does not divide the microbatch degrades to k=1
    r3 = analyze_pipeline(_spec(_mm, 4, m=4, interleave=3), generation="cpu")
    assert r3.interleave == 1


def test_per_stage_hbm_vs_flight_check():
    """Each stage holds 1/S of the stacked params: per-stage peaks sit
    under the whole-program flight-check peak, and the per-stage param
    bytes sum back to the full stack."""
    from accelerate_tpu.analysis.flightcheck import flight_check
    from accelerate_tpu.parallel.pipeline import pipeline_apply

    s, m, width, batch, L = 4, 4, 16, 32, 8
    mesh = _pipe_mesh(s)
    params = jax.ShapeDtypeStruct((L, width, width), jnp.float32)
    x = jax.ShapeDtypeStruct((batch, width), jnp.float32)
    spec = PipelineSpec(_mm, params, x, mesh, num_microbatches=m)
    r = analyze_pipeline(spec, generation="cpu")
    assert sum(st.param_bytes for st in r.stages) == L * width * width * 4

    def step(p, xx):
        return pipeline_apply(_mm, p, xx, mesh=mesh, num_microbatches=m).sum()

    fl = flight_check(step, params, x, mesh=mesh, generation="cpu")
    assert fl.peak_hbm_bytes > 0
    for st in r.stages:
        assert st.peak_hbm_bytes < fl.peak_hbm_bytes


def test_remat_keeps_stage_boundary_only():
    full = analyze_pipeline(_spec(_mm, 4, m=8), generation="cpu")
    re = analyze_pipeline(_spec(_mm, 4, m=8, remat=True), generation="cpu")
    # 2 layers/stage saved -> 1 boundary activation saved
    saved_delta = 8 * (2 - 1) * full.activation_bytes
    assert full.stages[0].peak_hbm_bytes - re.stages[0].peak_hbm_bytes == saved_delta


def test_traced_path_matches_declared():
    """The traced recognizer prices the real ``pipeline_apply`` program
    to the same schedule shape the declared spec gives."""
    from accelerate_tpu.parallel.pipeline import pipeline_apply

    s, m, width, batch = 4, 4, 16, 32
    mesh = _pipe_mesh(s)

    def step(p, xx):
        return pipeline_apply(_mm, p, xx, mesh=mesh, num_microbatches=m).sum()

    params = jax.ShapeDtypeStruct((8, width, width), jnp.float32)
    x = jax.ShapeDtypeStruct((batch, width), jnp.float32)
    r = pipe_check(step, params, x, mesh=mesh, rules=False, generation="cpu")
    assert r.source == "traced"
    assert r.n_stages == s and r.num_microbatches == m
    assert r.ticks == m + s - 1
    # per-shard (data=2) microbatch activation: (batch/2/m) x width f32
    assert r.activation_bytes == (batch // 2 // m) * width * 4
    assert r.predicted_step_us > 0


def test_pipelined_model_entry():
    from accelerate_tpu.parallel.pipeline import PipelinedModel

    width, batch = 16, 32
    mesh = _pipe_mesh(4)
    pm = PipelinedModel(
        pre_fn=lambda p, x: (x, ()),
        layer_fn=_mm,
        post_fn=lambda p, h: h.sum(),
        params={
            "pre": (),
            "layers": jax.ShapeDtypeStruct((8, width, width), jnp.float32),
            "post": (),
        },
        mesh=mesh,
        num_microbatches=4,
    )
    spec = from_pipelined_model(pm, jax.ShapeDtypeStruct((batch, width), jnp.float32))
    assert spec.x.shape == (batch // 2, width)  # one data shard's batch
    r = analyze_pipeline(spec, generation="cpu")
    assert r.n_stages == 4 and r.num_microbatches == 4


# --------------------------------------------------------------------- #
# TPU80x rules: each fires on its seeded defect, stays quiet on the twin
# --------------------------------------------------------------------- #


def _rules(report_args, **kw):
    r = pipe_check(report_args, generation="cpu", **kw)
    return r, {f.rule for f in r.findings}


def test_tpu801_pipe_on_ici_with_dcn_present():
    r, ids = _rules(_spec(_mm, 4, m=16, width=64), dcn=("data",))
    assert "TPU801" in ids
    msg = next(f.message for f in r.findings if f.rule == "TPU801")
    assert "us/step" in msg  # re-placement delta is priced
    _, ids = _rules(_spec(_mm, 4, m=16, width=64), dcn=("pipe",))
    assert not ids  # cut already on DCN: clean


def test_tpu802_stage_imbalance_names_worst_stage():
    r, ids = _rules(_spec(_mm, 4, m=16, stage_layers=(5, 1, 1, 1)))
    assert "TPU802" in ids
    msg = next(f.message for f in r.findings if f.rule == "TPU802")
    assert "stage 0" in msg
    _, ids = _rules(_spec(_mm, 4, m=16))
    assert "TPU802" not in ids


def test_tpu803_bubble_names_covering_microbatches():
    r, ids = _rules(_spec(_mm, 4, m=1))
    assert "TPU803" in ids
    m_cover = covering_microbatches(4, PIPE_BUBBLE_THRESHOLD)
    assert m_cover == 9
    msg = next(f.message for f in r.findings if f.rule == "TPU803")
    assert f"num_microbatches={m_cover}" in msg
    _, ids = _rules(_spec(_mm, 4, m=16))
    assert "TPU803" not in ids


def test_tpu804_collective_over_pipe_in_tick_body_is_error():
    def pipe_psum(p, h):
        return jax.lax.psum(h @ p, "pipe")

    r, ids = _rules(_spec(pipe_psum, 4, m=16))
    assert "TPU804" in ids
    assert not r.ok  # error severity: the strict gate
    r, ids = _rules(_spec(_mm, 4, m=16))
    assert "TPU804" not in ids and r.ok


def test_tpu805_stage_activations_over_budget():
    kw = dict(width=64, batch=4096)
    _, ids = _rules(_spec(_mm, 4, m=16, **kw), hbm_gb=0.0005)
    assert "TPU805" in ids
    _, ids = _rules(_spec(_mm, 4, m=16, remat=True, **kw), hbm_gb=0.0005)
    assert "TPU805" not in ids  # remat keeps stage boundaries only


def test_covering_microbatches_formula():
    for s in (2, 4, 8):
        m = covering_microbatches(s)
        assert (s - 1) / (m + s - 1) <= PIPE_BUBBLE_THRESHOLD
        if m > 1:
            assert (s - 1) / ((m - 1) + s - 1) > PIPE_BUBBLE_THRESHOLD
    assert covering_microbatches(1) == 1


# --------------------------------------------------------------------- #
# the wire-unit pin: costmodel prediction == compiled-HLO counters
# --------------------------------------------------------------------- #


def test_permute_and_scatter_wire_bytes_match_hlo():
    """``price_collective`` and the HLO counter must agree BYTE-EXACTLY
    on the real compiled pipeline program: the tick handoff
    (collective-permute) and the output reduction (reduce-scatter over
    ``pipe``) are both priced through the shared ring formulas."""
    from accelerate_tpu.parallel.pipeline import pipeline_apply
    from accelerate_tpu.telemetry.wire import hlo_wire_bytes

    s, m, width, batch = 4, 4, 16, 32
    mesh = _pipe_mesh(s)

    def step(p, xx):
        return pipeline_apply(_mm, p, xx, mesh=mesh, num_microbatches=m).sum()

    params = jax.ShapeDtypeStruct((8, width, width), jnp.float32)
    x = jax.ShapeDtypeStruct((batch, width), jnp.float32)
    hlo = jax.jit(step).lower(params, x).compile().as_text()
    measured = hlo_wire_bytes(hlo)
    sites = {st["prim"]: st for st in measured["sites"]}
    assert "ppermute" in sites and "reduce_scatter" in sites

    # tick handoff: one [B/data/M, W] f32 block crosses the pipe ring
    block_bytes = (batch // 2 // m) * width * 4
    predicted = price_collective("ppermute", ("pipe",), block_bytes, mesh)
    assert predicted.wire_bytes == sites["ppermute"]["wire_bytes"]
    assert sites["ppermute"]["result_bytes"] == block_bytes
    assert sites["ppermute"]["group_size"] == s

    # output reduction: the [M, k, B_blk, W] buffer reduce-scattered
    buf_bytes = m * (batch // 2 // m) * width * 4
    predicted = price_collective("psum_scatter", ("pipe",), buf_bytes, mesh)
    assert predicted.wire_bytes == sites["reduce_scatter"]["wire_bytes"]
    assert sites["reduce_scatter"]["group_size"] == s


# --------------------------------------------------------------------- #
# searchspace + tuner: the pipeline knobs close the loop
# --------------------------------------------------------------------- #


def test_searchspace_pipeline_knobs():
    from accelerate_tpu.analysis.searchspace import (
        ConfigPoint,
        SearchSpace,
        prune_reason,
    )

    p = ConfigPoint(mesh="pipe=4,data=2", num_microbatches=8, interleave=2, remat=True)
    assert p.has_pipeline_knobs
    assert p.pipeline_kwargs() == {"num_microbatches": 8, "interleave": 2, "remat": True}
    assert "mb=8" in p.label() and "interleave=2" in p.label() and "remat" in p.label()
    assert ConfigPoint.from_dict(p.as_dict()) == p
    assert prune_reason(p) is None
    # pipeline knobs without a pipe axis cannot run
    assert "pipe axis" in prune_reason(ConfigPoint(mesh="data=8", num_microbatches=8))
    assert "num_microbatches" in prune_reason(
        ConfigPoint(mesh="pipe=4,data=2", num_microbatches=0)
    )

    space = SearchSpace(
        meshes=("pipe=4,data=2",), microbatch_counts="2,8", remats=(False, True)
    )
    points = [p for p, reason in space.enumerate_points() if reason is None]
    assert len(points) == 4
    assert {pt.num_microbatches for pt in points} == {2, 8}
    assert SearchSpace.from_spec(
        {"meshes": ["pipe=4,data=2"], "microbatches": [2, 8], "remats": [False, True]}
    ).size() == 4


def test_tuner_scores_pipeline_knobs_with_bubble_model():
    """The loop the tentpole closes: enumerate num_microbatches, score
    each candidate with pipemodel's bubble-adjusted step time, and rank
    the full-bubble M=1 schedule last."""
    from accelerate_tpu.analysis.searchspace import SearchSpace
    from accelerate_tpu.analysis.tuner import tune
    from accelerate_tpu.parallel.pipeline import pipeline_apply

    width, batch = 16, 32

    def workload(point):
        mesh = MeshConfig(**point.mesh_shape).build()
        kw = point.pipeline_kwargs()

        def step(p, xx):
            return pipeline_apply(_mm, p, xx, mesh=mesh, **kw).sum()

        params = jax.ShapeDtypeStruct((8, width, width), jnp.float32)
        x = jax.ShapeDtypeStruct((batch, width), jnp.float32)
        return step, (params, x)

    workload.tune_factory = True
    space = SearchSpace(meshes=("pipe=4,data=2",), microbatch_counts=(1, 4, 16))
    report = tune(workload, space, generation="cpu")
    assert len(report.ranked) == 3
    assert all(c.bubble_fraction is not None for c in report.ranked)
    by_m = {c.point.num_microbatches: c for c in report.ranked}
    # the bubble model, not the serial roofline, must drive the ranking:
    # M=1 (75% bubble) is strictly slower than M=4 under pipemodel while
    # the serial roofline would call them equal-ish
    assert by_m[1].predicted_step_us > by_m[4].predicted_step_us
    assert by_m[1].bubble_fraction > by_m[4].bubble_fraction
    assert report.winner.point.num_microbatches != 1
    payload = report.winner.as_dict()
    assert "bubble_fraction" in payload


def test_accelerator_pipe_check_seeds_step_estimate():
    """``Accelerator.pipe_check`` hands the bubble-adjusted prediction to
    StepTelemetry as the static step estimate."""
    from accelerate_tpu import Accelerator

    acc = Accelerator()
    spec = _spec(_mm, 4, m=16)
    report = acc.pipe_check(spec)
    assert report.n_stages == 4
    assert report.ok


# --------------------------------------------------------------------- #
# the pipe selfcheck + CLI surface
# --------------------------------------------------------------------- #


def test_pipe_selfcheck_green():
    from accelerate_tpu.analysis.selfcheck import run_pipe_selfcheck

    ok, lines = run_pipe_selfcheck()
    assert ok, "\n".join(lines)
    assert sum("detected" in ln for ln in lines) == 5
    assert sum("clean twin: zero findings" in ln for ln in lines) == 5
    assert any("exact" in ln for ln in lines)


def test_cli_pipe_check_json():
    result = run_cli(
        "pipe-check",
        os.path.join(REPO, "examples", "by_feature", "pipe_check.py") + "::train_step",
        "--mesh", "pipe=4,data=2", "--generation", "cpu", "--format", "json",
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["schedule"] == {
        "n_stages": 4, "num_microbatches": 2, "interleave": 1,
        "remat": False, "ticks": 5,
    }
    assert any(f["rule"] == "TPU803" for f in doc["findings"])
    # warning severity: exit 0 non-strict, 1 under --strict
    strict = run_cli(
        "pipe-check",
        os.path.join(REPO, "examples", "by_feature", "pipe_check.py") + "::train_step",
        "--mesh", "pipe=4,data=2", "--generation", "cpu", "--strict",
    )
    assert strict.returncode == 1


def test_cli_pipe_check_sarif():
    result = run_cli(
        "pipe-check",
        os.path.join(REPO, "examples", "by_feature", "pipe_check.py") + "::train_step",
        "--mesh", "pipe=4,data=2", "--generation", "cpu", "--format", "sarif",
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["version"] == "2.1.0"
    assert "TPU803" in {r["ruleId"] for r in doc["runs"][0]["results"]}


@pytest.mark.slow
def test_cli_pipe_selfcheck():
    result = run_cli("pipe-check", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 5
    assert "exact" in result.stdout
