"""Accelerator end-to-end tests (reference analogue: tests/test_accelerator.py
+ test_utils/scripts/test_script.py training_check — distributed training
must match the single-device baseline)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn


def make_accelerator(**kwargs):
    return Accelerator(**kwargs)


def train_baseline(steps=8, lr=0.1, batch=16, accum=1):
    """Plain single-device optax loop for parity checking."""
    ds = RegressionDataset(length=64)
    params = {"a": np.float32(0.0), "b": np.float32(0.0)}
    tx = optax.sgd(lr)
    opt_state = tx.init(params)
    grad_buf = {"a": np.float32(0.0), "b": np.float32(0.0)}
    n = 0
    i = 0
    for s in range(steps):
        idx = np.arange(i, i + batch) % 64
        i += batch
        b = {"x": ds.x[idx], "y": ds.y[idx]}
        g = jax.grad(linear_loss_fn)(params, b)
        grad_buf = jax.tree_util.tree_map(lambda a, c: a + c / accum, grad_buf, g)
        n += 1
        if n % accum == 0:
            updates, opt_state = tx.update(grad_buf, opt_state, params)
            params = optax.apply_updates(params, updates)
            grad_buf = jax.tree_util.tree_map(lambda x: x * 0, grad_buf)
    return jax.tree_util.tree_map(np.asarray, params)


def run_fast_path(accelerator, steps=8, lr=0.1, accum=1):
    ds = RegressionDataset(length=64)
    model = accelerator.prepare_model(RegressionModel())
    optimizer = accelerator.prepare_optimizer(optax.sgd(lr))
    loader = accelerator.prepare_data_loader(ds)
    loader.batch_size = 16 // accelerator.num_data_shards if not accelerator.dataloader_config.split_batches else 16
    step = accelerator.build_train_step(linear_loss_fn)
    done = 0
    while done < steps:
        for batch in loader:
            step(batch)
            done += 1
            if done >= steps:
                break
    return jax.tree_util.tree_map(np.asarray, model.params)


def test_fast_path_matches_baseline_dp():
    acc = make_accelerator()
    params = run_fast_path(acc, steps=8)
    expected = train_baseline(steps=8)
    np.testing.assert_allclose(params["a"], expected["a"], rtol=1e-5)
    np.testing.assert_allclose(params["b"], expected["b"], rtol=1e-5)


def test_fast_path_matches_baseline_fsdp_mesh():
    acc = make_accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, fsdp=4)))
    params = run_fast_path(acc, steps=8)
    expected = train_baseline(steps=8)
    np.testing.assert_allclose(params["a"], expected["a"], rtol=1e-5)


def test_gradient_accumulation_fast_path():
    acc = make_accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=64)
    model = acc.prepare_model(RegressionModel())
    optimizer = acc.prepare_optimizer(optax.sgd(0.1))
    loader = acc.prepare_data_loader(ds)
    loader.batch_size = 16 // acc.num_data_shards
    step = acc.build_train_step(linear_loss_fn)
    for i, batch in enumerate(loader):
        step(batch)
        if i == 3:
            break
    expected = train_baseline(steps=4, accum=2)
    np.testing.assert_allclose(np.asarray(model.params["a"]), expected["a"], rtol=1e-5)


def test_imperative_path_matches_baseline():
    acc = make_accelerator()
    ds = RegressionDataset(length=64)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    loader.batch_size = 16 // acc.num_data_shards
    steps = 0
    while steps < 8:
        for batch in loader:
            with acc.accumulate(model):
                loss = acc.backward(linear_loss_fn, batch)
                optimizer.step()
                optimizer.zero_grad()
            steps += 1
            if steps >= 8:
                break
    expected = train_baseline(steps=8)
    np.testing.assert_allclose(np.asarray(model.params["a"]), expected["a"], rtol=1e-5)


def test_imperative_grad_accumulation_sync_flags():
    acc = make_accelerator(gradient_accumulation_steps=2)
    ds = RegressionDataset(length=64)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    loader.batch_size = 16 // acc.num_data_shards
    flags = []
    params_before = np.asarray(model.params["a"])
    for i, batch in enumerate(loader):
        with acc.accumulate(model):
            acc.backward(linear_loss_fn, batch)
            flags.append(acc.sync_gradients)
            optimizer.step()
        if i == 1:
            break
    # first micro-batch accumulates, second applies
    assert flags[0] in (False, True)
    assert np.asarray(model.params["a"]) != params_before


def test_clip_grad_norm_imperative():
    acc = make_accelerator()
    ds = RegressionDataset(length=64)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    batch = next(iter(loader))
    acc.backward(linear_loss_fn, batch)
    norm = acc.clip_grad_norm_(max_norm=0.01)
    assert float(norm) > 0
    # buffer now has norm <= 0.01 (plus epsilon slack)
    from accelerate_tpu.accelerator import optax_global_norm

    _, buf = acc._buffer_for(model)
    assert float(optax_global_norm(buf)) <= 0.0101


def test_prepare_idempotent_and_order_preserved():
    acc = make_accelerator()
    ds = RegressionDataset(length=32)
    model = RegressionModel()
    sched = optax.linear_schedule(0.1, 0.0, 100)
    m, opt, dl, sc = acc.prepare(model, optax.sgd(0.1), ds, sched)
    assert m is acc.prepare(m)
    assert opt.opt_state is not None
    from accelerate_tpu.scheduler import AcceleratedScheduler

    assert isinstance(sc, AcceleratedScheduler)


def test_gather_for_metrics_truncates_padding():
    acc = make_accelerator()
    ds = RegressionDataset(length=20)  # global batch 16 -> last batch padded
    loader = acc.prepare_data_loader(ds)
    loader.batch_size = 2
    seen = []
    for batch in loader:
        out = acc.gather_for_metrics(batch["x"])
        seen.extend(np.asarray(out).ravel().tolist())
    assert len(seen) == 20


def test_mixed_precision_bf16_computes():
    acc = make_accelerator(mixed_precision="bf16")
    ds = RegressionDataset(length=32)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    loader.batch_size = 16 // acc.num_data_shards
    step = acc.build_train_step(linear_loss_fn)
    loss = step(next(iter(loader)))
    assert jnp.isfinite(loss)
    # master params stay fp32
    assert model.params["a"].dtype == jnp.float32


def test_trigger_roundtrip():
    acc = make_accelerator()
    assert not acc.check_trigger()
    acc.set_trigger()
    assert acc.check_trigger()
    assert not acc.check_trigger()


def test_accumulate_syncs_on_end_of_dataloader():
    acc = make_accelerator(gradient_accumulation_steps=4)
    ds = RegressionDataset(length=32)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    loader.batch_size = 16 // acc.num_data_shards  # 2 batches/epoch, accum 4
    syncs = []
    for batch in loader:
        with acc.accumulate(model):
            acc.backward(linear_loss_fn, batch)
            syncs.append(acc.sync_gradients)
    # end of dataloader forces a sync even mid-accumulation window
    assert syncs[-1] is True


def test_fast_path_syncs_at_end_of_dataloader():
    """Regression: with accum=4 and 2 batches/epoch, the epoch tail must
    still apply an update (sync_with_dataloader semantics)."""
    acc = make_accelerator(gradient_accumulation_steps=4)
    ds = RegressionDataset(length=32)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.sgd(0.1), ds)
    loader.batch_size = 16 // acc.num_data_shards  # 2 batches per epoch
    step = acc.build_train_step(linear_loss_fn)
    a0 = float(model.params["a"])
    for batch in loader:
        step(batch)
    assert float(model.params["a"]) != a0  # update applied at epoch end


def test_clip_grad_norm_fast_path_after_build():
    """Reference-shaped loop: clip_grad_norm_ called *inside* the loop,
    after build_train_step, must actually cap the applied gradient (the
    round-1 footgun — the norm is now a traced step input)."""
    acc = make_accelerator()
    ds = RegressionDataset(length=64)
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(1.0))
    loader = acc.prepare_data_loader(ds)
    step = acc.build_train_step(linear_loss_fn)  # built BEFORE any clip call
    batch = next(iter(loader))

    # unclipped step moves params by the raw gradient
    a0 = float(np.asarray(model.params["a"]))
    step(batch)
    raw_delta = abs(float(np.asarray(model.params["a"])) - a0)
    assert float(acc._last_grad_norm) > 1e-3

    # now clip inside the loop to a tiny norm: the very next step's update
    # magnitude must shrink to ~max_norm (sgd lr=1 → |delta| ≈ |grad|)
    acc.clip_grad_norm_(max_norm=1e-4)
    a1 = float(np.asarray(model.params["a"]))
    step(batch)
    clipped_delta = abs(float(np.asarray(model.params["a"])) - a1)
    assert clipped_delta <= 1.2e-4, (raw_delta, clipped_delta)
    assert clipped_delta < raw_delta


def test_clip_grad_norm_zero_freezes_step():
    """max_norm=0.0 scales gradients to zero (torch semantics), it does NOT
    disable clipping."""
    acc = make_accelerator()
    ds = RegressionDataset(length=64)
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(1.0))
    loader = acc.prepare_data_loader(ds)
    step = acc.build_train_step(linear_loss_fn)
    batch = next(iter(loader))
    acc.clip_grad_norm_(max_norm=0.0)
    a0 = float(np.asarray(model.params["a"]))
    step(batch)
    assert float(np.asarray(model.params["a"])) == pytest.approx(a0, abs=1e-12)


def test_loss_fn_optional_rng_gets_per_step_key():
    """A loss whose ``rng`` parameter is keyword-with-default (the
    functools.partial(bert_classification_loss, apply_fn=...) shape) still
    receives the per-step key — dropout must not silently turn off."""
    import functools

    import optax

    from accelerate_tpu.test_utils import RegressionDataset, RegressionModel

    seen_rngs = []

    def loss_with_optional_rng(params, batch, apply_fn=None, rng=None):
        assert rng is not None, "per-step rng was not delivered"
        pred = apply_fn(params, batch["x"])
        return ((pred - batch["y"]) ** 2).mean()

    acc = Accelerator()
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(functools.partial(loss_with_optional_rng, apply_fn=model.apply_fn))
    ds = RegressionDataset(length=16)
    batch = {"x": ds.x, "y": ds.y}
    loss = step(batch)
    assert np.isfinite(float(loss))


def test_build_eval_step_applies_dtype_policy():
    """build_eval_step must run under the accelerator's compute dtype, not
    raw fp32 params."""
    import optax

    acc = Accelerator(mixed_precision="bf16")
    seen = {}

    def apply_fn(p, x):
        seen["dtype"] = p["w"].dtype
        return x @ p["w"]

    from accelerate_tpu.modeling import Model

    model = acc.prepare_model(Model(apply_fn, {"w": np.eye(4, dtype=np.float32)}))
    acc.prepare_optimizer(optax.sgd(0.1))
    eval_step = acc.build_eval_step(apply_fn)
    out = eval_step(np.ones((2, 4), np.float32))
    assert str(seen["dtype"]) == "bfloat16", seen


def test_fp16_scale_lives_on_device_and_backs_off():
    """The fast path keeps the dynamic loss scale as a carried device array:
    an overflow batch halves it ON DEVICE, the update is skipped (params
    unmoved), and step_was_skipped is a device value coerced only on read."""
    acc = Accelerator(mixed_precision="fp16")
    model = acc.prepare_model(RegressionModel())
    opt = acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(linear_loss_fn)
    ds = RegressionDataset(length=16)
    good = {"x": ds.x, "y": ds.y}
    step(good)
    params_before = jax.tree_util.tree_map(np.asarray, model.params)

    bad = {"x": ds.x, "y": np.full_like(ds.y, np.float16(1e30))}  # overflow grads
    step(bad)
    # lazy device value: stored as a jax array, coerced by the property
    assert not isinstance(opt._step_was_skipped, bool)
    assert opt.step_was_skipped is True
    for k, v in model.params.items():
        np.testing.assert_array_equal(np.asarray(v), params_before[k])

    # a later good step proceeds (scale backed off, update applies again)
    step(good)
    assert opt.step_was_skipped is False
