"""Tracker adapters against REAL SDKs (no mocks).

Round-3 verdict: every adapter had only ever run against recorder mocks,
so lifecycle bugs (arg names, finish semantics) would ship silently.
These tests execute the real third-party packages end to end and assert
on the artifacts they write:

* TensorBoard is baked into this image — its test always runs and reads
  the event file back with the real EventAccumulator.
* WandB (offline mode) and MLflow (file store) aren't installable here
  (zero-egress image); their tests are importorskip-gated so any
  environment that has the SDK runs the full real lifecycle.
"""

from __future__ import annotations

import os

import pytest

from accelerate_tpu import Accelerator


@pytest.mark.slow
def test_real_tensorboard_lifecycle(tmp_path):
    acc = Accelerator(log_with="tensorboard", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1, "layers": 2})
    acc.log({"loss": 1.5}, step=0)
    acc.log({"loss": 0.5, "note": "hello"}, step=1)
    acc.end_training()

    run_dir = os.path.join(str(tmp_path), "run1")
    event_files = [
        os.path.join(root, f)
        for root, _, files in os.walk(run_dir)
        for f in files
        if "tfevents" in f
    ]
    assert event_files, f"no event files under {run_dir}"

    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    scalars = {}
    for ef in event_files:
        accum = EventAccumulator(os.path.dirname(ef))
        accum.Reload()
        for tag in accum.Tags().get("scalars", []):
            scalars.setdefault(tag, []).extend(
                (e.step, e.value) for e in accum.Scalars(tag)
            )
    assert "loss" in scalars, scalars.keys()
    assert sorted(scalars["loss"]) == [(0, 1.5), (1, 0.5)], scalars["loss"]


@pytest.mark.slow
def test_real_wandb_offline_lifecycle(tmp_path, monkeypatch):
    wandb = pytest.importorskip("wandb")
    monkeypatch.setenv("WANDB_MODE", "offline")
    monkeypatch.setenv("WANDB_DIR", str(tmp_path))

    acc = Accelerator(log_with="wandb")
    acc.init_trackers(
        "proj", config={"lr": 0.1},
        init_kwargs={"wandb": {"mode": "offline", "dir": str(tmp_path)}},
    )
    run = acc.get_tracker("wandb", unwrap=True)
    assert run is not None and run.settings.mode == "offline"
    assert dict(run.config).get("lr") == 0.1  # offline restart baked the config in
    acc.log({"loss": 2.0}, step=0)
    acc.end_training()

    offline_runs = [
        d for d in os.listdir(os.path.join(str(tmp_path), "wandb"))
        if d.startswith("offline-run")
    ]
    assert offline_runs, os.listdir(str(tmp_path))


@pytest.mark.slow
def test_real_mlflow_file_store_lifecycle(tmp_path):
    mlflow = pytest.importorskip("mlflow")

    acc = Accelerator(log_with="mlflow")
    acc.init_trackers(
        "run-mlflow", config={"lr": 0.1},
        init_kwargs={"mlflow": {"logging_dir": str(tmp_path), "experiment_name": "exp1"}},
    )
    acc.log({"loss": 3.0}, step=0)
    acc.log({"loss": 1.0}, step=1)
    acc.end_training()

    client = mlflow.tracking.MlflowClient(tracking_uri="file://" + str(tmp_path))
    exp = client.get_experiment_by_name("exp1")
    assert exp is not None
    runs = client.search_runs([exp.experiment_id])
    assert runs and runs[0].data.params.get("lr") == "0.1"
    history = client.get_metric_history(runs[0].info.run_id, "loss")
    assert sorted((m.step, m.value) for m in history) == [(0, 3.0), (1, 1.0)]
