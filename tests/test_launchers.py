"""notebook_launcher / debug_launcher / tpu-config tests
(reference analogue: test_utils/scripts/test_notebook.py + tests/test_cli.py
tpu-config section)."""

import subprocess
import sys

from accelerate_tpu import debug_launcher, notebook_launcher


def _train_fn(expected_procs):
    from accelerate_tpu import Accelerator

    acc = Accelerator()
    assert acc.num_processes == expected_procs, acc.num_processes
    return "ok"


def test_notebook_launcher_in_process():
    # single-process path: runs fn inline and returns its value
    result = notebook_launcher(_train_fn, (1,), num_processes=1)
    assert result == "ok"


def test_notebook_launcher_rejects_live_state():
    from accelerate_tpu import Accelerator

    Accelerator()
    try:
        notebook_launcher(_train_fn, (1,), num_processes=1)
        raised = False
    except ValueError:
        raised = True
    assert raised


def test_debug_launcher():
    assert debug_launcher(_train_fn, (1,), num_processes=2) == "ok"


def test_debug_launcher_rejects_incompatible_live_backend():
    # The suite's shared backend is an 8-device CPU mesh; asking for more
    # devices than the live topology provides must raise, not silently
    # degrade (VERDICT r4 weak #5; reference launchers.py:165-257 pre-flight).
    import jax

    n_live = len(jax.devices())
    import pytest

    with pytest.raises(RuntimeError, match="fake mesh cannot be applied"):
        debug_launcher(_train_fn, (n_live + 1,), num_processes=n_live + 1)


def test_tpu_config_debug_print():
    result = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.commands.cli", "tpu-config",
            "--hosts", "h1,h2", "--command", "echo hello", "--command", "echo world",
            "--debug",
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert result.returncode == 0, result.stderr
    assert result.stdout.count("Running: ssh") == 2
    assert "echo hello; echo world" in result.stdout


def test_tpu_config_gcloud_debug_print():
    result = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.commands.cli", "tpu-config",
            "--tpu_name", "mypod", "--tpu_zone", "us-central2-b",
            "--command", "pip list", "--install_accelerate", "--debug",
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert result.returncode == 0, result.stderr
    assert "gcloud compute tpus tpu-vm ssh mypod" in result.stdout
    assert "--worker all" in result.stdout
    assert "pip install -e ." in result.stdout


def _crashing_fn():
    raise AssertionError("worker crash")


def test_notebook_launcher_worker_crash_raises_not_hangs():
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        notebook_launcher(_crashing_fn, (), num_processes=2, use_port="29631")
        raised = False
    except RuntimeError as e:
        raised = "nonzero" in str(e)
    assert raised
