"""Big-model stack tests (reference analogue: tests/test_big_modeling.py,
1099 LoC — dispatch/offload with tiny models; tests/test_offload.py)."""

import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    DispatchedParams,
    StreamedExecutor,
    abstract_params,
    compute_module_sizes,
    dispatch_model,
    get_balanced_memory,
    infer_auto_device_map,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    load_checkpoint_in_model,
)
from accelerate_tpu.modeling import Model
from accelerate_tpu.utils.offload import OffloadedWeightsLoader, offload_state_dict


def tiny_flat():
    return {
        "layer_0/w": np.ones((64, 64), np.float32),  # 16 KB
        "layer_0/b": np.ones((64,), np.float32),
        "layer_1/w": np.ones((64, 64), np.float32),
        "layer_1/b": np.ones((64,), np.float32),
        "head/w": np.ones((64, 8), np.float32),
    }


def nested(flat):
    out = {}
    for k, v in flat.items():
        a, b = k.split("/")
        out.setdefault(a, {})[b] = v
    return out


def test_abstract_params_is_memoryless():
    import jax.numpy as jnp

    def init():
        return {"w": jnp.zeros((10_000, 10_000))}  # 400 MB if real

    abstract = abstract_params(init)
    assert abstract["w"].shape == (10_000, 10_000)
    assert not hasattr(abstract["w"], "addressable_shards")  # ShapeDtypeStruct


def test_init_empty_weights_ctx():
    import jax.numpy as jnp

    with init_empty_weights() as empty:
        abstract = empty(lambda: {"w": jnp.zeros((4, 4))})
    assert abstract["w"].shape == (4, 4)


def test_compute_module_sizes():
    sizes = compute_module_sizes(nested(tiny_flat()), prefix_depth=1)
    assert sizes["layer_0"] == 64 * 64 * 4 + 64 * 4
    assert sizes["head"] == 64 * 8 * 4


def test_infer_auto_device_map_tiers():
    params = nested(tiny_flat())
    # budget fits exactly one layer on device 0, one on cpu, rest disk
    layer_bytes = 64 * 64 * 4 + 64 * 4
    dm = infer_auto_device_map(params, max_memory={0: layer_bytes, "cpu": layer_bytes}, prefix_depth=1)
    assert dm["layer_0"] == 0
    assert dm["layer_1"] == "cpu"
    assert dm["head"] == "disk"


def test_infer_auto_device_map_tied_groups():
    params = nested(tiny_flat())
    dm = infer_auto_device_map(
        params, max_memory={0: 10**9}, prefix_depth=1, tied_groups=[["layer_0", "head"]]
    )
    assert dm["head"] == dm["layer_0"]


def test_dispatched_params_tiers(tmp_path):
    flat = tiny_flat()
    dm = {"layer_0": 0, "layer_1": "cpu", "head": "disk"}
    dp = DispatchedParams(flat, dm, offload_dir=str(tmp_path / "offload"))
    import jax

    assert isinstance(dp["layer_0/w"], jax.Array)
    assert isinstance(dp["layer_1/w"], np.ndarray)
    head = dp["head/w"]
    np.testing.assert_array_equal(np.asarray(head), flat["head/w"])
    assert set(dp.keys()) == set(flat.keys())


def test_streamed_executor_double_buffer():
    import jax.numpy as jnp

    layers = [{"w": np.full((4, 4), float(i + 1), np.float32)} for i in range(3)]

    def layer_fn(params, x, i):
        return x @ params["w"]

    ex = StreamedExecutor(layers, layer_fn, jit=False)
    out = ex(jnp.ones((2, 4)))
    expected = np.ones((2, 4)) @ layers[0]["w"] @ layers[1]["w"] @ layers[2]["w"]
    np.testing.assert_allclose(np.asarray(out), expected)


def test_offloaded_weights_loader_roundtrip(tmp_path):
    state = {"a": np.arange(6.0).reshape(2, 3), "s": np.float32(7)}
    offload_state_dict(str(tmp_path), state)
    loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
    np.testing.assert_array_equal(np.asarray(loader["a"]), state["a"])
    assert float(loader["s"]) == 7
    assert len(loader) == 2


def test_load_checkpoint_and_dispatch(tmp_path):
    from accelerate_tpu.checkpointing import save_model

    flat = tiny_flat()
    model = Model(lambda p, x: x, nested(flat))
    save_model(model, str(tmp_path / "export"))

    fresh = Model(lambda p, x: x, nested({k: np.zeros_like(v) for k, v in flat.items()}))
    dispatched = load_checkpoint_and_dispatch(
        fresh,
        str(tmp_path / "export"),
        device_map={"layer_0": 0, "layer_1": "cpu", "head": "cpu"},
    )
    np.testing.assert_array_equal(np.asarray(dispatched.dispatched_params["head/w"]), flat["head/w"])


def test_load_checkpoint_missing_key_raises(tmp_path):
    from accelerate_tpu.checkpointing import save_model

    model = Model(lambda p, x: x, {"a": {"w": np.ones(4, np.float32)}})
    save_model(model, str(tmp_path / "export"))
    with pytest.raises(KeyError):
        load_checkpoint_in_model({"a/w": None, "b/missing": None}, str(tmp_path / "export"))


# ---------------------------------------------------------------------- #
# device-map inference edge cases (reference: tests/test_modeling_utils.py,
# 1067 LoC of infer_auto_device_map/module-size/tied-param math)
# ---------------------------------------------------------------------- #


def _params(sizes: dict):
    """{'group/leaf': n_float32} -> pytree with those leaf sizes."""
    tree = {}
    for path, n in sizes.items():
        node = tree
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = np.zeros(n, np.float32)
    return tree


def test_device_map_spill_order_is_device_then_cpu_then_disk():
    params = _params({"a/w": 100, "b/w": 100, "c/w": 100, "d/w": 100})
    nbytes = 100 * 4
    dm = infer_auto_device_map(params, max_memory={0: nbytes, 1: nbytes, "cpu": nbytes}, prefix_depth=1)
    assert dm == {"a": 0, "b": 1, "c": "cpu", "d": "disk"}


def test_device_map_greedy_no_backtracking():
    """The greedy cursor never returns to an earlier tier — the reference's
    behavior (utils/modeling.py:1294): a big block can strand space."""
    params = _params({"a/w": 60, "b/w": 100, "c/w": 30})
    dm = infer_auto_device_map(params, max_memory={0: 100 * 4, "cpu": 100 * 4}, prefix_depth=1)
    # b (400B) does not fit dev0's remaining 160B -> cpu (which it fills);
    # c COULD fit dev0's leftover but the cursor moved on (greedy,
    # matching the reference) -> disk
    assert dm["a"] == 0 and dm["b"] == "cpu" and dm["c"] == "disk"


def test_device_map_tied_groups_forced_together():
    params = _params({"embed/w": 100, "mid/w": 100, "head/w": 100})
    dm = infer_auto_device_map(
        params,
        max_memory={0: 150 * 4, "cpu": 1000 * 4},
        tied_groups=[["embed", "head"]],
        prefix_depth=1,
    )
    assert dm["head"] == dm["embed"]


def test_device_map_zero_budget_all_spills():
    params = _params({"a/w": 10, "b/w": 10})
    dm = infer_auto_device_map(params, max_memory={0: 0, "cpu": 0}, prefix_depth=1)
    assert set(dm.values()) == {"disk"}


def test_balanced_memory_floors_at_largest_group():
    params = _params({"embed/w": 1000, "l0/w": 10, "l1/w": 10})
    budgets = get_balanced_memory(params, num_devices=4, prefix_depth=1)
    # naive total/4 would be ~1020B; the floor must cover the 4000B embed
    assert all(v >= 1000 * 4 for v in budgets.values())
    dm = infer_auto_device_map(params, max_memory=budgets, prefix_depth=1)
    assert all(isinstance(v, int) for v in dm.values()), dm


def test_compute_module_sizes_prefix_depth():
    params = _params({"enc/l0/w": 4, "enc/l1/w": 4, "dec/l0/w": 4})
    s1 = compute_module_sizes(params, prefix_depth=1)
    assert s1 == {"enc": 32, "dec": 16}
    s2 = compute_module_sizes(params, prefix_depth=2)
    assert s2 == {"enc/l0": 16, "enc/l1": 16, "dec/l0": 16}


# ---------------------------------------------------------------------- #
# depth expansion (reference: test_modeling_utils.py device-map/size
# corners, test_offload.py, test_hooks.py streaming semantics)
# ---------------------------------------------------------------------- #


def test_parse_size_units_matrix():
    from accelerate_tpu.big_modeling import _parse_size

    assert _parse_size(1024) == 1024
    assert _parse_size("1KB") == 10**3
    assert _parse_size("1KiB") == 2**10
    assert _parse_size("2.5GB") == int(2.5 * 10**9)
    assert _parse_size("2.5GiB") == int(2.5 * 2**30)
    assert _parse_size(" 3 MiB ") == 3 * 2**20
    assert _parse_size("4tb") == 4 * 10**12
    for bad in ("x", "12XB", "GB1", ""):
        with pytest.raises(ValueError):
            _parse_size(bad)


def test_get_max_memory_explicit_budgets_parse():
    from accelerate_tpu.big_modeling import get_max_memory

    out = get_max_memory({0: "1GiB", 1: 500, "cpu": "2GB"})
    assert out == {0: 2**30, 1: 500, "cpu": 2 * 10**9}


def test_get_max_memory_probes_devices():
    from accelerate_tpu.big_modeling import get_max_memory

    out = get_max_memory()
    assert "cpu" in out and out["cpu"] > 0
    assert all(v > 0 for v in out.values())


def test_module_sizes_respect_dtype_and_definition_order():
    flat = {
        "z_first/w": np.ones((4, 4), np.float16),  # 32 B despite z-name
        "a_second/w": np.ones((4, 4), np.float32),  # 64 B
    }
    sizes = compute_module_sizes(nested(flat))
    assert list(sizes) == ["z_first", "a_second"]  # definition order, not sorted
    assert sizes["z_first"] == 32 and sizes["a_second"] == 64


def test_dispatched_params_longest_prefix_wins_and_keyerror():
    flat = tiny_flat()
    dm = {"layer_0": 0, "layer_0/b": "cpu", "layer_1": 0, "head": 0}
    dp = DispatchedParams(flat, dm)
    assert "layer_0/b" in dp.flat_host  # the more specific rule won
    assert "layer_0/w" in dp.flat_device
    assert sorted(dp.keys()) == sorted(flat)
    with pytest.raises(KeyError):
        dp["nonexistent/w"]


def test_dispatched_params_disk_requires_offload_dir():
    with pytest.raises(ValueError, match="offload_dir"):
        DispatchedParams(tiny_flat(), {"layer_0": "disk", "layer_1": 0, "head": 0})


def test_streamed_executor_empty_and_unjitted():
    ex = StreamedExecutor([], lambda w, c, i: c + 1, jit=False)
    assert ex(5) == 5  # zero layers: carry passes through untouched
    layers = [{"w": np.full((4, 4), float(i + 1), np.float32)} for i in range(3)]
    ex = StreamedExecutor(layers, lambda w, c, i: c @ w["w"], jit=False)
    out = np.asarray(ex(np.eye(4, dtype=np.float32)))
    ref = np.eye(4, dtype=np.float32)
    for l in layers:
        ref = ref @ l["w"]
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_streamed_executor_matches_direct_chain():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    layers = [{"w": rng.standard_normal((8, 8)).astype(np.float32) * 0.3} for _ in range(4)]
    ex = StreamedExecutor(layers, lambda w, c, i: jnp.tanh(c @ w["w"]))
    x = rng.standard_normal((2, 8)).astype(np.float32)
    got = np.asarray(ex(jnp.asarray(x)))
    ref = x
    for l in layers:
        ref = np.tanh(ref @ l["w"])
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_load_checkpoint_sharded_index(tmp_path):
    """Shard-index loading: weights spread over two safetensors shards with
    a weight_map index (the HF multi-file checkpoint layout)."""
    from safetensors.numpy import save_file

    flat = tiny_flat()
    keys = sorted(flat)
    shard_a = {k: flat[k] for k in keys[:3]}
    shard_b = {k: flat[k] for k in keys[3:]}
    save_file(shard_a, str(tmp_path / "model-00001-of-00002.safetensors"))
    save_file(shard_b, str(tmp_path / "model-00002-of-00002.safetensors"))
    index = {"weight_map": {k: "model-00001-of-00002.safetensors" for k in shard_a}}
    index["weight_map"].update({k: "model-00002-of-00002.safetensors" for k in shard_b})
    import json

    (tmp_path / "model.safetensors.index.json").write_text(json.dumps(index))

    state = load_checkpoint_in_model({k: None for k in flat}, str(tmp_path))
    assert sorted(state) == keys
    np.testing.assert_array_equal(state["head/w"], flat["head/w"])


def test_load_checkpoint_directory_without_index(tmp_path):
    from safetensors.numpy import save_file

    flat = tiny_flat()
    save_file(flat, str(tmp_path / "model.safetensors"))
    state = load_checkpoint_in_model({k: None for k in flat}, str(tmp_path))
    assert sorted(state) == sorted(flat)


def test_load_checkpoint_and_dispatch_balanced(tmp_path):
    from safetensors.numpy import save_file

    flat = tiny_flat()
    save_file(flat, str(tmp_path / "model.safetensors"))
    model = Model(lambda p, x: x, nested(flat))
    out = load_checkpoint_and_dispatch(model, str(tmp_path / "model.safetensors"), device_map="balanced")
    assert out.device_map  # every group placed
    assert set(out.device_map.values()) <= set(range(8)) | {"cpu", "disk"}
    # balanced: nothing should have spilled to disk for a tiny model
    assert "disk" not in out.device_map.values()


def test_streamed_generate_through_dispatched_layers():
    """End-to-end: a layer-streamed forward over host-resident weights
    computes the same logits as the fully device-resident model (the
    AlignDevicesHook 'model bigger than HBM' scenario)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n_layers, width = 3, 16
    layers = [
        {"w": rng.standard_normal((width, width)).astype(np.float32) * 0.2,
         "b": rng.standard_normal((width,)).astype(np.float32) * 0.1}
        for _ in range(n_layers)
    ]
    x = rng.standard_normal((4, width)).astype(np.float32)

    def layer_fn(w, c, i):
        return jnp.tanh(c @ w["w"] + w["b"])

    streamed = np.asarray(StreamedExecutor(layers, layer_fn)(jnp.asarray(x)))
    resident = jnp.asarray(x)
    for i, w in enumerate(layers):
        resident = layer_fn(jax.device_put(w), resident, i)
    np.testing.assert_allclose(streamed, np.asarray(resident), atol=1e-6)
