"""Static performance analyzer (``analysis.perfmodel`` +
``analysis.perf_rules``): roofline math against hand-computed
FLOPs/bytes, the TPU501-505 rules with their clean twins, the
``perf_model_drift`` telemetry cross-check, and the CLI surfaces
(text/json/sarif/selfcheck/baseline-diff)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from accelerate_tpu.analysis.costmodel import (
    BANDWIDTH_TABLE,
    HBM_BW_TABLE,
    PEAK_FLOPS_TABLE,
    device_generation,
    hbm_bandwidth,
    peak_flops,
)
from accelerate_tpu.analysis.perfmodel import PerfReport, perf_check
from accelerate_tpu.parallel.mesh import MeshConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(report: PerfReport):
    return sorted({f.rule for f in report.findings})


@pytest.fixture
def mesh1():
    return MeshConfig(data=1).build(jax.devices()[:1])


# --------------------------------------------------------------------- #
# cost tables: v6e + explicit cpu rows (deterministic host backend)
# --------------------------------------------------------------------- #


def test_tables_have_v6e_and_cpu_rows():
    for table in (BANDWIDTH_TABLE, PEAK_FLOPS_TABLE, HBM_BW_TABLE):
        assert "v6e" in table and "cpu" in table
    # the cpu row is explicit, not a silent v5e alias
    assert peak_flops("cpu") == 1e12
    assert hbm_bandwidth("cpu") == 100e9
    assert peak_flops("cpu") != peak_flops("v5e")
    # unknown generations still fall back to the conservative v5e row
    assert peak_flops("weird-future-chip") == peak_flops("v5e")


def test_device_generation_maps_cpu_backend():
    # the suite runs under JAX_PLATFORMS=cpu, so the attached device kind
    # must resolve to the explicit cpu row
    assert device_generation() == "cpu"
    assert device_generation(jax.devices()[0]) == "cpu"


# --------------------------------------------------------------------- #
# roofline math (hand-computed reference)
# --------------------------------------------------------------------- #


def test_matmul_over_mesh_exact_flops_bytes_wire(mesh8):
    """The acceptance-criterion fixture: FLOPs, HBM bytes, and psum wire
    bytes must match hand computation EXACTLY."""
    M, K, N = 64, 256, 128

    def ref_step(x, w):
        return jax.lax.psum(x @ w, "data")

    report = perf_check(
        ref_step,
        jax.ShapeDtypeStruct((M, K), jnp.float32),
        jax.ShapeDtypeStruct((K, N), jnp.float32),
        mesh=mesh8,
        generation="v5e",
    )
    [dot] = [o for o in report.ops if o.primitive == "dot_general"]
    [psum] = [o for o in report.ops if o.primitive == "psum"]
    assert dot.flops == 2 * M * K * N
    assert dot.hbm_bytes == (M * K + K * N + M * N) * 4
    assert psum.wire_bytes == int(M * N * 4 * 2 * 7 / 8)  # ring all-reduce
    assert psum.transport == "ici"
    assert report.total_flops == dot.flops
    assert report.predicted_step_ms > 0
    assert 0 < report.mfu_upper_bound <= 1
    assert not report.findings


def test_roofline_bound_classification(mesh1):
    """A big square matmul is compute-bound; a matvec is memory-bound."""

    def big(x, w):
        return x @ w

    sq = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)
    r = perf_check(big, sq, sq, mesh=mesh1, generation="v5e")
    [dot] = [o for o in r.ops if o.primitive == "dot_general"]
    assert dot.bound == "compute"

    vec = jax.ShapeDtypeStruct((1, 1024), jnp.float32)
    r = perf_check(big, vec, sq, mesh=mesh1, generation="v5e")
    [dot] = [o for o in r.ops if o.primitive == "dot_general"]
    assert dot.bound == "memory"


def test_scan_multiplies_op_counts(mesh1):
    def looped(x):
        def body(c, _):
            return jnp.tanh(c @ c), None

        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    r = perf_check(looped, jax.ShapeDtypeStruct((64, 64), jnp.float32), mesh=mesh1)
    dots = [o for o in r.ops if o.primitive == "dot_general"]
    assert dots and all(o.count == 5 for o in dots)
    assert dots[0].flops == 5 * 2 * 64**3


def test_sharded_output_divides_per_device_flops(mesh8):
    """A batch-sharded matmul parallelises over the data axis: per-device
    FLOPs are 1/8 of the global count."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    def step(x, w):
        return x @ w

    x = jax.device_put(np.zeros((64, 32), np.float32), NamedSharding(mesh8, P("data")))
    w = jax.ShapeDtypeStruct((32, 16), jnp.float32)
    r = perf_check(step, x, w, mesh=mesh8)
    [dot] = [o for o in r.ops if o.primitive == "dot_general"]
    assert dot.flops == 2 * 64 * 32 * 16 // 8


def test_report_dict_and_text_surfaces(mesh8):
    def step(x, w):
        return jax.lax.psum(x @ w, "data")

    r = perf_check(
        step,
        jax.ShapeDtypeStruct((64, 256), jnp.float32),
        jax.ShapeDtypeStruct((256, 128), jnp.float32),
        mesh=mesh8,
        generation="v6e",
    )
    d = r.as_dict()
    assert d["generation"] == "v6e"
    assert d["totals"]["flops_per_device"] == r.total_flops
    assert d["totals"]["predicted_step_ms"] == pytest.approx(r.predicted_step_ms, abs=1e-4)
    assert d["totals"]["wire_bytes_by_transport"]["ici"] > 0
    assert len(d["ops"]) == len(r.ops)
    text = r.render_text()
    assert "MFU upper bound" in text and "v6e roofline" in text
    by_bound = r.time_by_bound()
    assert by_bound["comms"] > 0


# --------------------------------------------------------------------- #
# TPU501-505: defect fires, clean twin silent
# --------------------------------------------------------------------- #


def test_tpu501_misaligned_matmul_and_clean_twin(mesh1):
    def step(x, w):
        return x @ w

    bad = perf_check(
        step,
        jax.ShapeDtypeStruct((256, 100), jnp.float32),
        jax.ShapeDtypeStruct((100, 512), jnp.float32),
        mesh=mesh1,
    )
    assert "TPU501" in _rules(bad)
    [f] = [f for f in bad.findings if f.rule == "TPU501"]
    assert "21.9%" in f.message  # waste is priced: 1 - 100/128
    assert "128" in f.message  # the covering bucket is named

    clean = perf_check(
        step,
        jax.ShapeDtypeStruct((256, 128), jnp.float32),
        jax.ShapeDtypeStruct((128, 512), jnp.float32),
        mesh=mesh1,
    )
    assert clean.findings == []


def test_tpu501_memory_bound_matvec_sublane_not_flagged(mesh1):
    """Decode-style matvec (M=1) is memory-bound: sublane padding costs
    nothing there, so a lane-aligned matvec must stay clean."""

    def step(x, w):
        return x @ w

    r = perf_check(
        step,
        jax.ShapeDtypeStruct((1, 1024), jnp.float32),
        jax.ShapeDtypeStruct((1024, 512), jnp.float32),
        mesh=mesh1,
    )
    assert "TPU501" not in _rules(r)


def test_tpu502_redundant_collective_and_clean_twin(mesh8):
    def bad_step(x):
        g = jax.lax.psum(x, "data")
        return jax.lax.psum(g * 0.5, "data")  # uniformity survives the scale

    x = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    bad = perf_check(bad_step, x, mesh=mesh8)
    assert "TPU502" in _rules(bad)
    assert any(f.is_error for f in bad.findings)  # the strict-gate rule

    def clean_step(x, y):
        # two reduces of DIFFERENT values: nothing redundant
        return jax.lax.psum(x, "data"), jax.lax.pmax(y, "data")

    clean = perf_check(clean_step, x, x, mesh=mesh8)
    assert clean.findings == []


def test_tpu502_mixed_operand_breaks_uniformity(mesh8):
    """f(uniform, sharded) is not uniform — re-reducing it is legitimate
    and must NOT fire."""

    def step(x, y):
        g = jax.lax.psum(x, "data")
        return jax.lax.psum(g * y, "data")  # y differs per shard

    x = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    r = perf_check(step, x, x, mesh=mesh8)
    assert "TPU502" not in _rules(r)


def test_tpu503_small_dcn_collectives_and_clean_twin(mesh8):
    def two_small(a, b):
        return jax.lax.psum(a, "data"), jax.lax.psum(b, "data")

    small = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    bad = perf_check(two_small, small, small, mesh=mesh8, dcn=("data",))
    assert "TPU503" in _rules(bad)

    # same collectives on ICI: no finding
    assert "TPU503" not in _rules(perf_check(two_small, small, small, mesh=mesh8))

    # ONE small DCN collective: nothing to coalesce with
    def one_small(a):
        return jax.lax.psum(a, "data")

    assert "TPU503" not in _rules(perf_check(one_small, small, mesh=mesh8, dcn=("data",)))

    # one BIG DCN collective: bandwidth-bound, not latency-bound
    big = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)
    assert "TPU503" not in _rules(perf_check(one_small, big, mesh=mesh8, dcn=("data",)))


def test_tpu504_missed_overlap_and_clean_twin(mesh8):
    a = jax.ShapeDtypeStruct((1024, 512), jnp.float32)
    b = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)

    def bad(a, b):
        g = jax.lax.psum(a, "data")
        h = g + 1.0  # consumed immediately
        c = b @ b  # independent compute stranded after the consumer
        return h, c

    report = perf_check(bad, a, b, mesh=mesh8, generation="v5e")
    assert "TPU504" in _rules(report)
    [f] = [f for f in report.findings if f.rule == "TPU504"]
    assert "us" in f.message  # the hideable time is priced

    def good(a, b):
        g = jax.lax.psum(a, "data")
        c = b @ b  # fills the collective's window
        h = g + 1.0
        return h, c

    assert "TPU504" not in _rules(perf_check(good, a, b, mesh=mesh8, generation="v5e"))


def test_tpu505_f32_matmul_with_bf16_provenance_and_clean_twin(mesh1):
    xb = jax.ShapeDtypeStruct((256, 128), jnp.bfloat16)
    wb = jax.ShapeDtypeStruct((128, 512), jnp.bfloat16)

    def upcast(x, w):
        return x.astype(jnp.float32) @ w.astype(jnp.float32)

    assert "TPU505" in _rules(perf_check(upcast, xb, wb, mesh=mesh1))

    # destination form: f32 matmul narrowed straight back to bf16
    xf = jax.ShapeDtypeStruct((256, 128), jnp.float32)
    wf = jax.ShapeDtypeStruct((128, 512), jnp.float32)

    def narrowed(x, w):
        return (x @ w).astype(jnp.bfloat16)

    assert "TPU505" in _rules(perf_check(narrowed, xf, wf, mesh=mesh1))

    # genuine f32 data staying f32: clean
    def native(x, w):
        return x @ w

    assert "TPU505" not in _rules(perf_check(native, xf, wf, mesh=mesh1))

    # the fix itself: bf16 inputs, f32 accumulation — clean
    def fixed(x, w):
        return jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    assert "TPU505" not in _rules(perf_check(fixed, xb, wb, mesh=mesh1))


def test_perf_findings_anchor_to_source_and_inline_suppression(tmp_path, mesh1):
    """TPU5xx findings carry real path:line, so # tpu-lint: disable works."""
    import importlib.util
    import textwrap

    mod = tmp_path / "padded.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture: misaligned matmul, suppressed inline."""
            import jax.numpy as jnp


            def step(x, w):
                return x @ w  # tpu-lint: disable=TPU501
            '''
        )
    )
    spec = importlib.util.spec_from_file_location("padded", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    r = perf_check(
        m.step,
        jax.ShapeDtypeStruct((256, 100), jnp.float32),
        jax.ShapeDtypeStruct((100, 512), jnp.float32),
        mesh=mesh1,
    )
    assert "TPU501" not in _rules(r)


def test_select_ignore_filtering(mesh1):
    def step(x, w):
        return x @ w

    x = jax.ShapeDtypeStruct((256, 100), jnp.float32)
    w = jax.ShapeDtypeStruct((100, 512), jnp.float32)
    assert _rules(perf_check(step, x, w, mesh=mesh1, ignore=("TPU501",))) == []
    assert _rules(perf_check(step, x, w, mesh=mesh1, select=("TPU501",))) == ["TPU501"]


# --------------------------------------------------------------------- #
# selfcheck (the executable spec)
# --------------------------------------------------------------------- #


def test_run_perf_selfcheck_passes(mesh8):
    from accelerate_tpu.analysis.selfcheck import run_perf_selfcheck

    ok, lines = run_perf_selfcheck(mesh8)
    assert ok, "\n".join(lines)
    for rule in ("TPU501", "TPU502", "TPU503", "TPU504", "TPU505"):
        assert f"{rule} fixture: detected" in "\n".join(lines)
        assert f"{rule} clean twin: zero findings" in "\n".join(lines)
    assert any("roofline reference" in line and "exact" in line for line in lines)


# --------------------------------------------------------------------- #
# perf_model_drift telemetry cross-check
# --------------------------------------------------------------------- #


class _FakeClock:
    """Deterministic clock: every reading advances by ``dt_s``."""

    def __init__(self, dt_s=0.001):
        self.t = 0.0
        self.dt = dt_s

    def __call__(self):
        self.t += self.dt
        return self.t


def _drive(st, n=8):
    f = st.wrap(lambda x: x)
    for _ in range(n):
        f(1.0)


def test_perf_model_drift_fires_once_on_mismatch(tmp_path):
    from accelerate_tpu.telemetry import StepTelemetry
    from accelerate_tpu.telemetry.eventlog import EventLog, read_events

    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    # fake clock: every step's busy time is exactly 2ms (dispatch+execute)
    st = StepTelemetry(log, warmup_steps=1, watchdog=False, fence=False, clock=_FakeClock(0.001))
    st.set_static_step_estimate(0.5)  # predicted 0.5ms vs observed 2ms: 300% off
    _drive(st, 8)
    assert st.perf_drift_event is not None
    assert st.perf_drift_event["rel_error"] == pytest.approx(3.0, rel=0.01)
    _drive(st, 8)  # fires ONCE, not per step
    log.close()
    events = read_events(path)
    drift = [e for e in events if e.get("name") == "perf_model_drift"]
    static = [e for e in events if e.get("name") == "perf_static_estimate"]
    assert len(drift) == 1 and len(static) == 1
    assert drift[0]["predicted_ms"] == 0.5
    assert drift[0]["observed_busy_ms"] == pytest.approx(2.0, rel=0.01)
    summary = st.summary()
    assert summary["static_step_ms"] == 0.5
    assert summary["perf_model_drift"] is True


def test_perf_model_drift_silent_on_matched_run(tmp_path):
    from accelerate_tpu.telemetry import StepTelemetry
    from accelerate_tpu.telemetry.eventlog import EventLog

    log = EventLog(str(tmp_path / "run.jsonl"), rank=0)
    st = StepTelemetry(log, warmup_steps=1, watchdog=False, fence=False, clock=_FakeClock(0.001))
    st.set_static_step_estimate(2.0)  # exactly the observed busy time
    _drive(st, 20)
    assert st.perf_drift_event is None
    assert st.summary()["perf_model_drift"] is False
    log.close()


def test_drift_needs_min_steady_records(tmp_path):
    from accelerate_tpu.telemetry import StepTelemetry

    st = StepTelemetry(warmup_steps=1, watchdog=False, fence=False, clock=_FakeClock(0.001))
    st.set_static_step_estimate(0.1)
    _drive(st, 4)  # 3 steady records < perf_drift_min_steady (5)
    assert st.perf_drift_event is None
    _drive(st, 4)
    assert st.perf_drift_event is not None


def test_summarize_renders_drift(tmp_path):
    from accelerate_tpu.telemetry import StepTelemetry
    from accelerate_tpu.telemetry.eventlog import EventLog
    from accelerate_tpu.telemetry.summarize import render_text, summarize_file

    path = str(tmp_path / "run.jsonl")
    log = EventLog(path, rank=0)
    st = StepTelemetry(log, warmup_steps=1, watchdog=False, fence=False, clock=_FakeClock(0.001))
    st.set_static_step_estimate(0.5)
    _drive(st, 8)
    log.close()
    report = summarize_file(path)
    assert report["steps"]["static_step_ms"] == 0.5
    assert len(report["steps"]["perf_drift_events"]) == 1
    text = render_text(report)
    assert "static prediction" in text and "DRIFT" in text


def test_accelerator_perf_check_seeds_telemetry(tmp_path):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils import TelemetryKwargs

    path = str(tmp_path / "run.jsonl")
    acc = Accelerator(kwargs_handlers=[TelemetryKwargs(output_path=path)])
    tel = acc.telemetry  # telemetry live before the check

    def step(x, w):
        return (x @ w).sum()

    report = acc.perf_check(
        step,
        jax.ShapeDtypeStruct((64, 256), jnp.float32),
        jax.ShapeDtypeStruct((256, 128), jnp.float32),
    )
    assert report.predicted_step_ms > 0
    assert report.generation == "cpu"  # attached backend resolves the row
    assert tel.steps.static_step_ms == pytest.approx(report.predicted_step_ms)


# --------------------------------------------------------------------- #
# ServingEngine dogfood: roofline the real prefill/decode programs
# --------------------------------------------------------------------- #


def test_serving_engine_perf_check_dogfood():
    from accelerate_tpu.models import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ServingEngine

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    eng = ServingEngine(model, num_slots=2, prompt_buckets=(8, 16))
    reports = eng.perf_check()
    # resume_recompute = the preempt->resume warm chunk window: the
    # analysis stack covers every program the scheduler can launch
    assert set(reports) == {"prefill", "decode_tick", "resume_recompute"}
    for name, rep in reports.items():
        assert rep.total_flops > 0, name
        assert rep.predicted_step_ms > 0, name
        # the strict-gate rule must be clean on the repo's own programs;
        # TPU501 warnings are expected here — the TINY test config's
        # 64-wide dims are deliberately sub-tile (real configs are
        # 128-multiples), which is exactly what the rule prices
        assert not any(f.rule == "TPU502" for f in rep.findings), name
        assert {f.rule for f in rep.findings} <= {"TPU501"}, name
    # the decode tick runs tick_block scan steps per call
    decode = reports["decode_tick"]
    assert any(o.count >= eng.tick_block for o in decode.ops)


# --------------------------------------------------------------------- #
# CLI: text / json / sarif / selfcheck / baseline diff
# --------------------------------------------------------------------- #

CPU_ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True, text=True, env=CPU_ENV, timeout=timeout, cwd=REPO,
    )


@pytest.mark.slow
def test_cli_perf_check_selfcheck():
    result = _run_cli("perf-check", "--selfcheck")
    assert result.returncode == 0, result.stderr
    for rule in ("TPU501", "TPU502", "TPU503", "TPU504", "TPU505"):
        assert f"{rule} fixture: detected" in result.stdout
        assert f"{rule} clean twin: zero findings" in result.stdout
    assert "roofline reference" in result.stdout and "exact" in result.stdout


@pytest.mark.slow
def test_cli_perf_check_example_step_text():
    result = _run_cli(
        "perf-check", "examples/by_feature/flight_check.py::train_step", "--mesh", "data=8",
    )
    assert result.returncode == 0, result.stderr
    assert "predicted step time" in result.stdout
    assert "MFU upper bound" in result.stdout
    # dogfood: the example tree is TPU5xx-clean (head matmul suppressed inline)
    assert "findings: none" in result.stdout


@pytest.mark.slow
def test_cli_perf_check_json_sarif_and_baseline(tmp_path):
    target = ("perf-check", "examples/by_feature/flight_check.py::train_step", "--mesh", "data=8")
    result = _run_cli(*target, "--format", "json")
    assert result.returncode == 0, result.stderr
    payload = json.loads(result.stdout)
    assert payload["totals"]["predicted_step_ms"] > 0
    assert payload["ops"] and all("time_us" in op for op in payload["ops"])

    sarif = _run_cli(*target, "--format", "sarif")
    assert sarif.returncode == 0, sarif.stderr
    doc = json.loads(sarif.stdout)
    assert doc["version"] == "2.1.0"

    base = tmp_path / "base.json"
    base.write_text(result.stdout)
    diff = _run_cli(*target, "--baseline", str(base))
    assert diff.returncode == 0, diff.stderr
    assert "ok: predicted step time +0.0%" in diff.stdout

    # a seeded 2x regression trips the threshold and the exit code
    slow = json.loads(result.stdout)
    slow["totals"]["predicted_step_ms"] /= 2  # pretend the past was 2x faster
    regress = tmp_path / "regress.json"
    regress.write_text(json.dumps(slow))
    diff = _run_cli(*target, "--baseline", str(regress))
    assert diff.returncode == 1
    assert "REGRESSION" in diff.stdout
    # a generous threshold lets the same diff pass
    diff = _run_cli(*target, "--baseline", str(regress), "--regress-pct", "150")
    assert diff.returncode == 0, diff.stdout


@pytest.mark.slow
def test_cli_perf_check_strict_gate_on_tpu502(tmp_path):
    """The error-severity rule fails the CLI without --strict — the
    mechanism that promotes TPU502 into the make lint gate."""
    import textwrap

    mod = tmp_path / "redundant.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture: redundant psum-of-psum."""
            import jax
            import jax.numpy as jnp


            def step(x):
                g = jax.lax.psum(x, "data")
                return jax.lax.psum(g, "data")


            def step_sample_args():
                return (jax.ShapeDtypeStruct((64, 64), jnp.float32),)
            '''
        )
    )
    result = _run_cli("perf-check", f"{mod}::step", "--mesh", "data=8")
    assert result.returncode == 1
    assert "TPU502" in result.stdout
