"""API-surface tests for Accelerator methods the core suites don't reach:
free_memory, autocast override, join_uneven_inputs, unwrap_model,
register_for_checkpointing validation, save/load-state pre-hooks.

Reference analogue: tests/test_accelerator.py (861 LoC) — the prepare
idempotency / free_memory / hook-registration sections.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn
from accelerate_tpu.utils.dataclasses import AutocastKwargs


@pytest.fixture
def acc():
    return Accelerator()


def test_free_memory_clears_prepared_objects(acc):
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    acc.prepare_data_loader(RegressionDataset(length=8))
    acc.step = 7
    leftover = acc.free_memory(model)
    assert acc._models == [] and acc._optimizers == [] and acc._schedulers == []
    assert acc._dataloaders == [] and acc._jit_cache == {} and acc.step == 0
    assert leftover == [None]  # release_memory nulls what it is handed


def test_clear_aliases_free_memory(acc):
    acc.prepare_model(RegressionModel())
    acc.clear()
    assert acc._models == []


def test_autocast_context_overrides_policy():
    acc = Accelerator(mixed_precision="bf16")
    x = {"w": jnp.ones(3, jnp.float32)}
    assert acc.cast_to_compute(x)["w"].dtype == jnp.bfloat16
    with acc.autocast(AutocastKwargs(enabled=False)):
        assert acc.cast_to_compute(x)["w"].dtype == jnp.float32
    # restored on exit
    assert acc.cast_to_compute(x)["w"].dtype == jnp.bfloat16


def test_autocast_keep_fp32_patterns():
    acc = Accelerator(mixed_precision="bf16")
    tree = {"layernorm_scale": jnp.ones(2), "dense_kernel": jnp.ones(2)}
    with acc.autocast(AutocastKwargs(keep_fp32_patterns=("layernorm",))):
        out = acc.cast_to_compute(tree)
    assert out["layernorm_scale"].dtype == jnp.float32
    assert out["dense_kernel"].dtype == jnp.bfloat16


def test_join_uneven_inputs_overrides_even_batches(acc):
    dl = acc.prepare_data_loader(RegressionDataset(length=10), even_batches=True)
    with acc.join_uneven_inputs([None], even_batches=False):
        assert dl.even_batches is False
    assert dl.even_batches is True


def test_unwrap_model_identity(acc):
    model = acc.prepare_model(RegressionModel())
    assert acc.unwrap_model(model) is model


def test_register_for_checkpointing_rejects_stateless(acc):
    class NoState:
        pass

    with pytest.raises(ValueError, match="state_dict"):
        acc.register_for_checkpointing(NoState())


def test_save_load_state_pre_hooks_fire_and_remove(acc, tmp_path):
    acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    events = []
    h1 = acc.register_save_state_pre_hook(lambda models, weights, out_dir: events.append(("save", out_dir)))
    h2 = acc.register_load_state_pre_hook(lambda models, in_dir: events.append(("load", in_dir)))
    out = str(tmp_path / "ckpt")
    acc.save_state(out)
    acc.load_state(out)
    assert [e[0] for e in events] == ["save", "load"]
    assert all(isinstance(e[1], str) for e in events)

    h1.remove()
    h2.remove()
    events.clear()
    acc.save_state(out)
    acc.load_state(out)
    assert events == []


def test_no_sync_blocks_apply_until_exit(acc):
    model = acc.prepare_model(RegressionModel())
    opt = acc.prepare_optimizer(optax.sgd(0.5))
    batch = {"x": np.ones((4, 1), np.float32), "y": np.ones((4, 1), np.float32) * 5}
    before = float(model.params["a"])
    with acc.no_sync():
        acc.backward(linear_loss_fn, batch)
        opt.step()
        assert float(model.params["a"]) == before, "no_sync must suppress the apply"
    acc.backward(linear_loss_fn, batch)
    opt.step()
    assert float(model.params["a"]) != before


def test_skip_first_batches_applies_to_next_iteration_only(acc):
    dl = acc.prepare_data_loader(RegressionDataset(length=32))
    dl.batch_size = max(1, 4 // acc.num_data_shards)  # global batch 4 on any mesh
    full = [np.asarray(b["x"]) for b in dl]
    assert len(full) == 32 // dl.total_batch_size
    skipped = acc.skip_first_batches(dl, 2)
    assert skipped is dl  # in-place marker, same loader object
    part = [np.asarray(b["x"]) for b in dl]
    assert len(part) == len(full) - 2
    np.testing.assert_array_equal(part[0], full[2])
    # the skip is consumed: the following epoch is complete again
    assert len([b for b in dl]) == len(full)


def test_prepare_varargs_roundtrip(acc):
    model, opt, dl = acc.prepare(RegressionModel(), optax.sgd(0.1), RegressionDataset(length=8))
    assert model in acc._models
    assert opt in acc._optimizers
    assert dl in acc._dataloaders


def test_profile_context_writes_trace(acc, tmp_path):
    """Accelerator.profile wraps jax.profiler and leaves a trace on disk
    (reference: accelerator.py:3859 exporting per-rank Chrome traces)."""
    import jax.numpy as jnp

    with acc.profile(str(tmp_path)):
        jnp.ones((8, 8)) @ jnp.ones((8, 8))
    files = [p for p in tmp_path.rglob("*") if p.is_file()]
    assert files, "profiler produced no trace files"


def test_softmax_dtype_policy_override():
    """A MixedPrecisionPolicy kwargs-handler overrides the state policy and
    the attention op reads it at trace time; bf16 softmax must track the
    f32 trajectory closely (the HBM-bandwidth lever, measured 1.10x on the
    v5e BERT step)."""
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import BertConfig, bert_classification_loss, create_bert_model
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import MixedPrecisionPolicy

    def run(handlers):
        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        acc = Accelerator(mixed_precision="bf16", kwargs_handlers=handlers)
        model = acc.prepare_model(create_bert_model(BertConfig.tiny(), seq_len=16))
        acc.prepare_optimizer(optax.adamw(1e-3))
        step = acc.build_train_step(lambda p, b: bert_classification_loss(p, b, model.apply_fn))
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": rng.integers(1, 90, size=(8, 16)).astype(np.int32),
            "attention_mask": np.ones((8, 16), np.bool_),
            "labels": rng.integers(0, 2, size=(8,)).astype(np.int32),
        }
        return [float(step(batch)) for _ in range(5)], acc

    base, acc = run([])
    assert acc.state.dtype_policy.softmax_dtype is None
    fast, acc = run([MixedPrecisionPolicy(softmax_dtype="bfloat16")])
    assert acc.state.dtype_policy.softmax_dtype == "bfloat16"
    np.testing.assert_allclose(fast, base, atol=0.02)
    assert fast != base  # the dtype actually changed the math


def test_mixed_precision_policy_conflict_raises():
    """A MixedPrecisionPolicy handler whose core dtype fields disagree with
    mixed_precision must raise instead of silently flipping the mode."""
    import pytest

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import MixedPrecisionPolicy

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    with pytest.raises(ValueError, match="conflicts with mixed_precision"):
        Accelerator(mixed_precision="no", kwargs_handlers=[MixedPrecisionPolicy(softmax_dtype="bfloat16")])
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    # matching fields are accepted
    acc = Accelerator(
        mixed_precision="no",
        kwargs_handlers=[MixedPrecisionPolicy(compute_dtype="float32", softmax_dtype="bfloat16")],
    )
    assert acc.state.dtype_policy.softmax_dtype == "bfloat16"
