"""OLMo2 family (models/olmo2.py): post-norm layout + flat q/k RMSNorm
through decode and serving. HF importer parity lives in test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Olmo2Config, create_olmo2_model


@pytest.fixture(scope="module")
def tiny_olmo2():
    return create_olmo2_model(Olmo2Config.tiny(), seq_len=16)


def test_post_norm_params(tiny_olmo2):
    block = tiny_olmo2.params["layers"]["block"]
    assert "post_attn_norm" in block and "post_ffn_norm" in block
    assert "input_norm" not in block  # post-norm layout has no input norms
    cfg = Olmo2Config.tiny()
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    # FLAT scales: all heads jointly, not one [head_dim] vector
    assert block["attn"]["q_norm"]["scale"].shape == (
        cfg.num_hidden_layers, cfg.num_attention_heads * head_dim,
    )
    assert block["attn"]["k_norm"]["scale"].shape == (
        cfg.num_hidden_layers, cfg.num_key_value_heads * head_dim,
    )


def test_greedy_decode_matches_full_prefix(tiny_olmo2):
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_olmo2, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_olmo2(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_tp_sharded_decode(tiny_olmo2):
    """The flat q/k norm reduces over the full [H*head_dim] axis that TP
    splits — GSPMD must insert the cross-shard reduction: sharded tokens
    == single-device tokens."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompt = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(tiny_olmo2, prompt, max_new_tokens=5))

    model = create_olmo2_model(Olmo2Config.tiny(), seq_len=16)
    mesh = MeshConfig(data=1, tensor=2).build(jax.devices()[:2])
    shard_model(model, mesh)
    got = np.asarray(generate(model, prompt, max_new_tokens=5))
    np.testing.assert_array_equal(got, want)


def test_serving(tiny_olmo2):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9, 6)]
    eng = ServingEngine(tiny_olmo2, num_slots=2, prompt_buckets=(4, 8, 16))
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_olmo2, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)
