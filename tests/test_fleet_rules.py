"""Tier-9b fleet-protocol model checker (analysis.fleet_rules):
extraction from the real serving_fleet.py, the bounded-exhaustive BFS,
the three PR-15 invariants on seeded defects, and the chaos-coverage
drift gate (model-checks = chaos-observes)."""

import ast
import dataclasses
import pathlib

from accelerate_tpu.analysis.fleet_rules import (
    CHAOS_COVERAGE,
    ProtocolSpec,
    coverage_map,
    extract_protocol_spec,
    fleet_protocol_check,
    load_protocol_spec,
    model_check,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _real_spec():
    spec, problems = load_protocol_spec()
    assert problems == [], problems
    return spec


# --------------------------------------------------------------------------- #
# extraction from the real sources
# --------------------------------------------------------------------------- #


def test_extraction_reads_the_real_health_machine():
    spec = _real_spec()
    assert spec.states == ("healthy", "degraded", "quarantined", "dead")
    assert spec.serving == frozenset({"healthy", "degraded"})
    assert spec.kind_target("crash") == "dead"
    assert spec.kind_target("poison") == "quarantined"
    assert spec.kind_target("timeout") == "quarantined"
    # the PR-15 contract: poisoned KV is never trusted, everything else is
    assert spec.kind_kv("poison") is False
    assert spec.kind_kv("crash") is True
    assert spec.kind_kv("drain") is True
    # every failure kind migrates its in-flight work
    assert all(m for _, m in spec.migrates)
    # shed_on_capacity trips exactly at zero routable replicas
    assert spec.breaker_trips_at == 0
    assert spec.drain_requires_other_routable is True
    assert spec.timeout_soft_state == "degraded"
    assert spec.heal_state == "healthy"


def test_extraction_drift_is_reported_not_guessed():
    fleet_src = (REPO / "accelerate_tpu" / "serving_fleet.py").read_text()
    sched_src = (REPO / "accelerate_tpu" / "scheduling.py").read_text()
    # rename the health constant: the extractor must say what it lost,
    # and fleet_protocol_check must turn that into TPU904, not a guess
    broken = fleet_src.replace("HEALTH_STATES", "HEALTH_STATES_V2")
    spec, problems = extract_protocol_spec(broken, sched_src)
    assert spec is None
    assert any("HEALTH_STATES" in p for p in problems)

    # drop the breaker branch out of scheduling.py
    sched_broken = sched_src.replace("shed_on_capacity", "shed_on_capacity_v2")
    spec2, problems2 = extract_protocol_spec(fleet_src, sched_broken)
    assert spec2 is None
    assert any("shed_on_capacity" in p for p in problems2)


def test_extraction_drift_becomes_tpu904(tmp_path, monkeypatch):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    fleet_src = (REPO / "accelerate_tpu" / "serving_fleet.py").read_text()
    sched_src = (REPO / "accelerate_tpu" / "scheduling.py").read_text()
    (pkg / "serving_fleet.py").write_text(fleet_src.replace("HEALTH_STATES", "HS"))
    (pkg / "scheduling.py").write_text(sched_src)
    findings, report = fleet_protocol_check(package_root=pkg)
    assert findings and all(f.rule == "TPU904" for f in findings)
    assert any("spec extraction drifted" in f.message for f in findings)
    assert report.explored_states == 0  # nothing provable without a spec


def test_unparseable_fleet_source_is_an_extraction_problem():
    spec, problems = extract_protocol_spec("def broken(:\n", "x = 1\n")
    assert spec is None
    assert any("cannot parse" in p for p in problems)


# --------------------------------------------------------------------------- #
# the real protocol proves out
# --------------------------------------------------------------------------- #


def test_real_protocol_has_no_violations_and_full_coverage():
    findings, report = fleet_protocol_check()
    assert findings == []
    assert report.violations == []
    assert not report.truncated
    assert report.explored_states > 1000
    # every explored failure path is pinned, and nothing in the pin map
    # is unexplorable fiction
    assert report.explored_paths == set(CHAOS_COVERAGE)
    cov = coverage_map(report)
    assert all(test is not None for test in cov.values())


def test_chaos_coverage_pins_real_tests():
    """Drift gate, the other direction: every test name in CHAOS_COVERAGE
    must exist as a real test function in tests/test_fleet.py."""
    tree = ast.parse((REPO / "tests" / "test_fleet.py").read_text())
    defined = {
        n.name for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)
    }
    missing = {t for t in CHAOS_COVERAGE.values() if t not in defined}
    assert missing == set(), f"CHAOS_COVERAGE pins tests that do not exist: {missing}"


# --------------------------------------------------------------------------- #
# seeded defects: each invariant's violation is found with a counterexample
# --------------------------------------------------------------------------- #


def test_defect_crash_without_migration_strands_requests():
    spec = dataclasses.replace(
        _real_spec(),
        migrates=tuple((k, k != "crash" and v) for k, v in _real_spec().migrates),
    )
    report = model_check(spec)
    kinds = {v[0] for v in report.violations}
    assert "stranded-request" in kinds
    # the counterexample must actually reach the defect: a crash event
    # precedes the stranding
    trace = next(t for k, t, _ in report.violations if k == "stranded-request")
    assert any(ev.startswith("crash(") for ev in trace), trace


def test_defect_trusting_poisoned_kv_ships_it():
    spec = dataclasses.replace(
        _real_spec(),
        kv_trust=tuple((k, True if k == "poison" else v) for k, v in _real_spec().kv_trust),
    )
    report = model_check(spec)
    kinds = {v[0] for v in report.violations}
    assert "poisoned-kv-shipped" in kinds
    trace = next(t for k, t, _ in report.violations if k == "poisoned-kv-shipped")
    assert any(ev.startswith("poison(") for ev in trace), trace


def test_defect_missing_breaker_black_holes_requests():
    spec = dataclasses.replace(_real_spec(), breaker_trips_at=None)
    report = model_check(spec)
    kinds = {v[0] for v in report.violations}
    assert "breaker-missing" in kinds


def test_defect_early_breaker_sheds_with_capacity_left():
    spec = dataclasses.replace(_real_spec(), breaker_trips_at=1)
    report = model_check(spec)
    kinds = {v[0] for v in report.violations}
    assert "breaker-mistimed" in kinds


def test_defects_become_tpu904_findings_with_counterexamples():
    spec = dataclasses.replace(_real_spec(), breaker_trips_at=None)
    findings, report = fleet_protocol_check(spec=spec)
    assert findings and all(f.rule == "TPU904" for f in findings)
    assert any("breaker-missing" in f.message for f in findings)
    assert any("counterexample:" in f.message for f in findings)


def test_unpinned_explored_path_is_tpu904():
    # same healthy protocol, but the pin map lost an entry
    partial = dict(CHAOS_COVERAGE)
    partial.pop(("crash", "failover"))
    findings, _report = fleet_protocol_check(spec=_real_spec(), chaos_coverage=partial)
    assert [f.rule for f in findings] == ["TPU904"]
    assert "('crash', 'failover')" in findings[0].message
    assert "pinned to no ReplicaChaos test" in findings[0].message


def test_coverage_map_marks_unpinned_paths_none():
    report = model_check(_real_spec())
    partial = dict(CHAOS_COVERAGE)
    partial.pop(("drain", "migrate"))
    cov = coverage_map(report, chaos_coverage=partial)
    assert cov["drain/migrate"] is None
    assert cov["crash/failover"] == "test_chaos_crash_matrix_token_and_logprob_exact"


def test_spec_defaults_match_the_extracted_spec():
    """The dataclass defaults document the protocol; keep them honest
    against what extraction reads from the code."""
    assert _real_spec() == ProtocolSpec()
