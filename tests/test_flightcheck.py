"""SPMD flight-check (``analysis.flightcheck`` + ``analysis.costmodel``):
peak-HBM liveness estimates, the collective cost model, the TPU3xx safety
rules with their negative (clean-code) paths, and the CLI/Accelerator
surfaces."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.analysis import flight_check
from accelerate_tpu.analysis.costmodel import collect_traffic, price_collective
from accelerate_tpu.parallel.mesh import DCN, ICI, MeshConfig, axis_transport, dcn_axes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(report):
    return [f.rule for f in report.findings]


@pytest.fixture
def mesh1():
    return MeshConfig(data=1).build(jax.devices()[:1])


# --------------------------------------------------------------------- #
# cost model units
# --------------------------------------------------------------------- #


def test_price_collective_allreduce_ring_bytes(mesh8):
    rec = price_collective("psum", ("data",), 1024, mesh8)
    assert rec.group_size == 8
    assert rec.wire_bytes == int(1024 * 2 * 7 / 8)
    assert rec.transport == ICI
    assert rec.time_us("v5e") > 0


def test_price_collective_trivial_axis_and_unknown_prim(mesh8):
    assert price_collective("psum", ("tensor",), 1024, mesh8) is None  # size-1 axis
    assert price_collective("add", ("data",), 1024, mesh8) is None


def test_price_collective_dcn_classification(mesh8):
    rec = price_collective("all_gather", ("data",), 1024, mesh8, dcn=("data",))
    assert rec.transport == DCN
    assert rec.wire_bytes == 1024 * 7
    # DCN time dominates the same bytes over ICI
    assert rec.time_us("v5e") > price_collective("all_gather", ("data",), 1024, mesh8).time_us("v5e")


def test_axis_transport_env_protocol(mesh8, monkeypatch):
    assert axis_transport(mesh8, "data") == ICI
    monkeypatch.setenv("ACCELERATE_MESH_DCN_AXES", "data,pipe")
    assert dcn_axes() == ("data", "pipe")
    assert axis_transport(mesh8, "data") == DCN
    assert axis_transport(mesh8, "pipe") == ICI  # size-1 axis carries nothing


def test_collect_traffic_scan_multiplier(mesh8):
    from accelerate_tpu.utils.compat import shard_map

    def body(x):
        def step(c, _):
            return jax.lax.psum(c, "data"), None

        out, _ = jax.lax.scan(step, x, None, length=4)
        return out

    wrapped = shard_map(body, mesh=mesh8, in_specs=P(), out_specs=P(), check_vma=False)
    closed = jax.make_jaxpr(wrapped)(jax.ShapeDtypeStruct((16, 16), jnp.float32))
    report = collect_traffic(closed.jaxpr, mesh8)
    psums = [r for r in report.records if r.primitive == "psum"]
    assert psums and psums[0].count == 4
    assert report.total_wire_bytes == psums[0].wire_bytes
    assert report.bytes_by_transport()[ICI] == report.total_wire_bytes


# --------------------------------------------------------------------- #
# peak-HBM liveness estimate
# --------------------------------------------------------------------- #


def test_peak_hbm_within_2x_of_live_buffers_on_1_device(mesh1):
    """Acceptance bound: on a 1-device mesh the estimate must be within 2x
    of the sum of the obviously-live buffers (args + outputs)."""

    def step(params, batch):
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
        return new, batch.sum()

    params = {"w": jax.ShapeDtypeStruct((256, 256), jnp.float32)}
    batch = jax.ShapeDtypeStruct((32, 256), jnp.float32)
    report = flight_check(step, params, batch, mesh=mesh1)
    live = 256 * 256 * 4 * 2 + 32 * 256 * 4  # params + new params + batch
    assert live <= report.peak_hbm_bytes <= 2 * live
    assert report.param_bytes == 256 * 256 * 4 + 32 * 256 * 4
    assert report.output_bytes >= 256 * 256 * 4


def test_peak_hbm_example_step_within_2x(mesh1):
    """The shipped example's step function, per the acceptance criterion."""
    sys.path.insert(0, os.path.join(REPO, "examples", "by_feature"))
    try:
        import flight_check as example
    finally:
        sys.path.pop(0)
    report = flight_check(example.train_step, *example.train_step_sample_args(), mesh=mesh1)
    args_bytes = sum(
        int(np.prod(l.shape or (1,))) * l.dtype.itemsize
        for a in example.train_step_sample_args()
        for l in jax.tree_util.tree_leaves(a)
    )
    live = args_bytes + report.output_bytes
    assert live <= report.peak_hbm_bytes <= 2 * live


def test_donation_lowers_peak(mesh1):
    """Donated read-and-replace params alias in place; the undonated step
    must account both copies."""

    def step(params, batch):
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        return new, batch.sum()

    params = {"w": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
    batch = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    plain = flight_check(step, params, batch, mesh=mesh1)
    donated = flight_check(step, params, batch, mesh=mesh1, donate_argnums=(0,))
    assert donated.peak_hbm_bytes < plain.peak_hbm_bytes
    assert donated.donated_bytes == 512 * 512 * 4


def test_sharded_inputs_divide_per_device_bytes(mesh8):
    def step(x):
        return x * 2.0

    x = jax.device_put(np.zeros((64, 128), np.float32), NamedSharding(mesh8, P("data")))
    sharded = flight_check(step, x, mesh=mesh8)
    replicated = flight_check(step, jax.ShapeDtypeStruct((64, 128), jnp.float32), mesh=mesh8)
    assert sharded.peak_hbm_bytes * 8 == replicated.peak_hbm_bytes


def test_report_surfaces(mesh1):
    def step(x):
        return x.sum()

    report = flight_check(step, jax.ShapeDtypeStruct((8, 8), jnp.float32), mesh=mesh1)
    text = report.render_text()
    assert "peak HBM / device" in text and "findings: none" in text
    d = report.as_dict()
    assert d["peak_hbm_bytes_per_device"] == report.peak_hbm_bytes
    assert d["findings"] == []
    assert report.fits(16.0) and not report.fits(1e-9)
    assert report.ok


def test_flight_check_requires_mesh():
    with pytest.raises(ValueError, match="mesh"):
        flight_check(lambda x: x, jnp.ones(4))


# --------------------------------------------------------------------- #
# TPU301 — collective under value-dependent control flow
# --------------------------------------------------------------------- #


def test_tpu301_collective_under_cond(mesh8):
    def step(x):
        return jax.lax.cond(x.sum() > 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x)

    report = flight_check(step, jax.ShapeDtypeStruct((8, 16), jnp.float32), mesh=mesh8)
    assert "TPU301" in _rules(report)
    assert not report.ok  # error severity


def test_tpu301_collective_under_while(mesh8):
    def step(x):
        def cond(c):
            return c.sum() < 100.0

        def body(c):
            return jax.lax.psum(c, "data") + 1.0

        return jax.lax.while_loop(cond, body, x)

    report = flight_check(step, jax.ShapeDtypeStruct((8,), jnp.float32), mesh=mesh8)
    assert "TPU301" in _rules(report)


def test_tpu301_scan_and_straightline_are_clean(mesh8):
    def step(x):
        def body(c, _):
            return jax.lax.psum(c, "data"), None

        out, _ = jax.lax.scan(body, x, None, length=3)
        return out + jax.lax.psum(x, "data")

    report = flight_check(step, jax.ShapeDtypeStruct((8,), jnp.float32), mesh=mesh8)
    assert "TPU301" not in _rules(report)


# --------------------------------------------------------------------- #
# TPU302 — implicit reshard
# --------------------------------------------------------------------- #


def test_tpu302_conflicting_constraints(mesh8):
    def step(x):
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh8, P("data", None)))
        x = x * 2.0
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh8, P(None, "data")))
        return x.sum()

    report = flight_check(step, jax.ShapeDtypeStruct((64, 64), jnp.float32), mesh=mesh8)
    assert "TPU302" in _rules(report)


def test_tpu302_from_input_sharding(mesh8):
    def step(x):
        return jax.lax.with_sharding_constraint(x * 1.0, NamedSharding(mesh8, P(None, "data"))).sum()

    x = jax.device_put(np.zeros((64, 64), np.float32), NamedSharding(mesh8, P("data", None)))
    report = flight_check(step, x, mesh=mesh8)
    assert "TPU302" in _rules(report)


def test_tpu302_consistent_constraints_are_clean(mesh8):
    def step(x):
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh8, P("data", None)))
        x = x * 2.0
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh8, P("data", None)))
        return x.sum()

    report = flight_check(step, jax.ShapeDtypeStruct((64, 64), jnp.float32), mesh=mesh8)
    assert "TPU302" not in _rules(report)


# --------------------------------------------------------------------- #
# TPU303 — donation defeated by a late read
# --------------------------------------------------------------------- #


def test_tpu303_late_read_after_aliased_output(mesh8):
    def step(params, batch):
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        loss = (params["w"] * batch).sum()  # reads params after `new` exists
        return new, loss

    report = flight_check(
        step,
        {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
        mesh=mesh8,
        donate_argnums=(0,),
    )
    assert "TPU303" in _rules(report)


def test_tpu303_clean_when_reads_precede_update(mesh8):
    def step(params, batch):
        loss = (params["w"] * batch).sum()
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        return new, loss

    report = flight_check(
        step,
        {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
        mesh=mesh8,
        donate_argnums=(0,),
    )
    assert "TPU303" not in _rules(report)


def test_tpu303_clean_without_donation(mesh8):
    def step(params, batch):
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        return new, (params["w"] * batch).sum()

    report = flight_check(
        step,
        {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
        mesh=mesh8,
    )
    assert "TPU303" not in _rules(report)


def test_select_ignore_filtering(mesh8):
    def step(x):
        return jax.lax.cond(x.sum() > 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x)

    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    assert _rules(flight_check(step, x, mesh=mesh8, ignore=("TPU301",))) == []
    assert "TPU301" in _rules(flight_check(step, x, mesh=mesh8, select=("TPU301",)))


# --------------------------------------------------------------------- #
# surfaces: Accelerator hook + CLI
# --------------------------------------------------------------------- #


def test_accelerator_flight_check_hook():
    from accelerate_tpu import Accelerator

    acc = Accelerator()

    def step(params, batch):
        new = jax.tree_util.tree_map(lambda p: p - 0.1, params)
        return new, batch.sum()

    report = acc.flight_check(
        step,
        {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        jax.ShapeDtypeStruct((8, 16), jnp.float32),
    )
    assert report.peak_hbm_bytes > 0
    assert report.ok


CPU_ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}


def _run_cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True, text=True, env=CPU_ENV, timeout=timeout, cwd=REPO,
    )


@pytest.mark.slow
def test_cli_flight_check_example_step():
    result = _run_cli(
        "flight-check", "examples/by_feature/flight_check.py::train_step",
        "--mesh", "data=8", "--donate", "0",
    )
    assert result.returncode == 0, result.stderr
    assert "peak HBM / device" in result.stdout
    assert "psum" in result.stdout  # the example's pmean is priced


@pytest.mark.slow
def test_cli_flight_check_selfcheck():
    result = _run_cli("flight-check", "--selfcheck")
    assert result.returncode == 0, result.stderr
    for rule in ("TPU301", "TPU302", "TPU303"):
        assert f"{rule}: detected" in result.stdout


@pytest.mark.slow
def test_cli_flight_check_arg_specs_and_json(tmp_path):
    import json
    import textwrap

    mod = tmp_path / "mystep.py"
    mod.write_text(
        textwrap.dedent(
            '''
            """Fixture step for the flight-check CLI."""
            import jax.numpy as jnp


            def step(w, x):
                return (x @ w).sum()
            '''
        )
    )
    result = _run_cli(
        "flight-check", f"{mod}::step",
        "--arg", "f32[128,64]", "--arg", "bf16[32,128]",
        "--format", "json",
    )
    assert result.returncode == 0, result.stderr
    payload = json.loads(result.stdout)
    assert payload["peak_hbm_bytes_per_device"] >= 128 * 64 * 4 + 32 * 128 * 2
