"""CLI + launcher tests (reference analogue: tests/test_cli.py, 643 LoC —
config YAML round-trips through launch arg synthesis; and the tier-2
subprocess-launch pattern from SURVEY §4)."""

import json
import os
import subprocess
import sys

import pytest

CPU_ENV = {
    **os.environ,
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


def run_cli(*args, env=None, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True,
        text=True,
        env=env or CPU_ENV,
        timeout=timeout,
    )


def test_env_command():
    result = run_cli("env")
    assert result.returncode == 0
    assert "accelerate_tpu version" in result.stdout
    assert "JAX backend" in result.stdout


def test_estimate_memory_param_count():
    result = run_cli("estimate-memory", "124M", "--num_devices", "4")
    assert result.returncode == 0
    assert "124,000,000" in result.stdout
    assert "bfloat16" in result.stdout


def _fake_hf_cache(tmp_path, repo="acme/tiny", n_rows=10, n_cols=20, index_only=False):
    """A minimal HF hub cache: models--org--name/snapshots/<rev>/ with either
    a real tiny safetensors file or just the index+config metadata."""
    import struct

    hf_home = tmp_path / "hf_home"
    repo_dir = hf_home / "hub" / ("models--" + repo.replace("/", "--"))
    snap = repo_dir / "snapshots" / "rev0"
    snap.mkdir(parents=True)
    (repo_dir / "refs").mkdir()
    (repo_dir / "refs" / "main").write_text("rev0")
    if index_only:
        (snap / "model.safetensors.index.json").write_text(
            json.dumps({"metadata": {"total_size": n_rows * n_cols * 2}, "weight_map": {}})
        )
        (snap / "config.json").write_text(json.dumps({"torch_dtype": "bfloat16"}))
    else:
        header = {"w": {"dtype": "F32", "shape": [n_rows, n_cols], "data_offsets": [0, n_rows * n_cols * 4]}}
        hb = json.dumps(header).encode()
        with open(snap / "model.safetensors", "wb") as f:
            f.write(struct.pack("<Q", len(hb)))
            f.write(hb)
            f.write(b"\0" * (n_rows * n_cols * 4))
    return hf_home


def test_estimate_memory_hub_repo_from_cache(tmp_path):
    """Repo-id source resolves offline from the local HF cache — no network,
    no torch (reference: estimate.py:34-116 needs the full meta-model)."""
    hf_home = _fake_hf_cache(tmp_path, n_rows=30, n_cols=10)
    result = run_cli(
        "estimate-memory", "acme/tiny",
        env={**CPU_ENV, "HF_HOME": str(hf_home), "HF_HUB_OFFLINE": "1"},
    )
    assert result.returncode == 0, result.stderr
    assert "300" in result.stdout and "local cache" in result.stdout


def test_estimate_memory_hub_repo_index_only_cache(tmp_path):
    """With only index.json + config.json cached (no weights), total_size /
    dtype width gives the parameter count."""
    hf_home = _fake_hf_cache(tmp_path, n_rows=40, n_cols=10, index_only=True)
    result = run_cli(
        "estimate-memory", "acme/tiny",
        env={**CPU_ENV, "HF_HOME": str(hf_home), "HF_HUB_OFFLINE": "1"},
    )
    assert result.returncode == 0, result.stderr
    assert "400" in result.stdout and "index total_size" in result.stdout


def test_estimate_memory_hub_repo_unreachable(tmp_path):
    """No cache + no network -> one actionable error naming the offline
    alternatives, not a bare traceback."""
    result = run_cli(
        "estimate-memory", "acme/absent",
        env={**CPU_ENV, "HF_HOME": str(tmp_path / "empty"), "HF_HUB_OFFLINE": "1"},
    )
    assert result.returncode != 0
    assert "could not resolve" in result.stderr and "parameter count like `7B`" in result.stderr


def test_estimate_memory_hub_metadata_mocked(monkeypatch, tmp_path):
    """The network path sums get_safetensors_metadata parameter counts
    (metadata-only ranged requests; no weight download)."""
    import types

    from accelerate_tpu.commands import estimate

    monkeypatch.setenv("HF_HOME", str(tmp_path / "empty"))
    import huggingface_hub

    monkeypatch.setattr(
        huggingface_hub,
        "get_safetensors_metadata",
        lambda repo_id, token=None: types.SimpleNamespace(parameter_count={"BF16": 1000, "F32": 24}),
    )
    n, how = estimate.count_params_from_hub("acme/remote")
    assert n == 1024 and how == "hub safetensors metadata"


def test_estimate_memory_fit_column():
    """--hbm_gb drives a fits/device verdict (north-star sizing aid)."""
    result = run_cli("estimate-memory", "7B", "--num_devices", "8", "--hbm_gb", "16")
    assert result.returncode == 0
    assert "fits/device" in result.stdout
    single = run_cli("estimate-memory", "7B", "--hbm_gb", "16")
    fp32 = [line for line in single.stdout.splitlines() if line.strip().startswith("float32")]
    assert fp32 and fp32[0].rstrip().endswith("no")  # 104 GB Adam state on one 16 GB chip
    sharded = [line for line in result.stdout.splitlines() if line.strip().startswith("float32")]
    assert sharded and sharded[0].rstrip().endswith("yes")  # /8 brings it under HBM


def test_config_roundtrip(tmp_path):
    cfg_path = tmp_path / "cfg.yaml"
    result = run_cli("config", "--default", "--config_file", str(cfg_path))
    assert result.returncode == 0
    from accelerate_tpu.commands.config import load_config

    config = load_config(str(cfg_path))
    assert config["mixed_precision"] == "bf16"
    assert config["mesh_data"] == -1


def test_launch_env_protocol(tmp_path):
    """Launcher flags surface as ACCELERATE_* env in the child
    (reference env protocol: utils/launch.py:203)."""
    script = tmp_path / "dump_env.py"
    script.write_text(
        "import os, json\n"
        "print(json.dumps({k: v for k, v in os.environ.items() if k.startswith('ACCELERATE_')}))\n"
    )
    result = run_cli(
        "launch",
        "--mixed_precision", "bf16",
        "--mesh_fsdp", "2",
        "--gradient_accumulation_steps", "4",
        "--debug",
        str(script),
    )
    assert result.returncode == 0, result.stderr
    env = json.loads(result.stdout.strip().splitlines()[-1])
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"
    assert env["ACCELERATE_MESH_FSDP"] == "2"
    assert env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] == "4"
    assert env["ACCELERATE_DEBUG_MODE"] == "1"


def test_accelerator_reads_launcher_env(tmp_path):
    """End-to-end: launch flags -> env -> AcceleratorState picks them up."""
    script = tmp_path / "report.py"
    script.write_text(
        "from accelerate_tpu import Accelerator\n"
        "acc = Accelerator()\n"
        "print('MESH', dict(acc.mesh.shape)['fsdp'], acc.mixed_precision, acc.gradient_accumulation_steps)\n"
    )
    result = run_cli(
        "launch", "--cpu", "--fake_devices", "8",
        "--mixed_precision", "bf16", "--mesh_fsdp", "4", "--gradient_accumulation_steps", "2",
        str(script),
    )
    assert result.returncode == 0, result.stderr
    assert "MESH 4 bf16 2" in result.stdout


@pytest.mark.slow
def test_multiprocess_launch(tmp_path):
    """Two real processes with a JAX coordinator (the reference's
    multi-process tier-2 pattern, tests/test_multigpu.py:49)."""
    script = tmp_path / "mp.py"
    script.write_text(
        "from accelerate_tpu import Accelerator\n"
        "acc = Accelerator()\n"
        "assert acc.num_processes == 2, acc.num_processes\n"
        "objs = acc.gather_for_metrics([acc.process_index], use_gather_object=True)\n"
        "assert sorted(objs) == [0, 1], objs\n"
        "acc.wait_for_everyone()\n"
        "print('MP_OK', acc.process_index)\n"
    )
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7811", str(script),
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("MP_OK") >= 1


def test_sync_script_single_process():
    """The self-checking sync-semantics script (reference analogue:
    test_utils/scripts/test_sync.py) through the launcher."""
    result = run_cli(
        "launch", "--cpu", "--fake_devices", "8", "-m",
        "accelerate_tpu.test_utils.scripts.test_sync",
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert "test_sync: ALL OK" in result.stdout


@pytest.mark.slow
def test_ops_script_multiprocess():
    """Collective-ops script on two real processes (reference analogue:
    test_utils/scripts/test_ops.py)."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7813", "-m",
        "accelerate_tpu.test_utils.scripts.test_ops",
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("test_ops: ALL OK") >= 1


@pytest.mark.slow
def test_dcn_script_multiprocess(tmp_path):
    """The DCN legs — orbax multi-host checkpoint save/load (+ reshard-on-
    load), DataLoaderDispatcher scatter, ring attention across processes —
    on a REAL 2-process mesh (VERDICT r4 weak #4; reference tier-2 pattern,
    tests/test_multigpu.py:49-53)."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7814", "-m",
        "accelerate_tpu.test_utils.scripts.test_dcn", "--tmpdir", str(tmp_path),
        timeout=420,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    for leg in (
        "dispatcher scatter OK",
        "checkpoint save/load across hosts OK",
        "checkpoint reshard-on-load (replicated -> fsdp) OK",
        "ring attention across processes OK",
        "test_dcn: ALL OK",
    ):
        assert leg in result.stdout, f"missing {leg!r}:\n{result.stdout}"


def test_migrate_command(tmp_path):
    """Reference accelerate YAML -> our schema (reference analogue:
    commands/to_fsdp2.py converter)."""
    ref = tmp_path / "ref.yaml"
    ref.write_text(
        "compute_environment: LOCAL_MACHINE\n"
        "distributed_type: FSDP\n"
        "mixed_precision: bf16\n"
        "num_processes: 8\n"
        "num_machines: 2\n"
        "fsdp_config:\n"
        "  fsdp_sharding_strategy: FULL_SHARD\n"
        "  fsdp_activation_checkpointing: true\n"
    )
    out = tmp_path / "ours.yaml"
    result = run_cli("migrate", str(ref), "--output_file", str(out))
    assert result.returncode == 0, result.stderr
    text = out.read_text()
    assert "mesh_fsdp: -1" in text
    assert "mixed_precision: bf16" in text
    assert "num_processes: 8" in text
    # refuses to clobber without --overwrite
    result = run_cli("migrate", str(ref), "--output_file", str(out))
    assert result.returncode != 0
    result = run_cli("migrate", str(ref), "--output_file", str(out), "--overwrite")
    assert result.returncode == 0

    # megatron tp/pp/sp mapping
    ref2 = tmp_path / "ref2.yaml"
    ref2.write_text(
        "distributed_type: MEGATRON_LM\n"
        "num_processes: 16\n"
        "megatron_lm_config:\n"
        "  tp_degree: 4\n"
        "  pp_degree: 2\n"
        "  sequence_parallelism: true\n"
    )
    result = run_cli("migrate", str(ref2))
    assert result.returncode == 0
    assert "mesh_tensor: 4" in result.stdout
    assert "mesh_pipe: 2" in result.stdout
    assert "mesh_seq" in result.stdout


def test_pod_autodiscovery_ssh_fanout(monkeypatch, tmp_path):
    """Bare `launch script.py` on a pod: TPU_WORKER_HOSTNAMES drives the SSH
    fan-out with correct coordinator/process-id wiring (reference:
    tpu_pod_launcher, commands/launch.py:909-965)."""
    from accelerate_tpu.commands import launch as L

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "tpu-w0,tpu-w1,tpu-w2")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    calls = []

    class FakeProc:
        def __init__(self, cmd, **kw):
            calls.append(cmd)

        def wait(self):
            return 0

    monkeypatch.setattr(L.subprocess, "Popen", FakeProc)
    parser = L.launch_parser()
    args = parser.parse_args(["train.py"])
    rc = L.launch_command(args)
    assert rc == 0
    assert len(calls) == 3
    for rank, cmd in enumerate(calls):
        assert cmd[0] == "ssh"
        remote = cmd[-1]
        assert "ACCELERATE_COORDINATOR_ADDRESS=tpu-w0:7777" in remote
        assert "ACCELERATE_NUM_PROCESSES=3" in remote
        assert f"ACCELERATE_PROCESS_ID={rank}" in remote
        assert f"tpu-w{rank}" in cmd[-2]

    # a non-zero worker defers to worker 0's fan-out
    calls.clear()
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    rc = L.launch_command(parser.parse_args(["train.py"]))
    assert rc == 0 and calls == []


def test_pod_autodiscovery_respects_yaml_topology(monkeypatch, tmp_path):
    """A topology configured in the YAML config file (not just CLI flags)
    must suppress the pod SSH fan-out — the config is a user topology
    request too."""
    from accelerate_tpu.commands import launch as L

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "tpu-w0,tpu-w1")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    ssh_calls = []
    monkeypatch.setattr(
        L, "pod_ssh_launcher", lambda args: ssh_calls.append(args) or 0
    )
    local_calls = []
    monkeypatch.setattr(
        L, "multi_process_launcher", lambda args: local_calls.append(args) or 0
    )
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("num_processes: 2\n")
    parser = L.launch_parser()
    rc = L.launch_command(parser.parse_args(["--config_file", str(cfg), "train.py"]))
    assert rc == 0
    assert ssh_calls == [] and len(local_calls) == 1

    # but DEFAULT-valued YAML topology keys (the config wizard writes
    # num_machines: 1 unconditionally) must NOT suppress pod discovery
    ssh_calls.clear()
    local_calls.clear()
    cfg2 = tmp_path / "cfg2.yaml"
    cfg2.write_text("num_machines: 1\nmixed_precision: bf16\n")
    rc = L.launch_command(parser.parse_args(["--config_file", str(cfg2), "train.py"]))
    assert rc == 0
    assert len(ssh_calls) == 1 and local_calls == []


def test_config_precedence_cli_wins(monkeypatch, tmp_path):
    """Explicit CLI flags beat YAML even when they equal a parser default
    (the round-1 sentinel bug: --num_processes 1 was overridden)."""
    from accelerate_tpu.commands import launch as L

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("num_processes: 8\nmachine_rank: 3\nmixed_precision: bf16\n")
    parser = L.launch_parser()

    args = parser.parse_args(["--config_file", str(cfg), "train.py"])
    L._load_config_into_args(args)
    # not given on the CLI -> YAML fills them
    assert args.num_processes == 8 and args.machine_rank == 3 and args.mixed_precision == "bf16"
    assert "num_processes" in args._from_config

    args = parser.parse_args(
        ["--config_file", str(cfg), "--num_processes", "1", "--machine_rank", "0", "train.py"]
    )
    L._load_config_into_args(args)
    # explicitly passed, equal to defaults -> must NOT be overridden
    assert args.num_processes == 1 and args.machine_rank == 0
    assert args.mixed_precision == "bf16"  # still filled from YAML


@pytest.mark.slow
def test_max_restarts_supervisor(tmp_path):
    """Crash-once-then-succeed script: --max_restarts relaunches it with
    ACCELERATE_RESTART_COUNT set (torchelastic analogue; checkpoint-based
    recovery is the script's load_state)."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, pathlib, sys\n"
        f"marker = pathlib.Path({str(tmp_path)!r}) / 'ran_once'\n"
        "if not marker.exists():\n"
        "    marker.write_text('1')\n"
        "    sys.exit(3)\n"
        "assert os.environ['ACCELERATE_RESTART_COUNT'] == '1'\n"
        "print('RECOVERED')\n"
    )
    result = run_cli(
        "launch", "--cpu", "--max_restarts", "1", "--monitor_interval", "0.1", str(script)
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert "RECOVERED" in result.stdout

    # without supervision the crash propagates
    (tmp_path / "ran_once").unlink()
    result = run_cli("launch", "--cpu", str(script))
    assert result.returncode == 3


@pytest.mark.slow
def test_max_restarts_multiprocess_group_restart(tmp_path):
    """One rank crashing takes the group down; the supervisor relaunches
    the whole group and the retry succeeds."""
    script = tmp_path / "flaky_mp.py"
    script.write_text(
        "import os, pathlib, sys\n"
        f"base = pathlib.Path({str(tmp_path)!r})\n"
        "rank = os.environ.get('ACCELERATE_PROCESS_ID', '0')\n"
        "attempt = os.environ['ACCELERATE_RESTART_COUNT']\n"
        "(base / f'saw_{rank}_{attempt}').write_text('1')\n"
        "if attempt == '0' and rank == '1':\n"
        "    sys.exit(5)\n"
        "print('MP_RECOVERED', rank)\n"
    )
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7917", "--max_restarts", "1",
        "--monitor_interval", "0.1", str(script),
        timeout=300,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    # both attempts ran both ranks
    for rank in (0, 1):
        for attempt in (0, 1):
            assert (tmp_path / f"saw_{rank}_{attempt}").exists(), (rank, attempt)
    assert result.stdout.count("MP_RECOVERED") >= 1


@pytest.mark.slow
def test_compression_script_multiprocess():
    """Compressed gradient reduction across two REAL processes (the
    multi-host DCN case the comm-hook analogue exists for)."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4", "-m",
        "accelerate_tpu.test_utils.scripts.test_compression",
        timeout=420,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("test_compression: ALL OK") >= 1


@pytest.mark.slow
def test_data_loop_script_multiprocess():
    """Distributed data-loop script (reference analogue:
    test_utils/scripts/test_distributed_data_loop.py) on two processes."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7815", "-m",
        "accelerate_tpu.test_utils.scripts.test_data_loop",
        timeout=300,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("test_data_loop: ALL OK") >= 1


def test_config_update_migrates_legacy_keys(tmp_path):
    """`config --update` renames legacy keys and drops unknown ones
    (reference analogue: accelerate config update)."""
    cfg = tmp_path / "old.yaml"
    cfg.write_text("dp: 4\nprecision: bf16\nmystery_key: 1\nnum_processes: 2\n")
    result = run_cli("config", "--update", "--config_file", str(cfg))
    assert result.returncode == 0, result.stderr
    from accelerate_tpu.commands.config import load_config

    migrated = load_config(str(cfg))
    assert migrated == {"mesh_data": 4, "mixed_precision": "bf16", "num_processes": 2}
    assert "mystery_key" in result.stdout

    # missing file is a clean error
    result = run_cli("config", "--update", "--config_file", str(tmp_path / "nope.yaml"))
    assert result.returncode == 1


def test_config_update_protects_current_keys_and_bad_casts(tmp_path):
    cfg = tmp_path / "half.yaml"
    cfg.write_text("mixed_precision: bf16\nprecision: fp16\n")
    result = run_cli("config", "--update", "--config_file", str(cfg))
    assert result.returncode == 0, result.stderr
    from accelerate_tpu.commands.config import load_config

    # the stale legacy spelling must not clobber the current value
    assert load_config(str(cfg))["mixed_precision"] == "bf16"

    bad = tmp_path / "bad.yaml"
    bad.write_text("dp: auto\n")
    result = run_cli("config", "--update", "--config_file", str(bad))
    assert result.returncode == 1
    assert "cannot migrate" in result.stdout and "Traceback" not in result.stderr


def test_config_update_reports_dropped_legacy_regardless_of_order(tmp_path):
    """When both the legacy and current spelling are present, the current
    value wins AND the legacy key is reported dropped in either file
    order."""
    from accelerate_tpu.commands.config import load_config

    for text in ("precision: fp16\nmixed_precision: bf16\n", "mixed_precision: bf16\nprecision: fp16\n"):
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text(text)
        result = run_cli("config", "--update", "--config_file", str(cfg))
        assert result.returncode == 0, result.stderr
        assert load_config(str(cfg))["mixed_precision"] == "bf16"
        assert "precision" in result.stdout and "dropped" in result.stdout, (text, result.stdout)


@pytest.mark.slow
def test_performance_gate_script():
    """Accuracy-floor regression gates per mesh layout (reference analogue:
    external_deps/test_performance.py MRPC thresholds per strategy)."""
    result = run_cli(
        "launch", "--cpu", "--fake_devices", "8", "-m",
        "accelerate_tpu.test_utils.scripts.test_performance",
        timeout=900,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert "test_performance: ALL OK" in result.stdout


@pytest.mark.slow
def test_manual_multi_machine_launch(tmp_path):
    """Manual multi-machine topology (reference: multi_gpu_launcher node
    ranks, commands/launch.py:790-822): the launcher is invoked ONCE PER
    MACHINE with --machine_rank 0/1 against one coordinator; global ranks
    are machine_rank * procs_per_machine + local_rank and the 2x2 group
    trains as four processes."""
    script = tmp_path / "mm.py"
    script.write_text(
        "import numpy as np\n"
        "import optax\n"
        "from accelerate_tpu import Accelerator\n"
        "from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn\n"
        "acc = Accelerator()\n"
        "assert acc.num_processes == 4, acc.num_processes\n"
        "ranks = acc.gather_for_metrics([acc.process_index], use_gather_object=True)\n"
        "assert sorted(ranks) == [0, 1, 2, 3], ranks\n"
        "model = acc.prepare_model(RegressionModel())\n"
        "acc.prepare_optimizer(optax.sgd(0.1))\n"
        "step = acc.build_train_step(linear_loss_fn)\n"
        "ds = RegressionDataset(length=64, seed=0)\n"
        "losses = [float(step({'x': ds.x[:16], 'y': ds.y[:16]})) for _ in range(20)]\n"
        "assert losses[-1] < losses[0], losses\n"
        "print('MULTI_MACHINE_OK', acc.process_index)\n"
    )
    common = [
        sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
        "--num_processes", "4", "--num_machines", "2",
        "--main_process_ip", "127.0.0.1", "--main_process_port", "7831",
        "--cpu", "--fake_devices", "2",
    ]
    procs = [
        subprocess.Popen(
            [*common, "--machine_rank", str(mr), str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=CPU_ENV,
        )
        for mr in (0, 1)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n---\n".join(outs)
    assert "MULTI_MACHINE_OK" in "".join(outs)


@pytest.mark.slow
def test_multi_machine_rejects_indivisible_topology(tmp_path):
    script = tmp_path / "noop.py"
    script.write_text("print('never runs')\n")
    result = run_cli(
        "launch", "--num_processes", "3", "--num_machines", "2", "--cpu",
        str(script),
    )
    assert result.returncode != 0
    assert "divisible" in result.stderr


@pytest.mark.slow
def test_script_multiprocess():
    """The canonical "does distributed work" script (reference analogue:
    test_utils/scripts/test_script.py run by tests/test_multigpu.py:49)
    under two REAL processes."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7829", "-m",
        "accelerate_tpu.test_utils.scripts.test_script",
        timeout=600,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert result.stdout.count("ALL CHECKS PASSED") >= 1


@pytest.mark.slow
def test_pipeline_bubble_pipe8_multiprocess():
    """pipe=8 GPipe rows measured under the REAL 2-process launcher
    (collective-permutes cross the process boundary) with the structural
    HLO checks green: reduce-scatter output (no replication psum) when
    microbatches divide over stages."""
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7833", "-m", "benchmarks.pipeline_bubble",
        "--", "--stages", "8", "--width", "1024", "--layers", "8", "--batch", "64",
        timeout=600,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    rows = [json.loads(line) for line in result.stdout.splitlines() if line.startswith("{")]
    pipe8 = [r for r in rows if r.get("stages") == 8]
    assert pipe8 and all(r["structural_ok"] for r in pipe8), rows
    assert all(r["multiprocess"] for r in pipe8)
    # schedule waste beyond the tick structure stays bounded (the
    # fake-mesh-meaningful bound; t_seq/S parallel speedup cannot exist on
    # shared host cores — documented in the benchmark)
    assert min(r["overhead_vs_serialized_bound"] for r in pipe8) <= 1.25, pipe8


@pytest.mark.slow
def test_checkpoint_resume_script_multiprocess(tmp_path):
    """2-process orbax checkpoint round-trip through the real launcher
    (reference analogue: test_state_checkpointing.py, run distributed)."""
    env = {**CPU_ENV, "ACCELERATE_TEST_CKPT_DIR": str(tmp_path / "ck")}
    result = run_cli(
        "launch", "--num_processes", "2", "--cpu", "--fake_devices", "4",
        "--main_process_port", "7823", "-m",
        "accelerate_tpu.test_utils.scripts.test_checkpoint_resume",
        env=env, timeout=420,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert "test_checkpoint_resume: ALL OK" in result.stdout


def test_config_yaml_templates_are_valid():
    """Every shipped template (examples/config_yaml_templates/, reference
    analogue: the same directory upstream) round-trips through the real
    loader with no key silently dropped."""
    import pathlib

    from accelerate_tpu.commands.config import CONFIG_KEYS, load_config, _load_yaml

    tdir = pathlib.Path(__file__).parent.parent / "examples" / "config_yaml_templates"
    templates = sorted(tdir.glob("*.yaml"))
    assert len(templates) >= 6, templates
    for path in templates:
        raw = _load_yaml(path.read_text())
        unknown = set(raw) - set(CONFIG_KEYS)
        assert not unknown, f"{path.name}: unknown keys {unknown}"
        loaded = load_config(str(path))
        assert set(loaded) == set(raw), f"{path.name}: keys dropped by loader"
        assert loaded["num_processes"] >= 1 and loaded["num_machines"] >= 1


@pytest.mark.slow
def test_config_template_run_me():
    """run_me.py launches under a template with CLI overrides winning
    (reference: config_yaml_templates/run_me.py)."""
    import pathlib

    tdir = pathlib.Path(__file__).parent.parent / "examples" / "config_yaml_templates"
    result = run_cli(
        "launch", "--config_file", str(tdir / "hybrid_mesh.yaml"),
        "--num_processes", "1", "--cpu", "--fake_devices", "8",
        str(tdir / "run_me.py"), timeout=300,
    )
    assert result.returncode == 0, result.stderr + result.stdout
    assert "Accelerator state" in result.stdout


# --------------------------------------------------------------------- #
# accelerate-tpu lint (the TPU correctness linter CLI)
# --------------------------------------------------------------------- #


def test_lint_repo_tree_clean():
    """The package tree must carry zero error-severity findings."""
    import pathlib

    pkg = pathlib.Path(__file__).parent.parent / "accelerate_tpu"
    result = run_cli("lint", str(pkg))
    assert result.returncode == 0, result.stdout + result.stderr
    assert "0 error(s)" in result.stdout


def test_lint_detects_seeded_defects_and_exits_nonzero(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        '"""Fixture."""\n'
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    if x > 0:\n"
        "        return jax.device_get(x)\n"
        "    return x\n"
    )
    result = run_cli("lint", str(bad))
    assert result.returncode == 1, result.stdout + result.stderr
    assert "TPU201" in result.stdout  # device_get in jit (error)
    assert "TPU202" in result.stdout  # tracer branch (warning)
    assert f"{bad}:8: TPU201" in result.stdout  # path:line: TPUxxx format


def test_lint_json_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n")
    result = run_cli("lint", str(bad), "--format", "json")
    payload = json.loads(result.stdout)
    assert {f["rule"] for f in payload} == {"TPU001", "TPU002"}
    assert all(f["severity"] == "error" for f in payload)


def test_lint_select_ignore_and_suppression(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os  # tpu-lint: disable=TPU001\n")
    result = run_cli("lint", str(bad), "--ignore", "TPU002")
    assert result.returncode == 0, result.stdout
    assert "0 finding(s)" in result.stdout


def test_lint_sarif_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n")
    result = run_cli("lint", str(bad), "--format", "sarif")
    doc = json.loads(result.stdout)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert {r["ruleId"] for r in results} == {"TPU001", "TPU002"}
    uri = results[0]["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
    assert uri == str(bad)


@pytest.mark.slow
def test_lint_selfcheck():
    """Every rule detects its seeded-defect fixture (CPU fake mesh)."""
    result = run_cli("lint", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 44  # 6 AST + 4 jaxpr + 3 flight + 5 divergence + 5 perf + 6 numerics + 5 config + 5 pipe + 5 fleet
    assert "honoured" in result.stdout
    assert "clean idiomatic script: zero findings" in result.stdout


# --------------------------------------------------------------------------- #
# accelerate-tpu fleet-check (TPU9xx host-concurrency + protocol gate)
# --------------------------------------------------------------------------- #

_DEADLOCK_SRC = """\
import threading

class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def route(self):
        with self._lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._stats_lock:
            with self._lock:
                pass
"""


def test_fleet_check_dogfoods_clean_and_proves_protocol():
    result = run_cli(
        "fleet-check",
        "accelerate_tpu/serving_fleet.py", "accelerate_tpu/scheduling.py", "accelerate_tpu/ft",
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "protocol:" in result.stdout and "states explored" in result.stdout
    assert "0 finding(s)" in result.stdout


def test_fleet_check_selfcheck():
    result = run_cli("fleet-check", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 5  # TPU901/902/903/905 + 904
    assert result.stdout.count("clean twin") == 5
    assert "MISSED" not in result.stdout and "DIRTY" not in result.stdout


def test_fleet_check_seeded_deadlock_gates_strictly(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_DEADLOCK_SRC)
    result = run_cli("fleet-check", str(bad), "--no-protocol")
    assert result.returncode == 1  # TPU901 is error severity: strict by default
    assert "TPU901" in result.stdout

    sarif = run_cli("fleet-check", str(bad), "--no-protocol", "--format", "sarif")
    doc = json.loads(sarif.stdout)
    assert [r["ruleId"] for r in doc["runs"][0]["results"]] == ["TPU901"]


def test_fleet_check_json_embeds_full_coverage_map():
    result = run_cli("fleet-check", "--format", "json")
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["findings"] == []
    proto = doc["protocol"]
    assert proto["explored_states"] > 1000 and not proto["truncated"]
    # model-checks = chaos-observes: every explored path pinned to a test
    assert proto["coverage"] and all(t for t in proto["coverage"].values())
    assert proto["coverage"]["poison/quarantine_no_kv"].startswith("test_chaos_poison")


def _seed_git_repo(repo):
    def git(*a):
        subprocess.run(
            ["git", *a], cwd=repo, capture_output=True, check=True,
            env={**CPU_ENV, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t", "HOME": str(repo)},
        )
    git("init", "-b", "main")
    # a committed file with findings that --changed must NOT rescan
    (repo / "old.py").write_text("import os\n")
    git("add", "-A")
    git("commit", "-m", "seed")


def test_lint_changed_scopes_to_git_touched_files(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    _seed_git_repo(repo)
    (repo / "new.py").write_text("import os\n")  # untracked: in scope
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "lint", "--changed", "--format", "json"],
        capture_output=True, text=True, env=CPU_ENV, cwd=repo, timeout=240,
    )
    assert result.returncode == 1, result.stdout + result.stderr  # TPU001 is an error
    paths = {f["path"] for f in json.loads(result.stdout)}
    assert paths and all(p.endswith("new.py") for p in paths), paths


def test_divergence_changed_scopes_too(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    _seed_git_repo(repo)
    (repo / "diverge.py").write_text(
        '"""Changed file with a rank-divergent gather."""\n'
        "def main(accelerator):\n"
        "    if accelerator.is_main_process:\n"
        "        accelerator.gather(1)\n"
    )
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "divergence", "--changed", "--format", "json"],
        capture_output=True, text=True, env=CPU_ENV, cwd=repo, timeout=240,
    )
    assert result.returncode == 1, result.stdout + result.stderr
    findings = json.loads(result.stdout)
    assert {f["rule"] for f in findings} == {"TPU401"}
    assert all(f["path"].endswith("diverge.py") for f in findings)


def test_fleet_check_changed_scopes_too(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    _seed_git_repo(repo)
    (repo / "dead.py").write_text(_DEADLOCK_SRC)
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "fleet-check",
         "--changed", "--no-protocol"],
        capture_output=True, text=True, env=CPU_ENV, cwd=repo, timeout=240,
    )
    assert result.returncode == 1
    assert "TPU901" in result.stdout and "old.py" not in result.stdout


def test_lint_sarif_merges_six_runs(tmp_path):
    """The Makefile's lint-sarif artifact carries one runs[] entry per
    analysis tier — AST, divergence, numerics, pipe, fleet, kernel. Pin
    the count in the recipe AND prove merge_sarif keeps all six."""
    makefile = open(os.path.join(os.path.dirname(__file__), "..", "Makefile")).read()
    recipe = makefile.split("lint-sarif:")[1].split("\n\n")[0]
    inputs = [tok for tok in recipe.split() if tok.startswith(".cache/") and tok.endswith(".sarif")]
    merge_line = next(l for l in recipe.splitlines() if "merge_sarif.py" in l)
    merged_inputs = [t for t in merge_line.split() if t.endswith(".sarif") and t != "lint-merged.sarif"]
    assert len(merged_inputs) == 6, merged_inputs
    assert ".cache/fleet.sarif" in merged_inputs and ".cache/kernel.sarif" in merged_inputs
    assert sorted(set(inputs)) == sorted(merged_inputs)

    from accelerate_tpu.analysis import Finding, render_sarif

    files = []
    for i in range(6):
        p = tmp_path / f"run{i}.sarif"
        p.write_text(render_sarif([Finding("TPU901", f"finding {i}")]))
        files.append(str(p))
    merged_path = tmp_path / "merged.sarif"
    repo = os.path.join(os.path.dirname(__file__), "..")
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "merge_sarif.py"), *files,
         "-o", str(merged_path)],
        capture_output=True, text=True, env=CPU_ENV,
    )
    assert result.returncode == 0, result.stderr
    assert len(json.loads(merged_path.read_text())["runs"]) == 6


# --------------------------------------------------------------------------- #
# accelerate-tpu checkpoints (fault-tolerance CLI)
# --------------------------------------------------------------------------- #


def _seed_checkpoint_fixtures(base):
    """Seed one good, one corrupt, and one uncommitted checkpoint using
    the manifest layer directly (no jax in the test process)."""
    import pickle

    from accelerate_tpu.ft.manifest import TMP_SUFFIX, build_manifest, write_manifest
    from accelerate_tpu.test_utils.fault_injection import corrupt_file

    def seed(n):
        d = base / f"checkpoint_{n}"
        (d / "model").mkdir(parents=True)
        (d / "model" / "arrays.bin").write_bytes(bytes(range(256)))
        (d / "accelerate_state.json").write_text(json.dumps({"step": n * 10, "save_iteration": n}))
        with open(d / "rng_state_0.pkl", "wb") as f:
            pickle.dump({"seed": 1}, f)
        write_manifest(d, build_manifest(d, step=n * 10, iteration=n))
        return d

    seed(0)
    corrupt_file(seed(1) / "accelerate_state.json", mode="garbage")
    partial = base / f"checkpoint_2{TMP_SUFFIX}"
    partial.mkdir(parents=True)
    (partial / "half_written.bin").write_bytes(b"x" * 32)


def test_checkpoints_list_and_verify(tmp_path):
    base = tmp_path / "checkpoints"
    _seed_checkpoint_fixtures(base)

    result = run_cli("checkpoints", "list", str(base), "--deep", "--format", "json")
    assert result.returncode == 0, result.stderr
    rows = {r["name"]: r for r in json.loads(result.stdout)["checkpoints"]}
    assert rows["checkpoint_0"]["valid"] and rows["checkpoint_0"]["step"] == 0
    assert not rows["checkpoint_1"]["valid"]
    assert "uncommitted" in rows["checkpoint_2.tmp"]["state"]

    result = run_cli("checkpoints", "verify", str(base))
    assert result.returncode == 1  # one checkpoint is corrupt
    assert "[OK ] checkpoint_0" in result.stdout
    assert "[BAD] checkpoint_1" in result.stdout and "crc32" in result.stdout

    result = run_cli("checkpoints", "verify", str(base / "checkpoint_0"))
    assert result.returncode == 0, result.stdout


def test_checkpoints_gc(tmp_path):
    base = tmp_path / "checkpoints"
    _seed_checkpoint_fixtures(base)

    result = run_cli("checkpoints", "gc", str(base), "--dry-run")
    assert result.returncode == 0
    assert (base / "checkpoint_2.tmp").exists(), "dry-run must not delete"

    result = run_cli("checkpoints", "gc", str(base), "--format", "json")
    assert result.returncode == 0
    report = json.loads(result.stdout)
    assert "checkpoint_2.tmp" in report["removed"]
    assert not (base / "checkpoint_2.tmp").exists()


def _seed_topology_checkpoint(base):
    """A committed checkpoint whose (v2) manifest carries a topology
    record — saved on mesh data=4, 2 processes."""
    from accelerate_tpu.ft.manifest import build_manifest, write_manifest

    d = base / "checkpoint_0"
    (d / "model").mkdir(parents=True)
    (d / "model" / "arrays.bin").write_bytes(bytes(range(64)))
    (d / "accelerate_state.json").write_text(json.dumps({"step": 12, "seed": 5}))
    topology = {
        "schema_version": 1,
        "process_count": 2,
        "mesh_shape": {"data": 4, "tensor": 1},
        "mesh_devices": 4,
        "dcn_axes": [],
        "data_parallel_degree": 4,
        "seed": 5,
        "arrays": {
            "model['w']": {"shape": [16, 16], "dtype": "float32", "spec": ["data", None], "bytes": 1024},
        },
    }
    write_manifest(d, build_manifest(d, step=12, iteration=0, topology=topology))
    return d


def test_checkpoints_describe_matching_and_mismatching(tmp_path):
    base = tmp_path / "checkpoints"
    ck = _seed_topology_checkpoint(base)

    # no --mesh: checked against the saved topology itself -> identical
    result = run_cli("checkpoints", "describe", str(ck), "--format", "json")
    assert result.returncode == 0, result.stderr
    info = json.loads(result.stdout)
    assert info["compatibility"] == "identical"
    assert info["reshard"]["total_bytes"] == 0
    assert info["saved_topology"]["mesh_shape"]["data"] == 4

    # mismatching target mesh -> elastic, with a nonzero reshard estimate
    result = run_cli(
        "checkpoints", "describe", str(ck),
        "--mesh", "data=4,fsdp=2", "--dcn-axes", "fsdp", "--processes", "4",
        "--format", "json",
    )
    assert result.returncode == 0, result.stderr
    info = json.loads(result.stdout)
    assert info["compatibility"] == "elastic"
    assert any("process count" in c for c in info["changes"])
    assert info["reshard"]["dcn_bytes"] == 1024 // 2  # 2-way DCN ring stage
    assert info["reshard"]["ici_bytes"] == 1024 * 3 // 4  # 4-way ICI stage

    # text output names the verdict and the traffic split
    result = run_cli("checkpoints", "describe", str(ck), "--mesh", "data=8")
    assert result.returncode == 0
    assert "ELASTIC" in result.stdout and "predicted reshard traffic" in result.stdout
    # base-dir form resolves to the newest valid checkpoint
    result = run_cli("checkpoints", "describe", str(base))
    assert result.returncode == 0
    assert "IDENTICAL" in result.stdout


def test_checkpoints_describe_no_topology(tmp_path):
    base = tmp_path / "checkpoints"
    _seed_checkpoint_fixtures(base)  # v2 manifests without topology blocks
    result = run_cli("checkpoints", "describe", str(base / "checkpoint_0"), "--format", "json")
    assert result.returncode == 0, result.stderr
    info = json.loads(result.stdout)
    assert info["compatibility"] == "unknown"
    assert info["saved_topology"] is None


def test_checkpoints_selfcheck():
    """The make ft-selfcheck gate: seeded fixtures classify correctly."""
    result = run_cli("checkpoints", "verify", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert "[checkpoints selfcheck] OK" in result.stdout
    assert "describe classifies" in result.stdout
