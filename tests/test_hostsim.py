"""Tier-9a host-concurrency lint (analysis.hostsim): lock-order graph,
cross-thread attribute map, blocking-under-lock, thread lifecycle —
plus the shared --changed git scoping (analysis.changed)."""

import subprocess
import textwrap

import pytest

from accelerate_tpu.analysis.hostsim import (
    host_check_file,
    host_check_paths,
    host_check_source,
)


def _rules(src, **kw):
    return [f.rule for f in host_check_source(textwrap.dedent(src), path="<t>", **kw)]


# --------------------------------------------------------------------------- #
# TPU901: lock-order inversion
# --------------------------------------------------------------------------- #

_ABBA = """
import threading

class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def route(self):
        with self._lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._stats_lock:
            with self._lock:
                pass
"""


def test_tpu901_abba_inversion_detected_and_message_names_both_sites():
    findings = host_check_source(textwrap.dedent(_ABBA), path="<t>")
    assert [f.rule for f in findings] == ["TPU901"]
    msg = findings[0].message
    assert "Router._lock" in msg and "Router._stats_lock" in msg
    assert "Router.route" in msg and "Router.report" in msg


def test_tpu901_consistent_order_is_clean():
    clean = _ABBA.replace(
        "with self._stats_lock:\n            with self._lock:",
        "with self._lock:\n            with self._stats_lock:",
    )
    assert _rules(clean) == []


def test_tpu901_one_call_deep_inversion():
    # the second lock is taken inside a method called while holding the
    # first — the cycle only exists across the call edge
    src = """
    import threading

    class R:
        def __init__(self):
            self.a_lock = threading.Lock()
            self.b_lock = threading.Lock()

        def _inner(self):
            with self.b_lock:
                pass

        def path1(self):
            with self.a_lock:
                self._inner()

        def path2(self):
            with self.b_lock:
                with self.a_lock:
                    pass
    """
    assert "TPU901" in _rules(src)


def test_tpu901_plain_lock_self_nest_flagged_rlock_exempt():
    src = """
    import threading

    class R:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self):
            with self._lock:
                with self._lock:
                    pass
    """
    assert "TPU901" in _rules(src)
    assert _rules(src.replace("threading.Lock()", "threading.RLock()")) == []


def test_tpu901_cross_class_nesting_one_direction_is_clean():
    # the serving_fleet convention: Replica.lock -> FleetRouter._lock,
    # never reversed
    src = """
    import threading

    class Replica:
        def __init__(self):
            self.lock = threading.RLock()

    class Router:
        def __init__(self):
            self._lock = threading.RLock()

        def migrate(self, rep):
            with rep.lock:
                with self._lock:
                    pass

        def poll(self, rep):
            with rep.lock:
                with self._lock:
                    pass
    """
    assert _rules(src) == []


# --------------------------------------------------------------------------- #
# TPU902: cross-thread attribute without the owning lock
# --------------------------------------------------------------------------- #

_RACE = """
import threading

class Fleet:
    def __init__(self):
        self._lock = threading.Lock()
        self.health = "healthy"

    def set_health(self, v):
        self.health = v

    def drain(self):
        def worker():
            if self.health == "healthy":
                pass
        t = threading.Thread(target=worker, daemon=True)
        t.start()
        self.set_health("dead")
"""


def test_tpu902_unlocked_cross_thread_write_detected():
    findings = host_check_source(textwrap.dedent(_RACE), path="<t>")
    assert [f.rule for f in findings] == ["TPU902"]
    assert "Fleet.health" in findings[0].message
    assert "worker" in findings[0].message


def test_tpu902_lock_on_both_sides_is_clean():
    fixed = _RACE.replace(
        "    def set_health(self, v):\n        self.health = v",
        "    def set_health(self, v):\n        with self._lock:\n            self.health = v",
    ).replace(
        "            if self.health == \"healthy\":\n                pass",
        "            with self._lock:\n                if self.health == \"healthy\":\n                    pass",
    )
    assert _rules(fixed) == []


def test_tpu902_init_writes_are_exempt():
    # construction happens-before thread publication: an unguarded
    # __init__ write must not fire (nor poison the lock analysis)
    src = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def bump(self):
            with self._lock:
                self.count += 1

        def spin(self):
            def worker():
                with self._lock:
                    if self.count:
                        pass
            threading.Thread(target=worker, daemon=True).start()
    """
    assert _rules(src) == []


def test_tpu902_property_reads_resolve_to_backing_attribute():
    # reading rep.is_serving is reading rep.health — the lint must see
    # through the property (the real serving_fleet finding's shape)
    src = """
    import threading

    class Replica:
        def __init__(self):
            self.lock = threading.RLock()
            self.health = "healthy"

        @property
        def is_serving(self):
            return self.health in ("healthy", "degraded")

    class Router:
        def set_health(self, rep, state):
            rep.health = state

        def drain(self, rep):
            def worker():
                if rep.is_serving:
                    pass
            threading.Thread(target=worker, daemon=True).start()
            self.set_health(rep, "dead")
    """
    findings = host_check_source(textwrap.dedent(src), path="<t>")
    assert [f.rule for f in findings] == ["TPU902"]
    assert "Replica.health" in findings[0].message


def test_tpu902_single_thread_module_is_quiet():
    src = """
    class Accounting:
        def __init__(self):
            self.total = 0

        def add(self, n):
            self.total += n
    """
    assert _rules(src) == []


# --------------------------------------------------------------------------- #
# TPU903: blocking call while holding a lock
# --------------------------------------------------------------------------- #


def test_tpu903_sleep_under_lock_priced():
    src = """
    import threading, time

    class P:
        def __init__(self):
            self._lock = threading.Lock()

        def poll(self):
            with self._lock:
                time.sleep(0.25)
    """
    findings = host_check_source(textwrap.dedent(src), path="<t>")
    assert [f.rule for f in findings] == ["TPU903"]
    assert ">=0.25s per call" in findings[0].message
    assert "P._lock" in findings[0].message


def test_tpu903_join_and_queue_get_and_device_sync_under_lock():
    src = """
    import queue
    import threading

    class P:
        def __init__(self):
            self._lock = threading.Lock()
            self.q = queue.Queue()

        def a(self, t):
            with self._lock:
                t.join()

        def b(self):
            with self._lock:
                item = self.q.get()
            return item

        def c(self, x):
            with self._lock:
                x.block_until_ready()
    """
    assert _rules(src) == ["TPU903", "TPU903", "TPU903"]


def test_tpu903_sleep_outside_lock_and_str_join_are_clean():
    src = """
    import os
    import threading, time

    class P:
        def __init__(self):
            self._lock = threading.Lock()

        def poll(self, parts):
            time.sleep(0.25)
            with self._lock:
                name = ",".join(parts)
                path = os.path.join("a", "b")
            return name, path
    """
    assert _rules(src) == []


def test_tpu903_one_call_deep_blocking_inherits_caller_lock():
    src = """
    import threading, time

    class P:
        def __init__(self):
            self._lock = threading.Lock()

        def _wait(self):
            time.sleep(1.0)

        def poll(self):
            with self._lock:
                self._wait()
    """
    assert "TPU903" in _rules(src)


# --------------------------------------------------------------------------- #
# TPU905: thread lifecycle
# --------------------------------------------------------------------------- #


def test_tpu905_unjoined_non_daemon_thread():
    src = """
    import threading

    def launch(work):
        t = threading.Thread(target=work)
        t.start()
    """
    assert _rules(src) == ["TPU905"]


def test_tpu905_joined_or_daemon_threads_are_clean():
    src = """
    import threading

    def launch(work):
        t = threading.Thread(target=work)
        t.start()
        t.join()
        d = threading.Thread(target=work, daemon=True)
        d.start()

    def launch_many(work):
        ts = [threading.Thread(target=work) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    """
    assert _rules(src) == []


def test_tpu905_worker_swallowed_exception():
    src = """
    import threading

    class W:
        def run_all(self):
            def worker():
                try:
                    self.step()
                except Exception:
                    pass
            ts = [threading.Thread(target=worker, daemon=True) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    """
    findings = host_check_source(textwrap.dedent(src), path="<t>")
    assert [f.rule for f in findings] == ["TPU905"]
    assert "swallows its exception" in findings[0].message


def test_tpu905_worker_recording_errors_is_clean():
    # the post-PR-15 drain_threaded shape: error captured for the caller
    src = """
    import threading

    class W:
        def run_all(self):
            errors = []
            err_lock = threading.Lock()

            def worker():
                try:
                    self.step()
                except Exception as e:
                    with err_lock:
                        errors.append(e)
            ts = [threading.Thread(target=worker, daemon=True) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return errors
    """
    assert _rules(src) == []


# --------------------------------------------------------------------------- #
# plumbing: suppressions, select/ignore, paths, syntax errors
# --------------------------------------------------------------------------- #


def test_inline_suppression_and_select_ignore():
    sup = _RACE.replace("self.health = v", "self.health = v  # tpu-lint: disable=TPU902")
    assert _rules(sup) == []
    assert _rules(_RACE, select=("TPU901",)) == []
    assert _rules(_RACE, ignore=("TPU902",)) == []


def test_host_check_paths_walks_directories(tmp_path):
    (tmp_path / "race.py").write_text(textwrap.dedent(_RACE))
    (tmp_path / "clean.py").write_text("x = 1\n")
    sub = tmp_path / "__pycache__"
    sub.mkdir()
    (sub / "skip.py").write_text(textwrap.dedent(_RACE))
    findings = host_check_paths([tmp_path])
    assert [f.rule for f in findings] == ["TPU902"]
    assert findings[0].path.endswith("race.py")


def test_syntax_error_is_tpu003(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    findings = host_check_file(bad)
    assert [f.rule for f in findings] == ["TPU003"]


def test_dogfood_fleet_surface_is_clean():
    """The shipped fleet layer passes its own gate: the dogfooded TPU902
    (Replica.health written without rep.lock while the drain_threaded
    workers read is_serving) stays fixed."""
    findings = host_check_paths(
        [
            "accelerate_tpu/serving_fleet.py",
            "accelerate_tpu/scheduling.py",
            "accelerate_tpu/ft",
        ]
    )
    assert findings == []


# --------------------------------------------------------------------------- #
# --changed scoping (analysis.changed)
# --------------------------------------------------------------------------- #


def _git(repo, *args):
    return subprocess.run(
        ["git", *args], cwd=repo, capture_output=True, text=True, check=True,
        env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t", "GIT_COMMITTER_NAME": "t",
             "GIT_COMMITTER_EMAIL": "t@t", "HOME": str(repo), "PATH": "/usr/bin:/bin:/usr/local/bin"},
    )


@pytest.fixture
def git_repo(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-b", "main")
    (repo / "base.py").write_text("x = 1\n")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-m", "seed")
    return repo


def test_changed_python_files_sees_working_tree_and_untracked(git_repo):
    from accelerate_tpu.analysis.changed import changed_python_files

    assert changed_python_files(git_repo) == []
    (git_repo / "base.py").write_text("x = 2\n")  # unstaged edit
    (git_repo / "fresh.py").write_text("y = 1\n")  # untracked
    (git_repo / "notes.txt").write_text("no\n")  # not python
    got = changed_python_files(git_repo)
    assert [p.split("/")[-1] for p in got] == ["base.py", "fresh.py"]


def test_changed_python_files_sees_branch_commits(git_repo):
    from accelerate_tpu.analysis.changed import changed_python_files

    _git(git_repo, "checkout", "-b", "feature")
    (git_repo / "feat.py").write_text("z = 1\n")
    _git(git_repo, "add", "-A")
    _git(git_repo, "commit", "-m", "feat")
    got = changed_python_files(git_repo)
    assert [p.split("/")[-1] for p in got] == ["feat.py"]


def test_changed_python_files_none_outside_git(tmp_path):
    from accelerate_tpu.analysis.changed import changed_python_files

    assert changed_python_files(tmp_path) is None
