"""Autotuner tests: the typed search space (``analysis.searchspace``),
the analyzer-oracle tuner (``analysis.tuner``), the TPU7xx configuration
rules (``analysis.tune_rules``), the ``accelerate-tpu tune`` CLI, and —
the pinned oracle contract — the perfmodel ranking TRUST test: on two
toy workloads with four configs each, the statically predicted
step-time ordering must match the StepTelemetry-measured ordering
(top-1 agreement + Spearman >= 0.8 on CPU)."""

import json
import os
import subprocess
import sys
import textwrap
import warnings

import pytest

from accelerate_tpu.analysis.searchspace import (
    ConfigPoint,
    SearchSpace,
    chosen_toml,
    default_space,
    format_mesh_spec,
    load_chosen,
    load_tune_section,
    parse_mesh_spec,
    prune_reason,
)

CPU_ENV = {
    **os.environ,
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, env=None, timeout=420, cwd=None):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True, text=True, env=env or CPU_ENV, timeout=timeout, cwd=cwd,
    )


# --------------------------------------------------------------------- #
# searchspace: ConfigPoint / SearchSpace / pruning / [tune.chosen]
# --------------------------------------------------------------------- #


def test_configpoint_normalization_and_label():
    p = ConfigPoint(mesh="data=4,tensor=2", buckets="32,128", compression="none")
    assert p.mesh_shape == {"data": 4, "tensor": 2}
    assert p.mesh_devices == 8
    assert p.buckets == (32, 128)
    assert p.compression is None  # "none" normalises away
    assert "data=4,tensor=2" in p.label() and "buckets=32,128" in p.label()
    # hashable (dedup in enumeration relies on it)
    assert hash(p) == hash(ConfigPoint(mesh={"data": 4, "tensor": 2}, buckets=(32, 128)))


def test_configpoint_dict_roundtrip():
    p = ConfigPoint(mesh="data=8", zero_stage=1, compression="int8",
                    token_budget=64, routing="least_loaded")
    q = ConfigPoint.from_dict(p.as_dict())
    assert q == p


def test_parse_mesh_spec():
    assert parse_mesh_spec("data=8") == {"data": 8}
    assert parse_mesh_spec({"data": 2, "tensor": 4}) == {"data": 2, "tensor": 4}
    assert format_mesh_spec({"data": 2, "tensor": 4}) == "data=2,tensor=4"
    with pytest.raises(ValueError):
        parse_mesh_spec("data8")


@pytest.mark.parametrize(
    "point,fragment",
    [
        (dict(mesh="data=16"), "devices"),
        (dict(mesh="banana=8"), "unknown mesh axis"),
        (dict(mesh="data=1", zero_stage=1), "needs a data axis"),
        (dict(mesh="data=4,tensor=2", zero_stage=1), "batch axes only"),
        (dict(mesh="data=8", dcn_axes="expert"), "not a mesh axis"),
        (dict(mesh="data=1", compression="int8"), "no data axis to compress"),
        (dict(compression="zstd"), "unknown compression"),
        (dict(buckets=(64, 32)), "ascending"),
        (dict(token_budget=8, tick_block=8, num_slots=4), "starves decode"),
        (dict(routing="random"), "unknown routing"),
        (dict(handoff="maybe"), "unknown handoff"),
        (dict(token_budget=0), "positive"),
    ],
)
def test_prune_constraints(point, fragment):
    reason = prune_reason(ConfigPoint(**point), max_devices=8)
    assert reason is not None and fragment in reason


def test_prune_accepts_valid_points():
    for kw in (
        dict(mesh="data=8", zero_stage=1, compression="int8"),
        dict(buckets=(32, 128), token_budget=64, tick_block=8, num_slots=4),
        dict(mesh="data=4,tensor=2", dcn_axes="data"),
    ):
        assert prune_reason(ConfigPoint(**kw), max_devices=8) is None


def test_searchspace_enumeration_and_dedup():
    space = SearchSpace(
        meshes=("data=8", "data=4,tensor=2"),
        zero_stages=(0, 1),
        compressions=("none", "int8"),
        max_devices=8,
    )
    pts = space.enumerate_points()
    assert len(pts) == space.size() == 8
    valid = space.valid_points()
    assert len(valid) == 6  # zero1-on-tensor-mesh combos pruned
    assert len({p for p, _ in pts}) == len(pts)
    reasons = [r for _, r in pts if r]
    assert all("batch axes only" in r for r in reasons)


def test_searchspace_from_spec_string_forms():
    space = SearchSpace.from_spec(
        {"meshes": ["data=8"], "bucket_sets": ["32,128", "64,256"],
         "token_budgets": [64, 128], "slots": 4},
        max_devices=8,
    )
    assert space.bucket_sets == ((32, 128), (64, 256))
    assert space.slot_counts == (4,)
    assert space.size() == 4


def test_default_space_prunes_to_runnable(mesh8):
    space = default_space(8)
    valid = space.valid_points()
    assert len(valid) >= 4
    assert all(prune_reason(p, max_devices=8) is None for p in valid)


def test_chosen_toml_roundtrip(tmp_path, monkeypatch):
    p = ConfigPoint(mesh="data=8", zero_stage=1, compression="int8", buckets=(32, 128))
    block = chosen_toml(p, predicted_step_ms=1.25)
    assert block.startswith("[tune.chosen]")
    (tmp_path / ".tpulint.toml").write_text("[tune]\ntop_k = 2\n\n" + block + "\n")
    monkeypatch.chdir(tmp_path)
    loaded = load_chosen()
    assert loaded == p
    section = load_tune_section()
    assert section["top_k"] == 2
    assert section["chosen"]["mesh"] == "data=8"


def test_chosen_feeds_parallelism_plugin(tmp_path, monkeypatch):
    (tmp_path / ".tpulint.toml").write_text(
        '[tune.chosen]\nmesh = "data=2,tensor=4"\nzero_stage = 0\ncompression = "int8"\n'
        'buckets = [32, 128]\ntoken_budget = 64\ntick_block = 8\n'
    )
    monkeypatch.chdir(tmp_path)
    point = load_chosen()
    kwargs = point.parallelism_kwargs()
    assert kwargs["zero_stage"] == 0 and kwargs["grad_compression"] == "int8"
    assert kwargs["mesh_config"].data == 2 and kwargs["mesh_config"].tensor == 4
    serving = point.serving_kwargs()
    assert serving["prompt_buckets"] == (32, 128)
    assert serving["scheduler"] == {"token_budget": 64, "tick_block": 8}


# --------------------------------------------------------------------- #
# TPU7xx configuration rules
# --------------------------------------------------------------------- #


def test_tpu703_waste_math():
    from accelerate_tpu.analysis.tune_rules import check_bucket_waste, padding_waste

    waste, detail = padding_waste((32,), {24: 100})
    assert waste == pytest.approx(8 / 24)
    assert detail[24] == (32, 800)
    assert check_bucket_waste((32,), {24: 100}, threshold=0.25)  # 33% > 25%
    assert not check_bucket_waste((32,), {24: 100}, threshold=0.40)
    # sizes above the largest bucket pad to it (honest denominator)
    waste_over, _ = padding_waste((32,), {64: 10})
    assert waste_over == 0.0


def test_tpu704_measured_sites_path():
    from accelerate_tpu.analysis.tune_rules import check_wire_upcast

    sites = [{"prim": "psum", "result_bytes": 4096, "group_size": 8,
              "dtypes": {"f32": 4096}}]
    hits = check_wire_upcast("bf16", sites=sites)
    assert hits and hits[0].rule == "TPU704" and "f32" in hits[0].message
    narrow = [{"prim": "psum", "result_bytes": 1024, "group_size": 8,
               "dtypes": {"s8": 1024}}]
    assert not check_wire_upcast("int8", sites=narrow)


def test_tpu705_structural_probe_real_optax():
    optax = pytest.importorskip("optax")
    from accelerate_tpu.analysis.tune_rules import check_zero1_optimizer

    fired = check_zero1_optimizer(1, optax.adafactor(1e-3))
    assert fired and fired[0].rule == "TPU705"
    assert not check_zero1_optimizer(1, optax.adamw(1e-3))
    assert not check_zero1_optimizer(0, optax.adafactor(1e-3))


def test_run_tune_selfcheck(mesh8):
    from accelerate_tpu.analysis.selfcheck import run_tune_selfcheck

    ok, lines = run_tune_selfcheck(mesh8)
    assert ok, "\n".join(lines)
    assert sum("detected" in line for line in lines) == 5
    assert sum("zero findings" in line for line in lines) == 5


# --------------------------------------------------------------------- #
# the tuner: static scoring, pruning, ranking, findings
# --------------------------------------------------------------------- #


def _token_factory(hidden=128):
    """Workload whose compute scales with the candidate's token budget —
    predictable ordering by construction."""
    import jax
    import jax.numpy as jnp

    def factory(point):
        tokens = point.token_budget or 32

        def step(w, x):
            return jnp.tanh(jnp.tanh(x @ w) @ w).sum()

        args = (
            jax.ShapeDtypeStruct((hidden, hidden), jnp.float32),
            jax.ShapeDtypeStruct((tokens, hidden), jnp.float32),
        )
        return step, args

    factory.tune_factory = True
    factory.__name__ = "token_workload"
    return factory


def test_tune_ranks_by_predicted_time(mesh8):
    from accelerate_tpu.analysis.tuner import tune

    space = SearchSpace(token_budgets=(256, 32, 128, 64))
    report = tune(_token_factory(), space, base_mesh=mesh8, generation="cpu")
    assert [c.point.token_budget for c in report.ranked] == [32, 64, 128, 256]
    assert report.winner.point.token_budget == 32
    assert report.ok
    # every scored candidate carries the full oracle output
    for c in report.ranked:
        assert c.predicted_step_us > 0 and c.peak_hbm_bytes > 0 and c.bound in (
            "compute", "memory", "comms"
        )


def test_tune_hbm_feasibility_prune(mesh8):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.analysis.tuner import tune

    def fat_step(w):
        return jnp.tanh(w @ w).sum()

    args = (jax.ShapeDtypeStruct((512, 512), jnp.float32),)
    space = SearchSpace(meshes=({"data": 1},))
    report = tune(fat_step, space, *args, generation="cpu", hbm_gb=0.0005)
    assert report.winner is None and report.infeasible_count == 1
    assert any(f.rule == "TPU701" for f in report.findings)
    assert not report.ok
    # the same candidate under a real budget is feasible and clean
    ok_report = tune(fat_step, space, *args, generation="cpu", hbm_gb=16.0)
    assert ok_report.ok and not ok_report.findings


def test_tune_search_run_keeps_tpu701_off_toplevel(mesh8):
    """In a multi-candidate search with a feasible winner, an infeasible
    candidate is a successful prune: status + per-candidate finding, but
    no top-level error gate."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.analysis.tuner import tune

    def factory(point):
        tokens = point.token_budget or 32

        def step(x):
            return jnp.tanh(x @ x.T).sum()

        return step, (jax.ShapeDtypeStruct((tokens, 64), jnp.float32),)

    factory.tune_factory = True
    space = SearchSpace(token_budgets=(16, 4096))
    report = tune(factory, space, generation="cpu", hbm_gb=0.001, base_mesh=mesh8)
    assert report.winner is not None and report.infeasible_count == 1
    assert not any(f.rule == "TPU701" for f in report.findings)
    infeasible = [c for c in report.candidates if c.status == "infeasible"]
    assert infeasible and any(f.rule == "TPU701" for f in infeasible[0].findings)
    assert report.ok


def test_tune_tpu702_dominated_in_real_search(mesh8):
    """A comms-bound candidate strictly dominated by a neighbor gets the
    TPU702 finding naming the winner."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.analysis.tuner import tune

    def psum_step(x):
        return jax.lax.psum(x, "data")

    args = (jax.ShapeDtypeStruct((1024, 1024), jnp.float32),)
    space = SearchSpace(meshes=("data=8", "data=2"), max_devices=8)
    report = tune(psum_step, space, *args, generation="cpu")
    assert report.winner.point.mesh_shape == {"data": 2}
    tpu702 = [f for f in report.findings if f.rule == "TPU702"]
    assert tpu702 and "data=2" in tpu702[0].message


def test_tune_plain_step_bucket_adapter(mesh8):
    """For a plain step fn, the buckets knob pads the leading batch dim
    to the covering bucket — bigger bucket, more predicted work."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.analysis.tuner import tune

    def step(x, w):
        return jnp.tanh(x @ w).sum()

    args = (
        jax.ShapeDtypeStruct((24, 64), jnp.float32),
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
    )
    space = SearchSpace(bucket_sets=("32", "256"))
    report = tune(step, space, *args, base_mesh=mesh8, generation="cpu")
    assert report.winner.point.buckets == (32,)
    times = {c.point.buckets: c.predicted_step_us for c in report.ranked}
    assert times[(256,)] > times[(32,)]


def test_tune_report_surfaces(mesh8):
    from accelerate_tpu.analysis.tuner import tune

    space = SearchSpace(token_budgets=(32, 64))
    report = tune(_token_factory(), space, base_mesh=mesh8, generation="cpu",
                  shape_histogram={24: 10})
    as_dict = report.as_dict()
    json.dumps(as_dict)  # fully serializable
    assert as_dict["winner"]["label"] == report.winner.label
    assert as_dict["chosen_toml"].startswith("[tune.chosen]")
    text = report.render_text()
    assert "winner:" in text and "[tune.chosen]" in text
    block = report.chosen_toml()
    assert f"token_budget = {report.winner.point.token_budget}" in block


def test_spearman_helper():
    from accelerate_tpu.analysis.tuner import spearman

    assert spearman([1, 2, 3], [10, 20, 30]) == pytest.approx(1.0)
    assert spearman([1, 2, 3], [30, 20, 10]) == pytest.approx(-1.0)
    assert spearman([1, 2], [5]) is None
    assert spearman([1, 1, 1], [1, 1, 1]) == pytest.approx(1.0)


def test_accelerator_tune(mesh8):
    from accelerate_tpu import Accelerator

    acc = Accelerator()
    report = acc.tune(_token_factory(), space=SearchSpace(token_budgets=(32, 64)),
                      generation="cpu")
    assert report.winner.point.token_budget == 32
    assert report.ok


# --------------------------------------------------------------------- #
# the ORACLE CONTRACT, pinned: predicted ordering == measured ordering
# on >=2 toy workloads with >=4 configs each (top-1 + Spearman >= 0.8)
# --------------------------------------------------------------------- #


def _bucket_factory(hidden=512, true_batch=96):
    """Trust workload 1 (train-shaped): the batch pads to the candidate
    bucket, so compute scales ~4x across the config set."""
    import jax
    import jax.numpy as jnp

    def factory(point):
        batch = point.buckets[0] if point.buckets else true_batch

        def step(w, x):
            return jnp.tanh(jnp.tanh(x @ w) @ w).sum()

        args = (
            jax.ShapeDtypeStruct((hidden, hidden), jnp.float32),
            jax.ShapeDtypeStruct((batch, hidden), jnp.float32),
        )
        return step, args

    factory.tune_factory = True
    factory.__name__ = "bucket_trust_workload"
    return factory


@pytest.mark.parametrize(
    "factory_builder,space_kwargs",
    [
        (_bucket_factory, dict(bucket_sets=("128", "256", "512", "1024"))),
        (lambda: _token_factory(hidden=512), dict(token_budgets=(128, 256, 512, 1024))),
    ],
    ids=["bucket-padding", "token-budget"],
)
def test_perfmodel_ranking_trust(mesh8, factory_builder, space_kwargs):
    """The tuner's oracle contract: static predicted-step-time ordering
    matches the StepTelemetry-measured ordering — top-1 agreement and
    Spearman >= 0.8 — on CPU, where the knobs change real compute."""
    from accelerate_tpu.analysis.tuner import tune

    report = tune(
        factory_builder(), SearchSpace(**space_kwargs),
        base_mesh=mesh8, generation="cpu",
        top_k=4, confirm=True, confirm_steps=6,
    )
    assert len(report.ranked) == 4
    ra = report.confirm["rank_agreement"]
    assert ra["n"] == 4, report.confirm
    assert ra["top1"] is True
    assert ra["spearman"] >= 0.8
    assert report.confirm["recompiles"] == 0


# --------------------------------------------------------------------- #
# CLI surface
# --------------------------------------------------------------------- #


def test_cli_tune_selfcheck():
    result = run_cli("tune", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 5
    assert result.stdout.count("zero findings") == 5


def test_cli_tune_json_and_emit(tmp_path):
    emit = tmp_path / "chosen.toml"
    result = run_cli(
        "tune", os.path.join(REPO, "examples", "by_feature", "tune.py") + "::serving_workload",
        "--mesh", "data=8", "--bucket-sets", "32,128;64,256", "--token-budgets", "32,64",
        "--generation", "cpu", "--format", "json", "--emit", str(emit),
        cwd=REPO,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    payload = result.stdout[: result.stdout.rindex("}") + 1]
    doc = json.loads(payload)
    assert doc["winner"] is not None
    assert len(doc["candidates"]) == 4
    assert emit.read_text().startswith("[tune.chosen]")


def test_cli_tune_reads_tune_section(tmp_path):
    """[tune] in .tpulint.toml specs the search space (typo'd sections
    would warn — the loader satellite)."""
    (tmp_path / ".tpulint.toml").write_text(
        '[tune]\ntoken_budgets = [32, 64]\ngeneration = "cpu"\n'
    )
    (tmp_path / "wl.py").write_text(textwrap.dedent('''
        """Tune workload fixture."""
        import jax
        import jax.numpy as jnp


        def wl(point):
            tokens = point.token_budget or 16

            def step(x):
                return jnp.tanh(x @ x.T).sum()

            return step, (jax.ShapeDtypeStruct((tokens, 32), jnp.float32),)


        wl.tune_factory = True
    '''))
    result = run_cli("tune", "wl.py::wl", "--mesh", "data=1", "--format", "json",
                     cwd=tmp_path)
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout[: result.stdout.rindex("}") + 1])
    budgets = {c["config"].get("token_budget") for c in doc["candidates"]}
    assert budgets == {32, 64}


def test_cli_tune_sarif_format():
    result = run_cli(
        "tune", os.path.join(REPO, "examples", "by_feature", "tune.py") + "::train_workload",
        "--mesh", "data=8", "--meshes", "data=8", "--compressions", "none",
        "--generation", "cpu", "--format", "sarif",
        cwd=REPO,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["tool"]["driver"]["name"] == "accelerate-tpu-lint"


def test_example_workloads_are_dogfood_clean():
    """The repo's own example workloads must tune without errors (the
    make tune-selfcheck gate)."""
    import importlib.util

    from accelerate_tpu.analysis.tuner import tune

    spec = importlib.util.spec_from_file_location(
        "tune_example", os.path.join(REPO, "examples", "by_feature", "tune.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = tune(
        mod.train_workload,
        SearchSpace(meshes=("data=8", "data=4,tensor=2"), compressions=("none", "int8"),
                    max_devices=8),
        generation="cpu",
    )
    assert report.ok, [f.as_dict() for f in report.findings]
    assert not any(f.is_error for f in report.findings)


# --------------------------------------------------------------------- #
# satellites: loader warnings, telemetry default path, shared SARIF
# --------------------------------------------------------------------- #


def test_project_config_warns_on_unknown_names(tmp_path):
    from accelerate_tpu.analysis.project_config import load_project_config

    (tmp_path / ".tpulint.toml").write_text(
        '[tunne]\nmeshes = ["data=8"]\n\n[lint]\nformt = "json"\n'
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        load_project_config(str(tmp_path))
    messages = [str(w.message) for w in caught]
    assert any("[tunne]" in m and "'tune'" in m for m in messages), messages
    assert any("'formt'" in m and "'format'" in m for m in messages), messages


def test_project_config_valid_schema_is_silent(tmp_path):
    from accelerate_tpu.analysis.project_config import load_project_config

    (tmp_path / ".tpulint.toml").write_text(
        '[lint]\nformat = "text"\ndisable = []\n\n[tune]\ntop_k = 3\n\n'
        '[tune.chosen]\nmesh = "data=8"\n\n[[suppress]]\npath = "examples/*"\n'
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        cfg = load_project_config(str(tmp_path))
    assert [str(w.message) for w in caught] == []
    assert cfg.format == "text"


def test_telemetry_default_path_under_runs():
    from accelerate_tpu.telemetry import default_path

    assert default_path(None) == os.path.join("runs", "telemetry.jsonl")
    assert default_path("proj/logs") == os.path.join("proj/logs", "telemetry.jsonl")


def test_checkpoints_describe_sarif(tmp_path):
    """describe --format sarif goes through the shared reporter: an
    uncommitted checkpoint is a CKPT001 error result."""
    ckpt = tmp_path / "checkpoint_0"
    (ckpt / "model").mkdir(parents=True)
    (ckpt / "model" / "data.bin").write_bytes(b"x" * 64)
    result = run_cli("checkpoints", "describe", str(ckpt), "--format", "sarif")
    assert result.returncode == 1
    doc = json.loads(result.stdout)
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "accelerate-tpu-checkpoints"
    assert run["results"][0]["ruleId"] == "CKPT001"
    assert run["results"][0]["level"] == "error"


def test_fleet_price_handoff_sarif():
    result = run_cli(
        "fleet", "price-handoff", "--layers", "4", "--kv-heads", "2", "--head-dim", "16",
        "--tokens", "128", "--params", "1e6", "--transport", "dcn", "--format", "sarif",
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "accelerate-tpu-fleet"
    assert run["results"][0]["ruleId"] == "FLEET001"


def test_merge_sarif_spans_all_surfaces(tmp_path):
    """Every CLI analysis surface merges into ONE artifact: a lint-tier
    run, a checkpoints run, and a fleet run."""
    from accelerate_tpu.analysis import Finding, render_sarif

    (tmp_path / "lint.sarif").write_text(render_sarif([Finding("TPU703", "waste")]))
    fleet = run_cli("fleet", "price-handoff", "--layers", "2", "--kv-heads", "2",
                    "--head-dim", "8", "--tokens", "16", "--format", "sarif")
    (tmp_path / "fleet.sarif").write_text(fleet.stdout)
    ckpt = tmp_path / "checkpoint_0"
    (ckpt / "model").mkdir(parents=True)
    desc = run_cli("checkpoints", "describe", str(ckpt), "--format", "sarif")
    (tmp_path / "ckpt.sarif").write_text(desc.stdout)
    merged_path = tmp_path / "merged.sarif"
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "merge_sarif.py"),
         str(tmp_path / "lint.sarif"), str(tmp_path / "fleet.sarif"),
         str(tmp_path / "ckpt.sarif"), "-o", str(merged_path)],
        capture_output=True, text=True, env=CPU_ENV,
    )
    assert result.returncode == 0, result.stderr
    merged = json.loads(merged_path.read_text())
    names = [r["tool"]["driver"]["name"] for r in merged["runs"]]
    assert names == ["accelerate-tpu-lint", "accelerate-tpu-fleet", "accelerate-tpu-checkpoints"]
