"""merge-weights CLI: sharded orbax checkpoint -> standalone safetensors.

Reference analogue: test_utils/scripts/test_merge_weights.py (FSDP DCP
shards merged offline via ``accelerate merge-weights``).
"""

from __future__ import annotations

import argparse

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.commands.merge import merge_command, merge_parser
from accelerate_tpu.test_utils import RegressionModel


def _flat_safetensors(directory):
    from pathlib import Path

    from safetensors.numpy import load_file

    out = {}
    for f in sorted(Path(directory).glob("*.safetensors")):
        out.update(load_file(str(f)))
    return out


def test_merge_weights_roundtrip(tmp_path):
    acc = Accelerator()
    model = acc.prepare_model(RegressionModel(a=1.5, b=-2.0))
    acc.prepare_optimizer(optax.sgd(0.1))
    ckpt = tmp_path / "ckpt"
    acc.save_state(str(ckpt))

    out = tmp_path / "merged"
    args = argparse.Namespace(checkpoint_dir=str(ckpt), output_dir=str(out), max_shard_size="10GB")
    assert merge_command(args) == 0

    tensors = _flat_safetensors(out)
    assert tensors, "merge produced no safetensors"
    by_suffix = {k.split("/")[-1]: v for k, v in tensors.items()}
    np.testing.assert_allclose(by_suffix["a"], 1.5)
    np.testing.assert_allclose(by_suffix["b"], -2.0)


def test_merge_weights_missing_checkpoint_raises(tmp_path):
    args = argparse.Namespace(checkpoint_dir=str(tmp_path), output_dir=str(tmp_path / "o"), max_shard_size="10GB")
    with pytest.raises(FileNotFoundError):
        merge_command(args)


def test_merge_parser_standalone_and_subcommand():
    p = merge_parser()
    ns = p.parse_args(["ckpt", "out"])
    assert ns.checkpoint_dir == "ckpt" and ns.output_dir == "out" and ns.max_shard_size == "10GB"

    root = argparse.ArgumentParser()
    sub = root.add_subparsers()
    merge_parser(sub)
    ns = root.parse_args(["merge-weights", "a", "b", "--max_shard_size", "1GB"])
    assert ns.func is merge_command and ns.max_shard_size == "1GB"
