"""Mesh-sharded (multi-device) decode.

The reference's headline big-model story is inference across devices
(reference: src/accelerate/inference.py:124-184 prepare_pippy,
big_modeling.py:309 dispatch_model, benchmarks/big_model_inference). The
TPU-native equivalent under test: params TP-sharded by the zoo's Megatron
rules, the KV cache sharded over ``tensor`` (heads) and ``data`` (batch)
inside the decode scan, and ``generate`` decoding in place with tokens
identical to single-device decode.
"""

import re

import jax
import numpy as np
import pytest

from accelerate_tpu.big_modeling import shard_model
from accelerate_tpu.generation import generate, generate_seq2seq
from accelerate_tpu.models import LlamaConfig, create_llama_model
from accelerate_tpu.parallel.mesh import MeshConfig


def _tp_mesh(data=2, tensor=2):
    return MeshConfig(data=data, tensor=tensor).build(jax.devices()[: data * tensor])


def test_tp_sharded_greedy_matches_single_device():
    """tensor2 x data2 greedy tokens == single-device greedy tokens."""
    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    ids = (np.arange(2 * 8).reshape(2, 8) % 256).astype(np.int32)
    want = np.asarray(generate(model, ids, max_new_tokens=6))

    shard_model(model, _tp_mesh())
    # params actually live sharded: the tensor axis splits at least one kernel
    specs = {
        s.spec for s in jax.tree_util.tree_leaves(model.param_shardings)
    }
    assert any("tensor" in str(sp) for sp in specs), specs
    got = np.asarray(generate(model, ids, max_new_tokens=6))
    np.testing.assert_array_equal(got, want)


def test_tp_sharded_sampling_matches_single_device():
    """Same seed -> same samples regardless of layout (the key chain is
    replicated; only the math is sharded)."""
    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    ids = np.ones((2, 4), np.int32)
    want = np.asarray(generate(model, ids, max_new_tokens=5, temperature=1.0, top_k=8, seed=7))
    shard_model(model, _tp_mesh())
    got = np.asarray(generate(model, ids, max_new_tokens=5, temperature=1.0, top_k=8, seed=7))
    np.testing.assert_array_equal(got, want)


def test_no_full_param_allgather_in_decode_hlo():
    """The decode program must not all-gather parameters (or the KV cache):
    every all-gather in the compiled HLO stays below the smallest full
    kernel/cache buffer (8192 elements for the tiny config) — gathering
    logits/tokens is fine, re-materialising weights per step is not."""
    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model, _tp_mesh())
    ids = np.ones((2, 8), np.int32)
    generate(model, ids, max_new_tokens=4)  # builds + caches the jitted runner
    (runner,) = model._generate_runners.values()
    from accelerate_tpu.generation import _shard_batch

    lowered = runner.lower(
        model.params, _shard_batch(np.asarray(ids), model.mesh), jax.random.key(0)
    )
    txt = lowered.compile().as_text()
    sizes = [
        int(np.prod([int(d) for d in m.group(1).split(",")]))
        for m in re.finditer(r"\[([\d,]+)\][^=\n]* all-gather", txt)
    ]
    assert all(s < 8192 for s in sizes), f"param/cache-sized all-gather in decode HLO: {sizes}"


def test_fsdp_sharded_decode_matches_single_device():
    """ZeRO-3-style layouts decode too: params sharded over ``fsdp`` via the
    auto-rules still produce identical tokens (XLA gathers per layer)."""
    from accelerate_tpu.parallel.sharding import fsdp_rules_for

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    ids = np.ones((2, 4), np.int32)
    want = np.asarray(generate(model, ids, max_new_tokens=4))
    mesh = MeshConfig(data=1, fsdp=4).build(jax.devices()[:4])
    rules = fsdp_rules_for(model.params, mesh) + list(model.sharding_rules)
    shard_model(model, mesh, rules=rules)
    got = np.asarray(generate(model, ids, max_new_tokens=4))
    np.testing.assert_array_equal(got, want)


def test_seq2seq_sharded_matches_single_device():
    """Encoder-decoder generation under TP: T5 cached decode on a
    tensor2 x data2 mesh equals the single-device tokens."""
    from accelerate_tpu.models.t5 import T5Config, create_t5_model

    m = create_t5_model(T5Config.tiny(max_decode_len=16), seed=0, seq_len=8)
    src = (np.arange(2 * 8).reshape(2, 8) % 250).astype(np.int32)
    want = np.asarray(generate_seq2seq(m, src, max_new_tokens=5))
    shard_model(m, _tp_mesh())
    got = np.asarray(generate_seq2seq(m, src, max_new_tokens=5))
    np.testing.assert_array_equal(got, want)


def test_accelerator_prepared_model_decodes_sharded():
    """The training-framework path: a model prepared by the Accelerator on
    a hybrid mesh generates directly — decode rides the prepared shardings
    (no re-dispatch step, unlike the reference where training and
    big-model-inference are separate stacks)."""
    from accelerate_tpu import Accelerator, ParallelismPlugin

    plugin = ParallelismPlugin(mesh_config=MeshConfig(data=2, fsdp=2, tensor=2))
    acc = Accelerator(parallelism_plugin=plugin)
    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    ids = np.ones((4, 4), np.int32)
    want = np.asarray(generate(model, ids, max_new_tokens=4))

    fresh = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    prepared = acc.prepare_model(fresh)
    got = np.asarray(generate(prepared, ids, max_new_tokens=4))
    np.testing.assert_array_equal(got, want)


def test_shard_model_defaults_to_all_devices_tensor():
    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model)
    assert model.mesh.shape["tensor"] == len(jax.devices())
    out = generate(model, np.ones((1, 4), np.int32), max_new_tokens=2)
    assert out.shape == (1, 6)


def test_hand_sharded_custom_axis_mesh_decodes():
    """A model sharded BY HAND on a mesh whose axes aren't the framework's
    names must still decode (framework batch/cache specs reference
    data/fsdp/tensor; absent axes are dropped, not a KeyError)."""
    from jax.sharding import NamedSharding, PartitionSpec

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    ids = np.ones((2, 4), np.int32)
    want = np.asarray(generate(model, ids, max_new_tokens=3))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    model.params = jax.device_put(model.params, NamedSharding(mesh, PartitionSpec()))
    got = np.asarray(generate(model, ids, max_new_tokens=3))
    np.testing.assert_array_equal(got, want)


def test_quantized_tp_sharded_decode_matches_single_device():
    """Quantized (int8 in-scan QuantDense) + TP-sharded decode: the llama
    rules carry qdata/qscale layouts, so a quantized model shards and
    generates the same tokens as its single-device quantized twin (the
    quantization guide's 'Quantized + sharded' claim, tested)."""
    from accelerate_tpu.utils.quantization import QuantizationConfig, load_and_quantize_model

    base = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    q = load_and_quantize_model(base, QuantizationConfig(bits=8))
    ids = (np.arange(2 * 6).reshape(2, 6) % 256).astype(np.int32)
    want = np.asarray(generate(q, ids, max_new_tokens=4))

    base2 = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    q2 = load_and_quantize_model(base2, QuantizationConfig(bits=8))
    shard_model(q2, _tp_mesh())
    specs = {str(s.spec) for s in jax.tree_util.tree_leaves(q2.param_shardings)}
    assert any("tensor" in sp for sp in specs), specs
    got = np.asarray(generate(q2, ids, max_new_tokens=4))
    np.testing.assert_array_equal(got, want)


def test_shard_model_dtype_cast():
    import jax.numpy as jnp

    model = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    shard_model(model, _tp_mesh(), dtype=jnp.bfloat16)
    leaf = jax.tree_util.tree_leaves(model.params)[0]
    assert leaf.dtype == jnp.bfloat16
    out = generate(model, np.ones((1, 4), np.int32), max_new_tokens=2)
    assert out.shape == (1, 6)
