"""MoE routing + expert-parallel tests (parity-plus: the reference has no
expert-parallel strategy, SURVEY §2.2 EP row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import MeshConfig
from accelerate_tpu.ops.moe import MoEBlock, moe_ffn, top_k_routing


def test_routing_invariants():
    t, e, cap, k = 64, 4, 24, 2
    logits = jax.random.normal(jax.random.PRNGKey(0), (t, e))
    dispatch, combine, aux = top_k_routing(logits, k, cap)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # each token occupies at most k slots, each slot at most once
    assert d.sum(axis=(1, 2)).max() <= k
    assert d.reshape(t, -1).sum(0).max() <= 1 + 0  # a slot holds one token
    # per-expert load never exceeds capacity
    assert d.sum(axis=(0, 2)).max() <= cap
    # combine weights live only on dispatched slots and sum to ~1 per kept token
    assert (c[~d] == 0).all()
    kept = d.sum(axis=(1, 2)) > 0
    np.testing.assert_allclose(c.sum(axis=(1, 2))[kept], 1.0, atol=1e-5)
    assert float(aux) > 0


def test_moe_ffn_matches_per_token_reference():
    """With capacity large enough that nothing drops, dense-dispatch MoE must
    equal the per-token top-k mixture computed naively."""
    t, d, ff, e, k = 32, 16, 24, 4, 2
    ks = jax.random.split(jax.random.PRNGKey(1), 4)
    x = jax.random.normal(ks[0], (t, d))
    router = jax.random.normal(ks[1], (d, e)) * 0.5
    wi = jax.random.normal(ks[2], (e, d, ff)) * 0.1
    wo = jax.random.normal(ks[3], (e, ff, d)) * 0.1

    out, _ = moe_ffn(x, router, wi, wo, num_selected=k, capacity_factor=float(e))

    probs = jax.nn.softmax(x @ router, axis=-1)
    ref = np.zeros((t, d), np.float32)
    for ti in range(t):
        p = np.asarray(probs[ti])
        top = np.argsort(-p)[:k]
        w = p[top] / p[top].sum()
        for wi_e, ei in zip(w, top):
            h = np.asarray(jax.nn.gelu(x[ti] @ wi[ei]))
            ref[ti] += wi_e * np.asarray(h @ wo[ei])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)


def test_expert_parallel_matches_single_device():
    """Same MoE computation under an expert=4 x data=2 mesh must match the
    unsharded result — GSPMD inserts the dispatch all-to-alls."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    t, d, ff, e = 64, 16, 24, 4
    ks = jax.random.split(jax.random.PRNGKey(2), 4)
    x = jax.random.normal(ks[0], (t, d))
    router = jax.random.normal(ks[1], (d, e)) * 0.5
    wi = jax.random.normal(ks[2], (e, d, ff)) * 0.1
    wo = jax.random.normal(ks[3], (e, ff, d)) * 0.1

    ref, aux_ref = moe_ffn(x, router, wi, wo)

    mesh = MeshConfig(data=2, expert=4).build()
    xs = jax.device_put(x, NamedSharding(mesh, P(("data", "fsdp"))))
    wis = jax.device_put(wi, NamedSharding(mesh, P("expert")))
    wos = jax.device_put(wo, NamedSharding(mesh, P("expert")))
    out, aux = jax.jit(moe_ffn)(xs, router, wis, wos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), atol=1e-6)


def test_moe_block_and_gradients():
    block = MoEBlock(num_experts=4, intermediate_size=32, num_selected=2)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 16))
    params = block.init(jax.random.PRNGKey(4), x)["params"]

    def loss(p, x):
        return jnp.sum(block.apply({"params": p}, x) ** 2)

    g = jax.jit(jax.grad(loss))(params, x)
    # router receives gradient through the combine weights
    assert float(jnp.abs(g["router/kernel"]).max()) > 0
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(g))


def test_mixtral_forward_and_train_step():
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.mixtral import MixtralConfig, create_mixtral_model, mixtral_lm_loss
    from accelerate_tpu.utils import ParallelismPlugin

    cfg = MixtralConfig.tiny()
    model = create_mixtral_model(cfg, seq_len=16)
    ids = (np.arange(2 * 16).reshape(2, 16) % cfg.vocab_size).astype(np.int32)
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, expert=4))
    )
    model = acc.prepare_model(model)
    opt = acc.prepare_optimizer(optax.adam(1e-3))
    step = acc.build_train_step(
        lambda p, b: mixtral_lm_loss(p, b, module=model.module, aux_coef=cfg.router_aux_loss_coef)
    )
    batch = {"input_ids": ids}
    l0 = float(step(batch))
    l5 = l0
    for _ in range(5):
        l5 = float(step(batch))
    assert np.isfinite(l0) and l5 < l0
    # expert weights really are sharded over the expert axis
    spec = model.params["layer_0"]["moe"]["experts/gate_proj"].sharding.spec
    assert spec[0] == "expert"


def test_default_capacity_fits_balanced_topk():
    """With the GShard capacity convention (factor * k * T / E), perfectly
    balanced top-2 routing must not drop any token at the default factor."""
    t, d, e, k = 32, 8, 4, 2
    # token i strongly prefers experts i%e and (i+1)%e -> exactly 2T/E
    # assignments per expert
    logits = np.full((t, e), -10.0, np.float32)
    for i in range(t):
        logits[i, i % e] = 10.0
        logits[i, (i + 1) % e] = 9.0
    capacity = max(1, int(1.25 * k * t / e))
    dispatch, combine, _ = top_k_routing(jnp.asarray(logits), k, capacity)
    kept = np.asarray(dispatch).sum(axis=(1, 2))
    assert (kept == k).all(), "balanced top-2 routing dropped tokens at default capacity"
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)), 1.0, atol=1e-5)
