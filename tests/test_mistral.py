"""Mistral family (models/mistral.py): sliding-window attention
semantics across every decode path — non-decode forward, KV-cache
greedy decode, and the paged serving engine. HF importer parity lives
in test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import LlamaConfig, MistralConfig, create_llama_model, create_mistral_model


@pytest.fixture(scope="module")
def tiny_mistral():
    # window 4 < seq lengths used below, so the band always bites
    return create_mistral_model(MistralConfig.tiny(sliding_window=4), seq_len=16)


def test_window_excludes_distant_context(tiny_mistral):
    """Two prompts differing ONLY at position 0: with 2 layers x window 4
    the last position's receptive field stops at position 9, so its
    logits must be identical — while a full-attention llama of the same
    shape must see the difference."""
    ids_a = (np.arange(16)[None] % 250 + 1).astype(np.int32)
    ids_b = ids_a.copy()
    ids_b[0, 0] = 123
    la, lb = np.asarray(tiny_mistral(ids_a)), np.asarray(tiny_mistral(ids_b))
    np.testing.assert_allclose(la[0, -1], lb[0, -1], atol=1e-6)
    assert not np.allclose(la[0, 1], lb[0, 1], atol=1e-6)  # inside the window it DOES see it

    full = create_llama_model(LlamaConfig.tiny(), seq_len=16)
    fa, fb = np.asarray(full(ids_a)), np.asarray(full(ids_b))
    assert not np.allclose(fa[0, -1], fb[0, -1], atol=1e-6)


def test_greedy_decode_matches_full_prefix(tiny_mistral):
    """Cached incremental decode applies the same band as the non-decode
    forward: tokens equal the O(S^2) full-prefix argmax loop."""
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_mistral, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_mistral(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_paged_serving_with_window(tiny_mistral):
    """The paged cache's band mask (ops/paged_kv.py) matches generate()."""
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9, 6, 12)]
    eng = ServingEngine(tiny_mistral, num_slots=2, prompt_buckets=(4, 8, 16), paged_block_size=4)
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_mistral, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)


def test_window_on_seq_mesh_matches_unsharded(tiny_mistral):
    """Windowed attention on a seq-sharded mesh runs the banded RING
    schedule (absolute positions make the band rotation-invariant):
    logits equal the unsharded forward."""
    import jax

    from accelerate_tpu.parallel.mesh import MeshConfig
    from accelerate_tpu.parallel.sharding import mesh_context

    ids = (np.arange(2 * 16).reshape(2, 16) % 250 + 1).astype(np.int32)
    want = np.asarray(tiny_mistral(ids))

    mesh = MeshConfig(seq=4, data=2).build()
    with mesh_context(mesh):
        got = np.asarray(jax.jit(tiny_mistral.apply_fn)(tiny_mistral.params, ids))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)
