"""Gradient-compression tests (reference parity: DDP comm hooks —
fp16/bf16 compress + register_comm_hook, utils/dataclasses.py:130-226)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.parallel.compression import compressed_psum_mean, wire_bytes
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn


def test_compressed_psum_mean_matches_plain(mesh8):
    from jax.sharding import PartitionSpec as P

    g = jax.random.normal(jax.random.key(0), (8, 16), jnp.float32)

    def reduce(method):
        def body(x):
            local = jax.tree.map(lambda l: l, {"g": x})
            if method is None:
                return jax.tree.map(lambda l: jax.lax.pmean(l, "data"), local)
            return compressed_psum_mean(local, "data", method)

        fn = jax.shard_map(body, mesh=mesh8, in_specs=P("data"), out_specs=P(), check_vma=False)
        return np.asarray(fn(g)["g"])

    exact = reduce(None)
    np.testing.assert_allclose(reduce("bf16"), exact, atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(reduce("int8"), exact, atol=2e-2, rtol=5e-2)


def test_wire_bytes_accounting():
    tree = {"a": jnp.zeros((100, 10)), "b": jnp.zeros((50,))}
    assert wire_bytes(tree, None) == 1050 * 8  # reduce-scatter + all-gather, f32
    assert wire_bytes(tree, "bf16") == 1050 * 4
    assert wire_bytes(tree, "int8") == 1050 * 2 + 2 * 8  # + per-leaf amax pair
    assert wire_bytes(tree, "int8") < wire_bytes(tree, None) // 3


def test_int8_keeps_int8_on_the_wire(mesh8):
    """The compiled HLO must not contain an int32/f32 allreduce of the
    gradient payload — the compression claim is about wire bytes."""
    from jax.sharding import PartitionSpec as P

    g = jax.random.normal(jax.random.key(0), (8, 64), jnp.float32)
    fn = jax.jit(
        jax.shard_map(
            lambda x: compressed_psum_mean({"g": x}, "data", "int8")["g"],
            mesh=mesh8, in_specs=P("data"), out_specs=P(), check_vma=False,
        )
    )
    hlo = fn.lower(g).compile().as_text()
    import re

    for op in ("all-to-all", "all-gather"):
        for m in re.finditer(rf"{op}[^=]*= \(?([a-z0-9]+)\[", hlo):
            assert m.group(1) in ("s8", "u8"), f"{op} moves {m.group(1)}, not int8:\n{m.group(0)}"


@pytest.mark.parametrize("method", ["bf16", "int8"])
def test_compressed_training_converges_like_plain(method):
    """Same model/data trained with and without compression: both converge,
    trajectories stay within compression tolerance (reference done-bar:
    identical convergence within tolerance)."""

    def train(compression):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(
            parallelism_plugin=ParallelismPlugin(
                mesh_config=MeshConfig(data=8), grad_compression=compression
            )
        )
        model = acc.prepare_model(RegressionModel())
        acc.prepare_optimizer(optax.sgd(0.1))
        step = acc.build_train_step(linear_loss_fn)
        ds = RegressionDataset(length=64)
        losses = []
        for s in range(48):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            batch = {"x": ds.x[idx], "y": ds.y[idx]}
            losses.append(float(step(batch)))
        return losses, jax.tree.map(np.asarray, model.params)

    plain_losses, plain_params = train(None)
    comp_losses, comp_params = train(method)
    assert comp_losses[-1] < 0.05, comp_losses[-5:]
    # per-step trajectory stays inside compression rounding of the exact run
    np.testing.assert_allclose(comp_losses, plain_losses, atol=0.02, rtol=0.1)
    for k in plain_params:
        np.testing.assert_allclose(comp_params[k], plain_params[k], atol=0.1, rtol=0.1)


def test_compression_rejects_sharded_axes():
    with pytest.raises(ValueError):
        ParallelismPlugin(grad_compression="fp4")
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=4, tensor=2), grad_compression="bf16"
        )
    )
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    with pytest.raises(ValueError, match="data"):
        acc.build_train_step(linear_loss_fn)
