"""Gradient-compression tests (reference parity: DDP comm hooks —
fp16/bf16 compress + register_comm_hook, utils/dataclasses.py:130-226)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin
from accelerate_tpu.utils.compat import shard_map
from accelerate_tpu.parallel.compression import compressed_psum_mean, wire_bytes
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn


def test_compressed_psum_mean_matches_plain(mesh8):
    from jax.sharding import PartitionSpec as P

    g = jax.random.normal(jax.random.key(0), (8, 16), jnp.float32)

    def reduce(method):
        def body(x):
            local = jax.tree.map(lambda l: l, {"g": x})
            if method is None:
                return jax.tree.map(lambda l: jax.lax.pmean(l, "data"), local)
            return compressed_psum_mean(local, "data", method)

        fn = shard_map(body, mesh=mesh8, in_specs=P("data"), out_specs=P(), check_vma=False)
        return np.asarray(fn(g)["g"])

    exact = reduce(None)
    np.testing.assert_allclose(reduce("bf16"), exact, atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(reduce("int8"), exact, atol=2e-2, rtol=5e-2)


def test_compressed_psum_mean_within_tpu606_bound(mesh8):
    """The parity pin behind numerics rule TPU606: the compressed mean
    must match the exact f32 mean within the per-leaf error bound the
    rule prices (``analysis.numerics_rules.COMPRESSION_NUMERICS``) —
    across five decades of gradient magnitude. If a compression change
    ever violates its published bound, this is the test that catches it."""
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.analysis.numerics_rules import COMPRESSION_NUMERICS

    n = 8
    for seed in (0, 2, 4):  # gradient scales 1e-2, 1, 1e2
        g = jax.random.normal(jax.random.key(seed), (8, 64), jnp.float32) * (10.0 ** (seed - 2))

        def reduce(method):
            def body(x):
                local = {"g": x}
                if method is None:
                    return jax.tree.map(lambda l: jax.lax.pmean(l, "data"), local)
                return compressed_psum_mean(local, "data", method)

            fn = shard_map(body, mesh=mesh8, in_specs=P("data"), out_specs=P(), check_vma=False)
            return np.asarray(fn(g)["g"])

        exact = reduce(None)
        amax = float(np.abs(np.asarray(g)).max())
        for method in ("bf16", "int8"):
            err = float(np.abs(reduce(method) - exact).max())
            bound = COMPRESSION_NUMERICS[method].bound(amax, n)
            assert err <= bound, (
                f"{method} @ seed {seed}: |error| {err:.3e} exceeds the "
                f"TPU606 bound {bound:.3e} ({COMPRESSION_NUMERICS[method].describe})"
            )


def test_wire_bytes_accounting():
    tree = {"a": jnp.zeros((100, 10)), "b": jnp.zeros((50,))}
    assert wire_bytes(tree, None) == 1050 * 8  # reduce-scatter + all-gather, f32
    assert wire_bytes(tree, "bf16") == 1050 * 4
    # int8: 1 B/elem per leg + two ring-priced pmax'd f32 amax scalars per
    # leaf (2 transfers x 4 B each in the limit)
    assert wire_bytes(tree, "int8") == 1050 * 2 + 2 * 2 * 2 * 4
    assert wire_bytes(tree, "fp8") == wire_bytes(tree, "int8")
    assert wire_bytes(tree, "int8") < wire_bytes(tree, None) // 3
    # exact ring terms with an explicit group size
    n = 8
    assert wire_bytes(tree, None, n=n) == round(1050 * 4 * 2 * (n - 1) / n)
    # zero_stage=1: reduce-scatter + all-gather legs over padded flats
    # (100*10 pads to 1000, 50 pads to 56 at n=8)
    assert wire_bytes(tree, None, n=n, zero_stage=1) == 2 * round(4 * 1000 * (n - 1) / n) + 2 * round(4 * 56 * (n - 1) / n)
    assert wire_bytes(tree, "int8", n=n, zero_stage=1) < wire_bytes(tree, None, n=n, zero_stage=1) // 3
    # quantized zero1 vs replicated f32 baseline: the headline claim
    assert wire_bytes(tree, "int8", n=n, zero_stage=1) <= 0.27 * wire_bytes(tree, None, n=n)


def test_wire_bytes_pins_costmodel_ring_formulas(mesh8):
    """Satellite pin: ``wire_bytes`` must agree with the cost model's ring
    formulas (``price_collective``) for every collective its plan fires —
    psum / reduce-scatter / all-gather / all-to-all, across methods and
    both zero stages. One set of formulas; units of truth cannot drift."""
    from accelerate_tpu.analysis.costmodel import price_collective, ring_wire_bytes
    from accelerate_tpu.parallel.compression import wire_plan

    tree = {"k": jnp.zeros((96, 16)), "b": jnp.zeros((50,))}
    n = 8
    for zero_stage in (0, 1):
        for method in (None, "bf16", "int8", "fp8"):
            total = 0
            for prim, payload in wire_plan(tree, method, zero_stage=zero_stage, n=n):
                # price_collective takes the jaxpr operand: the all_gather
                # operand is the per-shard input, everything else the full
                # payload
                operand = payload // n if prim == "all_gather" else payload
                rec = price_collective(prim, ("data",), operand, mesh8)
                assert rec is not None, prim
                assert rec.wire_bytes == ring_wire_bytes(prim, payload, n), (prim, payload)
                total += rec.wire_bytes
            assert total == wire_bytes(tree, method, n=n, zero_stage=zero_stage), (
                zero_stage, method,
            )


def test_int8_keeps_int8_on_the_wire(mesh8):
    """The compiled HLO must not contain an int32/f32 allreduce of the
    gradient payload — the compression claim is about wire bytes."""
    from jax.sharding import PartitionSpec as P

    g = jax.random.normal(jax.random.key(0), (8, 64), jnp.float32)
    fn = jax.jit(
        shard_map(
            lambda x: compressed_psum_mean({"g": x}, "data", "int8")["g"],
            mesh=mesh8, in_specs=P("data"), out_specs=P(), check_vma=False,
        )
    )
    hlo = fn.lower(g).compile().as_text()
    import re

    for op in ("all-to-all", "all-gather"):
        for m in re.finditer(rf"{op}[^=]*= \(?([a-z0-9]+)\[", hlo):
            assert m.group(1) in ("s8", "u8"), f"{op} moves {m.group(1)}, not int8:\n{m.group(0)}"


@pytest.mark.parametrize("method", ["bf16", "int8"])
def test_compressed_training_converges_like_plain(method):
    """Same model/data trained with and without compression: both converge,
    trajectories stay within compression tolerance (reference done-bar:
    identical convergence within tolerance)."""

    def train(compression):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(
            parallelism_plugin=ParallelismPlugin(
                mesh_config=MeshConfig(data=8), grad_compression=compression
            )
        )
        model = acc.prepare_model(RegressionModel())
        acc.prepare_optimizer(optax.sgd(0.1))
        step = acc.build_train_step(linear_loss_fn)
        ds = RegressionDataset(length=64)
        losses = []
        for s in range(48):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            batch = {"x": ds.x[idx], "y": ds.y[idx]}
            losses.append(float(step(batch)))
        return losses, jax.tree.map(np.asarray, model.params)

    plain_losses, plain_params = train(None)
    comp_losses, comp_params = train(method)
    assert comp_losses[-1] < 0.05, comp_losses[-5:]
    # per-step trajectory stays inside compression rounding of the exact run
    np.testing.assert_allclose(comp_losses, plain_losses, atol=0.02, rtol=0.1)
    for k in plain_params:
        np.testing.assert_allclose(comp_params[k], plain_params[k], atol=0.1, rtol=0.1)


def test_powersgd_rank_parsing():
    from accelerate_tpu.parallel.compression import powersgd_rank

    assert powersgd_rank("powersgd") == 1
    assert powersgd_rank("powersgd:4") == 4
    assert powersgd_rank("bf16") is None and powersgd_rank(None) is None
    with pytest.raises(ValueError):
        powersgd_rank("powersgd:0")
    with pytest.raises(ValueError):
        ParallelismPlugin(grad_compression="powersgd:x")
    # the plugin accepts the method strings
    ParallelismPlugin(grad_compression="powersgd:2")


def _psgd_reduce(mesh8, grads, state, rank):
    """Run one powersgd_psum_mean over the 8-way data axis; grads [8, n, m]
    (one matrix per shard), state error [8, n, m]."""
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.parallel.compression import powersgd_psum_mean

    def body(g, e, q):
        out, new = powersgd_psum_mean(
            {"w": g[0]}, "data", {"error": {"w": e[0]}, "q": {"w": q}}, rank
        )
        return out["w"], new["error"]["w"][None], new["q"]["w"]

    fn = shard_map(
        body, mesh=mesh8,
        in_specs=(P("data"), P("data"), P()),
        out_specs=(P(), P("data"), P()),
        check_vma=False,
    )
    return fn(grads, state["error"], state["q"])


def test_powersgd_exact_on_lowrank_and_feedback_identity(mesh8):
    """A gradient whose mean is rank-1 is reproduced exactly at r>=1, and
    the algebraic error-feedback identity g + e_prev == approx + e_new
    holds per shard (that identity is WHY the biased compressor converges:
    nothing is ever dropped, only delayed)."""
    from accelerate_tpu.parallel.compression import powersgd_init_state

    rng = np.random.default_rng(0)
    u = rng.normal(size=(24, 1)).astype(np.float32)
    v = rng.normal(size=(1, 16)).astype(np.float32)
    # identical rank-1 matrix on every shard -> mean is rank-1
    grads = jnp.broadcast_to(jnp.asarray(u @ v), (8, 24, 16))
    state = powersgd_init_state({"w": grads[0]}, 2, 8)
    state = {"error": state["error"]["w"], "q": state["q"]["w"]}
    approx, new_err, _ = _psgd_reduce(mesh8, grads, state, rank=2)
    np.testing.assert_allclose(np.asarray(approx), u @ v, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(new_err), 0.0, atol=1e-4)

    # feedback identity on a full-rank gradient with nonzero carried error
    grads2 = jnp.asarray(rng.normal(size=(8, 24, 16)).astype(np.float32))
    err0 = jnp.asarray(rng.normal(size=(8, 24, 16)).astype(np.float32))
    approx2, err2, _ = _psgd_reduce(mesh8, grads2, {"error": err0, "q": state["q"]}, rank=2)
    np.testing.assert_allclose(
        np.asarray(grads2 + err0),
        np.asarray(jnp.broadcast_to(approx2, (8, 24, 16)) + err2),
        atol=1e-4, rtol=1e-4,
    )


def test_powersgd_wire_bytes_and_hlo(mesh8):
    """Wire accounting: only the rank-r factors cross the wire; the HLO must
    not all-reduce anything gradient-sized."""
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.parallel.compression import (
        powersgd_init_state, powersgd_psum_mean, wire_bytes,
    )

    tree = {"k": jnp.zeros((256, 128)), "b": jnp.zeros((128,))}
    r = 2
    # k: P[256,2]+Q[128,2] f32 allreduced (2 transfers each); b: exact f32
    assert wire_bytes(tree, "powersgd:2") == 2 * 4 * r * (256 + 128) + 2 * 4 * 128
    assert wire_bytes(tree, "powersgd:2") < wire_bytes(tree, None) // 20

    g = jax.random.normal(jax.random.key(0), (256, 128), jnp.float32)
    state = powersgd_init_state({"w": g}, r, 8)

    def body(x, e, q):
        out, _ = powersgd_psum_mean({"w": x}, "data", {"error": {"w": e[0]}, "q": {"w": q}}, r)
        return out["w"]

    fn = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(P(), P("data"), P()), out_specs=P(), check_vma=False,
    ))
    hlo = fn.lower(g, state["error"]["w"], state["q"]["w"]).compile().as_text()
    import re as _re

    for m in _re.finditer(r"all-reduce[^=]*= \(?[a-z0-9]+\[([0-9,]*)\]", hlo):
        dims = [int(d) for d in m.group(1).split(",") if d]
        size = int(np.prod(dims)) if dims else 1
        assert size <= 256 * r, f"gradient-sized allreduce: {m.group(0)}"


def test_powersgd_training_converges():
    """End-to-end through the Accelerator: an eligible [32,16] kernel trains
    under powersgd:2 (error feedback carried in the step state) and reaches
    the same loss floor as the exact run."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    rng = np.random.default_rng(1)
    w_true = rng.normal(size=(32, 16)).astype(np.float32)
    x_all = rng.normal(size=(64, 32)).astype(np.float32)
    y_all = x_all @ w_true

    def mat_loss(params, batch):
        pred = batch["x"] @ params["w"]
        return ((pred - batch["y"]) ** 2).mean()

    def train(compression):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(
            parallelism_plugin=ParallelismPlugin(
                mesh_config=MeshConfig(data=8), grad_compression=compression
            )
        )
        from accelerate_tpu.modeling import Model

        model = acc.prepare_model(Model(lambda p, x: x @ p["w"],
                                        {"w": np.zeros((32, 16), np.float32)}))
        acc.prepare_optimizer(optax.adam(0.1))
        step = acc.build_train_step(mat_loss)
        losses = []
        for s in range(150):
            idx = np.arange(s * 16, (s + 1) * 16) % 64
            losses.append(float(step({"x": x_all[idx], "y": y_all[idx]})))
        return losses

    plain = train(None)
    psgd = train("powersgd:2")
    assert plain[-1] < 1e-3
    # lossy start, but error feedback catches the trajectory up
    assert psgd[-1] < 5e-2, psgd[-5:]
    assert psgd[-1] < psgd[0] / 100


@pytest.fixture
def no_persistent_compile_cache():
    """Disable jax's persistent compilation cache for one test.

    The fp16+powersgd train step is numerically reliable when freshly
    compiled (0 failures in 20+ runs) but NONDETERMINISTICALLY poisons
    its carried state to NaN in ~25% of runs when XLA:CPU restores the
    executable from the persistent disk cache — the same class of
    non-self-contained deserialized-executable bug PR 7 documented for
    `serialize_executable` (aot/ routes around it by compiling fresh
    once). Until the XLA:CPU cache restore is trustworthy for this
    program, the overflow-recovery semantics are tested against the
    freshly-compiled executable."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def test_powersgd_fp16_overflow_does_not_poison_state(no_persistent_compile_cache):
    """A loss-scale overflow step must leave the carried residual/Q finite
    (the step's finite gate already holds params): training recovers on the
    next good batches instead of dead-looping on a NaN carry. Also checks
    the residual is carried in UNSCALED units — after the backoff halves
    the scale, feedback still converges."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        mixed_precision="fp16",
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=8), grad_compression="powersgd:2"
        ),
    )
    from accelerate_tpu.modeling import Model

    rng = np.random.default_rng(3)
    w_true = rng.normal(size=(32, 16)).astype(np.float32)
    x_all = rng.normal(size=(64, 32)).astype(np.float32)
    y_all = x_all @ w_true

    def mat_loss(params, batch):
        return ((batch["x"] @ params["w"] - batch["y"]) ** 2).mean()

    model = acc.prepare_model(Model(lambda p, x: x @ p["w"],
                                    {"w": np.zeros((32, 16), np.float32)}))
    acc.prepare_optimizer(optax.adam(0.1))
    step = acc.build_train_step(mat_loss)
    good = {"x": x_all[:16], "y": y_all[:16]}
    for _ in range(5):
        step(good)
    # overflow batch: fp16 forward saturates -> non-finite grads
    bad = {"x": np.full((16, 32), 1e4, np.float32), "y": np.zeros((16, 16), np.float32)}
    step(bad)
    losses = [float(step({"x": x_all[s * 16:(s + 1) * 16], "y": y_all[s * 16:(s + 1) * 16]}))
              for s in [0, 1, 2, 3] * 20]
    assert np.isfinite(losses).all(), losses[:8]
    # recovery = still making progress after the overflow, not dead-looped
    assert losses[-1] < losses[0] / 3, (losses[0], losses[-1])


def test_compression_rejects_sharded_axes():
    with pytest.raises(ValueError):
        ParallelismPlugin(grad_compression="fp4")
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            mesh_config=MeshConfig(data=4, tensor=2), grad_compression="bf16"
        )
    )
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    with pytest.raises(ValueError, match="data"):
        acc.build_train_step(linear_loss_fn)
