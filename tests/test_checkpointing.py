"""Checkpoint round-trip tests (reference analogue:
tests/test_state_checkpointing.py, 444 LoC — save/load equality of
model/opt/RNG/dataloader state)."""

import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, ParallelismPlugin, ProjectConfiguration
from accelerate_tpu.checkpointing import load_model, save_model
from accelerate_tpu.test_utils import RegressionDataset, RegressionModel, linear_loss_fn


def train_some(acc, steps=4):
    ds = RegressionDataset(length=64)
    model, optimizer, loader = acc.prepare(RegressionModel(), optax.adam(0.05), ds)
    loader.batch_size = 16 // acc.num_data_shards
    step = acc.build_train_step(linear_loss_fn)
    it = iter(loader)
    for _ in range(steps):
        try:
            batch = next(it)
        except StopIteration:
            it = iter(loader)
            batch = next(it)
        step(batch)
    return model, optimizer, loader


def test_save_load_roundtrip(tmp_path):
    acc = Accelerator()
    model, optimizer, loader = train_some(acc)
    a_saved = float(model.params["a"])
    acc.save_state(str(tmp_path / "ckpt"))

    # perturb then restore
    import jax

    model.params = jax.tree_util.tree_map(lambda x: x * 0, model.params)
    acc.load_state(str(tmp_path / "ckpt"))
    assert float(model.params["a"]) == pytest.approx(a_saved)


def test_save_load_across_mesh_shapes(tmp_path):
    """Reshard-on-load: save on dp=8, load onto dp=2 x fsdp=4."""
    acc = Accelerator()
    model, _, _ = train_some(acc)
    a_saved = float(model.params["a"])
    acc.save_state(str(tmp_path / "ckpt"))

    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    acc2 = Accelerator(parallelism_plugin=ParallelismPlugin(mesh_config=MeshConfig(data=2, fsdp=4)))
    model2, opt2, loader2 = acc2.prepare(RegressionModel(), optax.adam(0.05), RegressionDataset(length=64))
    acc2.load_state(str(tmp_path / "ckpt"))
    assert float(model2.params["a"]) == pytest.approx(a_saved)


def test_automatic_checkpoint_naming_and_total_limit(tmp_path):
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
        )
    )
    model, optimizer, loader = train_some(acc, steps=1)
    for _ in range(3):
        acc.save_state()
    ckpts = sorted((tmp_path / "checkpoints").iterdir())
    assert [c.name for c in ckpts] == ["checkpoint_1", "checkpoint_2"]


def test_custom_object_checkpointing(tmp_path):
    class Counter:
        def __init__(self):
            self.n = 0

        def state_dict(self):
            return {"n": self.n}

        def load_state_dict(self, sd):
            self.n = sd["n"]

    acc = Accelerator()
    model, optimizer, loader = train_some(acc, steps=1)
    counter = Counter()
    counter.n = 42
    acc.register_for_checkpointing(counter)
    acc.save_state(str(tmp_path / "ckpt"))
    counter.n = 0
    acc.load_state(str(tmp_path / "ckpt"))
    assert counter.n == 42


def test_save_model_safetensors_roundtrip(tmp_path):
    acc = Accelerator()
    model, _, _ = train_some(acc)
    acc.save_model(model, str(tmp_path / "export"))
    assert (tmp_path / "export" / "model.safetensors").exists()

    fresh = RegressionModel()
    load_model(fresh, str(tmp_path / "export"))
    np.testing.assert_allclose(float(fresh.params["a"]), float(model.params["a"]))


def test_save_model_sharding_splits(tmp_path):
    from accelerate_tpu.modeling import Model

    params = {f"w{i}": np.ones((128, 128), np.float32) for i in range(4)}  # 64KB each
    model = Model(lambda p, x: x, params)
    save_model(model, str(tmp_path / "export"), max_shard_size="100KB")
    index = tmp_path / "export" / "model.safetensors.index.json"
    assert index.exists()
    import json

    weight_map = json.loads(index.read_text())["weight_map"]
    assert len(set(weight_map.values())) >= 2


def _batch_fingerprint(batch):
    import jax

    return tuple(float(np.asarray(l).sum()) for l in jax.tree_util.tree_leaves(batch))


def test_exact_mid_epoch_resume(tmp_path):
    """Kill-and-resume mid-epoch reproduces the exact batch sequence of an
    uninterrupted run (reference: StatefulDataLoader state persisted at
    checkpointing.py:139-143 + skip_first_batches data_loader.py:1371)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    def fresh(seed=7):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator()
        ds = RegressionDataset(length=64, seed=seed)
        model, optimizer, loader = acc.prepare(RegressionModel(), optax.adam(0.05), ds)
        loader.batch_size = 8 // acc.num_data_shards
        loader.sampler = __import__("accelerate_tpu.data_loader", fromlist=["SeedableRandomSampler"]).SeedableRandomSampler(64, seed=3)
        return acc, model, loader

    # ---- uninterrupted run: record the full 2-epoch batch sequence ----
    acc, model, loader = fresh()
    reference_seq = []
    for _epoch in range(2):
        for batch in loader:
            reference_seq.append(_batch_fingerprint(batch))

    # ---- interrupted run: stop after 3 batches of epoch 0, save ----
    acc, model, loader = fresh()
    got = []
    it = iter(loader)
    for _ in range(3):
        got.append(_batch_fingerprint(next(it)))
    acc.save_state(str(tmp_path / "ckpt"))
    del it  # simulate the process dying mid-epoch

    # ---- resumed run: fresh process, load, continue to the end ----
    acc, model, loader = fresh()
    acc.load_state(str(tmp_path / "ckpt"))
    assert loader.skip_batches == 3
    for batch in loader:  # rest of epoch 0
        got.append(_batch_fingerprint(batch))
    for batch in loader:  # epoch 1
        got.append(_batch_fingerprint(batch))

    assert got == reference_seq, (len(got), len(reference_seq))


def test_break_then_save_resume(tmp_path):
    """The max-steps idiom: break out of the epoch, THEN save. The epoch /
    sampler state must stay on the current epoch so the saved offset
    attaches to the right permutation."""
    from accelerate_tpu.data_loader import SeedableRandomSampler
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    def fresh():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator()
        ds = RegressionDataset(length=64, seed=9)
        model, optimizer, loader = acc.prepare(RegressionModel(), optax.adam(0.05), ds)
        loader.batch_size = 8 // acc.num_data_shards
        loader.sampler = SeedableRandomSampler(64, seed=5)
        return acc, loader

    acc, loader = fresh()
    reference_seq = [_batch_fingerprint(b) for b in loader]  # epoch 0

    acc, loader = fresh()
    got = []
    for i, b in enumerate(loader):
        got.append(_batch_fingerprint(b))
        if i == 2:
            break  # the generator CLOSES here (max-steps pattern) ...
    acc.save_state(str(tmp_path / "ckpt"))  # ... and only then we save

    acc, loader = fresh()
    acc.load_state(str(tmp_path / "ckpt"))
    got.extend(_batch_fingerprint(b) for b in loader)
    assert got == reference_seq, (len(got), len(reference_seq))


def test_model_state_roundtrip(tmp_path):
    """Non-trainable model.state (BatchNorm running stats) must survive
    save_state/load_state — torch carries these as buffers in the module
    state_dict; here they are a separate pytree."""
    import jax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import ResNetConfig, create_resnet_model, resnet_classification_loss
    from accelerate_tpu.parallel.mesh import batch_sharding

    acc = Accelerator()
    model = acc.prepare_model(create_resnet_model(ResNetConfig.tiny(), image_size=16))
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(
        lambda p, s, b: resnet_classification_loss(p, s, b, model.apply_fn), has_state=True
    )
    rng = np.random.default_rng(0)
    batch = {
        "images": rng.normal(size=(16, 16, 16, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, size=(16,)).astype(np.int32),
    }
    batch = jax.device_put(batch, batch_sharding(acc.mesh))
    for _ in range(3):
        step(batch)
    trained_stats = jax.tree_util.tree_map(np.asarray, model.state)
    acc.save_state(str(tmp_path / "ckpt"))

    # perturb the running stats, then restore
    model.state = jax.tree_util.tree_map(lambda x: x * 0, model.state)
    acc.load_state(str(tmp_path / "ckpt"))
    restored = jax.tree_util.tree_map(np.asarray, model.state)
    for a, b in zip(jax.tree_util.tree_leaves(trained_stats), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_load_state_before_first_step_commits_to_mesh(tmp_path):
    """Resume regression: a fresh process that builds the train step and
    calls load_state BEFORE stepping must not end up with params committed
    to the mesh but optimizer state committed to device 0 (jax rejects the
    mixed-device jit call)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.test_utils import RegressionModel, linear_loss_fn

    batch = {"x": np.ones((8,), np.float32), "y": np.ones((8,), np.float32)}
    acc = Accelerator()
    acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.adamw(1e-2))
    step = acc.build_train_step(linear_loss_fn)
    step(batch)
    ck = str(tmp_path / "ck")
    acc.save_state(ck)
    saved_a = float(acc._models[-1].params["a"])

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc2 = Accelerator()
    model2 = acc2.prepare_model(RegressionModel())
    acc2.prepare_optimizer(optax.adamw(1e-2))
    step2 = acc2.build_train_step(linear_loss_fn)
    acc2.load_state(ck)  # before any step2() call
    assert float(model2.params["a"]) == saved_a
    step2(batch)  # must not raise "incompatible devices"


def test_async_save_state_roundtrip(tmp_path):
    """async_save returns before disk IO completes; wait_for_checkpoint
    commits, and the checkpoint restores exactly (parity-plus: the
    reference has no async checkpoint path)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionModel, linear_loss_fn

    batch = {"x": np.ones((8,), np.float32), "y": 2 * np.ones((8,), np.float32)}
    acc = Accelerator()
    model = acc.prepare_model(RegressionModel())
    acc.prepare_optimizer(optax.sgd(0.1))
    step = acc.build_train_step(linear_loss_fn)
    step(batch)
    saved_a = float(model.params["a"])

    ck = str(tmp_path / "ck")
    acc.save_state(ck, async_save=True)
    # training continues while the write is in flight
    step(batch)
    assert float(model.params["a"]) != saved_a
    acc.wait_for_checkpoint()

    acc.load_state(ck)
    assert float(model.params["a"]) == saved_a
    step(batch)  # restored state still steps


def test_async_save_drained_by_next_load(tmp_path):
    """load_state must drain an in-flight async save rather than read a
    half-written checkpoint."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils import RegressionModel, linear_loss_fn

    acc = Accelerator()
    model = acc.prepare_model(RegressionModel(a=3.25))
    acc.prepare_optimizer(optax.sgd(0.1))
    acc.build_train_step(linear_loss_fn)
    ck = str(tmp_path / "ck")
    acc.save_state(ck, async_save=True)
    acc.load_state(ck)  # no explicit wait: load drains the pending save
    assert float(model.params["a"]) == 3.25


def test_every_save_writes_commit_manifest(tmp_path):
    """Atomic protocol: a committed checkpoint always carries a verifying
    commit_success.json, and the .tmp staging dir is gone."""
    from accelerate_tpu.ft.manifest import MANIFEST_NAME, verify_manifest

    acc = Accelerator()
    train_some(acc, steps=1)
    out = acc.save_state(str(tmp_path / "ckpt"))
    assert (tmp_path / "ckpt" / MANIFEST_NAME).exists()
    assert verify_manifest(out, deep=True) == []
    assert not (tmp_path / "ckpt.tmp").exists()


def test_explicit_dir_overwrite_stays_atomic(tmp_path):
    """Saving twice to the same explicit output_dir swaps atomically: the
    second save fully replaces the first and still verifies."""
    from accelerate_tpu.ft.manifest import read_manifest, verify_manifest

    acc = Accelerator()
    model, _, _ = train_some(acc, steps=1)
    ck = str(tmp_path / "ckpt")
    acc.save_state(ck)
    first_step = read_manifest(ck)["step"]
    model.params = {k: v + 1 for k, v in model.params.items()}
    acc.step += 5
    acc.save_state(ck)
    assert verify_manifest(ck, deep=True) == []
    assert read_manifest(ck)["step"] == first_step + 5
    leftovers = [p.name for p in (tmp_path).iterdir() if p.name != "ckpt"]
    assert leftovers == [], f"swap left debris: {leftovers}"
