"""The multi-host divergence analyzer (``analysis.ranksim`` +
``analysis.divergence``): taint propagation through the multi-rank
interpreter, per-rank trace diffing into the TPU4xx rules, the
Accelerator/collectives effect-summary tables, ``.tpulint.toml`` project
configuration, and the CLI/SARIF surface."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from accelerate_tpu.analysis.divergence import analyze_file, analyze_paths, analyze_source
from accelerate_tpu.analysis.project_config import (
    ProjectConfig,
    _parse_minimal_toml,
    find_project_config,
    load_project_config,
)
from accelerate_tpu.analysis.ranksim import (
    ACCELERATOR_EFFECTS,
    COLLECTIVE_EFFECTS,
    DIVERGENT,
    UNIFORM,
    ModuleSimulator,
    Value,
    join_values,
)
from accelerate_tpu.analysis.rules import ERROR, RULES, WARNING

import ast

CPU_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}


def run_cli(*args, cwd=None, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", *args],
        capture_output=True,
        text=True,
        env=CPU_ENV,
        cwd=cwd,
        timeout=timeout,
    )


def _rules(findings):
    return [f.rule for f in findings]


def _analyze(src, **kw):
    return analyze_source(textwrap.dedent(src), path="fix.py", **kw)


def _sim(src, n_ranks=3):
    return ModuleSimulator(ast.parse(textwrap.dedent(src)), n_ranks=n_ranks)


# --------------------------------------------------------------------- #
# the taint lattice
# --------------------------------------------------------------------- #


def test_join_values_divergent_wins():
    u, d = Value(UNIFORM), Value(DIVERGENT, None, "process_index")
    assert not join_values(u, u).divergent
    joined = join_values(u, d, u)
    assert joined.divergent and joined.origin == "process_index"


def test_taint_propagates_through_arithmetic():
    """rank-derived values stay divergent through computation; a guard on
    one sends synthetic ranks down different branches (trace diff)."""
    findings = _analyze(
        """
        def f(accelerator, x):
            shifted = accelerator.process_index + 1
            if shifted * 2 > 2:
                accelerator.wait_for_everyone()
        """
    )
    assert "TPU401" in _rules(findings)


def test_uniform_computation_stays_uniform():
    """pure computation over uniform values never diverges — a config
    branch around a barrier is fine (both worlds run it or skip it on
    EVERY rank)."""
    findings = _analyze(
        """
        def f(accelerator, cfg):
            n = cfg.batch_size * 2
            if n > 64:
                accelerator.wait_for_everyone()
            accelerator.gather(n)
        """
    )
    assert findings == []


def test_per_rank_concrete_branching():
    """is_main_process is True exactly on rank 0: the simulator sends each
    synthetic rank down its real branch, so main-only *local* work is
    clean but main-only collectives are not."""
    clean = _analyze(
        """
        def f(accelerator, metrics):
            if accelerator.is_main_process:
                print(metrics)
        """
    )
    assert clean == []
    deadlock = _analyze(
        """
        def f(accelerator, metrics):
            if accelerator.is_main_process:
                accelerator.gather(metrics)
        """
    )
    assert _rules(deadlock) == ["TPU401"]
    assert deadlock[0].severity == ERROR
    assert "gather" in deadlock[0].message and "is_main_process" in deadlock[0].message


def test_numeric_roots_not_mistaken_for_accelerator():
    """jnp.log / functools.reduce must not resolve to Accelerator.log /
    .reduce effect summaries."""
    findings = _analyze(
        """
        import functools
        import jax.numpy as jnp


        def f(accelerator, xs):
            if accelerator.is_main_process:
                return functools.reduce(lambda a, b: a + b, xs) + jnp.log(xs[0])
            return None
        """
    )
    assert findings == []


def test_host_entropy_taints():
    """random/time/hostname reads are per-host state: a barrier under such
    a guard deadlocks."""
    findings = _analyze(
        """
        import random


        def f(accelerator):
            if random.random() > 0.5:
                accelerator.wait_for_everyone()
        """
    )
    assert _rules(findings) == ["TPU401"]


# --------------------------------------------------------------------- #
# per-rank trace diffing: the rule family
# --------------------------------------------------------------------- #


def test_tpu401_divergent_early_return():
    """a rank-divergent return before a barrier strands the other ranks."""
    findings = _analyze(
        """
        def f(accelerator, batch):
            if accelerator.process_index > 0:
                return None
            return accelerator.gather(batch)
        """
    )
    assert "TPU401" in _rules(findings)


def test_tpu401_collective_inside_main_process_first():
    """ranks are serialized inside main_process_first: a collective in the
    body can never line up."""
    findings = _analyze(
        """
        def f(accelerator, ds):
            with accelerator.main_process_first():
                ds = accelerator.broadcast(ds)
            return ds
        """
    )
    assert "TPU401" in _rules(findings)
    assert "main_process_first" in findings[0].message


def test_tpu401_barrier_inside_solo_decorated_function():
    """@on_main_process makes the body main-only — a barrier inside one is
    itself a deadlock, and the simulator models the decorator."""
    findings = _analyze(
        """
        from accelerate_tpu.state import on_main_process


        @on_main_process
        def publish(accelerator, path):
            accelerator.wait_for_everyone()
        """
    )
    assert "TPU401" in _rules(findings)


def test_tpu402_divergent_loop_trip_count():
    findings = _analyze(
        """
        import os


        def drain(accelerator):
            for shard in os.listdir("/data"):
                accelerator.reduce(shard)
        """
    )
    assert "TPU402" in _rules(findings)
    assert RULES["TPU402"].severity == ERROR
    assert "listdir" in findings[0].message


def test_tpu402_uniform_loop_is_clean():
    findings = _analyze(
        """
        def train(accelerator, batches):
            for batch in batches:
                accelerator.backward(batch)
                loss = accelerator.gather(batch)
            return loss
        """
    )
    assert findings == []


def test_tpu403_mismatched_order():
    findings = _analyze(
        """
        def step(accelerator, x):
            if accelerator.is_main_process:
                x = accelerator.gather(x)
                accelerator.wait_for_everyone()
            else:
                accelerator.wait_for_everyone()
                x = accelerator.gather(x)
            return x
        """
    )
    assert "TPU403" in _rules(findings)
    assert "order" in findings[0].message


def test_matched_syncs_across_branches_are_clean():
    """both arms emit the SAME collective program (different lines):
    runtime-equivalent, must not fire."""
    findings = _analyze(
        """
        def step(accelerator, x, y):
            if accelerator.is_main_process:
                out = accelerator.gather(x)
            else:
                out = accelerator.gather(y)
            accelerator.wait_for_everyone()
            return out
        """
    )
    assert findings == []


def test_tpu404_divergent_break_skips_barrier():
    findings = _analyze(
        """
        def loop(accelerator, batches):
            for batch in batches:
                if accelerator.process_index > 0:
                    break
                accelerator.backward(batch)
            accelerator.wait_for_everyone()
        """
    )
    assert "TPU404" in _rules(findings)
    assert RULES["TPU404"].severity == WARNING
    assert "wait_for_everyone" in findings[0].message


def test_tpu405_unguarded_write_and_guarded_clean():
    dirty = _analyze(
        """
        import os


        def finish(accelerator, payload):
            os.makedirs("out")
            with open("out/summary.json", "w") as fh:
                fh.write(payload)
            accelerator.wait_for_everyone()
        """
    )
    assert _rules(dirty) == ["TPU405", "TPU405"]
    guarded = _analyze(
        """
        import os


        def finish(accelerator, payload):
            if accelerator.is_main_process:
                os.makedirs("out")
                with open("out/summary.json", "w") as fh:
                    fh.write(payload)
            accelerator.wait_for_everyone()
        """
    )
    assert guarded == []


def test_tpu405_needs_rank_aware_scope():
    """a pure IO helper (no rank vocabulary) is the caller's problem —
    TPU405 stays quiet there."""
    findings = _analyze(
        """
        def dump(path, payload):
            with open(path, "w") as fh:
                fh.write(payload)
        """
    )
    assert findings == []


def test_tpu405_solo_decorator_guards_writes():
    findings = _analyze(
        """
        import os

        from accelerate_tpu.state import on_main_process


        @on_main_process
        def publish(run_dir, payload):
            os.makedirs(run_dir)
            with open(run_dir + "/out.json", "w") as fh:
                fh.write(payload)
        """
    )
    assert findings == []


def test_rank_namespaced_write_is_clean():
    """writes to a path derived from process_index can't collide."""
    findings = _analyze(
        """
        def dump(accelerator, payload):
            path = f"out/rank{accelerator.process_index}.json"
            with open(path, "w") as fh:
                fh.write(payload)
            accelerator.wait_for_everyone()
        """
    )
    assert findings == []


def test_interprocedural_one_level():
    """calls are followed one level deep within the file: a guarded call
    to a helper that syncs is the same deadlock."""
    findings = _analyze(
        """
        def sync_all(accelerator, x):
            return accelerator.gather(x)


        def f(accelerator, x):
            if accelerator.is_main_process:
                return sync_all(accelerator, x)
            return None
        """
    )
    assert "TPU401" in _rules(findings)


def test_save_state_commit_barriers_uniform():
    """the PR-4 atomic commit protocol (save_state = enter+commit
    barriers) is rank-uniform when called unconditionally, deadlock when
    main-only."""
    clean = _analyze(
        """
        def f(accelerator):
            accelerator.save_state("ckpt")
        """
    )
    assert clean == []
    dirty = _analyze(
        """
        def f(accelerator):
            if accelerator.is_main_process:
                accelerator.save_state("ckpt")
        """
    )
    assert "TPU401" in _rules(dirty)


def test_entry_restriction_and_paths(tmp_path):
    src = textwrap.dedent(
        """
        \"\"\"Fixture module.\"\"\"


        def good(accelerator, x):
            return accelerator.gather(x)


        def bad(accelerator, x):
            if accelerator.is_main_process:
                return accelerator.gather(x)
            return None
        """
    )
    mod = tmp_path / "train.py"
    mod.write_text(src)
    assert analyze_file(mod, entry="good") == []
    assert "TPU401" in _rules(analyze_file(mod, entry="bad"))
    # file.py::fn targets through analyze_paths
    assert analyze_paths([f"{mod}::good"]) == []
    assert "TPU401" in _rules(analyze_paths([f"{mod}::bad"]))
    assert "TPU401" in _rules(analyze_paths([str(tmp_path)]))


def test_inline_suppression():
    findings = _analyze(
        """
        def f(accelerator, metrics):
            if accelerator.is_main_process:
                return accelerator.gather(metrics)  # tpu-lint: disable=TPU401
            return None
        """
    )
    assert findings == []


def test_selfcheck_fixtures_fire_and_clean_is_clean():
    from accelerate_tpu.analysis.selfcheck import run_divergence_selfcheck

    ok, lines = run_divergence_selfcheck()
    assert ok, "\n".join(lines)
    assert sum("detected" in line for line in lines) == 5
    assert any("zero findings" in line for line in lines)


# --------------------------------------------------------------------- #
# effect-summary tables
# --------------------------------------------------------------------- #


def test_collectives_effect_table_covers_module_surface():
    """every public symbol in parallel.collectives must carry a divergence
    model — a new collective cannot silently bypass the analyzer."""
    import inspect

    from accelerate_tpu.parallel import collectives

    public = {
        name
        for name, obj in vars(collectives).items()
        if not name.startswith("_") and inspect.isfunction(obj) and obj.__module__ == collectives.__name__
    }
    assert public, "parallel.collectives exposes no functions?"
    missing = public - set(COLLECTIVE_EFFECTS)
    assert missing == set(), f"collectives without a divergence model: {sorted(missing)}"


def test_accelerator_effect_table_semantics():
    assert ACCELERATOR_EFFECTS["save_state"].events == ("barrier:save_state/enter", "barrier:save_state/commit")
    assert ACCELERATOR_EFFECTS["wait_for_everyone"].events == ("barrier:wait_for_everyone",)
    assert ACCELERATOR_EFFECTS["prepare"].events == ()  # purely local
    assert COLLECTIVE_EFFECTS["axis_index"].returns == DIVERGENT


def test_simulator_traces_shape():
    """k ranks, two worlds per entry, events carry line numbers."""
    sim = _sim(
        """
        def f(accelerator, x):
            accelerator.wait_for_everyone()
            return accelerator.gather(x)
        """,
        n_ranks=4,
    )
    results = [r for r in sim.run(entry="f")]
    assert len(results) == 2  # then + else worlds
    for res in results:
        assert len(res.traces) == 4
        for tr in res.traces:
            names = [(e.kind, e.name) for e in tr.events if e.sync]
            assert names == [("barrier", "wait_for_everyone"), ("collective", "gather")]
            assert all(e.line > 0 for e in tr.events)


# --------------------------------------------------------------------- #
# .tpulint.toml project configuration
# --------------------------------------------------------------------- #


def test_minimal_toml_parser_matches_schema():
    doc = _parse_minimal_toml(
        textwrap.dedent(
            """
            # comment
            [lint]
            format = "sarif"     # trailing comment
            disable = ["TPU103", "TPU405"]

            [divergence]
            ranks = 5

            [[suppress]]
            path = "examples/*"
            rules = ["TPU405"]

            [[suppress]]
            path = "vendor/"
            """
        )
    )
    assert doc["lint"]["format"] == "sarif"
    assert doc["lint"]["disable"] == ["TPU103", "TPU405"]
    assert doc["divergence"]["ranks"] == 5
    assert len(doc["suppress"]) == 2
    assert doc["suppress"][1] == {"path": "vendor/"}


def test_project_config_discovery_and_merge(tmp_path):
    (tmp_path / ".tpulint.toml").write_text(
        textwrap.dedent(
            """
            [lint]
            format = "json"
            disable = ["TPU404"]

            [divergence]
            ranks = 4

            [[suppress]]
            path = "vendored/*"
            """
        )
    )
    sub = tmp_path / "vendored"
    sub.mkdir()
    assert find_project_config(sub) == str(tmp_path / ".tpulint.toml")
    cfg = load_project_config(sub)
    assert cfg.resolve_format(None) == "json"
    assert cfg.resolve_format("text") == "text"  # CLI flag wins
    assert cfg.resolve_ranks(None) == 4
    assert cfg.merge_ignore(("tpu103",)) == frozenset({"TPU103", "TPU404"})

    from accelerate_tpu.analysis.rules import Finding

    kept = cfg.apply_suppressions(
        [
            Finding("TPU401", "x", path=str(sub / "a.py"), line=1),
            Finding("TPU401", "y", path=str(tmp_path / "train.py"), line=1),
        ]
    )
    assert [f.message for f in kept] == ["y"]


def test_project_config_absent_is_default(tmp_path):
    cfg = load_project_config(tmp_path)
    assert cfg == ProjectConfig()
    assert cfg.resolve_format(None) == "text"
    assert cfg.resolve_ranks(None) == 3


def test_repo_config_parses():
    """the checked-in .tpulint.toml must stay loadable."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_project_config(repo)
    assert cfg.path and cfg.path.endswith(".tpulint.toml")
    assert cfg.resolve_format(None) == "text"
    assert cfg.resolve_ranks(None) == 3
    assert cfg.disable == frozenset()


# --------------------------------------------------------------------- #
# CLI + SARIF + the repo's own tree
# --------------------------------------------------------------------- #


@pytest.fixture
def bad_script(tmp_path):
    p = tmp_path / "train.py"
    p.write_text(
        textwrap.dedent(
            """
            \"\"\"Seeded multi-host deadlock.\"\"\"


            def evaluate(accelerator, metrics):
                if accelerator.is_main_process:
                    return accelerator.gather(metrics)
                return None
            """
        )
    )
    return p


def test_cli_divergence_detects_and_exits_nonzero(bad_script):
    result = run_cli("divergence", str(bad_script))
    assert result.returncode == 1, result.stdout + result.stderr
    assert f"{bad_script}:7: TPU401" in result.stdout  # path:line: TPUxxx contract
    assert "1 error(s)" in result.stdout


def test_cli_divergence_json(bad_script):
    result = run_cli("divergence", str(bad_script), "--format", "json")
    payload = json.loads(result.stdout)
    assert [f["rule"] for f in payload] == ["TPU401"]
    assert payload[0]["severity"] == "error"
    assert payload[0]["path"] == str(bad_script)


def test_cli_divergence_sarif(bad_script):
    result = run_cli("divergence", str(bad_script), "--format", "sarif")
    doc = json.loads(result.stdout)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert results[0]["ruleId"] == "TPU401" and results[0]["level"] == "error"
    assert results[0]["locations"][0]["physicalLocation"]["artifactLocation"]["uri"] == str(bad_script)
    rules = {r["id"]: r for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules["TPU401"]["properties"]["tier"] == "divergence"


def test_cli_divergence_entry_target_and_ranks(bad_script):
    ok = run_cli("divergence", f"{bad_script}::missing_entry")
    assert ok.returncode == 0  # no such entry -> nothing analyzed, no findings
    bad = run_cli("divergence", f"{bad_script}::evaluate", "--ranks", "5")
    assert bad.returncode == 1
    assert "TPU401" in bad.stdout


def test_cli_divergence_selfcheck():
    result = run_cli("divergence", "--selfcheck")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("detected") == 5
    assert "zero findings" in result.stdout


def test_cli_divergence_config_defaults(bad_script, tmp_path):
    (tmp_path / ".tpulint.toml").write_text('[lint]\nformat = "json"\ndisable = ["TPU401"]\n')
    result = run_cli("divergence", str(bad_script), cwd=tmp_path)
    assert result.returncode == 0, result.stdout + result.stderr
    assert json.loads(result.stdout) == []  # json default + TPU401 disabled


def test_cli_flightcheck_sarif():
    """--format sarif wired through flight-check (shared reporter)."""
    result = run_cli(
        "flight-check",
        "examples/by_feature/flight_check.py::train_step",
        "--mesh", "data=8", "--donate", "0", "--format", "sarif",
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert result.returncode == 0, result.stdout + result.stderr
    doc = json.loads(result.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["tool"]["driver"]["name"] == "accelerate-tpu-lint"


def test_merge_sarif_script(tmp_path, bad_script):
    a = run_cli("divergence", str(bad_script), "--format", "sarif").stdout
    (tmp_path / "a.sarif").write_text(a)
    (tmp_path / "b.sarif").write_text(a)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "merge_sarif.py"),
         str(tmp_path / "a.sarif"), str(tmp_path / "b.sarif"),
         str(tmp_path / "missing.sarif"), "-o", str(tmp_path / "merged.sarif")],
        capture_output=True, text=True, env=CPU_ENV,
    )
    assert result.returncode == 0, result.stderr
    merged = json.loads((tmp_path / "merged.sarif").read_text())
    assert len(merged["runs"]) == 2  # missing input skipped, not fatal


def test_accelerator_lint_runs_divergence_on_calling_module(tmp_path):
    """Accelerator.lint analyzes the module that called it."""
    script = tmp_path / "lint_me.py"
    script.write_text(
        textwrap.dedent(
            """
            \"\"\"Fixture: calls Accelerator.lint from a module with a seeded deadlock.\"\"\"
            import jax
            import jax.numpy as jnp

            from accelerate_tpu import Accelerator


            def evaluate(accelerator, metrics):
                if accelerator.is_main_process:
                    return accelerator.gather(metrics)
                return None


            def step(x):
                return x * 2


            acc = Accelerator()
            findings = acc.lint(step, jax.ShapeDtypeStruct((8,), jnp.float32))
            print("RULES", sorted({f.rule for f in findings}))
            quiet = acc.lint(step, jax.ShapeDtypeStruct((8,), jnp.float32), divergence=False)
            print("QUIET", sorted({f.rule for f in quiet}))
            """
        )
    )
    result = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True, env=CPU_ENV, timeout=240,
    )
    assert result.returncode == 0, result.stderr
    assert "RULES ['TPU401']" in result.stdout
    assert "QUIET []" in result.stdout


def test_repo_tree_is_divergence_clean():
    """dogfood: the package's own tree (checkpointing, tracking, ft/,
    accelerator, commands) must carry zero TPU4xx errors — the make lint
    strict gate."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = analyze_paths([os.path.join(repo, "accelerate_tpu")])
    errors = [f for f in findings if f.is_error]
    assert errors == [], "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}" for f in errors)
    warnings = [f for f in findings if not f.is_error]
    assert warnings == [], "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}" for f in warnings)
