"""Qwen3 family (models/qwen3.py): per-head q/k RMSNorm through decode,
explicit head_dim, TP-sharded decode, and serving. HF importer parity
lives in test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import Qwen3Config, create_qwen3_model


@pytest.fixture(scope="module")
def tiny_qwen3():
    return create_qwen3_model(Qwen3Config.tiny(), seq_len=16)


def test_qk_norm_params_exist(tiny_qwen3):
    block = tiny_qwen3.params["layers"]["block"]["attn"]
    cfg = Qwen3Config.tiny()
    for norm in ("q_norm", "k_norm"):
        # scan-over-layers stacks a leading layer dim over the [head_dim] scale
        assert block[norm]["scale"].shape == (cfg.num_hidden_layers, cfg.head_dim), norm
    for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
        assert "bias" not in block[proj], proj  # Qwen3 dropped the Qwen2 biases


def test_greedy_decode_matches_full_prefix(tiny_qwen3):
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_qwen3, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_qwen3(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_tp_sharded_decode(tiny_qwen3):
    """TP splits q/k/v kernels over heads while the shared [head_dim]
    norm scales stay replicated: sharded tokens == single-device tokens."""
    import jax

    from accelerate_tpu.big_modeling import shard_model
    from accelerate_tpu.parallel.mesh import MeshConfig

    prompt = (np.arange(8) % 250).astype(np.int32)[None]
    want = np.asarray(generate(tiny_qwen3, prompt, max_new_tokens=5))

    model = create_qwen3_model(Qwen3Config.tiny(), seq_len=16)
    mesh = MeshConfig(data=1, tensor=2).build(jax.devices()[:2])
    shard_model(model, mesh)
    norm_sh = model.param_shardings["layers"]["block"]["attn"]["q_norm"]["scale"]
    assert norm_sh.is_fully_replicated, norm_sh  # shared across split heads
    got = np.asarray(generate(model, prompt, max_new_tokens=5))
    np.testing.assert_array_equal(got, want)


def test_paged_serving(tiny_qwen3):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9, 6)]
    eng = ServingEngine(tiny_qwen3, num_slots=2, prompt_buckets=(4, 8, 16), paged_block_size=4)
    outs = eng.generate_many(prompts, max_new_tokens=5)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_qwen3, p[None], max_new_tokens=5))[0]
        np.testing.assert_array_equal(got, ref)


def test_loader_requires_norm_scales(tmp_path):
    """A Qwen3-config load without q/k norm tensors must fail loudly —
    _merge_into would otherwise silently keep random-init norm scales
    (and the all-or-none cross-layer stacking check must also hold)."""
    import pytest as _pytest

    from accelerate_tpu.models.hub import convert_hf_llama_state

    rng = np.random.default_rng(0)
    state = {}
    for i in range(2):
        for name, shape in (
            ("self_attn.q_proj.weight", (64, 64)),
            ("self_attn.k_proj.weight", (32, 64)),
            ("self_attn.v_proj.weight", (32, 64)),
            ("self_attn.o_proj.weight", (64, 64)),
            ("mlp.gate_proj.weight", (128, 64)),
            ("mlp.up_proj.weight", (128, 64)),
            ("mlp.down_proj.weight", (64, 128)),
            ("input_layernorm.weight", (64,)),
            ("post_attention_layernorm.weight", (64,)),
        ):
            state[f"model.layers.{i}.{name}"] = rng.normal(size=shape).astype(np.float32)
    with _pytest.raises(ValueError, match="q_norm"):
        convert_hf_llama_state(
            state, scan_layers=True, num_heads=4, num_kv_heads=2,
            require=("attn/q_norm/scale", "attn/k_norm/scale"),
        )
    # present in one layer but not the other: all-or-none check fires
    state["model.layers.0.self_attn.q_norm.weight"] = np.ones((16,), np.float32)
    with _pytest.raises(ValueError, match="present in some layers"):
        convert_hf_llama_state(state, scan_layers=True, num_heads=4, num_kv_heads=2)
