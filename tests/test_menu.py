"""Cursor-menu widget (commands/menu.py; reference: commands/menu/)."""

import io

import pytest

from accelerate_tpu.commands import menu


def test_fallback_select_default():
    idx = menu._fallback_select("pick", ["a", "b", "c"], 1, input_fn=lambda _: "")
    assert idx == 1


def test_fallback_select_number():
    idx = menu._fallback_select("pick", ["a", "b", "c"], 0, input_fn=lambda _: "2")
    assert idx == 2


def test_fallback_select_prefix_match():
    idx = menu._fallback_select("pick", ["no", "bf16", "fp16", "fp8"], 0, input_fn=lambda _: "b")
    assert idx == 1


def test_fallback_select_ambiguous_prefix_raises():
    with pytest.raises(ValueError, match="invalid choice"):
        menu._fallback_select("pick", ["fp16", "fp8"], 0, input_fn=lambda _: "fp")


def test_fallback_select_out_of_range_raises():
    with pytest.raises(ValueError, match="out of range"):
        menu._fallback_select("pick", ["a", "b"], 0, input_fn=lambda _: "7")


def test_select_non_tty_uses_fallback(monkeypatch, capsys):
    monkeypatch.setattr("sys.stdin", io.StringIO("1\n"))
    assert menu.select("pick", ["x", "y"]) == "y"
    out = capsys.readouterr().out
    assert "[0] x" in out and "[1] y" in out


def test_interactive_select_arrow_keys(monkeypatch, capsys):
    keys = iter(["down", "down", "up", "enter"])  # 0 -> 1 -> 2 -> 1 -> pick
    monkeypatch.setattr(menu, "_read_key", lambda stdin=None: next(keys))
    assert menu._interactive_select("pick", ["a", "b", "c"], 0) == 1


def test_interactive_select_wraps_and_digit_jump(monkeypatch):
    keys = iter(["up", "enter"])  # wraps 0 -> 2
    monkeypatch.setattr(menu, "_read_key", lambda stdin=None: next(keys))
    assert menu._interactive_select("pick", ["a", "b", "c"], 0) == 2
    keys = iter(["2", "enter"])
    monkeypatch.setattr(menu, "_read_key", lambda stdin=None: next(keys))
    assert menu._interactive_select("pick", ["a", "b", "c"], 0) == 2


def test_interactive_select_vim_keys_and_interrupt(monkeypatch):
    keys = iter(["j", "j", "k", "enter"])
    monkeypatch.setattr(menu, "_read_key", lambda stdin=None: next(keys))
    assert menu._interactive_select("pick", ["a", "b", "c"], 0) == 1
    keys = iter(["interrupt"])
    monkeypatch.setattr(menu, "_read_key", lambda stdin=None: next(keys))
    with pytest.raises(KeyboardInterrupt):
        menu._interactive_select("pick", ["a", "b"], 0)


def test_escape_sequence_keymap():
    assert menu._ESCAPE_SEQUENCES["[A"] == "up"
    assert menu._ESCAPE_SEQUENCES["[B"] == "down"


def test_select_empty_choices_raises():
    with pytest.raises(ValueError):
        menu.select("pick", [])
