"""Per-backend tracker tests with mocked third-party modules.

Reference analogue: tests/test_tracking.py (870 LoC — every tracker
exercised against a temp dir or a mocked API). Each fake module is
injected into sys.modules so the tracker's lazy ``import X`` inside
``start()``/``log()`` resolves to the recorder; assertions check the exact
third-party calls the reference's integrations make.
"""

from __future__ import annotations

import sys
import types
from unittest import mock

import pytest

from accelerate_tpu import tracking


class Recorder:
    """Attribute sink recording every call as (name, args, kwargs)."""

    def __init__(self, name="recorder", returns=None):
        self._name = name
        self.calls = []
        self._returns = returns or {}

    def __getattr__(self, item):
        def _call(*args, **kwargs):
            self.calls.append((item, args, kwargs))
            return self._returns.get(item)

        return _call

    def names(self):
        return [c[0] for c in self.calls]

    def get(self, name):
        return [c for c in self.calls if c[0] == name]


@pytest.fixture
def fake_module(monkeypatch):
    """Install a fake module (and record it) under the given name."""

    installed = []

    def _install(name: str, **attrs):
        mod = types.ModuleType(name)
        for k, v in attrs.items():
            setattr(mod, k, v)
        monkeypatch.setitem(sys.modules, name, mod)
        installed.append(name)
        return mod

    return _install


def test_wandb_offline_mode_restarts_with_config(fake_module, monkeypatch):
    """WANDB_MODE=offline: config can't be updated post-init, so the run is
    restarted with the config baked in (reference: tracking.py:343-352)."""
    init_calls = []
    runs = []

    def init(**kwargs):
        init_calls.append(kwargs)
        runs.append(Recorder("run"))
        return runs[-1]

    fake_module("wandb", init=init, config=Recorder("config"))
    monkeypatch.setenv("WANDB_MODE", "offline")
    t = tracking.WandBTracker("proj", entity="me")
    t.start()
    t.store_init_configuration({"lr": 0.1})
    assert len(init_calls) == 2
    assert init_calls[1]["config"] == {"lr": 0.1} and init_calls[1]["entity"] == "me"
    assert runs[0].get("finish")  # first (config-less) run was closed
    t.log({"loss": 1.0}, step=1)
    assert runs[1].get("log")


def test_wandb_tracker_calls(fake_module):
    run = Recorder("run")
    config = Recorder("config")
    init_calls = []

    def init(**kwargs):
        init_calls.append(kwargs)
        return run

    fake_module("wandb", init=init, config=config)
    t = tracking.WandBTracker("proj", entity="me")
    t.start()
    assert init_calls == [{"project": "proj", "entity": "me"}]
    t.store_init_configuration({"lr": 0.1})
    assert config.get("update")[0][1][0] == {"lr": 0.1}
    t.log({"loss": 1.0}, step=3)
    name, args, kwargs = run.get("log")[0]
    assert args[0] == {"loss": 1.0} and kwargs["step"] == 3
    t.finish()
    assert "finish" in run.names()
    assert t.tracker is run


def test_mlflow_tracker_calls(fake_module):
    m = Recorder("mlflow")
    mod = fake_module("mlflow")
    mod.start_run = lambda **kw: m.calls.append(("start_run", (), kw)) or m
    mod.log_params = lambda p: m.calls.append(("log_params", (p,), {}))
    mod.log_metrics = lambda metrics, step=None: m.calls.append(("log_metrics", (metrics,), {"step": step}))
    mod.end_run = lambda: m.calls.append(("end_run", (), {}))

    t = tracking.MLflowTracker("run1")
    t.start()
    # >100 params are chunked into multiple log_params calls (reference:
    # MLflow's 100-param batch limit)
    t.store_init_configuration({f"p{i}": i for i in range(150)})
    param_calls = m.get("log_params")
    assert len(param_calls) == 2
    assert sum(len(c[1][0]) for c in param_calls) == 150
    t.log({"loss": 0.5, "note": "skipme"}, step=7)
    metrics, = m.get("log_metrics")[0][1]
    assert metrics == {"loss": 0.5}  # non-numeric values filtered
    t.finish()
    assert "end_run" in m.names()


def test_mlflow_file_store_and_experiment(fake_module, tmp_path):
    """logging_dir routes to a file:// tracking URI and experiment_name is
    selected BEFORE the run starts (reference: tracking.py:705)."""
    m = Recorder("mlflow")
    mod = fake_module("mlflow")
    mod.set_tracking_uri = lambda uri: m.calls.append(("set_tracking_uri", (uri,), {}))
    mod.set_experiment = lambda name: m.calls.append(("set_experiment", (name,), {}))
    mod.start_run = lambda **kw: m.calls.append(("start_run", (), kw)) or m

    t = tracking.MLflowTracker("run1", logging_dir=str(tmp_path), experiment_name="exp1")
    t.start()
    assert m.names() == ["set_tracking_uri", "set_experiment", "start_run"]
    assert m.get("set_tracking_uri")[0][1][0] == "file://" + str(tmp_path)
    assert m.get("set_experiment")[0][1][0] == "exp1"
    # experiment_name must NOT leak into start_run kwargs
    assert "experiment_name" not in m.get("start_run")[0][2]


def test_aim_tracker_calls(fake_module, tmp_path):
    writer = Recorder("aim_run")
    writer.__dict__["name"] = None
    created = []

    class Run:
        def __new__(cls, repo=None, **kw):
            created.append(repo)
            return writer

    fake_module("aim", Run=Run)
    t = tracking.AimTracker("exp", logging_dir=str(tmp_path))
    t.start()
    assert created == [str(tmp_path)]
    t.log({"loss": 2.0}, step=1)
    name, args, kwargs = writer.get("track")[0]
    assert args[0] == 2.0 and kwargs == {"name": "loss", "step": 1}
    t.finish()
    assert "close" in writer.names()


def test_comet_tracker_calls(fake_module):
    exp = Recorder("experiment")

    class Experiment:
        def __new__(cls, project_name=None, **kw):
            exp.calls.append(("ctor", (project_name,), kw))
            return exp

    fake_module("comet_ml", Experiment=Experiment)
    t = tracking.CometMLTracker("proj")
    t.start()
    t.store_init_configuration({"bs": 8})
    assert exp.get("log_parameters")[0][1][0] == {"bs": 8}
    t.log({"acc": 0.9}, step=2)
    assert exp.get("set_step")[0][1][0] == 2
    assert exp.get("log_metrics")[0][1][0] == {"acc": 0.9}
    t.finish()
    assert "end" in exp.names()


def test_clearml_tracker_calls(fake_module):
    task = Recorder("task")
    logger = Recorder("logger")
    task._returns["get_logger"] = logger

    class Task:
        @staticmethod
        def init(project_name=None, **kw):
            task.calls.append(("init", (project_name,), kw))
            return task

    fake_module("clearml", Task=Task)
    t = tracking.ClearMLTracker("proj")
    t.start()
    t.store_init_configuration({"cfg": 1})
    assert "connect_configuration" in task.names()
    t.log({"loss": 1.5}, step=4)
    name, args, kwargs = logger.get("report_scalar")[0]
    assert kwargs == {"title": "loss", "series": "loss", "value": 1.5, "iteration": 4}
    t.log({"final": 2.0})  # step=None -> single value
    assert logger.get("report_single_value")[0][2] == {"name": "final", "value": 2.0}
    t.finish()
    assert "close" in task.names()


def test_trackio_tracker_calls(fake_module):
    run = Recorder("run")
    state = Recorder("trackio")
    mod = fake_module("trackio")
    mod.init = lambda project=None, **kw: state.calls.append(("init", (project,), kw)) or run
    mod.log = lambda values: state.calls.append(("log", (values,), {}))
    mod.finish = lambda: state.calls.append(("finish", (), {}))
    mod.config = Recorder("config")

    t = tracking.TrackioTracker("proj")
    t.start()
    assert state.get("init")[0][1] == ("proj",)
    t.log({"loss": 3.0}, step=9)
    assert state.get("log")[0][1][0] == {"loss": 3.0, "step": 9}
    t.finish()
    assert "finish" in state.names()


def test_dvclive_tracker_calls(fake_module):
    live = Recorder("live")
    fake_module("dvclive", Live=lambda **kw: live)
    t = tracking.DVCLiveTracker("run")
    t.start()
    t.store_init_configuration({"wd": 0.01})
    assert live.get("log_params")[0][1][0] == {"wd": 0.01}
    t.log({"loss": 0.25}, step=5)
    assert live.__dict__.get("step") == 5 or ("log_metric", ("loss", 0.25), {}) in live.calls
    assert "next_step" in live.names()
    t.finish()
    assert "end" in live.names()


def test_dvclive_accepts_existing_live_instance(fake_module):
    live = Recorder("live")
    fake_module("dvclive", Live=lambda **kw: pytest.fail("should reuse the provided Live"))
    t = tracking.DVCLiveTracker("run", live=live)
    t.start()
    assert t.tracker is live


def test_swanlab_tracker_calls(fake_module):
    run = Recorder("run", returns={})
    run.__dict__["config"] = Recorder("config")
    state = Recorder("swanlab")
    mod = fake_module("swanlab")
    mod.init = lambda project=None, **kw: state.calls.append(("init", (project,), kw)) or run
    mod.log = lambda values, step=None: state.calls.append(("log", (values,), {"step": step}))
    mod.finish = lambda: state.calls.append(("finish", (), {}))

    t = tracking.SwanLabTracker("proj")
    t.start()
    t.store_init_configuration({"opt": "adam"})
    assert run.config.get("update")[0][1][0] == {"opt": "adam"}
    t.log({"loss": 0.1}, step=2)
    assert state.get("log")[0][2] == {"step": 2}
    t.finish()
    assert "finish" in state.names()


def test_tensorboard_tracker_real_writer(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    t = tracking.TensorBoardTracker("run", logging_dir=str(tmp_path))
    t.start()
    t.store_init_configuration({"lr": 0.1, "name": "x", "skip": [1, 2]})
    t.log({"loss": 1.0, "msg": "hello", "pair": {"a": 1.0, "b": 2.0}}, step=0)
    t.finish()
    files = list(tmp_path.rglob("*"))
    assert any(f.is_file() for f in files), "tensorboard wrote no event files"


def test_init_trackers_with_mocked_wandb(fake_module, tmp_path, accelerator_factory=None):
    run = Recorder("run")
    mod = fake_module("wandb", init=lambda **kw: run, config=Recorder("config"))
    assert mod is sys.modules["wandb"]

    from accelerate_tpu import Accelerator

    with mock.patch.object(tracking, "_AVAILABILITY", {**tracking._AVAILABILITY, "wandb": lambda: True}):
        acc = Accelerator(log_with=["jsonl", "wandb"], project_dir=str(tmp_path))
        acc.init_trackers("proj", config={"lr": 1e-3})
        acc.log({"loss": 0.5}, step=1)
        tracker = acc.get_tracker("wandb")
        assert tracker.run is run
        acc.end_training()
    assert "finish" in run.names()
    assert (tmp_path / "proj").exists() or list(tmp_path.rglob("*.jsonl")), "jsonl tracker wrote nothing"


def test_logger_type_map_covers_all_availability_keys():
    assert set(tracking.LOGGER_TYPE_TO_CLASS) == set(tracking._AVAILABILITY)


def test_main_process_only_attribute():
    for cls in tracking.LOGGER_TYPE_TO_CLASS.values():
        assert isinstance(cls.name, str) and isinstance(cls.requires_logging_directory, bool)


# ---------------------------------------------------------------------------
# media logging (reference: tracking.py:272/:373/:392/:666/:998/:1016)
# ---------------------------------------------------------------------------


def _gray(v, h=4, w=6):
    import numpy as np

    return np.full((h, w, 3), v, np.uint8)


def test_wandb_log_images_and_table(fake_module):
    run = Recorder("run")

    class Image:
        def __init__(self, data, **kw):
            self.data = data

    class Table:
        def __init__(self, columns=None, data=None, dataframe=None):
            self.columns, self.data, self.dataframe = columns, data, dataframe

    fake_module("wandb", init=lambda **kw: run, Image=Image, Table=Table, config=Recorder("config"))
    t = tracking.WandBTracker("proj")
    t.start()
    t.log_images({"samples": [_gray(0), _gray(255)]}, step=3)
    name, args, kwargs = run.get("log")[0]
    assert [type(i) for i in args[0]["samples"]] == [Image, Image] and kwargs["step"] == 3
    t.log_table("preds", columns=["x", "y"], data=[[1, 2]], step=4)
    name, args, kwargs = run.get("log")[1]
    table = args[0]["preds"]
    assert isinstance(table, Table) and table.columns == ["x", "y"] and table.data == [[1, 2]]


def test_comet_log_images_and_table(fake_module):
    exp = Recorder("experiment")

    class Experiment:
        def __new__(cls, project_name=None, **kw):
            return exp

    fake_module("comet_ml", Experiment=Experiment)
    t = tracking.CometMLTracker("proj")
    t.start()
    t.log_images({"gen": [_gray(10)]}, step=1)
    name, args, kwargs = exp.get("log_image")[0]
    assert kwargs["name"] == "gen_0" and kwargs["step"] == 1 and args[0].shape == (4, 6, 3)
    t.log_table("metrics", columns=["a"], data=[[1]], step=2)
    name, args, kwargs = exp.get("log_table")[0]
    assert args[0] == "metrics.csv" and kwargs["tabular_data"] == [[1]] and kwargs["headers"] == ["a"]
    import pytest as _pytest

    with _pytest.raises(ValueError, match="log_table needs"):
        t.log_table("empty")


def test_clearml_log_images_and_table(fake_module):
    task = Recorder("task")
    logger = Recorder("logger")
    task._returns["get_logger"] = logger

    class Task:
        @staticmethod
        def init(project_name=None, **kw):
            return task

    fake_module("clearml", Task=Task)
    t = tracking.ClearMLTracker("proj")
    t.start()
    t.log_images({"viz": [_gray(1), _gray(2)]}, step=7)
    calls = logger.get("report_image")
    assert len(calls) == 2
    assert calls[0][2]["title"] == "viz" and calls[0][2]["series"] == "0" and calls[0][2]["iteration"] == 7
    t.log_table("tbl", columns=["c"], data=[[9]], step=1)
    name, args, kwargs = logger.get("report_table")[0]
    assert kwargs["table_plot"] == [["c"], [9]] and kwargs["iteration"] == 1


def test_aim_log_images_with_captions(fake_module, tmp_path):
    writer = Recorder("aim_run")
    writer.__dict__["name"] = None
    images = []

    class AimImage:
        def __init__(self, data, caption=None, **kw):
            images.append((data, caption))

    class Run:
        def __new__(cls, repo=None, **kw):
            return writer

    fake_module("aim", Run=Run, Image=AimImage)
    t = tracking.AimTracker("exp", logging_dir=str(tmp_path))
    t.start()
    t.log_images({"single": _gray(3), "pair": [(_gray(4), "cap")]}, step=2)
    assert len(images) == 2 and images[1][1] == "cap"
    assert len(writer.get("track")) == 2


def test_tensorboard_log_images_real_writer(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    import numpy as np

    t = tracking.TensorBoardTracker("run", logging_dir=str(tmp_path))
    t.start()
    # mixed inputs: uint8 HWC + float [0,1] grayscale HW
    t.log_images({"batch": [_gray(128), np.linspace(0, 1, 24).reshape(4, 6)]}, step=0)
    t.finish()
    assert any(f.is_file() for f in tmp_path.rglob("*")), "no event files written"


def test_jsonl_log_images_and_table(tmp_path):
    import json as _json

    t = tracking.JSONLTracker("run", logging_dir=str(tmp_path))
    t.start()
    t.log_images({"x": [_gray(7)]}, step=5)
    t.log_table("t", columns=["a", "b"], data=[[1, 2]], step=6)
    lines = [_json.loads(line) for line in open(t.path)]
    img_paths = lines[0]["_images/x"]
    assert len(img_paths) == 1 and img_paths[0].endswith(".png")
    import os as _os

    assert _os.path.exists(img_paths[0])
    assert lines[1]["_table/t"] == {"columns": ["a", "b"], "data": [[1, 2]]}


def test_accelerator_log_images_dispatch(fake_module, tmp_path):
    """Accelerator.log_images routes to capable trackers and silently skips
    trackers that don't override the base method."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.tracking import GeneralTracker

    seen = []

    class NoMedia(GeneralTracker):
        name = "nomedia"
        requires_logging_directory = False
        main_process_only = True

        def __init__(self):
            super().__init__()

        def store_init_configuration(self, values):
            pass

        def log(self, values, step=None, **kw):
            seen.append(("log", values))

    acc = Accelerator(log_with=["jsonl", NoMedia()], project_dir=str(tmp_path))
    acc.init_trackers("proj")
    acc.log_images({"img": [_gray(9)]}, step=1)
    acc.log_table("t", columns=["a"], data=[[1]], step=1)
    jsonl = acc.get_tracker("jsonl")
    lines = open(jsonl.tracker).read().splitlines()
    assert len(lines) == 2  # images + table records, no error from NoMedia
    assert not seen  # NoMedia.log was never used as a media fallback
