"""Gemma family (models/gemma.py): the four llama-core deviations
(explicit head_dim, GeGLU, (1+scale) norms, scaled embeddings) through
decode, MQA TP sharding, and serving. HF importer parity lives in
test_hf_parity.py."""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models import GemmaConfig, create_gemma_model


@pytest.fixture(scope="module")
def tiny_gemma():
    return create_gemma_model(GemmaConfig.tiny(), seq_len=16)


def test_head_dim_decoupled(tiny_gemma):
    """head_dim 32 with hidden 64 / 4 heads: q_proj is [64, 128], not
    [64, 64] — the explicit width actually takes effect."""
    kern = tiny_gemma.params["layers"]["block"]["attn"]["q_proj"]["kernel"]
    assert kern.shape[-1] == 4 * 32, kern.shape
    v = tiny_gemma.params["layers"]["block"]["attn"]["v_proj"]["kernel"]
    assert v.shape[-1] == 1 * 32, v.shape  # MQA: one KV head


def test_greedy_decode_matches_full_prefix(tiny_gemma):
    """MQA + explicit head_dim through the KV-cache decode contract."""
    ids = (np.arange(2 * 8).reshape(2, 8) % 250 + 1).astype(np.int32)
    out = np.asarray(generate(tiny_gemma, ids, max_new_tokens=6))
    full = ids
    for _ in range(6):
        logits = np.asarray(tiny_gemma(full))
        full = np.concatenate([full, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], 1)
    np.testing.assert_array_equal(out, full)


def test_tied_head_shares_the_table(tiny_gemma):
    """tie_word_embeddings: no lm_head param exists, and perturbing the
    embedding table changes the logits through BOTH ends."""
    import jax

    assert "lm_head" not in tiny_gemma.params
    ids = np.arange(1, 9, dtype=np.int32)[None]
    base = np.asarray(tiny_gemma(ids))
    bumped = jax.tree_util.tree_map(lambda x: x, tiny_gemma.params)
    bumped["embed_tokens"]["embedding"] = bumped["embed_tokens"]["embedding"] * 1.01
    out = np.asarray(tiny_gemma.apply_fn(bumped, ids))
    assert not np.allclose(base, out)


def test_norm_plus_one_zero_init_is_identity_scale():
    """Fresh params carry zero offsets: (1 + 0) == llama's ones init, so
    an untrained gemma norm behaves like a llama norm."""
    m = create_gemma_model(GemmaConfig.tiny(), seq_len=16)
    scale = m.params["layers"]["block"]["input_norm"]["scale"]
    assert np.allclose(np.asarray(scale), 0.0)


def test_train_step_converges(tiny_gemma):
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.llama import causal_lm_loss
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc = Accelerator()
    model = acc.prepare_model(create_gemma_model(GemmaConfig.tiny(), seq_len=16))
    acc.prepare_optimizer(optax.adam(3e-3))
    step = acc.build_train_step(lambda p, b: causal_lm_loss(p, b, model.apply_fn))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(1, 250, size=(4, 16)).astype(np.int32)}
    losses = [float(step(batch)) for _ in range(40)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_paged_serving(tiny_gemma):
    from accelerate_tpu.serving import ServingEngine

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 250, size=n).astype(np.int32) for n in (3, 9)]
    eng = ServingEngine(tiny_gemma, num_slots=2, prompt_buckets=(4, 16), paged_block_size=4)
    outs = eng.generate_many(prompts, max_new_tokens=4)
    for p, got in zip(prompts, outs):
        ref = np.asarray(generate(tiny_gemma, p[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(got, ref)
